"""Kernel-contract verifier (analysis/kernelcheck) — the static
VMEM/exactness/lowerability audit behind `analyze --kernel`.

The load-bearing assertions RE-DERIVE the headline numbers from first
principles rather than restating the module's constants: the contender
cap comes out of an independent exact-rational evaluation of the
summation-error lemma, the VMEM peak is cross-pinned against the
kernel's io-contract byte count, and every seeded mutant in
analysis.mutations.KERNEL_MUTATIONS must be killed by the static
passes alone (trace=False).
"""

import dataclasses
from fractions import Fraction

import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu.analysis import kernelcheck as kc
from ue22cs343bb1_openmp_assignment_tpu.analysis import mutations
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops import pallas_round as pr


def _deep(n, dd=2, tw=2, **kw):
    return dataclasses.replace(
        SystemConfig.scale(num_nodes=n, drain_depth=dd, txn_width=tw),
        **{"deep_window": True, "deep_slots": 3,
           "deep_ownerval_slots": 1, **kw})


# ---------------------------------------------------------------- pass 1:
# exact arithmetic


def test_exact_cap_rederived_independently():
    """The certified cap at the production ladder (G=15, f32) must be
    2**14 — derived here by a brute linear scan of the lemma
    ``R * (1 + eps)**(R - 1) < 2**G`` in exact rationals around the
    bisection's answer, NOT by comparing against a copied constant."""
    cap = kc.exact_cap(15)
    eps = Fraction(1, 1 << 24)
    # independent check of maximality: cap satisfies the bound, its
    # successor does not (the tightness witness)
    assert cap * (1 + eps) ** (cap - 1) < 1 << 15
    assert (cap + 1) * (1 + eps) ** cap >= 1 << 15
    b = kc.derived_bounds(kc.headline_config())
    assert b["cap_exact"] == cap
    # the gate's power-of-two sub-cap: the legacy hand-proved 2**14
    # must fall out of the derivation at the current ladder params
    assert b["cap_limit"] == 1 << 14
    assert b["cap_limit"] <= cap < 2 * b["cap_limit"]


def test_derived_bounds_headline():
    b = kc.derived_bounds(kc.headline_config())
    assert (b["A"], b["G"], b["chunk_bits"]) == (100, 15, 4)
    # L = prio(12) + valid(1) + slot_bits; 4 passes of 4 bits
    assert b["num_passes"] == -(-b["L_bits"] // b["chunk_bits"])
    # ladder spans normal f32 only — re-derived from the params
    assert b["ladder_min_exp"] == b["A"] - b["G"] * 15 >= kc.F32_MIN_EXP
    assert b["ladder_max_exp"] == b["A"] + b["G"] <= kc.F32_MAX_EXP
    # one contender per (node, entry) at deep_waves=1
    assert b["max_contenders"] == 4096


def test_exactness_clean_at_headline():
    rep = kc.check_exactness(kc.headline_config())
    assert rep["ok"], rep["findings"]
    assert rep["lemmas"]["cap_margin_symbolic"]
    assert rep["lemmas"]["readout_adversarial_f32"]


def test_exactness_flags_cap_boundary():
    """A config whose per-entry contenders reach the certified cap is a
    `contender_cap` finding (the analyzer's cap+1 adversary: 16384
    single-wave nodes == 2**14 contenders, not strictly under)."""
    rep = kc.check_exactness(_deep(16384, deep_slots=2))
    kinds = [f["kind"] for f in rep["findings"]]
    assert "contender_cap" in kinds
    # one node fewer is strictly under the cap: clean
    assert kc.check_exactness(_deep(8192, deep_slots=2))["ok"]


def test_scatter_min_exact_at_derived_cap():
    """Runtime witness for the derived cap: cap_limit contenders piled
    on one entry (the analyzer-certified maximum for a <-cap config)
    still recover the exact minimum, at adversarial chunk values."""
    from ue22cs343bb1_openmp_assignment_tpu.ops import deep_engine as de
    cfg = _deep(8)
    ix = pr.RoutedIndexOps(cfg, 3)
    nat = de.XlaIndexOps()
    L = ix._L
    R = kc.derived_bounds(cfg)["cap_limit"]
    rng = np.random.default_rng(3)
    import jax.numpy as jnp
    M = 16
    low = rng.integers(0, 1 << L, R).astype(np.int32)
    low[:-1] = (1 << L) - 1          # crowd at the worst chunk...
    low[-1] = 1                      # ...one true minimum hiding below
    idx = np.zeros(R, np.int32)      # ALL on entry 0
    vals = jnp.asarray((int(ix._cd) << L) | low)
    dest = jnp.full((M,), 2 ** 31 - 1, jnp.int32)
    got = ix.scatter_min(dest, jnp.asarray(idx), vals)
    want = nat.scatter_min(dest, jnp.asarray(idx), vals)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_split16_join16_roundtrip_extremes():
    """The one-hot matmul routing's int32 <-> two-exact-f32-halves side
    contract, at the integer extremes and the half boundaries."""
    import jax.numpy as jnp
    v = jnp.asarray(np.array(
        [0, 1, -1, 2 ** 31 - 1, -(2 ** 31), 0x7FFF8000, -0x8000,
         0xFFFF, 0x10000, -0x10000], np.int64).astype(np.int32))
    halves = pr._split16(v[:, None])
    # each half must be a nonnegative integer < 2**16: exact in f32
    h = np.asarray(halves)
    assert h.dtype == np.float32
    assert (h >= 0).all() and (h < 2 ** 16).all()
    assert (h == np.trunc(h)).all()
    lo, hi = halves[:, 0], halves[:, 1]
    np.testing.assert_array_equal(np.asarray(pr._join16(lo, hi)),
                                  np.asarray(v))


def test_mutants_killed_statically():
    """Every seeded kernel mutant must be caught by the static passes
    alone — no trace, no execution — with its documented finding
    kind."""
    for name, (cm, kind) in mutations.KERNEL_MUTATIONS.items():
        with cm():
            rep = kc.check(trace=False)
        kinds = [f["kind"] for f in rep["findings"]]
        assert not rep["ok"] and kind in kinds, (name, kinds)
    # and the unmutated world is clean again (mutators restore state)
    assert kc.check(trace=False)["ok"]


# ---------------------------------------------------------------- pass 2:
# VMEM


def test_vmem_verdict_boundaries():
    """The budget rule's boundary semantics: exactly-at-budget passes,
    one byte over fails; multi-step grids pay input headroom."""
    v = kc.vmem_verdict(600, 400, None, grid_steps=1, vmem_bytes=1000)
    assert v["ok"] and v["required_bytes"] == 1000
    v = kc.vmem_verdict(601, 400, None, grid_steps=1, vmem_bytes=1000)
    assert not v["ok"] and v["required_bytes"] == 1001
    # traced peak dominates resident when larger
    v = kc.vmem_verdict(600, 400, 1001, grid_steps=1, vmem_bytes=1000)
    assert not v["ok"]
    # a 2-step grid double-buffers its inputs
    v = kc.vmem_verdict(300, 100, None, grid_steps=2, vmem_bytes=700)
    assert v["ok"] and v["headroom_bytes"] == 300 \
        and v["required_bytes"] == 700
    assert not kc.vmem_verdict(300, 100, None, grid_steps=2,
                               vmem_bytes=699)["ok"]


def test_resident_bytes_cross_pinned_to_io_contract():
    """The block-table resident bytes ARE the kernel's HBM I/O contract
    (one VMEM load + one store of every block) — two independently
    maintained shape tables that must never drift."""
    cfg = kc.headline_config()
    r_in, r_out = kc.resident_bytes(cfg)
    io_in, io_out = pr.io_contract_bytes(cfg)
    assert (r_in, r_out) == (io_in, io_out)
    assert r_in + r_out == 5_079_040      # the pinned headline contract


@pytest.mark.slow
def test_traced_vmem_peak_headline():
    """The liveness walk over the real traced body at deep@4096: the
    peak must land in the documented ~13 MB window and fit the 16 MiB
    budget with the resident blocks accounted."""
    cfg = kc.headline_config()
    rows = kc.vmem_rows(cfg, device_kind="cpu", trace=True)
    (row,) = rows
    assert row["basis"] == "traced-liveness"
    assert row["ok"]
    assert 11_500_000 < row["peak_bytes"] < 14_500_000
    assert row["required_bytes"] <= 16 * 2 ** 20
    # grid (1,): no double-buffer headroom
    assert row["headroom_bytes"] == 0


def test_peak_live_bytes_on_synthetic_jaxpr():
    """The liveness model itself, on a program small enough to verify
    by hand: b = a + a frees nothing (a lives on), c = b * b frees b
    before allocating c under in-place reuse."""
    import jax
    import jax.numpy as jnp

    def f(a):
        b = a + a          # live: a(400) + b(400) = 800
        c = b * b          # b dies here: 400 freed, c(400) allocated
        return c + a       # a dies; out 400

    closed = jax.make_jaxpr(f)(jnp.zeros((10, 10), jnp.float32))
    # peak = a + b live simultaneously = 800 bytes
    assert kc.peak_live_bytes(closed.jaxpr) == 800


# ---------------------------------------------------------------- pass 3:
# lowerability


def test_lowerability_clean_on_small_trace():
    rep = kc.check_lowerability(_deep(8))
    assert rep["ok"], rep["findings"]
    assert rep["eqns"] > 1000      # the whole round really is in there


def test_lowerability_flags_banned_primitives():
    import jax
    import jax.numpy as jnp

    def bad(x, i):
        return jnp.sort(x)[i[0]] + x.astype(jnp.float64).sum()

    # x64 must be on for the float64 widening to survive tracing
    # (without it astype truncates to f32 and the bug self-heals)
    with jax.experimental.enable_x64():
        closed = jax.make_jaxpr(bad)(
            jnp.zeros((8,), jnp.float32), jnp.zeros((1,), jnp.int32))
    findings = []
    kc.audit_lowerability(closed.jaxpr, findings, target="t")
    kinds = {f["kind"] for f in findings}
    assert "mosaic_lowerability" in kinds      # sort / gather
    assert "wide_dtype" in kinds               # float64


# ---------------------------------------------------------------- pass 4:
# gates — supported() consumes the derived bounds


def test_gate_widened_for_single_wave():
    """The derivation splits the legacy slots*N product bound: deep@8192
    q3 single-wave (24576 under the old bound) is now ADMITTED, its
    multi-wave sibling is not, and the cap boundary stays rejected."""
    assert pr.supported(_deep(8192))
    assert not pr.supported(_deep(8192, deep_waves=2))
    assert not pr.supported(_deep(16384, deep_slots=2))
    # storm remains a structural gate regardless of contender margin
    assert not pr.supported(_deep(256, deep_read_storm=True,
                                  deep_ownerval_slots=2))


def test_check_gates_records_widening():
    rep = kc.check_gates()
    assert rep["ok"], rep["findings"]
    p = rep["probes"]
    assert p["widened_8192_q3_w1"]["supported"]
    assert p["widened_8192_q3_w1"]["widened"]
    assert not p["widened_8192_q3_w1"]["legacy_product_bound"]
    assert not p["multiwave_8192_q3_w2"]["supported"]
    assert not p["cap_boundary_16384"]["supported"]
    assert not p["storm_256"]["supported"]
    assert p["headline_4096"]["supported"]


# ---------------------------------------------------------------- the CLI


def test_runner_kernel_prong_exit_codes(capsys):
    from ue22cs343bb1_openmp_assignment_tpu.analysis import runner
    rc = runner.main(["--kernel", "--kernel-static", "--kernel-nodes",
                      "256", "--skip-model-check", "--skip-lint"])
    assert rc == 0
    assert "kernel contracts: ok" in capsys.readouterr().out
    rc = runner.main(["--kernel", "--skip-model-check", "--skip-lint",
                      "--mutation", "widen_min_chunk"])
    assert rc == 1
    assert "ladder_range" in capsys.readouterr().out


def test_runner_rejects_kernel_mutation_elsewhere():
    from ue22cs343bb1_openmp_assignment_tpu.analysis import runner
    with pytest.raises(SystemExit, match="kernel mutation"):
        runner.main(["--skip-lint", "--mutation", "widen_min_chunk"])


def test_report_render_and_schema():
    rep = kc.check(_deep(256), trace=False)
    assert rep["schema"] == kc.SCHEMA and rep["ok"]
    lines = kc.render_text(rep)
    assert any("kernel contracts: ok" in ln for ln in lines)
    assert any("cap 16384" in ln for ln in lines)
    import json
    json.dumps(rep)      # the --json path must serialize as-is
