"""The declarative protocol table and its verification stack.

Four layers, mirroring the subsystem (analysis/protocol_table.py,
verify_table.py, conformance.py):

* table verification — the four static passes are clean on all three
  shipped tables, and each seeded TABLE mutant trips exactly its
  expected finding (the passes' own regression suite).
* conformance gate — the MESI table is bit-equivalent to the live
  handlers over full small scopes (fast: 2n2h; slow: the
  symmetry-reduced 4-node scope and the 3-node eviction scope), and
  every seeded HANDLER mutant diverges from the table (fast: two
  representative mutants; slow: all six).
* protocol variants — MOESI and MESIF table-compiled phases run clean
  through the unmodified model checker, with engaged-pair evidence
  that OWNED/FORWARD states were actually reached.
* plumbing — cfg.protocol validation, the protocol-aware state-range
  invariant, and the `analyze --table` CLI exit codes.
"""

import dataclasses

import pytest


# ---------------------------------------------------------------------------
# verify_table: static passes
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", ["mesi", "moesi", "mesif"])
def test_verify_passes_clean(name):
    from ue22cs343bb1_openmp_assignment_tpu.analysis import (protocol_table,
                                                             verify_table)
    rep = verify_table.verify(protocol_table.TABLES[name]())
    assert rep["ok"], rep["findings"]
    assert rep["rows"] == 30
    assert set(rep["passes"]) == {"totality_determinism", "conservation",
                                  "stability", "anchors"}
    assert all(v == "ok" for v in rep["passes"].values())


def test_anchors_cover_the_registry_bidirectionally():
    """Every row cites a registered reference anchor AND every
    registered anchor/quirk is cited by some row — the table can
    neither invent provenance nor silently drop a documented
    transition."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis import (protocol_table,
                                                             verify_table)
    from ue22cs343bb1_openmp_assignment_tpu.ops import handlers
    table = protocol_table.mesi_table()
    registered = {a for anchors in handlers.TRANSITION_ANCHORS.values()
                  for a in anchors}
    cited = {r.anchor for r in table.rows}
    assert cited == registered
    assert {q for r in table.rows for q in r.quirks} == set(handlers.QUIRKS)


@pytest.mark.parametrize("mutation", ["table_guard_overlap",
                                      "table_drop_row"])
def test_table_mutant_is_caught(mutation):
    from ue22cs343bb1_openmp_assignment_tpu.analysis import (mutations,
                                                             protocol_table,
                                                             verify_table)
    fn, expected = mutations.TABLE_MUTATIONS[mutation]
    rep = verify_table.verify(fn(protocol_table.mesi_table()))
    assert not rep["ok"], f"{mutation} survived verify_table"
    assert expected in {f["kind"] for f in rep["findings"]}, (
        mutation, expected, rep["findings"])


def test_conservation_catches_missing_assumes():
    """The FLUSH_INVACK home rows are conservation-safe only under
    their declared dir-state precondition; stripping the `assumes`
    must surface the latent quirk (a U-state delivery would resurrect
    a sharer bit)."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis import (protocol_table,
                                                             verify_table)
    from ue22cs343bb1_openmp_assignment_tpu.analysis.protocol_table import \
        Guard
    table = protocol_table.mesi_table()
    rows = tuple(
        dataclasses.replace(r, assumes=Guard())
        if r.name.startswith("fia_") and r.guard.at_home else r
        for r in table.rows)
    rep = verify_table.verify(dataclasses.replace(table, rows=rows))
    assert "conservation_violation" in {f["kind"] for f in rep["findings"]}


# ---------------------------------------------------------------------------
# conformance: table == handlers, by exhaustion
# ---------------------------------------------------------------------------

def _conform(scope_name, **kw):
    from ue22cs343bb1_openmp_assignment_tpu.analysis import (conformance,
                                                             protocol_table)
    scope = conformance.conformance_scopes()[scope_name]
    return conformance.check_conformance(scope, protocol_table.mesi_table(),
                                         **kw)


def test_conformance_2n2h_bit_exact():
    rep = _conform("2n2h")
    assert rep["ok"], rep["findings"]
    assert rep["stats"]["states"] == 60
    assert rep["stats"]["msg_events"] > 0
    # the dynamic audit matched exactly one row at every message event
    assert not [f for f in rep["findings"] if f["check"] == "row_match"]


@pytest.mark.slow
def test_conformance_4n1a_sym_bit_exact():
    """The symmetry-reduced 4-node scope: orbit representatives only,
    but every explored transition is still checked both ways."""
    rep = _conform("4n1a_sym")
    assert rep["ok"], rep["findings"]
    assert rep["stats"]["symmetry_group_order"] == 6


@pytest.mark.slow
def test_conformance_3n2a_ev_covers_eviction_rows():
    """The conformance-only scope exists to light up the EVICT_SHARED
    bookkeeping classes and UPGRADE; only the two structurally
    unreachable bystander totality-completions may stay dark."""
    rep = _conform("3n2a_ev")
    assert rep["ok"], rep["findings"]
    for row in ("es_home_last", "es_home_promote_self",
                "es_home_promote_other", "es_home_many",
                "es_remote_promote", "upgrade_grant", "inv_miss_noop"):
        assert row in rep["row_coverage"], row


@pytest.mark.slow
def test_union_row_coverage_reaches_every_reachable_row():
    from ue22cs343bb1_openmp_assignment_tpu.analysis import (conformance,
                                                             protocol_table)
    covered = set()
    for name in conformance.conformance_scopes():
        rep = _conform(name)
        assert rep["ok"], (name, rep["findings"])
        covered |= set(rep["row_coverage"])
    dark = {r.name
            for r in protocol_table.mesi_table().rows} - covered
    assert dark == {"flush_bystander", "fia_bystander"}, dark


_FAST_MUTANTS = ["skip_em_bitvec_clear", "no_wait_clear_on_reply_rd"]


def _assert_mutant_diverges(mutation):
    from ue22cs343bb1_openmp_assignment_tpu.analysis.mutations import \
        MUTATIONS
    fn, scope_name, _ = MUTATIONS[mutation]
    rep = _conform(scope_name, message_phase=fn)
    assert not rep["ok"], f"{mutation} conforms to the MESI table"
    div = [f for f in rep["findings"] if f["check"] == "divergence"]
    assert div and div[0]["fields"], mutation
    assert div[0]["ref_render"] != div[0]["table_render"]


@pytest.mark.parametrize("mutation", _FAST_MUTANTS)
def test_handler_mutant_diverges_from_table(mutation):
    """The gate's own mutation test: a perturbed handler phase cannot
    stay bit-equal to the table. Two representatives in the fast tier
    (directory-side and wait-flag-side)."""
    _assert_mutant_diverges(mutation)


@pytest.mark.slow
@pytest.mark.parametrize("mutation", [
    "upgrade_keeps_other_sharers", "drop_evict_modified",
    "stale_owner_forward", "evict_shared_keeps_bit"])
def test_handler_mutant_diverges_from_table_full(mutation):
    _assert_mutant_diverges(mutation)


# ---------------------------------------------------------------------------
# protocol variants: MOESI / MESIF through the unchanged model checker
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("protocol,state_name", [("moesi", "OWNED"),
                                                 ("mesif", "FORWARD")])
def test_variant_table_model_checks_clean(protocol, state_name):
    """The variant table phase, run through the unmodified engine and
    checker, verifies clean on a write/evict scope — and the engaged-
    pair coverage proves the protocol's extra state was actually
    reached, not just defined."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis import (conformance,
                                                             model_check,
                                                             protocol_table)
    from ue22cs343bb1_openmp_assignment_tpu.analysis.protocol_table import \
        table_message_phase
    scope = conformance.variant_scope(
        model_check.builtin_scopes()["2n2a"], protocol)
    rep = model_check.check_scope(
        scope,
        message_phase=table_message_phase(
            protocol_table.TABLES[protocol]()))
    assert rep["ok"], rep["violations"]
    assert rep["stats"]["deadlocked_states"] == 0
    assert any(state_name in p for p in rep["coverage"]["engaged_pairs"]), (
        protocol, rep["coverage"]["engaged_pairs"])


# ---------------------------------------------------------------------------
# plumbing: cfg.protocol, protocol-aware invariants, CLI
# ---------------------------------------------------------------------------

def test_cfg_protocol_validation_and_allowed_states():
    from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
    from ue22cs343bb1_openmp_assignment_tpu.types import CacheState
    assert SystemConfig().protocol == "mesi"
    with pytest.raises(ValueError):
        SystemConfig(protocol="dragon")
    base = {CacheState.MODIFIED, CacheState.EXCLUSIVE, CacheState.SHARED,
            CacheState.INVALID}
    assert set(SystemConfig().allowed_cache_states) == base
    assert set(SystemConfig(protocol="moesi").allowed_cache_states) == (
        base | {CacheState.OWNED})
    assert set(SystemConfig(protocol="mesif").allowed_cache_states) == (
        base | {CacheState.FORWARD})


def test_state_range_invariant_is_protocol_aware():
    """An OWNED line is in-range under a MOESI config but an
    out-of-range violation under plain MESI — the invariant follows
    cfg.protocol, so a MESI run writing 4 still gets flagged."""
    import jax.numpy as jnp

    from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
    from ue22cs343bb1_openmp_assignment_tpu.ops import invariants
    from ue22cs343bb1_openmp_assignment_tpu.state import init_state
    from ue22cs343bb1_openmp_assignment_tpu.types import CacheState

    for protocol, bad in (("mesi", 1), ("moesi", 0)):
        cfg = SystemConfig(num_nodes=2, protocol=protocol)
        st = init_state(cfg)
        st = st.replace(cache_state=st.cache_state.at[0, 0].set(
            int(CacheState.OWNED)))
        v = invariants.step_violations(cfg, st)
        assert int(v["cache_state_out_of_range"]) == bad, protocol
        assert int(jnp.asarray(
            invariants.step_violations(cfg, init_state(cfg))
            ["cache_state_out_of_range"])) == 0


def test_analyze_table_cli_exit_codes():
    """`analyze --table` joins the CI gate: 0 clean, 1 under either a
    seeded table mutant (verify-table finding) or a seeded handler
    mutant (conformance divergence). In-process to stay fast."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis import runner
    common = ["--table", "--skip-model-check", "--skip-lint", "-q"]
    assert runner.main(common + ["--scopes", "2n1a"]) == 0
    assert runner.main(common + ["--mutation", "table_drop_row"]) == 1
    assert runner.main(common + ["--mutation",
                                 "skip_em_bitvec_clear"]) == 1
    # a table mutation aimed at the model-check prong is a usage error
    with pytest.raises(SystemExit):
        runner.main(["--skip-lint", "-q", "--mutation", "table_drop_row"])
