"""Memory-consistency litmus suite + axiomatic checker acceptance.

* DSL round-trip — every builtin test concretizes cleanly, seeds as a
  litmus-tagged FuzzCase, and survives the fixture loader
  (analysis/fixtures.py) byte-for-byte, tag included.
* exact outcome sets — for the classic shapes under MESI the model
  checker's exhaustively enumerated outcome set EXACTLY equals the
  DSL's allowed set (both directions: no forbidden outcome reachable,
  no allowed outcome unreachable).
* axiomatic witness — the po/rf/co/fr reconstruction flags a
  hand-built coherence-violating event list with a rendered
  SC-per-location cycle, and stays silent on the SC version.
* consistency mutants — each seeded bug in CONSISTENCY_MUTATIONS is
  killed by BOTH referees: the litmus enumeration observes a forbidden
  outcome, and the fuzzer's consistency oracle (analysis/axioms.py)
  produces an sc_cycle witness on the pinned interleaving, which ddmin
  shrinks and the fixture loader replays.
* CLI — `cache-sim analyze --litmus` honors the 0/1/3 exit contract.

The full protocol matrix (MOESI/MESIF) and the 4-node IRIW shape are
slow-tier; scripts/check.sh time-boxes the fast MESI subset.
"""

import dataclasses

import pytest

from ue22cs343bb1_openmp_assignment_tpu.analysis import (axioms, fixtures,
                                                         fuzz, litmus)
from ue22cs343bb1_openmp_assignment_tpu.analysis import shrink as sh
from ue22cs343bb1_openmp_assignment_tpu.analysis.mutations import (
    CONSISTENCY_MUTATIONS)

#: concrete MESI outcome sets (x0=1, y0=20, A=65, B=66) — hand-derived
#: from SC + the engine's blocking frontend, locked by enumeration
EXACT_MESI = {
    "corr": {(1, 1), (1, 65), (65, 65)},
    "mp": {(20, 1), (20, 65), (66, 65)},
    "sb": {(20, 65), (66, 1), (66, 65)},
    "mp_reload": {(1, 20, 1), (1, 20, 65), (65, 20, 65),
                  (1, 66, 65), (65, 66, 65)},
    "mp_upgrade": {(1, 1, 20, 1), (1, 1, 20, 65), (1, 65, 20, 65),
                   (1, 1, 66, 65), (1, 65, 66, 65)},
}


# -- DSL ------------------------------------------------------------------


def test_builtin_suite_well_formed():
    assert set(litmus.SEED_ORDER) == set(litmus.BUILTIN)
    for name, t in litmus.BUILTIN.items():
        assert t.name == name
        cfg = litmus.litmus_cfg(t.num_nodes)
        conc = litmus.concretize(t, cfg)
        assert len(conc["traces"]) == t.num_nodes
        for prog, tr in zip(t.programs, conc["traces"]):
            assert len(prog) == len(tr)
        n_reads = sum(1 for p in t.programs for op in p
                      if op[0] == "R")
        for out in conc["allowed"]:
            assert len(out) == n_reads + len(conc["final_addrs"])
            assert all(isinstance(v, int) for v in out)
        # 0 is never a litmus init or write value: a literal 0 in an
        # allowed set only ever marks a sanctioned blind-WRITEBACK
        # ghost (module docstring) — and only IRIW has those
        if name != "iriw":
            assert all(0 not in out for out in conc["allowed"]), name


def test_dsl_round_trips_through_fixture_loader(tmp_path):
    for i, name in enumerate(litmus.SEED_ORDER):
        case = litmus.to_fuzz_case(litmus.BUILTIN[name], i)
        assert case.litmus == name
        d = str(tmp_path / name)
        fixtures.write_fixture(d, case, "ok", "litmus seed")
        back = fixtures.load_case(d)
        assert back == case
        assert back.litmus == name
    # mutation must drop the tag: a mutated program is no longer the
    # litmus test, so its allowed set must not be applied
    import numpy as np
    rng = np.random.default_rng(0)
    seed = litmus.seed_cases(1)[0]
    assert fuzz.mutate_case(rng, seed, 99).litmus is None


def test_seed_cases_order_and_ids():
    seeds = litmus.seed_cases(4)
    assert [c.litmus for c in seeds] == list(litmus.SEED_ORDER[:4])
    assert [c.case_id for c in seeds] == [0, 1, 2, 3]


# -- exact enumeration under MESI -----------------------------------------


@pytest.mark.parametrize("name", sorted(EXACT_MESI))
def test_exact_outcome_set_mesi(name):
    rep = litmus.enumerate_outcomes(litmus.BUILTIN[name], "mesi")
    assert rep["ok"], (rep["unexpected"], rep["unobserved"],
                       rep["violations"])
    assert set(map(tuple, rep["observed"])) == EXACT_MESI[name]
    assert set(map(tuple, rep["allowed"])) == EXACT_MESI[name]


# -- axiomatic checker on hand-built events -------------------------------


def _ev(node, idx, t, kind, addr, obs, val=None):
    e = {"node": node, "idx": idx, "t": t, "kind": kind,
         "addr": addr, "obs": obs}
    if val is not None:
        e["val"] = val
    return e


def test_axioms_flag_hand_built_coherence_violation():
    """CoRR backwards: a reader sees the new value then the init —
    rf -> po-loc -> fr must close into an SC-per-location cycle."""
    cfg = litmus.litmus_cfg(2)
    events = [_ev(0, 0, 5, "W", 0x01, 65, val=65),
              _ev(1, 0, 10, "R", 0x01, 65),
              _ev(1, 1, 12, "R", 0x01, 1)]
    rep = axioms.check_events(cfg, events)
    checks = [v["check"] for v in rep["violations"]]
    assert "sc_per_location" in checks, rep
    wit = [v for v in rep["violations"]
           if v["check"] == "sc_per_location"][0]["witness"]
    assert len(wit) == 3 and any("-rf->" in w for w in wit) \
        and any("-fr->" in w for w in wit), wit
    # the SC-ordered version of the same history is clean
    ok_events = [_ev(1, 0, 3, "R", 0x01, 1),
                 _ev(0, 0, 5, "W", 0x01, 65, val=65),
                 _ev(1, 1, 12, "R", 0x01, 65)]
    rep = axioms.check_events(cfg, ok_events)
    assert not rep["violations"] and rep["pristine"], rep


# -- consistency mutants: killed by both referees -------------------------


@pytest.mark.parametrize("mutation", sorted(CONSISTENCY_MUTATIONS))
def test_consistency_mutant_killed_by_enumeration(mutation):
    fn, tname, _check, _d, _p = CONSISTENCY_MUTATIONS[mutation]
    rep = litmus.enumerate_outcomes(litmus.BUILTIN[tname], "mesi",
                                    message_phase=fn)
    assert not rep["ok"], f"{mutation} survived litmus {tname}"
    assert rep["unexpected"], rep
    assert all(tuple(o) not in EXACT_MESI[tname]
               for o in rep["unexpected"])


@pytest.mark.parametrize("mutation", sorted(CONSISTENCY_MUTATIONS))
def test_consistency_mutant_oracle_witness_shrinks_and_replays(
        mutation, tmp_path):
    """On the pinned interleaving the axiomatic oracle raises the
    documented check with a rendered cycle; the witness case ddmin-
    shrinks under a same-check predicate and replays through the
    fixture loader."""
    fn, tname, check, delays, periods = CONSISTENCY_MUTATIONS[mutation]
    case = dataclasses.replace(
        litmus.to_fuzz_case(litmus.BUILTIN[tname], 0),
        delays=delays, periods=periods)

    rep = axioms.check_case(case, message_phase=fn)
    vio = [v for v in rep["violations"] if v["check"] == check]
    assert vio, (mutation, rep["violations"], rep["skips"])
    assert vio[0]["witness"], vio
    # the fuzzer's consistency rung sees the same thing
    verdict, detail = fuzz._consistency_join(case, fn, None)
    assert verdict == "consistency" and check in detail, (verdict,
                                                         detail)

    cache = {}

    def pred(items):
        key = tuple(items)
        if key not in cache:
            c = sh._rebuild(case, list(items))
            r = axioms.check_case(c, message_phase=fn)
            cache[key] = any(v["check"] == check
                             for v in r["violations"])
        return cache[key]

    items = sh._flatten(case)
    assert pred(items)
    kept = sh.ddmin(list(items), pred)
    assert pred(kept) and len(kept) <= len(items)
    small = sh._rebuild(case, kept)

    # replayable witness: fixture round-trip preserves the recorded
    # verdict under the mutant engine (run_case's earlier state rung
    # may fire first — the recorded verdict is whatever the full
    # oracle chain says, and replay must reproduce it exactly)
    res = fuzz.run_case(small, fn)
    assert res["verdict"] != "ok"
    d = str(tmp_path / mutation)
    fixtures.write_fixture(d, small, res["verdict"], res["detail"])
    rr = fixtures.replay(d, fn)
    assert rr["reproduced"], (rr["verdict"], rr["expected_verdict"])


def test_membership_check_flags_forbidden_outcome():
    fn, tname, _check, delays, periods = \
        CONSISTENCY_MUTATIONS["skip_inv_fanout"]
    test = litmus.BUILTIN[tname]
    case = dataclasses.replace(litmus.to_fuzz_case(test, 0),
                               delays=delays, periods=periods)
    cfg = case.config()
    rep = axioms.check_case(case, message_phase=fn)
    finding = litmus.check_run_outcome(test, cfg, rep["events"],
                                       rep["final_state"])
    assert finding is not None and "forbidden" in finding["detail"]
    # the clean engine on the same schedule stays in the allowed set
    rep = axioms.check_case(case)
    assert litmus.check_run_outcome(test, cfg, rep["events"],
                                    rep["final_state"]) is None


@pytest.mark.slow
def test_litmus_seeded_fuzz_smoke():
    """Fixed-seed smoke: the litmus seeds ride in the corpus and no
    forbidden outcome / consistency violation appears on the shipped
    handlers."""
    rep = fuzz.fuzz(8, seed=0)
    assert rep["ok"], rep["findings"]
    assert rep["verdicts"].get("ok") == 8


# -- CLI exit contract ----------------------------------------------------


def test_cli_exit_code_matrix(tmp_path):
    import json

    from ue22cs343bb1_openmp_assignment_tpu.analysis import runner
    out = str(tmp_path / "rep.json")
    base = ["--skip-model-check", "--skip-lint", "-q"]
    # 0: clean pass
    assert runner.main(["--litmus", "--litmus-tests", "corr,coww",
                        "--json", out] + base) == 0
    doc = json.load(open(out))
    assert doc["litmus"]["mesi"]["corr"]["ok"] is True
    # 3: budget exhausted, no finding
    assert runner.main(["--litmus", "--litmus-tests", "corr",
                        "--max-states", "10"] + base) == 3
    # 1: the seeded consistency mutant reaches a forbidden outcome;
    # the clean sibling test in the same run stays green and does not
    # mask the finding
    assert runner.main(["--litmus", "--litmus-tests",
                        "mp_upgrade,corr", "--mutation",
                        "skip_inv_fanout"] + base) == 1
    # usage errors
    with pytest.raises(SystemExit):
        runner.main(["--litmus", "--litmus-tests", "nope"] + base)
    with pytest.raises(SystemExit):
        # a consistency mutation outside the litmus/fuzz prongs is
        # rejected with guidance, not silently ignored
        runner.main(["--mutation", "stale_fill_from_invalid"])


def test_dashboard_litmus_matrix_renders():
    from ue22cs343bb1_openmp_assignment_tpu.obs import dashboard
    suite = {"mesi": {
        "corr": {"ok": True, "observed": [[1, 1]], "allowed": [[1, 1]],
                 "unexpected": []},
        "mp": {"ok": False, "observed": [[20, 1], [66, 1]],
               "allowed": [[20, 1]], "unexpected": [[66, 1]]},
        "sb": {"ok": None, "budget_exhausted": True,
               "detail": "> 10 states"}}}
    m = dashboard.build_model([], litmus=suite)
    assert [c["test"] for c in m["litmus"]] == ["corr", "mp", "sb"]
    html = dashboard.render_html(m)
    md = dashboard.render_markdown(m)
    assert "Litmus matrix" in html and "ok (1/1)" in html
    assert "FAIL (2/1)" in md and "budget" in md
    # empty model keeps the placeholder (and the golden svg count)
    m0 = dashboard.build_model([])
    assert m0["litmus"] == []
    assert "no litmus report loaded" in dashboard.render_html(m0)


# -- slow tier: IRIW + the protocol matrix --------------------------------


@pytest.mark.slow
def test_iriw_exact_under_mesi():
    rep = litmus.enumerate_outcomes(litmus.BUILTIN["iriw"], "mesi",
                                    max_states=600_000)
    assert rep["ok"], (rep["unexpected"], rep["unobserved"])
    assert len(rep["observed"]) == len(rep["allowed"]) == 32


@pytest.mark.slow
@pytest.mark.parametrize("protocol", ["moesi", "mesif"])
def test_protocol_sweep(protocol):
    names = [n for n in litmus.SEED_ORDER if n != "iriw"]
    out = litmus.run_suite(tests=names, protocols=(protocol,),
                           max_states=600_000)
    bad = {n: (r["unexpected"], r["unobserved"])
           for n, r in out[protocol].items() if not r["ok"]}
    assert not bad, bad


@pytest.mark.slow
@pytest.mark.parametrize("protocol", ["moesi", "mesif"])
def test_iriw_protocol_variants(protocol):
    rep = litmus.enumerate_outcomes(litmus.BUILTIN["iriw"], protocol,
                                    max_states=600_000)
    assert rep["ok"], (rep["unexpected"], rep["unobserved"])
