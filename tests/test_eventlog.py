"""Structured event tracing (SURVEY §5: the reference's printf-only
-DDEBUG_INSTR/-DDEBUG_MSG tracing, assignment.c:649-652,179-182, rebuilt
as device-side event arrays + byte-compatible host rendering)."""

import os

from tests.conftest import REFERENCE_TESTS, requires_reference
from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
from ue22cs343bb1_openmp_assignment_tpu.utils import eventlog

import pytest


def _run_traced(suite):
    sys_ = CoherenceSystem.from_test_dir(f"{REFERENCE_TESTS}/{suite}")
    sys_, events = sys_.run_traced()
    assert sys_.quiescent
    return sys_, events


@requires_reference
@pytest.mark.parametrize("suite", ["sample", "test_1", "test_2"])
def test_per_node_projection_matches_fixture(suite):
    """Per-node projections of our instr log equal the fixture's —
    per-node order is program order in both engines; only the cross-node
    interleave (OS there, cycle/node-id here) differs."""
    sys_, events = _run_traced(suite)
    ours = eventlog.per_node_projection(
        eventlog.to_lines(events))
    with open(os.path.join(REFERENCE_TESTS, suite,
                           "instruction_order.txt")) as f:
        theirs = eventlog.per_node_projection(f.readlines())
    assert ours == theirs


@requires_reference
def test_line_format_byte_compatible():
    """Rendered lines appear verbatim in the reference fixture."""
    sys_, events = _run_traced("sample")
    lines = set(eventlog.to_lines(events))
    with open(os.path.join(REFERENCE_TESTS, "sample",
                           "instruction_order.txt")) as f:
        fixture = set(l.strip() for l in f if l.strip())
    assert lines == fixture


@requires_reference
def test_msg_events_match_metrics():
    """Message-dequeue event count equals the metrics counter."""
    sys_, events = _run_traced("test_3")
    recs = eventlog.to_records(events)
    n_msgs = sum(1 for r in recs if r["kind"] == "msg")
    assert n_msgs == sum(sys_.metrics["msgs_processed"])
    n_instr = sum(1 for r in recs if r["kind"] == "instr")
    assert n_instr == sum(
        len(open(os.path.join(REFERENCE_TESTS, "test_3",
                              f"core_{n}.txt")).read().splitlines())
        for n in range(4))


@requires_reference
def test_traced_run_state_matches_untraced():
    """Tracing is observation only — final dumps are identical."""
    base = CoherenceSystem.from_test_dir(f"{REFERENCE_TESTS}/test_2")
    a = base.run()
    b, _ = base.run_traced()
    assert a.dumps() == b.dumps()


@requires_reference
def test_cli_trace_log(tmp_path):
    from ue22cs343bb1_openmp_assignment_tpu import cli
    log = tmp_path / "order.txt"
    rc = cli.main(["test_1", "--tests-root", REFERENCE_TESTS,
                   "--out-dir", str(tmp_path), "--trace-log", str(log)])
    assert rc == 0
    ours = eventlog.per_node_projection(log.read_text().splitlines())
    with open(os.path.join(REFERENCE_TESTS, "test_1",
                           "instruction_order.txt")) as f:
        theirs = eventlog.per_node_projection(f.readlines())
    assert ours == theirs
    # golden dumps still written alongside the trace
    assert (tmp_path / "core_0_output.txt").exists()


def test_msg_log_format():
    """--trace-msgs line format mirrors assignment.c:180-181."""
    rec = {"kind": "msg", "cycle": 3, "node": 2, "sender": 1,
           "type": 0, "type_name": "READ_REQUEST", "addr": 0x15}
    assert (eventlog.format_record(rec)
            == "Processor 2 msg from: 1, type: 0, address: 0x15")
    rec = {"kind": "instr", "cycle": 0, "node": 0, "op": 1,
           "addr": 0x05, "value": 200}
    assert (eventlog.format_record(rec)
            == "Processor 0: instr type=W, address=0x05, value=200")


@requires_reference
def test_sync_engine_trace_log_program_order(tmp_path):
    """The sync engine's retirement log (run_rounds_traced +
    eventlog.sync_to_records) projects to per-node program order,
    matching the reference's instruction_order.txt projection for the
    deterministic suite."""
    from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
    from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
    from ue22cs343bb1_openmp_assignment_tpu.state import init_state
    from ue22cs343bb1_openmp_assignment_tpu.utils.trace import load_test_dir

    ref_dir = os.path.join(REFERENCE_TESTS, "test_1")
    cfg = SystemConfig.reference()
    traces = load_test_dir(ref_dir)
    st = se.from_sim_state(cfg, init_state(cfg, traces))
    st, events = se.run_rounds_traced(cfg, st, 64)
    assert bool(st.quiescent())
    lines = [eventlog.format_record(r)
             for r in eventlog.sync_to_records(events)]
    golden = open(f"{ref_dir}/instruction_order.txt").read().splitlines()
    ours = eventlog.per_node_projection(lines)
    theirs = eventlog.per_node_projection(golden)
    assert ours == theirs

    path = str(tmp_path / "order.txt")
    eventlog.write_sync_log(path, events)
    assert open(path).read().splitlines() == lines


FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def _run_mini_traced():
    sys_ = CoherenceSystem.from_test_dir(os.path.join(FIXTURES, "mini"))
    sys_, events = sys_.run_traced()
    assert sys_.quiescent
    return sys_, events


def test_to_records_sorted_by_cycle_then_node():
    """to_records emits the deterministic global interleave: primary
    key cycle, tie-break node id (the engine's replacement for the
    reference's OS-scheduler ordering)."""
    _, events = _run_mini_traced()
    recs = eventlog.to_records(events)
    assert recs, "mini fixture produced no events"
    keys = [(r["cycle"], r["node"]) for r in recs]
    assert keys == sorted(keys)
    # both kinds present and every record carries its decode fields
    kinds = {r["kind"] for r in recs}
    assert kinds == {"instr", "msg"}
    for r in recs:
        if r["kind"] == "instr":
            assert {"op", "addr", "value"} <= set(r)
        else:
            assert {"sender", "type", "type_name", "addr"} <= set(r)


def test_to_lines_byte_parity_with_fixture():
    """Rendered instruction lines reproduce the in-repo
    instruction_order.txt byte-for-byte (the fixture is the engine's
    own deterministic interleave, pinned so format drift is caught)."""
    _, events = _run_mini_traced()
    ours = eventlog.to_lines(events)
    with open(os.path.join(FIXTURES, "mini",
                           "instruction_order.txt")) as f:
        fixture = [line.rstrip("\n") for line in f]
    assert ours == fixture


@requires_reference
def test_multi_txn_window_trace_log_program_order():
    """Multi-transaction windows (txn_width>1) must still emit a
    retirement log whose per-node projection is exact program order."""
    from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
    from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
    from ue22cs343bb1_openmp_assignment_tpu.state import init_state
    from ue22cs343bb1_openmp_assignment_tpu.utils.trace import load_test_dir

    ref_dir = os.path.join(REFERENCE_TESTS, "test_1")
    cfg = SystemConfig.reference(txn_width=3)
    traces = load_test_dir(ref_dir)
    st = se.from_sim_state(cfg, init_state(cfg, traces))
    st, events = se.run_rounds_traced(cfg, st, 64)
    assert bool(st.quiescent())
    lines = [eventlog.format_record(r)
             for r in eventlog.sync_to_records(events)]
    golden = open(f"{ref_dir}/instruction_order.txt").read().splitlines()
    assert (eventlog.per_node_projection(lines)
            == eventlog.per_node_projection(golden))
