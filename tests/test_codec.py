"""Address codec unit tests (reference nibble scheme, assignment.c:46-49)."""

import jax.numpy as jnp

from ue22cs343bb1_openmp_assignment_tpu import codec
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig


def test_reference_nibbles():
    cfg = SystemConfig.reference()
    # 0x36 = block 6 of node 3 (assignment.c:49)
    assert codec.home_node(cfg, 0x36) == 3
    assert codec.block_index(cfg, 0x36) == 6
    assert codec.cache_index(cfg, 0x36) == 6 % 4
    assert codec.make_address(cfg, 3, 6) == 0x36


def test_codec_vectorized():
    cfg = SystemConfig.reference()
    addrs = jnp.array([0x00, 0x0F, 0x15, 0x3F])
    assert codec.home_node(cfg, addrs).tolist() == [0, 0, 1, 3]
    assert codec.block_index(cfg, addrs).tolist() == [0, 15, 5, 15]
    assert codec.cache_index(cfg, addrs).tolist() == [0, 3, 1, 3]


def test_generalized_geometry():
    cfg = SystemConfig.scale(num_nodes=256)
    assert cfg.block_bits == 4
    assert cfg.bitvec_words == 8
    a = codec.make_address(cfg, 200, 9)
    assert codec.home_node(cfg, a) == 200
    assert codec.block_index(cfg, a) == 9


def test_roundtrip_all_reference_addresses():
    cfg = SystemConfig.reference()
    for node in range(4):
        for block in range(16):
            a = codec.make_address(cfg, node, block)
            assert codec.home_node(cfg, a) == node
            assert codec.block_index(cfg, a) == block
            assert a <= 0x3F
