"""Live reference-binary oracle: compile and run the actual reference.

The golden fixtures under /root/reference/tests are stored outputs of
the reference simulator; this test removes the trust in the stored
copies by compiling the reference itself (gcc -fopenmp, its documented
build line), running it on the deterministic suites exactly as its
harness does (background run, fixed grace period, SIGKILL — the
program never exits on its own, reference test3.sh), and diffing OUR
CLI's dumps against the binary's live output byte for byte.

The reference is used strictly as a black-box oracle — nothing is
copied from it; it is built in a temp dir and its outputs are read
back like any fixture.
"""

import os
import shutil
import signal
import subprocess
import time

import pytest

from tests.conftest import REFERENCE_TESTS, requires_reference

REFERENCE_SRC = "/root/reference/assignment.c"

pytestmark = [
    requires_reference,
    pytest.mark.skipif(shutil.which("gcc") is None, reason="needs gcc"),
    pytest.mark.skipif(not os.path.isfile(REFERENCE_SRC),
                       reason="reference source not present"),
]


@pytest.fixture(scope="module")
def reference_binary(tmp_path_factory):
    build = tmp_path_factory.mktemp("refbuild")
    exe = build / "cache_simulator"
    subprocess.run(
        ["gcc", "-fopenmp", "-std=c2x", REFERENCE_SRC, "-o", str(exe)],
        check=True, capture_output=True)
    # the loader hardcodes a tests/ prefix relative to CWD
    os.symlink(os.path.dirname(REFERENCE_TESTS) + "/tests",
               build / "tests")
    return build, exe


def run_reference(build, exe, suite, grace=1.0, deadline=10.0):
    """Run-until-killed, as the reference harness does (test3.sh).

    The harness sleeps a fixed second before the SIGKILL; on a loaded
    host the OpenMP threads may still be writing the four output files
    at that point, so instead of trusting one fixed grace period we
    poll until all four files exist with stable sizes (or a hard
    deadline passes), then kill."""
    outs = [build / f"core_{n}_output.txt" for n in range(4)]
    for out in outs:
        if out.exists():
            out.unlink()
    proc = subprocess.Popen([str(exe), suite], cwd=build,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    time.sleep(grace)
    t0, last, stable = time.monotonic(), None, 0
    while time.monotonic() - t0 < deadline:
        sizes = [out.stat().st_size if out.exists() else -1
                 for out in outs]
        # require a quiet window much longer than one buffered-stdio
        # flush gap, not just two identical samples
        stable = stable + 1 if (min(sizes) >= 0 and sizes == last) else 0
        if stable >= 3:
            break
        last = sizes
        time.sleep(0.25)
    proc.send_signal(signal.SIGKILL)
    proc.wait()
    missing = [out.name for out in outs if not out.exists()]
    assert not missing, (
        f"reference binary produced no {missing} within {deadline}s")
    return {n: outs[n].read_text() for n in range(4)}


@pytest.mark.parametrize("suite", ["sample", "test_1", "test_2"])
def test_cli_matches_live_reference_binary(suite, reference_binary,
                                           tmp_path, monkeypatch):
    build, exe = reference_binary
    theirs = run_reference(build, exe, suite)

    from ue22cs343bb1_openmp_assignment_tpu import cli
    monkeypatch.chdir(tmp_path)
    rc = cli.main([suite, "--tests-root", REFERENCE_TESTS, "--cpu"])
    assert rc == 0
    for n in range(4):
        ours = (tmp_path / f"core_{n}_output.txt").read_text()
        assert ours == theirs[n], (
            f"{suite} core_{n}: CLI dump diverges from the live "
            "reference binary")
