"""Backpressure (admission window) semantics.

The reference silently drops messages on a full mailbox
(``assignment.c:754-762``); at its 4-node/256-slot dimensions overflow is
unreachable, but at scale a dropped reply leaves its requester blocked
forever — livelock (SURVEY quirk 6 calls out the latent deadlock). The
admission window caps outstanding transactions so bounded mailboxes can
never overflow; these tests pin both the failure mode and the fix.
"""

import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
from ue22cs343bb1_openmp_assignment_tpu.native.bindings import NativeEngine
from ue22cs343bb1_openmp_assignment_tpu.ops.step import run_to_quiescence
from ue22cs343bb1_openmp_assignment_tpu.state import init_state

from tests.test_native_differential import (assert_state_equal,
                                            random_traces)


def hot_spot_system(admission, num_nodes=32, queue_capacity=8):
    cfg = SystemConfig.scale(num_nodes=num_nodes,
                             queue_capacity=queue_capacity,
                             admission_window=admission)
    return CoherenceSystem.from_workload(cfg, "false_sharing", trace_len=8,
                                         seed=0)


def test_hot_spot_livelocks_without_admission():
    """Documents the reference-semantics failure mode: overflow drops a
    reply and the machine never quiesces."""
    sys_ = hot_spot_system(admission=None).run(max_cycles=20_000)
    m = sys_.metrics
    assert m["msgs_dropped"] > 0
    assert not sys_.quiescent  # livelocked: blocked nodes wait forever


def test_admission_window_prevents_livelock():
    window = 8 // 6  # Q/6 bound from config docstring
    sys_ = hot_spot_system(admission=max(1, window)).run(max_cycles=50_000)
    m = sys_.metrics
    assert m["msgs_dropped"] == 0
    assert sys_.quiescent
    assert m["instrs_retired"] == 32 * 8  # every instruction completed


def test_admission_differential_with_native():
    """JAX and C++ engines must gate identically (same admitted set)."""
    cfg = SystemConfig(num_nodes=8, cache_size=4, mem_size=16,
                       queue_capacity=16, max_instrs=16,
                       admission_window=2)
    rng = np.random.RandomState(42)
    traces = random_traces(rng, cfg, trace_len=12)
    jx_final = run_to_quiescence(cfg, init_state(cfg, traces), 50_000)
    assert bool(jx_final.quiescent())

    nat = NativeEngine(cfg)
    nat.load_traces(traces)
    nat.run(50_000)
    assert nat.quiescent
    assert_state_equal(jx_final, nat.export_state(), "admission window 2")


def test_parity_configs_unaffected():
    """The reference parity config never gates (admission_window=None)."""
    cfg = SystemConfig.reference()
    assert cfg.admission_window is None


def test_chunked_quiescence_matches_exact_fixpoint():
    """run_chunked_to_quiescence (one-dispatch bench runner) may overshoot
    quiescence by up to chunk-1 cycles; a quiescent state is a fixpoint of
    `cycle` apart from the cycle counters, so the final state and all
    non-cycle metrics must equal the exact per-cycle runner's."""
    from ue22cs343bb1_openmp_assignment_tpu.ops.step import (
        run_chunked_to_quiescence)
    from tests.test_native_differential import FIELDS

    sys_ = hot_spot_system(admission=1, num_nodes=16, queue_capacity=16)
    exact = run_to_quiescence(sys_.cfg, sys_.state, 50_000)
    chunked = run_chunked_to_quiescence(sys_.cfg, sys_.state, 7, 50_000)
    assert bool(exact.quiescent()) and bool(chunked.quiescent())
    for f in FIELDS:
        assert np.array_equal(np.asarray(getattr(exact, f)),
                              np.asarray(getattr(chunked, f))), f
    me, mc = exact.metrics, chunked.metrics
    assert int(me.instrs_retired) == int(mc.instrs_retired)
    assert int(me.msgs_dropped) == int(mc.msgs_dropped)
    assert np.array_equal(np.asarray(me.msgs_processed),
                          np.asarray(mc.msgs_processed))
    # overshoot is bounded by one chunk
    assert int(me.cycles) <= int(mc.cycles) < int(me.cycles) + 7
