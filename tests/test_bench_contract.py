"""bench.py driver contract: exactly ONE JSON line on stdout.

The driver records bench.py's stdout as the round's benchmark result,
so the schema (metric/value/unit/vs_baseline) and the one-line
guarantee are load-bearing across every engine mode; extras must go to
stderr. Smoke configs on CPU keep this fast.
"""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_bench(*args):
    res = subprocess.run(
        [sys.executable, "bench.py", "--smoke", *args],
        capture_output=True, text=True, timeout=600, cwd=REPO_ROOT)
    assert res.returncode == 0, res.stderr[-2000:]
    return res.stdout, res.stderr


@pytest.mark.parametrize("args", [
    (),                                        # deep + procedural (default)
    ("--engine", "sync",),
    ("--engine", "async",),
    ("--no-procedural",),                      # deep on stored traces
    ("--engine", "sync", "--replicas", "2", "--no-procedural"),
    ("--engine", "sync", "--txn-width", "1",),
])
def test_single_json_line_on_stdout(args):
    out, err = run_bench(*args)
    lines = [ln for ln in out.splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout must be one JSON line, got: {out!r}"
    rec = json.loads(lines[0])
    assert set(rec) == {"metric", "value", "unit", "vs_baseline"}
    assert rec["unit"] == "instrs/sec"
    assert rec["value"] > 0
    # vs_baseline is rounded to 4 decimals in the report
    assert rec["vs_baseline"] == pytest.approx(rec["value"] / 1e8,
                                               abs=5e-5)
    extras = json.loads(err.strip().splitlines()[-1])
    assert extras["quiescent"] is True
    assert extras["retired"] > 0


def test_bad_flag_combinations_fail_loudly():
    res = subprocess.run(
        [sys.executable, "bench.py", "--smoke", "--engine", "async",
         "--txn-width", "4"],
        capture_output=True, text=True, timeout=120, cwd=REPO_ROOT)
    assert res.returncode == 2
    assert "--engine sync" in res.stderr
