"""Serving observability: the open-loop soak harness, job-lifecycle
spans, and the latency SLO gate.

Anchors pinned here: the deterministic seeded arrival schedule, the
byte-identical virtual-clock soak doc (two runs, same bytes), the span
decomposition invariant (queue_wait + run + extract == e2e EXACTLY,
with submit stamped at the SCHEDULED arrival — the
coordinated-omission guard), both backpressure regimes (under- and
over-loaded), the SLO breach rc-4 path with its flight-recorder
incident directory, and the bench-diff latency gate verdict matrix
(regression / noise / incomparable) over v1.4 latency blocks.
"""

import json

import pytest

from ue22cs343bb1_openmp_assignment_tpu import soak
from ue22cs343bb1_openmp_assignment_tpu.obs import (history, regress,
                                                    timeseries)
from ue22cs343bb1_openmp_assignment_tpu.obs.clock import VirtualClock
from ue22cs343bb1_openmp_assignment_tpu.serve import JobSpec

# small slot shape shared by every soak here: waves stay cheap and the
# wave jit is warmed once for the whole module
SOAK = dict(slots=2, arrival_rate=50.0)


def _arrivals(rate=50.0, duration=0.3, seed=0):
    return soak.soak_stream(rate, duration, nodes=2, trace_len=4,
                            seed=seed)


# -- arrival schedule ------------------------------------------------------


def test_soak_stream_deterministic():
    a = _arrivals()
    b = _arrivals()
    assert a == b                      # same seed, same bytes
    assert a != _arrivals(seed=1)
    assert all(t0 < t1 for (t0, _), (t1, _) in zip(a, a[1:]))
    assert all(0.0 < t < 0.3 for t, _ in a)
    # mixed traffic: the mix cycles through the serve workload set
    assert len({s.workload for _, s in a}) > 1
    with pytest.raises(ValueError, match="arrival_rate"):
        soak.soak_stream(0.0, 1.0)
    with pytest.raises(ValueError, match="duration_s"):
        soak.soak_stream(1.0, 0.0)


def test_soak_single_protocol_enforced():
    arr = [(0.0, JobSpec(name="a", nodes=2, trace_len=4)),
           (0.01, JobSpec(name="b", nodes=2, trace_len=4,
                          protocol="msi"))]
    with pytest.raises(ValueError, match="single-protocol"):
        soak.soak(arr, **SOAK)


# -- virtual-clock determinism and the span invariant ----------------------


def _virtual_soak(wave_s=0.01):
    return soak.soak(_arrivals(), clock=VirtualClock(wave_s=wave_s),
                     **SOAK)


def test_soak_virtual_clock_byte_identical():
    a = _virtual_soak()
    b = _virtual_soak()
    assert json.dumps(a, sort_keys=True) == \
        json.dumps(b, sort_keys=True)
    assert a["schema"] == "cache-sim/soak/v1"
    assert a["jobs_total"] == len(_arrivals())
    assert a["jobs_quiesced"] == a["jobs_total"]
    assert a["trace"]["schema"] == "cache-sim/serve-trace/v1"
    assert a["trace"]["clock"] == "virtual"
    assert a["latency"]["jobs"] == a["jobs_total"]
    # the host series sampled every turn, summarized for the verdict
    assert a["series"]["samples"] == len(a["series"]["series"]["t_s"])
    assert a["series_summary"]["queue_depth_peak"] >= 0
    assert 0.0 <= a["padding_waste"] <= 1.0


def test_span_decomposition_invariant():
    doc = _virtual_soak()
    arrivals = dict((s.name, t) for t, s in _arrivals())
    assert len(doc["trace"]["spans"]) == len(arrivals)
    for s in doc["trace"]["spans"]:
        # segments sum EXACTLY (floats included) — computed in one
        # place from the lifecycle timestamps, never re-derived
        assert s["e2e_s"] == \
            s["queue_wait_s"] + s["run_s"] + s["extract_s"]
        assert s["t_submit"] <= s["t_admitted"] <= s["t_quiescent"] \
            <= s["t_extracted"]
        # open loop: submit is the SCHEDULED arrival (the virtual
        # clock starts at t0=0, so offsets compare directly) — a busy
        # machine cannot slow the load generator down
        assert s["t_submit"] == pytest.approx(arrivals[s["job"]])
        assert s["quiesced"] is True


def test_backpressure_regimes():
    # fast waves: the machine drains faster than jobs arrive
    under = _virtual_soak(wave_s=0.001)
    assert not under["verdict"]["saturated"]
    assert under["verdict"]["drain_rate_jobs_per_s"] > 50.0
    # slow waves: arrivals outpace the drain and the queue backs up
    over = _virtual_soak(wave_s=0.2)
    assert over["verdict"]["saturated"]
    assert over["verdict"]["queue_depth_peak"] > \
        under["verdict"]["queue_depth_peak"]
    # saturation never loses jobs: everything still quiesces
    assert over["jobs_quiesced"] == over["jobs_total"]


# -- SLO parsing and the gate ----------------------------------------------


def test_parse_slo():
    assert soak.parse_slo("p95=5,p99=20") == {"p95_ms": 5.0,
                                              "p99_ms": 20.0}
    assert soak.parse_slo(" p50 = 1.5 ") == {"p50_ms": 1.5}
    with pytest.raises(ValueError, match="unknown SLO metric"):
        soak.parse_slo("p42=1")
    with pytest.raises(ValueError, match="bad SLO term"):
        soak.parse_slo("p95")
    with pytest.raises(ValueError, match="bad SLO bound"):
        soak.parse_slo("p95=fast")
    with pytest.raises(ValueError, match="must be > 0"):
        soak.parse_slo("p95=0")
    with pytest.raises(ValueError, match="empty SLO spec"):
        soak.parse_slo(",")


def test_check_slo():
    lat = {"p50_ms": 1.0, "p95_ms": 5.0, "p99_ms": 9.0}
    assert soak.check_slo(lat, {"p95_ms": 10.0}) == []
    br = soak.check_slo(lat, {"p50_ms": 0.5, "p95_ms": 10.0})
    assert br == [{"metric": "p50_ms", "limit_ms": 0.5,
                   "observed_ms": 1.0}]
    assert soak.check_slo(None, {"p95_ms": 0.001}) == []


_CLI = ["--arrival-rate", "50", "--duration", "0.3", "--nodes", "2",
        "--trace-len", "4", "--slots", "2", "--virtual-clock",
        "--wave-s", "0.01"]


def test_soak_cli_slo_pass(tmp_path, capsys):
    out = tmp_path / "soak.json"
    rc = soak.main(_CLI + ["--slo", "p95=100000", "--out", str(out)])
    assert rc == 0
    cap = capsys.readouterr()
    assert "keeping up" in cap.out or "SATURATED" in cap.out
    doc = json.loads(out.read_text())
    assert doc["schema"] == "cache-sim/soak/v1"
    assert doc["jobs_quiesced"] == doc["jobs_total"]


def test_soak_cli_slo_breach_exit4_and_incident(tmp_path, capsys):
    inc_dir = tmp_path / "incident"
    # virtual run_s is wave_s = 10ms per wave, so a 0.001ms p95 bound
    # must breach deterministically
    rc = soak.main(_CLI + ["--slo", "p95=0.001",
                           "--incident-dir", str(inc_dir)])
    assert rc == soak.EXIT_SLO_BREACH == 4
    cap = capsys.readouterr()
    assert "SLO BREACH p95_ms" in cap.err
    assert "incident dumped" in cap.err
    inc = soak.load_incident(inc_dir)
    assert inc["reason"] == "slo-breach"
    assert inc["breaches"][0]["metric"] == "p95_ms"
    assert inc["breaches"][0]["observed_ms"] > \
        inc["breaches"][0]["limit_ms"]
    # slowest-first, full spans, capped at INCIDENT_SLOWEST
    slow = inc["slowest_jobs"]
    assert 0 < len(slow) <= soak.INCIDENT_SLOWEST
    assert all(x["e2e_s"] >= y["e2e_s"]
               for x, y in zip(slow, slow[1:]))
    assert {"job", "t_submit", "queue_wait_s", "run_s",
            "extract_s"} <= set(slow[0])
    # the Perfetto rendering rides along, listed in files
    assert sorted(inc["files"]) == ["incident.json",
                                    "trace.perfetto.json"]
    trace = json.loads((inc_dir / "trace.perfetto.json").read_text())
    assert any(ev.get("ph") == "X" for ev in trace["traceEvents"])
    # a bad schema id is rejected on load
    (inc_dir / "incident.json").write_text(
        json.dumps({"schema": "nope"}))
    with pytest.raises(ValueError, match="schema"):
        soak.load_incident(inc_dir)


# -- the bench-diff latency gate over v1.4 entries -------------------------


def _lat_entry(label, scale=1.0, rate=40.0, n=40, device="cpu",
               saturated=None):
    lat_s = [0.002 * (1.0 + 0.05 * (i % 17)) * scale
             for i in range(n)]
    lat = timeseries.latency_summary(lat_s, arrival_rate=rate,
                                     queue_depth_peak=3)
    lat["samples_ms"] = [round(s * 1000.0, 6) for s in lat_s]
    if saturated is not None:
        lat["saturated"] = saturated
    e = history.entry(
        label=label, source="test",
        result={"metric": "soak p95 job latency", "value": lat["p95_ms"],
                "unit": "ms p95"},
        extra={"engine": "async", "rep_times_s": [0.1]},
        device_kind=device, latency=lat)
    return history.validate_entry(e)


def test_compare_latency_verdict_matrix():
    a = _lat_entry("base")
    # self-compare: zero delta is noise, never a regression
    rep = regress.compare_latency(a, _lat_entry("again"))
    assert rep["verdict"] == "noise"
    assert rep["delta_pct"] == 0.0
    # +20% uniform scaling over 40 samples/side: the rank test has
    # power (PERF.md: >= 20 samples/side for a 1.2x shift) and the p95
    # delta clears the practical bar
    rep = regress.compare_latency(a, _lat_entry("slow", scale=1.2))
    assert rep["verdict"] == "regression"
    assert rep["p"] is not None and rep["p"] <= rep["alpha"]
    assert rep["delta_pct"] == pytest.approx(20.0, abs=0.1)
    # and the mirror image is an improvement
    rep = regress.compare_latency(_lat_entry("slow", scale=1.2), a)
    assert rep["verdict"] == "improvement"
    # different offered load = different operating point
    rep = regress.compare_latency(a, _lat_entry("othr", rate=80.0))
    assert rep["verdict"] == "incomparable"
    assert "arrival_rate_mismatch" in rep["flags"]
    # no latency block on one side
    bare = history.entry(
        label="bare", source="test",
        result={"metric": "soak p95 job latency", "value": 1.0,
                "unit": "ms p95"},
        extra={"engine": "async", "rep_times_s": [0.1]},
        device_kind="cpu")
    rep = regress.compare_latency(a, bare)
    assert rep["verdict"] == "incomparable"
    assert "bench.py --soak" in rep["detail"]
    # cross-device latency is never compared
    rep = regress.compare_latency(a, _lat_entry("tpu", device="tpu"))
    assert rep["verdict"] == "incomparable"
    # a saturated side is flagged, not silently averaged in
    rep = regress.compare_latency(a, _lat_entry("sat", saturated=True))
    assert "saturated:b" in rep["flags"]
    # every verdict formats without raising
    assert "bench-diff --latency" in regress.format_latency_report(rep)


def test_compare_latency_low_power():
    rep = regress.compare_latency(_lat_entry("a", n=2),
                                  _lat_entry("b", n=2, scale=1.2))
    assert "low_power" in rep["flags"]
    assert rep["p"] is None
