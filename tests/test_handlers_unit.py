"""Per-handler MESI/directory transition tests.

The reference has zero unit tests (SURVEY §4) — its entire contract is
end-state golden diffs. These tests pin each handler's transition table
(SURVEY §2 "C8 per-handler detail") directly, including the quirky
behaviors that golden tests only exercise incidentally.

Each test stages one node's state, injects one message (or one
instruction), runs exactly one cycle, and asserts the masked updates and
emitted messages.
"""

import jax.numpy as jnp
import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops.mailbox import push_message
from ue22cs343bb1_openmp_assignment_tpu.ops.step import cycle
from ue22cs343bb1_openmp_assignment_tpu.state import (MB_ADDR, MB_BV0,
                                                      MB_DIRSTATE, MB_SECOND,
                                                      MB_SENDER, MB_TYPE,
                                                      MB_VALUE, init_state)
from ue22cs343bb1_openmp_assignment_tpu.types import (CacheState, DirState,
                                                      Msg, Op)

CFG = SystemConfig.reference()


def fresh():
    return init_state(CFG)


def inbox(state, node):
    """All messages currently queued at `node` as dicts, FIFO order."""
    out = []
    h, c = int(state.mb_head[node]), int(state.mb_count[node])
    for i in range(c):
        s = (h + i) % CFG.queue_capacity
        row = state.mb_pack[:, node, s]
        out.append(dict(type=Msg(int(row[MB_TYPE])),
                        sender=int(row[MB_SENDER]),
                        addr=int(row[MB_ADDR]),
                        value=int(row[MB_VALUE]),
                        second=int(row[MB_SECOND]),
                        dirstate=int(row[MB_DIRSTATE]),
                        bitvec=int(np.uint32(row[MB_BV0]))))
    return out


def set_cache(state, node, idx, addr, value, cstate):
    return state.replace(
        cache_addr=state.cache_addr.at[node, idx].set(addr),
        cache_val=state.cache_val.at[node, idx].set(value),
        cache_state=state.cache_state.at[node, idx].set(int(cstate)))


def set_dir(state, node, block, dstate, bitvec):
    return state.replace(
        dir_state=state.dir_state.at[node, block].set(int(dstate)),
        dir_bitvec=state.dir_bitvec.at[node, block, 0].set(bitvec))


# ---------------------------------------------------------------------------
# READ_REQUEST at home (assignment.c:191-237)

def test_read_request_unowned():
    st = fresh()
    st = push_message(CFG, st, 1, type=Msg.READ_REQUEST, sender=3, addr=0x15)
    st2 = cycle(CFG, st)
    # home replies with memory value, dirState=EM; directory U -> EM {3}
    [msg] = inbox(st2, 3)
    assert msg["type"] == Msg.REPLY_RD
    assert msg["value"] == 20 * 1 + 5
    assert msg["dirstate"] == int(DirState.EM)
    assert int(st2.dir_state[1, 5]) == int(DirState.EM)
    assert int(st2.dir_bitvec[1, 5, 0]) == 0b1000


def test_read_request_shared_adds_sharer():
    st = set_dir(fresh(), 1, 5, DirState.S, 0b0001)
    st = push_message(CFG, st, 1, type=Msg.READ_REQUEST, sender=2, addr=0x15)
    st2 = cycle(CFG, st)
    [msg] = inbox(st2, 2)
    assert msg["type"] == Msg.REPLY_RD and msg["dirstate"] == int(DirState.S)
    assert int(st2.dir_bitvec[1, 5, 0]) == 0b0101
    assert int(st2.dir_state[1, 5]) == int(DirState.S)


def test_read_request_em_forwards_writeback_int_and_defers_dir():
    """Quirk 4: dir untouched until FLUSH returns (assignment.c:199-210)."""
    st = set_dir(fresh(), 1, 5, DirState.EM, 0b0100)  # owner = node 2
    st = push_message(CFG, st, 1, type=Msg.READ_REQUEST, sender=0, addr=0x15)
    st2 = cycle(CFG, st)
    [msg] = inbox(st2, 2)
    assert msg["type"] == Msg.WRITEBACK_INT
    assert msg["second"] == 0 and msg["sender"] == 1
    # directory deliberately unchanged until FLUSH
    assert int(st2.dir_state[1, 5]) == int(DirState.EM)
    assert int(st2.dir_bitvec[1, 5, 0]) == 0b0100


# ---------------------------------------------------------------------------
# REPLY_RD at requester (assignment.c:239-255)

def test_reply_rd_fills_exclusive_or_shared():
    st = fresh()
    st = push_message(CFG, st, 2, type=Msg.REPLY_RD, sender=1, addr=0x15,
                      value=77, dirstate=DirState.EM)
    st2 = cycle(CFG, st)
    assert int(st2.cache_addr[2, 1]) == 0x15
    assert int(st2.cache_val[2, 1]) == 77
    assert int(st2.cache_state[2, 1]) == int(CacheState.EXCLUSIVE)
    assert not bool(st2.waiting[2])

    st = push_message(CFG, fresh(), 2, type=Msg.REPLY_RD, sender=1,
                      addr=0x15, value=9, dirstate=DirState.S)
    st2 = cycle(CFG, st)
    assert int(st2.cache_state[2, 1]) == int(CacheState.SHARED)


def test_reply_rd_evicts_conflicting_line():
    st = set_cache(fresh(), 2, 1, 0x25, 99, CacheState.MODIFIED)
    st = push_message(CFG, st, 2, type=Msg.REPLY_RD, sender=1, addr=0x15,
                      value=7, dirstate=DirState.EM)
    st2 = cycle(CFG, st)
    # dirty line 0x25 -> EVICT_MODIFIED with value to its home (node 2)
    msgs = inbox(st2, 2)
    assert [m["type"] for m in msgs] == [Msg.EVICT_MODIFIED]
    assert msgs[0]["addr"] == 0x25 and msgs[0]["value"] == 99
    assert int(st2.cache_addr[2, 1]) == 0x15


# ---------------------------------------------------------------------------
# WRITEBACK_INT at old owner (assignment.c:257-286)

def test_writeback_int_flushes_and_demotes():
    st = set_cache(fresh(), 2, 1, 0x15, 55, CacheState.MODIFIED)
    st = push_message(CFG, st, 2, type=Msg.WRITEBACK_INT, sender=1,
                      addr=0x15, second=0)
    st2 = cycle(CFG, st)
    assert int(st2.cache_state[2, 1]) == int(CacheState.SHARED)
    [at_home] = inbox(st2, 1)
    [at_req] = inbox(st2, 0)
    for m in (at_home, at_req):
        assert m["type"] == Msg.FLUSH and m["value"] == 55 and m["second"] == 0


def test_writeback_int_dedups_home_eq_requester():
    """Quirk 3 (first half): single FLUSH when home == requester
    (assignment.c:281)."""
    st = set_cache(fresh(), 2, 1, 0x15, 55, CacheState.EXCLUSIVE)
    st = push_message(CFG, st, 2, type=Msg.WRITEBACK_INT, sender=1,
                      addr=0x15, second=1)
    st2 = cycle(CFG, st)
    assert len(inbox(st2, 1)) == 1  # one FLUSH, not two


# ---------------------------------------------------------------------------
# FLUSH (assignment.c:288-323)

def test_flush_at_home_updates_dir_and_memory():
    st = set_dir(fresh(), 1, 5, DirState.EM, 0b0100)
    st = push_message(CFG, st, 1, type=Msg.FLUSH, sender=2, addr=0x15,
                      value=55, second=0)
    st2 = cycle(CFG, st)
    assert int(st2.dir_state[1, 5]) == int(DirState.S)
    assert int(st2.dir_bitvec[1, 5, 0]) == 0b0101  # requester ORed in
    assert int(st2.memory[1, 5]) == 55


def test_flush_at_requester_fills_shared():
    st = push_message(CFG, fresh(), 0, type=Msg.FLUSH, sender=2, addr=0x15,
                      value=55, second=0)
    st = st.replace(waiting=st.waiting.at[0].set(True))
    st2 = cycle(CFG, st)
    assert int(st2.cache_state[0, 1]) == int(CacheState.SHARED)
    assert int(st2.cache_val[0, 1]) == 55
    assert not bool(st2.waiting[0])


def test_flush_unconditionally_unblocks_pure_home():
    """Quirk 2: a node acting only as home still clears waitingForReply
    (assignment.c:322)."""
    st = fresh().replace(waiting=jnp.zeros(4, bool).at[1].set(True))
    st = push_message(CFG, st, 1, type=Msg.FLUSH, sender=2, addr=0x15,
                      value=1, second=0)  # node 1 is home, not requester
    st2 = cycle(CFG, st)
    assert not bool(st2.waiting[1])


# ---------------------------------------------------------------------------
# UPGRADE / REPLY_ID / INV (assignment.c:325-399)

def test_upgrade_returns_other_sharers_and_takes_ownership():
    st = set_dir(fresh(), 1, 5, DirState.S, 0b1101)
    st = push_message(CFG, st, 1, type=Msg.UPGRADE, sender=0, addr=0x15)
    st2 = cycle(CFG, st)
    [msg] = inbox(st2, 0)
    assert msg["type"] == Msg.REPLY_ID
    assert msg["bitvec"] == 0b1100  # requester excluded (assignment.c:335)
    assert int(st2.dir_state[1, 5]) == int(DirState.EM)
    assert int(st2.dir_bitvec[1, 5, 0]) == 0b0001


def test_reply_id_fans_out_inv_and_fills_from_latched_instr():
    """Quirk 1: fill value comes from the in-flight instruction, not the
    message (assignment.c:383)."""
    st = fresh().replace(cur_val=jnp.zeros(4, jnp.int32).at[0].set(123),
                         waiting=jnp.zeros(4, bool).at[0].set(True))
    st = push_message(CFG, st, 0, type=Msg.REPLY_ID, sender=1, addr=0x15,
                      bitvec=0b1100)
    st2 = cycle(CFG, st)
    for sharer in (2, 3):
        [msg] = inbox(st2, sharer)
        assert msg["type"] == Msg.INV and msg["addr"] == 0x15
    assert int(st2.cache_val[0, 1]) == 123
    assert int(st2.cache_state[0, 1]) == int(CacheState.MODIFIED)
    assert not bool(st2.waiting[0])


def test_inv_only_applies_on_tag_match():
    st = set_cache(fresh(), 2, 1, 0x15, 5, CacheState.SHARED)
    st = push_message(CFG, st, 2, type=Msg.INV, sender=0, addr=0x15)
    st2 = cycle(CFG, st)
    assert int(st2.cache_state[2, 1]) == int(CacheState.INVALID)

    # different tag in the same slot -> untouched (assignment.c:396)
    st = set_cache(fresh(), 2, 1, 0x25, 5, CacheState.SHARED)
    st = push_message(CFG, st, 2, type=Msg.INV, sender=0, addr=0x15)
    st2 = cycle(CFG, st)
    assert int(st2.cache_state[2, 1]) == int(CacheState.SHARED)


# ---------------------------------------------------------------------------
# WRITE_REQUEST / REPLY_WR / WRITEBACK_INV / FLUSH_INVACK
# (assignment.c:401-536)

def test_write_request_unowned():
    st = push_message(CFG, fresh(), 1, type=Msg.WRITE_REQUEST, sender=3,
                      addr=0x15, value=42)
    st2 = cycle(CFG, st)
    [msg] = inbox(st2, 3)
    assert msg["type"] == Msg.REPLY_WR
    assert int(st2.dir_state[1, 5]) == int(DirState.EM)
    assert int(st2.dir_bitvec[1, 5, 0]) == 0b1000


def test_write_request_shared_sends_reply_id():
    st = set_dir(fresh(), 1, 5, DirState.S, 0b0111)
    st = push_message(CFG, st, 1, type=Msg.WRITE_REQUEST, sender=0,
                      addr=0x15, value=42)
    st2 = cycle(CFG, st)
    [msg] = inbox(st2, 0)
    assert msg["type"] == Msg.REPLY_ID and msg["bitvec"] == 0b0110
    assert int(st2.dir_bitvec[1, 5, 0]) == 0b0001


def test_write_request_em_sends_writeback_inv_and_updates_dir_now():
    """Quirk 4 (second half): write path updates the directory
    immediately and unconditionally (assignment.c:455-457)."""
    st = set_dir(fresh(), 1, 5, DirState.EM, 0b0100)
    st = push_message(CFG, st, 1, type=Msg.WRITE_REQUEST, sender=0,
                      addr=0x15, value=42)
    st2 = cycle(CFG, st)
    [msg] = inbox(st2, 2)  # old owner
    assert msg["type"] == Msg.WRITEBACK_INV and msg["second"] == 0
    assert int(st2.dir_state[1, 5]) == int(DirState.EM)
    assert int(st2.dir_bitvec[1, 5, 0]) == 0b0001  # already the requester


def test_reply_wr_unconditional_replacement_call():
    """REPLY_WR calls handleCacheReplacement without the tag-mismatch
    check (assignment.c:467) — a clean E line is evicted even for the
    same address."""
    st = set_cache(fresh(), 3, 1, 0x15, 7, CacheState.EXCLUSIVE)
    st = st.replace(cur_val=jnp.zeros(4, jnp.int32).at[3].set(42))
    st = push_message(CFG, st, 3, type=Msg.REPLY_WR, sender=1, addr=0x15)
    st2 = cycle(CFG, st)
    [ev] = inbox(st2, 1)
    assert ev["type"] == Msg.EVICT_SHARED and ev["addr"] == 0x15
    assert int(st2.cache_val[3, 1]) == 42
    assert int(st2.cache_state[3, 1]) == int(CacheState.MODIFIED)


def test_writeback_inv_no_dedup_double_send():
    """Quirk 3 (second half): home==requester receives FLUSH_INVACK twice
    (assignment.c:492-498)."""
    st = set_cache(fresh(), 2, 1, 0x15, 88, CacheState.MODIFIED)
    st = push_message(CFG, st, 2, type=Msg.WRITEBACK_INV, sender=1,
                      addr=0x15, second=1)  # home 1 == requester 1
    st2 = cycle(CFG, st)
    msgs = inbox(st2, 1)
    assert [m["type"] for m in msgs] == [Msg.FLUSH_INVACK, Msg.FLUSH_INVACK]
    assert all(m["value"] == 88 for m in msgs)
    assert int(st2.cache_state[2, 1]) == int(CacheState.INVALID)


def test_flush_invack_at_home_and_requester():
    st = set_dir(fresh(), 1, 5, DirState.EM, 0b0001)
    st = push_message(CFG, st, 1, type=Msg.FLUSH_INVACK, sender=2,
                      addr=0x15, value=66, second=0)
    st2 = cycle(CFG, st)
    assert int(st2.dir_bitvec[1, 5, 0]) == 0b0001
    assert int(st2.memory[1, 5]) == 66

    st = fresh().replace(cur_val=jnp.zeros(4, jnp.int32).at[0].set(42),
                         waiting=jnp.zeros(4, bool).at[0].set(True))
    st = push_message(CFG, st, 0, type=Msg.FLUSH_INVACK, sender=2,
                      addr=0x15, value=66, second=0)
    st2 = cycle(CFG, st)
    assert int(st2.cache_val[0, 1]) == 42  # latched instr value, not 66
    assert int(st2.cache_state[0, 1]) == int(CacheState.MODIFIED)
    assert not bool(st2.waiting[0])


# ---------------------------------------------------------------------------
# EVICT_SHARED / EVICT_MODIFIED (assignment.c:538-617)

def test_evict_shared_last_sharer_promotion():
    st = set_dir(fresh(), 1, 5, DirState.S, 0b0101)
    st = push_message(CFG, st, 1, type=Msg.EVICT_SHARED, sender=0, addr=0x15)
    st2 = cycle(CFG, st)
    assert int(st2.dir_state[1, 5]) == int(DirState.EM)
    assert int(st2.dir_bitvec[1, 5, 0]) == 0b0100
    [msg] = inbox(st2, 2)  # remaining sharer told to promote S -> E
    assert msg["type"] == Msg.EVICT_SHARED
    # ... and the recipient blindly promotes (no tag check,
    # assignment.c:558)
    st3 = cycle(CFG, st2)
    assert int(st3.cache_state[2, 1]) == int(CacheState.EXCLUSIVE)


def test_evict_shared_home_self_promotion():
    st = set_dir(fresh(), 1, 5, DirState.S, 0b0011)
    st = set_cache(st, 1, 1, 0x15, 3, CacheState.SHARED)
    st = push_message(CFG, st, 1, type=Msg.EVICT_SHARED, sender=0, addr=0x15)
    st2 = cycle(CFG, st)
    # home itself is the last sharer: inline promotion (assignment.c:584-587)
    assert int(st2.cache_state[1, 1]) == int(CacheState.EXCLUSIVE)
    assert int(st2.dir_state[1, 5]) == int(DirState.EM)
    assert all(len(inbox(st2, n)) == 0 for n in range(4))


def test_evict_shared_to_unowned():
    st = set_dir(fresh(), 1, 5, DirState.EM, 0b0100)
    st = push_message(CFG, st, 1, type=Msg.EVICT_SHARED, sender=2, addr=0x15)
    st2 = cycle(CFG, st)
    assert int(st2.dir_state[1, 5]) == int(DirState.U)
    assert int(st2.dir_bitvec[1, 5, 0]) == 0


def test_evict_modified_writes_back_and_clears():
    st = set_dir(fresh(), 1, 5, DirState.EM, 0b0100)
    st = push_message(CFG, st, 1, type=Msg.EVICT_MODIFIED, sender=2,
                      addr=0x15, value=201)
    st2 = cycle(CFG, st)
    assert int(st2.memory[1, 5]) == 201
    assert int(st2.dir_state[1, 5]) == int(DirState.U)
    assert int(st2.dir_bitvec[1, 5, 0]) == 0


# ---------------------------------------------------------------------------
# Instruction frontend (assignment.c:654-735)

def set_instr(state, node, instrs):
    for i, (op, addr, val) in enumerate(instrs):
        state = state.replace(
            instr_op=state.instr_op.at[node, i].set(int(op)),
            instr_addr=state.instr_addr.at[node, i].set(addr),
            instr_val=state.instr_val.at[node, i].set(val))
    return state.replace(
        instr_count=state.instr_count.at[node].set(len(instrs)))


def test_read_miss_blocks_on_read_request():
    st = set_instr(fresh(), 0, [(Op.READ, 0x15, 0)])
    st2 = cycle(CFG, st)
    [msg] = inbox(st2, 1)
    assert msg["type"] == Msg.READ_REQUEST and msg["sender"] == 0
    assert bool(st2.waiting[0])
    assert int(st2.instr_idx[0]) == 0


def test_write_hit_exclusive_goes_modified_locally():
    st = set_cache(fresh(), 0, 1, 0x15, 7, CacheState.EXCLUSIVE)
    st = set_instr(st, 0, [(Op.WRITE, 0x15, 99)])
    st2 = cycle(CFG, st)
    assert int(st2.cache_val[0, 1]) == 99
    assert int(st2.cache_state[0, 1]) == int(CacheState.MODIFIED)
    assert not bool(st2.waiting[0])
    assert all(len(inbox(st2, n)) == 0 for n in range(4))


def test_write_hit_shared_sends_upgrade():
    st = set_cache(fresh(), 0, 1, 0x15, 7, CacheState.SHARED)
    st = set_instr(st, 0, [(Op.WRITE, 0x15, 99)])
    st2 = cycle(CFG, st)
    [msg] = inbox(st2, 1)
    assert msg["type"] == Msg.UPGRADE and msg["value"] == 99
    assert bool(st2.waiting[0])


def test_message_processing_preempts_instruction_fetch():
    """Drain-before-fetch priority (assignment.c:165-177)."""
    st = set_instr(fresh(), 2, [(Op.READ, 0x20, 0)])
    st = push_message(CFG, st, 2, type=Msg.INV, sender=0, addr=0x15)
    st2 = cycle(CFG, st)
    assert int(st2.instr_idx[2]) == -1  # instruction not fetched yet
    st3 = cycle(CFG, st2)
    assert int(st3.instr_idx[2]) == 0
