"""Tiled uint32 sharer-bitvector helpers (replaces the reference's single
byte, assignment.c:63; enables >8 nodes)."""

import jax.numpy as jnp
import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.state import (bit_get, bit_set,
                                                      bit_single, ctz,
                                                      popcount)


def test_single_word():
    n = jnp.array([0, 5, 31])
    bv = bit_single(1, n)
    assert bv.tolist() == [[1], [1 << 5], [1 << 31]]
    assert bit_get(bv, n).tolist() == [True, True, True]
    assert popcount(bv).tolist() == [1, 1, 1]
    assert ctz(bv).tolist() == [0, 5, 31]


def test_multi_word():
    n = jnp.array([0, 32, 95, 64])
    bv = bit_single(3, n)
    assert bit_get(bv, n).tolist() == [True] * 4
    assert ctz(bv).tolist() == [0, 32, 95, 64]
    assert popcount(bv).tolist() == [1] * 4
    # clearing returns to empty
    cleared = bit_set(bv, n, on=False)
    assert popcount(cleared).tolist() == [0] * 4
    assert ctz(cleared).tolist() == [96] * 4  # sentinel = num bits


def test_set_accumulates():
    bv = jnp.zeros((1, 2), jnp.uint32)
    for node in (0, 33, 63):
        bv = bit_set(bv, jnp.array([node]))
    assert popcount(bv).tolist() == [3]
    assert ctz(bv).tolist() == [0]
    assert bit_get(bv, jnp.array([33])).tolist() == [True]
    assert bit_get(bv, jnp.array([34])).tolist() == [False]


def test_matches_reference_byte_semantics():
    # __builtin_ctz / __builtin_popcount on the byte vector
    # (assignment.c:209,451,564)
    rng = np.random.RandomState(0)
    for _ in range(50):
        b = int(rng.randint(1, 256))
        bv = jnp.array([[b]], jnp.uint32)
        assert int(popcount(bv)[0]) == bin(b).count("1")
        assert int(ctz(bv)[0]) == (b & -b).bit_length() - 1
