"""Interleaving replay from the fixture order logs (VERDICT r2 #8).

``instruction_order.txt`` records the exact global interleaving behind
each golden set (``assignment.c:649-652``). With
``state.order_rank`` set (utils.order_replay), the machine must (a)
reproduce the goldens byte-exact under the recorded order and (b)
issue instructions in *exactly* that order — asserted line-for-line
against the fixture log itself.
"""

import os

import numpy as np
import pytest

from tests.conftest import REFERENCE_TESTS, requires_reference

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops.step import run_cycles_traced
from ue22cs343bb1_openmp_assignment_tpu.state import init_state
from ue22cs343bb1_openmp_assignment_tpu.utils import eventlog, order_replay
from ue22cs343bb1_openmp_assignment_tpu.utils.golden import (
    format_node_dump, state_to_dumps)
from ue22cs343bb1_openmp_assignment_tpu.utils.trace import load_test_dir

CFG = SystemConfig.reference()


def _fixture_lines(suite_dir):
    with open(os.path.join(suite_dir, "instruction_order.txt")) as f:
        return [ln.strip() for ln in f if ln.strip()]


def _replay(suite_dir, traces, order):
    st = init_state(CFG, traces, order_rank=order)
    final, events = run_cycles_traced(CFG, st, 1500)
    assert bool(final.quiescent()), "replay did not quiesce"
    dumps = [format_node_dump(d) for d in state_to_dumps(CFG, final)]
    return dumps, eventlog.to_lines(events)


@requires_reference
@pytest.mark.parametrize("suite", ["sample", "test_1", "test_2"])
def test_replay_reproduces_golden_and_log(suite):
    suite_dir = os.path.join(REFERENCE_TESTS, suite)
    traces = load_test_dir(suite_dir)
    order = order_replay.load_order_rank(CFG, suite_dir, traces)
    dumps, got_lines = _replay(suite_dir, traces, order)
    for n in range(CFG.num_nodes):
        golden = open(f"{suite_dir}/core_{n}_output.txt").read()
        assert dumps[n] == golden, f"{suite} core_{n} diverged under replay"
    assert got_lines == _fixture_lines(suite_dir), (
        f"{suite}: replayed issue order is not the recorded order")


@requires_reference
def test_alternative_order_changes_log_not_goldens():
    """A different (valid) global order is genuinely enforced: the
    replayed log changes, the deterministic goldens do not."""
    suite_dir = os.path.join(REFERENCE_TESTS, "test_1")
    traces = load_test_dir(suite_dir)
    recs = order_replay.parse_order_log(_fixture_lines(suite_dir))
    # node-major order: all of node 0's instructions first, then 1, ...
    resorted = sorted(range(len(recs)), key=lambda g: (recs[g][0], g))
    lines = _fixture_lines(suite_dir)
    alt_lines = [lines[g] for g in resorted]
    order = order_replay.order_rank_from_log(CFG, alt_lines, traces)
    dumps, got_lines = _replay(suite_dir, traces, order)
    for n in range(CFG.num_nodes):
        golden = open(f"{suite_dir}/core_{n}_output.txt").read()
        assert dumps[n] == golden
    assert got_lines == alt_lines
    assert got_lines != _fixture_lines(suite_dir)


@requires_reference
def test_log_trace_mismatch_rejected():
    suite_dir = os.path.join(REFERENCE_TESTS, "test_1")
    traces = load_test_dir(suite_dir)
    lines = _fixture_lines(suite_dir)
    with pytest.raises(ValueError, match="trace"):
        order_replay.order_rank_from_log(CFG, lines[:-1], traces)
    # racy suites record no order log at all (SURVEY §4)
    with pytest.raises((FileNotFoundError, ValueError)):
        order_replay.load_order_rank(
            CFG, os.path.join(REFERENCE_TESTS, "test_3"), traces)
