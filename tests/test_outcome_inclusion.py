"""Outcome-set inclusion: sync ⊆ async on contended micro-traces.

The sync engine's claim (ops/sync_engine.py docstring) is that every
atomic-transaction serialization it realizes is a *reachable* schedule
of the message-level machine. For tiny, maximally contended traces the
outcome space is small enough to sample exhaustively: sweep the sync
engine's arbitration seeds, sweep the async engine's schedule knobs
(issue delays + arbitration permutations), fingerprint final states,
and require every sync outcome to appear in the async outcome set.

A failure here would mean the transactional engine produces a final
state the reference machine cannot — a real semantic divergence, not a
schedule difference.
"""

import itertools

import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
from ue22cs343bb1_openmp_assignment_tpu.ops.step import run_to_quiescence
from ue22cs343bb1_openmp_assignment_tpu.state import init_state


def fingerprint_async(st):
    return (np.asarray(st.cache_addr).tobytes()
            + np.asarray(st.cache_val).tobytes()
            + np.asarray(st.cache_state).tobytes()
            + np.asarray(st.memory).tobytes()
            + np.asarray(st.dir_state).tobytes()
            + np.asarray(st.dir_bitvec).tobytes())


def fingerprint_sync(cfg, st):
    mem, ds, bv = se.to_sim_arrays(cfg, st)
    return (np.asarray(st.cache_addr).tobytes()
            + np.asarray(st.cache_val).tobytes()
            + np.asarray(st.cache_state).tobytes()
            + mem.tobytes() + ds.astype(np.int32).tobytes()
            + bv.tobytes())


def async_outcomes(cfg, traces, max_delay=6, delay_step=2, n_ranks=4):
    """Final-state set over issue-delay tuples x arbitration ranks."""
    out = {}
    active = [n for n, tr in enumerate(traces) if tr]
    ranks = list(itertools.permutations(range(cfg.num_nodes)))
    for delays in itertools.product(range(0, max_delay, delay_step),
                                    repeat=len(active)):
        d = np.zeros(cfg.num_nodes, np.int32)
        for n, dv in zip(active, delays):
            d[n] = dv
        for rank in ranks[:n_ranks]:
            st = init_state(cfg, traces, issue_delay=d,
                            arb_rank=np.asarray(rank, np.int32))
            st = run_to_quiescence(cfg, st, 10_000)
            assert bool(st.quiescent())
            out[fingerprint_async(st)] = (tuple(delays), rank)
    return out


def sync_outcomes(cfg, traces, seeds=range(12), fp=None):
    """fp: fingerprint callable (cfg, state) -> key; defaults to the
    binary fingerprint_sync (tests/test_native_enumeration.py passes
    its dump-string fingerprint instead)."""
    fp = fp or fingerprint_sync
    out = {}
    for seed in seeds:
        st = se.from_sim_state(cfg, init_state(cfg, traces), seed=seed)
        st = se.run_sync_to_quiescence(cfg, st, 4, 10_000)
        assert bool(st.quiescent())
        se.check_exact_directory(cfg, st)
        out[fp(cfg, st)] = seed
    return out


CASES = {
    # write-write race on one remote block
    "ww_race": [[(1, 0x20, 11)], [(1, 0x20, 22)], [], []],
    # read-write race: reader may see before or after
    "rw_race": [[(0, 0x20, 0)], [(1, 0x20, 33)], [], []],
    # upgrade race: both read (SHARED) then both write
    "upgrade_race": [[(0, 0x20, 0), (1, 0x20, 44)],
                     [(0, 0x20, 0), (1, 0x20, 55)], [], []],
    # eviction pressure: conflict-miss displacement during sharing
    "evict_race": [[(1, 0x21, 66), (0, 0x31, 0)],
                   [(0, 0x21, 0), (1, 0x21, 77)], [], []],
    # three-way ownership migration
    "migrate3": [[(1, 0x30, 1)], [(1, 0x30, 2)], [(1, 0x30, 3)], []],
}


@pytest.mark.parametrize("name", sorted(CASES))
def test_sync_outcomes_are_reachable_async_outcomes(name):
    cfg = SystemConfig.reference()
    traces = CASES[name]
    a = async_outcomes(cfg, traces)
    s = sync_outcomes(cfg, traces)
    assert len(a) >= 1 and len(s) >= 1
    missing = {fp: seed for fp, seed in s.items() if fp not in a}
    assert not missing, (
        f"{name}: sync seeds {sorted(missing.values())} produced final "
        f"states outside the async outcome set "
        f"({len(s)} sync / {len(a)} async outcomes)")


# Window-composition races: per-node sequences long enough that a
# txn_width>1 window exercises release (same-slot displacement of an
# own fill), reacquire (evict-then-miss on one entry), and dependent
# write hits (write on an own read fill) *under contention* from a
# second node. 0x20/0x24 share a cache slot (blocks 0 and 4, C=4).
WINDOW_CASES = {
    "window_release": [
        [(1, 0x20, 11), (1, 0x24, 12)],          # fill then displace
        [(1, 0x20, 99)], [], []],
    "window_reacquire": [
        [(1, 0x20, 1), (1, 0x24, 2), (0, 0x20, 0)],  # evict, reacquire
        [(1, 0x20, 9)], [], []],
    "window_dep_hit": [
        [(0, 0x20, 0), (1, 0x20, 5)],            # rd fill then write
        [(0, 0x20, 0), (1, 0x24, 7)], [], []],
    "window_chain_race": [
        [(1, 0x20, 1), (1, 0x24, 2), (0, 0x20, 0), (1, 0x20, 3)],
        [(0, 0x24, 0), (1, 0x20, 8)], [], []],
    # shared-line eviction (last-sharer promotion of node 1) racing
    # node 1's own upgrade of the same block
    "window_promote_vs_upgrade": [
        [(0, 0x20, 0), (1, 0x24, 4)],
        [(0, 0x20, 0), (1, 0x20, 6)], [], []],
    # both nodes run fill-then-displace windows over the same two
    # conflicting blocks in opposite orders
    "window_crossed_releases": [
        [(1, 0x20, 1), (1, 0x24, 2)],
        [(1, 0x24, 3), (1, 0x20, 4)], [], []],
}


@pytest.mark.parametrize("name", sorted({**CASES, **WINDOW_CASES}))
def test_multi_txn_window_outcomes_are_reachable(name):
    """txn_width=4 windows (release/reacquire/dependent-hit composition)
    must still land only in the message-level machine's outcome set."""
    traces = {**CASES, **WINDOW_CASES}[name]
    # longer per-node sequences reach more interleavings than the short
    # CASES — enumerate the async schedule space densely (all single
    # delays, every arbitration permutation) or inclusion misreports
    a = async_outcomes(SystemConfig.reference(), traces, max_delay=8,
                       delay_step=1, n_ranks=24)
    cfg = SystemConfig.reference(txn_width=4)
    s = sync_outcomes(cfg, traces)
    assert len(a) >= 1 and len(s) >= 1
    missing = {fp: seed for fp, seed in s.items() if fp not in a}
    assert not missing, (
        f"{name}: txn_width=4 seeds {sorted(missing.values())} produced "
        f"final states outside the async outcome set "
        f"({len(s)} sync / {len(a)} async outcomes)")


# Absorption-wave races: 3+ requesters funnel onto ONE remote entry in
# a single round, in mixed read/write class sequences — the shapes the
# wave-stamp fan-out encoding (ops/deep_engine, round 4) must resolve
# per line: write-after-read downgrades-then-kills, read-after-write
# spares the flushed writer as SHARED while pre-write holders die,
# upgrade storms serialize through one entry, and a home chain
# composes with foreign waves under the poison/clean rules.
WAVE_CASES = {
    "wave_wrw": [[(1, 0x30, 1)], [(0, 0x30, 0)], [(1, 0x30, 2)], []],
    "wave_rrw": [[(0, 0x30, 0)], [(0, 0x30, 0)], [(1, 0x30, 7)], []],
    "wave_rwr": [[(0, 0x30, 0)], [(1, 0x30, 3)], [(0, 0x30, 0)], []],
    "wave_upgrade_storm": [
        [(0, 0x30, 0), (1, 0x30, 4)],
        [(0, 0x30, 0), (1, 0x30, 5)],
        [(0, 0x30, 0), (1, 0x30, 6)], []],
    # home 3's own chain on 0x30 (write) + foreign mixed waves, then a
    # second own-entry touch (poison/clean arbitration paths)
    "wave_home_chain": [
        [(1, 0x30, 1)], [(0, 0x30, 0)], [(1, 0x30, 2)],
        [(1, 0x30, 9), (0, 0x31, 0)]],
    # displacement notice (0x31/0x21 share a cache slot) crossing a
    # foreign read and write of the evicted entry
    "wave_evict_mix": [
        [(1, 0x31, 1), (0, 0x21, 0)], [(0, 0x31, 0)],
        [(1, 0x31, 5)], []],
}


# Read-storm races: many READERS funnel onto one entry in a single
# round — the shapes the bulk grant (cfg.deep_read_storm, round 5)
# k-aggregates: pure read storms (U -> E for one reader, all-SHARED
# for two+), a storm on a freshly written EM row (owner flushes and
# downgrades via the dw stamp), a storm racing the home's own chain,
# and a storm crossing an eviction notice.
STORM_CASES = {
    "storm_rrr": [[(0, 0x30, 0)], [(0, 0x30, 0)], [(0, 0x30, 0)], []],
    "storm_w_rr": [[(1, 0x30, 5)], [(0, 0x30, 0)], [(0, 0x30, 0)], []],
    "storm_rr_then_w": [[(0, 0x30, 0), (1, 0x30, 1)],
                        [(0, 0x30, 0)], [(0, 0x30, 0)], []],
    "storm_home_chain": [[(0, 0x30, 0)], [(0, 0x30, 0)],
                         [(0, 0x30, 0)], [(1, 0x30, 9)]],
    "storm_evict": [[(0, 0x31, 0), (0, 0x21, 0)], [(0, 0x31, 0)],
                    [(0, 0x31, 0)], []],
}


@pytest.mark.parametrize("waves", [1, 2])
@pytest.mark.parametrize(
    "name", sorted(STORM_CASES) + ["wave_rrw", "migrate3"])
def test_deep_read_storm_outcomes_are_reachable(name, waves):
    """Deep rounds with the read-storm bulk grant must still land only
    in the message-level machine's outcome set (the k-aggregated
    read composition is a legal read-after-read serialization)."""
    import dataclasses
    traces = {**CASES, **WAVE_CASES, **STORM_CASES}[name]
    a = async_outcomes(SystemConfig.reference(), traces, max_delay=24,
                       delay_step=6, n_ranks=12)
    a.update(async_outcomes(SystemConfig.reference(), traces,
                            max_delay=6, delay_step=2, n_ranks=12))
    cfg = dataclasses.replace(
        SystemConfig.reference(), deep_window=True, drain_depth=3,
        txn_width=2, deep_slots=4, deep_ownerval_slots=2,
        deep_waves=waves, deep_read_storm=True)
    s = sync_outcomes(cfg, traces)
    assert len(a) >= 1 and len(s) >= 1
    missing = {fp: seed for fp, seed in s.items() if fp not in a}
    assert not missing, (
        f"{name}: read-storm waves={waves} seeds "
        f"{sorted(missing.values())} produced final states outside "
        f"the async outcome set ({len(s)} deep / {len(a)} async)")


@pytest.mark.parametrize("waves", [1, 3])
@pytest.mark.parametrize(
    "name", sorted(WAVE_CASES) + ["migrate3", "upgrade_race",
                                  "window_chain_race"])
def test_deep_wave_outcomes_are_reachable(name, waves):
    """Deep-window rounds with absorption waves (mixed classes) must
    still land only in the message-level machine's outcome set."""
    import dataclasses
    traces = {**CASES, **WINDOW_CASES, **WAVE_CASES}[name]
    # The deep engine serializes whole chains atomically, so its
    # outcomes include SEQUENTIAL transaction orders — in the async
    # machine those need issue-delay separations of a full transaction
    # latency (~6 cycles/hop chain). Enumerate the union of a WIDE
    # coarse grid (delays 0/6/12/18: whole-transaction orderings) and
    # a TIGHT grid (delays 0/2/4: mid-flight interleavings); ranks
    # cover same-cycle arbitration. A full fine product over 4 active
    # nodes would be 8^4 x 24 runs — this union keeps the set rich and
    # the test minutes-fast.
    a = async_outcomes(SystemConfig.reference(), traces, max_delay=24,
                       delay_step=6, n_ranks=12)
    a.update(async_outcomes(SystemConfig.reference(), traces,
                            max_delay=6, delay_step=2, n_ranks=12))
    cfg = dataclasses.replace(
        SystemConfig.reference(), deep_window=True, drain_depth=3,
        txn_width=2, deep_slots=4, deep_ownerval_slots=2,
        deep_waves=waves)
    s = sync_outcomes(cfg, traces)
    assert len(a) >= 1 and len(s) >= 1
    missing = {fp: seed for fp, seed in s.items() if fp not in a}
    assert not missing, (
        f"{name}: deep waves={waves} seeds {sorted(missing.values())} "
        f"produced final states outside the async outcome set "
        f"({len(s)} deep / {len(a)} async outcomes)")
