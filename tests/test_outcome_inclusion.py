"""Outcome-set inclusion: sync ⊆ async on contended micro-traces.

The sync engine's claim (ops/sync_engine.py docstring) is that every
atomic-transaction serialization it realizes is a *reachable* schedule
of the message-level machine. For tiny, maximally contended traces the
outcome space is small enough to sample exhaustively: sweep the sync
engine's arbitration seeds, sweep the async engine's schedule knobs
(issue delays + arbitration permutations), fingerprint final states,
and require every sync outcome to appear in the async outcome set.

A failure here would mean the transactional engine produces a final
state the reference machine cannot — a real semantic divergence, not a
schedule difference.
"""

import itertools

import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
from ue22cs343bb1_openmp_assignment_tpu.ops.step import run_to_quiescence
from ue22cs343bb1_openmp_assignment_tpu.state import init_state


def fingerprint_async(st):
    return (np.asarray(st.cache_addr).tobytes()
            + np.asarray(st.cache_val).tobytes()
            + np.asarray(st.cache_state).tobytes()
            + np.asarray(st.memory).tobytes()
            + np.asarray(st.dir_state).tobytes()
            + np.asarray(st.dir_bitvec).tobytes())


def fingerprint_sync(cfg, st):
    mem, ds, bv = se.to_sim_arrays(cfg, st)
    return (np.asarray(st.cache_addr).tobytes()
            + np.asarray(st.cache_val).tobytes()
            + np.asarray(st.cache_state).tobytes()
            + mem.tobytes() + ds.astype(np.int32).tobytes()
            + bv.tobytes())


def async_outcomes(cfg, traces, max_delay=6):
    """Final-state set over issue-delay tuples x arbitration ranks."""
    out = {}
    active = [n for n, tr in enumerate(traces) if tr]
    ranks = list(itertools.permutations(range(cfg.num_nodes)))[:8]
    for delays in itertools.product(range(0, max_delay, 2),
                                    repeat=len(active)):
        d = np.zeros(cfg.num_nodes, np.int32)
        for n, dv in zip(active, delays):
            d[n] = dv
        for rank in ranks[:4]:
            st = init_state(cfg, traces, issue_delay=d,
                            arb_rank=np.asarray(rank, np.int32))
            st = run_to_quiescence(cfg, st, 10_000)
            assert bool(st.quiescent())
            out[fingerprint_async(st)] = (tuple(delays), rank)
    return out


def sync_outcomes(cfg, traces, seeds=range(12)):
    out = {}
    for seed in seeds:
        st = se.from_sim_state(cfg, init_state(cfg, traces), seed=seed)
        st = se.run_sync_to_quiescence(cfg, st, 4, 10_000)
        assert bool(st.quiescent())
        se.check_exact_directory(cfg, st)
        out[fingerprint_sync(cfg, st)] = seed
    return out


CASES = {
    # write-write race on one remote block
    "ww_race": [[(1, 0x20, 11)], [(1, 0x20, 22)], [], []],
    # read-write race: reader may see before or after
    "rw_race": [[(0, 0x20, 0)], [(1, 0x20, 33)], [], []],
    # upgrade race: both read (SHARED) then both write
    "upgrade_race": [[(0, 0x20, 0), (1, 0x20, 44)],
                     [(0, 0x20, 0), (1, 0x20, 55)], [], []],
    # eviction pressure: conflict-miss displacement during sharing
    "evict_race": [[(1, 0x21, 66), (0, 0x31, 0)],
                   [(0, 0x21, 0), (1, 0x21, 77)], [], []],
    # three-way ownership migration
    "migrate3": [[(1, 0x30, 1)], [(1, 0x30, 2)], [(1, 0x30, 3)], []],
}


@pytest.mark.parametrize("name", sorted(CASES))
def test_sync_outcomes_are_reachable_async_outcomes(name):
    cfg = SystemConfig.reference()
    traces = CASES[name]
    a = async_outcomes(cfg, traces)
    s = sync_outcomes(cfg, traces)
    assert len(a) >= 1 and len(s) >= 1
    missing = {fp: seed for fp, seed in s.items() if fp not in a}
    assert not missing, (
        f"{name}: sync seeds {sorted(missing.values())} produced final "
        f"states outside the async outcome set "
        f"({len(s)} sync / {len(a)} async outcomes)")
