"""Pallas deep-window round vs the XLA path.

round_step with cfg.deep_window + cfg.pallas_burst on a procedural
config routes through ops.pallas_deep (pre kernel -> XLA lane
scatter/verdicts -> replay kernel); rounds must be bit-identical to
`deep_engine.round_step_deep`.

As with the window kernels (tests/test_pallas_window.py), the Pallas
CPU interpreter is superlinearly slow in kernel size, so the CPU
differential uses a deliberately tiny machine (8 nodes, W=4, Q=4) —
still exercising chains, absorbed requests, releases and truncation.
The full-size compiled path is validated on the TPU backend
(test_full_size_on_tpu).
"""

import dataclasses

import jax
import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se


def _cfgs(num_nodes=8, drain_depth=2, txn_width=2, deep_slots=4,
          deep_ownerval_slots=2, local_permille=700):
    cfg = SystemConfig.scale(num_nodes=num_nodes, drain_depth=drain_depth,
                             txn_width=txn_width)
    cfg = dataclasses.replace(
        cfg, procedural="uniform", max_instrs=1, deep_window=True,
        deep_slots=deep_slots, deep_ownerval_slots=deep_ownerval_slots,
        proc_local_permille=local_permille)
    return cfg, dataclasses.replace(cfg, pallas_burst=True)


def _assert_states_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.slow  # >30 s single-CPU (deep+pallas double compile)
def test_rounds_bit_identical_mid_run():
    """Jitted multi-round equality on a warmed machine, where chains,
    absorbed requests and truncations occur."""
    cfg, pcfg = _cfgs()
    st = se.procedural_state(cfg, 200, seed=1)
    st = se.run_rounds(cfg, st, 30)          # warm: caches full, races on
    a = se.run_rounds(cfg, st, 4)
    b = se.run_rounds(pcfg, st, 4)
    _assert_states_equal(a, b)
    se.check_exact_directory(pcfg, b)


@pytest.mark.slow  # >60 s single-CPU (deep+pallas double compile)
def test_rounds_bit_identical_contended():
    """Same, at 20% locality (request-absorption heavy)."""
    cfg, pcfg = _cfgs(local_permille=200)
    st = se.procedural_state(cfg, 200, seed=5)
    st = se.run_rounds(cfg, st, 30)
    a = se.run_rounds(cfg, st, 3)
    b = se.run_rounds(pcfg, st, 3)
    _assert_states_equal(a, b)
    se.check_exact_directory(pcfg, b)


@pytest.mark.slow  # >40 s single-CPU (deep+pallas double compile)
def test_rounds_bit_identical_waves():
    """Absorption waves (deep_waves > 1, mixed classes) run under
    either fold backend — the round middle is shared code
    (deep_engine.round_step_deep), so only the fold kernels differ."""
    cfg, pcfg = _cfgs(local_permille=200)
    cfg = dataclasses.replace(cfg, deep_waves=3)
    pcfg = dataclasses.replace(pcfg, deep_waves=3)
    st = se.procedural_state(cfg, 200, seed=9)
    st = se.run_rounds(cfg, st, 30)
    a = se.run_rounds(cfg, st, 3)
    b = se.run_rounds(pcfg, st, 3)
    _assert_states_equal(a, b)
    se.check_exact_directory(pcfg, b)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="compiled Pallas path needs the TPU backend "
                           "(CPU interpreter is impractically slow at "
                           "full kernel size)")
def test_full_size_on_tpu():
    cfg, pcfg = _cfgs(num_nodes=1024, drain_depth=13, txn_width=3,
                      deep_slots=8, deep_ownerval_slots=4,
                      local_permille=800)
    st = se.procedural_state(cfg, 256, seed=3)
    st = se.run_rounds(cfg, st, 20)
    a = se.run_rounds(cfg, st, 8)
    b = se.run_rounds(pcfg, st, 8)
    _assert_states_equal(a, b)
