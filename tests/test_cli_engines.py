"""CLI engine selection: async (default), sync, native.

All three execution paths must serve the reference-compat contract:
byte-exact core_<n>_output.txt dumps on a deterministic suite, metrics
on demand, and clean errors for engine/feature mismatches.
"""

import json

import pytest

from tests.conftest import REFERENCE_TESTS, requires_reference

from ue22cs343bb1_openmp_assignment_tpu import cli


def run_cli(args, tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    rc = cli.main(args)
    out, err = capsys.readouterr()
    return rc, out, err


@requires_reference
@pytest.mark.parametrize("engine", ["async", "sync", "native"])
def test_engines_byte_exact_on_test_1(engine, tmp_path, monkeypatch,
                                      capsys):
    rc, _, err = run_cli(
        ["test_1", "--tests-root", REFERENCE_TESTS, "--cpu",
         "--engine", engine, "--metrics"], tmp_path, monkeypatch, capsys)
    assert rc == 0
    metrics = json.loads(err.strip().splitlines()[-1])
    assert metrics["instrs_retired"] == 68
    for n in range(4):
        got = (tmp_path / f"core_{n}_output.txt").read_text()
        golden = open(
            f"{REFERENCE_TESTS}/test_1/core_{n}_output.txt").read()
        assert got == golden, f"{engine} core_{n} diverged"


def test_sync_rejects_async_only_flags(tmp_path, monkeypatch, capsys):
    rc, _, err = run_cli(
        ["--workload", "uniform", "--cpu", "--engine", "sync",
         "--delays", "1", "2", "3", "4"], tmp_path, monkeypatch, capsys)
    assert rc == 2 and "--engine async" in err


def test_native_rejects_jax_only_flags(tmp_path, monkeypatch, capsys):
    rc, _, err = run_cli(
        ["--workload", "uniform", "--cpu", "--engine", "native",
         "--check"], tmp_path, monkeypatch, capsys)
    assert rc == 2 and "--engine async" in err


def test_missing_dir_clean_exit(tmp_path, monkeypatch, capsys):
    for engine in ("async", "sync", "native"):
        rc, _, err = run_cli(
            ["no_such_dir", "--tests-root", REFERENCE_TESTS, "--cpu",
             "--engine", engine], tmp_path, monkeypatch, capsys)
        assert rc == 1, engine


def test_native_workload_long_trace(tmp_path, monkeypatch, capsys):
    """--trace-len beyond the default 32 must size the native engine's
    trace storage (regression: out-of-bounds reads)."""
    rc, _, err = run_cli(
        ["--workload", "uniform", "--nodes", "8", "--trace-len", "64",
         "--cpu", "--engine", "native", "--metrics"],
        tmp_path, monkeypatch, capsys)
    assert rc == 0
    metrics = json.loads(err.strip().splitlines()[-1])
    assert metrics["instrs_retired"] == 8 * 64


@requires_reference
def test_native_nodes_beyond_fixture_errors(tmp_path, monkeypatch,
                                            capsys):
    """--nodes larger than the fixture's core files fails loudly (like
    the async path / assignment.c:826-829), not silently half-loaded."""
    rc, _, err = run_cli(
        ["test_1", "--tests-root", REFERENCE_TESTS, "--cpu",
         "--engine", "native", "--nodes", "8"],
        tmp_path, monkeypatch, capsys)
    assert rc == 1 and "core_4" in err


@requires_reference
def test_sweep_seeds_matches_accepted_runs(tmp_path, monkeypatch, capsys):
    """--sweep-seeds: the batched run-until-match harness (test3.sh
    replacement) reports seeds reproducing accepted outcomes."""
    rc, out, _ = run_cli(
        ["test_3", "--tests-root", REFERENCE_TESTS, "--cpu",
         "--engine", "sync", "--sweep-seeds", "8"],
        tmp_path, monkeypatch, capsys)
    assert rc == 0
    report = json.loads(out.strip().splitlines()[-1])
    assert report["accepted_runs"] == 2
    assert report["matches"]  # some seed reproduces an accepted run
    assert set(report["matches"].values()) <= {"run_1", "run_2"}


def test_sweep_seeds_needs_sync_engine(tmp_path, monkeypatch, capsys):
    rc, _, err = run_cli(
        ["test_3", "--tests-root", REFERENCE_TESTS, "--cpu",
         "--sweep-seeds", "4"], tmp_path, monkeypatch, capsys)
    assert rc == 2 and "--engine sync" in err


def test_procedural_cli(tmp_path, monkeypatch, capsys):
    """--procedural: in-round generated stream, trace-len beyond any
    stored array, invariant-checked."""
    rc, _, err = run_cli(
        ["--engine", "sync", "--procedural", "--nodes", "32",
         "--trace-len", "400", "--cpu", "--metrics", "--check"],
        tmp_path, monkeypatch, capsys)
    assert rc == 0
    lines = err.strip().splitlines()
    assert "invariant check passed" in lines[-2]
    assert json.loads(lines[-1])["instrs_retired"] == 32 * 400


def test_procedural_needs_sync(tmp_path, monkeypatch, capsys):
    rc, _, err = run_cli(
        ["--procedural", "--nodes", "8", "--cpu"],
        tmp_path, monkeypatch, capsys)
    assert rc == 2 and "--engine sync" in err


@requires_reference
def test_txn_width_byte_exact_and_checked(tmp_path, monkeypatch, capsys):
    """Multi-transaction windows through the CLI: byte parity plus the
    exact-directory invariant on a deterministic suite."""
    rc, _, err = run_cli(
        ["test_1", "--tests-root", REFERENCE_TESTS, "--cpu",
         "--engine", "sync", "--txn-width", "4", "--check",
         "--metrics"], tmp_path, monkeypatch, capsys)
    assert rc == 0
    lines = err.strip().splitlines()
    assert "invariant check passed" in lines[-2]
    assert json.loads(lines[-1])["instrs_retired"] == 68
    for n in range(4):
        got = (tmp_path / f"core_{n}_output.txt").read_text()
        golden = open(
            f"{REFERENCE_TESTS}/test_1/core_{n}_output.txt").read()
        assert got == golden, f"txn-width core_{n} diverged"


def test_txn_width_needs_sync(tmp_path, monkeypatch, capsys):
    rc, _, err = run_cli(
        ["--workload", "uniform", "--nodes", "8", "--cpu",
         "--txn-width", "3"], tmp_path, monkeypatch, capsys)
    assert rc == 2 and "--engine sync" in err
