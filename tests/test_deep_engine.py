"""Deep-window engine (ops/deep_engine): invariants, progress, parity.

The deep engine commits arbitrarily deep own-entry transaction chains
plus absorbed remote events per round. Its correctness net here:

* **Exact directory every round** — the transactional engines' core
  invariant (sync_engine.check_exact_directory), checked after every
  single round across contended workloads, seeds, and slot budgets.
  This is the strongest machine-checkable statement that each round is
  a legal serialization (the reference's -DDEBUG asserts, upgraded).
* **Progress** — every configuration drains to quiescence with all
  instructions retired (the priority symmetry-breaking argument in the
  module docstring; regression net for the ghost-event deadlocks found
  during development: attempt-based marks, crossed evict/fill pairs).
* **Local-workload parity** — on node-local (schedule-independent)
  workloads every legal schedule produces the same final state, so the
  deep engine must agree bit-for-bit with the single-transaction
  engine.
* **Golden parity** — reference suites test_1/test_2 are node-local,
  so the deep engine must reproduce their golden dumps byte-exactly.
"""

import dataclasses

import jax
import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se

from tests.conftest import REFERENCE_TESTS, requires_reference


def deep_cfg(N, lf, seed=0, dd=4, tw=4, Q=6, G=3):
    cfg = SystemConfig.scale(N, drain_depth=dd, txn_width=tw)
    return dataclasses.replace(
        cfg, procedural="uniform", max_instrs=1,
        proc_local_permille=lf, proc_seed=seed,
        deep_window=True, deep_slots=Q, deep_ownerval_slots=G)


def drain_checked(cfg, length=48, max_rounds=4000, check_every=1):
    st = se.procedural_state(cfg, length)
    step = jax.jit(lambda s: se.round_step(cfg, s))
    rounds = 0
    while not bool(st.quiescent()) and rounds < max_rounds:
        st = step(st)
        rounds += 1
        if rounds % check_every == 0:
            se.check_exact_directory(cfg, st)
    assert bool(st.quiescent()), (
        f"no quiescence after {max_rounds} rounds; idx="
        f"{np.asarray(st.idx)}")
    se.check_exact_directory(cfg, st)
    assert int(st.metrics.instrs_retired) == cfg.num_nodes * length
    return st, rounds


@pytest.mark.parametrize("lf", [0, 200, 500, 800])
@pytest.mark.parametrize("seed", [0, 1])
def test_contended_invariants_and_progress(lf, seed):
    cfg = deep_cfg(4, lf, seed=seed)
    drain_checked(cfg, length=48)


@pytest.mark.parametrize("N,lf,dd,tw,Q,G", [
    (8, 500, 4, 4, 6, 3),
    (16, 300, 8, 8, 8, 4),     # crossed evict/fill regression regime
    (16, 800, 12, 4, 8, 4),
    (32, 500, 6, 2, 4, 2),     # tight slot budgets
    (8, 100, 2, 1, 3, 1),      # near-degenerate window
])
def test_parameter_sweep(N, lf, dd, tw, Q, G):
    cfg = deep_cfg(N, lf, dd=dd, tw=tw, Q=Q, G=G)
    drain_checked(cfg, length=48, check_every=2)


@pytest.mark.parametrize("lf,seed,waves", [
    (200, 0, 1),      # heavy contention, classic single-winner rounds
    (800, 1, 1),      # headline-like locality
    (0, 2, 2),        # all-remote + waves-and-storm composition
])
def test_read_storm_invariants_and_progress(lf, seed, waves):
    """The read-storm bulk grant (cfg.deep_read_storm) preserves the
    exact directory at every round boundary and drains to quiescence:
    k same-round readers compose in one k-aggregated step (S count +=
    k, EM owners flush+downgrade, U rows grant E-to-one / S-to-many)."""
    cfg = deep_cfg(8, lf, seed=seed)
    cfg = dataclasses.replace(cfg, deep_waves=waves,
                              deep_read_storm=True)
    drain_checked(cfg, length=48)


def test_local_only_parity_with_single_engine():
    """All-local workloads are schedule-independent: the deep engine
    must match the single-transaction engine's final state exactly."""
    base = SystemConfig.scale(16, drain_depth=6, txn_width=4)
    base = dataclasses.replace(base, procedural="uniform", max_instrs=1,
                               proc_local_permille=1000)
    deep = dataclasses.replace(base, deep_window=True)
    out_d = se.run_sync_to_quiescence(deep, se.procedural_state(deep, 64),
                                      chunk=8, max_rounds=4000)
    out_s = se.run_sync_to_quiescence(base, se.procedural_state(base, 64),
                                      chunk=8, max_rounds=4000)
    se.check_exact_directory(deep, out_d)
    for f in ("cache_addr", "cache_val", "cache_state"):
        np.testing.assert_array_equal(np.asarray(getattr(out_d, f)),
                                      np.asarray(getattr(out_s, f)), f)
    dm_d, dm_s = np.asarray(out_d.dm), np.asarray(out_s.dm)
    np.testing.assert_array_equal(dm_d[:, 0], dm_s[:, 0], "dir state")
    np.testing.assert_array_equal(dm_d[:, 3], dm_s[:, 3], "memory")
    # deep windows must actually be deep: fewer rounds than single-txn
    assert int(out_d.metrics.rounds) < int(out_s.metrics.rounds)


def test_runner_integration_and_budget():
    """run_sync_to_quiescence dispatches deep rounds and asserts the
    claim budget: the lane spends one key bit on the ev tag, and the
    wave-stamp DM_ACT packing (round << 11, sync_engine.py) caps the
    absolute round counter at 2^20 - 1 for every deep config."""
    cfg = deep_cfg(8, 700)
    nb = max(1, (cfg.num_nodes - 1).bit_length())
    assert se.claim_max_rounds(cfg) == min((1 << (30 - nb - 1)) - 1,
                                           (1 << 20) - 1)
    # at 8 nodes (nb=3) the 2^20 DM_ACT cap is the binding bound
    assert se.claim_max_rounds(cfg) == (1 << 20) - 1
    # with waves, slot-index bits shrink the lane budget further, but
    # the DM_ACT cap still binds at small N
    waved = dataclasses.replace(cfg, deep_waves=4)
    sb = max(1, (cfg.deep_slots - 1).bit_length())
    assert se.claim_max_rounds(waved) == min((1 << (30 - nb - 1 - sb)) - 1,
                                             (1 << 20) - 1)
    # at large N the lane-key budget binds instead of the DM_ACT cap
    big = dataclasses.replace(deep_cfg(4096, 700), deep_waves=4)
    nb_big = max(1, (big.num_nodes - 1).bit_length())
    sb_big = max(1, (big.deep_slots - 1).bit_length())
    assert se.claim_max_rounds(big) == min(
        (1 << (30 - nb_big - 1 - sb_big)) - 1, (1 << 20) - 1)
    out = se.run_sync_to_quiescence(cfg, se.procedural_state(cfg, 32),
                                    chunk=8, max_rounds=4000)
    assert bool(out.quiescent())


@requires_reference
@pytest.mark.parametrize("suite", ["test_1", "test_2"])
def test_golden_parity_deterministic_suites(suite, tmp_path):
    """test_1/test_2 are node-local => deterministic; the deep engine
    must reproduce the reference's golden dumps byte-for-byte."""
    from ue22cs343bb1_openmp_assignment_tpu.models.transactional import (
        TransactionalSystem)

    cfg = dataclasses.replace(SystemConfig.reference(),
                              deep_window=True, deep_slots=6,
                              deep_ownerval_slots=3)
    sys_ = TransactionalSystem.from_test_dir(
        f"{REFERENCE_TESTS}/{suite}", cfg).run()
    sys_.check_invariants()
    dumps = sys_.dumps()
    for n in range(4):
        want = open(f"{REFERENCE_TESTS}/{suite}/core_{n}_output.txt",
                    "rb").read().decode()
        assert dumps[n] == want, f"{suite} core_{n} diverges"


def test_event_tracing_matches_program_order():
    """with_events retirement records: per-node projections are exact
    program-order prefixes of the procedural stream, and the total
    retired count matches the metrics (utils.eventlog contract)."""
    from ue22cs343bb1_openmp_assignment_tpu.procedural import (
        procedural_instr)
    cfg = deep_cfg(8, 500, seed=3)
    st = se.procedural_state(cfg, 24)
    final, events = se.run_rounds_traced(cfg, st, 30)
    ret = np.asarray(events["retired"])          # [rounds, N, W]
    op = np.asarray(events["op"])
    addr = np.asarray(events["addr"])
    total = int(ret.sum())
    assert total == int(final.metrics.instrs_retired)
    import jax.numpy as jnp
    for n in range(cfg.num_nodes):
        got = [(int(o), int(a))
               for t in range(ret.shape[0])
               for k in range(ret.shape[2])
               for o, a in [(op[t, n, k], addr[t, n, k])]
               if ret[t, n, k]]
        idxs = jnp.arange(len(got), dtype=jnp.int32)
        oa, _ = procedural_instr(cfg, jnp.full_like(idxs, n), idxs)
        want = [(int(x) >> 28, int(x) & 0x0FFFFFFF)
                for x in np.asarray(oa)]
        assert got == want, f"node {n}: traced order != program order"


@pytest.mark.parametrize("waves,lf,seed", [
    (2, 0, 5), (3, 200, 2), (4, 500, 7), (3, 0, 11)])
def test_absorption_waves_invariants_and_progress(waves, lf, seed):
    """cfg.deep_waves > 1: extra fill requests — mixed read/write
    sequences included (wave-stamp fan-out) — compose per entry per
    round (the contended-workload lever). The exact-directory
    invariant must hold after EVERY round and every trace must
    drain."""
    cfg = dataclasses.replace(deep_cfg(8, lf, seed=seed, dd=3, tw=2,
                                       Q=4, G=2), deep_waves=waves)
    drain_checked(cfg, length=30)
