"""obs v2: bench history + regression detector + flight recorder.

The acceptance anchors from the archive are pinned exactly: the
r03→r04 delta (the one PERF.md argued by hand) must classify as noise,
and a synthetic ≥10% slowdown of the same capture as a regression.
The flight recorder's incident dirs must validate against both the
Perfetto and metrics schemas and replay through the analysis repro
path.
"""

import copy
import json
import math
import os

import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu import cli
from ue22cs343bb1_openmp_assignment_tpu.obs import (flight, history,
                                                    perfetto, regress,
                                                    schema)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
R03 = os.path.join(REPO, "BENCH_r03.json")
R04 = os.path.join(REPO, "BENCH_r04.json")


def run_cli(args, capsys):
    rc = cli.main(args)
    out = capsys.readouterr()
    return rc, out.out, out.err


# -- Mann-Whitney U --------------------------------------------------------


def test_mwu_exact_disjoint_3v3_hits_the_floor():
    # fully separated 3v3: exactly one of C(6,3)=20 splits reaches the
    # observed U, so the one-sided p is its floor, 0.05
    r = regress.mann_whitney_u([1.0, 1.1, 1.2], [2.0, 2.1, 2.2])
    assert r["method"] == "exact"
    assert r["u"] == 9.0
    assert math.isclose(r["p"], 0.05)


def test_mwu_exact_handles_ties_and_reversal():
    r = regress.mann_whitney_u([1.0, 1.0, 2.0], [1.0, 2.0, 2.0])
    assert r["method"] == "exact" and 0.0 < r["p"] <= 1.0
    # reversing the sides flips the hypothesis: both p's can't be small
    r2 = regress.mann_whitney_u([2.0, 2.1, 2.2], [1.0, 1.1, 1.2])
    assert r2["p"] > 0.9


def test_mwu_normal_approximation_for_large_samples():
    a = [1.0 + 0.01 * i for i in range(60)]
    b = [1.5 + 0.01 * i for i in range(60)]
    r = regress.mann_whitney_u(a, b)
    assert r["method"] == "normal"
    assert r["p"] < 1e-6


def test_mwu_rejects_single_rep():
    with pytest.raises(ValueError):
        regress.mann_whitney_u([1.0], [1.0, 2.0])


# -- verdicts on the archived captures -------------------------------------


def test_archived_r03_vs_r04_is_noise():
    a = history.ingest_capture(R03)
    b = history.ingest_capture(R04)
    assert a["rep_times_s"] == [0.85, 0.859, 0.889]
    rep = regress.compare(a, b)
    assert rep["verdict"] == "noise"
    # the delta PERF.md argued about: +3.5% against a ~4.5% rep spread
    assert rep["delta_pct"] < rep["threshold_pct"]


def test_synthetic_ten_percent_slowdown_is_regression():
    a = history.ingest_capture(R03)
    b = copy.deepcopy(a)
    b["rep_times_s"] = [t * 1.10 for t in a["rep_times_s"]]
    rep = regress.compare(a, b)
    assert rep["verdict"] == "regression"
    assert rep["p"] == pytest.approx(0.05)


def test_symmetric_improvement():
    a = history.ingest_capture(R03)
    b = copy.deepcopy(a)
    b["rep_times_s"] = [t * 0.85 for t in a["rep_times_s"]]
    assert regress.compare(a, b)["verdict"] == "improvement"


def test_variance_shift_same_median_is_noise():
    a = history.ingest_capture(R03)
    b = copy.deepcopy(a)
    med = sorted(a["rep_times_s"])[1]
    # same median, much wider spread: not a regression, and the wider
    # spread raises the practical bar rather than tripping it
    b["rep_times_s"] = [med * 0.8, med, med * 1.25]
    rep = regress.compare(a, b)
    assert rep["verdict"] == "noise"
    assert rep["threshold_pct"] > 40.0


def test_two_rep_sides_are_practical_only():
    a = history.ingest_capture(R03)
    a["rep_times_s"] = [1.0, 1.01]
    b = copy.deepcopy(a)
    b["rep_times_s"] = [1.3, 1.31]
    rep = regress.compare(a, b)
    # 2v2 can't reach alpha (floor 1/6): the rank test goes mute and
    # the practical bar alone calls the clear 30% delta
    assert "low_power" in rep["flags"]
    assert rep["verdict"] == "regression"


def test_metric_mismatch_is_incomparable():
    a = history.ingest_capture(R03)
    b = copy.deepcopy(a)
    b["metric"] = "something else entirely"
    assert regress.compare(a, b)["verdict"] == "incomparable"


# -- history storage -------------------------------------------------------


def test_history_entry_round_trip(tmp_path):
    a = history.ingest_capture(R03)
    b = history.ingest_capture(R04)
    p = str(tmp_path / "h.jsonl")
    history.append(p, a)
    history.append(p, b)
    prev, last = history.last_two(p)
    assert prev["label"] == "r03" and last["label"] == "r04"
    assert prev["source"] == "BENCH_r03.json"
    assert prev["config"]["engine"] == "deep"


def test_history_validate_catches_corruption(tmp_path):
    a = history.ingest_capture(R03)
    bad = dict(a, rep_times_s=[-1.0])
    with pytest.raises(ValueError, match="rep_times_s"):
        history.validate_entry(bad)
    with pytest.raises(ValueError, match="unknown key"):
        history.validate_entry(dict(a, extra_field=1))
    p = str(tmp_path / "h.jsonl")
    with open(p, "w") as f:
        f.write(json.dumps(dict(a, schema="wrong/v0")) + "\n")
    with pytest.raises(ValueError, match=":1:"):
        history.load(p)


# -- bench-diff CLI --------------------------------------------------------


def test_bench_diff_cli_archived_noise(capsys):
    rc, out, _ = run_cli(["bench-diff", R03, R04], capsys)
    assert rc == 0
    assert "NOISE" in out


def test_bench_diff_cli_synthetic_regression(capsys):
    rc, out, _ = run_cli(
        ["bench-diff", R03, "--synthetic-slowdown", "12", "--json"],
        capsys)
    assert rc == 4
    doc = json.loads(out)
    assert doc["verdict"] == "regression"
    assert doc["delta_pct"] == pytest.approx(12.0)


def test_bench_diff_cli_against_last_flows(tmp_path, capsys):
    p = str(tmp_path / "h.jsonl")
    rc, _, err = run_cli(["bench-diff", "--history", p,
                          "--against-last"], capsys)
    assert rc == 2 and "not found" in err
    history.append(p, history.ingest_capture(R03))
    rc, out, _ = run_cli(["bench-diff", "--history", p,
                          "--against-last"], capsys)
    assert rc == 0 and "baseline recorded" in out
    history.append(p, history.ingest_capture(R04))
    rc, out, _ = run_cli(["bench-diff", "--history", p,
                          "--against-last"], capsys)
    assert rc == 0 and "NOISE" in out


def test_bench_diff_cli_usage_errors(capsys):
    rc, _, err = run_cli(["bench-diff"], capsys)
    assert rc == 2 and "provide captures" in err


# -- profiler --------------------------------------------------------------


def test_kernel_cost_report_attaches_to_phase_timer():
    import jax
    import jax.numpy as jnp

    from ue22cs343bb1_openmp_assignment_tpu.obs import profiler
    from ue22cs343bb1_openmp_assignment_tpu.obs.phases import PhaseTimer

    @jax.jit
    def f(x):
        return (x * x).sum()

    timer = PhaseTimer()
    timer.add("run", 0.5)
    rep = profiler.attach_kernel_costs(timer, f,
                                       jnp.ones(128, jnp.float32))
    doc = timer.report()
    assert doc["kernels"] is rep
    assert doc["phases"]["run"]["count"] == 1
    if rep["available"]:  # CPU exposes the cost model today
        assert rep["cost"].get("flops", 0) > 0


def test_timer_self_check_trusts_cpu_barrier():
    import jax
    import jax.numpy as jnp

    from ue22cs343bb1_openmp_assignment_tpu.obs import profiler

    @jax.jit
    def f(x):
        return jnp.cumsum(x)

    chk = profiler.timer_self_check(f, jnp.ones(256, jnp.float32),
                                    reps=2)
    # in-process CPU: block_until_ready IS the computation barrier
    assert chk["barrier_trustworthy"] is True
    assert chk["device_get_tail_s"] >= 0.0


# -- flight recorder -------------------------------------------------------


@pytest.fixture(scope="module")
def _finding_case():
    from ue22cs343bb1_openmp_assignment_tpu.analysis import fuzz
    rng = np.random.default_rng(0)
    return fuzz.gen_case(rng, 0)


def test_flight_ring_is_bounded(_finding_case):
    fr = flight.record_case(_finding_case, k=24)
    st = fr.run(512)
    assert bool(st.quiescent())
    ring = fr.ring()
    assert 0 < ring["counters"].shape[0] <= 24
    # every telemetry channel trims to the same window
    assert len({v.shape[0] for v in ring.values()}) == 1


def test_flight_incident_dump_validates_and_replays(tmp_path,
                                                    _finding_case):
    case = _finding_case
    fr = flight.record_case(case, k=32)
    fr.run(256)
    inc = str(tmp_path / "incident_t")
    doc = fr.dump_incident(inc, "fuzz:state", "synthetic incident",
                           case=case.to_dict())
    # self-contained: metrics doc passes the metrics validator, the
    # trace passes the Perfetto validator, the repro is the exact
    # analysis/shrink fixture format
    schema.validate(doc["metrics"])
    with open(os.path.join(inc, "trace.perfetto.json")) as f:
        perfetto.validate_trace(json.load(f))
    loaded = flight.load_incident(inc)
    assert loaded["reason"] == "fuzz:state"
    assert loaded["ring"]["cycles"] <= 32
    for n in range(case.num_nodes):
        assert os.path.exists(os.path.join(inc, f"core_{n}.txt"))
    with open(os.path.join(inc, "repro.json")) as f:
        assert json.load(f)["schema"] == "cache-sim/repro/v1"
    # replay through the differential oracle: the clean engine on a
    # clean case comes back ok (the incident reason belonged to the
    # mutant that raised it)
    assert flight.replay_incident(inc)["verdict"] == "ok"


def test_flight_replay_mutant_reproduces_verdict(tmp_path,
                                                 _finding_case):
    from ue22cs343bb1_openmp_assignment_tpu.analysis import fuzz
    from ue22cs343bb1_openmp_assignment_tpu.analysis.mutations import (
        MUTATIONS)
    mp = MUTATIONS["skip_em_bitvec_clear"][0]
    case = _finding_case
    res = fuzz.run_case(case, mp)
    assert res["verdict"] != "ok"
    fr = flight.record_case(case, k=16, message_phase=mp)
    fr.run(max(res["cycles"], 1), stop_on_quiescence=False)
    inc = str(tmp_path / "incident_m")
    fr.dump_incident(inc, f"fuzz:{res['verdict']}", res["detail"],
                     case=case.to_dict())
    replay = flight.replay_incident(inc, message_phase=mp)
    assert replay["verdict"] == res["verdict"]


def test_cli_hang_incident(tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    rc, _, err = run_cli(
        ["--workload", "uniform", "--nodes", "4", "--trace-len", "8",
         "--max-cycles", "6", "--cpu", "--flight-dir", "fl",
         "--flight-ring", "8"], capsys)
    assert rc == 0 and "incident dumped" in err
    inc = tmp_path / "fl" / "incident_hang"
    doc = flight.load_incident(str(inc))
    assert doc["reason"] == "hang:not_quiescent"
    assert not doc["quiescent"] and not doc["has_repro"]
    with open(inc / "trace.perfetto.json") as f:
        perfetto.validate_trace(json.load(f))


def test_flight_dir_rejected_on_sync_engine(capsys):
    rc, _, err = run_cli(
        ["--workload", "uniform", "--engine", "sync", "--cpu",
         "--flight-dir", "/tmp/never"], capsys)
    assert rc == 2 and "flight" in err


# -- bench.py exit-code contract -------------------------------------------


def test_bench_nonzero_exit_when_not_quiescent(tmp_path, monkeypatch,
                                               capsys):
    import bench
    hist = str(tmp_path / "h.jsonl")
    monkeypatch.setattr(
        "sys.argv",
        ["bench.py", "--smoke", "--engine", "async", "--reps", "1",
         "--max-cycles", "4", "--record", hist])
    rc = bench.main()
    out = capsys.readouterr()
    assert rc == 1
    assert "not quiescent" in out.err
    # the capture still records (with quiescent=false preserved) so a
    # bad run is visible in the history, not silently absent
    h = history.load(hist)
    assert len(h) == 1 and h[0]["quiescent"] is False
