"""Protocol-invariant checker (SURVEY §5: deterministic engine ⇒ race
detection becomes whole-machine invariant checking; the reference's only
equivalents are three -DDEBUG asserts, assignment.c:449,556,608-614)."""

import jax.numpy as jnp
import pytest

from tests.conftest import requires_reference
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
from ue22cs343bb1_openmp_assignment_tpu.ops import invariants
from ue22cs343bb1_openmp_assignment_tpu.state import bit_single
from ue22cs343bb1_openmp_assignment_tpu.types import CacheState, DirState


@requires_reference
@pytest.mark.parametrize("suite", ["sample", "test_1", "test_2",
                                   "test_3", "test_4"])
def test_reference_suites_clean(suite):
    """Every reference suite passes both invariant tiers at quiescence."""
    sys_ = CoherenceSystem.from_test_dir(
        f"/root/reference/tests/{suite}").run()
    assert sys_.quiescent
    sys_.check_invariants()  # must not raise


def test_scale_local_workload_strictly_clean():
    """Race-free (all-local) workload at 128 nodes: full coherence tier
    must be exactly zero."""
    cfg = SystemConfig.scale(num_nodes=128, queue_capacity=32,
                             admission_window=5)
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=8,
                                         seed=1, local_frac=1.0).run()
    assert sys_.quiescent
    report = sys_.check_invariants(strict_coherence=True)
    assert all(v == 0 for v in report.values())


def test_scale_serialized_writers_strictly_clean():
    """Cross-node write sharing WITHOUT races: 64 nodes all write then
    read block (0,0), serialized via issue_delay so each ownership
    transfer completes before the next begins. Exercises the scatter-INV
    and WRITEBACK_INV paths; a correct engine leaves a coherent machine,
    so the strict tier must pass."""
    import numpy as np
    cfg = SystemConfig.scale(num_nodes=64, queue_capacity=32)
    traces = [[("W", 0x00, 10 + n), ("R", 0x00, 0)] for n in range(64)]
    from ue22cs343bb1_openmp_assignment_tpu.types import Op
    traces = [[(Op.WRITE, a, v) if o == "W" else (Op.READ, a, v)
               for o, a, v in t] for t in traces]
    sys_ = CoherenceSystem.from_traces(
        cfg, traces,
        issue_delay=np.arange(64, dtype=np.int32) * 24,
        issue_period=np.full(64, 12, np.int32)).run(max_cycles=4000)
    assert sys_.quiescent
    report = sys_.check_invariants(strict_coherence=True)
    assert all(v == 0 for v in report.values())
    # last writer owns the line MODIFIED; memory holds its value
    assert int(sys_.state.memory[0, 0]) == 10 + 63 or \
        int(sys_.state.cache_val[63, 0]) == 10 + 63


def test_racy_workload_reports_but_passes_engine_tier():
    """Heavy false sharing: engine tier clean; coherence tier may report
    stale copies — the protocol's documented unacked-INV envelope
    (assignment.c:358-361), surfaced as diagnostics."""
    cfg = SystemConfig.scale(num_nodes=128, queue_capacity=32,
                             admission_window=5)
    sys_ = CoherenceSystem.from_workload(cfg, "false_sharing",
                                         trace_len=8, seed=1).run()
    assert sys_.quiescent
    report = sys_.check_invariants(strict_coherence=False)  # no raise
    assert isinstance(report, dict) and report  # diagnostics surfaced


def test_run_checked_clean_and_equivalent():
    """run_checked == run_cycles on a clean machine, and doesn't raise."""
    cfg = SystemConfig.reference()
    base = CoherenceSystem.from_workload(cfg, "uniform", trace_len=6, seed=2)
    a = base.run_cycles(30)
    b = base.run_checked(30)
    assert a.dumps() == b.dumps()


def _corrupt(state, **kw):
    return state.replace(**kw)


def test_detects_em_multi_owner():
    """Directory EM with two sharer bits — the reference's assert at
    assignment.c:449 — is caught by the per-cycle tier."""
    cfg = SystemConfig.reference()
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=4)
    st = sys_.state
    bv = st.dir_bitvec.at[0, 0].set(
        bit_single(cfg.bitvec_words, jnp.asarray(0))
        | bit_single(cfg.bitvec_words, jnp.asarray(1)))
    st = _corrupt(st, dir_state=st.dir_state.at[0, 0].set(int(DirState.EM)),
                  dir_bitvec=bv)
    v = invariants.step_violations(cfg, st)
    assert int(v["em_not_single_owner"]) == 1
    with pytest.raises(AssertionError, match="em_not_single_owner"):
        invariants.assert_invariants(cfg, st)


def test_detects_unowned_with_sharers():
    cfg = SystemConfig.reference()
    st = CoherenceSystem.from_workload(cfg, "uniform", trace_len=4).state
    st = _corrupt(st, dir_bitvec=st.dir_bitvec.at[1, 2].set(
        bit_single(cfg.bitvec_words, jnp.asarray(3))))
    v = invariants.step_violations(cfg, st)
    assert int(v["unowned_with_sharers"]) == 1


@requires_reference
def test_detects_hidden_copy_at_quiescence():
    """A valid cache line the home directory doesn't know about — the
    coherence bug class the protocol exists to prevent."""
    cfg = SystemConfig.reference()
    sys_ = CoherenceSystem.from_test_dir(
        "/root/reference/tests/test_1").run()
    st = sys_.state
    # plant a MODIFIED line at node 3 for address 0x00 (home 0, block 0)
    st = _corrupt(
        st,
        cache_addr=st.cache_addr.at[3, 0].set(0x00),
        cache_val=st.cache_val.at[3, 0].set(0x42),
        cache_state=st.cache_state.at[3, 0].set(int(CacheState.MODIFIED)))
    v = invariants.quiescent_violations(cfg, st)
    assert int(v["valid_line_unknown_to_home"]) >= 1
    with pytest.raises(AssertionError):
        invariants.assert_invariants(cfg, st, quiescent=True)


def test_detects_stale_clean_value():
    from ue22cs343bb1_openmp_assignment_tpu.types import Op
    cfg = SystemConfig.reference()
    # node 1 read-misses 0x00 → fills EXCLUSIVE with home memory value
    sys_ = CoherenceSystem.from_traces(
        cfg, [[], [(Op.READ, 0x00, 0)], [], []]).run()
    assert sys_.quiescent
    assert int(sys_.state.cache_state[1, 0]) == int(CacheState.EXCLUSIVE)
    sys_.check_invariants(strict_coherence=True)
    st = _corrupt(sys_.state,
                  cache_val=sys_.state.cache_val.at[1, 0].add(1))
    v = invariants.quiescent_violations(cfg, st)
    assert int(v["clean_line_stale_value"]) == 1


def test_run_checked_catches_corruption():
    cfg = SystemConfig.reference()
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=4)
    bad = _corrupt(
        sys_.state,
        mb_count=sys_.state.mb_count.at[0].set(cfg.queue_capacity + 7))
    import dataclasses
    sys_bad = dataclasses.replace(sys_, state=bad)
    with pytest.raises(AssertionError, match="mailbox_count_oob"):
        sys_bad.run_checked(1)


@requires_reference
def test_cli_check_flag(tmp_path):
    from ue22cs343bb1_openmp_assignment_tpu import cli
    rc = cli.main(["test_2", "--tests-root", "/root/reference/tests",
                   "--out-dir", str(tmp_path), "--check"])
    assert rc == 0
