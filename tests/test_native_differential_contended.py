"""Contended differential: async JAX vs native C++ in lockstep.

The one test class outcome-set sampling cannot replace (VERDICT r2
#7): both engines implement the SAME deterministic cycle model —
drain-before-fetch, (arb_rank, program-order) delivery
(``assignment.c:741-765`` semantics), identical schedule knobs — so on
*contended* cross-node traffic under the *same* arbitration rank and
issue schedule they must agree state-for-state at every cycle
checkpoint, not just at quiescence. A divergence here is a real
semantic bug in one engine, pinpointed to a k-cycle window.
"""

import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.native.bindings import NativeEngine
from ue22cs343bb1_openmp_assignment_tpu.ops.step import run_cycles
from ue22cs343bb1_openmp_assignment_tpu.state import init_state

N_WORKLOADS = 108          # >= 100 contended workloads (VERDICT r2 #7)
CHECK_EVERY = 25           # cycles between state comparisons
N_CHECKS = 10


def contended_traces(rng, cfg, n_instrs, local_frac=0.3):
    """Cross-node-heavy random traffic: ~70% of accesses target remote
    homes, concentrated on half the address space to force collisions."""
    out = []
    for n in range(cfg.num_nodes):
        tr = []
        for _ in range(n_instrs):
            if rng.random() < local_frac:
                home = n
            else:
                home = int(rng.integers(cfg.num_nodes))
            block = int(rng.integers(max(2, cfg.mem_size // 2)))
            a = (home << cfg.block_bits) | block
            if rng.random() < 0.45:
                tr.append((0, a, 0))
            else:
                tr.append((1, a, int(rng.integers(256))))
        out.append(tr)
    return out


def _compare(tag, a_state, n_state):
    for name, av, nv in [
        ("cache_addr", a_state.cache_addr, n_state["cache_addr"]),
        ("cache_val", a_state.cache_val, n_state["cache_val"]),
        ("cache_state", a_state.cache_state, n_state["cache_state"]),
        ("memory", a_state.memory, n_state["memory"]),
        ("dir_state", a_state.dir_state, n_state["dir_state"]),
        ("dir_bitvec", a_state.dir_bitvec, n_state["dir_bitvec"]),
    ]:
        np.testing.assert_array_equal(
            np.asarray(av), np.asarray(nv),
            f"{tag}: {name} diverged (async vs native)")


@pytest.mark.parametrize("chunk", [0, 1, 2])
def test_lockstep_equality_on_contended_traffic(chunk):
    """36 workloads per chunk x 3 chunks: random contended traces,
    random issue delays/periods, random arbitration rank — identical
    knobs into both engines, states compared every CHECK_EVERY cycles."""
    cfg = SystemConfig.reference(num_nodes=8)
    per = N_WORKLOADS // 3
    for trial in range(per):
        seed = chunk * per + trial
        rng = np.random.default_rng(1000 + seed)
        traces = contended_traces(rng, cfg, 24)
        delays = rng.integers(0, 7, cfg.num_nodes).astype(np.int32)
        periods = rng.integers(1, 4, cfg.num_nodes).astype(np.int32)
        rank = rng.permutation(cfg.num_nodes).astype(np.int32)

        ast = init_state(cfg, traces, issue_delay=delays,
                         issue_period=periods, arb_rank=rank)
        nat = NativeEngine(cfg)
        nat.load_traces(traces)
        nat.set_schedule(delays.tolist(), periods.tolist())
        nat.set_arbitration(rank)

        for ck in range(N_CHECKS):
            ast = run_cycles(cfg, ast, CHECK_EVERY)
            nat.run(CHECK_EVERY)
            _compare(f"seed {seed} cycle {(ck + 1) * CHECK_EVERY}",
                     ast, nat.export_state())
        assert bool(ast.quiescent()) == nat.quiescent, (
            f"seed {seed}: quiescence disagreement at cycle "
            f"{N_CHECKS * CHECK_EVERY}")
        assert bool(ast.quiescent()), (
            f"seed {seed}: not quiescent after "
            f"{N_CHECKS * CHECK_EVERY} cycles")
