"""Trace streaming: unbounded workloads in bounded memory.

The reference caps runs at 32 instructions per node (assignment.c:10);
continue_with_traces chains max_instrs-sized phases through a quiescent
machine. Chaining inserts a quiescence barrier, which is itself a legal
schedule of the concatenated trace — so schedule-independent (node-
local) workloads must end byte-identical to one long run.
"""

import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
from ue22cs343bb1_openmp_assignment_tpu.ops.step import run_to_quiescence
from ue22cs343bb1_openmp_assignment_tpu.state import (continue_with_traces,
                                                      init_state)


def local_traces(rng, cfg, n_instrs):
    out = []
    for n in range(cfg.num_nodes):
        tr = []
        for _ in range(n_instrs):
            a = (n << cfg.block_bits) | int(rng.integers(cfg.mem_size))
            if rng.random() < 0.5:
                tr.append((0, a, 0))
            else:
                tr.append((1, a, int(rng.integers(256))))
        out.append(tr)
    return out


def test_async_chained_phases_equal_one_run():
    cfg = SystemConfig.reference(num_nodes=4, max_instrs=16)
    rng = np.random.default_rng(11)
    p1 = local_traces(rng, cfg, 16)
    p2 = local_traces(rng, cfg, 16)

    st = run_to_quiescence(cfg, init_state(cfg, p1), 20_000)
    st = continue_with_traces(cfg, st, traces=p2)
    st = run_to_quiescence(cfg, st, 20_000)
    assert bool(st.quiescent())

    cfg_long = SystemConfig.reference(num_nodes=4, max_instrs=32)
    concat = [a + b for a, b in zip(p1, p2)]
    ref = run_to_quiescence(cfg_long, init_state(cfg_long, concat), 20_000)
    for f in ("cache_addr", "cache_val", "cache_state", "memory",
              "dir_state", "dir_bitvec"):
        np.testing.assert_array_equal(np.asarray(getattr(st, f)),
                                      np.asarray(getattr(ref, f)), f)


def test_sync_chained_phases_equal_one_run():
    cfg = SystemConfig.reference(num_nodes=4, max_instrs=16)
    rng = np.random.default_rng(13)
    p1 = local_traces(rng, cfg, 16)
    p2 = local_traces(rng, cfg, 16)

    st = se.from_sim_state(cfg, init_state(cfg, p1))
    st = se.run_sync_to_quiescence(cfg, st, 8, 20_000)
    st = se.continue_with_traces(cfg, st, traces=p2)
    st = se.run_sync_to_quiescence(cfg, st, 8, 20_000)
    assert bool(st.quiescent())
    se.check_exact_directory(cfg, st)
    assert int(st.metrics.instrs_retired) == 4 * 32

    cfg_long = SystemConfig.reference(num_nodes=4, max_instrs=32)
    concat = [a + b for a, b in zip(p1, p2)]
    ref = se.run_sync_to_quiescence(
        cfg_long, se.from_sim_state(cfg_long, init_state(cfg_long, concat)),
        8, 20_000)
    for f in ("cache_addr", "cache_val", "cache_state"):
        np.testing.assert_array_equal(np.asarray(getattr(st, f)),
                                      np.asarray(getattr(ref, f)), f)
    mem_a, ds_a, bv_a = se.to_sim_arrays(cfg, st)
    mem_b, ds_b, bv_b = se.to_sim_arrays(cfg_long, ref)
    np.testing.assert_array_equal(mem_a, mem_b)
    np.testing.assert_array_equal(ds_a, ds_b)
    np.testing.assert_array_equal(bv_a, bv_b)


def test_cross_node_streaming_invariants():
    """Racy cross-node phases: chained outcome is a legal (barriered)
    schedule — retire counts and invariants must hold."""
    cfg = SystemConfig.scale(num_nodes=32, max_instrs=16)
    st = se.from_sim_state(
        cfg, CoherenceSystem.from_workload(
            cfg, "uniform", trace_len=16, seed=0, local_frac=0.2).state)
    total = 0
    for phase_seed in range(3):
        st = se.run_sync_to_quiescence(cfg, st, 16, 50_000)
        assert bool(st.quiescent())
        se.check_exact_directory(cfg, st)
        total += 32 * 16
        assert int(st.metrics.instrs_retired) == total
        nxt = CoherenceSystem.from_workload(
            cfg, "uniform", trace_len=16, seed=phase_seed + 1,
            local_frac=0.2).state
        st = se.continue_with_traces(
            cfg, st, instr_arrays=(nxt.instr_op, nxt.instr_addr,
                                   nxt.instr_val, nxt.instr_count))


def test_not_quiescent_rejected():
    cfg = SystemConfig.reference(num_nodes=4)
    traces = [[(1, 0x15, 9)], [], [], []]  # cross-node write, needs hops
    st = init_state(cfg, traces)
    with pytest.raises(ValueError, match="quiescent"):
        continue_with_traces(cfg, st, traces=traces)
    ss = se.from_sim_state(cfg, st)
    with pytest.raises(ValueError, match="retired"):
        se.continue_with_traces(cfg, ss, traces=traces)
