"""Trace streaming: unbounded workloads in bounded memory.

The reference caps runs at 32 instructions per node (assignment.c:10);
continue_with_traces chains max_instrs-sized phases through a quiescent
machine. Chaining inserts a quiescence barrier, which is itself a legal
schedule of the concatenated trace — so schedule-independent (node-
local) workloads must end byte-identical to one long run.
"""

import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
from ue22cs343bb1_openmp_assignment_tpu.ops.step import run_to_quiescence
from ue22cs343bb1_openmp_assignment_tpu.state import (continue_with_traces,
                                                      init_state)


def local_traces(rng, cfg, n_instrs):
    out = []
    for n in range(cfg.num_nodes):
        tr = []
        for _ in range(n_instrs):
            a = (n << cfg.block_bits) | int(rng.integers(cfg.mem_size))
            if rng.random() < 0.5:
                tr.append((0, a, 0))
            else:
                tr.append((1, a, int(rng.integers(256))))
        out.append(tr)
    return out


def test_async_chained_phases_equal_one_run():
    cfg = SystemConfig.reference(num_nodes=4, max_instrs=16)
    rng = np.random.default_rng(11)
    p1 = local_traces(rng, cfg, 16)
    p2 = local_traces(rng, cfg, 16)

    st = run_to_quiescence(cfg, init_state(cfg, p1), 20_000)
    st = continue_with_traces(cfg, st, traces=p2)
    st = run_to_quiescence(cfg, st, 20_000)
    assert bool(st.quiescent())

    cfg_long = SystemConfig.reference(num_nodes=4, max_instrs=32)
    concat = [a + b for a, b in zip(p1, p2)]
    ref = run_to_quiescence(cfg_long, init_state(cfg_long, concat), 20_000)
    for f in ("cache_addr", "cache_val", "cache_state", "memory",
              "dir_state", "dir_bitvec"):
        np.testing.assert_array_equal(np.asarray(getattr(st, f)),
                                      np.asarray(getattr(ref, f)), f)


def test_sync_chained_phases_equal_one_run():
    cfg = SystemConfig.reference(num_nodes=4, max_instrs=16)
    rng = np.random.default_rng(13)
    p1 = local_traces(rng, cfg, 16)
    p2 = local_traces(rng, cfg, 16)

    st = se.from_sim_state(cfg, init_state(cfg, p1))
    st = se.run_sync_to_quiescence(cfg, st, 8, 20_000)
    st = se.continue_with_traces(cfg, st, traces=p2)
    st = se.run_sync_to_quiescence(cfg, st, 8, 20_000)
    assert bool(st.quiescent())
    se.check_exact_directory(cfg, st)
    assert int(st.metrics.instrs_retired) == 4 * 32

    cfg_long = SystemConfig.reference(num_nodes=4, max_instrs=32)
    concat = [a + b for a, b in zip(p1, p2)]
    ref = se.run_sync_to_quiescence(
        cfg_long, se.from_sim_state(cfg_long, init_state(cfg_long, concat)),
        8, 20_000)
    for f in ("cache_addr", "cache_val", "cache_state"):
        np.testing.assert_array_equal(np.asarray(getattr(st, f)),
                                      np.asarray(getattr(ref, f)), f)
    mem_a, ds_a, bv_a = se.to_sim_arrays(cfg, st)
    mem_b, ds_b, bv_b = se.to_sim_arrays(cfg_long, ref)
    np.testing.assert_array_equal(mem_a, mem_b)
    np.testing.assert_array_equal(ds_a, ds_b)
    np.testing.assert_array_equal(bv_a, bv_b)


def test_cross_node_streaming_invariants():
    """Racy cross-node phases: chained outcome is a legal (barriered)
    schedule — retire counts and invariants must hold."""
    cfg = SystemConfig.scale(num_nodes=32, max_instrs=16)
    st = se.from_sim_state(
        cfg, CoherenceSystem.from_workload(
            cfg, "uniform", trace_len=16, seed=0, local_frac=0.2).state)
    total = 0
    for phase_seed in range(3):
        st = se.run_sync_to_quiescence(cfg, st, 16, 50_000)
        assert bool(st.quiescent())
        se.check_exact_directory(cfg, st)
        total += 32 * 16
        assert int(st.metrics.instrs_retired) == total
        nxt = CoherenceSystem.from_workload(
            cfg, "uniform", trace_len=16, seed=phase_seed + 1,
            local_frac=0.2).state
        st = se.continue_with_traces(
            cfg, st, instr_arrays=(nxt.instr_op, nxt.instr_addr,
                                   nxt.instr_val, nxt.instr_count))


def test_not_quiescent_rejected():
    cfg = SystemConfig.reference(num_nodes=4)
    traces = [[(1, 0x15, 9)], [], [], []]  # cross-node write, needs hops
    st = init_state(cfg, traces)
    with pytest.raises(ValueError, match="quiescent"):
        continue_with_traces(cfg, st, traces=traces)
    ss = se.from_sim_state(cfg, st)
    with pytest.raises(ValueError, match="retired"):
        se.continue_with_traces(cfg, ss, traces=traces)


def test_checkpoint_stream_shard_composition(tmp_path):
    """Feature composition: a sharded sync run, checkpointed mid-phase,
    restored, streamed into a second phase — equals the unsharded
    two-phase run bit-for-bit (local traffic)."""
    import jax
    from ue22cs343bb1_openmp_assignment_tpu.parallel import (
        make_mesh, make_sharded_round, shard_state)
    from ue22cs343bb1_openmp_assignment_tpu.utils import checkpoint as ckpt

    cfg = SystemConfig.reference(num_nodes=8, max_instrs=16)
    rng = np.random.default_rng(21)
    p1 = local_traces(rng, cfg, 16)
    p2 = local_traces(rng, cfg, 16)

    # sharded phase 1, checkpoint after 5 rounds
    mesh = make_mesh(jax.devices()[:8])
    st = shard_state(cfg, mesh, se.from_sim_state(cfg, init_state(cfg, p1)))
    round_fn = make_sharded_round(cfg, mesh, st)
    for _ in range(5):
        st = round_fn(st)
    path = str(tmp_path / "mid.ckpt")
    ckpt.save_checkpoint(path, cfg, st)

    # restore (host-backed), finish phase 1, stream phase 2, finish
    cfg2, restored, meta = ckpt.load_checkpoint(path)
    assert meta["kind"] == "sync"
    restored = se.run_sync_to_quiescence(cfg2, restored, 8, 20_000)
    restored = se.continue_with_traces(cfg2, restored, traces=p2)
    final = se.run_sync_to_quiescence(cfg2, restored, 8, 20_000)
    assert bool(final.quiescent())
    se.check_exact_directory(cfg2, final)

    # unsharded, uncheckpointed two-phase reference; the round/rounds
    # counters tick during chunk overshoot past quiescence (harmless
    # fixpoint rounds) and legitimately differ between the two paths —
    # machine state and retire counts must not
    ref = se.run_sync_to_quiescence(
        cfg, se.from_sim_state(cfg, init_state(cfg, p1)), 8, 20_000)
    ref = se.continue_with_traces(cfg, ref, traces=p2)
    ref = se.run_sync_to_quiescence(cfg, ref, 8, 20_000)
    for f in ("cache_addr", "cache_val", "cache_state", "instr_pack",
              "instr_count", "idx"):
        np.testing.assert_array_equal(np.asarray(getattr(final, f)),
                                      np.asarray(getattr(ref, f)), f)
    np.testing.assert_array_equal(np.asarray(final.dm[:, :4]),
                                  np.asarray(ref.dm[:, :4]))
    assert (int(final.metrics.instrs_retired)
            == int(ref.metrics.instrs_retired) == 8 * 32)


def test_multi_txn_chained_phases_equal_one_run():
    """Phase streaming under multi-transaction windows: chained phases
    must land exactly where one long run lands (local traffic), and
    the phase boundary must reset the claim/action columns correctly
    for the window machinery."""
    cfg = SystemConfig.reference(num_nodes=4, max_instrs=16, txn_width=3)
    rng = np.random.default_rng(29)
    p1 = local_traces(rng, cfg, 16)
    p2 = local_traces(rng, cfg, 16)

    st = se.from_sim_state(cfg, init_state(cfg, p1))
    st = se.run_sync_to_quiescence(cfg, st, 8, 20_000)
    st = se.continue_with_traces(cfg, st, traces=p2)
    st = se.run_sync_to_quiescence(cfg, st, 8, 20_000)
    assert bool(st.quiescent())
    se.check_exact_directory(cfg, st)
    assert int(st.metrics.instrs_retired) == 4 * 32

    cfg_long = SystemConfig.reference(num_nodes=4, max_instrs=32,
                                      txn_width=3)
    concat = [a + b for a, b in zip(p1, p2)]
    ref = se.run_sync_to_quiescence(
        cfg_long,
        se.from_sim_state(cfg_long, init_state(cfg_long, concat)),
        8, 20_000)
    for f in ("cache_addr", "cache_val", "cache_state"):
        np.testing.assert_array_equal(np.asarray(getattr(st, f)),
                                      np.asarray(getattr(ref, f)), f)
    mem_a, ds_a, _ = se.to_sim_arrays(cfg, st)
    mem_b, ds_b, _ = se.to_sim_arrays(cfg_long, ref)
    np.testing.assert_array_equal(mem_a, mem_b)
    np.testing.assert_array_equal(ds_a, ds_b)
