"""Pallas burst kernel (ops.pallas_burst) vs the XLA burst phase.

The kernel must be bit-exact against the XLA path: same hits, same
burst lengths, same write effects, same stop-slot pick — and therefore
identical full-round and full-run results with cfg.pallas_burst on.
Runs in Pallas interpreter mode on CPU (the conftest platform); the
compiled path is exercised when a TPU backend is attached.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops import pallas_burst
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se


def _proc_cfg(**kw):
    cfg = SystemConfig.scale(num_nodes=kw.pop("num_nodes", 128),
                             drain_depth=kw.pop("drain_depth", 6), **kw)
    return dataclasses.replace(cfg, procedural="uniform", max_instrs=1,
                               proc_local_permille=700)


def test_burst_kernel_matches_round_phase():
    """Direct comparison: pallas_burst.burst vs one engine round's
    state delta on a warmed-up machine (so caches are populated and
    bursts actually retire hits)."""
    cfg = _proc_cfg()
    st = se.procedural_state(cfg, 200)
    st = se.run_rounds(cfg, st, 40)          # warm caches mid-run
    pcfg = dataclasses.replace(cfg, pallas_burst=True)
    a = se.round_step(cfg, st)
    b = se.round_step(pcfg, st)
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_full_run_bit_identical_with_pallas_burst():
    """Whole procedural run to quiescence: flag on == flag off."""
    cfg = _proc_cfg(num_nodes=256, drain_depth=4)
    st = se.procedural_state(cfg, 96, seed=3)
    off = se.run_sync_to_quiescence(cfg, st, 16, 50_000)
    pcfg = dataclasses.replace(cfg, pallas_burst=True)
    on = se.run_sync_to_quiescence(pcfg, st, 16, 50_000)
    assert bool(on.quiescent())
    for x, y in zip(jax.tree_util.tree_leaves(off),
                    jax.tree_util.tree_leaves(on)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    se.check_exact_directory(cfg, on)


def test_burst_outputs_internally_consistent():
    """Kernel-level sanity on a cold machine: a cold cache bursts zero
    hits and stops on its first live instruction."""
    cfg = _proc_cfg(num_nodes=128)
    st = se.procedural_state(cfg, 10)
    d, rh, wh, oa, val, live, cv, cs = pallas_burst.burst(
        cfg, st.cache_addr, st.cache_val, st.cache_state, st.idx,
        st.instr_count)
    assert d.shape == (128,)
    np.testing.assert_array_equal(np.asarray(d), 0)
    np.testing.assert_array_equal(np.asarray(rh), 0)
    assert bool(jnp.all(live))
    np.testing.assert_array_equal(np.asarray(cv),
                                  np.asarray(st.cache_val))
