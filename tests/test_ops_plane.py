"""The live ops plane: structured event stream, watch streaming,
Prometheus exposition, burn-rate alerting, and the fleet aggregator.

Anchors pinned here:

- **Event byte-determinism**: two identical VirtualClock daemon
  sessions with an attached emitter produce byte-identical
  ``cache-sim/events/v1`` streams (``dumps()`` equality), and the
  stream passes its own validator (strictly increasing seq,
  non-decreasing t_s).
- **Ring bounding**: the emitter holds at most ``ring`` rows; dropped
  rows are counted, never silently lost from the accounting.
- **Watch over a live socket**: the long-lived ``watch`` verb pushes
  a baseline stats row, then event rows and stats deltas, then a
  terminal end row — and the connection is reusable for plain
  request/response afterwards. A bare ``watch`` through the
  request/response path errors instead of falling through.
- **Fleet merge exactness**: ``cache-sim/fleet/v1`` counters equal
  the integer sums of the per-replica docs; shared-edge histograms
  merge elementwise; mismatched edges are refused.
- **Burn-rate matrix**: an alert needs BOTH windows burning; the
  hysteresis latch yields one alert per excursion and recovery
  re-arms it.
- **Exposition golden**: the Prometheus text rendering of a fixed
  stats doc is byte-pinned.
- **Empty-sample hardening**: ``percentile`` of an empty sample is a
  clear ValueError (list and numpy array alike), and
  ``latency_summary`` of nothing is None, not a crash.
"""

import json
import pathlib
import threading

import pytest

from ue22cs343bb1_openmp_assignment_tpu.daemon.client import DaemonClient
from ue22cs343bb1_openmp_assignment_tpu.daemon.core import (
    DaemonCore, attach_emitter, drive)
from ue22cs343bb1_openmp_assignment_tpu.daemon.server import DaemonServer
from ue22cs343bb1_openmp_assignment_tpu.obs import burnrate, events, fleet
from ue22cs343bb1_openmp_assignment_tpu.obs import schema as obs_schema
from ue22cs343bb1_openmp_assignment_tpu.obs import promexpo
from ue22cs343bb1_openmp_assignment_tpu.obs.clock import VirtualClock
from ue22cs343bb1_openmp_assignment_tpu.serve import JobSpec

GOLDEN = pathlib.Path(__file__).parent / "golden"


def _spec(name, nodes=2, trace_len=4, workload="uniform", seed=0):
    return JobSpec(name=name, workload=workload, nodes=nodes,
                   trace_len=trace_len, seed=seed)


def _driven_core(schedule=None, **kw):
    core = DaemonCore(slots=2, max_buckets=2, chunk=8,
                      clock=VirtualClock(), **kw)
    em = attach_emitter(core)
    drive(core, schedule if schedule is not None else [
        (0.0, _spec("a"), "batch"),
        (0.001, _spec("b"), "interactive"),
        (0.002, _spec("c", nodes=4), "batch"),
    ])
    return core, em


# -- event stream ----------------------------------------------------------


def test_event_stream_byte_deterministic():
    _, e1 = _driven_core()
    _, e2 = _driven_core()
    assert e1.dumps() == e2.dumps()
    assert e1.rows, "a driven session must emit events"
    events.validate(None, e1.rows, "run")
    kinds = [r["kind"] for r in e1.rows]
    assert "submit-accepted" in kinds
    assert "admitted" in kinds
    assert "quiesced" in kinds
    # admitted rows carry the wave/slot placement
    adm = next(r for r in e1.rows if r["kind"] == "admitted")
    assert "wave" in adm and "slot" in adm and "bucket" in adm
    qui = next(r for r in e1.rows if r["kind"] == "quiesced")
    assert qui["ok"] and qui["e2e_ms"] > 0


def test_event_stream_rides_the_stats_doc():
    core, em = _driven_core()
    stats = core.stats()
    obs_schema.validate_daemon_stats(stats)
    assert stats["events"] == {"path": None, "ring": events.DEFAULT_RING,
                               "seq": em.seq, "dropped": 0}
    # stats_seq is monotonic per snapshot
    assert core.stats()["stats_seq"] == stats["stats_seq"] + 1
    # per-lane latency histograms ride along and agree with the jobs
    hist = stats["lanes"]["batch"]["hist"]
    assert hist is not None
    assert sum(hist["counts"]) == hist["count"] == 2


def test_event_ring_bounds_memory_not_accounting():
    core = DaemonCore(slots=2, max_buckets=1, chunk=8,
                      clock=VirtualClock())
    em = attach_emitter(core, ring=4)
    drive(core, [(0.001 * i, _spec(f"j{i}"), "batch")
                 for i in range(6)])
    assert len(em.rows) <= 4
    assert em.dropped == em.seq - len(em.rows) > 0
    # the surviving window still validates on its own
    events.validate(None, em.rows, "ring")


def test_event_file_round_trip(tmp_path):
    core = DaemonCore(slots=2, max_buckets=2, chunk=8,
                      clock=VirtualClock())
    em = attach_emitter(core, path=tmp_path)
    drive(core, [(0.0, _spec("a"), "batch")])
    em.close()
    art = events.load(tmp_path / events.FILENAME)
    assert art["schema"] == events.SCHEMA_ID
    assert art["clock"] == "virtual"
    assert [r["kind"] for r in art["rows"]] == \
        [r["kind"] for r in em.rows]


def test_event_validator_rejects_malformed():
    ok = {"seq": 0, "t_s": 0.0, "kind": "admitted", "job": "a"}
    events.validate(None, [ok], "v")
    with pytest.raises(ValueError, match="kind"):
        events.validate(None, [dict(ok, kind="warp-drive")], "v")
    with pytest.raises(ValueError, match="seq"):
        events.validate(None, [dict(ok), dict(ok, seq=0)], "v")
    with pytest.raises(ValueError, match="t_s"):
        events.validate(
            None, [dict(ok, t_s=1.0), dict(ok, seq=1, t_s=0.5)], "v")


def test_lane_reject_and_eviction_events():
    core = DaemonCore(slots=2, max_buckets=1, chunk=8,
                      clock=VirtualClock(), lane_depth=2,
                      retain_results=2)
    em = attach_emitter(core)
    # a burst overflows the 2-deep batch queue; the stragglers land
    # after the burst drains and push completions past retain_results
    sched = [(0.0, _spec(f"q{i}"), "batch") for i in range(5)]
    sched += [(0.5, _spec("late0"), "batch"),
              (0.6, _spec("late1"), "batch")]
    drive(core, sched)
    kinds = [r["kind"] for r in em.rows]
    assert "lane-reject" in kinds
    rej = next(r for r in em.rows if r["kind"] == "lane-reject")
    assert rej["reason"] == "queue-full"
    assert "result-evicted" in kinds


# -- watch streaming -------------------------------------------------------


def _serving(tmp_path, **core_kw):
    addr = str(tmp_path / "sock")
    core = DaemonCore(slots=2, max_buckets=2, chunk=8, **core_kw)
    srv = DaemonServer(core, addr)
    t = threading.Thread(target=srv.run, daemon=True)
    t.start()
    return srv, addr, t


def test_watch_streams_stats_and_events(tmp_path):
    srv, addr, t = _serving(tmp_path)
    try:
        with DaemonClient(addr, timeout_s=None) as c:
            c.wait_up(10)
            for i in range(3):
                r = c.submit(_spec(f"j{i}"))
                assert r.get("status") == "queued", r
            rows = list(c.watch(interval_s=0.05, max_s=10.0,
                                max_rows=80))
            types = [r.get("type") for r in rows]
            assert types[0] == "stats", "baseline stats row first"
            assert rows[-1]["type"] == "end"
            assert rows[-1]["reason"] in ("max-rows", "max-s")
            kinds = [r["event"]["kind"] for r in rows
                     if r.get("type") == "event"]
            assert "quiesced" in kinds
            evs = [r["event"] for r in rows
                   if r.get("type") == "event"]
            events.validate(None, evs, "watch")
            # stream over: the connection answers plain requests again
            assert c.ping().get("ok")
            assert c.stats()["jobs"]["done"] == 3
    finally:
        srv.stop()
        t.join(timeout=10)


def test_watch_max_rows_bounds_the_stream(tmp_path):
    srv, addr, t = _serving(tmp_path)
    try:
        with DaemonClient(addr, timeout_s=None) as c:
            c.wait_up(10)
            rows = list(c.watch(interval_s=0.02, max_rows=1))
            assert [r["type"] for r in rows] == ["stats", "end"]
            assert rows[-1]["reason"] == "max-rows"
    finally:
        srv.stop()
        t.join(timeout=10)


def test_watch_through_request_path_errors_not_shuts_down(tmp_path):
    srv, addr, t = _serving(tmp_path)
    try:
        resp = srv._handle({"op": "watch"})
        assert resp.get("error")
        assert not srv._stop.is_set(), \
            "a stray watch request must not shut the daemon down"
    finally:
        srv.stop()
        t.join(timeout=10)


# -- fleet aggregation -----------------------------------------------------


def test_fleet_merge_counters_are_exact_sums():
    c1, _ = _driven_core()
    c2, _ = _driven_core([(0.0, _spec("x"), "batch"),
                          (0.001, _spec("y"), "batch")])
    s1, s2 = c1.stats(), c2.stats()
    doc = fleet.merge_stats([s1, s2], labels=["A", "B"])
    obs_schema.validate_fleet(doc)
    for k in ("submitted", "rejected", "done", "quiesced"):
        assert doc["jobs"][k] == s1["jobs"][k] + s2["jobs"][k]
    assert doc["chunks"] == s1["chunks"] + s2["chunks"]
    assert doc["uptime_s"] == max(s1["uptime_s"], s2["uptime_s"])
    assert doc["replicas"] == 2
    assert [r["replica"] for r in doc["per_replica"]] == ["A", "B"]
    # histogram merge is elementwise-exact
    h1 = s1["lanes"]["batch"]["hist"]
    h2 = s2["lanes"]["batch"]["hist"]
    hm = doc["lanes"]["batch"]["hist"]
    assert hm["count"] == h1["count"] + h2["count"]
    assert hm["counts"] == [a + b for a, b
                            in zip(h1["counts"], h2["counts"])]
    assert hm["sum_ms"] == pytest.approx(h1["sum_ms"] + h2["sum_ms"])
    # buckets keep replica identity instead of being summed
    assert all("replica" in b for b in doc["buckets"])
    # the fleet doc renders through both human surfaces
    assert "TOTAL" in fleet.render_top(doc)
    assert "cache_sim_jobs_done_total" in promexpo.render(doc)


def test_fleet_merge_refuses_bad_input():
    with pytest.raises(ValueError):
        fleet.merge_stats([])
    c1, _ = _driven_core()
    with pytest.raises(ValueError):
        fleet.merge_stats([c1.stats()], labels=["a", "b"])


def test_fleet_hist_merge_refuses_mismatched_edges():
    a = {"edges_ms": [1.0, 2.0], "counts": [1, 0, 0], "count": 1,
         "sum_ms": 0.5}
    b = {"edges_ms": [1.0, 4.0], "counts": [0, 1, 0], "count": 1,
         "sum_ms": 3.0}
    with pytest.raises(ValueError, match="mismatched bucket edges"):
        fleet._merge_hists([a, b])
    merged = fleet._merge_hists([a, dict(a)])
    assert merged["counts"] == [2, 0, 0] and merged["count"] == 2
    assert fleet._merge_hists([None, None]) is None


def test_fleet_tolerates_pre_ops_stats_docs():
    """A v1 stats doc from before this PR (no stats_seq / hist /
    events / slo_alerts) still validates and still merges."""
    core, _ = _driven_core()
    old = json.loads(json.dumps(core.stats()))
    for k in ("stats_seq", "events", "slo_alerts", "burnrate"):
        old.pop(k, None)
    for lane in old["lanes"].values():
        lane.pop("hist", None)
    obs_schema.validate_daemon_stats(old)
    doc = fleet.merge_stats([old], labels=["legacy"])
    assert doc["slo_alerts"] == 0
    assert doc["per_replica"][0]["stats_seq"] is None


# -- burn-rate alerting ----------------------------------------------------


def _feed(mon, t0, t1, latency_s, n=50):
    dt = (t1 - t0) / n
    out = []
    for i in range(n):
        a = mon.feed(t0 + i * dt, latency_s)
        if a:
            out.append(a)
    return out


def test_burn_needs_both_windows():
    # a short bad burst lights the fast window but not the slow one
    mon = burnrate.BurnRateMonitor(threshold_ms=5.0, objective=0.99,
                                   fast_s=10.0, slow_s=100.0,
                                   factor=2.0)
    _feed(mon, 0.0, 90.0, 0.001, n=1000)  # dense good traffic
    _feed(mon, 90.0, 95.0, 0.5, n=10)     # 5s burst of 500ms jobs
    s = mon.summary()
    assert s["fast_burn"] >= 2.0
    assert s["slow_burn"] < 2.0
    assert not mon.breached(), \
        "fast-only burn must not page (transient spike)"


def test_burn_alert_is_edge_triggered_and_rearms():
    mon = burnrate.BurnRateMonitor(threshold_ms=5.0, objective=0.99,
                                   fast_s=10.0, slow_s=30.0,
                                   factor=2.0)
    first = _feed(mon, 0.0, 40.0, 0.5)    # sustained breach
    assert len(first) == 1, "hysteresis: one alert per excursion"
    assert mon.breached() and mon.summary()["alerting"]
    # recovery: both windows drain below the factor
    _feed(mon, 40.0, 120.0, 0.001)
    assert not mon.summary()["alerting"]
    again = _feed(mon, 120.0, 160.0, 0.5)
    assert len(again) == 1, "a fresh excursion re-alerts"
    assert len(mon.alerts) == 2
    a = mon.alerts[0]
    assert a["fast_burn"] >= 2.0 and a["slow_burn"] >= 2.0
    assert a["threshold_ms"] == 5.0


def test_parse_burn_spec():
    m = burnrate.parse_burn_spec(
        "5ms,objective=0.999,fast=30,slow=120,factor=4")
    assert m == {"threshold_ms": 5.0, "objective": 0.999,
                 "fast_s": 30.0, "slow_s": 120.0, "factor": 4.0}
    assert burnrate.parse_burn_spec("2.5") == {"threshold_ms": 2.5}
    with pytest.raises(ValueError):
        burnrate.parse_burn_spec("")
    with pytest.raises(ValueError):
        burnrate.parse_burn_spec("5ms,warp=9")


def test_burn_feeds_from_daemon_core():
    mon = burnrate.monitor_from_spec("0.000001ms,fast=60,slow=300")
    core = DaemonCore(slots=2, max_buckets=2, chunk=8,
                      clock=VirtualClock(), burn=mon)
    attach_emitter(core)
    drive(core, [(0.0, _spec("a"), "batch"),
                 (0.001, _spec("b"), "batch")])
    stats = core.stats()
    assert stats["slo_alerts"] >= 1
    assert stats["burnrate"]["samples"] == 2
    assert any(r["kind"] == "slo-alert" for r in core.emitter.rows)
    obs_schema.validate_daemon_stats(stats)


# -- exposition golden -----------------------------------------------------

_FIXED_STATS = {
    "schema": "cache-sim/daemon-stats/v1",
    "clock": "virtual",
    "uptime_s": 12.5,
    "stats_seq": 7,
    "jobs": {"submitted": 10, "rejected": 2, "done": 8, "quiesced": 8},
    "lanes": {
        "batch": {"queued": 1, "submitted": 7, "admitted": 6,
                  "rejected": 2, "done": 5,
                  "hist": {"edges_ms": [1.0, 2.0, 4.0],
                           "counts": [1, 2, 1, 1], "count": 5,
                           "sum_ms": 11.5}},
        "interactive": {"queued": 0, "submitted": 3, "admitted": 3,
                        "rejected": 0, "done": 3, "hist": None},
    },
    "buckets": [{"bucket": "mesi:2x4", "busy": 1, "admitted": 6,
                 "chunks": 3}],
    "chunks": 4,
    "busy_s": 9.25,
    "mb_dropped": 0,
    "mid_wave_swaps": 1,
    "bucket_growths": 0,
    "results_evicted": 2,
    "slo_alerts": 1,
    "queue_depth_peak": 3,
    "draining": False,
}


def test_promexpo_golden():
    text = promexpo.render(_FIXED_STATS)
    golden = GOLDEN / "promexpo.txt"
    assert text == golden.read_text(), \
        f"regenerate with: python -c \"import json,sys; " \
        f"sys.path.insert(0,'tests'); from test_ops_plane import " \
        f"_FIXED_STATS; from " \
        f"ue22cs343bb1_openmp_assignment_tpu.obs import promexpo; " \
        f"open('{golden}','w').write(" \
        f"promexpo.render(_FIXED_STATS))\""


def test_promexpo_histogram_is_cumulative():
    text = promexpo.render(_FIXED_STATS)
    lines = [ln for ln in text.splitlines()
             if ln.startswith("cache_sim_job_latency_ms")]
    by_le = [ln for ln in lines if "le=" in ln]
    counts = [float(ln.rsplit(" ", 1)[1]) for ln in by_le]
    assert counts == sorted(counts), "le buckets must be cumulative"
    assert by_le[-1].startswith(
        'cache_sim_job_latency_ms_bucket{lane="batch",le="+Inf"}')
    assert counts[-1] == 5.0


def test_promexpo_skips_missing_keys():
    text = promexpo.render({"jobs": {"done": 3}})
    assert "cache_sim_jobs_done_total 3" in text
    assert "uptime" not in text


# -- empty-sample hardening ------------------------------------------------


def test_percentile_of_empty_sample_raises():
    from ue22cs343bb1_openmp_assignment_tpu.obs import timeseries
    import numpy as np
    with pytest.raises(ValueError, match="empty sample"):
        timeseries.percentile([], 95.0)
    with pytest.raises(ValueError, match="empty sample"):
        timeseries.percentile(np.array([]), 95.0)
    assert timeseries.latency_summary([]) is None
    assert timeseries.latency_summary(np.array([])) is None


def test_log_histogram_observe_and_merge():
    from ue22cs343bb1_openmp_assignment_tpu.obs import timeseries
    h = timeseries.LogHistogram()
    for ms in (0.0005, 1.0, 3.0, 1e9):
        h.observe(ms)
    doc = h.to_doc()
    assert doc["count"] == 4 == sum(doc["counts"])
    assert doc["counts"][-1] == 1, "1e9 ms lands in the overflow"
    assert doc["edges_ms"] == list(timeseries.HIST_EDGES_MS)
    merged = timeseries.merge_hist_docs([doc, doc])
    assert merged["count"] == 8
    assert merged == fleet._merge_hists([doc, doc]), \
        "the inline jax-free twin must agree with timeseries"
