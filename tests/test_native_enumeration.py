"""Deep-engine outcome inclusion against NATIVE-engine enumeration.

The JAX-side inclusion suite (tests/test_outcome_inclusion.py) samples
the async schedule space on coarse+tight delay grids with a rank
subset — a deep outcome falling only in the unsampled region would be
a silent false pass (round-4 verdict). The native C++ engine runs
these 4-node micro-traces orders of magnitude faster than the JAX
async path, so here the message-level outcome set is enumerated over
a DENSE schedule product — a wide delay grid covering both
whole-transaction serializations and mid-flight interleavings, times
ALL 24 rank permutations — and every deep-engine outcome (classic,
waves, read-storm) must land inside it. A seeded fuzzer extends the
check to randomized micro-traces so the fixed case list cannot
overfit.

The native and async JAX engines are lockstep-identical
(tests/test_native_differential*.py), so native enumeration speaks
for the message-level machine.
"""

import dataclasses
import itertools

import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.native.bindings import NativeEngine
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
from ue22cs343bb1_openmp_assignment_tpu.state import init_state
from ue22cs343bb1_openmp_assignment_tpu.utils.golden import (
    format_node_dump, state_to_dumps)

from tests.test_outcome_inclusion import (CASES, STORM_CASES,
                                          WAVE_CASES, sync_outcomes)

# dense grid: 0/1/2 catch mid-flight interleavings (a hop is ~1
# cycle), 4/6/9/12/18 whole-transaction separations (~6 cycles/txn)
DELAYS = (0, 1, 2, 4, 6, 9, 12, 18)
RANKS = list(itertools.permutations(range(4)))


def _fp_native(cfg, eng):
    import types
    ns = types.SimpleNamespace(**eng.export_state())
    return "".join(format_node_dump(d) for d in state_to_dumps(cfg, ns))


def _fp_sync(cfg, st):
    return "".join(format_node_dump(d)
                   for d in state_to_dumps(cfg, se.to_dump_view(cfg, st)))


_NATIVE_CACHE = {}


def native_outcomes_cached(cfg, key, traces):
    """native_outcomes memoized per trace (the four deep engine modes
    check against the same message-level set). The cache key is the
    case name, so every caller must enumerate under the one reference
    config — asserted, or a config variant would silently reuse the
    wrong outcome set."""
    assert cfg == SystemConfig.reference(), (
        "native_outcomes_cached keys on the case name only; "
        "non-reference configs must call native_outcomes directly")
    if key not in _NATIVE_CACHE:
        _NATIVE_CACHE[key] = native_outcomes(cfg, traces)
    return _NATIVE_CACHE[key]


def native_outcomes(cfg, traces):
    """Final-dump set over the dense delay product x all 24 ranks."""
    active = [n for n, tr in enumerate(traces) if tr]
    out = set()
    for delays in itertools.product(DELAYS, repeat=len(active)):
        d = [0] * cfg.num_nodes
        for n, dv in zip(active, delays):
            d[n] = dv
        for rank in RANKS:
            eng = NativeEngine(cfg)
            eng.load_traces(traces)
            eng.set_schedule(d, None)
            eng.set_arbitration(np.asarray(rank, np.int32))
            eng.run(100_000)
            assert eng.quiescent
            out.add(_fp_native(cfg, eng))
    return out


def deep_outcomes(cfg, traces, seeds=range(16)):
    """test_outcome_inclusion.sync_outcomes with the dump-string
    fingerprint this module shares with the native side."""
    return sync_outcomes(cfg, traces, seeds=seeds, fp=_fp_sync)


def _deep_cfg(waves, storm):
    return dataclasses.replace(
        SystemConfig.reference(), deep_window=True, drain_depth=3,
        txn_width=2, deep_slots=4, deep_ownerval_slots=2,
        deep_waves=waves, deep_read_storm=storm)


FIXED = {**CASES, **WAVE_CASES, **STORM_CASES}


@pytest.mark.parametrize("waves,storm", [(1, False), (3, False),
                                         (1, True), (2, True)])
@pytest.mark.parametrize("name", sorted(FIXED))
def test_deep_outcomes_within_native_enumeration(name, waves, storm):
    """Every deep outcome (all engine modes) must be reachable by the
    message-level machine under SOME schedule in the dense set."""
    traces = FIXED[name]
    a = native_outcomes_cached(SystemConfig.reference(), name, traces)
    s = deep_outcomes(_deep_cfg(waves, storm), traces)
    missing = {fp: seed for fp, seed in s.items() if fp not in a}
    assert not missing, (
        f"{name} waves={waves} storm={storm}: deep seeds "
        f"{sorted(missing.values())} produced final states outside the "
        f"native-enumerated outcome set ({len(s)} deep / {len(a)} "
        f"native outcomes)")


def _random_trace(rng):
    """A 4-node micro-trace of 1-3 ops per node over four hot blocks
    homed at nodes 2 and 3 — 0x20/0x24 and 0x30 conflict on cache
    slots, so fills, upgrades, eviction notices, and storms all arise
    from the same small address set."""
    blocks = [0x20, 0x30, 0x24, 0x21]
    traces = []
    for n in range(4):
        tr = []
        for _ in range(int(rng.integers(1, 4))):
            op = int(rng.integers(0, 2))
            addr = blocks[int(rng.integers(0, len(blocks)))]
            val = int(rng.integers(1, 100))
            tr.append((op, addr, val if op else 0))
        traces.append(tr)
    return traces


@pytest.mark.slow  # ~35 s/case single-CPU: dense native enumeration
@pytest.mark.parametrize("case_seed", range(6))
def test_fuzzed_microtraces_within_native_enumeration(case_seed):
    """Seeded random micro-traces: the deep engine's outcome (classic +
    storm modes) must stay inside the native-enumerated set, so the
    fixed case list above cannot overfit the wave/storm algebra."""
    rng = np.random.default_rng(1000 + case_seed)
    traces = _random_trace(rng)
    a = native_outcomes_cached(SystemConfig.reference(),
                               f"fuzz{case_seed}", traces)
    for waves, storm in [(1, False), (2, True)]:
        s = deep_outcomes(_deep_cfg(waves, storm), traces,
                          seeds=range(8))
        missing = {fp: seed for fp, seed in s.items() if fp not in a}
        assert not missing, (
            f"fuzz case {case_seed} waves={waves} storm={storm}: deep "
            f"seeds {sorted(missing.values())} outside the native set "
            f"({len(s)} deep / {len(a)} native)")
