"""Index-pressure auditor (analysis/indexcheck) — the static
gather/scatter attribution behind `analyze --index`.

The load-bearing assertions avoid restating the module's constants
where they can be re-derived: per-plane indices must sum to the
engine's indices/step, every pinned budget must equal a freshly traced
site count, the merge detector is exercised on synthetic jaxprs small
enough to verify by hand, and the seeded mutation
(INDEX_MUTATIONS.split_packed_scatter) must be killed by the static
pass alone AND stay invisible to the dynamic semantics (bit-identical
eager parity).

Golden regen (deliberate inventory changes only):

    JAX_PLATFORMS=cpu python - <<'PY'
    import json
    from ue22cs343bb1_openmp_assignment_tpu.analysis import indexcheck
    rep = indexcheck.check(engines=["async"], probe=False)
    open("tests/golden/index_async_n8.json", "w").write(
        json.dumps(rep, indent=2, sort_keys=True) + "\n")
    PY
"""

import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu.analysis import indexcheck as ic
from ue22cs343bb1_openmp_assignment_tpu.analysis import (lint_jaxpr,
                                                         lint_trace,
                                                         mutations,
                                                         runner)
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops import step
from ue22cs343bb1_openmp_assignment_tpu.state import init_state

GOLDEN = pathlib.Path(__file__).parent / "golden" / "index_async_n8.json"


# ------------------------------------------------------------- inventory


def test_golden_async_inventory_byte_identical():
    """The full async audit doc (all four targets, plane attribution,
    signatures, budgets) is deterministic and pinned byte-for-byte:
    any new index site, plane reattribution or signature drift shows
    up as a golden diff, not a silent number change."""
    rep = ic.check(engines=["async"], probe=False)
    got = json.dumps(rep, indent=2, sort_keys=True) + "\n"
    assert got == GOLDEN.read_text()


def test_plane_split_sums_to_per_step_indices():
    """by_plane is a partition: per-plane indices sum to the target's
    indices/call, and the hot body's indices/call IS the engine's
    indices/step."""
    rep = ic.check(engines=["async", "sync"], probe=False)
    for eng in ("async", "sync"):
        er = rep["engines"][eng]
        for name, t in er["targets"].items():
            assert sum(v["indices"] for v in t["by_plane"].values()) \
                == t["indices_per_call"], name
            assert sum(r["indices"] for r in t["ops"]) \
                == t["indices_per_call"], name
        hot = er["hot_body"]
        assert er["indices_per_step"] \
            == er["targets"][hot]["indices_per_call"]


@pytest.mark.slow
def test_budgets_match_freshly_traced_sites():
    """Every pinned budget equals a site count traced NOW — the table
    can never drift from the code it describes (this is the assertion
    that makes the PERF.md numbers machine-checked)."""
    rep = ic.check(probe=False)     # all five engines
    seen = {}
    for er in rep["engines"].values():
        for name, t in er["targets"].items():
            seen[name] = t["index_sites"]
    for name, budget in ic.INDEX_BUDGETS.items():
        assert seen[name] == budget, name
    assert rep["ok"], rep["findings"]


def test_sites_independent_of_n():
    """Budgets are pinned at DEFAULT_NODES but sites are a property of
    the traced program, not the config size: N=4 traces the same
    counts (and is reported as budgets_enforced=False)."""
    rep = ic.check(engines=["async"], nodes=4, probe=False)
    assert not rep["budgets_enforced"]
    assert rep["ok"]
    for name, t in rep["engines"]["async"]["targets"].items():
        b = ic.INDEX_BUDGETS.get(name)
        if b is not None:
            assert t["index_sites"] == b, name


def test_fused_round_has_no_gather_scatter():
    """The fused kernel's whole point: the round body contains zero
    gather/scatter primitives — its only index eqns are the window
    dynamic slices. This is the cross-engine diff ROADMAP item 5
    builds on."""
    rep = ic.check(engines=["fused"], probe=False)
    ops = rep["engines"]["fused"]["targets"][
        "pallas_round.round_body"]["ops"]
    prims = {o["primitive"] for o in ops}
    assert not any(p == "gather" or p.startswith("scatter")
                   for p in prims), prims


# -------------------------------------------------------- merge detector


def _ops_of(fn, *args):
    closed = jax.make_jaxpr(fn)(*args)
    names = [f"arg{i}" for i in range(len(args))]
    return ic.inventory(closed, names, "t")


def test_merge_candidate_positive_pair():
    """Two scatters sharing one index vector into two different arrays
    is exactly the PR-8 shape: one candidate naming both dests."""
    def f(a, b, idx, u):
        return (a.at[idx].set(u, mode="drop"),
                b.at[idx].set(u + 1, mode="drop"))

    a = jnp.zeros((8,), jnp.int32)
    idx = jnp.arange(4, dtype=jnp.int32)
    cands = ic.merge_candidates(_ops_of(f, a, a, idx, idx))
    assert len(cands) == 1
    assert cands[0]["count"] == 2
    assert sorted(d.split("#")[0] for d in cands[0]["dests"]) \
        == ["arg0", "arg1"]


def test_merge_candidate_negative_different_index():
    """Different index vectors (structurally different producers) must
    NOT pair — there is no shared row to pack into."""
    def f(a, b, idx, u):
        return (a.at[idx].set(u, mode="drop"),
                b.at[idx + 1].set(u, mode="drop"))

    a = jnp.zeros((8,), jnp.int32)
    idx = jnp.arange(4, dtype=jnp.int32)
    assert ic.merge_candidates(_ops_of(f, a, a, idx, idx)) == []


def test_merge_candidate_boundary_chained_same_dest():
    """Two scatters chained into the SAME destination share the index
    vector but are sequential writes to one buffer — not mergeable;
    the dest-token anchoring must collapse the chain to one token."""
    def f(a, idx, u):
        return a.at[idx].set(u, mode="drop").at[idx].set(u + 1,
                                                         mode="drop")

    a = jnp.zeros((8,), jnp.int32)
    idx = jnp.arange(4, dtype=jnp.int32)
    assert ic.merge_candidates(_ops_of(f, a, idx, idx)) == []


def test_shipped_engines_name_a_candidate():
    """Acceptance: the detector names at least one concrete candidate
    in the shipped engines (the RDMA router's header/payload pair)."""
    rep = ic.check(engines=["async"], probe=False)
    cands = rep["engines"]["async"]["merge_candidates"]
    assert any("rdma_comm.route" in c["scope"] for c in cands)


# ------------------------------------------------------- seeded mutation


def test_index_mutants_killed_statically():
    """Every seeded index mutant must be caught by the static pass
    alone — budget breach plus merge candidates naming the re-split
    planes — and the world must be clean after the context exits."""
    for name, (cm, kind) in mutations.INDEX_MUTATIONS.items():
        with cm():
            rep = ic.check(engines=["async"], probe=False)
        kinds = [f["kind"] for f in rep["findings"]]
        assert not rep["ok"] and kind in kinds, (name, kinds)
        cands = [c for c in rep["engines"]["async"]["merge_candidates"]
                 if c["scope"].startswith("step.cycle")]
        assert cands, "detector must hand back the consolidation"
        dests = {d.split("#")[0] for c in cands for d in c["dests"]}
        assert {"cache_state", "cache_addr", "cache_val"} <= dests
    assert ic.check(engines=["async"], probe=False)["ok"]


@pytest.mark.slow
def test_split_commit_is_bit_identical_eagerly():
    """The mutation's cover: the de-consolidated commit is semantically
    invisible — eager per-plane commit equals the packed commit on
    every state leaf, so only the static audit can see it."""
    cfg = SystemConfig.scale(4)
    traces = [[(0, 1, 7), (1, 1, 9)], [(0, 0, 0)],
              [(2, 1, 3)], [(1, 0, 0)]]
    ref = init_state(cfg, traces)
    mut = init_state(cfg, traces)
    for _ in range(12):
        ref = step.cycle(cfg, ref)
    with mutations.split_packed_scatter():
        for _ in range(12):
            mut = step.cycle(cfg, mut)
    ref_leaves, _ = jax.tree_util.tree_flatten_with_path(ref)
    mut_leaves, _ = jax.tree_util.tree_flatten_with_path(mut)
    for (pa, la), (_, lb) in zip(ref_leaves, mut_leaves):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb),
                                      err_msg=jax.tree_util.keystr(pa))


def test_mutant_raises_site_count_everywhere_async():
    """The split commit re-adds one gather+scatter per extra plane on
    both commit rows: 27 -> 35 sites, on the cycle AND every wrapper
    that traces through it."""
    with mutations.split_packed_scatter():
        rep = ic.check(engines=["async"], probe=False)
    t = rep["engines"]["async"]["targets"]
    assert t["step.cycle"]["index_sites"] == 35
    assert t["step.run_cycles[8]"]["index_sites"] == 35
    assert t["parallel.sharded_cycle"]["index_sites"] == 35


# ------------------------------------------------- always-on jaxpr prong


@pytest.mark.slow
def test_lint_jaxpr_enforces_index_pins():
    """The --jaxpr prong pins index sites exactly (mailbox-mode deltas
    applied), covers the wave chunk as a first-class target, and the
    mutant trips it without ever running --index."""
    rep = lint_jaxpr.lint()
    assert rep["ok"], rep["findings"]
    assert "step.run_wave_chunk[2x4]" in rep["targets"]
    assert rep["targets"]["step.run_wave_chunk[2x4]"] \
        <= lint_jaxpr.EQN_BUDGETS["step.run_wave_chunk[2x4]"]
    ref = SystemConfig.reference()
    for name, sites in rep["index_sites"].items():
        assert sites == ic.index_budget(name, ref.inv_mode), name
    with mutations.split_packed_scatter():
        bad = lint_jaxpr.lint()
    rules = {f["rule"] for f in bad["findings"]}
    assert not bad["ok"] and "index_budget" in rules


# ------------------------------------------------------ no-jax boundary


def test_daemon_wire_layer_is_jax_free():
    targets = lint_trace.no_jax_targets()
    assert [p.name for p in targets] == [
        "server.py", "client.py", "events.py", "promexpo.py",
        "burnrate.py", "fleet.py"]
    assert all(p.exists() for p in targets)
    assert lint_trace.lint_no_jax() == []


def test_no_jax_flags_every_route_in():
    src = ("import jax.numpy as jnp\n"
           "from jax import lax\n"
           "import importlib\n"
           "m = importlib.import_module('jax')\n"
           "y = jnp.zeros(3)\n")
    rules = [f.rule for f in lint_trace.lint_no_jax_source(src, "s.py")]
    assert rules == ["no-jax"] * 4
    # jax inside a string or comment is NOT a finding
    assert lint_trace.lint_no_jax_source(
        "x = 'jax'  # jax\n", "s.py") == []


def test_no_jax_rides_the_default_lint_prong():
    rep = runner.run_lint(None, quiet=True)
    assert rep["ok"], rep["findings"]


# ---------------------------------------------------------------- the CLI


def test_runner_index_prong_exit_codes(capsys):
    rc = runner.main(["--index", "--index-engine", "async",
                      "--max-states", "128",
                      "--skip-model-check", "--skip-lint"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "index audit: ok" in out
    assert "indices/instr" in out
    rc = runner.main(["--index", "--skip-model-check", "--skip-lint",
                      "--mutation", "split_packed_scatter"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "index_budget" in out
    assert "merge candidate" in out


def test_runner_index_prong_budget_exhaustion(capsys):
    """A probe that cannot quiesce inside --max-states is exit 3
    (inconclusive), not a fake pass or fail."""
    rc = runner.main(["--index", "--index-engine", "async",
                      "--max-states", "1",
                      "--skip-model-check", "--skip-lint"])
    assert rc == 3


def test_runner_rejects_index_mutation_elsewhere():
    with pytest.raises(SystemExit, match="index mutation"):
        runner.main(["--skip-lint", "--mutation",
                     "split_packed_scatter"])


def test_index_row_for_perf_report():
    row = ic.index_row("async", 8)
    assert row["target"] == "step.cycle"
    assert row["index_sites"] == ic.INDEX_BUDGETS["step.cycle"]
    assert row["indices_per_step"] \
        == sum(row["by_plane"].values())
    json.dumps(row)     # must embed into the --json perf report as-is
