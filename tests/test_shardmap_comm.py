"""Explicit shard_map all-to-all message routing (parallel/shardmap_comm).

The router must be a faithful transport for the mailbox delivery
contract (ops/mailbox.deliver): every valid candidate reaches exactly
the shard owning its receiver, with payload and global arbitration
priority intact, so sorting inbound rows on (receiver, prio)
reproduces the global delivery order per receiver. Lane caps truncate
in priority order with accounting.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops.mailbox import Candidates
from ue22cs343bb1_openmp_assignment_tpu.parallel import make_mesh
from ue22cs343bb1_openmp_assignment_tpu.parallel.shardmap_comm import (
    candidate_prio, make_router, pack_fields)
from ue22cs343bb1_openmp_assignment_tpu.types import Msg

needs_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                             reason="needs 8 (virtual) devices")


def random_candidates(cfg, rng, p_send=0.6):
    N, S, W = cfg.num_nodes, cfg.out_slots, cfg.msg_bitvec_words
    send = rng.random((N, S)) < p_send
    ctype = np.where(send, rng.integers(0, 13, (N, S)), int(Msg.NONE))
    return Candidates(
        type=jnp.asarray(ctype, jnp.int32),
        recv=jnp.asarray(rng.integers(0, N, (N, S)), jnp.int32),
        sender=jnp.asarray(np.broadcast_to(np.arange(N)[:, None], (N, S)),
                           jnp.int32),
        addr=jnp.asarray(rng.integers(0, 256, (N, S)), jnp.int32),
        value=jnp.asarray(rng.integers(0, 256, (N, S)), jnp.int32),
        second=jnp.asarray(rng.integers(0, N, (N, S)), jnp.int32),
        dirstate=jnp.asarray(rng.integers(0, 3, (N, S)), jnp.int32),
        bitvec=jnp.asarray(rng.integers(0, 2**32, (N, S, W),
                                        dtype=np.uint64), jnp.uint32),
    )


@needs_8
def test_routing_is_lossless_and_order_preserving():
    cfg = SystemConfig.scale(num_nodes=64, queue_capacity=32)
    mesh = make_mesh(jax.devices()[:8])
    D = 8
    L = cfg.num_nodes // D
    rng = np.random.default_rng(0)
    cand = random_candidates(cfg, rng)
    arb = jnp.asarray(rng.permutation(cfg.num_nodes), jnp.int32)
    prio = candidate_prio(cfg, arb)
    fields = pack_fields(cand)
    route = make_router(cfg, mesh)
    out = route(cand.type, cand.recv, prio, fields)
    assert int(out.truncated) == 0

    v = np.asarray(out.valid)
    recv = np.asarray(out.recv)[v]
    pr = np.asarray(out.prio)[v]
    fl = np.asarray(out.fields)[v]
    # ownership: global row i belongs to shard i // (D * cap); every
    # inbound receiver must be local to its shard
    cap = L * cfg.out_slots
    shard_of_row = np.repeat(np.arange(D), D * cap)[np.asarray(out.valid)]
    np.testing.assert_array_equal(recv // L, shard_of_row)

    # conservation: the routed multiset equals the sent multiset
    c_valid = (np.asarray(cand.type) != int(Msg.NONE))
    sent = {(int(r), int(p)): tuple(f) for r, p, f in zip(
        np.asarray(cand.recv)[c_valid],
        np.asarray(prio)[c_valid],
        np.asarray(fields)[c_valid])}
    got = {(int(r), int(p)): tuple(f) for r, p, f in zip(recv, pr, fl)}
    assert got == sent

    # order: per receiver, sorting inbound by prio gives exactly the
    # global delivery order (deliver's total key = recv, then prio)
    for r in np.unique(recv):
        inbound = sorted(pr[recv == r])
        expected = sorted(np.asarray(prio)[c_valid][
            np.asarray(cand.recv)[c_valid] == r])
        assert inbound == expected


@needs_8
def test_lane_cap_truncates_in_priority_order():
    cfg = SystemConfig.scale(num_nodes=64, queue_capacity=32)
    mesh = make_mesh(jax.devices()[:8])
    rng = np.random.default_rng(3)
    cand = random_candidates(cfg, rng, p_send=1.0)
    # every candidate targets node 0: one hot lane
    cand = cand._replace(recv=jnp.zeros_like(cand.recv))
    arb = jnp.asarray(rng.permutation(cfg.num_nodes), jnp.int32)
    prio = candidate_prio(cfg, arb)
    route = make_router(cfg, mesh, lane_cap=4)
    out = route(cand.type, cand.recv, prio, pack_fields(cand))
    v = np.asarray(out.valid)
    # 8 shards x 4 lane slots survive; the rest are truncated
    assert int(v.sum()) == 8 * 4
    n_sent = int((np.asarray(cand.type) != int(Msg.NONE)).sum())
    assert int(out.truncated) == n_sent - 8 * 4
    # survivors are each source shard's lowest-priority-value rows
    pr = np.asarray(out.prio)
    ct = np.asarray(cand.type)
    gprio = np.asarray(prio)
    L = cfg.num_nodes // 8
    for src in range(8):
        sent_p = np.sort(gprio[src * L:(src + 1) * L][
            ct[src * L:(src + 1) * L] != int(Msg.NONE)].ravel())[:4]
        got_p = np.sort(pr[np.asarray(out.valid)
                           & (np.arange(pr.size) % (8 * 4) // 4 == src)])
        np.testing.assert_array_equal(got_p, sent_p)


@needs_8
def test_rdma_router_bit_parity_with_all_to_all():
    """The Pallas remote-DMA ring router (parallel/rdma_comm, interpret
    mode on CPU — the CI correctness contract) must reproduce the
    all_to_all router's lanes bit-for-bit, lossless and truncating."""
    from ue22cs343bb1_openmp_assignment_tpu.parallel import rdma_comm
    cfg = SystemConfig.scale(num_nodes=64, queue_capacity=32)
    mesh = make_mesh(jax.devices()[:8])
    rng = np.random.default_rng(11)
    cand = random_candidates(cfg, rng)
    arb = jnp.asarray(rng.permutation(cfg.num_nodes), jnp.int32)
    prio = candidate_prio(cfg, arb)
    fields = pack_fields(cand)
    for cap in (None, 3):
        a = make_router(cfg, mesh, lane_cap=cap)(
            cand.type, cand.recv, prio, fields)
        b = rdma_comm.make_rdma_router(cfg, mesh, lane_cap=cap)(
            cand.type, cand.recv, prio, fields)
        for name, x, y in zip(a._fields, a, b):
            np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y),
                err_msg=f"lane_cap={cap} field={name}")


@needs_8
def test_routed_deliver_matches_unsharded_engine():
    """Both explicit transports, threaded into the async engine's
    phase-3 delivery (ops.step cycle deliver_fn), must leave every
    SimState leaf bit-identical to the unsharded reference run."""
    import dataclasses

    from ue22cs343bb1_openmp_assignment_tpu.models.system import (
        CoherenceSystem)
    from ue22cs343bb1_openmp_assignment_tpu.ops import step
    from ue22cs343bb1_openmp_assignment_tpu.parallel import (
        make_transport_runner, shard_state)
    cfg = SystemConfig.scale(num_nodes=64, queue_capacity=32)
    mesh = make_mesh(jax.devices()[:8])
    for transport in ("rdma", "all_to_all"):
        c2 = dataclasses.replace(cfg, transport=transport)
        sys_ = CoherenceSystem.from_workload(c2, "uniform", trace_len=8,
                                             seed=3)
        ref = jax.device_get(step.run_cycles(c2, sys_.state, 16))
        st = shard_state(c2, mesh, sys_.state)
        got = jax.device_get(
            make_transport_runner(c2, mesh, st, 16)(st))
        for i, (x, y) in enumerate(zip(jax.tree.leaves(ref),
                                       jax.tree.leaves(got))):
            np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y),
                err_msg=f"{transport} leaf {i}")


def test_wire_bytes_rdma_strictly_fewer():
    """The rdma wire format (validity via the receiver column's -1
    sentinel) must move strictly fewer bytes per round than the
    all_to_all format (separate valid plane) at any config."""
    from ue22cs343bb1_openmp_assignment_tpu.parallel import rdma_comm
    for nodes, shards in ((64, 8), (256, 8), (64, 2)):
        cfg = SystemConfig.scale(num_nodes=nodes)
        a = rdma_comm.wire_bytes(cfg, shards, transport="all_to_all")
        r = rdma_comm.wire_bytes(cfg, shards, transport="rdma")
        assert r < a, (nodes, shards, r, a)
    with pytest.raises(ValueError):
        rdma_comm.wire_bytes(SystemConfig.scale(num_nodes=64), 7)


def test_routed_deliver_requires_zero_drop_prob():
    """The fault plane draws one global bernoulli per message slot in
    delivery order; that order is irreproducible per-shard, so the
    routed transports refuse configs with drop_prob > 0."""
    import dataclasses

    from ue22cs343bb1_openmp_assignment_tpu.parallel import rdma_comm
    cfg = dataclasses.replace(SystemConfig.scale(num_nodes=64),
                              drop_prob=0.25)
    assert not rdma_comm.supported(cfg)
    mesh = make_mesh(jax.devices()[:1])
    with pytest.raises(ValueError, match="drop_prob"):
        rdma_comm.make_routed_deliver(cfg, mesh)
