"""Three-way differential: async JAX vs sync JAX vs native C++.

On schedule-independent workloads (every access node-local, SURVEY §4)
all legal schedules produce one final state, so the three engines must
agree bit-for-bit on caches, memory and directory — across random
workloads and dimensions. This is the strongest cross-implementation
check the framework has: three independently written engines, one
contract.
"""

import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.native.bindings import NativeEngine
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
from ue22cs343bb1_openmp_assignment_tpu.ops.step import run_to_quiescence
from ue22cs343bb1_openmp_assignment_tpu.state import init_state


def local_traces(rng, cfg, n_instrs):
    out = []
    for n in range(cfg.num_nodes):
        tr = []
        for _ in range(n_instrs):
            a = (n << cfg.block_bits) | int(rng.integers(cfg.mem_size))
            if rng.random() < 0.4:
                tr.append((0, a, 0))
            else:
                tr.append((1, a, int(rng.integers(256))))
        out.append(tr)
    return out


@pytest.mark.parametrize("seed,num_nodes,n_instrs", [
    (0, 4, 24), (1, 8, 32), (2, 6, 16), (3, 8, 24),
])
def test_three_engines_agree_on_local_traffic(seed, num_nodes, n_instrs):
    cfg = SystemConfig.reference(num_nodes=num_nodes)
    rng = np.random.default_rng(seed)
    traces = local_traces(rng, cfg, n_instrs)

    a = run_to_quiescence(cfg, init_state(cfg, traces), 50_000)
    assert bool(a.quiescent())

    s = se.run_sync_to_quiescence(
        cfg, se.from_sim_state(cfg, init_state(cfg, traces)), 8, 50_000)
    assert bool(s.quiescent())
    se.check_exact_directory(cfg, s)

    nat = NativeEngine(cfg)
    nat.load_traces(traces)
    nat.run(1_000_000)
    assert nat.quiescent
    n_st = nat.export_state()

    s_mem, s_ds, s_bv = se.to_sim_arrays(cfg, s)
    for name, av, sv, nv in [
        ("cache_addr", a.cache_addr, s.cache_addr, n_st["cache_addr"]),
        ("cache_val", a.cache_val, s.cache_val, n_st["cache_val"]),
        ("cache_state", a.cache_state, s.cache_state, n_st["cache_state"]),
        ("memory", a.memory, s_mem, n_st["memory"]),
        ("dir_state", a.dir_state, s_ds, n_st["dir_state"]),
        ("dir_bitvec", a.dir_bitvec, s_bv, n_st["dir_bitvec"]),
    ]:
        np.testing.assert_array_equal(np.asarray(av), np.asarray(sv),
                                      f"{name}: async vs sync")
        np.testing.assert_array_equal(np.asarray(av), np.asarray(nv),
                                      f"{name}: async vs native")


@pytest.mark.parametrize("seed,width", [(4, 2), (5, 4)])
def test_multi_txn_windows_agree_with_native(seed, width):
    """Multi-transaction windows vs the C++ oracle on schedule-
    independent (node-local) traffic: the composed windows must land
    the same final state as the message-level native engine."""
    cfg = SystemConfig.reference(num_nodes=8, txn_width=width)
    rng = np.random.default_rng(seed)
    traces = local_traces(rng, cfg, 30)

    s = se.run_sync_to_quiescence(
        cfg, se.from_sim_state(cfg, init_state(cfg, traces)), 8, 50_000)
    assert bool(s.quiescent())
    se.check_exact_directory(cfg, s)

    nat = NativeEngine(cfg)
    nat.load_traces(traces)
    nat.run(1_000_000)
    assert nat.quiescent
    n_st = nat.export_state()

    s_mem, s_ds, s_bv = se.to_sim_arrays(cfg, s)
    for name, sv, nv in [
        ("cache_addr", s.cache_addr, n_st["cache_addr"]),
        ("cache_val", s.cache_val, n_st["cache_val"]),
        ("cache_state", s.cache_state, n_st["cache_state"]),
        ("memory", s_mem, n_st["memory"]),
        ("dir_state", s_ds, n_st["dir_state"]),
        ("dir_bitvec", s_bv, n_st["dir_bitvec"]),
    ]:
        np.testing.assert_array_equal(np.asarray(sv), np.asarray(nv),
                                      f"{name}: sync(K={width}) vs native")
