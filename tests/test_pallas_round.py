"""Fused Pallas round kernel (ops/pallas_round) vs the XLA path.

Two layers of bit-parity, mirroring the module's design:

1. **Routed index ops** (fast tier): `RoutedIndexOps` is plain jnp, so
   its one-hot-matmul gathers/scatters and the chunked exponent
   scatter-min are pinned against `deep_engine.XlaIndexOps` on random
   data directly — including the 2**14-contender rounding margin the
   `supported` gate enforces — without paying a Pallas trace or an
   engine compile.
2. **Engine rounds** (slow tier): the full round through
   `deep_round_core` with routed ops, and through the fused kernel in
   interpret mode, must equal `round_step_deep` leaf-for-leaf on
   warmed machines (the tests/test_pallas_deep.py pattern: tiny
   machine on CPU, full size validated on a TPU backend).

The io-contract arithmetic (the perf-report comparison row) is pinned
against the recorded headline numbers: 64 rounds / 131072 retired at
deep@4096 put the fused kernel at 2480.00 bytes/instr vs the measured
191377.95 on the unfused path (PERF.md).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.obs import roofline
from ue22cs343bb1_openmp_assignment_tpu.ops import deep_engine as de
from ue22cs343bb1_openmp_assignment_tpu.ops import pallas_round as pr
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se


def _cfg(**kw):
    local = kw.pop("local", 200)
    cfg = SystemConfig.scale(num_nodes=8, drain_depth=2, txn_width=2)
    return dataclasses.replace(
        cfg, procedural="uniform", max_instrs=1, deep_window=True,
        deep_slots=4, deep_ownerval_slots=2,
        proc_local_permille=local, **kw)


def _assert_states_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------- fast:
# routed index ops vs the native ops on raw data


def test_routed_gather_scatter_match_native():
    """One-hot matmul routing is exact on int32 payloads — including
    negative values (owner -1 round-trips the 16-bit halves) and the
    one-past-the-end drop sentinel."""
    rng = np.random.default_rng(7)
    M, K, R = 96, 7, 64
    mat = jnp.asarray(
        rng.integers(-(2 ** 31), 2 ** 31, (M, K), dtype=np.int64)
        .astype(np.int32))
    idx = jnp.asarray(rng.integers(0, M, (4, R // 4), dtype=np.int64)
                      .astype(np.int32))
    nat, rt = de.XlaIndexOps(), pr.RoutedIndexOps(_cfg(), 3)
    np.testing.assert_array_equal(
        np.asarray(rt.gather_rows(mat, idx)),
        np.asarray(nat.gather_rows(mat, idx)))
    np.testing.assert_array_equal(
        np.asarray(rt.gather(mat[:, 0], idx)),
        np.asarray(nat.gather(mat[:, 0], idx)))
    # scatter: unique in-range indices + dropped sentinels
    perm = rng.permutation(M)[:R].astype(np.int32)
    sidx = jnp.asarray(np.where(rng.random(R) < 0.3, M, perm))
    rows = jnp.asarray(
        rng.integers(-(2 ** 31), 2 ** 31, (R, K), dtype=np.int64)
        .astype(np.int32))
    np.testing.assert_array_equal(
        np.asarray(rt.scatter_rows(mat, sidx, rows)),
        np.asarray(nat.scatter_rows(mat, sidx, rows)))
    np.testing.assert_array_equal(
        np.asarray(rt.scatter_col(mat, sidx, 2, rows[:, 2])),
        np.asarray(nat.scatter_col(mat, sidx, 2, rows[:, 2])))


def test_routed_scatter_min_exact_including_margin():
    """The chunked exponent scatter-min is exact at the supported
    cap: 2**14 contenders piled on single entries, adversarial chunk
    patterns (all-equal, one-below-the-crowd), and drop sentinels."""
    cfg = _cfg()
    round_ = 5
    ix = pr.RoutedIndexOps(cfg, round_)
    nat = de.XlaIndexOps()
    L, cd = ix._L, int(ix._cd)
    rng = np.random.default_rng(11)
    M, R = 128, 1 << 14
    dest = jnp.asarray(
        rng.integers((cd + 1) << L, 2 ** 30, M, dtype=np.int64)
        .astype(np.int32))
    low = rng.integers(0, 1 << L, R, dtype=np.int64).astype(np.int32)
    # adversarial rows: entry 0 takes ALL contenders of one chunk value
    # but one (the threshold-count boundary); entry 1 takes all-equal
    idx = rng.integers(0, M, R, dtype=np.int64).astype(np.int32)
    idx[: R // 2] = 0
    low[: R // 2] = (1 << L) - 1
    low[0] = 1
    idx[R // 2: 3 * R // 4] = 1
    low[R // 2: 3 * R // 4] = (1 << L) // 2
    idx[-8:] = M          # dropped
    vals = jnp.asarray((cd << L) | low)
    idx = jnp.asarray(idx)
    np.testing.assert_array_equal(
        np.asarray(ix.scatter_min(dest, idx, vals)),
        np.asarray(nat.scatter_min(dest, idx, vals)))
    # the wave variant: INT_MAX-filled destination
    full = jnp.full((M,), 2 ** 31 - 1, jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(ix.scatter_min(full, idx, vals)),
        np.asarray(nat.scatter_min(full, idx, vals)))


def test_supported_gate():
    cfg = _cfg()
    assert pr.supported(cfg)
    assert not pr.supported(
        dataclasses.replace(cfg, deep_read_storm=True))
    assert not pr.supported(dataclasses.replace(cfg, deep_window=False))
    # the scatter-min rounding margin is analyzer-derived
    # (analysis/kernelcheck): per-entry contenders N * (slots if
    # waves > 1 else 1) must stay under the certified cap 2**14.  At
    # waves=1 the window dup-stop admits at most one same-entry event
    # per node, so 8192 nodes with 3 slots is 8192 contenders — ADMITTED
    # now (the legacy slots*nodes < 2**14 product bound rejected it)
    big = SystemConfig.scale(num_nodes=8192, drain_depth=2,
                             txn_width=2)
    big = dataclasses.replace(big, deep_window=True, deep_slots=3)
    assert pr.supported(big)
    # multi-wave multiplies contenders by slots: 8192*3 over the cap
    assert not pr.supported(dataclasses.replace(big, deep_waves=2))
    # N alone at the cap boundary: 16384 contenders == 2**14 rejected
    huge = SystemConfig.scale(num_nodes=16384, drain_depth=2,
                              txn_width=2)
    assert not pr.supported(
        dataclasses.replace(huge, deep_window=True, deep_slots=2))


def test_io_contract_bytes_pinned_headline():
    """The fused kernel's per-round HBM contract at the perf-report
    deep@4096 config, against the recorded unfused measurement
    (PERF.md: 64 rounds retire 131072; bytes/instr 191377.95)."""
    cfg = SystemConfig.scale(num_nodes=4096, drain_depth=13,
                             txn_width=3)
    cfg = dataclasses.replace(cfg, deep_window=True, deep_slots=3,
                              deep_ownerval_slots=1)
    assert pr.supported(cfg)
    io_in, io_out = pr.io_contract_bytes(cfg)
    assert io_in + io_out == 5_079_040        # ~4.8 MB/round
    fused_bpi = (io_in + io_out) * 64 / 131072
    assert fused_bpi == 2480.0
    assert fused_bpi < 191377.95              # the ISSUE 8 gate


def test_io_contract_report_row_and_render():
    """io-contract records ride build_report as ordinary kernel rows
    (labeled by basis), and the fused comparison section renders."""
    per = {"name": "sync.round_step", "flops": 4e8, "hbm_bytes": 4e8,
           "output_bytes": 1e8, "cost_available": True,
           "hlo_fingerprint": "ab"}
    fused = roofline.io_contract_record("deep.round_fused[io-contract]",
                                        2_867_200, 2_211_840)
    assert fused["basis"] == "io-contract"
    doc = roofline.build_report(
        "deep", {"nodes": 4096}, [per, fused], "sync.round_step",
        64, 131072, device_kind="cpu")
    row = next(k for k in doc["kernels"]
               if k.get("basis") == "io-contract")
    assert row["cost_available"] and row["hbm_bytes"] == 5_079_040
    doc["fused"] = {"kernel": row["name"], "basis": "io-contract",
                    "bytes_per_instr": 2480.0,
                    "unfused_bytes_per_instr": doc["bytes_per_instr"]}
    text = roofline.render_text(doc)
    assert "io-contract" in text and "2480.00" in text


# ---------------------------------------------------------------- slow:
# full engine rounds (CPU interpreter; tiny machine, the
# tests/test_pallas_deep.py pattern)


@pytest.mark.slow  # >60 s single-CPU (deep compile + eager routing)
def test_routed_round_bit_identical_mid_run():
    """round_step_deep with RoutedIndexOps injected — the fused
    kernel's routing math through the REAL shared middle — equals the
    native path leaf-for-leaf on a warmed, contended machine."""
    cfg = _cfg()
    st = se.procedural_state(cfg, 200, seed=1)
    st = se.run_rounds(cfg, st, 30)
    for _ in range(2):
        a = de.round_step_deep(cfg, st)
        b = de.round_step_deep(
            cfg, st, index_ops=pr.RoutedIndexOps(cfg, st.round))
        _assert_states_equal(a, b)
        st = a


@pytest.mark.slow  # >60 s single-CPU
def test_routed_round_bit_identical_waves():
    """Absorption waves route extra scatter-min/gather pairs per wave
    through the strategy; parity must hold there too."""
    cfg = dataclasses.replace(_cfg(), deep_waves=3)
    st = se.procedural_state(cfg, 200, seed=9)
    st = se.run_rounds(cfg, st, 30)
    a = de.round_step_deep(cfg, st)
    b = de.round_step_deep(cfg, st,
                           index_ops=pr.RoutedIndexOps(cfg, st.round))
    _assert_states_equal(a, b)


@pytest.mark.slow  # >120 s single-CPU (whole-round kernel, interpreter)
def test_fused_round_bit_identical_mid_run():
    """The tentpole contract: the single fused kernel (interpret mode
    on CPU) reproduces round_step_deep bit-for-bit, and round_step
    dispatches to it under cfg.fused_round."""
    cfg = _cfg()
    fcfg = dataclasses.replace(cfg, fused_round=True)
    st = se.procedural_state(cfg, 200, seed=1)
    st = se.run_rounds(cfg, st, 30)
    a = de.round_step_deep(cfg, st)
    b = pr.round_step_deep_fused(cfg, st)
    c = se.round_step(fcfg, st)
    _assert_states_equal(a, b)
    _assert_states_equal(a, c)
    se.check_exact_directory(cfg, b)


@pytest.mark.slow  # >90 s single-CPU
def test_fused_round_stored_trace():
    """Stored-trace windows (the non-procedural gather build) feed the
    same kernel — the window is built in XLA either way."""
    from ue22cs343bb1_openmp_assignment_tpu.models.system import (
        CoherenceSystem)
    cfg = dataclasses.replace(_cfg(), procedural=None, max_instrs=64)
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=48,
                                         seed=3, local_frac=0.3)
    st = se.from_sim_state(cfg, sys_.state, seed=1)
    st = se.run_rounds(cfg, st, 6)
    a = de.round_step_deep(cfg, st)
    b = pr.round_step_deep_fused(cfg, st)
    _assert_states_equal(a, b)


@pytest.mark.slow  # >120 s single-CPU (two protocol variants)
@pytest.mark.parametrize("protocol", ["moesi", "mesif"])
def test_fused_round_protocol_variants(protocol):
    """Protocol-variant configs (MOESI/MESIF state ranges) run the
    fused kernel bit-identically — cold start, so first fills and
    promotions happen under the variant config."""
    cfg = dataclasses.replace(_cfg(local=500), protocol=protocol)
    st = se.procedural_state(cfg, 64, seed=4)
    st = se.run_rounds(cfg, st, 4)
    a = de.round_step_deep(cfg, st)
    b = pr.round_step_deep_fused(cfg, st)
    _assert_states_equal(a, b)


@pytest.mark.slow  # >120 s single-CPU (8-device dryrun + interpreter)
def test_fused_round_matches_sharded_reference():
    """Sharded 8-device dryrun parity: the single-device fused round
    equals the 8-way sharded XLA deep round (conftest forces
    xla_force_host_platform_device_count=8)."""
    from ue22cs343bb1_openmp_assignment_tpu.parallel import (
        make_mesh, make_sharded_round, shard_state)
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU dryrun")
    cfg = _cfg(local=500)
    st = se.procedural_state(cfg, 64, seed=2)
    st = se.run_rounds(cfg, st, 6)
    mesh = make_mesh(jax.devices()[:8])
    sharded = shard_state(cfg, mesh, st)
    ref = make_sharded_round(cfg, mesh, sharded)(sharded)
    out = pr.round_step_deep_fused(cfg, st)
    _assert_states_equal(jax.device_get(ref), jax.device_get(out))
