"""Coherence profiler (ISSUE 20 tentpole): per-line miss taxonomy on a
hand-built trace, schema validate/reject matrix, profile-plane-off
bit-parity with all three engines, the workload fingerprint matrix,
flight-incident embedding, and the measured deep-engine ghost-poison
window.

The hand trace is the profiler's ground truth: two nodes, six
instructions, every miss class exercised exactly once or twice by
construction (serialized via issue_delay so the interleaving is
pinned) — see test_hand_trace_miss_taxonomy for the script.
"""

import copy
import dataclasses
import json
import os

import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu import cli
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models.system import (
    CoherenceSystem)
from ue22cs343bb1_openmp_assignment_tpu.models.transactional import (
    TransactionalSystem)
from ue22cs343bb1_openmp_assignment_tpu.obs import cohprof, schema
from ue22cs343bb1_openmp_assignment_tpu.ops import step
from ue22cs343bb1_openmp_assignment_tpu.types import Op

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")

# the hand-built ground-truth trace: addresses A (home 0, block 0) and
# B (home 1, block 0) share cache index 0 (block % cache_size), so
# node 0's read of B evicts its modified A copy.
#
#   t=0   n0  W A   cold write miss, A -> M at node 0
#   t=20  n0  R B   cold read miss; evicts A (dirty writeback)
#   t=40  n0  R A   CONFLICT-EVICTION miss (tag B at A's index)
#   t=50  n1  R A   cold read miss, A shared
#   t=60  n0  W A   UPGRADE (S write hit) -> INV to node 1, fan-out 1
#   t=80  n1  R A   COHERENCE-INVALIDATION miss (tag match, INVALID)
A_ADDR, B_ADDR = 0x00, 0x10
HAND_TRACES = [
    [(Op.WRITE, A_ADDR, 11), (Op.READ, B_ADDR, 0),
     (Op.READ, A_ADDR, 0), (Op.WRITE, A_ADDR, 22)],
    [(Op.READ, A_ADDR, 0), (Op.READ, A_ADDR, 0)],
]


def _hand_system(cfg):
    return CoherenceSystem.from_traces(
        cfg, HAND_TRACES,
        issue_delay=np.array([0, 50], np.int32),
        issue_period=np.array([20, 30], np.int32))


@pytest.mark.parametrize("cfg", [
    SystemConfig.reference(num_nodes=2),   # mailbox INV attribution
    SystemConfig.scale(num_nodes=2),       # scatter INV attribution
], ids=["mailbox", "scatter"])
def test_hand_trace_miss_taxonomy(cfg):
    sysm = _hand_system(cfg)
    fin = sysm.run(400)
    assert fin.quiescent
    cycles = int(fin.state.cycle)
    _, prof = step.run_cycles_profile(cfg, sysm.state, cycles)

    # miss classes per node: (cold, conflict, coherence-inv, upgrade)
    mn = np.asarray(prof["miss_node"])
    np.testing.assert_array_equal(mn, [[2, 1, 0, 1], [1, 0, 1, 0]])
    # the same classes land on the address plane, at A and B only
    ma = np.asarray(prof["miss_addr"])
    assert ma[A_ADDR].tolist() == [2, 1, 1, 1]
    assert ma[B_ADDR].tolist() == [1, 0, 0, 0]
    assert int(ma.sum()) == int(mn.sum())

    # exactly one invalidation, at A, with fan-out 1 (bucket [1,2))
    inv = np.asarray(prof["inv_addr"])
    assert int(inv.sum()) == 1 and int(inv[A_ADDR]) == 1
    fan = np.asarray(prof["inv_fanout"])
    assert int(fan[1]) == 1 and int(fan.sum()) == 1
    # 3 dirty writebacks (eviction flush + two reads of an M line);
    # node 0 is the only writer, so no ownership migration
    assert int(np.asarray(prof["wb_addr"]).sum()) == 3
    assert int(np.asarray(prof["mig_addr"]).sum()) == 0

    # profile totals reconcile with the engine's own metrics
    m = fin.metrics
    misses = int(np.sum(m["read_misses"])) + int(np.sum(m["write_misses"]))
    assert int(mn[:, :3].sum()) == misses == 5
    assert int(mn[:, 3].sum()) == int(np.sum(m["upgrades"])) == 1
    assert int(inv.sum()) == int(np.sum(m["invalidations"]))
    rd, wr = np.asarray(prof["rd"]), np.asarray(prof["wr"])
    assert int(rd.sum() + wr.sum()) == int(np.sum(m["instrs_retired"]))


def test_hand_trace_doc_and_classifier():
    cfg = SystemConfig.reference(num_nodes=2)
    sysm = _hand_system(cfg)
    cycles = int(sysm.run(400).state.cycle)
    doc = cohprof.capture_async(cfg, sysm.state, cycles)
    assert doc["miss_classes"] == {
        "cold": 3, "conflict_eviction": 1,
        "coherence_invalidation": 1, "upgrade": 1}
    assert doc["invalidations"]["applied"] == 1
    assert doc["invalidations"]["fanout_hist"]["counts"][1] == 1
    assert doc["writebacks"] == 3 and doc["ownership_migrations"] == 0
    # A: node 0 reads+writes, node 1 reads -> migratory RMW sharing;
    # B: node 0 only -> private.  (Untouched lines stay -1.)
    pat = cohprof.classify(np.zeros((2, 32)), np.zeros((2, 32)))
    assert pat.shape == (32,) and (pat == -1).all()
    top = doc["top_contended"]
    assert top[0]["addr"] == A_ADDR
    assert top[0]["pattern"] == "migratory"
    assert top[0]["writers"] == 1 and top[0]["readers"] == 2
    assert doc["sharing"]["by_pattern"]["private"]["lines"] == 1
    # byte-determinism of the emitted doc
    doc2 = cohprof.capture_async(cfg, sysm.state, cycles)
    assert json.dumps(doc, sort_keys=True) == \
        json.dumps(doc2, sort_keys=True)


def _assert_states_equal(plain, prof_st, tag):
    import jax
    a = jax.tree_util.tree_leaves_with_path(plain)
    b = jax.tree_util.tree_leaves(prof_st)
    assert len(a) == len(b)
    for (path, la), lb in zip(a, b):
        np.testing.assert_array_equal(
            np.asarray(la), np.asarray(lb),
            err_msg=f"{tag}{jax.tree_util.keystr(path)}")


def test_profile_plane_off_bit_parity_async():
    """run_cycles_profile must walk the exact trajectory of
    run_cycles — the profile plane reads, never steers."""
    cfg = SystemConfig.scale(8)
    sysm = CoherenceSystem.from_workload(cfg, "false_sharing_vars",
                                         trace_len=32, seed=7)
    plain = step.run_cycles(cfg, sysm.state, 64)
    prof_st, _ = step.run_cycles_profile(cfg, sysm.state, 64)
    _assert_states_equal(plain, prof_st, "async")


def test_profile_plane_off_bit_parity_sync_and_deep():
    from ue22cs343bb1_openmp_assignment_tpu.ops import (deep_engine,
                                                        sync_engine)
    base = SystemConfig.scale(8, drain_depth=13, txn_width=3)
    deep_cfg = dataclasses.replace(
        base, deep_window=True, deep_slots=3, deep_ownerval_slots=1,
        deep_horizon_slack=4, deep_waves=1, deep_read_storm=False,
        deep_exact_flags=True)
    for cfg, runner in ((base, sync_engine.run_sync_profile),
                        (deep_cfg, deep_engine.run_deep_profile)):
        ts = TransactionalSystem.from_workload(
            cfg, "false_sharing_vars", trace_len=32, workload_seed=7)
        plain = sync_engine.run_rounds(cfg, ts.state, 24)
        prof_st = runner(cfg, ts.state, 24)[0]
        _assert_states_equal(plain, prof_st,
                             "deep" if cfg.deep_window else "sync")


def _valid_doc():
    cfg = SystemConfig.reference(num_nodes=2)
    sysm = _hand_system(cfg)
    cycles = int(sysm.run(400).state.cycle)
    return cohprof.capture_async(cfg, sysm.state, cycles)


def test_schema_validate_reject_matrix():
    doc = _valid_doc()
    cohprof.validate(doc)                       # the positive control

    def reject(mutate, msg_part):
        bad = copy.deepcopy(doc)
        mutate(bad)
        with pytest.raises(ValueError, match=msg_part):
            cohprof.validate(bad)

    reject(lambda d: d.update(schema="cache-sim/profile/v0"), "schema")
    reject(lambda d: d.pop("sharing"), "missing key")
    reject(lambda d: d.update(bogus=1), "unknown key")
    reject(lambda d: d.update(engine="turbo"), "engine")
    reject(lambda d: d.update(steps=-1), "steps")
    reject(lambda d: d["accesses"].update(reads=-2), "accesses")
    reject(lambda d: d["miss_classes"].pop("cold"), "miss_classes")
    reject(lambda d: d["miss_classes"].update(upgrade=True),
           "miss_classes")
    reject(lambda d: d["invalidations"]["fanout_hist"]["bucket_lo"]
           .reverse(), "fanout_hist")
    reject(lambda d: d["sharing"].update(dominant="gregarious"),
           "dominant")
    reject(lambda d: d["sharing"]["by_pattern"].pop("private"),
           "by_pattern")
    reject(lambda d: d["top_contended"][0].pop("score"),
           "top_contended")
    reject(lambda d: d.update(extra=None), "extra")
    # abort-anatomy arm (deep docs)
    deep = copy.deepcopy(doc)
    deep["abort_anatomy"] = {
        "rounds": 4, "retired": 10,
        "aborts": {k: 0 for k in cohprof.ABORT_CLASSES},
        "window_stops": {k: 0 for k in cohprof.STOP_CLASSES},
        "poison_flags": {"raised": 0, "committed": 0,
                         "ghost_fraction": None},
        "aborts_per_node_round": {k: 0.0
                                  for k in cohprof.ABORT_CLASSES}}
    cohprof.validate(deep)
    bad = copy.deepcopy(deep)
    bad["abort_anatomy"]["poison_flags"]["ghost_fraction"] = 0.5
    with pytest.raises(ValueError, match="ghost_fraction"):
        cohprof.validate(bad)                   # raised=0 forbids it
    bad = copy.deepcopy(deep)
    bad["abort_anatomy"]["aborts"]["poison_ghost"] = -1
    with pytest.raises(ValueError, match="aborts"):
        cohprof.validate(bad)


def test_daemon_stats_profile_validates_when_present():
    base = {
        "schema": schema.DAEMON_STATS_SCHEMA_ID, "clock": "virtual",
        "uptime_s": 1.0, "draining": False,
        "jobs": {"submitted": 1, "rejected": 0, "done": 1,
                 "quiesced": 1},
        "lanes": {"interactive": {"weight": 1, "depth": 4, "queued": 0,
                                  "submitted": 1, "admitted": 1,
                                  "rejected": 0, "done": 1,
                                  "latency": None}},
        "buckets": [], "chunks": 0, "busy_s": 0.0,
        "drain_rate_jobs_per_s": None, "mb_dropped": 0,
        "mid_wave_swaps": 0, "bucket_growths": 0,
        "queue_depth_peak": 0, "retain_results": 64,
        "results_evicted": 0, "recording": None,
        "padding_waste": None, "single_shape_padding_waste": None,
    }
    schema.validate_daemon_stats(dict(base))
    ok = dict(base, profile=_valid_doc())
    schema.validate_daemon_stats(ok)            # validate-when-present
    bad = dict(base, profile={"schema": "nope"})
    with pytest.raises(ValueError, match="profile"):
        schema.validate_daemon_stats(bad)


WL_EXPECT = {
    # the workload fingerprint matrix (ISSUE 20 satellite): every
    # builtin generator pinned to its dominant sharing pattern at
    # scale(16)/trace_len 32/seed 0.  false_sharing (all nodes
    # read+write node 0's two blocks) is TRUE migratory sharing;
    # false_sharing_vars is the block-vs-variable-granularity shape
    # the classifier exists to catch; _padded is its fix, and must
    # classify private — the padding proven observable.
    "uniform": "private",
    "false_sharing": "migratory",
    "false_sharing_vars": "false_sharing",
    "false_sharing_vars_padded": "private",
    "producer_consumer": "producer_consumer",
    "hotspot": "private",
    "zipf_hotspot": "migratory",
}


@pytest.mark.parametrize("wl,expect", sorted(WL_EXPECT.items()))
def test_workload_fingerprints(wl, expect):
    cfg = SystemConfig.scale(16)
    sysm = CoherenceSystem.from_workload(cfg, wl, trace_len=32, seed=0)
    steps = int(sysm.run(20000).metrics["cycles"])
    doc = cohprof.capture_async(cfg, sysm.state, steps)
    assert doc["sharing"]["dominant"] == expect, doc["sharing"]


def test_flight_incident_embeds_profile(tmp_path):
    from ue22cs343bb1_openmp_assignment_tpu.obs import flight
    cfg = SystemConfig.reference(num_nodes=2)
    rec = flight.FlightRecorder(cfg, _hand_system(cfg).state, k=16,
                                chunk=8)
    rec.run(200)
    doc = rec.dump_incident(str(tmp_path / "inc"), "test:profile")
    assert doc["profile"] is not None
    cohprof.validate(doc["profile"])
    assert doc["profile"]["steps"] == doc["cycles_run"]
    assert doc["profile"]["miss_classes"]["cold"] == 3
    # round-trip: load_incident re-validates the embedded profile
    loaded = flight.load_incident(str(tmp_path / "inc"))
    assert loaded["profile"] == doc["profile"]
    bad = dict(loaded, profile={"schema": "nope"})
    with open(tmp_path / "inc" / "incident.json", "w") as f:
        json.dump(bad, f)
    with pytest.raises(ValueError):
        flight.load_incident(str(tmp_path / "inc"))


def run_cli(args, tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    rc = cli.main(args)
    out, err = capsys.readouterr()
    return rc, out, err


def test_cli_profile_smoke(tmp_path, monkeypatch, capsys):
    rc, out, _ = run_cli(
        ["profile", "mini", "--tests-root", FIXTURES, "--cpu"],
        tmp_path, monkeypatch, capsys)
    assert rc == 0
    assert "coherence profile [async]" in out
    rc, out, _ = run_cli(
        ["profile", "--workload", "false_sharing_vars", "--nodes", "8",
         "--trace-len", "32", "--cpu", "--json"],
        tmp_path, monkeypatch, capsys)
    assert rc == 0
    doc = cohprof.validate(json.loads(out))
    assert doc["sharing"]["dominant"] == "false_sharing"
    rc, out, _ = run_cli(
        ["profile", "--workload", "uniform", "--nodes", "4",
         "--trace-len", "8", "--engine", "sync", "--cpu", "--json"],
        tmp_path, monkeypatch, capsys)
    assert rc == 0
    doc = cohprof.validate(json.loads(out))
    assert doc["engine"] == "sync" and doc["miss_classes"] is None
    # error paths
    rc, _, err = run_cli(["profile", "--cpu"],
                         tmp_path, monkeypatch, capsys)
    assert rc == 2 and "workload" in err
    rc, _, err = run_cli(
        ["profile", "--workload", "uniform", "--no-exact-flags",
         "--cpu"], tmp_path, monkeypatch, capsys)
    assert rc == 2 and "deep" in err


def test_cli_profile_deep_smoke(tmp_path, monkeypatch, capsys):
    rc, out, _ = run_cli(
        ["profile", "--workload", "false_sharing", "--nodes", "8",
         "--trace-len", "16", "--engine", "deep", "--cpu", "--json"],
        tmp_path, monkeypatch, capsys)
    assert rc == 0
    doc = cohprof.validate(json.loads(out))
    assert doc["engine"] == "deep"
    ab = doc["abort_anatomy"]
    assert ab is not None and ab["retired"] > 0
    assert set(ab["aborts"]) == set(cohprof.ABORT_CLASSES)


def _ghost_cfg(num_nodes, exact):
    cfg = SystemConfig.scale(num_nodes, drain_depth=13, txn_width=3)
    return dataclasses.replace(
        cfg, proc_local_permille=800, deep_window=True, deep_slots=6,
        deep_ownerval_slots=3, deep_horizon_slack=8, deep_waves=1,
        deep_read_storm=False, deep_exact_flags=exact,
        procedural="uniform", max_instrs=1)


def test_deep_ghost_poison_fraction_window():
    """The measured replacement for PERF.md round-4's hand estimate
    ('roughly 2/3 of poison flags are GHOSTS'): at the anatomy config
    shrunk to N=64, the attempt-based flag pass must raise poison on
    entries whose attempts never commit at a fraction inside the
    pinned window.  Measured 0.6470 (N=64), 0.6614 (N=256, the PERF.md
    config — see the slow tier)."""
    from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
    cfg = _ghost_cfg(64, exact=False)
    st = se.run_rounds(cfg, se.procedural_state(cfg, 256, seed=0), 12)
    doc = cohprof.capture_deep(cfg, st, 6)
    pf = doc["abort_anatomy"]["poison_flags"]
    assert pf["raised"] > 1000, pf
    assert 0.55 <= pf["ghost_fraction"] <= 0.72, pf


@pytest.mark.slow
def test_deep_ghost_poison_exact_flags_reduction():
    """At the PERF.md anatomy config (N=256 W=16 Q=6 slack=8
    local=0.8): attempt-based flags sit in the measured 2/3-ghost
    window, and cfg.deep_exact_flags cuts ghost-poison ABORTS by >2x
    (measured 0.267 -> 0.065 per node per round)."""
    from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se

    def anatomy(exact):
        cfg = _ghost_cfg(256, exact)
        st = se.run_rounds(cfg, se.procedural_state(cfg, 2048, seed=0),
                           40)
        return cohprof.capture_deep(cfg, st, 8)["abort_anatomy"]

    loose, sharp = anatomy(False), anatomy(True)
    assert 0.60 <= loose["poison_flags"]["ghost_fraction"] <= 0.72
    ratio = (loose["aborts_per_node_round"]["poison_ghost"]
             / max(sharp["aborts_per_node_round"]["poison_ghost"],
                   1e-9))
    assert ratio > 2.0, (loose, sharp)
