"""Test environment: force CPU with 8 virtual devices (sharding tests).

Must run before the first `import jax` anywhere in the test session.
Real-TPU behavior is exercised by bench.py / the driver, not by pytest.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon TPU plugin ignores JAX_PLATFORMS; force CPU explicitly so the
# suite is hermetic and the 8-device virtual mesh is available.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the deep-round XLA graphs take ~1-2 min
# EACH to compile on this single-core host, and the suite compiles
# dozens of jit variants — without a cache every pytest invocation pays
# the full compile bill again (~an hour). Cached entries key on the
# exact HLO, so code changes recompile exactly what they touched.
jax.config.update("jax_compilation_cache_dir",
                  os.path.expanduser("~/.cache/jax_pytest_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import pytest  # noqa: E402

REFERENCE_TESTS = "/root/reference/tests"


def reference_available() -> bool:
    return os.path.isdir(REFERENCE_TESTS)


requires_reference = pytest.mark.skipif(
    not reference_available(),
    reason="reference fixture tree not mounted at /root/reference/tests")


# Parametrized cases that individually cost >20 s on the single-core CI
# box (measured via --durations=0; see PERF.md). Whole tests that are
# uniformly slow carry @pytest.mark.slow at their definition; the entries
# here are the heavy OUTLIER params of otherwise-fast parametrized tests,
# so the fast params keep covering the differential gates in tier-1
# while the 870 s budget holds. The full suite (no -m filter) still runs
# everything.
_SLOW_PARAM_IDS = {
    # the home_chain scenarios' deep enumeration is memoized per
    # process, so whichever param runs first pays the whole ~30-40 s
    # warm-up: marking a subset just moves the cost to a sibling.
    # All four params of each scenario live here (the same treatment
    # the outcome-inclusion home_chain params got); evict_race /
    # migrate3 / storm_* keep the native-enumeration gate in tier-1.
    "tests/test_native_enumeration.py::"
    "test_deep_outcomes_within_native_enumeration[storm_home_chain-1-False]",
    "tests/test_native_enumeration.py::"
    "test_deep_outcomes_within_native_enumeration[storm_home_chain-3-False]",
    "tests/test_native_enumeration.py::"
    "test_deep_outcomes_within_native_enumeration[storm_home_chain-1-True]",
    "tests/test_native_enumeration.py::"
    "test_deep_outcomes_within_native_enumeration[storm_home_chain-2-True]",
    "tests/test_native_enumeration.py::"
    "test_deep_outcomes_within_native_enumeration[wave_home_chain-1-False]",
    "tests/test_native_enumeration.py::"
    "test_deep_outcomes_within_native_enumeration[wave_home_chain-3-False]",
    "tests/test_native_enumeration.py::"
    "test_deep_outcomes_within_native_enumeration[wave_home_chain-1-True]",
    "tests/test_native_enumeration.py::"
    "test_deep_outcomes_within_native_enumeration[wave_home_chain-2-True]",
    "tests/test_outcome_inclusion.py::"
    "test_multi_txn_window_outcomes_are_reachable[migrate3]",
    "tests/test_outcome_inclusion.py::"
    "test_deep_wave_outcomes_are_reachable[wave_home_chain-1]",
    "tests/test_outcome_inclusion.py::"
    "test_deep_wave_outcomes_are_reachable[wave_home_chain-3]",
    "tests/test_outcome_inclusion.py::"
    "test_deep_read_storm_outcomes_are_reachable[storm_home_chain-1]",
    "tests/test_outcome_inclusion.py::"
    "test_deep_read_storm_outcomes_are_reachable[storm_home_chain-2]",
    "tests/test_bench_contract.py::"
    "test_single_json_line_on_stdout[args0]",
    "tests/test_bench_contract.py::"
    "test_single_json_line_on_stdout[args3]",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.nodeid in _SLOW_PARAM_IDS:
            item.add_marker(pytest.mark.slow)
