"""Test environment: force CPU with 8 virtual devices (sharding tests).

Must run before the first `import jax` anywhere in the test session.
Real-TPU behavior is exercised by bench.py / the driver, not by pytest.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon TPU plugin ignores JAX_PLATFORMS; force CPU explicitly so the
# suite is hermetic and the 8-device virtual mesh is available.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the deep-round XLA graphs take ~1-2 min
# EACH to compile on this single-core host, and the suite compiles
# dozens of jit variants — without a cache every pytest invocation pays
# the full compile bill again (~an hour). Cached entries key on the
# exact HLO, so code changes recompile exactly what they touched.
jax.config.update("jax_compilation_cache_dir",
                  os.path.expanduser("~/.cache/jax_pytest_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import pytest  # noqa: E402

REFERENCE_TESTS = "/root/reference/tests"


def reference_available() -> bool:
    return os.path.isdir(REFERENCE_TESTS)


requires_reference = pytest.mark.skipif(
    not reference_available(),
    reason="reference fixture tree not mounted at /root/reference/tests")
