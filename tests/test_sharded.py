"""Multi-device sharded execution on the 8-device virtual CPU mesh.

Validates that the node axis shards over a Mesh, that the sharded cycle
produces bit-identical results to the single-device engine, and that
cross-shard message delivery (a message whose receiver lives on another
device) works — the distributed-communication-backend contract.
"""

import jax
import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
from ue22cs343bb1_openmp_assignment_tpu.ops.step import run_cycles
from ue22cs343bb1_openmp_assignment_tpu.parallel import (make_mesh,
                                                         make_sharded_runner,
                                                         shard_state)
from ue22cs343bb1_openmp_assignment_tpu.state import init_state
from ue22cs343bb1_openmp_assignment_tpu.types import Op

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")

FIELDS = ("cache_addr", "cache_val", "cache_state", "memory", "dir_state",
          "dir_bitvec", "mb_count", "waiting", "instr_idx")


def test_sharded_matches_single_device():
    cfg = SystemConfig.scale(num_nodes=32, queue_capacity=8)
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=8, seed=3)

    single = run_cycles(cfg, sys_.state, 48)

    mesh = make_mesh(jax.devices()[:8])
    sharded_in = shard_state(cfg, mesh, sys_.state)
    run = make_sharded_runner(cfg, mesh, sharded_in, 48)
    sharded = run(sharded_in)

    for f in FIELDS:
        a, b = np.asarray(getattr(single, f)), np.asarray(getattr(sharded, f))
        assert np.array_equal(a, b), f"sharded run diverged on {f}"


def test_cross_shard_messages():
    """Node 0 (device 0) reads node 31's memory (device 7): the request,
    reply, and directory update all cross the mesh."""
    cfg = SystemConfig.scale(num_nodes=32, queue_capacity=8)
    traces = [[] for _ in range(32)]
    remote_addr = (31 << cfg.block_bits) | 3
    traces[0] = [(int(Op.READ), remote_addr, 0),
                 (int(Op.WRITE), remote_addr, 77)]
    state = init_state(cfg, traces)

    mesh = make_mesh(jax.devices()[:8])
    sharded = shard_state(cfg, mesh, state)
    run = make_sharded_runner(cfg, mesh, sharded, 32)
    out = run(sharded)

    assert bool(out.quiescent())
    # node 0 ends MODIFIED on the remote block; home dir says EM {0}
    line = 3 % cfg.cache_size
    assert int(out.cache_addr[0, line]) == remote_addr
    assert int(out.cache_val[0, line]) == 77
    assert int(out.dir_state[31, 3]) == 0  # EM
    assert int(out.dir_bitvec[31, 3, 0]) == 1


@pytest.mark.slow  # ~120 s single-CPU: compiles the full 8-chip mesh
def test_dryrun_multichip_entrypoint():
    import __graft_entry__
    __graft_entry__.dryrun_multichip(8)


def test_sharded_sync_round_bit_identical():
    """The transactional engine shards over the node mesh: per-node state
    and the node-major directory table partition; results are
    bit-identical to a single-device run."""
    import numpy as np
    from ue22cs343bb1_openmp_assignment_tpu.models.system import (
        CoherenceSystem)
    from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
    from ue22cs343bb1_openmp_assignment_tpu.parallel import (
        make_mesh, make_sharded_round, shard_state)

    cfg = SystemConfig.scale(num_nodes=64, max_instrs=16, drain_depth=4)
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=16,
                                         seed=3, local_frac=0.3)
    st = se.from_sim_state(cfg, sys_.state, seed=1)
    mesh = make_mesh(jax.devices()[:8])
    sharded = shard_state(cfg, mesh, st)
    round_fn = make_sharded_round(cfg, mesh, sharded)
    out = sharded
    for _ in range(12):
        out = round_fn(out)
    ref = se.run_rounds(cfg, st, 12)
    for a, b in zip(jax.tree_util.tree_leaves(out),
                    jax.tree_util.tree_leaves(ref)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    se.check_exact_directory(cfg, out)


def test_multihost_mesh_bit_identical():
    """A 2-D (hosts, nodes) mesh — DCN outer, ICI inner — folds the node
    axis over both axes; results match the single-device run for both
    engines."""
    import numpy as np
    from ue22cs343bb1_openmp_assignment_tpu.models.system import (
        CoherenceSystem)
    from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
    from ue22cs343bb1_openmp_assignment_tpu.ops.step import run_cycles
    from ue22cs343bb1_openmp_assignment_tpu.parallel import (
        make_multihost_mesh, make_sharded_round, make_sharded_runner,
        shard_state)

    cfg = SystemConfig.scale(num_nodes=32, max_instrs=8, drain_depth=4,
                             queue_capacity=16)
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=8,
                                         seed=5, local_frac=0.3)
    mesh = make_multihost_mesh(num_hosts=2, devices=jax.devices()[:8])
    assert mesh.devices.shape == (2, 4)

    sharded = shard_state(cfg, mesh, sys_.state)
    out = make_sharded_runner(cfg, mesh, sharded, 16)(sharded)
    ref = run_cycles(cfg, sys_.state, 16)
    np.testing.assert_array_equal(np.asarray(out.cache_val),
                                  np.asarray(ref.cache_val))

    st = se.from_sim_state(cfg, sys_.state)
    sh = shard_state(cfg, mesh, st)
    round_fn = make_sharded_round(cfg, mesh, sh)
    out2 = round_fn(round_fn(sh))
    ref2 = se.run_rounds(cfg, st, 2)
    np.testing.assert_array_equal(np.asarray(out2.dm), np.asarray(ref2.dm))


def test_sharded_round_runner_multi_txn_bit_identical():
    """The multi-round sharded runner (one dispatch, scan over rounds,
    read-only trace hoist) with txn_width>1 matches the single-device
    multi-transaction run bit for bit."""
    import numpy as np
    from ue22cs343bb1_openmp_assignment_tpu.models.system import (
        CoherenceSystem)
    from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
    from ue22cs343bb1_openmp_assignment_tpu.parallel import (
        make_mesh, make_sharded_round_runner, shard_state)

    cfg = SystemConfig.scale(num_nodes=64, max_instrs=16, drain_depth=4,
                             txn_width=3)
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=16,
                                         seed=5, local_frac=0.4)
    st = se.from_sim_state(cfg, sys_.state, seed=2)
    mesh = make_mesh(jax.devices()[:8])
    sharded = shard_state(cfg, mesh, st)
    run = make_sharded_round_runner(cfg, mesh, sharded, 12)
    out = run(sharded)
    ref = se.run_rounds(cfg, st, 12)
    for a, b in zip(jax.tree_util.tree_leaves(out),
                    jax.tree_util.tree_leaves(ref)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    se.check_exact_directory(cfg, out)


@pytest.mark.slow  # ~2 min single-CPU: two 2^20-node txn machines
def test_2d_mesh_million_node_txn_rung():
    """The >=1M-simulated-core rung (dryrun_multichip check 8): a
    1048576-node sync-txn machine sharded hosts x nodes over the
    2-D mesh runs 2 rounds bit-identical to the unsharded reference.
    The deep window stays off — it packs requester ids in 16 bits,
    capping deep machines at 65536 nodes (config.py) — and the O(N)
    procedural_state constructor avoids init_state's O(N^2) transient
    sharer bitvector (2 TB at this N). The ladder below this rung
    (32 / 64 / 65536) is covered by the fast multihost tests and the
    driver captures (MULTICHIP_r*.json)."""
    import dataclasses

    from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
    from ue22cs343bb1_openmp_assignment_tpu.parallel import (
        make_multihost_mesh, make_sharded_round_runner, shard_state)

    huge = dataclasses.replace(
        SystemConfig.scale(num_nodes=1048576, drain_depth=4,
                           txn_width=2),
        procedural="uniform", max_instrs=1)
    hst = se.procedural_state(huge, 8, seed=3)
    ref = se.run_rounds(huge, hst, 2)
    mesh2 = make_multihost_mesh(num_hosts=2, devices=jax.devices()[:8])
    sh = shard_state(huge, mesh2, hst)
    out = make_sharded_round_runner(huge, mesh2, sh, 2)(sh)
    jax.block_until_ready(out)
    for i, (x, y) in enumerate(zip(jax.tree.leaves(ref),
                                   jax.tree.leaves(out))):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=f"leaf {i}")


def test_transport_runner_single_device_falls_back():
    """On a 1-device mesh there is no cross-shard traffic: the
    transport runner must fall back to the plain delivery path and
    still match run_cycles bit-for-bit."""
    from ue22cs343bb1_openmp_assignment_tpu.parallel import (
        make_transport_runner, shard_state)
    cfg = SystemConfig.scale(num_nodes=16, queue_capacity=16)
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=4,
                                         seed=1)
    ref = run_cycles(cfg, sys_.state, 6)
    mesh = make_mesh(jax.devices()[:1])
    st = shard_state(cfg, mesh, sys_.state)
    out = make_transport_runner(cfg, mesh, st, 6)(st)
    for x, y in zip(jax.tree.leaves(ref), jax.tree.leaves(out)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
