"""Checkpoint/resume: bit-exact continuation (SURVEY §5 — the reference
has no persistence; its only artifact is the end-state dump,
``assignment.c:853-905``)."""

import jax
import numpy as np
import pytest

from tests.conftest import requires_reference

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
from ue22cs343bb1_openmp_assignment_tpu.utils import checkpoint


def _assert_states_equal(a, b):
    la = jax.tree_util.tree_leaves_with_path(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for (path, x), y in zip(la, lb):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=str(path))


@requires_reference
def test_roundtrip_identity(tmp_path):
    cfg = SystemConfig.reference()
    sys_ = CoherenceSystem.from_test_dir("/root/reference/tests/test_1", cfg)
    sys_ = sys_.run_cycles(7)
    p = str(tmp_path / "ckpt.npz")
    sys_.save(p, meta={"note": "mid-run"})
    cfg2, state2, meta = checkpoint.load_checkpoint(p)
    assert cfg2 == cfg
    assert meta["note"] == "mid-run"
    _assert_states_equal(sys_.state, state2)


@requires_reference
def test_resume_matches_uninterrupted_run(tmp_path):
    """run(k) → save → load → run-to-quiescence == straight run."""
    cfg = SystemConfig.reference()
    base = CoherenceSystem.from_test_dir("/root/reference/tests/test_2", cfg)

    straight = base.run()
    assert straight.quiescent

    p = str(tmp_path / "mid.npz")
    base.run_cycles(5).save(p)
    resumed = CoherenceSystem.load(p).run()
    assert resumed.quiescent

    _assert_states_equal(straight.state, resumed.state)
    assert straight.dumps() == resumed.dumps()


def test_resume_scale_config(tmp_path):
    """Checkpointing works for the scale path (scatter INV, >64 nodes)."""
    cfg = SystemConfig.scale(num_nodes=128, queue_capacity=16)
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=8, seed=3)
    mid = sys_.run_cycles(4)
    p = str(tmp_path / "scale.npz")
    mid.save(p)
    resumed = CoherenceSystem.load(p)
    assert resumed.cfg == mid.cfg  # from_workload rewrote max_instrs
    _assert_states_equal(mid.state, resumed.state)
    a = mid.run_cycles(6)
    b = resumed.run_cycles(6)
    _assert_states_equal(a.state, b.state)


def test_version_gate(tmp_path):
    cfg = SystemConfig.reference()
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=4)
    p = str(tmp_path / "v.npz")
    old = checkpoint.FORMAT_VERSION
    try:
        checkpoint.FORMAT_VERSION = old + 1
        sys_.save(p)
    finally:
        checkpoint.FORMAT_VERSION = old
    with pytest.raises(ValueError, match="format"):
        checkpoint.load_checkpoint(p)


def test_checkpoint_bytes_reports_payload():
    cfg = SystemConfig.reference()
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=4)
    n = checkpoint.checkpoint_bytes(sys_.state)
    # at least the mailbox payload alone: N*Q int32 x 6 fields
    assert n > cfg.num_nodes * cfg.queue_capacity * 4 * 6


@requires_reference
def test_cli_checkpoint_resume_roundtrip(tmp_path):
    """cache-sim test_1 --run-cycles 5 --save-checkpoint → --resume
    reproduces the straight run's golden dumps."""
    from ue22cs343bb1_openmp_assignment_tpu import cli

    straight_dir = tmp_path / "straight"
    resumed_dir = tmp_path / "resumed"
    straight_dir.mkdir()
    resumed_dir.mkdir()
    ck = str(tmp_path / "cli.npz")

    rc = cli.main(["test_1", "--tests-root", "/root/reference/tests",
                   "--out-dir", str(straight_dir)])
    assert rc == 0
    rc = cli.main(["test_1", "--tests-root", "/root/reference/tests",
                   "--run-cycles", "5", "--save-checkpoint", ck,
                   "--out-dir", str(tmp_path)])
    assert rc == 0
    rc = cli.main(["--resume", ck, "--dump", "--out-dir", str(resumed_dir)])
    assert rc == 0

    for n in range(4):
        f = f"core_{n}_output.txt"
        assert ((straight_dir / f).read_text()
                == (resumed_dir / f).read_text()), f


@requires_reference
def test_cli_resume_applies_schedule_knobs(tmp_path):
    """--arb-seed/--delays on --resume override the checkpointed knobs."""
    from ue22cs343bb1_openmp_assignment_tpu import cli
    from ue22cs343bb1_openmp_assignment_tpu.utils import checkpoint as ckpt

    ck = str(tmp_path / "k.npz")
    rc = cli.main(["test_1", "--tests-root", "/root/reference/tests",
                   "--run-cycles", "2", "--save-checkpoint", ck,
                   "--out-dir", str(tmp_path)])
    assert rc == 0
    ck2 = str(tmp_path / "k2.npz")
    rc = cli.main(["--resume", ck, "--arb-seed", "7",
                   "--delays", "3", "0", "0", "0",
                   "--run-cycles", "0", "--save-checkpoint", ck2,
                   "--out-dir", str(tmp_path)])
    assert rc == 0
    _, st, _ = ckpt.load_checkpoint(ck2)
    rnd = np.argsort(np.random.RandomState(7).rand(4)).astype(np.int32)
    np.testing.assert_array_equal(np.asarray(st.arb_rank), rnd)
    np.testing.assert_array_equal(np.asarray(st.issue_delay), [3, 0, 0, 0])
