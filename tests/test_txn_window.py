"""Multi-transaction windows (ops.sync_engine._round_step_multi).

With cfg.txn_width > 1 the transactional engine commits up to K
coherence transactions per node per round (pairwise-distinct directory
entries, program-order-prefix retirement). Every committed round must
remain a legal serialization of the same protocol, so the multi-txn
engine is held to the single-txn engine's own bar:

* byte-exact golden dumps on the deterministic reference suites,
* final-state identity with the single-txn engine on node-local traffic
  (schedule-independent, so any legal schedule lands the same state),
* the exact-directory invariant at quiescence on cross-node traffic,
* full retirement + metric accounting,
* procedural-stream equivalence with materialized traces.
"""

import dataclasses
import os

import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import REFERENCE_TESTS, requires_reference

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
from ue22cs343bb1_openmp_assignment_tpu.state import init_state
from ue22cs343bb1_openmp_assignment_tpu.utils.golden import (format_node_dump,
                                                             state_to_dumps)
from ue22cs343bb1_openmp_assignment_tpu.utils.trace import load_test_dir


def run_to_quiescence(cfg, st, chunk=8, max_rounds=50_000):
    final = se.run_sync_to_quiescence(cfg, st, chunk, max_rounds)
    assert bool(final.quiescent()), "did not quiesce"
    return final


@requires_reference
@pytest.mark.parametrize("suite", ["sample", "test_1", "test_2"])
@pytest.mark.parametrize("width", [2, 4])
def test_deterministic_suites_byte_exact(suite, width):
    cfg = SystemConfig.reference(txn_width=width)
    traces = load_test_dir(os.path.join(REFERENCE_TESTS, suite))
    final = run_to_quiescence(cfg, se.from_sim_state(cfg, init_state(cfg, traces)))
    dumps = [format_node_dump(d)
             for d in state_to_dumps(cfg, se.to_dump_view(cfg, final))]
    for n in range(4):
        golden = open(f"{REFERENCE_TESTS}/{suite}/core_{n}_output.txt").read()
        assert dumps[n] == golden, f"{suite} core_{n} diverged (K={width})"


def _final_tuple(cfg, st):
    mem, ds, bv = se.to_sim_arrays(cfg, st)
    return (mem, ds, bv, np.asarray(st.cache_addr),
            np.asarray(st.cache_val), np.asarray(st.cache_state))


def test_matches_single_on_local_traffic():
    """All-local traces are schedule-independent (SURVEY §4): any legal
    schedule — one transaction per round or eight — must land on
    identical cache/memory/directory state."""
    rng = np.random.default_rng(11)
    N, M = 8, 16
    traces = []
    for n in range(N):
        tr = []
        for _ in range(30):
            b = int(rng.integers(M))
            if rng.random() < 0.5:
                tr.append((0, n * M + b, 0))
            else:
                tr.append((1, n * M + b, int(rng.integers(256))))
        traces.append(tr)
    finals = []
    for width in (1, 8):
        cfg = SystemConfig.reference(num_nodes=N, txn_width=width)
        finals.append(_final_tuple(cfg, run_to_quiescence(
            cfg, se.from_sim_state(cfg, init_state(cfg, traces)))))
    for a, b in zip(*finals):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("width", [2, 8])
@pytest.mark.parametrize("workload,kw", [
    ("uniform", {"local_frac": 0.6}),
    ("producer_consumer", {}),
    ("false_sharing", {}),
    ("hotspot", {}),
])
def test_exact_directory_on_cross_node_traffic(width, workload, kw):
    """Cross-node races resolve differently per schedule, but the
    directory must stay exact and every trace must fully retire."""
    cfg = SystemConfig.scale(num_nodes=64, txn_width=width, drain_depth=4)
    sys_ = CoherenceSystem.from_workload(cfg, workload, trace_len=48,
                                         seed=3, **kw)
    final = run_to_quiescence(
        cfg, se.from_sim_state(cfg, sys_.state, seed=5))
    se.check_exact_directory(cfg, final)
    m = final.metrics
    assert int(m.instrs_retired) == int(jnp.sum(final.instr_count))
    retired_kinds = (int(m.read_hits) + int(m.write_hits)
                     + int(m.read_misses) + int(m.write_misses)
                     + int(m.upgrades))
    assert retired_kinds == int(m.instrs_retired)


def test_procedural_matches_materialized():
    """cfg.procedural computes the window inside the round; the
    materialized procedural_uniform trace must land the same state."""
    N, L = 64, 96
    cfg = SystemConfig.scale(num_nodes=N, txn_width=4, drain_depth=4)
    pcfg = dataclasses.replace(cfg, procedural="uniform", max_instrs=1,
                               proc_local_permille=700)
    p_final = run_to_quiescence(pcfg, se.procedural_state(pcfg, L, seed=2))
    mcfg = dataclasses.replace(cfg, proc_local_permille=700)
    sys_ = CoherenceSystem.from_workload(mcfg, "procedural_uniform",
                                         trace_len=L)
    m_final = run_to_quiescence(
        mcfg, se.from_sim_state(mcfg, sys_.state, seed=2))
    for a, b in zip(_final_tuple(pcfg, p_final), _final_tuple(mcfg, m_final)):
        np.testing.assert_array_equal(a, b)


def test_seed_determinism_and_schedule_variation():
    """Same seed -> bit-identical run; the arbitration seed remains a
    live schedule knob under multi-txn windows (contended workloads may
    land different — individually legal — final states)."""
    cfg = SystemConfig.scale(num_nodes=16, txn_width=4, drain_depth=4)
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=40,
                                         seed=9, local_frac=0.3)
    a = run_to_quiescence(cfg, se.from_sim_state(cfg, sys_.state, seed=1))
    b = run_to_quiescence(cfg, se.from_sim_state(cfg, sys_.state, seed=1))
    for x, y in zip(_final_tuple(cfg, a), _final_tuple(cfg, b)):
        np.testing.assert_array_equal(x, y)
    se.check_exact_directory(cfg, a)


def test_wider_windows_take_fewer_rounds():
    """The point of the feature: K transactions per round means fewer
    rounds for the same miss-heavy trace."""
    rounds = {}
    for width in (1, 8):
        cfg = SystemConfig.scale(num_nodes=32, txn_width=width,
                                 drain_depth=4)
        sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=64,
                                             seed=4, local_frac=1.0)
        final = run_to_quiescence(
            cfg, se.from_sim_state(cfg, sys_.state))
        rounds[width] = int(final.metrics.rounds)
    # all-local traffic never conflicts: the wide window should cut
    # rounds by at least 3x on a miss-heavy uniform trace
    assert rounds[8] * 3 <= rounds[1], rounds


def test_non_power_of_two_nodes():
    """Window machinery must not assume power-of-two node counts
    (claim priority bits, entry strides, clip bounds)."""
    cfg = SystemConfig.scale(num_nodes=24, txn_width=3, drain_depth=3)
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=40,
                                         seed=6, local_frac=0.5)
    final = run_to_quiescence(cfg, se.from_sim_state(cfg, sys_.state))
    se.check_exact_directory(cfg, final)
    assert int(final.metrics.instrs_retired) == 24 * 40
