"""Traffic recording + universal replay: the capture/replay plane.

The load-bearing gates:

- **Byte-determinism**: the same VirtualClock session writes the same
  ``cache-sim/recording/v1`` bytes, twice — every row is a pure
  function of the schedule and the injected clock.
- **Digest-before-eviction**: result digests land in the recording
  BEFORE ``retain_results`` eviction, so the digest column is complete
  even for jobs whose result docs the daemon already dropped.
- **The e2e demo** (ISSUE acceptance): a virtual-clock session is
  recorded, replayed through ``cache-sim replay`` with the ORIGINAL
  arrival times, per-job dumps come back byte-identical (digest
  audit), and ``bench-diff --latency`` over the emitted recorded /
  replayed entries exits 0.
- **Auto-detection**: the one front door classifies a recording, a
  soak-incident dir, a flight-incident dir, a repro fixture — and
  rejects garbage with a clear error.
- **Shrink**: ddmin over the JOB LIST converges a seeded SLO breach to
  <= 3 jobs that still breach on replay of the emitted incident
  fixture.
"""

import json
import os
import threading

import pytest

from ue22cs343bb1_openmp_assignment_tpu import replay, soak
from ue22cs343bb1_openmp_assignment_tpu.daemon.core import (
    DaemonCore, attach_recorder, drive)
from ue22cs343bb1_openmp_assignment_tpu.daemon.server import DaemonServer
from ue22cs343bb1_openmp_assignment_tpu.obs import recording
from ue22cs343bb1_openmp_assignment_tpu.obs.clock import VirtualClock
from ue22cs343bb1_openmp_assignment_tpu.serve import JobSpec


def _arrivals(n=8, rate=60.0, nodes=2, trace_len=4, seed=2):
    arr = soak.soak_stream(rate, max(0.05, n / rate), nodes=nodes,
                           trace_len=trace_len, seed=seed)[:n]
    return [(t, s, ("interactive", "batch")[i % 2])
            for i, (t, s) in enumerate(arr)]


def _record(path, arrivals, wave_s=1e-3, **core_kw):
    core_kw.setdefault("slots", 2)
    core_kw.setdefault("chunk", 16)
    core = DaemonCore(clock=VirtualClock(wave_s=wave_s), **core_kw)
    attach_recorder(core, str(path))
    drive(core, arrivals)
    core.recorder.close()
    return core


# -- the artifact ----------------------------------------------------------


def test_recording_byte_determinism_virtual_clock(tmp_path):
    """Two fresh VirtualClock sessions over the same schedule write
    byte-identical recordings (the capture analogue of the daemon's
    trace/stats determinism gate)."""
    arrivals = _arrivals(8)
    c1 = _record(tmp_path / "a", arrivals)
    c2 = _record(tmp_path / "b", arrivals)
    b1 = (tmp_path / "a" / recording.FILENAME).read_bytes()
    b2 = (tmp_path / "b" / recording.FILENAME).read_bytes()
    assert b1 == b2
    assert c1.recorder.submits == c2.recorder.submits == len(arrivals)
    assert c1.recorder.results == len(arrivals)
    rec = recording.load(tmp_path / "a")
    assert rec["clock"] == "virtual"
    assert rec["config"]["slots"] == 2
    # submit rows carry the full spec and scheduled arrival offsets
    sched = recording.arrivals(rec)
    assert [(s.name, lane) for _, s, lane in sched] == \
        [(s.name, lane) for _, s, lane in
         sorted(arrivals, key=lambda a: (a[0], a[1].name))]
    assert all(isinstance(s, JobSpec) for _, s, _ in sched)


def test_recording_stats_block_and_validation(tmp_path):
    """stats() exposes live capture counters; the loader rejects
    structurally broken artifacts with named violations."""
    arrivals = _arrivals(4)
    core = _record(tmp_path / "r", arrivals)
    st = core.stats()
    assert st["recording"]["submits"] == 4
    assert st["recording"]["results"] == 4
    assert st["recording"]["path"].endswith(recording.FILENAME)
    # no recorder -> null block, still schema-valid
    bare = DaemonCore(slots=2, clock=VirtualClock())
    assert bare.stats()["recording"] is None

    path = tmp_path / "r" / recording.FILENAME
    rows = [json.loads(x) for x in path.read_text().splitlines()]
    with pytest.raises(ValueError, match="schema"):
        recording.validate({**rows[0], "schema": "nope"}, rows[1:])
    with pytest.raises(ValueError, match="no prior submit"):
        recording.validate(rows[0], [r for r in rows[1:]
                                     if r["event"] == "result"])
    dup = [r for r in rows[1:] if r["event"] == "submit"][:1] * 2
    with pytest.raises(ValueError, match="duplicate submit"):
        recording.validate(rows[0], dup)


def test_digest_recorded_before_retention_eviction(tmp_path):
    """The satellite fix: with retain_results far below the job count,
    evicted jobs answer 'unknown' over the wire but their digests are
    complete in the recording (computed in _extract BEFORE
    _retire)."""
    arrivals = _arrivals(8)
    core = _record(tmp_path / "r", arrivals, retain_results=2,
                   keep_dumps=False)
    assert core.results_evicted > 0
    rec = recording.load(tmp_path / "r")
    results = recording.results_by_job(rec)
    assert len(results) == len(arrivals)
    assert all(r["digest"] and r["digest"] != "None"
               for r in results.values())
    # the evicted jobs really are gone from the daemon's memory
    assert len(core.results) <= 2


def test_subset_and_slice_window():
    rec = {"schema": recording.SCHEMA_ID, "clock": "virtual",
           "config": {},
           "rows": [
               {"event": "submit", "job": "a", "lane": "batch",
                "t_s": 0.0, "depth": 1, "spec": {"name": "a"}},
               {"event": "submit", "job": "b", "lane": "batch",
                "t_s": 1.0, "depth": 2, "spec": {"name": "b"}},
               {"event": "result", "job": "a", "t_s": 1.5,
                "quiesced": True, "digest": "x", "cycles": 3,
                "bucket": "mesi:2x4"},
               {"event": "submit", "job": "c", "lane": "batch",
                "t_s": 2.0, "depth": 1, "spec": {"name": "c"}},
           ]}
    sub = recording.subset(rec, {"b"})
    assert [r["job"] for r in sub["rows"]] == ["b"]
    # slice keeps jobs SUBMITTED in-window; result rows ride along
    win = recording.slice_window(rec, 0.0, 1.0)
    assert {r["job"] for r in win["rows"]} == {"a", "b"}
    assert any(r["event"] == "result" for r in win["rows"])
    assert recording.derived_arrival_rate(rec) == pytest.approx(1.5)


# -- universal replay ------------------------------------------------------


def test_replay_detect_matrix(tmp_path):
    """One front door, four artifact kinds, and a clear refusal."""
    arrivals = _arrivals(3)
    _record(tmp_path / "rec", arrivals)
    assert replay.detect(tmp_path / "rec") == "recording"
    assert replay.detect(
        tmp_path / "rec" / recording.FILENAME) == "recording"

    soak_inc = tmp_path / "soak_inc"
    soak_inc.mkdir()
    (soak_inc / "incident.json").write_text(json.dumps(
        {"schema": soak.INCIDENT_SCHEMA_ID}))
    assert replay.detect(soak_inc) == "soak-incident"

    flight_inc = tmp_path / "flight_inc"
    flight_inc.mkdir()
    (flight_inc / "incident.json").write_text(json.dumps(
        {"schema": "cache-sim/incident/v1"}))
    assert replay.detect(flight_inc) == "flight-incident"

    fix = tmp_path / "fix"
    fix.mkdir()
    (fix / "repro.json").write_text(json.dumps(
        {"schema": "cache-sim/repro/v1"}))
    assert replay.detect(fix) == "fixture"
    assert replay.detect(fix / "repro.json") == "fixture"

    garbage = tmp_path / "garbage.jsonl"
    garbage.write_text("not json at all\n")
    with pytest.raises(ValueError, match="not a replayable artifact"):
        replay.detect(garbage)
    with pytest.raises(ValueError, match="not a replayable artifact"):
        replay.detect(tmp_path / "does_not_exist")
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(ValueError, match="not a replayable artifact"):
        replay.detect(empty)
    # the CLI maps the refusal to exit 2, not a traceback
    assert replay.main([str(garbage)]) == 2


def test_record_replay_e2e_demo(tmp_path, capsys):
    """ISSUE acceptance, pinned: record a virtual-clock session,
    replay it via `cache-sim replay` with original arrival times, all
    per-job dumps byte-identical (digest audit), and bench-diff
    --latency over the emitted entry pair exits 0."""
    from ue22cs343bb1_openmp_assignment_tpu.obs import cli as obs_cli
    arrivals = _arrivals(10)
    _record(tmp_path / "rec", arrivals)
    out = tmp_path / "out"
    rc = replay.main([str(tmp_path / "rec"), "--out", str(out)])
    assert rc == 0
    doc = json.loads((out / "replay.json").read_text())
    assert doc["transport"] == "replay"
    assert doc["jobs_total"] == doc["jobs_quiesced"] == len(arrivals)
    assert doc["digests_matched"] == len(arrivals)
    assert doc["digest_mismatches"] == []
    # the deterministic in-proc replay reproduces the RECORDED latency
    # distribution exactly (same clock, same schedule, same machine)
    assert doc["latency"]["samples_ms"] == \
        doc["recorded_latency"]["samples_ms"]
    assert doc["latency_verdict"]["verdict"] != "incomparable"
    rc2 = obs_cli.main_bench_diff(
        ["--latency", str(out / "recorded.entry.json"),
         str(out / "replayed.entry.json")])
    assert rc2 == 0
    capsys.readouterr()


def test_replay_flags_divergent_dumps(tmp_path):
    """A replay under a DIFFERENT scheduler shape may still quiesce —
    but if any dump digest drifts, the replay exits 1 and names the
    jobs. Tampering with a recorded digest is the cheap way to force
    the path."""
    arrivals = _arrivals(4)
    _record(tmp_path / "rec", arrivals)
    path = tmp_path / "rec" / recording.FILENAME
    lines = path.read_text().splitlines()
    out = []
    for ln in lines:
        row = json.loads(ln)
        if row.get("event") == "result":
            row["digest"] = "0" * 16
        out.append(json.dumps(row, sort_keys=True))
    path.write_text("\n".join(out) + "\n")
    rc = replay.main([str(tmp_path / "rec")])
    assert rc == 1


def test_replay_through_live_daemon_round_trip(tmp_path):
    """Tentpole (b) over a real socket: a daemon in record mode
    captures client traffic; the recording then replays and the
    recorded/replayed latency entries are comparable (same metric,
    same derived arrival rate — never 'incomparable')."""
    from ue22cs343bb1_openmp_assignment_tpu.obs import regress
    core = DaemonCore(slots=2, chunk=8)
    attach_recorder(core, str(tmp_path / "cap"))
    server = DaemonServer(core, str(tmp_path / "daemon.sock"),
                          quiet=True)
    th = threading.Thread(target=server.run, daemon=True)
    th.start()
    try:
        arrivals = soak.soak_stream(40.0, 0.15, nodes=2, trace_len=4,
                                    seed=7)
        soak.soak_daemon(arrivals, str(tmp_path / "daemon.sock"),
                         arrival_rate=40.0)
    finally:
        server.stop()
        th.join(10.0)
    core.recorder.close()
    rec = recording.load(tmp_path / "cap")
    assert rec["clock"] == "monotonic"
    assert len(recording.arrivals(rec)) == len(arrivals)
    # original lanes are preserved row by row
    lanes = [lane for _, _, lane in recording.arrivals(rec)]
    assert set(lanes) == {"interactive", "batch"}
    doc = replay.replay_recording(rec)
    assert doc["jobs_quiesced"] == doc["jobs_total"] == len(arrivals)
    assert doc["digests_matched"] == len(arrivals)
    a, b = replay.latency_entries(rec, doc)
    rep = regress.compare_latency(a, b)
    assert rep["verdict"] != "incomparable"
    assert a["latency"]["arrival_rate"] == b["latency"]["arrival_rate"]


def test_slo_breach_incident_embeds_breach_window_slice(tmp_path):
    """Tentpole (a): an SLO breach on replay dumps an incident dir
    whose embedded recording slice is itself a replayable artifact."""
    arrivals = [(t, s, "batch") for t, s, _ in _arrivals(6)]
    _record(tmp_path / "rec", arrivals, wave_s=0.05)
    inc = tmp_path / "inc"
    rc = replay.main([str(tmp_path / "rec"), "--wave-s", "0.05",
                      "--slo", "p95=1",
                      "--incident-dir", str(inc)])
    assert rc == soak.EXIT_SLO_BREACH
    doc = soak.load_incident(str(inc))
    assert recording.FILENAME in doc["files"]
    slice_rec = recording.load(inc)
    assert len(recording.arrivals(slice_rec)) >= 1
    assert replay.detect(inc) == "soak-incident"
    # the incident dir replays through the same front door
    rc2 = replay.main([str(inc), "--wave-s", "0.05"])
    assert rc2 == 0


def test_shrink_converges_to_minimal_breaching_subset(tmp_path):
    """Satellite + acceptance: ddmin over the JOB LIST shrinks a
    seeded breach to <= 3 jobs, written as an incident fixture that
    still breaches when replayed."""
    arrivals = [(t, s, "batch") for t, s, _ in _arrivals(6)]
    _record(tmp_path / "rec", arrivals, wave_s=0.05)
    shr = tmp_path / "shrunk"
    rc = replay.main([str(tmp_path / "rec"), "--wave-s", "0.05",
                      "--slo", "p95=1",
                      "--incident-dir", str(tmp_path / "inc"),
                      "--shrink", "--shrink-out", str(shr)])
    assert rc == soak.EXIT_SLO_BREACH
    shrunk = recording.load(shr)
    jobs = {r["job"] for r in shrunk["rows"]
            if r["event"] == "submit"}
    assert 1 <= len(jobs) <= 3
    rc2 = replay.main([str(shr), "--wave-s", "0.05", "--slo", "p95=1",
                       "--incident-dir", str(tmp_path / "inc2")])
    assert rc2 == soak.EXIT_SLO_BREACH


def test_shrink_recording_predicate_memoized():
    """shrink_recording is 1-minimal and replays each distinct subset
    once (the predicate cache)."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis import shrink
    rows = []
    for i, name in enumerate("abcd"):
        rows.append({"event": "submit", "job": name, "lane": "batch",
                     "t_s": float(i), "depth": 1,
                     "spec": {"name": name}})
    rec = {"schema": recording.SCHEMA_ID, "clock": "virtual",
           "config": {}, "rows": rows}
    calls = []

    def pred(sub):
        names = {r["job"] for r in sub["rows"]}
        calls.append(names)
        return "c" in names

    small, n = shrink.shrink_recording(rec, pred)
    assert {r["job"] for r in small["rows"]} == {"c"}
    assert n == len(calls) == len({frozenset(c) for c in calls})
    with pytest.raises(ValueError, match="does not hold"):
        shrink.shrink_recording(rec, lambda sub: False)


# -- heavy-tail load generators --------------------------------------------


def test_bursty_stream_deterministic_and_bursty():
    a = soak.bursty_stream(20.0, 2.0, seed=4)
    b = soak.bursty_stream(20.0, 2.0, seed=4)
    assert [(t, s.name) for t, s in a] == [(t, s.name) for t, s in b]
    assert [(t, s.name) for t, s in a] != \
        [(t, s.name) for t, s in soak.bursty_stream(20.0, 2.0, seed=5)]
    ts = [t for t, _ in a]
    assert ts == sorted(ts) and all(0 <= t < 2.0 for t in ts)
    # on/off structure: the largest inter-arrival gap (an OFF window)
    # dwarfs the in-burst median gap
    gaps = [y - x for x, y in zip(ts, ts[1:])]
    gaps.sort()
    assert gaps[-1] > 4 * gaps[len(gaps) // 2]
    with pytest.raises(ValueError, match="peak_factor"):
        soak.bursty_stream(20.0, 1.0, peak_factor=0)
    with pytest.raises(ValueError, match="on_s/off_s"):
        soak.bursty_stream(20.0, 1.0, on_s=0)


def test_soak_cli_bursty_flag(tmp_path, capsys):
    rc = soak.main(["--bursty", "--arrival-rate", "30",
                    "--duration", "0.3", "--nodes", "2",
                    "--trace-len", "4", "--virtual-clock",
                    "--out", str(tmp_path / "doc.json")])
    assert rc == 0
    doc = json.loads((tmp_path / "doc.json").read_text())
    assert doc["jobs_quiesced"] == doc["jobs_total"] > 0
    capsys.readouterr()


def test_zipf_hotspot_workload_skew_and_registry():
    import jax
    import numpy as np
    from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
    from ue22cs343bb1_openmp_assignment_tpu.models import workloads
    assert "zipf_hotspot" in workloads.GENERATORS
    cfg = SystemConfig(num_nodes=4)
    op, addr, val, count = workloads.zipf_hotspot(
        jax.random.PRNGKey(3), cfg, 128)
    op2, addr2, _, _ = workloads.zipf_hotspot(
        jax.random.PRNGKey(3), cfg, 128)
    assert (np.array(addr) == np.array(addr2)).all()
    assert (np.array(op) == np.array(op2)).all()
    assert op.shape == addr.shape == (4, 128)
    assert (np.array(count) == 128).all()
    # popularity skew: the hottest block takes far more than the
    # uniform share of a 64-rank universe
    _, counts = np.unique(np.array(addr), return_counts=True)
    assert counts.max() / counts.sum() > 4.0 / 64
    # and it runs end to end through the serving stack
    from ue22cs343bb1_openmp_assignment_tpu import serve
    dumps = serve.solo_dumps(JobSpec(name="z", workload="zipf_hotspot",
                                     nodes=2, trace_len=8))
    assert len(dumps) == 2
