"""Procedural workloads: O(1)-trace-memory instruction streams.

The sync engine can compute instructions per (node, index) from a
counter-based hash inside the round (cfg.procedural) instead of
gathering from a stored [N, T] trace. The materializer
(workloads.procedural_uniform) produces the identical stream as arrays,
so procedural and materialized runs must agree bit-for-bit — and trace
length can far exceed any storable array.
"""

import dataclasses

import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se


def test_procedural_equals_materialized():
    cfg = SystemConfig.scale(num_nodes=64, max_instrs=48,
                             procedural="uniform", proc_seed=7)
    proc = se.procedural_state(cfg, 48, seed=3)
    proc = se.run_sync_to_quiescence(cfg, proc, 16, 50_000)
    assert bool(proc.quiescent())
    se.check_exact_directory(cfg, proc)

    cfg_mat = dataclasses.replace(cfg, procedural=None)
    mat_sys = CoherenceSystem.from_workload(cfg_mat, "procedural_uniform",
                                            trace_len=48)
    mat = se.from_sim_state(cfg_mat, mat_sys.state, seed=3)
    mat = se.run_sync_to_quiescence(cfg_mat, mat, 16, 50_000)
    assert bool(mat.quiescent())

    np.testing.assert_array_equal(np.asarray(proc.cache_val),
                                  np.asarray(mat.cache_val))
    np.testing.assert_array_equal(np.asarray(proc.cache_addr),
                                  np.asarray(mat.cache_addr))
    np.testing.assert_array_equal(np.asarray(proc.dm[:, :4]),
                                  np.asarray(mat.dm[:, :4]))
    assert (int(proc.metrics.instrs_retired)
            == int(mat.metrics.instrs_retired) == 64 * 48)


def test_procedural_beyond_storable_length():
    """Trace length way past max_instrs: no [N, T] array ever exists."""
    cfg = SystemConfig.scale(num_nodes=32, max_instrs=8,
                             procedural="uniform", drain_depth=8)
    length = 5000                      # >> max_instrs; storage stays [N,1]
    st = se.procedural_state(cfg, length)
    assert st.instr_pack.shape == (32, 1, 2)
    st = se.run_sync_to_quiescence(cfg, st, 32, 100_000)
    assert bool(st.quiescent())
    assert int(st.metrics.instrs_retired) == 32 * length
    se.check_exact_directory(cfg, st)


def test_procedural_addresses_valid():
    cfg = SystemConfig.scale(num_nodes=16, procedural="uniform")
    import jax.numpy as jnp
    nodes = jnp.arange(16, dtype=jnp.int32)[:, None]
    idxs = jnp.arange(200, dtype=jnp.int32)[None, :]
    oa, val = se.procedural_instr(cfg, nodes, idxs)
    addr = np.asarray(oa & 0x0FFFFFFF)
    op = np.asarray(oa >> 28)
    assert addr.min() >= 0 and addr.max() < (16 << cfg.block_bits)
    assert set(np.unique(op)) <= {0, 1}
    assert np.asarray(val).min() >= 0 and np.asarray(val).max() < 256
    # locality roughly matches proc_local_permille
    home = addr >> cfg.block_bits
    local_frac = float((home == np.arange(16)[:, None]).mean())
    assert 0.7 < local_frac < 0.9
