"""Synchronous transactional engine (ops.sync_engine).

Validates that the round-based atomic-transaction engine executes the
same protocol as the async message-level engine:

* byte-exact golden dumps on the deterministic reference suites,
* final-state agreement with the async engine on node-local traffic,
* the exact-directory invariant (dir state/count/owner always consistent
  with the set of valid tag-matching cache lines) on cross-node traffic,
* progress under adversarial all-nodes-one-address contention,
* seed determinism.
"""

import os

import jax

import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import REFERENCE_TESTS, requires_reference

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
from ue22cs343bb1_openmp_assignment_tpu.ops.step import run_to_quiescence
from ue22cs343bb1_openmp_assignment_tpu.state import init_state
from ue22cs343bb1_openmp_assignment_tpu.utils.golden import (format_node_dump,
                                                             state_to_dumps)
from ue22cs343bb1_openmp_assignment_tpu.utils.trace import load_test_dir

CFG = SystemConfig.reference()


def run_sync_suite(suite, seed=0):
    traces = load_test_dir(os.path.join(REFERENCE_TESTS, suite))
    st = se.from_sim_state(CFG, init_state(CFG, traces), seed=seed)
    final = se.run_sync_to_quiescence(CFG, st, 8, 10_000)
    assert bool(final.quiescent()), f"{suite} did not quiesce"
    return final


@requires_reference
@pytest.mark.parametrize("suite", ["sample", "test_1", "test_2"])
def test_deterministic_suites_byte_exact(suite):
    final = run_sync_suite(suite)
    dumps = [format_node_dump(d)
             for d in state_to_dumps(CFG, se.to_dump_view(CFG, final))]
    for n in range(4):
        golden = open(f"{REFERENCE_TESTS}/{suite}/core_{n}_output.txt").read()
        assert dumps[n] == golden, f"{suite} core_{n} diverged"


def test_matches_async_on_local_traffic():
    """All-local traces are schedule-independent (SURVEY §4): both engines
    must land on identical cache/memory/directory state."""
    rng = np.random.default_rng(7)
    N, M = 8, 16
    cfg = SystemConfig.reference(num_nodes=N)
    traces = []
    for n in range(N):
        tr = []
        for _ in range(24):
            b = int(rng.integers(M))
            if rng.random() < 0.5:
                tr.append((0, n * M + b, 0))
            else:
                tr.append((1, n * M + b, int(rng.integers(256))))
        traces.append(tr)
    a_final = run_to_quiescence(cfg, init_state(cfg, traces), 50_000)
    assert bool(a_final.quiescent())
    s_final = se.run_sync_to_quiescence(
        cfg, se.from_sim_state(cfg, init_state(cfg, traces)), 8, 50_000)
    assert bool(s_final.quiescent())
    mem, ds, bv = se.to_sim_arrays(cfg, s_final)
    np.testing.assert_array_equal(mem, np.asarray(a_final.memory))
    np.testing.assert_array_equal(ds, np.asarray(a_final.dir_state))
    np.testing.assert_array_equal(bv, np.asarray(a_final.dir_bitvec))
    np.testing.assert_array_equal(np.asarray(s_final.cache_addr),
                                  np.asarray(a_final.cache_addr))
    np.testing.assert_array_equal(np.asarray(s_final.cache_val),
                                  np.asarray(a_final.cache_val))
    np.testing.assert_array_equal(np.asarray(s_final.cache_state),
                                  np.asarray(a_final.cache_state))
    se.check_exact_directory(cfg, s_final)


@pytest.mark.parametrize("seed", [0, 3])
def test_invariants_cross_node_traffic(seed):
    cfg = SystemConfig.scale(num_nodes=64, max_instrs=32,
                             drain_depth=4)
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=32,
                                         seed=seed, local_frac=0.3)
    st = se.from_sim_state(cfg, sys_.state, seed=seed)
    # invariant must hold at every chunk boundary, not just at the end
    for _ in range(6):
        st = se.run_rounds(cfg, st, 13)
        se.check_exact_directory(cfg, st)
    st = se.run_sync_to_quiescence(cfg, st, 16, 100_000)
    assert bool(st.quiescent())
    se.check_exact_directory(cfg, st)
    m = st.metrics
    total = int(jnp.sum(st.instr_count))
    assert int(m.instrs_retired) == total
    assert (int(m.read_hits) + int(m.write_hits) + int(m.read_misses)
            + int(m.write_misses) + int(m.upgrades)) == total


def test_adversarial_single_address_contention():
    """Every node hammers one remote block: one transaction wins per
    round, the hash rotates winners, and the run still terminates with a
    consistent directory."""
    cfg = SystemConfig.reference(num_nodes=8)
    addr = 0x05
    traces = [[(1, addr, n + 1), (0, addr, 0)] * 4 for n in range(8)]
    st = se.from_sim_state(cfg, init_state(cfg, traces))
    st = se.run_sync_to_quiescence(cfg, st, 8, 50_000)
    assert bool(st.quiescent())
    se.check_exact_directory(cfg, st)
    assert int(st.metrics.conflicts) > 0  # contention actually happened
    # final memory value must be one of the written values
    mem, _, _ = se.to_sim_arrays(cfg, st)
    assert int(mem[0, 5]) in set(range(1, 9)) | {20 * 0 + 5}


def test_seed_determinism_and_schedule_sensitivity():
    cfg = SystemConfig.scale(num_nodes=32, max_instrs=16)
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=16,
                                         seed=1, local_frac=0.2)

    def run(seed):
        st = se.from_sim_state(cfg, sys_.state, seed=seed)
        return se.run_sync_to_quiescence(cfg, st, 8, 50_000)

    a, b = run(5), run(5)
    np.testing.assert_array_equal(np.asarray(a.cache_val),
                                  np.asarray(b.cache_val))
    np.testing.assert_array_equal(np.asarray(a.dm), np.asarray(b.dm))
    assert int(a.round) == int(b.round)


def test_nop_in_trace_retires():
    """Malformed trace lines load as in-trace NOPs (utils.trace); they
    must retire with no effect instead of livelocking the round loop."""
    cfg = SystemConfig.reference(num_nodes=4)
    traces = [[(1, 0x03, 9), (2, 0, 0), (0, 0x03, 0)], [], [], []]
    st = se.from_sim_state(cfg, init_state(cfg, traces))
    st = se.run_sync_to_quiescence(cfg, st, 4, 2000)
    assert bool(st.quiescent())
    assert int(st.metrics.instrs_retired) == 3
    a_final = run_to_quiescence(cfg, init_state(cfg, traces), 10_000)
    np.testing.assert_array_equal(np.asarray(st.cache_val),
                                  np.asarray(a_final.cache_val))


def test_non_power_of_two_mem_size():
    """dm rows are strided by 2**block_bits, so address==row holds even
    when mem_size is not a power of two (codec packs the home id above
    ceil(log2(mem_size)) bits)."""
    cfg = SystemConfig.reference(num_nodes=4, mem_size=12)
    # node 0 writes (home 1, block 0) = addr 16, reads it back
    traces = [[(1, 0x10, 77), (0, 0x10, 0)], [], [], []]
    st = se.from_sim_state(cfg, init_state(cfg, traces))
    st = se.run_sync_to_quiescence(cfg, st, 4, 2000)
    assert bool(st.quiescent())
    se.check_exact_directory(cfg, st)
    a_final = run_to_quiescence(cfg, init_state(cfg, traces), 10_000)
    mem, ds, bv = se.to_sim_arrays(cfg, st)
    np.testing.assert_array_equal(mem, np.asarray(a_final.memory))
    np.testing.assert_array_equal(ds, np.asarray(a_final.dir_state))
    np.testing.assert_array_equal(np.asarray(st.cache_val),
                                  np.asarray(a_final.cache_val))


@requires_reference
@pytest.mark.parametrize("suite", ["test_3", "test_4"])
def test_racy_suites_seed_sweep_matches_accepted(suite):
    """The batched seed sweep (utils.search) replaces the reference's
    run-until-match harness (test3.sh:6-33): some arbitration seed must
    reproduce an accepted run_* outcome, found in one vmapped dispatch."""
    from ue22cs343bb1_openmp_assignment_tpu.utils import search
    traces = load_test_dir(os.path.join(REFERENCE_TESTS, suite))
    accepted = search.load_accepted(os.path.join(REFERENCE_TESTS, suite))
    assert accepted
    matches = search.match_accepted(
        CFG, init_state(CFG, traces), accepted, seeds=range(8),
        max_rounds=10_000)
    assert matches, f"{suite}: no seed in 0..7 matched an accepted run"


def test_ensemble_equals_individual_runs():
    """vmapped ensemble replicas are bit-identical to solo runs."""
    cfg = SystemConfig.scale(num_nodes=16, max_instrs=16)
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=16,
                                         seed=2, local_frac=0.2)
    seeds = [0, 1, 2]
    reps = [se.from_sim_state(cfg, sys_.state, seed=s) for s in seeds]
    ens = se.run_ensemble_to_quiescence(cfg, se.make_ensemble(reps), 8,
                                        5000)
    for r, s in enumerate(seeds):
        solo = se.run_sync_to_quiescence(cfg, reps[r], 8, 5000)
        rep = se.ensemble_replica(ens, r)
        np.testing.assert_array_equal(np.asarray(rep.cache_val),
                                      np.asarray(solo.cache_val))
        np.testing.assert_array_equal(np.asarray(rep.dm),
                                      np.asarray(solo.dm))


def test_sync_checkpoint_roundtrip(tmp_path):
    """Checkpoint/resume of the transactional engine is bit-exact."""
    from ue22cs343bb1_openmp_assignment_tpu.utils import checkpoint as ckpt
    cfg = SystemConfig.scale(num_nodes=32, max_instrs=24)
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=24,
                                         seed=4, local_frac=0.4)
    st = se.from_sim_state(cfg, sys_.state, seed=9)
    mid = se.run_rounds(cfg, st, 7)
    path = str(tmp_path / "sync.ckpt")
    ckpt.save_checkpoint(path, cfg, mid)
    cfg2, restored, meta = ckpt.load_checkpoint(path)
    assert meta["kind"] == "sync" and cfg2 == cfg
    a = se.run_sync_to_quiescence(cfg, mid, 8, 5000)
    b = se.run_sync_to_quiescence(cfg, restored, 8, 5000)
    for fa, fb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))


def test_burst_retires_consecutive_hits_in_one_round():
    """A node-local all-hit trace retires drain_depth instrs per round
    after the first fill."""
    cfg = SystemConfig.reference(num_nodes=4, drain_depth=4)
    # node 0: one write-miss fill, then 12 hits on the same line
    traces = [[(1, 0x03, 9)] + [(0, 0x03, 0)] * 12, [], [], []]
    st = se.from_sim_state(cfg, init_state(cfg, traces))
    st = se.run_rounds(cfg, st, 1)
    assert int(st.idx[0]) == 1          # round 1: the miss commits
    st = se.run_rounds(cfg, st, 1)
    assert int(st.idx[0]) == 5          # round 2: burst of 4 hits
    st = se.run_sync_to_quiescence(cfg, st, 4, 1000)
    assert bool(st.quiescent())
    assert int(st.metrics.read_hits) == 12
