"""Pallas multi-transaction window round vs the XLA path.

round_step with cfg.pallas_burst on a txn_width>1 procedural config
routes through ops.pallas_window (window kernel -> XLA claim/commit ->
replay kernel); rounds must be bit-identical to `_round_step_multi`.

The Pallas interpreter's cost grows superlinearly with kernel size
(a K=3/W=7 window kernel takes ~4 min to interpret on CPU), so the
CPU differential here uses a deliberately tiny window (K=2, W=3) —
still exercising multi-transaction commits, releases and truncation.
The full-size compiled path is validated on the TPU backend
(test_full_size_on_tpu; scripts/verify recipe runs it on hardware,
where 8 warmed rounds at K=3/H=4 match bit-for-bit).
"""

import dataclasses

import jax
import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se


def _cfgs(num_nodes=64, drain_depth=1, txn_width=2):
    cfg = SystemConfig.scale(num_nodes=num_nodes, drain_depth=drain_depth,
                             txn_width=txn_width)
    cfg = dataclasses.replace(cfg, procedural="uniform", max_instrs=1,
                              proc_local_permille=700)
    return cfg, dataclasses.replace(cfg, pallas_burst=True)


def _assert_states_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_rounds_bit_identical_mid_run():
    """Jitted multi-round equality on a warmed machine, where
    multi-transaction windows, releases and conflicts occur."""
    cfg, pcfg = _cfgs()
    st = se.procedural_state(cfg, 200, seed=1)
    st = se.run_rounds(cfg, st, 40)          # warm: caches full, races on
    a = se.run_rounds(cfg, st, 4)
    b = se.run_rounds(pcfg, st, 4)
    _assert_states_equal(a, b)
    se.check_exact_directory(pcfg, b)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="compiled Pallas path needs the TPU backend "
                           "(CPU interpreter is impractically slow at "
                           "full kernel size)")
def test_full_size_on_tpu():
    cfg, pcfg = _cfgs(num_nodes=1024, drain_depth=4, txn_width=3)
    st = se.procedural_state(cfg, 256, seed=3)
    st = se.run_rounds(cfg, st, 20)
    a = se.run_rounds(cfg, st, 8)
    b = se.run_rounds(pcfg, st, 8)
    _assert_states_equal(a, b)
