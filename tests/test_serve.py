"""Serving layer (serve.py): batch packing, per-job bit-parity, wave
admission, padding-waste accounting, and the wave recompile guard.

The load-bearing property is the per-job parity gate: a batched wave
containing job J must produce a final state dump byte-identical to
running J solo at its own geometry — including jobs padded into a
bigger slot (node count AND trace length) and non-MESI protocol
variants. Early-exit masking makes a quiescent slot a frozen fixpoint,
so batching is bit-invisible per tenant.
"""

import dataclasses
import json

import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu import serve
from ue22cs343bb1_openmp_assignment_tpu import state as st
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops import step


def _specs_small():
    specs = serve.mixed_jobs(5, nodes=4, trace_len=8)
    # one job padded on both axes into the 4x8 slot
    specs[2] = dataclasses.replace(specs[2], nodes=2, trace_len=4)
    return specs


def test_stack_index_roundtrip():
    cfg = SystemConfig.scale(num_nodes=4, max_instrs=8)
    s0 = serve.build_job_state(cfg, cfg, serve.mixed_jobs(1, 4, 8)[0])
    s1 = st.init_state(cfg)
    b = st.stack_states([s0, s1])
    assert st.batch_size(b) == 2
    import jax
    for want, got in ((s0, st.index_state(b, 0)),
                      (s1, st.index_state(b, 1))):
        for leaf_w, leaf_g in zip(jax.tree.leaves(want),
                                  jax.tree.leaves(got)):
            np.testing.assert_array_equal(np.asarray(leaf_w),
                                          np.asarray(leaf_g))


def test_set_state_swaps_one_slot():
    cfg = SystemConfig.scale(num_nodes=4, max_instrs=8)
    specs = serve.mixed_jobs(3, 4, 8)
    a, b_, c = (serve.build_job_state(cfg, cfg, s) for s in specs)
    batch = st.stack_states([a, b_])
    batch = st.set_state(batch, 1, c)
    np.testing.assert_array_equal(
        np.asarray(st.index_state(batch, 0).instr_addr),
        np.asarray(a.instr_addr))
    np.testing.assert_array_equal(
        np.asarray(st.index_state(batch, 1).instr_addr),
        np.asarray(c.instr_addr))


def test_batched_wave_matches_solo_dumps():
    """Fast parity gate: every job in a batched serve run dumps
    byte-identical to its solo run — including the padded job."""
    specs = _specs_small()
    doc = serve.serve(specs, slots=3, out_dir=None)
    assert doc["jobs_quiesced"] == len(specs)
    scfg = serve.slot_config(specs)
    # re-run and compare dumps through the out_dir path for job 0 and
    # the padded job 2
    import tempfile
    import pathlib
    with tempfile.TemporaryDirectory() as td:
        serve.serve(specs, slots=3, out_dir=td)
        for spec in (specs[0], specs[2]):
            solo = serve.solo_dumps(spec)
            jdir = pathlib.Path(td) / spec.name
            got = [(jdir / f"core_{n}_output.txt").read_text()
                   for n in range(spec.nodes)]
            assert got == solo, f"batched dump != solo for {spec.name}"
    assert scfg.num_nodes == 4 and scfg.max_instrs == 8


def test_wave_freezes_finished_jobs_exactly():
    """Early-exit masking: a short job's cycle counter stops at its own
    quiescence point even while a longer slot-mate runs on."""
    specs = serve.mixed_jobs(2, nodes=4, trace_len=8)
    doc = serve.serve(specs, slots=2)
    solo = {}
    for spec in specs:
        cfg = serve.job_config(spec)
        s0 = st.init_state(cfg, instr_arrays=serve.build_job_arrays(
            cfg, spec))
        fin = step.run_chunked_to_quiescence(cfg, s0, 1, 100_000)
        solo[spec.name] = int(np.asarray(fin.cycle))
    for name, j in doc["jobs"].items():
        # batched runs chunk-granular, so the frozen counter may stop
        # up to chunk-1 short of the solo chunk=1 count — but never
        # after quiescence (the fixpoint freeze)
        assert j["quiesced"]
        assert j["cycles"] <= solo[name] + 32


def test_admission_between_waves_and_padding_waste():
    """More jobs than slots: finished jobs swap out, queued jobs admit
    in, and every wave reports its padded-instr fraction."""
    specs = serve.mixed_jobs(5, nodes=4, trace_len=8)
    doc = serve.serve(specs, slots=2)
    assert doc["jobs_total"] == 5 and doc["jobs_quiesced"] == 5
    assert doc["wave_count"] >= 3          # ceil(5/2) waves at least
    for w in doc["waves"]:
        assert 0.0 <= w["padding_waste"] <= 1.0
        assert w["slot_instr_budget"] == 2 * 4 * 8
    # the last wave holds 1 job in 2 slots: at least half the budget
    # is padding
    assert doc["waves"][-1]["padding_waste"] >= 0.5
    assert 0.0 <= doc["padding_waste"] <= 1.0


def test_padded_job_metrics_match_solo():
    """Per-job metrics survive batching: the padded job's retired
    count equals its solo run's."""
    specs = _specs_small()
    doc = serve.serve(specs, slots=5)
    spec = specs[2]
    cfg = serve.job_config(spec)
    s0 = st.init_state(cfg, instr_arrays=serve.build_job_arrays(
        cfg, spec))
    fin = step.run_chunked_to_quiescence(cfg, s0, 8, 100_000)
    got = doc["jobs"][spec.name]["metrics"]
    assert got["instrs_retired"] == int(fin.metrics.instrs_retired)
    assert got["schema"].startswith("cache-sim/metrics/v1")


def test_wave_recompile_guard():
    """Two heterogeneous waves at one slot shape compile once; the
    daemon's bucketed admission loop compiles at most one chunk runner
    per bucket and a replay adds nothing."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis import lint_jaxpr
    rep = lint_jaxpr.recompile_guard()
    assert rep["wave_cache_size"] == 1
    assert rep["daemon_buckets"] == 2      # two non-nesting shapes
    assert rep["daemon_wave_compiles"] <= rep["daemon_buckets"]
    assert rep["ok"]


def test_weighted_padding_waste_two_wave_regression():
    """Pin the budget-weighted aggregate: with per-wave instr budgets
    differing (what shape bucketing produces), the summary must weight
    each wave by its budget — an unweighted mean of the per-wave
    ratios is a different (wrong) number."""
    waves = [
        {"slot_instr_budget": 64, "real_instrs": 64},    # 0% waste
        {"slot_instr_budget": 1024, "real_instrs": 512},  # 50% waste
    ]
    got = serve.weighted_padding_waste(waves)
    assert got == pytest.approx(1.0 - 576.0 / 1088.0)    # ~0.4706
    unweighted = np.mean([1.0 - 64 / 64, 1.0 - 512 / 1024])
    assert abs(got - unweighted) > 0.2    # the distinction is real
    assert serve.weighted_padding_waste([]) == 0.0


def test_serve_summary_padding_waste_is_budget_weighted():
    """End to end: serve()'s summary padding_waste equals the
    budget-weighted recomputation from its own per-wave docs."""
    specs = serve.mixed_jobs(5, nodes=4, trace_len=8)
    doc = serve.serve(specs, slots=2)
    assert doc["padding_waste"] == pytest.approx(
        serve.weighted_padding_waste(doc["waves"]))


def test_load_jobs_jsonl_and_dir(tmp_path):
    specs = serve.mixed_jobs(3, nodes=4, trace_len=8)
    jl = tmp_path / "jobs.jsonl"
    jl.write_text("".join(
        json.dumps(dataclasses.asdict(s)) + "\n" for s in specs))
    assert serve.load_jobs(jl) == specs
    d = tmp_path / "jobs"
    d.mkdir()
    for s in specs:
        (d / f"{s.name}.json").write_text(
            json.dumps(dataclasses.asdict(s)))
    assert serve.load_jobs(d) == specs
    with pytest.raises(ValueError, match="unknown keys"):
        serve.JobSpec.from_dict({"name": "x", "nope": 1})
    with pytest.raises(ValueError, match="needs a 'name'"):
        serve.JobSpec.from_dict({"workload": "uniform"})


def test_serve_cli_smoke(tmp_path, capsys):
    from ue22cs343bb1_openmp_assignment_tpu import cli
    jobs = tmp_path / "jobs.jsonl"
    jobs.write_text("".join(
        json.dumps(dataclasses.asdict(s)) + "\n"
        for s in serve.mixed_jobs(3, nodes=4, trace_len=8)))
    out = tmp_path / "out"
    rc = cli.main(["serve", "--jobs", str(jobs), "--slots", "2",
                   "--chunk", "8", "--out-dir", str(out)])
    assert rc == 0
    text = capsys.readouterr().out
    assert "3/3 jobs quiesced" in text
    assert "padding_waste" in text
    summary = json.loads((out / "serve_summary.json").read_text())
    assert summary["schema"] == "cache-sim/serve/v1"
    assert (out / "job000" / "core_0_output.txt").exists()
    assert (out / "job000" / "metrics.json").exists()


def test_slot_too_small_rejected():
    specs = serve.mixed_jobs(2, nodes=8, trace_len=8)
    with pytest.raises(ValueError, match="exceed slot shape"):
        serve.slot_config(specs, slot_nodes=4)


@pytest.mark.slow
@pytest.mark.parametrize("protocol", ["mesi", "moesi", "mesif"])
def test_protocol_variant_parity_with_padded_job(protocol):
    """Slow differential gate: a mixed wave under each protocol table
    produces solo-identical dumps, including a padded slot."""
    specs = [dataclasses.replace(s, protocol=protocol)
             for s in serve.mixed_jobs(4, nodes=4, trace_len=8)]
    specs[1] = dataclasses.replace(specs[1], nodes=2, trace_len=4)
    import tempfile
    import pathlib
    with tempfile.TemporaryDirectory() as td:
        doc = serve.serve(specs, slots=4, out_dir=td)
        assert doc["jobs_quiesced"] == 4
        for spec in specs:
            solo = serve.solo_dumps(spec)
            jdir = pathlib.Path(td) / spec.name
            got = [(jdir / f"core_{n}_output.txt").read_text()
                   for n in range(spec.nodes)]
            assert got == solo, (
                f"{protocol}: batched dump != solo for {spec.name}")
