"""End-to-end golden parity against the reference fixture tree.

Deterministic suites (sample/test_1/test_2: every access is node-local,
SURVEY §4) must match byte-for-byte under any schedule. Racy suites
(test_3/test_4) must match one of the accepted run_* outcomes; the
schedule knobs (issue delays / arbitration) take the place of the
reference's run-until-match retry harness (test3.sh:6-33).
"""

import os

import pytest

from tests.conftest import REFERENCE_TESTS, requires_reference

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops.step import run_to_quiescence
from ue22cs343bb1_openmp_assignment_tpu.state import init_state
from ue22cs343bb1_openmp_assignment_tpu.utils.golden import (format_node_dump,
                                                             state_to_dumps)
from ue22cs343bb1_openmp_assignment_tpu.utils.trace import load_test_dir

CFG = SystemConfig.reference()


def run_suite(suite, **init_kw):
    traces = load_test_dir(os.path.join(REFERENCE_TESTS, suite))
    state = init_state(CFG, traces, **init_kw)
    final = run_to_quiescence(CFG, state, 10_000)
    assert bool(final.quiescent()), f"{suite} did not quiesce"
    return [format_node_dump(d) for d in state_to_dumps(CFG, final)]


@requires_reference
@pytest.mark.parametrize("suite", ["sample", "test_1", "test_2"])
def test_deterministic_suites_byte_exact(suite):
    dumps = run_suite(suite)
    for n in range(4):
        golden = open(f"{REFERENCE_TESTS}/{suite}/core_{n}_output.txt").read()
        assert dumps[n] == golden, f"{suite} core_{n} diverged"


@requires_reference
@pytest.mark.parametrize("suite", ["test_3", "test_4"])
def test_racy_suites_match_an_accepted_run(suite):
    from ue22cs343bb1_openmp_assignment_tpu.utils.search import load_accepted
    dumps = run_suite(suite)
    accepted = load_accepted(os.path.join(REFERENCE_TESTS, suite))
    assert any(dumps == g for g in accepted), (
        f"{suite}: default schedule matched no accepted run")


@requires_reference
def test_deterministic_suites_schedule_independent():
    """test_1/test_2 touch only node-local addresses, so any issue
    schedule must produce the same bytes (SURVEY §4 'prove order
    independence')."""
    import numpy as np
    base = run_suite("test_1")
    rng = np.random.RandomState(7)
    for trial in range(3):
        dumps = run_suite(
            "test_1",
            issue_delay=rng.randint(0, 5, size=4).astype(np.int32),
            issue_period=rng.randint(1, 4, size=4).astype(np.int32))
        assert dumps == base, f"schedule trial {trial} changed test_1 output"


@requires_reference
@pytest.mark.parametrize("suite,delays_a,delays_b", [
    # test_3: delaying core 2 past core 0's final write flips 0x01 from
    # EM/{0}+MODIFIED (run_1) to S/{0,2}+SHARED (run_2)
    ("test_3", [0, 0, 0, 0], [0, 0, 20, 0]),
    ("test_4", [0, 0, 0, 0], [4, 0, 0, 0]),
])
def test_schedule_knobs_reach_distinct_accepted_runs(suite, delays_a,
                                                     delays_b):
    """The schedule knobs genuinely explore the racy outcome space:
    different issue delays reproduce *different* accepted runs — the
    property the reference could only get from OS scheduling luck
    (README.md:10)."""
    import numpy as np

    from ue22cs343bb1_openmp_assignment_tpu.utils.search import load_accepted
    accepted = load_accepted(os.path.join(REFERENCE_TESTS, suite))

    def outcome(delays):
        dumps = run_suite(suite, issue_delay=np.asarray(delays, np.int32))
        for i, acc in enumerate(accepted):
            if dumps == acc:
                return i
        return None

    a = outcome(delays_a)
    b = outcome(delays_b)
    assert a is not None and b is not None, (a, b)
    assert a != b, "both delay schedules landed on the same accepted run"
