"""The obs layer: unified metrics schema, on-device telemetry,
Perfetto export, phase timers, CLI surfaces (ISSUE 2 tentpole).

Everything here runs on the in-repo mini fixture or synthetic
workloads — no reference tree needed.
"""

import copy
import json
import os

import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu import cli
from ue22cs343bb1_openmp_assignment_tpu.obs import (
    PhaseTimer, perfetto, schema, timeseries)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
GOLDEN = os.path.join(os.path.dirname(__file__), "golden")


def run_cli(args, tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    rc = cli.main(args)
    out, err = capsys.readouterr()
    return rc, out, err


def _stats(engine, tmp_path, monkeypatch, capsys, extra_args=()):
    rc, out, _ = run_cli(
        ["stats", "--workload", "uniform", "--cpu", "--engine", engine,
         *extra_args], tmp_path, monkeypatch, capsys)
    assert rc == 0
    return json.loads(out)


# -- schema ---------------------------------------------------------------

def test_validate_accepts_all_engines(tmp_path, monkeypatch, capsys):
    for engine in ("async", "sync", "native"):
        doc = _stats(engine, tmp_path, monkeypatch, capsys)
        schema.validate(doc)            # raises on violation
        assert doc["engine"] == engine
        assert doc["schema"] == schema.SCHEMA_ID


def test_validate_rejects_malformed():
    good = schema.from_sync(
        {"rounds": 3, "instrs_retired": 5, "read_hits": 1,
         "write_hits": 1, "read_misses": 1, "write_misses": 2,
         "upgrades": 0, "conflicts": 0, "evictions": 0,
         "invalidations": 0, "promotions": 0})
    schema.validate(good)
    for mutate, frag in [
            (lambda d: d.pop("instrs_retired"), "missing key"),
            (lambda d: d.update(schema="nope"), "schema must be"),
            (lambda d: d.update(read_hits=-1), "non-negative"),
            (lambda d: d.update(step_unit="epochs"), "step_unit"),
            (lambda d: d.update(bogus=1), "unknown key"),
            (lambda d: d["messages"].pop("by_type"),
             "messages missing key")]:
        bad = copy.deepcopy(good)
        mutate(bad)
        with pytest.raises(ValueError, match=frag):
            schema.validate(bad)


def test_by_type_must_sum_to_processed_total():
    doc = schema.from_async(
        {"cycles": 2, "instrs_retired": 1, "read_hits": 0,
         "write_hits": 0, "read_misses": 1, "write_misses": 0,
         "upgrades": 0, "msgs_processed": [1] + [0] * 12,
         "msgs_dropped": 0, "msgs_injected_dropped": 0,
         "invalidations": 0, "evictions": 0,
         "lat_hist": [0] * 16, "mb_depth_peak": 1})
    schema.validate(doc)
    doc["messages"]["processed_total"] = 99
    with pytest.raises(ValueError, match="does not sum"):
        schema.validate(doc)


def test_cross_engine_consistency(tmp_path, monkeypatch, capsys):
    """async and native implement the same message-level semantics, so
    the unified reports must agree on every core counter AND the cycle
    count for a deterministic workload."""
    a = _stats("async", tmp_path, monkeypatch, capsys)
    n = _stats("native", tmp_path, monkeypatch, capsys)
    for k in schema.CORE_COUNTERS:
        assert a[k] == n[k], k
    assert a["steps"] == n["steps"]
    # the transactional engine retires the same instruction stream
    s = _stats("sync", tmp_path, monkeypatch, capsys)
    assert s["instrs_retired"] == a["instrs_retired"]


def test_metrics_flag_unified_all_engines(tmp_path, monkeypatch, capsys):
    """The pre-existing --metrics stderr dumps now emit the same
    schema (satellite: one documented schema for three paths)."""
    for engine in ("async", "sync", "native"):
        rc, _, err = run_cli(
            ["--workload", "uniform", "--cpu", "--engine", engine,
             "--metrics"], tmp_path, monkeypatch, capsys)
        assert rc == 0
        doc = schema.validate(json.loads(err.strip().splitlines()[-1]))
        assert doc["engine"] == engine
        assert doc["instrs_retired"] == 128


# -- golden stats ---------------------------------------------------------

def test_stats_golden_mini(tmp_path, monkeypatch, capsys):
    rc, out, _ = run_cli(
        ["stats", "mini", "--tests-root", FIXTURES, "--cpu"],
        tmp_path, monkeypatch, capsys)
    assert rc == 0
    golden = json.load(open(os.path.join(GOLDEN, "stats_mini.json")))
    assert json.loads(out) == golden


# -- telemetry ------------------------------------------------------------

def _telemetry_run(num_cycles=200):
    from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
    from ue22cs343bb1_openmp_assignment_tpu.models.system import (
        CoherenceSystem)
    from ue22cs343bb1_openmp_assignment_tpu.ops import step
    cfg = SystemConfig(num_nodes=4)
    system = CoherenceSystem.from_workload(cfg, "uniform", trace_len=64,
                                           seed=0)
    final, telem = step.run_cycles_telemetry(cfg, system.state,
                                             num_cycles)
    return cfg, system, final, telem


def test_telemetry_sums_match_cumulative_metrics():
    """Per-cycle deltas integrate to exactly the cumulative Metrics —
    one capture, two views."""
    from ue22cs343bb1_openmp_assignment_tpu.ops.step import (
        TELEMETRY_COUNTERS)
    _, _, final, telem = _telemetry_run()
    m = final.metrics
    totals = np.asarray(telem["counters"]).sum(axis=0)
    for i, name in enumerate(TELEMETRY_COUNTERS):
        assert totals[i] == int(getattr(m, name)), name
    np.testing.assert_array_equal(
        np.asarray(telem["msgs_processed"]).sum(axis=0),
        np.asarray(m.msgs_processed))
    np.testing.assert_array_equal(
        np.asarray(telem["lat_hist"]).sum(axis=0),
        np.asarray(m.lat_hist))
    assert int(np.asarray(telem["queue_depth_max"]).max()) \
        == int(m.mb_depth_peak)


def test_telemetry_is_observation_only():
    """The telemetry runner must not perturb the simulation: same
    final machine as the plain runner."""
    from ue22cs343bb1_openmp_assignment_tpu.ops import step
    cfg, system, final, _ = _telemetry_run()
    plain = step.run_cycles(cfg, system.state, 200)
    for f in ("cache_addr", "cache_val", "cache_state", "memory",
              "dir_state", "dir_bitvec", "cur_op", "waiting"):
        np.testing.assert_array_equal(
            np.asarray(getattr(plain, f)), np.asarray(getattr(final, f)),
            err_msg=f)
    assert int(plain.metrics.instrs_retired) \
        == int(final.metrics.instrs_retired)


def test_latency_histogram_counts_waits():
    """Every completed coherence wait lands in exactly one bucket:
    total histogram mass == number of misses that completed (each miss
    waits once), and no mass sits beyond the max observed latency."""
    _, _, final, _ = _telemetry_run(400)
    m = final.metrics
    hist = np.asarray(m.lat_hist)
    completed = int(hist.sum())
    # every retired instruction was a hit or a completed miss-wait
    hits = int(m.read_hits) + int(m.write_hits)
    assert completed == int(m.instrs_retired) - hits
    assert completed > 0


def test_timeseries_rendering():
    _, _, _, telem = _telemetry_run(100)
    series = timeseries.to_series(telem)
    assert series["cycles"] == 100
    assert len(series["series"]["instrs_retired"]) == 100
    assert len(series["series"]["msgs_READ_REQUEST"]) == 100
    summary = timeseries.summarize(telem)
    assert summary["counter_totals"]["instrs_retired"] \
        == sum(series["series"]["instrs_retired"])
    assert set(summary["dir_occupancy_last"]) == {"EM", "S", "U"}


def test_stats_timeseries_cli(tmp_path, monkeypatch, capsys):
    ts_path = tmp_path / "series.json"
    doc = _stats("async", tmp_path, monkeypatch, capsys,
                 ["--timeseries", "--timeseries-out", str(ts_path)])
    tel = doc["extra"]["telemetry"]
    assert tel["counter_totals"]["instrs_retired"] \
        == doc["instrs_retired"]
    assert tel["queue_depth_peak"] == doc["queue_depth_peak"]
    series = json.loads(ts_path.read_text())
    assert series["cycles"] == doc["steps"]


# -- perfetto -------------------------------------------------------------

def test_perfetto_trace_valid_with_tracks(tmp_path, monkeypatch,
                                          capsys):
    out = tmp_path / "trace.json"
    rc, _, err = run_cli(
        ["trace", "mini", "--tests-root", FIXTURES, "--cpu",
         "--perfetto", str(out)], tmp_path, monkeypatch, capsys)
    assert rc == 0 and out.exists()
    doc = perfetto.validate_trace(json.loads(out.read_text()))
    tracks = perfetto.tracks(doc)
    assert set(tracks) == {0, 1, 2, 3}
    for n in range(4):
        assert tracks[n] == {"instr", "msg"}, n
    instr = [e for e in doc["traceEvents"]
             if e.get("cat") == "instr"]
    # 13 instructions in the mini fixture -> 13 instr slices
    assert len(instr) == 13
    # slice names carry the decoded op
    assert all(e["name"].split()[0] in ("RD", "WR") for e in instr)


def test_perfetto_deep_engine_retirement_tracks(tmp_path, monkeypatch,
                                                capsys):
    out = tmp_path / "deep.json"
    rc, _, err = run_cli(
        ["trace", "--workload", "uniform", "--cpu", "--engine", "deep",
         "--perfetto", str(out)], tmp_path, monkeypatch, capsys)
    assert rc == 0
    doc = perfetto.validate_trace(json.loads(out.read_text()))
    instr = [e for e in doc["traceEvents"] if e.get("cat") == "instr"]
    assert len(instr) == 128    # 4 nodes x 32 uniform instructions
    assert not [e for e in doc["traceEvents"] if e.get("cat") == "msg"]


def test_validate_trace_rejects_malformed():
    with pytest.raises(ValueError):
        perfetto.validate_trace({"notTraceEvents": []})
    with pytest.raises(ValueError, match="bad ph"):
        perfetto.validate_trace({"traceEvents": [{"ph": "Z", "pid": 0,
                                                  "name": "x"}]})
    with pytest.raises(ValueError, match="missing ts"):
        perfetto.validate_trace({"traceEvents": [
            {"ph": "X", "pid": 0, "tid": 0, "name": "x", "dur": 1}]})


# -- phase timers ---------------------------------------------------------

def test_phase_timer_accumulates():
    t = PhaseTimer()
    with t.phase("a"):
        pass
    with t.phase("a"):
        pass
    t.add("b", 1.5)
    rep = t.report()
    assert rep["phases"]["a"]["count"] == 2
    assert rep["phases"]["b"] == {"seconds": 1.5, "count": 1}
    assert rep["total_seconds"] >= 1.5
    assert list(rep["phases"]) == ["a", "b"]   # insertion order


def test_stats_phases_flag(tmp_path, monkeypatch, capsys):
    doc = _stats("async", tmp_path, monkeypatch, capsys, ["--phases"])
    phases = doc["extra"]["phases"]["phases"]
    assert {"build", "run", "device_get"} <= set(phases)


# -- checkpoint forward-compat -------------------------------------------

def test_old_checkpoint_without_obs_metrics_loads(tmp_path):
    """Checkpoints written before the obs counters existed resume with
    neutral zeros (same pattern as horizon/order_rank)."""
    from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
    from ue22cs343bb1_openmp_assignment_tpu.models.system import (
        CoherenceSystem)
    from ue22cs343bb1_openmp_assignment_tpu.utils import checkpoint
    cfg = SystemConfig(num_nodes=4)
    system = CoherenceSystem.from_workload(cfg, "uniform",
                                           trace_len=8).run_cycles(5)
    path = tmp_path / "new.npz"
    checkpoint.save_checkpoint(str(path), cfg, system.state)
    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files}
    for k in ("metrics.lat_hist", "metrics.mb_depth_peak"):
        assert k in arrays      # new checkpoints carry the fields
        del arrays[k]           # ...old ones did not
    old = tmp_path / "old.npz"
    with open(old, "wb") as f:
        np.savez(f, **arrays)
    _, state, _ = checkpoint.load_checkpoint(str(old))
    assert np.asarray(state.metrics.lat_hist).sum() == 0
    assert int(state.metrics.mb_depth_peak) == 0
    assert int(state.metrics.instrs_retired) \
        == int(system.state.metrics.instrs_retired)
