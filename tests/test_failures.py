"""Fault injection + stall watchdog (SURVEY §5: the reference's only
fault is a silent overflow drop, and a stranded node spins forever with
no detection, assignment.c:754-762,624-629)."""

import numpy as np

from tests.conftest import REFERENCE_TESTS, requires_reference
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem


def _cross_node_system(drop_prob, fault_seed=0, nodes=16):
    cfg = SystemConfig.scale(num_nodes=nodes, queue_capacity=32,
                             drop_prob=drop_prob)
    return CoherenceSystem.from_workload(
        cfg, "uniform", trace_len=6, seed=4,
        init_kw={"fault_seed": fault_seed}, local_frac=0.0)


def test_full_drop_strands_and_watchdog_detects():
    """drop_prob=1.0: every request dies in flight; requesters stall and
    the watchdog names them with the stuck request."""
    sys_ = _cross_node_system(1.0).run(max_cycles=300)
    assert not sys_.quiescent
    m = sys_.metrics
    assert m["msgs_injected_dropped"] > 0
    stalled = sys_.stalled(threshold=50)
    assert stalled, "watchdog missed stranded nodes"
    assert {"node", "since_cycle", "op", "addr"} <= set(stalled[0])
    # every stalled node really is waiting
    waiting = np.asarray(sys_.state.waiting)
    assert all(waiting[s["node"]] for s in stalled)


def test_injection_is_seed_deterministic():
    a = _cross_node_system(0.3, fault_seed=7).run(max_cycles=200)
    b = _cross_node_system(0.3, fault_seed=7).run(max_cycles=200)
    assert a.metrics == b.metrics
    np.testing.assert_array_equal(np.asarray(a.state.cache_val),
                                  np.asarray(b.state.cache_val))
    c = _cross_node_system(0.3, fault_seed=8).run(max_cycles=200)
    assert (c.metrics["msgs_injected_dropped"]
            != a.metrics["msgs_injected_dropped"]
            or c.metrics["cycles"] != a.metrics["cycles"])


def test_healthy_run_reports_no_stalls():
    sys_ = _cross_node_system(0.0).run()
    assert sys_.quiescent
    assert sys_.stalled(threshold=50) == []
    # waiting_since resets to -1 once unblocked
    assert (np.asarray(sys_.state.waiting_since) == -1).all()
    assert sys_.metrics["msgs_injected_dropped"] == 0


@requires_reference
def test_zero_drop_prob_is_bitfree():
    """drop_prob=0 pays nothing and changes nothing: golden parity."""
    cfg = SystemConfig.reference(drop_prob=0.0)
    sys_ = CoherenceSystem.from_test_dir(
        f"{REFERENCE_TESTS}/test_1", cfg).run()
    import os
    for n in range(4):
        with open(os.path.join(REFERENCE_TESTS, "test_1",
                               f"core_{n}_output.txt")) as f:
            assert sys_.dumps()[n] == f.read()


def test_watchdog_threshold_respected():
    sys_ = _cross_node_system(1.0).run(max_cycles=60)
    assert sys_.stalled(threshold=10_000) == []


@requires_reference
def test_cli_drop_prob_watchdog(tmp_path, capsys):
    from ue22cs343bb1_openmp_assignment_tpu import cli
    rc = cli.main(["test_3", "--tests-root", REFERENCE_TESTS,
                   "--out-dir", str(tmp_path),
                   "--drop-prob", "1.0", "--max-cycles", "300",
                   "--stall-threshold", "50", "--metrics"])
    assert rc == 0
    err = capsys.readouterr().err
    assert "fault injection" in err
    assert "watchdog" in err


def test_cli_resume_overrides_behavior_knobs(tmp_path):
    """--admission/--drop-prob on --resume override the checkpointed
    config (the watchdog's recommended recovery path), while a changed
    --queue-capacity is rejected (shape-determining)."""
    from ue22cs343bb1_openmp_assignment_tpu import cli
    from ue22cs343bb1_openmp_assignment_tpu.utils import checkpoint as ckpt

    ck = str(tmp_path / "r.npz")
    rc = cli.main(["--workload", "uniform", "--nodes", "8",
                   "--queue-capacity", "16", "--drop-prob", "1.0",
                   "--run-cycles", "10", "--save-checkpoint", ck,
                   "--out-dir", str(tmp_path)])
    assert rc == 0
    cfg0, _, _ = ckpt.load_checkpoint(ck)
    assert cfg0.drop_prob == 1.0

    ck2 = str(tmp_path / "r2.npz")
    rc = cli.main(["--resume", ck, "--drop-prob", "0", "--admission", "2",
                   "--run-cycles", "0", "--save-checkpoint", ck2,
                   "--out-dir", str(tmp_path)])
    assert rc == 0
    cfg1, _, _ = ckpt.load_checkpoint(ck2)
    assert cfg1.drop_prob == 0.0 and cfg1.admission_window == 2

    rc = cli.main(["--resume", ck, "--queue-capacity", "32",
                   "--out-dir", str(tmp_path)])
    assert rc == 2


def test_checkpoint_roundtrip_with_faults(tmp_path):
    """fault_key and waiting_since survive checkpoint/resume so the
    injected drop sequence continues identically."""
    mid = _cross_node_system(0.3, fault_seed=7).run_cycles(20)
    p = str(tmp_path / "f.npz")
    mid.save(p)
    resumed = CoherenceSystem.load(p).run_cycles(30)
    straight = mid.run_cycles(30)
    np.testing.assert_array_equal(
        np.asarray(straight.state.fault_key),
        np.asarray(resumed.state.fault_key))
    assert straight.metrics == resumed.metrics
