"""Differential testing: JAX vectorized engine vs native C++ engine.

The two engines implement the same cycle-lockstep semantics through
completely different architectures (masked tensor updates vs sequential
scheduler). Agreement on random cross-node workloads over the full final
state is the strongest correctness evidence short of exhaustive search —
the cross-backend fuzzing layer the reference never had (SURVEY §4).
"""

import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.native.bindings import NativeEngine
from ue22cs343bb1_openmp_assignment_tpu.ops.step import run_to_quiescence
from ue22cs343bb1_openmp_assignment_tpu.state import init_state
from ue22cs343bb1_openmp_assignment_tpu.types import Op


def random_traces(rng, cfg, trace_len, num_active=None):
    num_active = num_active or cfg.num_nodes
    traces = []
    for n in range(cfg.num_nodes):
        tr = []
        if n < num_active:
            for _ in range(trace_len):
                op = Op.WRITE if rng.rand() < 0.5 else Op.READ
                node = rng.randint(cfg.num_nodes)
                block = rng.randint(cfg.mem_size)
                addr = (node << cfg.block_bits) | block
                tr.append((int(op), addr, int(rng.randint(256))))
        traces.append(tr)
    return traces


def run_both(cfg, traces, delays=None, periods=None):
    jx = init_state(cfg, traces,
                    issue_delay=delays, issue_period=periods)
    jx_final = run_to_quiescence(cfg, jx, 50_000)
    assert bool(jx_final.quiescent())

    nat = NativeEngine(cfg)
    nat.load_traces(traces)
    if delays is not None or periods is not None:
        nat.set_schedule(delays, periods)
    nat.run(50_000)
    assert nat.quiescent
    return jx_final, nat.export_state()


FIELDS = ("cache_addr", "cache_val", "cache_state", "memory", "dir_state",
          "dir_bitvec")


def assert_state_equal(jx_final, nat_state, ctx=""):
    for f in FIELDS:
        a = np.asarray(getattr(jx_final, f))
        b = nat_state[f]
        assert np.array_equal(a, b), (
            f"{ctx}: field {f} diverged\njax:\n{a}\nnative:\n{b}")


@pytest.mark.parametrize("seed", range(8))
def test_reference_dims_random_workloads(seed):
    cfg = SystemConfig.reference()
    rng = np.random.RandomState(seed)
    traces = random_traces(rng, cfg, trace_len=24)
    jx_final, nat_state = run_both(cfg, traces)
    assert_state_equal(jx_final, nat_state, f"seed={seed}")


@pytest.mark.parametrize("seed", range(4))
def test_random_schedules_agree(seed):
    """Schedule knobs must steer both engines identically."""
    cfg = SystemConfig.reference()
    rng = np.random.RandomState(100 + seed)
    traces = random_traces(rng, cfg, trace_len=16)
    delays = rng.randint(0, 6, size=cfg.num_nodes).astype(np.int32)
    periods = rng.randint(1, 4, size=cfg.num_nodes).astype(np.int32)
    jx_final, nat_state = run_both(cfg, traces, delays, periods)
    assert_state_equal(jx_final, nat_state, f"sched seed={seed}")


@pytest.mark.parametrize("seed", range(3))
def test_arbitration_permutations_agree(seed):
    """The seedable arbitration rank must steer both engines identically
    (the knob replacing OS lock-acquisition order)."""
    cfg = SystemConfig.reference()
    rng = np.random.RandomState(200 + seed)
    traces = random_traces(rng, cfg, trace_len=16)
    rank = rng.permutation(cfg.num_nodes).astype(np.int32)

    jx = init_state(cfg, traces, arb_rank=rank)
    jx_final = run_to_quiescence(cfg, jx, 50_000)
    nat = NativeEngine(cfg)
    nat.load_traces(traces)
    nat.set_arbitration(rank)
    nat.run(50_000)
    assert_state_equal(jx_final, nat.export_state(), f"arb seed={seed}")


def test_sixteen_nodes_multiword_free():
    """Beyond the reference's 8-node bitvector cap (README.md:60)."""
    cfg = SystemConfig(num_nodes=16, cache_size=4, mem_size=16,
                       queue_capacity=64, max_instrs=16)
    rng = np.random.RandomState(7)
    traces = random_traces(rng, cfg, trace_len=12)
    jx_final, nat_state = run_both(cfg, traces)
    assert_state_equal(jx_final, nat_state, "16 nodes")


def test_forty_nodes_two_word_bitvector():
    """num_nodes > 32 exercises the tiled multi-word sharer bitvector."""
    cfg = SystemConfig(num_nodes=40, cache_size=4, mem_size=16,
                       queue_capacity=64, max_instrs=8)
    assert cfg.bitvec_words == 2
    rng = np.random.RandomState(11)
    traces = random_traces(rng, cfg, trace_len=8)
    jx_final, nat_state = run_both(cfg, traces)
    assert_state_equal(jx_final, nat_state, "40 nodes")


@pytest.mark.parametrize("seed", range(4))
def test_scatter_inv_mode_agrees(seed):
    """The scale path (inv_mode='scatter': home-side invalidation, no
    sharer payload in messages) must agree across engines too — this is
    the semantics bench.py measures at 4096+ nodes."""
    cfg = SystemConfig(num_nodes=32, cache_size=4, mem_size=16,
                       queue_capacity=64, max_instrs=16,
                       inv_mode="scatter")
    assert cfg.msg_bitvec_words == 1
    rng = np.random.RandomState(300 + seed)
    traces = random_traces(rng, cfg, trace_len=12)
    jx_final, nat_state = run_both(cfg, traces)
    assert_state_equal(jx_final, nat_state, f"scatter seed={seed}")


def test_scatter_inv_mode_admission_agrees():
    """Scatter mode composed with the admission window (the bench's
    backpressure configuration)."""
    cfg = SystemConfig(num_nodes=48, cache_size=4, mem_size=16,
                       queue_capacity=16, max_instrs=12,
                       inv_mode="scatter", admission_window=4)
    rng = np.random.RandomState(77)
    traces = random_traces(rng, cfg, trace_len=10)
    jx_final, nat_state = run_both(cfg, traces)
    assert_state_equal(jx_final, nat_state, "scatter+admission")
