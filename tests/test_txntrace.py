"""Causal transaction tracer + critical-path attribution (ISSUE 5).

Everything here runs on the in-repo mini fixture, a hand-built
two-node trace, or small synthetic workloads — no reference tree
needed. The heavy sharded-parity check is slow-marked.
"""

import copy
import json
import os

import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu import cli
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
from ue22cs343bb1_openmp_assignment_tpu.obs import (critpath, perfetto,
                                                    schema, txntrace)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def run_cli(args, tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    rc = cli.main(args)
    out, err = capsys.readouterr()
    return rc, out, err


def _mini_spans():
    cfg = SystemConfig.reference()
    system = CoherenceSystem.from_test_dir(
        os.path.join(FIXTURES, "mini"), cfg)
    total = int(system.run(10_000).metrics["cycles"])
    _, ledger, base = txntrace.capture(cfg, system.state, total,
                                       stop_on_quiescence=False)
    spans, trace = txntrace.reconstruct(
        cfg, ledger, base, arb_rank=np.asarray(system.state.arb_rank))
    return cfg, spans, trace, total, ledger


# -- span reconstruction ---------------------------------------------------

def test_mini_spans_golden():
    """The mini fixture's exact span population: every coherence
    transaction closes, is causally attributed to its issuing fetch,
    and decomposes exactly."""
    _, spans, _, total, _ = _mini_spans()
    assert total == 18
    assert len(spans) == 12
    assert all(s["t_end"] is not None for s in spans)
    assert all(s["attributed"] for s in spans)
    by_type = {}
    for s in spans:
        by_type[s["type"]] = by_type.get(s["type"], 0) + 1
    assert by_type == {"read_miss": 8, "write_miss": 4}


def test_decomposition_sums_exactly():
    """Invariant: the four segments sum to the end-to-end latency for
    EVERY closed span — attributed or not, fixture or workload."""
    _, mini_spans, _, _, _ = _mini_spans()
    cfg = SystemConfig(num_nodes=8)
    system = CoherenceSystem.from_workload(cfg, "uniform",
                                           trace_len=32, seed=3)
    total = int(system.run(10_000).metrics["cycles"])
    _, ledger, base = txntrace.capture(cfg, system.state, total,
                                       stop_on_quiescence=False)
    wl_spans, _ = txntrace.reconstruct(
        cfg, ledger, base, arb_rank=np.asarray(system.state.arb_rank))
    checked = 0
    for s in mini_spans + wl_spans:
        if s["t_end"] is None:
            continue
        assert all(v >= 0 for v in s["segments"].values()), s
        assert sum(s["segments"].values()) == s["e2e"], s
        checked += 1
    assert checked >= 12


# -- hand-built ground truth ----------------------------------------------

def _two_node_system(tmp_path):
    # node 0 issues one write miss to 0x10, whose home is node 1
    # (node nibble above the block nibble); node 1 runs nothing.
    d = tmp_path / "two_node"
    d.mkdir()
    (d / "core_0.txt").write_text("WR 0x10 5\n")
    (d / "core_1.txt").write_text("")
    cfg = SystemConfig.reference(num_nodes=2)
    return cfg, CoherenceSystem.from_test_dir(str(d), cfg)


def test_two_node_known_span(tmp_path):
    """Hand-computable trace: fetch@0 at n0 -> WRITE_REQUEST dequeued
    @1 at home n1 -> REPLY_WR dequeued @2 back at n0. One span, e2e 2,
    all of it in flight."""
    cfg, system = _two_node_system(tmp_path)
    total = int(system.run(100).metrics["cycles"])
    _, ledger, base = txntrace.capture(cfg, system.state, total,
                                       stop_on_quiescence=False)
    spans, _ = txntrace.reconstruct(
        cfg, ledger, base, arb_rank=np.asarray(system.state.arb_rank))
    assert len(spans) == 1
    s = spans[0]
    assert (s["requester"], s["addr"], s["type"]) == (0, 0x10,
                                                      "write_miss")
    assert (s["t_issue"], s["t_end"], s["e2e"]) == (0, 2, 2)
    assert s["attributed"]
    assert s["segments"] == {"queue_wait": 0, "dir_service": 0,
                             "in_flight": 2, "ack_wait": 0}
    assert [h["type"] for h in s["chain"]] == ["WRITE_REQUEST",
                                               "REPLY_WR"]


def test_two_node_known_critical_path(tmp_path):
    """The same trace's critical path, end to end by hand: root
    instr@n0 cycle 0, then two message-edge hops; length exactly 2,
    one attributed cycle on each node, all service_msg."""
    cfg, system = _two_node_system(tmp_path)
    total = int(system.run(100).metrics["cycles"])
    _, ledger, base = txntrace.capture(cfg, system.state, total,
                                       stop_on_quiescence=False)
    _, trace = txntrace.reconstruct(
        cfg, ledger, base, arb_rank=np.asarray(system.state.arb_rank))
    rep = critpath.critical_path(trace, total_cycles=total)
    assert rep["length"] == 2
    assert rep["events_on_path"] == 3
    assert rep["start"] == {"node": 0, "cycle": 0, "kind": "instr"}
    assert rep["end"] == {"node": 0, "cycle": 2, "kind": "msg"}
    assert rep["by_node"] == {"0": 1, "1": 1}
    assert rep["by_phase"] == {"service_instr": 0, "service_msg": 2,
                               "queue_wait": 0, "stall": 0}
    assert [s["edge"] for s in rep["steps"]] == ["root", "msg", "msg"]


# -- critical path on real runs -------------------------------------------

def test_critical_path_mini_golden_and_deterministic():
    cfg, _, trace, total, _ = _mini_spans()
    rep1 = critpath.critical_path(trace, total_cycles=total)
    rep2 = critpath.critical_path(trace, total_cycles=total)
    assert rep1 == rep2
    assert rep1["length"] == 17
    assert rep1["by_node"] == {"0": 1, "1": 10, "2": 6}
    assert rep1["by_phase"] == {"service_instr": 3, "service_msg": 14,
                                "queue_wait": 0, "stall": 0}
    # structural invariants: both attributions sum to the length,
    # which is bounded by the run length
    assert sum(rep1["by_node"].values()) == rep1["length"]
    assert sum(rep1["by_phase"].values()) == rep1["length"]
    assert rep1["length"] <= total


def test_critical_path_sums_on_workload():
    cfg = SystemConfig(num_nodes=8)
    system = CoherenceSystem.from_workload(cfg, "hotspot",
                                           trace_len=24, seed=1)
    total = int(system.run(10_000).metrics["cycles"])
    _, ledger, base = txntrace.capture(cfg, system.state, total,
                                       stop_on_quiescence=False)
    _, trace = txntrace.reconstruct(
        cfg, ledger, base, arb_rank=np.asarray(system.state.arb_rank))
    rep = critpath.critical_path(trace, total_cycles=total)
    assert 0 < rep["length"] <= total
    assert sum(rep["by_node"].values()) == rep["length"]
    assert sum(rep["by_phase"].values()) == rep["length"]
    assert rep["steps"][0]["edge"] == "root"


# -- Perfetto flow export --------------------------------------------------

def test_perfetto_flow_events_bind_to_slices():
    cfg, spans, trace, _, ledger = _mini_spans()
    records = txntrace.ledger_to_records(ledger, trace["base_cycle"])
    flows = perfetto.span_flow_events(spans)
    doc = perfetto.build_trace(records, cfg.num_nodes, flows=flows)
    perfetto.validate_trace(doc)
    by_id = {}
    slices = {(e["pid"], e["tid"], e["ts"])
              for e in doc["traceEvents"] if e.get("ph") == "X"}
    for ev in doc["traceEvents"]:
        if ev.get("ph") in ("s", "t", "f"):
            by_id.setdefault(ev["id"], []).append(ev)
            # every flow point binds to an existing slice
            assert (ev["pid"], ev["tid"], ev["ts"]) in slices, ev
    assert len(by_id) == 12          # one flow per attributed span
    for fid, evs in by_id.items():
        phases = [e["ph"] for e in evs]
        assert phases[0] == "s" and phases[-1] == "f", phases
        assert evs[-1]["bp"] == "e"


# -- schema v1.1 backcompat ------------------------------------------------

def _v1_doc():
    doc = schema.from_sync(
        {"rounds": 3, "instrs_retired": 5, "read_hits": 1,
         "write_hits": 1, "read_misses": 1, "write_misses": 2,
         "upgrades": 0, "conflicts": 0, "evictions": 0,
         "invalidations": 0, "promotions": 0})
    doc["schema"] = schema.SCHEMA_V1
    # a genuine v1 doc predates the v1.2 mb_dropped key from_sync now
    # emits — drop it so the fixture stays a faithful old-schema doc
    doc.pop("mb_dropped", None)
    return doc


def test_schema_v1_accepted_unchanged():
    schema.validate(_v1_doc())


def test_schema_v1_rejects_txn_latency():
    doc = _v1_doc()
    doc["txn_latency"] = {"spans": 0, "open": 0, "by_type": {},
                          "segments_total": {}}
    with pytest.raises(ValueError, match="unknown key"):
        schema.validate(doc)


def test_schema_v11_txn_latency_validated():
    good = _v1_doc()
    good["schema"] = schema.SCHEMA_ID
    good["mb_dropped"] = 0      # required again at the current schema
    good["txn_latency"] = {
        "spans": 2, "open": 1,
        "by_type": {"read_miss": {"count": 2, "p50": 3, "p95": 5,
                                  "p99": 5}},
        "segments_total": {"queue_wait": 1, "dir_service": 0,
                           "in_flight": 6, "ack_wait": 1}}
    schema.validate(good)
    for mutate, frag in [
            (lambda d: d["txn_latency"].update(spans=-1),
             "non-negative"),
            (lambda d: d["txn_latency"]["by_type"].update(x={}),
             "must carry"),
            (lambda d: d["txn_latency"].update(segments_total=3),
             "segments_total")]:
        bad = copy.deepcopy(good)
        mutate(bad)
        with pytest.raises(ValueError, match=frag):
            schema.validate(bad)


# -- flight-recorder embedding --------------------------------------------

def test_flight_incident_embeds_txn_summary(tmp_path):
    from ue22cs343bb1_openmp_assignment_tpu.obs import flight
    cfg = SystemConfig(num_nodes=8)
    system = CoherenceSystem.from_workload(cfg, "uniform",
                                           trace_len=16, seed=0)
    fr = flight.FlightRecorder(cfg, system.state, k=16, chunk=8)
    fr.run(400)
    doc = fr.dump_incident(str(tmp_path / "incident"), "test:hang")
    ts = doc["txn_summary"]
    assert ts is not None and not ts["warm_start"]
    assert ts["spans_closed"] > 0
    assert len(ts["slowest"]) <= 5
    for s in ts["slowest"]:
        assert sum(s["segments"].values()) == s["e2e"]
    # round-trips through the incident file
    loaded = flight.load_incident(str(tmp_path / "incident"))
    assert loaded["txn_summary"] == ts


# -- sharded parity --------------------------------------------------------

@pytest.mark.slow
def test_sharded_ledger_bit_parity():
    """The sharded runner's ledger (and the spans reconstructed from
    it) is bit-identical to the unsharded capture across all attached
    devices (conftest forces 8 virtual CPU devices)."""
    import jax

    from ue22cs343bb1_openmp_assignment_tpu.parallel import (
        make_mesh, shard_state, sharded_step)
    cfg = SystemConfig.scale(num_nodes=64)
    system = CoherenceSystem.from_workload(cfg, "uniform",
                                           trace_len=16, seed=1)
    T = 64
    _, led_u, base = txntrace.capture(cfg, system.state, T,
                                      stop_on_quiescence=False)
    mesh = make_mesh(jax.devices())
    st_sh = shard_state(cfg, mesh, system.state)
    runner = sharded_step.make_sharded_ledger_runner(cfg, mesh, st_sh,
                                                     T)
    _, led_s = runner(st_sh)
    led_s = {k: np.asarray(v) for k, v in led_s.items()}
    assert set(led_u) == set(led_s)
    for k in led_u:
        assert led_u[k].dtype == led_s[k].dtype, k
        assert np.array_equal(led_u[k], led_s[k]), k
    rank = np.asarray(system.state.arb_rank)
    su, _ = txntrace.reconstruct(cfg, led_u, base, arb_rank=rank)
    ss, _ = txntrace.reconstruct(cfg, led_s, base, arb_rank=rank)
    assert su == ss and len(su) > 0


# -- CLI surfaces ----------------------------------------------------------

def test_cli_txns_json(tmp_path, monkeypatch, capsys):
    rc, out, _ = run_cli(
        ["txns", "mini", "--tests-root", FIXTURES, "--cpu", "--json"],
        tmp_path, monkeypatch, capsys)
    assert rc == 0
    doc = json.loads(out)
    assert doc["schema"] == txntrace.SCHEMA_ID
    assert doc["spans_closed"] == 12 and doc["spans_open"] == 0
    assert doc["attributed"] == 12
    tl = doc["txn_latency"]
    assert tl["spans"] == 12
    assert sum(e["count"] for e in tl["by_type"].values()) == 12


def test_cli_critical_path_json(tmp_path, monkeypatch, capsys):
    rc, out, _ = run_cli(
        ["critical-path", "mini", "--tests-root", FIXTURES, "--cpu",
         "--json"], tmp_path, monkeypatch, capsys)
    assert rc == 0
    doc = json.loads(out)
    assert doc["schema"] == critpath.SCHEMA_ID
    assert doc["length"] == 17 and doc["total_cycles"] == 18


def test_cli_stats_txns_block(tmp_path, monkeypatch, capsys):
    rc, out, _ = run_cli(
        ["stats", "mini", "--tests-root", FIXTURES, "--cpu", "--txns"],
        tmp_path, monkeypatch, capsys)
    assert rc == 0
    doc = json.loads(out)
    schema.validate(doc)
    assert doc["schema"] == schema.SCHEMA_ID
    assert doc["txn_latency"]["spans"] == 12
    # sync/native engines reject the ledger flag instead of lying
    rc, _, err = run_cli(
        ["stats", "--workload", "uniform", "--cpu", "--engine", "sync",
         "--txns"], tmp_path, monkeypatch, capsys)
    assert rc == 2 and "--txns" in err
