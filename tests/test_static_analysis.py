"""The static-analysis subsystem's own tests.

Three layers:

* model checker — golden JSON report on the smallest scope (pins the
  state count, coverage and quirk set: any protocol or checker change
  shows up as a golden diff), plus mutation testing: every seeded
  handler bug in analysis.mutations must produce exactly its expected
  finding, and the shipped handlers must stay clean on every scope.
* trace linter — one unit case per banned pattern (each must be
  caught), the host-side escape hatches, the idioms that must NOT
  fire, and the gate itself: 0 findings on ops/ parallel/ models/.
* sanitizer build — slow-marked ASan+UBSan differential run of the
  native engine against the JAX engine (satellite of the analysis
  work: memory bugs in engine.cpp are invisible to the model checker,
  which only drives the JAX handlers).
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

GOLDEN = pathlib.Path(__file__).parent / "golden" / "analyze_2n1a.json"


# ---------------------------------------------------------------------------
# model checker
# ---------------------------------------------------------------------------

def test_golden_report_2n1a():
    from ue22cs343bb1_openmp_assignment_tpu.analysis.model_check import (
        builtin_scopes, check_scope)
    rep = check_scope(builtin_scopes()["2n1a"])
    want = json.loads(GOLDEN.read_text())
    got = json.loads(json.dumps(rep))    # normalize tuples -> lists
    assert got == want, (
        "2n1a model-check report drifted from the golden; if the "
        "protocol change is intentional, regenerate "
        "tests/golden/analyze_2n1a.json and review the diff")


def test_shipped_handlers_clean_on_all_scopes():
    from ue22cs343bb1_openmp_assignment_tpu.analysis.model_check import (
        builtin_scopes, check_scope)
    for name, scope in builtin_scopes().items():
        rep = check_scope(scope)
        assert rep["ok"], (name, [v["name"] for v in rep["violations"]])
        assert rep["stats"]["deadlocked_states"] == 0, name


def test_quirks_are_allowlisted_not_silenced():
    """Sanctioned quirks must still be REPORTED (with a rationale and a
    witness state), not dropped: the allowlist is documentation."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis.model_check import (
        QUIRK_ALLOWLIST, builtin_scopes, check_scope)
    rep = check_scope(builtin_scopes()["3n1a"])
    assert rep["ok"]
    names = {q["name"] for q in rep["quirks"]}
    # the unacked-INV race family is reachable in the 3-node scope
    assert "valid_line_unknown_to_home" in names
    for q in rep["quirks"]:
        assert q["name"] in QUIRK_ALLOWLIST
        assert q["rationale"]
        assert q["example_state"]


@pytest.mark.parametrize("mutation", [
    "skip_em_bitvec_clear",
    "upgrade_keeps_other_sharers",
    "no_wait_clear_on_reply_rd",
    "drop_evict_modified",
    "stale_owner_forward",
    "evict_shared_keeps_bit",
])
def test_mutation_is_caught(mutation):
    """Each seeded handler bug must produce exactly its expected
    finding class — the checker's own regression suite."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis.model_check import (
        builtin_scopes, check_scope)
    from ue22cs343bb1_openmp_assignment_tpu.analysis.mutations import (
        MUTATIONS)
    fn, scope_name, expected = MUTATIONS[mutation]
    rep = check_scope(builtin_scopes()[scope_name], message_phase=fn)
    assert not rep["ok"], f"{mutation} survived the model checker"
    found = {v["name"] for v in rep["violations"]}
    assert expected in found, (mutation, expected, found)
    # counterexamples must come with a replayable trace
    witness = [v for v in rep["violations"] if v["name"] == expected][0]
    assert witness.get("path"), mutation


def test_analyze_cli_exit_codes():
    """`cache-sim analyze` is the CI gate: 0 on the shipped handlers,
    1 under a seeded mutation, 3 when a scope exhausts --max-states
    without a finding (distinct from a pass — nothing was proven).
    In-process to stay fast."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis import runner
    assert runner.main(["--scopes", "2n1a", "--skip-lint", "-q"]) == 0
    assert runner.main(["--mutation", "upgrade_keeps_other_sharers",
                        "--skip-lint", "-q"]) == 1
    assert runner.main(["--scopes", "2n1a", "--skip-lint", "-q",
                        "--max-states", "50"]) == 3
    # a genuine finding wins over budget exhaustion on another scope
    assert runner.main(["--scopes", "2n1a_r,2n1a", "--skip-lint", "-q",
                        "--mutation", "no_wait_clear_on_reply_rd",
                        "--max-states", "50"]) == 1


def test_symmetry_reduction_is_sound_and_effective():
    """The symmetric scopes must verify clean under a nontrivial
    automorphism group, and canonicalization must actually shrink the
    reachable graph (4n1a_sym explores its three symmetric readers
    once, not 3! times)."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis.model_check import (
        ModelChecker, builtin_scopes)
    scopes = builtin_scopes()
    ck = ModelChecker(scopes["4n1a_sym"])
    assert len(ck._group) == 6          # S3 over the reader nodes
    rep = ck.run()
    assert rep["ok"], [v["name"] for v in rep["violations"]]
    assert rep["stats"]["symmetry_group_order"] == 6
    ck2 = ModelChecker(scopes["2n2h"])
    assert len(ck2._group) == 2         # node swap x address swap
    rep2 = ck2.run()
    assert rep2["ok"], [v["name"] for v in rep2["violations"]]
    # asymmetric scopes keep the trivial group (soundness: the node-
    # asymmetric reference memory init admits no automorphisms)
    assert ModelChecker(scopes["2n1a"])._group[0].is_identity
    assert len(ModelChecker(scopes["2n1a"])._group) == 1


# ---------------------------------------------------------------------------
# trace linter
# ---------------------------------------------------------------------------

def _rules(src):
    from ue22cs343bb1_openmp_assignment_tpu.analysis.lint_trace import (
        lint_source)
    return {f.rule for f in lint_source(src, "<case>")}


@pytest.mark.parametrize("src,rule", [
    ("def f(cfg, x):\n    if x > 0:\n        return x\n    return x\n",
     "traced-branch"),
    ("def f(cfg, x):\n    while x > 0:\n        x = x - 1\n    return x\n",
     "traced-branch"),
    ("def f(cfg, x):\n    assert x > 0\n    return x\n", "traced-branch"),
    ("def f(cfg, x):\n    return 1 if x > 0 else 0\n", "traced-branch"),
    ("def f(cfg, n):\n    for i in range(n):\n        pass\n",
     "traced-branch"),
    ("def f(cfg, x):\n    return x.item()\n", "host-sync"),
    ("def f(cfg, x):\n    return x.tolist()\n", "host-sync"),
    ("def f(cfg, x):\n    return int(x)\n", "host-sync"),
    ("def f(cfg, x):\n    return bool(x)\n", "host-sync"),
    ("def f(cfg, x):\n    return f'{x}'\n", "host-sync"),
    ("import numpy as np\ndef f(cfg, x):\n    return np.sum(x)\n",
     "host-call"),
    ("def f(cfg, x):\n    print(x)\n    return x\n", "host-call"),
    ("import jax\ndef f(cfg, x):\n    jax.debug.print('{}', x)\n", "host-call"),
    ("import jax\ndef f(cfg, x):\n    return jax.pure_callback(abs, x, x)\n",
     "host-call"),
    ("import jax.numpy as jnp\ndef f(cfg):\n    return jnp.arange(4)\n",
     "dtype-drift"),
    ("import jax.numpy as jnp\ndef f(cfg):\n    return jnp.zeros((3,))\n",
     "dtype-drift"),
    ("import jax.numpy as jnp\ndef f(cfg):\n    return jnp.ones((3,))\n",
     "dtype-drift"),
    ("import jax.numpy as jnp\ndef f(cfg):\n    return jnp.full((3,), 7)\n",
     "dtype-drift"),
    ("import random\n", "nondeterminism"),
    ("from secrets import token_bytes\n", "nondeterminism"),
    ("def f(cfg, x):\n    import time\n    return x + time.time()\n",
     "nondeterminism"),
    ("import numpy as np\ndef f(cfg, x):\n    return x + np.random.rand()\n",
     "nondeterminism"),
])
def test_linter_catches(src, rule):
    assert rule in _rules(src), f"linter missed {rule} in:\n{src}"


@pytest.mark.parametrize("src", [
    # host-side escape hatches
    'def f(cfg, x):\n    "Host-side check."\n    return int(x)\n',
    "def f(cfg, x):  # lint: host\n    return int(x)\n",
    # identity tests are host-decidable
    "def f(cfg, x, y=None):\n    if y is None:\n        y = x\n    return y\n",
    # static unrolling over containers of traced values is the idiom
    ("def f(cfg, xs):\n    acc = 0\n    for x in [xs, xs]:\n"
     "        acc = acc + x\n    return acc\n"),
    # static metadata kills taint
    ("def f(cfg, x):\n    if x.ndim > 1:\n        return x\n    return x\n"),
    # explicit dtypes are the rule, not a finding
    ("import jax.numpy as jnp\ndef f(cfg):\n"
     "    return jnp.arange(4, dtype=jnp.int32)\n"),
    ("import jax.numpy as jnp\ndef f(cfg):\n"
     "    return jnp.zeros((3,), jnp.int32)\n"),
    # *_like inherits its base dtype
    ("import jax.numpy as jnp\ndef f(cfg, x):\n"
     "    return jnp.zeros_like(x)\n"),
])
def test_linter_stays_quiet(src):
    assert not _rules(src), f"false positive on:\n{src}"


def test_linter_nested_function_inherits_taint():
    src = ("def f(cfg, x):\n"
           "    def body(c, _):\n"
           "        if c > 0:\n"
           "            return c, None\n"
           "        return c, None\n"
           "    return body\n")
    from ue22cs343bb1_openmp_assignment_tpu.analysis.lint_trace import (
        lint_source)
    hits = [f for f in lint_source(src, "<case>")
            if f.rule == "traced-branch"]
    assert hits and hits[0].func == "f.body"


def test_traced_packages_lint_clean():
    """The acceptance gate: ops/ parallel/ models/ carry 0 findings."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis.lint_trace import (
        lint_paths)
    findings = lint_paths()
    assert not findings, "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# sanitizer differential (slow tier)
# ---------------------------------------------------------------------------

_NATIVE_SANITIZED = r"""
import json, sys
import numpy as np
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.native.bindings import NativeEngine
from ue22cs343bb1_openmp_assignment_tpu.types import Op

cfg = SystemConfig.reference()
rng = np.random.RandomState(7)
traces = []
for n in range(cfg.num_nodes):
    tr = []
    for _ in range(24):
        op = Op.WRITE if rng.rand() < 0.5 else Op.READ
        addr = (rng.randint(cfg.num_nodes) << cfg.block_bits) | \
            rng.randint(cfg.mem_size)
        tr.append((int(op), int(addr), int(rng.randint(256))))
    traces.append(tr)
eng = NativeEngine(cfg)
eng.load_traces(traces)
eng.run(50_000)
assert eng.quiescent
out = {k: np.asarray(v).tolist() for k, v in eng.export_state().items()}
json.dump(out, sys.stdout)
"""


@pytest.mark.slow
def test_native_sanitizer_differential():
    """Build engine.cpp with ASan+UBSan (COHERENCE_NATIVE_SANITIZE=1),
    run a random workload in a subprocess, and require (a) no
    sanitizer reports and (b) bit-identical final state vs the JAX
    engine. LD_PRELOAD is needed because python itself is not
    sanitized; leak checking is off (the interpreter never frees)."""
    libasan = subprocess.run(
        ["gcc", "-print-file-name=libasan.so"],
        capture_output=True, text=True).stdout.strip()
    if not libasan or not os.path.exists(libasan):
        pytest.skip("libasan not available")

    env = dict(os.environ,
               COHERENCE_NATIVE_SANITIZE="1",
               LD_PRELOAD=libasan,
               ASAN_OPTIONS="detect_leaks=0,abort_on_error=1",
               UBSAN_OPTIONS="halt_on_error=1",
               JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", _NATIVE_SANITIZED],
                          capture_output=True, text=True, env=env,
                          timeout=600)
    assert proc.returncode == 0, (
        f"sanitized native run failed\nstdout:\n{proc.stdout[-2000:]}\n"
        f"stderr:\n{proc.stderr[-4000:]}")
    assert "ERROR: AddressSanitizer" not in proc.stderr
    assert "runtime error:" not in proc.stderr
    nat = {k: __import__("numpy").asarray(v)
           for k, v in json.loads(proc.stdout).items()}

    import numpy as np

    from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
    from ue22cs343bb1_openmp_assignment_tpu.ops.step import (
        run_to_quiescence)
    from ue22cs343bb1_openmp_assignment_tpu.state import init_state
    from ue22cs343bb1_openmp_assignment_tpu.types import Op

    cfg = SystemConfig.reference()
    rng = np.random.RandomState(7)
    traces = []
    for n in range(cfg.num_nodes):
        tr = []
        for _ in range(24):
            op = Op.WRITE if rng.rand() < 0.5 else Op.READ
            addr = (rng.randint(cfg.num_nodes) << cfg.block_bits) | \
                rng.randint(cfg.mem_size)
            tr.append((int(op), int(addr), int(rng.randint(256))))
        traces.append(tr)
    jx = run_to_quiescence(cfg, init_state(cfg, traces), 50_000)
    assert bool(jx.quiescent())
    for f in ("cache_addr", "cache_val", "cache_state", "memory",
              "dir_state", "dir_bitvec"):
        a, b = np.asarray(getattr(jx, f)), nat[f]
        assert np.array_equal(a, b), f"{f} diverged under sanitizer build"
