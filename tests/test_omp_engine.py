"""`--engine omp`: the reference OpenMP binary as a CLI backend.

BASELINE's north star names a "--backend={omp,jax}" switch at the
cache_simulator entry point; `cli --engine omp` closes it by building
the reference source live (as tests/test_reference_binary_oracle.py
already does for the oracle role) and running it through the same CLI
surface. The test diffs the omp backend's dumps byte-for-byte against
our async JAX engine on a deterministic suite — the two backends must
agree exactly where the reference is deterministic.
"""

import os
import shutil

import pytest

from tests.conftest import REFERENCE_TESTS, requires_reference

REFERENCE_SRC = "/root/reference/assignment.c"

pytestmark = [
    requires_reference,
    pytest.mark.skipif(shutil.which("gcc") is None, reason="needs gcc"),
    pytest.mark.skipif(not os.path.isfile(REFERENCE_SRC),
                       reason="reference source not present"),
]


def test_omp_backend_matches_jax_engine(tmp_path):
    from ue22cs343bb1_openmp_assignment_tpu import cli

    omp_dir = tmp_path / "omp"
    jax_dir = tmp_path / "jax"
    rc = cli.main(["sample", "--tests-root", REFERENCE_TESTS,
                   "--engine", "omp", "--out-dir", str(omp_dir)])
    assert rc == 0
    rc = cli.main(["sample", "--tests-root", REFERENCE_TESTS,
                   "--cpu", "--out-dir", str(jax_dir)])
    assert rc == 0
    for n in range(4):
        theirs = (omp_dir / f"core_{n}_output.txt").read_text()
        ours = (jax_dir / f"core_{n}_output.txt").read_text()
        assert ours == theirs, f"core_{n}: omp backend diverges"


def test_omp_backend_rejects_jax_only_flags(tmp_path):
    from ue22cs343bb1_openmp_assignment_tpu import cli

    rc = cli.main(["sample", "--tests-root", REFERENCE_TESTS,
                   "--engine", "omp", "--out-dir", str(tmp_path),
                   "--arb-seed", "3"])
    assert rc == 2
