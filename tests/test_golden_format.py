"""Golden-dump formatter parity: byte-exact round-trip of every reference
golden file through parse_dump -> format_node_dump (proves the writer
reproduces printProcessorState, assignment.c:853-905, including the
0x%08B binary bitvector and the ' \\t|' cache-row tail)."""

import glob

from tests.conftest import REFERENCE_TESTS, requires_reference

from ue22cs343bb1_openmp_assignment_tpu.utils.golden import (NodeDump,
                                                             format_node_dump,
                                                             parse_dump)


@requires_reference
def test_roundtrip_every_reference_golden():
    paths = sorted(glob.glob(f"{REFERENCE_TESTS}/**/core_*_output.txt",
                             recursive=True))
    assert len(paths) >= 36
    for p in paths:
        text = open(p).read()
        assert format_node_dump(parse_dump(text)) == text, p


def test_format_traps():
    """The format traps survive synthetic state (quirk 8)."""
    import numpy as np
    d = NodeDump(node_id=2,
                 memory=np.arange(16) + 40,
                 dir_state=np.array([0, 1, 2] + [2] * 13),
                 dir_bitvec=np.array([0b11, 0b1000, 0] + [0] * 13,
                                     dtype=object),
                 cache_addr=np.array([0xFF, 0x21, 0x36, 0x0B]),
                 cache_val=np.array([0, 7, 255, 13]),
                 cache_state=np.array([3, 1, 0, 2]))
    out = format_node_dump(d)
    # binary rendering behind a literal 0x prefix
    assert "|   0x00000011   |" in out
    assert "|   0x00001000   |" in out
    # cache rows end in space + hard tab + pipe
    assert "|  EXCLUSIVE \t|" in out
    assert "|   INVALID \t|" in out
    # home-node-prefixed addresses: node 2 block 0 -> 0x20
    assert "|    0  |  0x20   |" in out
