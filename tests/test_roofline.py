"""obs v4: roofline attribution, the exact bytes/instr gate, and the
bench-history dashboard.

Anchors pinned here: cost normalization across every shape XLA has
shipped (dict / list / None / junk), the full bytes-gate rc matrix
(pass 0 / synthetic +20% regression 4 / cross-device incomparable 2),
bench-history schema v1.4 backward compatibility (v1..v1.3 docs
still validate, and may NOT smuggle newer keys), the multichip ingest
(32/32/64/65536/65536/1048576 from the archived dryruns), and the
dashboard golden render from exactly the eleven committed captures.
"""

import copy
import json
import os

import pytest

from ue22cs343bb1_openmp_assignment_tpu import cli
from ue22cs343bb1_openmp_assignment_tpu.obs import (dashboard, history,
                                                    regress, roofline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "golden")
BENCH = [os.path.join(REPO, f"BENCH_r0{i}.json") for i in range(1, 6)]
MULTI = [os.path.join(REPO, f"MULTICHIP_r0{i}.json")
         for i in range(1, 7)]


def run_cli(args, capsys):
    rc = cli.main(args)
    out = capsys.readouterr()
    return rc, out.out, out.err


# -- cost normalization across backend shapes ------------------------------


def test_normalize_cost_dict_passthrough():
    c = roofline.normalize_cost({"flops": 10, "bytes accessed": 40.0})
    assert c == {"flops": 10.0, "bytes accessed": 40.0}


def test_normalize_cost_sums_list_of_computations():
    c = roofline.normalize_cost([{"flops": 10.0}, {"flops": 5.0,
                                                   "bytes accessed": 8}])
    assert c == {"flops": 15.0, "bytes accessed": 8.0}


def test_normalize_cost_none_and_empty_are_unavailable_not_keyerror():
    # the CPU backend under JAX_PLATFORMS=cpu has returned None and []
    # across jax versions; both must collapse to {} (ISSUE 7 satellite)
    assert roofline.normalize_cost(None) == {}
    assert roofline.normalize_cost([]) == {}
    assert roofline.normalize_cost("junk") == {}
    assert roofline.normalize_cost([{"flops": "n/a"}, 7]) == {}


def test_profiler_normalize_delegates_and_marks_unavailable():
    from ue22cs343bb1_openmp_assignment_tpu.obs import profiler
    assert profiler._normalize_cost(None) == {}
    assert profiler._normalize_cost({"flops": 1}) == {"flops": 1.0}


# -- device peaks ----------------------------------------------------------


def test_device_peaks_static_table_and_fallback():
    p = roofline.device_peaks("TPU v5 lite")
    assert p["source"] == "static_table"
    assert p["ridge_flops_per_byte"] == pytest.approx(197e12 / 819e9)
    q = roofline.device_peaks("quantum-abacus-9000")
    assert q["source"] == "generic_fallback"
    assert q["ridge_flops_per_byte"] > 0


# -- classification + cost vector ------------------------------------------


def _rec(name, flops, hbm):
    return {"name": name, "flops": float(flops),
            "hbm_bytes": float(hbm), "output_bytes": 0.0,
            "cost_available": True, "hlo_fingerprint": "f" * 16}


def test_classify_bound_verdicts():
    peaks = roofline.device_peaks("cpu")      # ridge = 2.5 flop/B
    low = roofline.classify(_rec("k", 10, 100), peaks)    # AI = 0.1
    assert low["bound"] == "hbm" and low["ceiling_frac"] < 1
    hi = roofline.classify(_rec("k", 1000, 100), peaks)   # AI = 10
    assert hi["bound"] == "compute" and hi["ceiling_frac"] == 1.0
    na = roofline.classify({"name": "k", "flops": None,
                            "hbm_bytes": None, "output_bytes": None,
                            "cost_available": False}, peaks)
    assert na["bound"] == "cost_unavailable"


def test_cost_vector_bytes_per_instr_arithmetic():
    vec = roofline.cost_vector(_rec("step", 50, 1000), None,
                               steps=8, retired=64)
    assert vec["bytes_per_instr"] == pytest.approx(1000 * 8 / 64)
    assert vec["flops_per_instr"] == pytest.approx(50 * 8 / 64)
    assert vec["cost_available"] and "step" in vec["kernels"]


def test_build_report_ranks_by_traffic_and_flags_per_step():
    recs = [_rec("small", 1, 10), _rec("big", 1, 10_000)]
    doc = roofline.build_report("deep", {"nodes": 4}, recs, "small",
                                steps=2, retired=8, device_kind="cpu")
    assert doc["top_hbm_kernel"] == "big"
    assert [k["name"] for k in doc["kernels"]] == ["big", "small"]
    assert [k["per_step"] for k in doc["kernels"]] == [False, True]
    assert doc["bytes_per_instr"] == pytest.approx(10 * 2 / 8)
    roofline.render_text(doc)   # must not raise
    with pytest.raises(ValueError):
        roofline.build_report("deep", {}, recs, "absent", 1, 1,
                              device_kind="cpu")


# -- the exact bytes/instr gate --------------------------------------------


def _entry(label, bpi=100.0, kernels=None, device="cpu", hlo="a" * 16):
    cost = {"per_step_kernel": "step", "steps": 8, "retired": 64,
            "bytes_per_instr": bpi, "flops_per_instr": 10.0,
            "cost_available": True,
            "kernels": kernels or {"step": {"flops": 80.0,
                                            "hbm_bytes": 800.0,
                                            "output_bytes": 0.0,
                                            "cost_available": True}}}
    return history.entry(
        label=label, source="test",
        result={"metric": "m", "value": 1.0, "unit": "instrs/sec"},
        extra={"engine": "deep", "rep_times_s": [1.0, 1.1, 1.2]},
        device_kind=device, hlo_fingerprint=hlo, cost=cost)


def test_compare_cost_rc_matrix():
    a = _entry("a")
    assert regress.compare_cost(a, copy.deepcopy(a))["verdict"] == \
        "pass"
    # +20% bytes: deterministic regression naming the kernel
    b = _entry("b", bpi=120.0,
               kernels={"step": {"flops": 80.0, "hbm_bytes": 960.0,
                                 "output_bytes": 0.0,
                                 "cost_available": True}})
    rep = regress.compare_cost(a, b)
    assert rep["verdict"] == "regression"
    assert rep["offending_kernels"][0]["name"] == "step"
    regress.format_cost_report(rep)   # must not raise
    # -20%: improvement, never a gate failure
    assert regress.compare_cost(b, a)["verdict"] == "improvement"
    # inside tolerance: pass
    assert regress.compare_cost(a, _entry("c", bpi=101.0),
                                tol_pct=2.0)["verdict"] == "pass"
    # no cost on one side -> incomparable
    plain = _entry("p")
    plain["cost"] = None
    assert regress.compare_cost(a, plain)["verdict"] == "incomparable"
    # cross-device -> incomparable before any numbers are read
    tpu = _entry("t", device="TPU v5e")
    rep = regress.compare_cost(a, tpu)
    assert rep["verdict"] == "incomparable"
    assert "device" in rep["detail"]


def test_compare_times_refuses_cross_device_and_flags_hlo():
    a, b = _entry("a"), _entry("b", device="TPU v5e")
    rep = regress.compare(a, b)
    assert rep["verdict"] == "incomparable"
    assert "device_mismatch" in rep["flags"]
    c = _entry("c", hlo="b" * 16)
    assert "hlo_changed" in regress.compare(a, c)["flags"]


def test_bench_diff_bytes_cli_rc_matrix(tmp_path, capsys):
    hist = str(tmp_path / "h.jsonl")
    history.append(hist, _entry("a"))
    history.append(hist, _entry("b"))
    rc, _, _ = run_cli(["bench-diff", "--history", hist,
                        "--against-last", "--bytes"], capsys)
    assert rc == 0
    rc, _, _ = run_cli(["bench-diff", hist, "--synthetic-bytes", "20"],
                       capsys)
    assert rc == 4
    history.append(hist, _entry("c", device="TPU v5e"))
    rc, out, _ = run_cli(["bench-diff", "--history", hist,
                          "--against-last", "--bytes"], capsys)
    assert rc == 2 and "different device" in out


# -- schema v1.4 backcompat ------------------------------------------------


def test_schema_backcompat_matrix():
    v14 = _entry("x")
    assert v14["schema"] == "cache-sim/bench/v1.4"
    history.validate_entry(v14)
    # a well-formed latency block rides v1.4 (the bench.py --soak row)
    soaked = copy.deepcopy(v14)
    soaked["latency"] = {"p50_ms": 1.0, "p95_ms": 2.0, "p99_ms": 3.0,
                         "arrival_rate": 20.0, "queue_depth_peak": 4,
                         "samples_ms": [0.5, 1.0, 2.0, 3.0]}
    history.validate_entry(soaked)
    # v1.3: serve allowed, latency NOT
    v13 = copy.deepcopy(v14)
    v13["schema"] = "cache-sim/bench/v1.3"
    del v13["latency"]
    v13["serve"] = {"slots": 8, "jobs": 16, "waves": 2,
                    "padding_waste": 0.125}
    history.validate_entry(v13)
    v13_bad = copy.deepcopy(v13)
    v13_bad["latency"] = soaked["latency"]
    with pytest.raises(ValueError, match="unknown key: latency"):
        history.validate_entry(v13_bad)
    # v1.2: cost allowed, serve NOT
    v12 = copy.deepcopy(v13)
    v12["schema"] = "cache-sim/bench/v1.2"
    del v12["serve"]
    history.validate_entry(v12)
    v12_bad = copy.deepcopy(v12)
    v12_bad["serve"] = {"slots": 1, "jobs": 1, "waves": 1,
                        "padding_waste": 0.0}
    with pytest.raises(ValueError, match="unknown key: serve"):
        history.validate_entry(v12_bad)
    # v1.1: comparability keys allowed, cost NOT
    v11 = copy.deepcopy(v12)
    v11["schema"] = "cache-sim/bench/v1.1"
    del v11["cost"]
    history.validate_entry(v11)
    v11_bad = copy.deepcopy(v11)
    v11_bad["cost"] = {"kernels": {}}
    with pytest.raises(ValueError, match="unknown key: cost"):
        history.validate_entry(v11_bad)
    # v1: no generation of optional keys
    v1 = copy.deepcopy(v12)
    v1["schema"] = "cache-sim/bench/v1"
    for k in ("cost", "device_kind", "hlo_fingerprint"):
        del v1[k]
    history.validate_entry(v1)
    v1_bad = copy.deepcopy(v1)
    v1_bad["device_kind"] = "cpu"
    with pytest.raises(ValueError, match="unknown key: device_kind"):
        history.validate_entry(v1_bad)
    # malformed cost is rejected even on v1.4
    bad = copy.deepcopy(v14)
    bad["cost"] = {"bytes_per_instr": -1}
    with pytest.raises(ValueError):
        history.validate_entry(bad)
    # malformed serve blocks are rejected on v1.3
    for block in ({"slots": -1, "jobs": 1, "waves": 1,
                   "padding_waste": 0.0},
                  {"slots": 1, "jobs": 1, "waves": 1,
                   "padding_waste": 1.5},
                  ["not", "a", "dict"]):
        bad = copy.deepcopy(v13)
        bad["serve"] = block
        with pytest.raises(ValueError, match="serve"):
            history.validate_entry(bad)
    # malformed latency blocks are rejected on v1.4
    for block in ({"p50_ms": 3.0, "p95_ms": 2.0, "p99_ms": 4.0,
                   "arrival_rate": 20.0, "queue_depth_peak": 0},
                  {"p50_ms": 1.0, "p95_ms": 2.0, "p99_ms": 3.0,
                   "arrival_rate": 20.0, "queue_depth_peak": 0,
                   "bogus": 1},
                  {"p50_ms": 1.0, "p95_ms": 2.0, "p99_ms": 3.0,
                   "arrival_rate": 20.0, "queue_depth_peak": 0,
                   "samples_ms": [1.0, -2.0]},
                  ["not", "a", "dict"]):
        bad = copy.deepcopy(v14)
        bad["latency"] = block
        with pytest.raises(ValueError, match="latency"):
            history.validate_entry(bad)


def test_archived_v1_ingest_still_validates():
    # the adapters emit the current schema id, and archived captures
    # keep loading (the whole point of the compat window)
    doc = history.ingest_capture(BENCH[2])
    history.validate_entry(doc)


# -- multichip ingest ------------------------------------------------------


def test_ingest_multichip_scaling_ladder():
    vals = [history.ingest_multichip(p) for p in MULTI]
    assert [int(v["value"]) for v in vals] == [32, 32, 64, 65536,
                                               65536, 1048576]
    assert vals[0]["label"] == "mc-r01"
    assert all(v["config"]["kind"] == "multichip" for v in vals)
    assert all(v["rep_times_s"] == [] for v in vals)


def test_ingest_multichip_rejects_non_multichip(tmp_path):
    p = tmp_path / "x.json"
    p.write_text(json.dumps({"nope": 1}))
    with pytest.raises(ValueError, match="n_devices"):
        history.ingest_multichip(str(p))
    p.write_text(json.dumps({"n_devices": 4, "tail": "no markers"}))
    with pytest.raises(ValueError, match="nodes"):
        history.ingest_multichip(str(p))


# -- dashboard -------------------------------------------------------------


def _archive_entries():
    return ([history.ingest_capture(p) for p in BENCH]
            + [history.ingest_multichip(p) for p in MULTI])


def test_dashboard_model_from_archive():
    m = dashboard.build_model(_archive_entries())
    assert len(m["headline"]) == 5
    # the 1.9e7 plateau the ISSUE names
    assert m["headline"][-1]["value"] == pytest.approx(1.896e7,
                                                       rel=0.01)
    assert m["target"] == pytest.approx(1e8)
    assert [int(s["nodes"]) for s in m["scaling"]] == [32, 32, 64,
                                                       65536, 65536,
                                                       1048576]
    verdicts = [v["verdict"] for v in m["verdicts"]]
    assert "noise" in verdicts            # r03 -> r04, PERF.md's call
    assert "mesi/uniform" in m["cells"]
    assert m["roofline"] == []            # archives predate v1.2


def test_dashboard_roofline_points_from_cost_vector():
    entries = _archive_entries() + [_entry("live")]
    m = dashboard.build_model(entries)
    assert len(m["roofline"]) == 1
    pt = m["roofline"][0]
    assert pt["kernel"] == "step"
    assert pt["ai"] == pytest.approx(80.0 / 800.0)
    # both artifacts must render the scatter without raising
    assert "roofline" in dashboard.render_html(m)
    assert "| live | step |" in dashboard.render_markdown(m)


def test_dashboard_serving_series():
    serve_e = history.entry(
        label="serve@8", source="test",
        result={"metric": "serve jobs/sec", "value": 550.0,
                "unit": "jobs/sec"},
        extra={"engine": "async", "rep_times_s": [0.03]},
        device_kind="cpu",
        serve={"slots": 8, "jobs": 16, "waves": 2,
               "padding_waste": 0.125})
    m = dashboard.build_model(_archive_entries() + [serve_e])
    assert len(m["serving"]) == 1
    assert m["serving"][0]["slots"] == 8
    assert m["serving"][0]["value"] == pytest.approx(550.0)
    assert "Serving throughput" in dashboard.render_html(m)
    assert "| serve@8 | 8 |" in dashboard.render_markdown(m)
    # instrs/sec entries never leak into the serving series
    m2 = dashboard.build_model(_archive_entries())
    assert m2["serving"] == []
    assert "no serving entries" in dashboard.render_markdown(m2)


def test_dashboard_golden_render(tmp_path, capsys):
    html = str(tmp_path / "dashboard.html")
    md = str(tmp_path / "dashboard.md")
    rc, _, err = run_cli(["dashboard"] + BENCH + MULTI
                         + ["--html", html, "--md", md], capsys)
    assert rc == 0 and "wrote" in err
    for got, want in ((html, "dashboard.html"), (md, "dashboard.md")):
        with open(got) as f, open(os.path.join(GOLDEN, want)) as g:
            assert f.read() == g.read(), (
                f"{want} drifted from tests/golden/{want} — if the "
                "change is intentional, regenerate with: cache-sim "
                "dashboard BENCH_r0*.json MULTICHIP_r0*.json "
                f"--html/--md tests/golden/{want}")
    with open(html) as f:
        page = f.read()
    assert "target 1e+08 instrs/sec" in page     # the north-star line
    assert page.count("<svg") == 2               # headline + scaling


def test_dashboard_cli_usage_errors(capsys):
    rc, _, err = run_cli(["dashboard"], capsys)
    assert rc == 2 and "provide" in err
    rc, _, err = run_cli(["dashboard", BENCH[0]], capsys)
    assert rc == 2 and "--html" in err


# -- perf-report CLI -------------------------------------------------------


def test_perf_report_cli_smoke(capsys):
    rc, out, _ = run_cli(["perf-report", "--engine", "async",
                          "--nodes", "2", "--trace-len", "4",
                          "--chunk", "4", "--json"], capsys)
    assert rc == 0
    doc = json.loads(out)
    assert doc["schema"] == "cache-sim/perfreport/v1"
    assert doc["per_step_kernel"] == "step.cycle"
    names = [k["name"] for k in doc["kernels"]]
    assert "step.cycle" in names and "mailbox.dequeue" in names
    if doc["cost_available"]:   # CPU exposes the cost model today
        assert doc["bytes_per_instr"] > 0
        assert doc["bound"] in ("hbm", "compute")
        assert doc["top_hbm_kernel"] in names
    else:
        assert doc["bound"] == "cost_unavailable"
    assert "timing" not in doc   # deterministic by default
