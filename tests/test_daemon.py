"""daemon/: the persistent serving front door.

The load-bearing gates, in rough order of importance:

- **Parity through the daemon**: every job extracted from a bucketed,
  continuously-admitted chunk wave — including one swapped in
  MID-WAVE next to in-flight slot-mates — dumps byte-identical to its
  solo run. This is the PR-9 fixpoint argument surviving the daemon's
  whole scheduler (and the socket).
- **Bucketing**: at most ``max_buckets`` slot classes per protocol,
  and on a bimodal shape mix the budget-weighted padding waste is
  STRICTLY below the single-max-shape counterfactual the stats doc
  carries.
- **Lanes**: under contention the interactive lane's p95 end-to-end
  latency beats batch (weighted admission), without starving batch.
- **Backpressure**: a full lane rejects explicitly; ``mb_dropped``
  stays zero — transport-level refusal never reaches the machines.
- **Determinism**: under a VirtualClock two identical schedules emit
  byte-identical trace and stats docs.
"""

import dataclasses
import json
import threading

import pytest

from ue22cs343bb1_openmp_assignment_tpu import serve
from ue22cs343bb1_openmp_assignment_tpu.daemon import bucketing
from ue22cs343bb1_openmp_assignment_tpu.daemon.client import DaemonClient
from ue22cs343bb1_openmp_assignment_tpu.daemon.core import (DaemonCore,
                                                            drive)
from ue22cs343bb1_openmp_assignment_tpu.daemon.server import (
    DaemonServer, parse_lane_weights)
from ue22cs343bb1_openmp_assignment_tpu.obs.clock import VirtualClock
from ue22cs343bb1_openmp_assignment_tpu.serve import JobSpec


def _spec(name, nodes=2, trace_len=4, workload="uniform", seed=0):
    return JobSpec(name=name, workload=workload, nodes=nodes,
                   trace_len=trace_len, seed=seed)


def _bimodal(n, small=(2, 4), big=(8, 16)):
    """n jobs alternating a small and a big shape (worst case for a
    single slot class, the shape mix bucketing exists for)."""
    out = []
    for i in range(n):
        nodes, tl = small if i % 2 == 0 else big
        out.append(_spec(f"bi{i:03d}", nodes=nodes, trace_len=tl,
                         workload=("uniform", "hotspot")[i % 2],
                         seed=i))
    return out


# -- bucketing unit --------------------------------------------------------


def test_choose_buckets_bimodal_exact():
    hist = {(2, 4): 10, (8, 16): 3}
    assert bucketing.choose_buckets(hist, 2) == [(2, 4), (8, 16)]
    # k=1 must collapse to the covering max shape
    assert bucketing.choose_buckets(hist, 1) == [(8, 16)]


def test_bucket_for_picks_min_area_cover():
    buckets = [(2, 8), (4, 4), (8, 16)]
    assert bucketing.bucket_for((2, 4), buckets) == (2, 8)
    assert bucketing.bucket_for((3, 4), buckets) == (4, 4)
    assert bucketing.bucket_for((8, 16), buckets) == (8, 16)
    assert bucketing.bucket_for((9, 1), buckets) is None


def test_bucketing_waste_improves_with_classes():
    hist = {(2, 4): 8, (4, 8): 4, (8, 16): 2}
    w1 = bucketing.padding_waste(hist, bucketing.choose_buckets(hist, 1))
    w2 = bucketing.padding_waste(hist, bucketing.choose_buckets(hist, 2))
    w3 = bucketing.padding_waste(hist, bucketing.choose_buckets(hist, 3))
    assert w3 == 0.0                       # one class per shape
    assert w3 < w2 < w1                    # strictly better each step


def test_parse_lane_weights():
    assert parse_lane_weights("interactive=4,batch=1") == {
        "interactive": 4, "batch": 1}
    with pytest.raises(ValueError, match="lane=N"):
        parse_lane_weights("interactive")
    with pytest.raises(ValueError, match=">= 1"):
        parse_lane_weights("batch=0")


# -- core: parity, bucketing, lanes, backpressure --------------------------


def test_core_parity_with_mid_wave_swap():
    """5 mixed-shape jobs through 2 slots of ONE bucket (the small
    jobs padded into the big class finish chunks before their
    slot-mates): continuous admission must swap at least one job in
    mid-wave, and EVERY dump must be byte-identical to the solo run
    anyway."""
    specs = _bimodal(5)
    # chunk=8 shares the padded (8,16)-slots2 wave compile with the
    # bucket-budget test below (tier-1 time budget)
    core = DaemonCore(slots=2, max_buckets=1, chunk=8,
                      clock=VirtualClock())
    resp = drive(core, [(0.0, s, ("interactive", "batch")[i % 2])
                        for i, s in enumerate(specs)])
    assert all(r["status"] == "queued" for r in resp)
    assert core.mid_wave_swaps >= 1, (
        "no mid-wave swap happened — the schedule no longer exercises "
        "continuous admission")
    for s in specs:
        r = core.result(s.name)
        assert r["status"] == "done" and r["quiesced"]
        assert r["dumps"] == serve.solo_dumps(s), (
            f"daemon dump != solo for {s.name} (bucket {r['bucket']})")


def test_core_bucket_budget_and_weighted_waste_beats_single_shape():
    specs = _bimodal(8)
    core = DaemonCore(slots=2, max_buckets=2, chunk=8,
                      clock=VirtualClock(), keep_dumps=False)
    drive(core, [(0.001 * i, s, "batch") for i, s in enumerate(specs)])
    st = core.stats()
    assert len(st["buckets"]) <= 2         # the class budget held
    assert {b["bucket"] for b in st["buckets"]} == {"mesi:2x4",
                                                    "mesi:8x16"}
    # the acceptance inequality: budget-weighted waste strictly below
    # the single-max-shape counterfactual in the same stats doc
    assert st["padding_waste"] < st["single_shape_padding_waste"]
    assert st["jobs"]["done"] == len(specs)


def test_core_lane_priority_under_contention():
    """Both lanes saturated on ONE slot: the 4:1 weights must put the
    interactive p95 strictly under batch — and batch must still
    finish (no starvation)."""
    arrivals = []
    for i in range(6):
        arrivals.append((0.0, _spec(f"i{i:02d}", seed=i), "interactive"))
        arrivals.append((0.0, _spec(f"b{i:02d}", seed=10 + i), "batch"))
    core = DaemonCore(slots=1, max_buckets=1, chunk=8,
                      clock=VirtualClock(), keep_dumps=False)
    drive(core, arrivals)
    st = core.stats()
    il = st["lanes"]["interactive"]
    bl = st["lanes"]["batch"]
    assert il["done"] == 6 and bl["done"] == 6
    assert il["latency"]["p95_ms"] < bl["latency"]["p95_ms"]


def test_core_backpressure_rejects_never_drops():
    core = DaemonCore(slots=1, max_buckets=1, chunk=8, lane_depth=2,
                      clock=VirtualClock(), keep_dumps=False)
    resp = [core.submit(_spec(f"j{i}", seed=i), lane="batch")
            for i in range(5)]
    statuses = [r["status"] for r in resp]
    assert statuses == ["queued", "queued", "rejected", "rejected",
                        "rejected"]
    for r in resp[2:]:
        assert r["ok"] is False and "queue full" in r["reason"]
    while not core.idle():
        core.pump()
    st = core.stats()
    assert st["jobs"] == {"submitted": 2, "rejected": 3, "done": 2,
                          "quiesced": 2}
    # backpressure is a transport refusal: the simulated machines
    # never saw the rejected jobs, so the quirk-6 counter stays zero
    assert st["mb_dropped"] == 0
    # a previously-rejected name may retry once there is room
    assert core.submit(_spec("j2", seed=2))["status"] == "queued"


def test_core_drain_flushes_then_rejects():
    core = DaemonCore(slots=2, max_buckets=1, chunk=8,
                      clock=VirtualClock(), keep_dumps=False)
    for i in range(3):
        assert core.submit(_spec(f"d{i}", seed=i))["status"] == "queued"
    core.drain()
    r = core.submit(_spec("late"))
    assert r["status"] == "rejected" and r["reason"] == "draining"
    while not core.idle():
        core.pump()
    st = core.stats()
    assert st["draining"] is True
    assert st["jobs"]["done"] == 3 and st["jobs"]["quiesced"] == 3


def test_core_pump_survives_mid_pump_bucket_growth():
    """Regression: a slot freeing MID-pump admits the head-of-line
    blocked job and then grows an idle later-keyed bucket for the job
    queued behind it — the growth deletes a key the pump loop's
    snapshot still holds, which used to KeyError (killing the
    scheduler thread, and with it the whole daemon)."""
    # chunk=8 shares the (2,4)-slots1 compiled wave signature with the
    # lane-priority and retention tests (tier-1 time budget)
    core = DaemonCore(slots=1, max_buckets=2, chunk=8,
                      clock=VirtualClock())
    # bucket ('mesi', 4, 2): run jb to completion so it sits idle
    assert core.submit(_spec("jb", nodes=4, trace_len=2))["status"] \
        == "queued"
    while not core.idle():
        core.pump()
    # bucket ('mesi', 2, 4) — sorts BEFORE the idle one — takes ja;
    # j3 (same lane, same shape) is head-of-line blocked behind it;
    # j4 fits neither class and its cheapest cover victim is the idle
    # (4, 2) bucket
    for name, nodes, tl in (("ja", 2, 4), ("j3", 2, 4), ("j4", 4, 3)):
        assert core.submit(_spec(name, nodes=nodes, trace_len=tl,
                                 seed=7))["status"] == "queued"
    while not core.idle():
        core.pump()                  # KeyError here before the fix
    assert core.bucket_growths == 1
    for name in ("jb", "ja", "j3", "j4"):
        r = core.result(name)
        assert r["status"] == "done" and r["quiesced"], name


def test_core_result_retention_is_bounded():
    """A long-lived daemon keeps only the newest ``retain_results``
    terminal jobs' results/statuses/spans; lifetime counters stay
    exact."""
    core = DaemonCore(slots=1, max_buckets=1, chunk=8,
                      clock=VirtualClock(), keep_dumps=False,
                      retain_results=3)
    specs = [_spec(f"r{i}", seed=i) for i in range(6)]
    drive(core, [(0.0, s, "batch") for s in specs])
    st = core.stats()
    assert st["jobs"]["done"] == 6 and st["jobs"]["quiesced"] == 6
    assert st["retain_results"] == 3 and st["results_evicted"] == 3
    assert len(core.results) == 3
    assert len(core.book.spans()) == 3
    # single lane + single slot: completion order IS r0..r5, so the
    # oldest three evicted, the newest three retained
    for name in ("r0", "r1", "r2"):
        assert core.status(name)["status"] == "unknown"
        assert core.result(name)["ok"] is False
    for name in ("r3", "r4", "r5"):
        assert core.result(name)["status"] == "done"
    # an evicted name is submittable again (names recycle over a
    # daemon's lifetime)
    assert core.submit(_spec("r0"))["status"] == "queued"


def test_core_blocked_lane_keeps_its_credit():
    """A head-of-line-blocked lane must NOT pay the WRR payback for
    admissions that never happened: its credit accumulates while
    blocked (catch-up once unblocked) instead of drifting negative
    and ceding its configured share."""
    core = DaemonCore(slots=1, max_buckets=2, chunk=4,
                      clock=VirtualClock(), keep_dumps=False)
    core.submit(_spec("i0"), lane="interactive")
    core._admit()                    # i0 owns the one (2, 4) slot
    core.submit(_spec("i1", seed=1), lane="interactive")
    for i in range(3):
        core.submit(_spec(f"b{i}", nodes=4, trace_len=2, seed=10 + i),
                    lane="batch")
    for _ in range(4):               # i1 head-of-line blocked each turn
        core._admit()
    assert core.lanes["interactive"].credit > 0, (
        "blocked interactive lane was charged for admissions that "
        "never happened (credit drifted negative)")


def test_core_bucket_growth_carries_lifetime_counters():
    """Growing a bucket replaces its class: the grown bucket's stats
    must include the retired victim's admitted/chunks history."""
    core = DaemonCore(slots=1, max_buckets=1, chunk=8,
                      clock=VirtualClock(), keep_dumps=False)
    # (4,2) -> grown (4,3): the same compiled wave signatures the
    # mid-pump-growth test exercises (tier-1 time budget)
    drive(core, [(0.0, _spec("g0", nodes=4, trace_len=2), "batch")])
    before = core.stats()["buckets"][0]
    assert before["admitted"] == 1 and before["chunks"] >= 1
    drive(core, [(0.0, _spec("g1", nodes=4, trace_len=3, seed=1),
                  "batch")])
    st = core.stats()
    assert core.bucket_growths == 1
    [b] = st["buckets"]
    assert b["bucket"] == "mesi:4x3"
    assert b["admitted"] == 2              # g0 rode the retired class
    assert b["chunks"] > before["chunks"]


def test_core_duplicate_and_unknown_lane_errors():
    core = DaemonCore(clock=VirtualClock())
    assert core.submit(_spec("a"))["status"] == "queued"
    r = core.submit(_spec("a"))
    assert r["ok"] is False and "duplicate" in r["error"]
    r = core.submit(_spec("b"), lane="bulk")
    assert r["ok"] is False and "unknown lane" in r["error"]


def test_core_virtual_clock_docs_byte_identical():
    """Same schedule, fresh core, VirtualClock: the trace doc AND the
    stats doc serialize byte-identically — every scheduler decision
    is a pure function of the schedule."""
    def run():
        core = DaemonCore(slots=2, max_buckets=2, chunk=4,
                          clock=VirtualClock(), keep_dumps=False)
        drive(core, [(0.002 * i, s, ("interactive", "batch")[i % 2])
                     for i, s in enumerate(_bimodal(6))])
        return (json.dumps(core.trace_doc(), sort_keys=True),
                json.dumps(core.stats(), sort_keys=True))
    t1, s1 = run()
    t2, s2 = run()
    assert t1 == t2
    assert s1 == s2
    spans = json.loads(t1)["spans"]
    assert {s["lane"] for s in spans} == {"interactive", "batch"}
    assert all("bucket" in s for s in spans)


# -- socket layer ----------------------------------------------------------


def _start_server(tmp_path, **core_kw):
    sock = str(tmp_path / "daemon.sock")
    core_kw.setdefault("slots", 2)
    core_kw.setdefault("chunk", 8)
    server = DaemonServer(DaemonCore(**core_kw), sock, quiet=True)
    th = threading.Thread(target=server.run, daemon=True)
    th.start()
    return sock, server, th


def test_socket_submit_parity_stats_drain_shutdown(tmp_path):
    sock, server, th = _start_server(tmp_path)
    spec = _spec("net0", nodes=2, trace_len=4)
    with DaemonClient(sock) as c:
        c.wait_up()
        assert c.ping()["ok"]
        assert c.submit(spec, lane="interactive")["status"] == "queued"
        r = c.wait("net0", timeout_s=120.0)
        assert r["status"] == "done" and r["quiesced"]
        assert r["dumps"] == serve.solo_dumps(spec)
        st = c.stats()
        assert st["jobs"]["done"] == 1
        assert st["lanes"]["interactive"]["done"] == 1
        spans = c.trace()["spans"]
        assert spans[0]["lane"] == "interactive"
        assert spans[0]["bucket"] == "mesi:2x4"
        d = c.drain()
        assert d["drained"] and d["jobs_done"] == 1
        c.shutdown()
    th.join(10.0)
    assert not th.is_alive()
    import os
    assert not os.path.exists(sock)        # unix socket unlinked


def test_socket_bad_requests_keep_connection(tmp_path):
    sock, server, th = _start_server(tmp_path)
    try:
        with DaemonClient(sock) as c:
            c.wait_up()
            r = c.request(op="nope")
            assert r["ok"] is False and "unknown op" in r["error"]
            r = c.request(op="submit", spec={"name": "x", "bogus": 1})
            assert r["ok"] is False and "bad job spec" in r["error"]
            # the connection survived both errors
            assert c.ping()["ok"]
            assert c.status("ghost")["status"] == "unknown"
            assert c.result("ghost")["status"] == "unknown"
    finally:
        server.stop()
        th.join(10.0)


def test_soak_daemon_through_socket(tmp_path):
    """The --daemon soak transport end to end: open-loop release over
    the socket, client-observed latency block, daemon trace embedded,
    and the doc feeds dump_incident unchanged."""
    from ue22cs343bb1_openmp_assignment_tpu import soak
    sock, server, th = _start_server(tmp_path)
    try:
        arrivals = soak.soak_stream(40.0, 0.2, nodes=2, trace_len=4,
                                    seed=3)
        doc = soak.soak_daemon(arrivals, sock, arrival_rate=40.0)
        assert doc["transport"] == "daemon"
        assert doc["jobs_quiesced"] == doc["jobs_total"] == len(arrivals)
        assert doc["rejected"] == [] and doc["mb_dropped"] == 0
        assert doc["latency"]["jobs"] == len(arrivals)
        assert len(doc["samples_ms"]) == len(arrivals)
        assert set(doc["lane_latency"]) == {"interactive", "batch"}
        # server-side spans rode along, annotated
        assert all("lane" in s for s in doc["trace"]["spans"])
        soak.dump_incident(
            str(tmp_path / "incident"), doc,
            [{"metric": "p95_ms", "observed_ms": 1.0, "limit_ms": 0.5}])
        loaded = soak.load_incident(str(tmp_path / "incident"))
        assert loaded["schema"] == soak.INCIDENT_SCHEMA_ID
    finally:
        server.stop()
        th.join(10.0)


# -- the acceptance soak ---------------------------------------------------


@pytest.mark.slow
def test_sixty_virtual_second_mixed_lane_soak():
    """ISSUE acceptance: a mixed interactive+batch bimodal stream
    sustained over >= 60 virtual seconds of daemon uptime — SLO-grade
    latency present, zero mb_dropped, interactive p95 < batch p95
    under contention, bucketed weighted waste strictly below the
    single-max-shape counterfactual, and EVERY job's dump
    byte-identical to its solo run."""
    import numpy as np
    rng = np.random.default_rng(42)
    t, arrivals = 0.0, []
    i = 0
    while t < 65.0:                        # arrivals span > 60 s uptime
        nodes, tl = ((2, 4), (8, 16))[i % 2]
        spec = _spec(f"s{i:03d}", nodes=nodes, trace_len=tl,
                     workload=("uniform", "hotspot")[i % 3 == 1],
                     seed=i)
        arrivals.append((t, spec, ("interactive", "batch")[i % 2]))
        t += float(rng.exponential(1.0 / 2.0))     # ~2 jobs/s
        i += 1
    core = DaemonCore(slots=2, max_buckets=2, chunk=8,
                      clock=VirtualClock(), keep_dumps=True)
    resp = drive(core, arrivals)
    assert all(r["status"] == "queued" for r in resp)
    st = core.stats()
    assert st["uptime_s"] >= 60.0
    assert st["jobs"]["done"] == st["jobs"]["quiesced"] == len(arrivals)
    assert st["mb_dropped"] == 0
    assert (st["lanes"]["interactive"]["latency"]["p95_ms"]
            <= st["lanes"]["batch"]["latency"]["p95_ms"])
    assert st["padding_waste"] < st["single_shape_padding_waste"]
    for _, spec, _ in arrivals:
        assert core.result(spec.name)["dumps"] == serve.solo_dumps(spec)
