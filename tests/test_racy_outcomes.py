"""Every accepted racy outcome is reachable by a pinned schedule.

The reference's retry harness can land on any of test_3/run_{1,2} and
test_4/run_{1..4} (``test3.sh:6-33``, ``test4.sh:6-32``); this repo
replaces wall-clock retries with explicit schedule knobs. Here each
accepted run is pinned to ONE witness schedule (found by
``scripts/search_racy.py`` sweeping delays x periods x arbitration on
the native engine) and verified on BOTH the native C++ engine and the
async JAX engine — the two message-level implementations must realize
the same outcome under the same knobs (they are lockstep-identical,
tests/test_native_differential_contended.py).
"""

import os
import types

import numpy as np
import pytest

from tests.conftest import REFERENCE_TESTS, requires_reference

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.native.bindings import NativeEngine
from ue22cs343bb1_openmp_assignment_tpu.ops.step import run_to_quiescence
from ue22cs343bb1_openmp_assignment_tpu.state import init_state
from ue22cs343bb1_openmp_assignment_tpu.utils.golden import (
    format_node_dump, state_to_dumps)
from ue22cs343bb1_openmp_assignment_tpu.utils.search import (
    load_accepted_named)
from ue22cs343bb1_openmp_assignment_tpu.utils.trace import load_test_dir

# (suite, accepted run) -> (issue delays, issue periods, arb rank)
# witnesses found by scripts/search_racy.py + the targeted large-delay
# search; periods/rank None = default. The interesting ones: test_4's
# run_3/run_4 flip the 0x20 race (cores 1-2 must re-read AFTER core
# 3's 14th-instruction write), which needs delays ~40-50 — and run_3
# additionally needs core 2's first read (the 0x11 race) to stay
# early while its fifth goes late, i.e. a PERIOD stretch, not a delay.
WITNESSES = {
    ("test_3", "run_1"): ((0, 0, 0, 0), None, None),
    ("test_3", "run_2"): ((0, 0, 9, 9), None, None),
    ("test_4", "run_1"): ((0, 0, 0, 0), None, None),
    ("test_4", "run_2"): ((4, 0, 0, 0), None, None),
    ("test_4", "run_3"): ((0, 40, 0, 0), (1, 1, 10, 1), None),
    ("test_4", "run_4"): ((4, 50, 0, 0), None, None),
}


def _accepted(suite):
    return dict(load_accepted_named(os.path.join(REFERENCE_TESTS, suite)))


def _native_dumps(cfg, traces, delays, periods, rank):
    eng = NativeEngine(cfg)
    eng.load_traces(traces)
    if delays or periods:
        eng.set_schedule(list(delays) if delays else None,
                         list(periods) if periods else None)
    if rank is not None:
        eng.set_arbitration(np.asarray(rank, np.int32))
    eng.run(100_000)
    assert eng.quiescent
    ns = types.SimpleNamespace(**eng.export_state())
    return [format_node_dump(d) for d in state_to_dumps(cfg, ns)]


def _async_dumps(cfg, traces, delays, periods, rank):
    kw = {}
    if delays:
        kw["issue_delay"] = np.asarray(delays, np.int32)
    if periods:
        kw["issue_period"] = np.asarray(periods, np.int32)
    if rank is not None:
        kw["arb_rank"] = np.asarray(rank, np.int32)
    st = run_to_quiescence(cfg, init_state(cfg, traces, **kw), 50_000)
    assert bool(st.quiescent())
    return [format_node_dump(d) for d in state_to_dumps(cfg, st)]


@requires_reference
@pytest.mark.parametrize("suite,run", sorted(WITNESSES))
def test_witness_schedule_reaches_accepted_run(suite, run):
    cfg = SystemConfig.reference()
    traces = load_test_dir(os.path.join(REFERENCE_TESTS, suite))
    delays, periods, rank = WITNESSES[(suite, run)]
    want = _accepted(suite)[run]
    got_native = _native_dumps(cfg, traces, delays, periods, rank)
    assert got_native == want, f"native missed {suite}/{run}"
    got_async = _async_dumps(cfg, traces, delays, periods, rank)
    assert got_async == want, f"async missed {suite}/{run}"


@requires_reference
@pytest.mark.parametrize("suite,n_runs", [("test_3", 2), ("test_4", 4)])
def test_every_accepted_run_is_witnessed(suite, n_runs):
    """The WITNESSES table covers the complete accepted-outcome set."""
    names = {name for name, _ in load_accepted_named(
        os.path.join(REFERENCE_TESTS, suite))}
    assert names == {f"run_{i}" for i in range(1, n_runs + 1)}
    covered = {r for s, r in WITNESSES if s == suite}
    assert covered == names


@requires_reference
def test_fixture_audit_every_shipped_run_is_a_quiescent_outcome():
    """Divergence audit (ARCHITECTURE.md decision 6): the reference's
    sleep-then-`kill -9` harness (``test3.sh:9-12``) CAN freeze a
    non-quiescent snapshot (dump re-armed at ``assignment.c:171-173``,
    written at ``assignment.c:639-645`` before late traffic lands);
    this repo's engines realize quiescent outcomes only. That design
    rests on the empirical fact that every fixture shipped with the
    reference is a quiescent state — proven constructively by the
    witness tests above, which reach each one AT QUIESCENCE. This
    audit scans the reference tree directly (independent of the
    accepted-outcome loader), so a future fixture drop that adds a
    kill snapshot fails here instead of silently losing parity."""
    for suite in ("test_3", "test_4"):
        shipped = {d for d in os.listdir(
            os.path.join(REFERENCE_TESTS, suite))
            if d.startswith("run_")}
        pinned = {r for s, r in WITNESSES if s == suite}
        assert shipped == pinned, (
            f"{suite}: shipped runs {sorted(shipped)} != quiescent-"
            f"witnessed runs {sorted(pinned)} — a new fixture may be "
            "a non-quiescent kill snapshot (ARCHITECTURE.md decision "
            "6); find a witness with scripts/search_racy.py or "
            "document the divergence")
