"""Fuzzer + shrinker acceptance: determinism, mutation kill, ddmin
convergence, repro emission.

* determinism — a seed fully determines the corpus and every verdict
  (the whole fuzz loop is driven by one numpy Generator and two
  deterministic engines), so two runs must produce bit-identical
  reports.
* clean gate — the shipped handlers pass the default fixed-seed budget
  (the same run scripts/check.sh time-boxes).
* mutation kill — every seeded handler bug in analysis.mutations must
  be caught under the default budget, and its first finding must ddmin
  to a <=8-instruction repro with the verdict kind preserved (the
  "shrunk witness" every finding ships with).
* repro — the emitted fixture directory round-trips through
  utils.trace.load_test_dir and carries a schema-valid Perfetto trace.
"""

import json
import os

import pytest

DEFAULT_CASES = 16          # the fixed-seed CI budget (scripts/check.sh)
DEFAULT_SEED = 0


def _fuzz(n=DEFAULT_CASES, seed=DEFAULT_SEED, message_phase=None):
    from ue22cs343bb1_openmp_assignment_tpu.analysis import fuzz
    return fuzz.fuzz(n, seed=seed, message_phase=message_phase)


def test_fuzzer_is_deterministic():
    a = _fuzz(n=10, seed=3)
    b = _fuzz(n=10, seed=3)
    assert json.loads(json.dumps(a)) == json.loads(json.dumps(b))
    assert a["cases"] == 10 and a["coverage_points"] >= 1


def test_clean_handlers_pass_default_budget():
    rep = _fuzz()
    assert rep["ok"], rep["findings"]
    assert rep["verdicts"].get("ok") == DEFAULT_CASES
    # the corpus kept at least a few coverage-novel cases
    assert rep["corpus_size"] >= 3


@pytest.mark.parametrize("mutation", [
    "skip_em_bitvec_clear",
    "upgrade_keeps_other_sharers",
    "no_wait_clear_on_reply_rd",
    "drop_evict_modified",
    "stale_owner_forward",
    "evict_shared_keeps_bit",
])
def test_fuzzer_kills_mutant_with_shrunk_witness(mutation):
    """Every seeded mutant is caught under the default fixed-seed
    budget AND its witness trace ddmin-shrinks to <=8 instructions
    without changing verdict kind."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis import fuzz, shrink
    from ue22cs343bb1_openmp_assignment_tpu.analysis.mutations import (
        MUTATIONS)
    fn = MUTATIONS[mutation][0]
    rep = _fuzz(message_phase=fn)
    assert not rep["ok"], f"{mutation} survived the fuzzer"
    f0 = rep["findings"][0]
    shrunk = shrink.shrink_case(fuzz.case_from_dict(f0["case"]), fn,
                                verdict=f0["verdict"])
    assert shrunk["verdict"] == f0["verdict"]
    assert shrunk["instrs_after"] <= 8, (
        mutation, shrunk["instrs_after"])
    assert shrunk["instrs_after"] < shrunk["instrs_before"]


def test_repro_emission_round_trips(tmp_path):
    """emit_repro writes a loadable fixture + valid Perfetto trace."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis import fuzz, shrink
    from ue22cs343bb1_openmp_assignment_tpu.analysis.mutations import (
        MUTATIONS)
    from ue22cs343bb1_openmp_assignment_tpu.obs import perfetto
    from ue22cs343bb1_openmp_assignment_tpu.utils.trace import (
        load_test_dir)
    fn = MUTATIONS["no_wait_clear_on_reply_rd"][0]
    rep = _fuzz(message_phase=fn)
    f0 = rep["findings"][0]
    shrunk = shrink.shrink_case(fuzz.case_from_dict(f0["case"]), fn,
                                verdict=f0["verdict"])
    out = str(tmp_path / "repro")
    meta = shrink.emit_repro(shrunk, out, fn)

    cfg = shrunk["case"].config()
    traces = load_test_dir(out, cfg.num_nodes, cfg.max_instrs)
    assert len(traces) == cfg.num_nodes
    loaded = sum(len(t) for t in traces)
    assert loaded == shrunk["instrs_after"] == meta["instrs"]
    doc = json.load(open(os.path.join(out, "trace.perfetto.json")))
    perfetto.validate_trace(doc)
    saved = json.load(open(os.path.join(out, "repro.json")))
    assert saved["verdict"] == f0["verdict"]
    # the serialized case round-trips
    assert fuzz.case_from_dict(saved["case"]) == shrunk["case"]


def test_shrink_refuses_passing_case():
    import numpy as np

    from ue22cs343bb1_openmp_assignment_tpu.analysis import fuzz, shrink
    case = fuzz.gen_case(np.random.default_rng(0), 0, local=True)
    with pytest.raises(ValueError):
        shrink.shrink_case(case)
