"""Synthetic workload generators (BASELINE.json config ladder; the
reference ships only hand-written fixtures up to 68 instructions)."""

import jax
import numpy as np
import pytest

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models import workloads
from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
from ue22cs343bb1_openmp_assignment_tpu.types import Op


@pytest.mark.parametrize("name", sorted(workloads.GENERATORS))
def test_generator_shapes_and_ranges(name):
    cfg = SystemConfig.scale(num_nodes=64, queue_capacity=16)
    op, addr, val, count = workloads.GENERATORS[name](
        jax.random.PRNGKey(0), cfg, 12)
    assert op.shape == addr.shape == val.shape == (64, 12)
    assert count.shape == (64,)
    op, addr, val = map(np.asarray, (op, addr, val))
    assert set(np.unique(op)) <= {int(Op.READ), int(Op.WRITE)}
    h = addr >> cfg.block_bits
    assert (0 <= h).all() and (h < 64).all()
    assert (0 <= val).all() and (val < 256).all()


def test_fft_local_writes_remote_reads():
    """FFT writes only home-local blocks but reads partners' — staged
    all-to-all read traffic. Local writes still *race* remote reads of
    the same blocks, so coherence is checked at the diagnostic tier
    (quirk-2 premature unblocks can leave phantom sharers, a faithful
    reference race); the engine tier must be clean."""
    cfg = SystemConfig.scale(num_nodes=32, queue_capacity=32,
                             admission_window=5)
    op, addr, _, _ = workloads.GENERATORS["fft"](
        jax.random.PRNGKey(1), cfg, 10)
    op, addr = np.asarray(op), np.asarray(addr)
    h = addr >> cfg.block_bits
    ids = np.arange(32)[:, None]
    # all writes are home-local; some reads are remote
    assert (h[op == int(Op.WRITE)]
            == np.broadcast_to(ids, op.shape)[op == int(Op.WRITE)]).all()
    assert (h[op == int(Op.READ)]
            != np.broadcast_to(ids, op.shape)[op == int(Op.READ)]).any()

    sys_ = CoherenceSystem.from_workload(cfg, "fft", trace_len=10,
                                         seed=1).run()
    assert sys_.quiescent
    assert sys_.instrs_retired == 32 * 10
    report = sys_.check_invariants(strict_coherence=False)
    assert isinstance(report, dict)


def test_radix_runs_to_quiescence_with_backpressure():
    cfg = SystemConfig.scale(num_nodes=64, queue_capacity=32,
                             admission_window=5)
    sys_ = CoherenceSystem.from_workload(cfg, "radix", trace_len=8,
                                         seed=2).run()
    assert sys_.quiescent
    assert sys_.instrs_retired == 64 * 8
    # permutation phase really crosses nodes
    assert sys_.metrics["write_misses"] > 0
    sys_.check_invariants(strict_coherence=False)


def test_generators_are_seed_deterministic():
    cfg = SystemConfig.scale(num_nodes=16, queue_capacity=16)
    for name, gen in workloads.GENERATORS.items():
        a = gen(jax.random.PRNGKey(3), cfg, 6)
        b = gen(jax.random.PRNGKey(3), cfg, 6)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                          err_msg=name)


def test_false_sharing_vars_structure():
    """Unpadded: every node touches exactly one block, groupmates the
    *same* one (the collision IS the workload); padded: footprints are
    disjoint across nodes, so the run is race-free and the strict
    coherence tier must be exactly zero."""
    cfg = SystemConfig.scale(num_nodes=16, queue_capacity=16)
    key = jax.random.PRNGKey(7)

    op, addr, _, _ = workloads.false_sharing_vars(key, cfg, 8,
                                                  vars_per_block=4)
    addr = np.asarray(addr)
    # one block per node, shared within each group of 4
    assert all(len(np.unique(addr[n])) == 1 for n in range(16))
    for g in range(4):
        assert len(np.unique(addr[4 * g:4 * g + 4])) == 1
    assert len(np.unique(addr[::4])) == 4      # distinct across groups
    assert (np.asarray(op) == int(Op.WRITE)).mean() > 0.5  # write-mostly

    _, paddr, _, _ = workloads.false_sharing_vars(key, cfg, 8, padded=True)
    paddr = np.asarray(paddr)
    assert all(len(np.unique(paddr[n])) == 1 for n in range(16))
    assert len(np.unique(paddr[:, 0])) == 16   # fully disjoint

    # deterministic in the seed (same key -> bit-identical trace)
    again = workloads.false_sharing_vars(key, cfg, 8, vars_per_block=4)
    np.testing.assert_array_equal(np.asarray(again[0]), np.asarray(op))
    np.testing.assert_array_equal(np.asarray(again[1]), addr)

    # the padded fix is race-free: strict coherence must hold
    sys_ = CoherenceSystem.from_workload(
        cfg, "false_sharing_vars_padded", trace_len=8, seed=7).run()
    assert sys_.quiescent
    sys_.check_invariants(strict_coherence=True)


def test_hotspot_temporal_locality():
    """Hotspot traces must be hit-dominated: consecutive accesses revisit
    a small working set, unlike the capacity-miss-bound uniform load."""
    import jax
    from ue22cs343bb1_openmp_assignment_tpu.models.system import (
        CoherenceSystem)
    from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se

    cfg = SystemConfig.scale(num_nodes=32, max_instrs=64)
    op, addr, val, count = workloads.hotspot(
        jax.random.PRNGKey(0), cfg, 64)
    assert op.shape == (32, 64) and int(count[0]) == 64
    # addresses valid
    import numpy as np
    a = np.asarray(addr)
    assert a.min() >= 0 and a.max() < (32 << cfg.block_bits)

    sys_ = CoherenceSystem.from_workload(cfg, "hotspot", trace_len=64,
                                         seed=0)
    st = se.run_sync_to_quiescence(
        cfg, se.from_sim_state(cfg, sys_.state), 16, 50_000)
    assert bool(st.quiescent())
    m = st.metrics
    hits = int(m.read_hits) + int(m.write_hits)
    misses = int(m.read_misses) + int(m.write_misses) + int(m.upgrades)
    assert hits > misses, (hits, misses)  # temporal locality pays off


def test_lu_writes_are_node_local_reads_share_pivots():
    """LU-style blocked factorization: all writes hit the writer's own
    home blocks (no write races), while each phase's pivot block is
    read by every node (wide sharer sets)."""
    import jax
    from ue22cs343bb1_openmp_assignment_tpu import codec
    cfg = SystemConfig.scale(num_nodes=16)
    op, addr, val, cnt = workloads.lu_blocked(
        jax.random.PRNGKey(0), cfg, 32)
    import numpy as np
    op, addr = np.asarray(op), np.asarray(addr)
    home = addr >> cfg.block_bits
    ids = np.arange(16)[:, None]
    assert (home[op == 1] == np.broadcast_to(ids, op.shape)[op == 1]).all()
    # slot-0 columns: one pivot address shared by every node
    pivot_cols = addr[:, 0::4]
    assert (pivot_cols == pivot_cols[0]).all()


def test_lu_runs_to_quiescence_with_exact_directory():
    from ue22cs343bb1_openmp_assignment_tpu.models.system import (
        CoherenceSystem)
    from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
    cfg = SystemConfig.scale(num_nodes=32, txn_width=3, drain_depth=3)
    sys_ = CoherenceSystem.from_workload(cfg, "lu", trace_len=40, seed=2)
    final = se.run_sync_to_quiescence(
        cfg, se.from_sim_state(cfg, sys_.state), 16, 50_000)
    assert bool(final.quiescent())
    se.check_exact_directory(cfg, final)
