"""examples/quickstart.py must keep running end-to-end (docs that rot
are worse than no docs)."""

import pathlib
import runpy

REPO_ROOT = pathlib.Path(__file__).parents[1]


def test_quickstart_runs(capsys, monkeypatch):
    monkeypatch.chdir(REPO_ROOT)
    runpy.run_path(str(REPO_ROOT / "examples" / "quickstart.py"),
                   run_name="__main__")
    out = capsys.readouterr().out
    assert "async engine quiescent: True" in out
    assert "sync engine:" in out
    assert "streamed 2nd phase: 131072" in out
    assert "sharded one round" in out
