"""TransactionalSystem high-level API (models.transactional)."""

import numpy as np
import pytest

from tests.conftest import REFERENCE_TESTS, requires_reference

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
from ue22cs343bb1_openmp_assignment_tpu.models.transactional import (
    TransactionalSystem)


@requires_reference
def test_fixture_run_and_dumps(tmp_path):
    sys_ = TransactionalSystem.from_test_dir(
        f"{REFERENCE_TESTS}/test_1").run()
    assert sys_.quiescent
    assert sys_.metrics["instrs_retired"] == 68
    sys_.check_invariants()
    dumps = sys_.dumps()
    for n in range(4):
        golden = open(
            f"{REFERENCE_TESTS}/test_1/core_{n}_output.txt").read()
        assert dumps[n] == golden
    paths = sys_.write_dumps(str(tmp_path))
    assert len(paths) == 4


def test_workload_run_save_load_continue(tmp_path):
    cfg = SystemConfig.scale(num_nodes=32, max_instrs=16)
    sys_ = TransactionalSystem.from_workload(
        cfg, "uniform", trace_len=16, workload_seed=1, seed=3,
        local_frac=0.4).run()
    assert sys_.quiescent and sys_.instrs_retired == 32 * 16
    path = str(tmp_path / "t.ckpt")
    sys_.save(path)
    restored = TransactionalSystem.load(path)
    assert restored.quiescent
    nxt = CoherenceSystem.from_workload(cfg, "uniform", trace_len=16,
                                        seed=2).state
    cont = restored.continue_with(
        instr_arrays=(nxt.instr_op, nxt.instr_addr, nxt.instr_val,
                      nxt.instr_count)).run()
    assert cont.quiescent and cont.instrs_retired == 2 * 32 * 16
    cont.check_invariants()


def test_step_and_ensemble():
    cfg = SystemConfig.scale(num_nodes=16, max_instrs=8)
    sys_ = TransactionalSystem.from_workload(cfg, "uniform", trace_len=8)
    one = sys_.step()
    assert int(one.state.round) == 1
    ens = sys_.ensemble([0, 1, 2])
    assert ens.cache_addr.shape[0] == 3
    assert [int(s) for s in ens.seed] == [0, 1, 2]


def test_load_rejects_async_checkpoint(tmp_path):
    cfg = SystemConfig.scale(num_nodes=8, max_instrs=8)
    base = CoherenceSystem.from_workload(cfg, "uniform", trace_len=8)
    path = str(tmp_path / "a.ckpt")
    base.save(path)
    with pytest.raises(ValueError, match="CoherenceSystem"):
        TransactionalSystem.load(path)
