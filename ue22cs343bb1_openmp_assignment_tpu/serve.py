"""Batched multi-tenant serving: pack independent sim jobs into
fixed-shape waves and run them under one vmap'd stepper.

"Millions of users" for a simulator means thousands of *independent*
sims in flight (ROADMAP item 2), not one giant sim. This module is the
front door for that shape: a stream of (config, trace) jobs is packed
into ``slots`` fixed-shape batch positions, the whole machine state —
caches, directory, mailboxes, traces, metrics — carries a leading job
axis (``state.stack_states``), and each *wave* runs every slot to
quiescence inside a single jitted ``ops.step.run_wave_to_quiescence``
call. Finished jobs are swapped out between waves and queued jobs
admitted in place (``state.set_state``), so XLA compiles the wave
stepper exactly once per (slot shape, protocol) — the recompile guard
(analysis/lint_jaxpr.py) checks this stays true.

Slot-fit rules
--------------
Every job runs inside the *slot* config (``slot_nodes`` x
``slot_trace_len``), padded:

* trace padding: instructions [job_T, slot_T) are NOPs with
  ``instr_count`` unchanged, so the frontend never fetches them;
* node padding: nodes [job_N, slot_N) get ``instr_count == 0`` — born
  exhausted, they never issue and (being un-referenced by any job
  address) never receive traffic;
* address geometry: traces are generated with the JOB's own config, so
  job addresses/homes are independent of the slot size (the codec packs
  ``home << block_bits | block``).

The only place slot and job configs disagree observably is the
invalid-address sentinel (it depends on num_nodes), so extraction
remaps ``slot_cfg.invalid_address -> job_cfg.invalid_address`` and
slices the directory bitvec down to the job's word count. That makes a
padded batched run *bit-identical* per job to running the job solo —
the parity gate in tests/test_serve.py holds byte-for-byte on the
golden state dumps.

Early exit: a quiescent state is a fixpoint of ``cycle`` apart from
the cycle counters, so the wave runner freezes finished slots via a
where-mask. Finished jobs therefore keep their *exact* solo cycle
count while stragglers run on.

Padding waste: jobs/sec at a traffic mix can silently hide slot-fit
loss, so every wave reports ``padding_waste`` — the fraction of the
slot instruction budget (slots * slot_nodes * slot_trace_len) that is
padding rather than real job instructions. It lands in the serve
summary doc and in bench history's ``serve`` block.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.obs.clock import MonotonicClock
from ue22cs343bb1_openmp_assignment_tpu.types import Op

SCHEMA_ID = "cache-sim/serve/v1"

#: workloads the serve traffic mix cycles through (all N-generic)
DEFAULT_MIX = ("uniform", "false_sharing", "producer_consumer", "hotspot")


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """One serving job: a workload trace under its own machine config."""

    name: str
    workload: str = "uniform"
    nodes: int = 4
    trace_len: int = 8
    seed: int = 0
    protocol: str = "mesi"

    @classmethod
    def from_dict(cls, d: dict) -> "JobSpec":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"job spec has unknown keys {sorted(unknown)}")
        if "name" not in d:
            raise ValueError("job spec needs a 'name'")
        return cls(**d)


# lint: host
def load_jobs(path) -> List[JobSpec]:
    """Jobs from a .jsonl file (one spec per line) or a directory of
    .json files (sorted by filename)."""
    p = pathlib.Path(path)
    specs: List[JobSpec] = []
    if p.is_dir():
        for f in sorted(p.glob("*.json")):
            specs.append(JobSpec.from_dict(json.loads(f.read_text())))
    else:
        for line in p.read_text().splitlines():
            line = line.strip()
            if line:
                specs.append(JobSpec.from_dict(json.loads(line)))
    if not specs:
        raise ValueError(f"no jobs found under {path}")
    return specs


def job_config(spec: JobSpec, queue_capacity: int = 64) -> SystemConfig:
    return SystemConfig.scale(num_nodes=spec.nodes,
                              max_instrs=spec.trace_len,
                              queue_capacity=queue_capacity,
                              protocol=spec.protocol)


def slot_config(specs, slot_nodes: Optional[int] = None,
                slot_trace_len: Optional[int] = None,
                queue_capacity: int = 64,
                protocol: str = "mesi") -> SystemConfig:
    """The fixed batch-slot shape: defaults to the max over the jobs."""
    n = slot_nodes or max(s.nodes for s in specs)
    t = slot_trace_len or max(s.trace_len for s in specs)
    bad = [s.name for s in specs
           if s.nodes > n or s.trace_len > t]
    if bad:
        raise ValueError(f"jobs {bad} exceed slot shape ({n}x{t})")
    return SystemConfig.scale(num_nodes=n, max_instrs=t,
                              queue_capacity=queue_capacity,
                              protocol=protocol)


# one phase callable per protocol, cached so the wave jit sees a stable
# identity across waves (a fresh closure per wave would recompile)
_PHASE_CACHE: Dict[str, object] = {}


def protocol_phase(protocol: str):
    """message_phase override for a protocol: None for MESI (the
    handler core *is* MESI); the table-compiled phase otherwise."""
    if protocol == "mesi":
        return None
    if protocol not in _PHASE_CACHE:
        from ue22cs343bb1_openmp_assignment_tpu.analysis import protocol_table
        _PHASE_CACHE[protocol] = protocol_table.table_message_phase(
            protocol_table.TABLES[protocol]())
    return _PHASE_CACHE[protocol]


# trace synthesis is deterministic in the spec, so repeated serve()
# passes over the same stream (bench reps) re-ingest for free; a real
# service receives traces as data, so synthesis is not serving cost
_ARRAYS_CACHE: Dict[JobSpec, tuple] = {}


# lint: host
def build_job_arrays(job_cfg: SystemConfig, spec: JobSpec):
    """The job's instr arrays at its OWN geometry (host numpy)."""
    import jax
    from ue22cs343bb1_openmp_assignment_tpu.models import workloads
    if spec in _ARRAYS_CACHE:
        return _ARRAYS_CACHE[spec]
    if spec.workload not in workloads.GENERATORS:
        raise ValueError(f"unknown workload {spec.workload!r}")
    gen = workloads.GENERATORS[spec.workload]
    op, addr, val, count = gen(jax.random.PRNGKey(spec.seed), job_cfg,
                               spec.trace_len)
    arrays = tuple(np.asarray(a) for a in (op, addr, val, count))
    _ARRAYS_CACHE[spec] = arrays
    return arrays


# lint: host
def pad_arrays(slot_cfg: SystemConfig, arrays):
    """Pad (op, addr, val, count) from job geometry to the slot's
    [slot_N, slot_T]: NOP-fill ops, zero addr/val, zero count on pad
    nodes (born exhausted — the frontend never fetches for them)."""
    op, addr, val, count = arrays
    n, t = op.shape
    N, T = slot_cfg.num_nodes, slot_cfg.max_instrs
    opP = np.full((N, T), int(Op.NOP), np.int32)
    adP = np.zeros((N, T), np.int32)
    vaP = np.zeros((N, T), np.int32)
    cnP = np.zeros((N,), np.int32)
    opP[:n, :t] = op
    adP[:n, :t] = addr
    vaP[:n, :t] = val
    cnP[:n] = count
    return opP, adP, vaP, cnP


# slot-shaped initial states are immutable, so admission can reuse
# them across waves and passes; keyed by (spec, slot config)
_STATE_CACHE: Dict[tuple, object] = {}


# lint: host
def build_job_state(slot_cfg: SystemConfig, job_cfg: SystemConfig,
                    spec: JobSpec):
    """Slot-shaped SimState carrying the job's (padded) trace."""
    from ue22cs343bb1_openmp_assignment_tpu import state as st
    key = (spec, slot_cfg)
    if key not in _STATE_CACHE:
        padded = pad_arrays(slot_cfg, build_job_arrays(job_cfg, spec))
        _STATE_CACHE[key] = st.init_state(slot_cfg, instr_arrays=padded)
    return _STATE_CACHE[key]


# lint: host
def extract_job_view(slot_cfg: SystemConfig, job_cfg: SystemConfig,
                     job_state):
    """Slice a finished slot back down to the job's own geometry.

    Row-slices every per-node plane to the job's num_nodes, remaps the
    slot invalid-address sentinel to the job's, and trims the directory
    bitvec to the job's word count. The result formats through
    utils.golden byte-identically to a solo run of the job."""
    import jax
    import types as _types
    n, W = job_cfg.num_nodes, job_cfg.bitvec_words
    g = lambda x: np.asarray(jax.device_get(x))
    ca = g(job_state.cache_addr)[:n]
    ca = np.where(ca == slot_cfg.invalid_address,
                  job_cfg.invalid_address, ca).astype(ca.dtype)
    return _types.SimpleNamespace(
        memory=g(job_state.memory)[:n],
        dir_state=g(job_state.dir_state)[:n],
        dir_bitvec=g(job_state.dir_bitvec)[:n, :, :W],
        cache_addr=ca,
        cache_val=g(job_state.cache_val)[:n],
        cache_state=g(job_state.cache_state)[:n])


# lint: host
def job_dumps(slot_cfg: SystemConfig, job_cfg: SystemConfig,
              job_state) -> List[str]:
    """Per-node golden-format state dumps for one extracted job."""
    from ue22cs343bb1_openmp_assignment_tpu.utils import golden
    view = extract_job_view(slot_cfg, job_cfg, job_state)
    return [golden.format_node_dump(d)
            for d in golden.state_to_dumps(job_cfg, view)]


# lint: host
def job_metrics_doc(job_state) -> dict:
    """cache-sim/metrics/v1 report for one extracted job slot."""
    import jax
    from ue22cs343bb1_openmp_assignment_tpu.obs import schema
    m = job_state.metrics
    md = {f: np.asarray(jax.device_get(getattr(m, f))).tolist()
          for f in m.__dataclass_fields__}
    return schema.from_async(md, engine="async")


# lint: host
def solo_dumps(spec: JobSpec, chunk: int = 32, max_cycles: int = 100_000,
               queue_capacity: int = 64) -> List[str]:
    """Reference: the job run alone at its own geometry (the parity
    oracle for the batched path)."""
    from ue22cs343bb1_openmp_assignment_tpu import state as st
    from ue22cs343bb1_openmp_assignment_tpu.ops import step
    from ue22cs343bb1_openmp_assignment_tpu.utils import golden
    cfg = job_config(spec, queue_capacity)
    s0 = st.init_state(cfg, instr_arrays=build_job_arrays(cfg, spec))
    final = step.run_chunked_to_quiescence(
        cfg, s0, chunk, max_cycles, message_phase=protocol_phase(spec.protocol))
    return [golden.format_node_dump(d)
            for d in golden.state_to_dumps(cfg, final)]


class SpanBook:
    """Host-side assembly of Dapper-style job-lifecycle spans.

    One span per job, advanced through the lifecycle ``submit ->
    queued -> admitted(wave, slot) -> running -> quiescent ->
    extracted`` by the wave loop (serve) or the open-loop scheduler
    (soak). Every timestamp defaults to ``clock.now()`` of the ONE
    injected clock (obs.clock) — the same time base as the wave
    records — and the three segment durations are computed here, in
    one place, from the lifecycle timestamps::

        queue_wait_s = t_admitted  - t_submit
        run_s        = t_quiescent - t_admitted
        extract_s    = t_extracted - t_quiescent
        e2e_s        = queue_wait_s + run_s + extract_s

    so the decomposition invariant (segments sum EXACTLY to e2e, the
    obs.txntrace convention) holds by construction —
    obs.schema.validate_serve_trace re-checks it on every emitted doc.
    """

    # lint: host
    def __init__(self, clock) -> None:
        self.clock = clock
        self._open: Dict[str, dict] = {}
        self._done: List[dict] = []

    # lint: host
    def _t(self, t: Optional[float]) -> float:
        return float(self.clock.now() if t is None else t)

    # lint: host
    def submit(self, job: str, t: Optional[float] = None) -> None:
        t = self._t(t)
        self._open[job] = {"job": job, "t_submit": t, "t_queued": t}

    # lint: host
    def queued(self, job: str, t: Optional[float] = None) -> None:
        self._open[job]["t_queued"] = self._t(t)

    # lint: host
    def admitted(self, job: str, wave: int, slot: int,
                 t: Optional[float] = None) -> None:
        s = self._open[job]
        s["wave"] = int(wave)
        s["slot"] = int(slot)
        s["t_admitted"] = self._t(t)

    # lint: host
    def running(self, job: str, t: Optional[float] = None) -> None:
        self._open[job]["t_running"] = self._t(t)

    # lint: host
    def quiescent(self, job: str, ok: bool,
                  t: Optional[float] = None) -> None:
        s = self._open[job]
        s["quiesced"] = bool(ok)
        s["t_quiescent"] = self._t(t)

    # lint: host
    def extracted(self, job: str, t: Optional[float] = None) -> None:
        s = self._open.pop(job)
        s["t_extracted"] = self._t(t)
        s["queue_wait_s"] = s["t_admitted"] - s["t_submit"]
        s["run_s"] = s["t_quiescent"] - s["t_admitted"]
        s["extract_s"] = s["t_extracted"] - s["t_quiescent"]
        s["e2e_s"] = s["queue_wait_s"] + s["run_s"] + s["extract_s"]
        self._done.append(s)

    # lint: host
    def annotate(self, job: str, **fields) -> None:
        """Attach optional span fields (``lane``, ``bucket`` — the
        daemon's tenancy annotations, obs.schema._SPAN_OPT_KEYS) to an
        open span."""
        self._open[job].update(fields)

    # lint: host
    def spans(self) -> List[dict]:
        """Closed spans, in extraction order."""
        return list(self._done)

    # lint: host
    def prune(self, keep: int) -> int:
        """Drop all but the newest ``keep`` closed spans (the serving
        daemon's result-retention bound — latency summaries over a
        pruned book are a sliding window); returns the drop count."""
        drop = len(self._done) - keep
        if drop > 0:
            del self._done[:drop]
        return max(drop, 0)


# lint: host
def weighted_padding_waste(waves: List[dict]) -> float:
    """Summary padding_waste over per-wave records, weighted by each
    wave's slot instruction budget::

        1 - sum(real_instrs) / sum(slot_instr_budget)

    An unweighted mean of the per-wave ``padding_waste`` fractions
    over-counts small waves: with shape bucketing (daemon/bucketing)
    waves run at DIFFERENT slot budgets, and a tiny well-packed bucket
    wave must not cancel a huge badly-packed one. Weighting by budget
    makes the summary the true fraction of issued slot capacity that
    was padding — the number the bucketing win is measured in
    (tests/test_serve.py pins a two-wave case where the two averages
    disagree). serve/soak/daemon summaries all report THIS number.
    """
    budget = sum(w["slot_instr_budget"] for w in waves)
    real = sum(w["real_instrs"] for w in waves)
    return 1.0 - real / budget if budget else 0.0


# lint: host
def serve_trace_doc(spans: List[dict], clock_kind: str) -> dict:
    """Closed spans → the validated ``cache-sim/serve-trace/v1`` doc
    (the machine surface; the Perfetto rendering of the same spans is
    obs.perfetto.build_serve_trace)."""
    from ue22cs343bb1_openmp_assignment_tpu.obs import schema, timeseries
    doc = {
        "schema": schema.SERVE_TRACE_SCHEMA_ID,
        "clock": clock_kind,
        "jobs": len(spans),
        "latency": timeseries.latency_summary(
            [s["e2e_s"] for s in spans]),
        "spans": spans,
    }
    return schema.validate_serve_trace(doc)


# lint: host
def _host_quiescent(host) -> np.ndarray:
    """SimState.quiescent() per batch slot, in numpy over the one
    host copy the wave loop pulls (no extra device round trips)."""
    mb_idle = (np.asarray(host.mb_count) == 0).all(axis=-1)
    no_wait = (~np.asarray(host.waiting).astype(bool)).all(axis=-1)
    exhausted = (np.asarray(host.instr_idx)
                 >= np.asarray(host.instr_count) - 1).all(axis=-1)
    return mb_idle & no_wait & exhausted


# lint: host
def batch_shardings(mesh, bstate):
    """NamedShardings partitioning every batched leaf's leading slot
    axis over the 1-D ('batch',) mesh. ``state.stack_states`` stacks
    EVERY leaf (scalars included), so each one has the [B] axis and the
    whole wave partitions with zero replicated per-slot state."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    return jax.tree.map(
        lambda x: NamedSharding(
            mesh, P("batch", *([None] * (x.ndim - 1)))), bstate)


# lint: host
def serve(specs, slots: int = 4, slot_nodes: Optional[int] = None,
          slot_trace_len: Optional[int] = None, chunk: int = 32,
          max_cycles: int = 100_000, queue_capacity: int = 64,
          out_dir=None, quiet: bool = True, devices: int = 1,
          clock=None) -> dict:
    """Run a stream of jobs through fixed-shape batch waves.

    Jobs are grouped by protocol (each protocol is its own wave
    sequence — the message phase is a static jit argument). Within a
    group, the first ``slots`` jobs are stacked into a batch; each wave
    runs every slot to quiescence (or the cycle budget); finished jobs
    are extracted and their slots refilled from the queue via
    ``state.set_state`` — admission never restacks, so the jit cache
    stays warm.

    ``devices > 1`` shards the batch (slot) axis over that many local
    devices (the ROADMAP item-2 remainder): every stacked leaf
    partitions its leading axis over a 1-D ('batch',) mesh, so each
    device runs slots/devices independent sims and XLA inserts no
    cross-device collectives inside the wave (slots are independent by
    construction). Admission (``set_state``) and extraction are
    unchanged — jit keeps the sharding layout across waves. Requires
    ``slots % devices == 0``.

    ALL serving timing reads the injected ``clock`` (obs.clock;
    default the production MonotonicClock) — wave ``wall_s`` and the
    per-job lifecycle spans share that one time base, and a
    VirtualClock makes every timestamp (hence the whole trace doc)
    deterministic. Spans are assembled host-side (SpanBook) and ride
    the summary as ``doc["trace"]``, a validated
    ``cache-sim/serve-trace/v1`` doc; with ``out_dir`` the Perfetto
    rendering (flow arrows per job, obs.perfetto.build_serve_trace)
    lands at ``<out_dir>/trace.perfetto.json``.

    Returns the ``cache-sim/serve/v1`` summary doc; per-job results
    (dumps + metrics docs) are in ``doc["jobs"]`` and, when ``out_dir``
    is given, streamed to ``<out_dir>/<job>/`` as they finish. Any
    wave that reports mailbox-overflow drops (``mb_dropped`` — quirk
    6's silent drop, surfaced) warns LOUDLY on stderr even under
    ``quiet``: a dropped reply can leave its requester blocked forever,
    so drops usually explain a non-quiescing job.
    """
    import jax

    from ue22cs343bb1_openmp_assignment_tpu import state as st
    from ue22cs343bb1_openmp_assignment_tpu.ops import step
    from ue22cs343bb1_openmp_assignment_tpu.utils import golden

    if devices < 1:
        raise ValueError("devices must be >= 1")
    mesh = None
    if devices > 1:
        from jax.sharding import Mesh
        avail = jax.devices()
        if devices > len(avail):
            raise ValueError(
                f"devices={devices} but only {len(avail)} available")
        if slots % devices:
            raise ValueError(
                f"slots={slots} does not shard over devices={devices}")
        mesh = Mesh(avail[:devices], ("batch",))

    clock = clock if clock is not None else MonotonicClock()
    t_start = clock.now()
    book = SpanBook(clock)
    by_proto: Dict[str, List[JobSpec]] = {}
    for s in specs:
        # the whole stream is present at serve() entry (closed loop) —
        # every job submits and queues at t_start; the open-loop
        # arrival schedule is the soak harness's job (soak.py)
        book.submit(s.name, t_start)
        by_proto.setdefault(s.protocol, []).append(s)

    out_path = pathlib.Path(out_dir) if out_dir is not None else None
    job_docs: Dict[str, dict] = {}
    waves: List[dict] = []
    mb_dropped_total = 0

    for protocol, queue in by_proto.items():
        scfg = slot_config(queue, slot_nodes, slot_trace_len,
                           queue_capacity, protocol)
        phase = protocol_phase(protocol)
        N, T = scfg.num_nodes, scfg.max_instrs
        # dummy slot filler: zero traces = instantly quiescent
        if ("empty", scfg) not in _STATE_CACHE:
            _STATE_CACHE[("empty", scfg)] = st.init_state(scfg)
        empty = _STATE_CACHE[("empty", scfg)]
        queue = list(queue)

        # slot i currently holds job `occupant[i]` (None = empty dummy)
        occupant: List[Optional[JobSpec]] = [None] * slots
        real_by_slot = [0] * slots   # real (unpadded) instrs per slot
        states = []
        for i in range(slots):
            if queue:
                spec = queue.pop(0)
                occupant[i] = spec
                real_by_slot[i] = int(np.sum(build_job_arrays(
                    job_config(spec, queue_capacity), spec)[3]))
                states.append(build_job_state(
                    scfg, job_config(spec, queue_capacity), spec))
                book.admitted(spec.name, wave=len(waves) + 1, slot=i)
            else:
                states.append(empty)
        bstate = st.stack_states(states)
        if mesh is not None:
            bstate = jax.device_put(bstate, batch_shardings(mesh, bstate))

        while any(o is not None for o in occupant):
            real = sum(real_by_slot)
            t0 = clock.now()
            for o in occupant:
                if o is not None:
                    book.running(o.name, t0)
            bstate = step.run_wave_to_quiescence(
                scfg, bstate, chunk, max_cycles, phase)
            # ONE device->host transfer per wave; per-job extraction
            # below is numpy slicing on this copy
            host = jax.device_get(bstate)
            quiet_mask = _host_quiescent(host)
            clock.on_wave()
            t_wave_end = clock.now()
            wave_s = t_wave_end - t0
            budget = slots * N * T
            finished = [o.name for o in occupant if o is not None]
            # quirk 6 surfaced: per-slot mailbox-overflow drop counts
            # (cumulative per job — each occupied slot resolves this
            # wave, so this is the finishing jobs' total)
            occ = np.array([o is not None for o in occupant])
            wave_dropped = int(np.sum(
                np.asarray(host.metrics.msgs_dropped)[occ]))
            waves.append({
                "protocol": protocol,
                "jobs": finished,
                "wall_s": wave_s,
                "slot_instr_budget": budget,
                "real_instrs": real,
                "padding_waste": 1.0 - real / budget,
                "mb_dropped": wave_dropped,
            })
            mb_dropped_total += wave_dropped
            if wave_dropped:
                # loud on purpose, quiet or not: a silently dropped
                # reply leaves its requester blocked forever (the
                # reference's unreachable overflow, quirk 6) — this is
                # almost always why a job fails to quiesce
                import sys
                print(f"serve: WARNING wave {len(waves)} [{protocol}] "
                      f"dropped {wave_dropped} mailbox message(s) on "
                      f"overflow (quirk 6) — raise --queue-capacity; "
                      f"affected jobs: {', '.join(finished)}",
                      file=sys.stderr)
            if not quiet:
                print(f"serve: wave {len(waves)} [{protocol}] "
                      f"jobs={len(finished)} wall={wave_s:.3f}s "
                      f"padding_waste={waves[-1]['padding_waste']:.3f}")

            # every slot resolves per wave: quiescent, or over budget
            # (recorded as failed and evicted either way)
            for i, spec in enumerate(occupant):
                if spec is None:
                    continue
                jstate = st.index_state(host, i)
                jcfg = job_config(spec, queue_capacity)
                doc = job_metrics_doc(jstate)
                ok = bool(quiet_mask[i])
                book.quiescent(spec.name, ok, t_wave_end)
                job_docs[spec.name] = {
                    "spec": dataclasses.asdict(spec),
                    "quiesced": ok,
                    "cycles": int(np.asarray(jstate.cycle)),
                    "metrics": doc,
                }
                if out_path is not None:
                    jdir = out_path / spec.name
                    jdir.mkdir(parents=True, exist_ok=True)
                    view = extract_job_view(scfg, jcfg, jstate)
                    golden.write_dumps(jcfg, view, jdir)
                    (jdir / "metrics.json").write_text(
                        json.dumps(job_docs[spec.name], indent=2) + "\n")
                book.extracted(spec.name)
                # swap out; admit the next queued job into this slot
                if queue:
                    nxt = queue.pop(0)
                    occupant[i] = nxt
                    real_by_slot[i] = int(np.sum(build_job_arrays(
                        job_config(nxt, queue_capacity), nxt)[3]))
                    bstate = st.set_state(bstate, i, build_job_state(
                        scfg, job_config(nxt, queue_capacity), nxt))
                    book.admitted(nxt.name, wave=len(waves) + 1,
                                  slot=i)
                else:
                    # no replacement: leave the finished (quiescent =
                    # fixpoint) or budget-dead (cycle >= max_cycles =
                    # masked) state in place — the wave cond ignores
                    # both, so clearing the slot would be a wasted
                    # whole-batch update
                    occupant[i] = None
                    real_by_slot[i] = 0

    wall = clock.now() - t_start
    n_jobs = len(job_docs)
    spans = book.spans()
    doc = {
        "schema": SCHEMA_ID,
        "slots": slots,
        "devices": devices,
        "mb_dropped": mb_dropped_total,
        "jobs_total": n_jobs,
        "jobs_quiesced": sum(1 for d in job_docs.values() if d["quiesced"]),
        "waves": waves,
        "wave_count": len(waves),
        "wall_s": wall,
        "jobs_per_sec": (n_jobs / wall) if wall > 0 else 0.0,
        "padding_waste": weighted_padding_waste(waves),
        "jobs": job_docs,
        "trace": serve_trace_doc(spans, clock.kind),
    }
    if out_path is not None:
        out_path.mkdir(parents=True, exist_ok=True)
        (out_path / "serve_summary.json").write_text(
            json.dumps(doc, indent=2) + "\n")
        from ue22cs343bb1_openmp_assignment_tpu.obs import perfetto
        trace = perfetto.validate_trace(perfetto.build_serve_trace(spans))
        perfetto.write_trace(str(out_path / "trace.perfetto.json"), trace)
    return doc


# lint: host
def mixed_jobs(n: int, nodes: int = 4, trace_len: int = 8,
               protocol: str = "mesi",
               mix: Tuple[str, ...] = DEFAULT_MIX) -> List[JobSpec]:
    """The fixed traffic mix: n jobs cycling through ``mix`` workloads
    with seeds 0..n-1 (the jobs/sec measurement protocol in PERF.md)."""
    return [JobSpec(name=f"job{i:03d}", workload=mix[i % len(mix)],
                    nodes=nodes, trace_len=trace_len, seed=i,
                    protocol=protocol)
            for i in range(n)]


# lint: host
def main(argv=None) -> int:
    """``cache-sim serve`` entry point."""
    import argparse
    ap = argparse.ArgumentParser(
        prog="cache-sim serve",
        description="batched multi-tenant serving: run a stream of "
                    "(config, trace) jobs in fixed-shape waves")
    ap.add_argument("--jobs", required=True,
                    help=".jsonl file or directory of .json job specs")
    ap.add_argument("--slots", type=int, default=4,
                    help="batch slots per wave (default 4)")
    ap.add_argument("--slot-nodes", type=int, default=None,
                    help="slot node count (default: max over jobs)")
    ap.add_argument("--slot-trace-len", type=int, default=None,
                    help="slot trace length (default: max over jobs)")
    ap.add_argument("--queue-capacity", type=int, default=64)
    ap.add_argument("--devices", type=int, default=1,
                    help="shard the batch axis over N local devices "
                         "(slots must divide evenly; default 1)")
    ap.add_argument("--chunk", type=int, default=32)
    ap.add_argument("--max-cycles", type=int, default=100_000)
    ap.add_argument("--out-dir", default=None,
                    help="stream per-job dumps + metrics docs here")
    ap.add_argument("--json", action="store_true",
                    help="print the full serve summary doc as JSON")
    ap.add_argument("--cpu", action="store_true",
                    help="force JAX_PLATFORMS=cpu (set before jax import)")
    args = ap.parse_args(argv)
    if args.cpu:
        import os
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    specs = load_jobs(args.jobs)
    doc = serve(specs, slots=args.slots, slot_nodes=args.slot_nodes,
                slot_trace_len=args.slot_trace_len, chunk=args.chunk,
                max_cycles=args.max_cycles,
                queue_capacity=args.queue_capacity,
                out_dir=args.out_dir, quiet=False,
                devices=args.devices)
    if args.json:
        print(json.dumps(doc, indent=2))
    else:
        print(f"serve: {doc['jobs_quiesced']}/{doc['jobs_total']} jobs "
              f"quiesced in {doc['wave_count']} waves, "
              f"{doc['jobs_per_sec']:.2f} jobs/sec, "
              f"padding_waste={doc['padding_waste']:.3f}")
    return 0 if doc["jobs_quiesced"] == doc["jobs_total"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
