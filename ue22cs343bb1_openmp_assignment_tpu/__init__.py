"""TPU-native directory-based cache-coherence simulation framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the reference
OpenMP simulator (``/root/reference/assignment.c``): a DASH-style 3-state
directory over MESI caches on a distributed-shared-memory machine.

Instead of the reference's thread-per-node / lock / spin architecture
(one OpenMP thread per simulated processor, ``assignment.c:149``), the
whole system is expressed as a **synchronous vectorized state machine**:

* node state is a pytree of ``[num_nodes, ...]`` device arrays,
* one ``cycle`` = every node processes at most one mailbox message or
  fetches at most one instruction (branch-free, masked updates),
* the message network is a padded ``[num_nodes, capacity]`` ring-buffer
  tensor; delivery is a vectorized sort+scatter with a *seedable,
  deterministic* arbitration order replacing the reference's OS-scheduling
  nondeterminism,
* scale-out shards the node axis over a ``jax.sharding.Mesh`` with
  cross-shard delivery via collectives (``parallel/``).

Byte parity: the golden-dump writer (``utils.golden``) reproduces
``printProcessorState`` (``assignment.c:853-905``) byte for byte, and the
engine reproduces the reference's observable protocol behavior including
its quirks (see SURVEY.md "behavioral quirks" and ``ops/handlers.py``).
"""

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu import types

__version__ = "0.1.0"
__all__ = ["SystemConfig", "types", "__version__"]
