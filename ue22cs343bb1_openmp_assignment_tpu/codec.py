"""Address codec: (home node, block index) <-> flat address.

The reference packs an address into one byte — high nibble = home node id,
low nibble = block index (``assignment.c:46-49``), decoded as
``(addr >> 4) & 0x0F`` / ``addr & 0x0F`` (``assignment.c:186-188``) with
``cacheIndex = block % CACHE_SIZE`` (``assignment.c:188``).

Generalized here: the block field is ``cfg.block_bits`` wide (4 when
mem_size=16, identical to the nibble scheme), the node id occupies the
bits above it. Works on Python ints and on JAX arrays alike.
"""

from __future__ import annotations

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig


def home_node(cfg: SystemConfig, addr):
    """Home node id of an address (assignment.c:186,657)."""
    return addr >> cfg.block_bits


def block_index(cfg: SystemConfig, addr):
    """Block index within the home node's memory (assignment.c:187,658)."""
    return addr & ((1 << cfg.block_bits) - 1)


def cache_index(cfg: SystemConfig, addr):
    """Direct-mapped cache slot for an address (assignment.c:188,659)."""
    return block_index(cfg, addr) % cfg.cache_size


def make_address(cfg: SystemConfig, node, block):
    """Compose a flat address from (home node, block index)."""
    return (node << cfg.block_bits) | block
