"""Protocol enumerations and message field layout.

Integer values mirror the reference enums so state dumps and differential
tests line up positionally:

* cache line states: ``assignment.c:17`` (MODIFIED, EXCLUSIVE, SHARED,
  INVALID) — the golden dump indexes a string table by this value
  (``assignment.c:855``).
* directory states: ``assignment.c:28`` (EM, S, U) — dump table at
  ``assignment.c:857``.
* transaction types: ``assignment.c:30-44`` (13 messages).

All are plain ints (not jnp arrays) so they fold into traced constants.
"""

from __future__ import annotations

import enum


class CacheState(enum.IntEnum):
    MODIFIED = 0
    EXCLUSIVE = 1
    SHARED = 2
    INVALID = 3
    # Variant-protocol states (analysis/protocol_table.py). Appended
    # after INVALID so the reference values 0-3 — and every dump/golden
    # that indexes by them — are untouched; INVALID stays the MESI
    # fill/reset sentinel. Only table-driven MOESI/MESIF phases emit
    # these; the range invariant (ops/invariants.py) admits them only
    # when cfg.protocol does.
    OWNED = 4     # MOESI: dirty but shared, owner responds instead of memory
    FORWARD = 5   # MESIF: clean designated forwarder among sharers


class DirState(enum.IntEnum):
    EM = 0  # exclusive-or-modified: block lives in exactly one cache
    S = 1   # shared: block lives in multiple caches
    U = 2   # unowned: block lives in no cache


class Msg(enum.IntEnum):
    """Transaction vocabulary (assignment.c:30-44)."""

    READ_REQUEST = 0    # requester -> home, on read miss
    WRITE_REQUEST = 1   # requester -> home, on write miss
    REPLY_RD = 2        # home -> requester, data for a read
    REPLY_WR = 3        # home -> requester, go-ahead for a write
    REPLY_ID = 4        # home -> requester, sharer id list
    INV = 5             # new owner -> sharers, invalidate
    UPGRADE = 6         # requester -> home, S write-hit promotion
    WRITEBACK_INV = 7   # home -> old owner, flush + invalidate
    WRITEBACK_INT = 8   # home -> old owner, flush + demote to shared
    FLUSH = 9           # old owner -> home (+ requester), data writeback
    FLUSH_INVACK = 10   # old owner -> home + requester, flush + inv-ack
    EVICT_SHARED = 11   # evictor -> home, shared/exclusive line replaced
    EVICT_MODIFIED = 12 # evictor -> home, dirty line replaced (with value)

    # Sentinel for an empty candidate/mailbox slot (never a real message).
    NONE = 13


CACHE_STATE_NAMES = ("MODIFIED", "EXCLUSIVE", "SHARED", "INVALID",
                     "OWNED", "FORWARD")
DIR_STATE_NAMES = ("EM", "S", "U")

MSG_NAMES = tuple(m.name for m in Msg if m is not Msg.NONE)


# Instruction opcodes ('R'/'W' bytes in the reference, assignment.c:51).
class Op(enum.IntEnum):
    READ = 0
    WRITE = 1
    NOP = 2   # padding beyond a node's trace length
