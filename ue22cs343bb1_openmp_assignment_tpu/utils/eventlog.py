"""Structured event log — tracing the TPU way (SURVEY §5).

The reference's only tracing is compile-time printf: ``-DDEBUG_INSTR``
logs every instruction fetch (``assignment.c:649-652`` — the provenance
of the ``instruction_order.txt`` fixtures) and ``-DDEBUG_MSG`` every
dequeued message (``assignment.c:179-182``). Here the engine records the
same facts as device arrays stacked by ``lax.scan``
(ops.step.run_cycles_traced): one dispatch, no host round-trips, then
this module renders them — byte-compatible with the reference's line
formats so existing ``instruction_order.txt`` tooling keeps working —
or hands them over as structured records for programmatic analysis.

Ordering note: the reference log's cross-node interleaving is OS
scheduling; ours is (cycle, node id) — deterministic and seedable via
the schedule knobs. Per-node projections are program order in both, and
that is the property tests assert (SURVEY §4).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.types import MSG_NAMES, Op

# printf templates from the reference (assignment.c:650-651, 180-181)
_INSTR_FMT = "Processor {n}: instr type={t}, address=0x{a:02X}, value={v}"
_MSG_FMT = "Processor {n} msg from: {s}, type: {ty}, address: 0x{a:02X}"


def _np_events(events: Dict) -> Dict[str, np.ndarray]:
    return {k: np.asarray(v) for k, v in events.items()}


def to_records(events: Dict, base_cycle: int = 0) -> List[dict]:
    """Flatten [T, N] event arrays into a (cycle, node)-ordered list of
    dicts: {"kind": "instr"|"msg", "cycle", "node", ...}.

    Vectorized over the (usually sparse) event masks — cost scales with
    the number of events, not T×N. A node never both dequeues and
    fetches in one cycle (drain-before-fetch priority, ops.step), so
    (cycle, node) ordering has no ties to break.
    """
    ev = _np_events(events)
    mt, mn = np.nonzero(ev["msg"])
    msgs = [{"kind": "msg", "cycle": base_cycle + int(t), "node": int(n),
             "sender": int(s), "type": int(ty),
             "type_name": MSG_NAMES[int(ty)], "addr": int(a)}
            for t, n, s, ty, a in zip(
                mt, mn, ev["msg_sender"][mt, mn],
                ev["msg_type"][mt, mn], ev["msg_addr"][mt, mn])]
    ft, fn = np.nonzero(ev["fetch"])
    instrs = [{"kind": "instr", "cycle": base_cycle + int(t),
               "node": int(n), "op": int(o), "addr": int(a),
               "value": int(v)}
              for t, n, o, a, v in zip(
                  ft, fn, ev["op"][ft, fn], ev["addr"][ft, fn],
                  ev["value"][ft, fn])]
    return sorted(msgs + instrs, key=lambda r: (r["cycle"], r["node"]))


def sync_to_records(events: Dict, base_round: int = 0) -> List[dict]:
    """Flatten the sync engine's [T, N, K] retirement record
    (ops.sync_engine.run_rounds_traced) into (round, node, slot)-ordered
    instr records. Slot order within a round is program order; the
    canonical cross-node order is (round, node) — one legal
    serialization, like the async engine's (cycle, node)."""
    ev = _np_events(events)
    rt, rn, rk = np.nonzero(ev["retired"])
    return [{"kind": "instr", "cycle": base_round + int(t), "node": int(n),
             "op": int(o), "addr": int(a), "value": int(v)}
            for t, n, o, a, v in zip(
                rt, rn, ev["op"][rt, rn, rk], ev["addr"][rt, rn, rk],
                ev["value"][rt, rn, rk])
            if int(o) != int(Op.NOP)]  # NOP padding retires silently


def format_record(rec: dict) -> str:
    """One record → the reference's printf line (byte-compatible)."""
    if rec["kind"] == "instr":
        t = "W" if rec["op"] == int(Op.WRITE) else "R"
        return _INSTR_FMT.format(n=rec["node"], t=t, a=rec["addr"],
                                 v=rec["value"] & 0xFF)
    return _MSG_FMT.format(n=rec["node"], s=rec["sender"],
                           ty=rec["type"], a=rec["addr"])


def to_lines(events: Dict, kinds=("instr",),
             base_cycle: int = 0) -> List[str]:
    """Render the log; default only instruction fetches — exactly the
    ``instruction_order.txt`` surface."""
    return [format_record(r) for r in to_records(events, base_cycle)
            if r["kind"] in kinds]


def write_log(path: str, events: Dict, kinds=("instr",),
              base_cycle: int = 0) -> None:
    with open(path, "w") as f:
        for line in to_lines(events, kinds, base_cycle):
            f.write(line + "\n")


def write_sync_log(path: str, events: Dict, base_round: int = 0) -> None:
    """Render a sync-engine retirement record to instruction_order.txt
    format."""
    with open(path, "w") as f:
        for rec in sync_to_records(events, base_round):
            f.write(format_record(rec) + "\n")


def per_node_projection(lines: List[str]) -> Dict[int, List[str]]:
    """Split a rendered (or fixture) log by node id — per-node order is
    program order regardless of interleaving, the invariant shared with
    the reference's logs."""
    out: Dict[int, List[str]] = {}
    for line in lines:
        if not line.strip():
            continue
        n = int(line.split()[1].rstrip(":"))
        out.setdefault(n, []).append(line.strip())
    return out
