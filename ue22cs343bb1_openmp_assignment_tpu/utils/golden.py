"""Byte-parity golden state dump (and its parser).

Reproduces ``printProcessorState`` (``assignment.c:853-905``) byte for
byte, including its format traps (SURVEY §2 C10 / quirk 8):

* the sharer bitvector renders with C23 ``"0x%08B"`` — **binary** digits
  behind a literal, misleading ``0x`` prefix (sharers {0,1} →
  ``0x00000011``),
* cache rows end in ``" \\t|"`` (space + hard tab, ``assignment.c:898``),
* memory/directory rows print the home-node-prefixed address
  ``(processorId<<4)+i`` (``assignment.c:877,888``),
* ``%2s`` / ``%8s`` right-justification of state names, which lets the
  9-char ``EXCLUSIVE`` overflow its %8s field exactly as C does.

The parser (:func:`parse_dump`) inverts the format so reference golden
files can be round-tripped (formatter proof) and compared structurally.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import List

import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.types import (CACHE_STATE_NAMES,
                                                      DIR_STATE_NAMES)


@dataclass
class NodeDump:
    """Host-side view of one node's dumped state."""

    node_id: int
    memory: np.ndarray      # [M] int
    dir_state: np.ndarray   # [M] int (DirState)
    dir_bitvec: np.ndarray  # [M] int (full integer, words already joined)
    cache_addr: np.ndarray  # [C] int
    cache_val: np.ndarray   # [C] int
    cache_state: np.ndarray # [C] int (CacheState)
    mem_addr: np.ndarray = None  # [M] int; home-prefixed block addresses.
    # Default (reference layout): (node_id << 4) + i, assignment.c:877.

    def __post_init__(self):
        if self.mem_addr is None:
            self.mem_addr = (self.node_id << 4) + np.arange(len(self.memory))


def format_node_dump(d: NodeDump) -> str:
    """Render one node's dump exactly as printProcessorState does."""
    L: List[str] = []
    L.append("=======================================")
    L.append(f" Processor Node: {d.node_id}")
    L.append("=======================================")
    L.append("")
    L.append("-------- Memory State --------")
    L.append("| Index | Address |   Value  |")
    L.append("|----------------------------|")
    for i, v in enumerate(d.memory):
        L.append(f"|  {i:3d}  |  0x{int(d.mem_addr[i]):02X}   |"
                 f"  {int(v):5d}   |")
    L.append("------------------------------")
    L.append("")
    L.append("------------ Directory State ---------------")
    L.append("| Index | Address | State |    BitVector   |")
    L.append("|------------------------------------------|")
    for i in range(len(d.memory)):
        st = DIR_STATE_NAMES[int(d.dir_state[i])]
        bv = int(d.dir_bitvec[i])
        L.append(f"|  {i:3d}  |  0x{int(d.mem_addr[i]):02X}   |  {st:>2s}   |"
                 f"   0x{bv:08b}   |")
    L.append("--------------------------------------------")
    L.append("")
    L.append("------------ Cache State ----------------")
    L.append("| Index | Address | Value |    State    |")
    L.append("|---------------------------------------|")
    for i in range(len(d.cache_addr)):
        st = CACHE_STATE_NAMES[int(d.cache_state[i])]
        L.append(f"|  {i:3d}  |  0x{int(d.cache_addr[i]):02X}   |"
                 f"  {int(d.cache_val[i]):3d}  |  {st:>8s} \t|")
    L.append("----------------------------------------")
    L.append("")
    return "\n".join(L) + "\n"


def state_to_dumps(cfg: SystemConfig, state) -> List[NodeDump]:
    """Pull a SimState (or any pytree with the same fields) to host dumps."""
    mem = np.asarray(state.memory)
    ds = np.asarray(state.dir_state)
    bv = np.asarray(state.dir_bitvec).astype(np.uint64)
    ca, cv, cs = (np.asarray(state.cache_addr), np.asarray(state.cache_val),
                  np.asarray(state.cache_state))
    # join bitvector words into one Python-int-sized value per entry
    joined = np.zeros(bv.shape[:2], dtype=object)
    for w in range(bv.shape[-1]):
        joined = joined + (bv[..., w].astype(object) << (32 * w))
    from ue22cs343bb1_openmp_assignment_tpu import codec
    blocks = np.arange(cfg.mem_size)
    return [NodeDump(node_id=n, memory=mem[n], dir_state=ds[n],
                     dir_bitvec=joined[n], cache_addr=ca[n], cache_val=cv[n],
                     cache_state=cs[n],
                     mem_addr=codec.make_address(cfg, n, blocks))
            for n in range(cfg.num_nodes)]


def write_dumps(cfg: SystemConfig, state, out_dir: str) -> List[str]:
    """Write core_<n>_output.txt files like the reference (assignment.c:860)."""
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for d in state_to_dumps(cfg, state):
        p = os.path.join(out_dir, f"core_{d.node_id}_output.txt")
        with open(p, "w") as f:
            f.write(format_node_dump(d))
        paths.append(p)
    return paths


# -- parser ----------------------------------------------------------------

_MEM_RE = re.compile(r"^\|\s+(\d+)\s+\|\s+0x([0-9A-Fa-f]+)\s+\|\s+(\d+)\s+\|$")
_DIR_RE = re.compile(
    r"^\|\s+(\d+)\s+\|\s+0x([0-9A-Fa-f]+)\s+\|\s+(EM|S|U)\s+\|\s+0x([01]+)\s+\|$")
_CACHE_RE = re.compile(
    r"^\|\s+(\d+)\s+\|\s+0x([0-9A-Fa-f]+)\s+\|\s+(\d+)\s+\|\s+"
    r"(MODIFIED|EXCLUSIVE|SHARED|INVALID) \t\|$")


def parse_dump(text: str) -> NodeDump:
    """Invert format_node_dump on a reference-produced golden file."""
    node_id = int(re.search(r"Processor Node: (\d+)", text).group(1))
    mem_rows, dir_rows, cache_rows = [], [], []
    for line in text.splitlines():
        m = _MEM_RE.match(line)
        if m:
            mem_rows.append((int(m.group(2), 16), int(m.group(3))))
            continue
        m = _DIR_RE.match(line)
        if m:
            dir_rows.append((DIR_STATE_NAMES.index(m.group(3)),
                             int(m.group(4), 2)))
            continue
        m = _CACHE_RE.match(line)
        if m:
            cache_rows.append((int(m.group(2), 16), int(m.group(3)),
                               CACHE_STATE_NAMES.index(m.group(4))))
    return NodeDump(
        node_id=node_id,
        mem_addr=np.array([r[0] for r in mem_rows], dtype=np.int64),
        memory=np.array([r[1] for r in mem_rows], dtype=np.int64),
        dir_state=np.array([r[0] for r in dir_rows], dtype=np.int64),
        dir_bitvec=np.array([r[1] for r in dir_rows], dtype=object),
        cache_addr=np.array([r[0] for r in cache_rows], dtype=np.int64),
        cache_val=np.array([r[1] for r in cache_rows], dtype=np.int64),
        cache_state=np.array([r[2] for r in cache_rows], dtype=np.int64),
    )
