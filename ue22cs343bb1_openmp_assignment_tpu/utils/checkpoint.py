"""Checkpoint / resume for the full machine state.

The reference has no persistence at all — its only artifact is the
one-shot end-state dump (``assignment.c:853-905``), re-armed on late
messages (``assignment.c:171-173``); a crashed or killed run loses
everything (SURVEY §5 "checkpoint/resume: none").

Here the entire simulator — caches, memories, directories, mailboxes,
in-flight instruction latches, schedule knobs, metrics — is one pytree
of device arrays (state.SimState), so a checkpoint is just "device_get
the leaves at any cycle boundary" and resume is bit-exact: running k
cycles, checkpointing, restoring, and running to quiescence yields the
same final state (and golden dumps) as an uninterrupted run
(tests/test_checkpoint.py pins this).

Format: a single ``.npz`` (zip of npy arrays) with

* one entry per state leaf, keyed by its dotted pytree path
  (``metrics.cycles``, ``cache_state``, ...),
* ``__config__``: the SystemConfig as JSON (shapes are config-derived,
  so a checkpoint is self-describing),
* ``__meta__``: user metadata + a format version + the state kind
  ("sim" = async message-level engine, "sync" = transactional engine;
  both engines' states are plain pytrees, so one format serves both).

No framework dependency: numpy only. The state is an ordinary pytree,
so orbax users can equally hand ``state`` to
``orbax.checkpoint.StandardCheckpointer`` — this module exists so the
core has zero optional deps.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional, Tuple

import jax
import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.state import Metrics, SimState

FORMAT_VERSION = 4  # v4: plane-major mailbox ring ([P, N, Q] mb_pack); v3: packed mailbox tensor; v2: + waiting_since, fault_key, injected-drop metric


def _state_classes(kind: str):
    if kind == "sync":
        from ue22cs343bb1_openmp_assignment_tpu.ops.sync_engine import (
            SyncMetrics, SyncState)
        return SyncState, SyncMetrics
    return SimState, Metrics


def _state_kind(state) -> str:
    return "sync" if type(state).__name__ == "SyncState" else "sim"

_CONFIG_KEY = "__config__"
_META_KEY = "__meta__"


def _leaf_dict(state: SimState) -> dict:
    """Flatten the state pytree to {dotted-path: host ndarray}."""
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        name = ".".join(
            p.name if hasattr(p, "name") else str(p) for p in path)
        flat[name] = np.asarray(jax.device_get(leaf))
    return flat


def save_checkpoint(path: str, cfg: SystemConfig, state,
                    meta: Optional[dict] = None) -> None:
    """Write a self-describing checkpoint of (cfg, state) to ``path``.

    ``state`` may be a SimState (async engine) or SyncState
    (transactional engine); the kind is recorded and restored."""
    arrays = _leaf_dict(state)
    arrays[_CONFIG_KEY] = np.frombuffer(
        json.dumps(dataclasses.asdict(cfg)).encode(), dtype=np.uint8)
    arrays[_META_KEY] = np.frombuffer(
        json.dumps({**(meta or {}), "kind": _state_kind(state),
                    "format_version": FORMAT_VERSION}).encode(),
        dtype=np.uint8)
    with open(path, "wb") as f:
        np.savez(f, **arrays)


def load_checkpoint(path: str) -> Tuple[SystemConfig, SimState, dict]:
    """Restore (cfg, state, meta) written by :func:`save_checkpoint`.

    The returned state's arrays are host-backed; the first jitted step
    moves them to the default device (or shard them explicitly with
    parallel.shard_state for a mesh run).
    """
    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files}
    if _CONFIG_KEY not in arrays or _META_KEY not in arrays:
        raise ValueError(
            f"{path} is not a checkpoint written by save_checkpoint "
            f"(missing {_CONFIG_KEY}/{_META_KEY})")
    cfg_d = json.loads(bytes(arrays.pop(_CONFIG_KEY).tobytes()).decode())
    meta = json.loads(bytes(arrays.pop(_META_KEY).tobytes()).decode())
    version = meta.get("format_version")
    if version == 3:
        # v3 -> v4: the only layout change is the async mailbox ring
        # going slot-major [N, Q, P] -> plane-major [P, N, Q]; sync
        # checkpoints carry no mb_pack and need no migration
        if "mb_pack" in arrays:
            arrays["mb_pack"] = np.moveaxis(arrays["mb_pack"], -1, 0)
        version = FORMAT_VERSION
    if version != FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format {meta.get('format_version')} != "
            f"supported {FORMAT_VERSION}")
    cfg = SystemConfig(**cfg_d)
    state_cls, metrics_cls = _state_classes(meta.get("kind", "sim"))

    metric_fields = {}
    state_fields = {}
    for name, arr in arrays.items():
        if name.startswith("metrics."):
            metric_fields[name.split(".", 1)[1]] = arr
        else:
            state_fields[name] = arr
    expected = set(f.name for f in dataclasses.fields(state_cls))
    got = set(state_fields) | {"metrics"}
    # fields added after a checkpoint was written get their neutral
    # init (currently only the deep-window attempt horizon)
    if "horizon" in expected and "horizon" not in got:
        n = state_fields["idx"].shape[-1]
        state_fields["horizon"] = np.full(
            state_fields["idx"].shape[:-1] + (n,), 1 << 20, np.int32)
        got.add("horizon")
    if "order_rank" in expected and "order_rank" not in got:
        # replay gating is off by default; older checkpoints resume ungated
        state_fields["order_rank"] = np.zeros(
            state_fields["instr_count"].shape + (0,), np.int32)
        got.add("order_rank")
    if meta.get("kind", "sim") == "sim":
        # obs-layer counters added after the checkpoint was written
        # resume from their neutral init
        from ue22cs343bb1_openmp_assignment_tpu.state import LAT_BUCKETS
        metric_fields.setdefault(
            "lat_hist", np.zeros((LAT_BUCKETS,), np.int32))
        metric_fields.setdefault("mb_depth_peak", np.zeros((), np.int32))
    if got != expected:
        raise ValueError(f"checkpoint fields {sorted(got)} != "
                         f"state fields {sorted(expected)}")
    state = state_cls(metrics=metrics_cls(**metric_fields), **state_fields)
    return cfg, state, meta


def checkpoint_bytes(state) -> int:
    """Total checkpoint payload size (useful for scale planning).

    Computed from shapes/dtypes only — no device→host transfer.
    """
    return sum(l.nbytes for l in jax.tree_util.tree_leaves(state))
