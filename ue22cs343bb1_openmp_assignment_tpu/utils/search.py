"""Schedule search: run many arbitration seeds at once, match goldens.

The reference validates its racy suites by re-running the binary until
some accepted interleaving happens to occur (``test3.sh:6-33``,
``test4.sh:6-32`` — sleep 1s, kill -9, diff, repeat). Here the schedule
is an explicit, seedable parameter, so the search is a *batched sweep*:
an ensemble of identical machines differing only in arbitration seed
runs as one vmapped device dispatch (ops.sync_engine ensembles), and
every replica's final dump is compared against the accepted ``run_*``
outcomes on the host.

This is the same ensemble mechanism the benchmark uses for throughput
(PERF.md): on a dispatch-overhead-bound device, S seeds cost barely
more than one.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
from ue22cs343bb1_openmp_assignment_tpu.utils.golden import (format_node_dump,
                                                             state_to_dumps)


def sweep_seeds(cfg: SystemConfig, sim_state, seeds: Sequence[int],
                chunk: int = 16, max_rounds: int = 50_000):
    """Run one transactional machine per seed; returns the [S, ...]
    ensemble final state."""
    reps = [se.from_sim_state(cfg, sim_state, seed=int(s)) for s in seeds]
    ens = se.make_ensemble(reps)
    return se.run_ensemble_to_quiescence(cfg, ens, chunk, max_rounds)


def replica_dumps(cfg: SystemConfig, ens, r: int) -> List[str]:
    """Golden-format dumps of ensemble replica r."""
    rep = se.ensemble_replica(ens, r)
    return [format_node_dump(d)
            for d in state_to_dumps(cfg, se.to_dump_view(cfg, rep))]


def match_accepted(cfg: SystemConfig, sim_state,
                   accepted: Sequence[List[str]],
                   seeds: Sequence[int] = range(16),
                   chunk: int = 16,
                   max_rounds: int = 50_000) -> Dict[int, int]:
    """Map seed -> index of the accepted run its outcome reproduces.

    ``accepted``: one list of per-core dump strings per accepted run
    (e.g. loaded from ``tests/test_3/run_*/core_<n>_output.txt``).
    Seeds whose outcome matches no accepted run are omitted — like the
    reference harness, absence of a match proves nothing by itself
    (the accepted sets are samples, not exhaustive enumerations).
    """
    ens = sweep_seeds(cfg, sim_state, seeds, chunk, max_rounds)
    out: Dict[int, int] = {}
    for r, seed in enumerate(seeds):
        dumps = replica_dumps(cfg, ens, r)
        for i, acc in enumerate(accepted):
            if dumps == list(acc):
                out[int(seed)] = i
                break
    return out


def load_accepted_named(suite_dir: str, num_cores: int = 4):
    """[(run_dir_name, per-core dumps)] for a racy suite's run_* dirs."""
    import glob
    import os
    out = []
    for rd in sorted(glob.glob(f"{suite_dir}/run_*")):
        out.append((os.path.basename(rd),
                    [open(f"{rd}/core_{n}_output.txt").read()
                     for n in range(num_cores)]))
    return out


def load_accepted(suite_dir: str, num_cores: int = 4) -> List[List[str]]:
    """Load the accepted run_* dump sets of a reference racy suite."""
    return [dumps for _, dumps in load_accepted_named(suite_dir, num_cores)]
