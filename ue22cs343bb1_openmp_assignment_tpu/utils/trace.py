"""Instruction-trace file I/O.

File format (reference ``assignment.c:833-847``, ``README.md:64-77``):
one instruction per line, ``RD <hexaddr>`` or ``WR <hexaddr> <decvalue>``;
per-node files named ``core_<n>.txt`` inside a test directory. Values are
parsed with C ``%hhu`` semantics (truncate to a byte); addresses with
``%hhx`` (hex, optional 0x prefix).

Divergence note: the reference increments ``instructionCount`` even for a
line that is neither RD nor WR, leaving an *uninitialized stack slot* to
execute as garbage (``assignment.c:833-846``). No shipped fixture contains
such a line; we load them as explicit NOPs (retired with no effect) and
flag them, rather than reproducing undefined behavior.
"""

from __future__ import annotations

import os
from typing import List, Sequence, Tuple

from ue22cs343bb1_openmp_assignment_tpu.types import Op

Instr = Tuple[int, int, int]  # (op, address, value)


def parse_trace(text: str, max_instrs: int = 32) -> List[Instr]:
    """Parse one core_<n>.txt body into [(op, addr, value), ...]."""
    out: List[Instr] = []
    for line in text.splitlines():
        if len(out) >= max_instrs:  # MAX_INSTR_NUM cap (assignment.c:833-834)
            break
        if line.startswith("RD"):
            addr = int(line.split()[1], 16) & 0xFF
            out.append((int(Op.READ), addr, 0))
        elif line.startswith("WR"):
            parts = line.split()
            addr = int(parts[1], 16) & 0xFF
            val = int(parts[2]) & 0xFF  # %hhu truncation
            out.append((int(Op.WRITE), addr, val))
        else:
            # reference would execute stack garbage here; we load a NOP
            out.append((int(Op.NOP), 0, 0))
    return out


def load_test_dir(test_dir: str, num_nodes: int = 4,
                  max_instrs: int = 32) -> List[List[Instr]]:
    """Load core_<n>.txt for every node from a test directory.

    Missing file is a hard error, like the reference
    (``assignment.c:826-829``).
    """
    traces = []
    for n in range(num_nodes):
        path = os.path.join(test_dir, f"core_{n}.txt")
        if not os.path.exists(path):
            raise FileNotFoundError(f"Error: could not open file {path}")
        with open(path) as f:
            traces.append(parse_trace(f.read(), max_instrs))
    return traces


def format_trace(instrs: Sequence[Instr]) -> str:
    """Inverse of parse_trace — used by workload generators to emit fixtures."""
    lines = []
    for op, addr, val in instrs:
        if op == Op.READ:
            lines.append(f"RD 0x{addr:02X}")
        elif op == Op.WRITE:
            lines.append(f"WR 0x{addr:02X} {val}")
    return "\n".join(lines) + ("\n" if lines else "")


def save_test_dir(test_dir: str, traces: Sequence[Sequence[Instr]]) -> None:
    os.makedirs(test_dir, exist_ok=True)
    for n, tr in enumerate(traces):
        with open(os.path.join(test_dir, f"core_{n}.txt"), "w") as f:
            f.write(format_trace(tr))
