"""Interleaving replay from recorded ``instruction_order.txt`` logs.

The reference's ``-DDEBUG_INSTR`` build prints one line per instruction
fetch (``assignment.c:649-652``); the fixture trees capture the exact
global interleaving that produced each golden set (populated for
``sample``/``test_1``/``test_2``, SURVEY §4). This module parses that
log into a per-instruction *global issue rank* array: instruction i of
node n carries the file position of its line. With
``state.order_rank`` set, the frontend issues instruction i of node n
only when exactly ``order_rank[n, i]`` instructions have issued
machine-wide (ops.frontend) — at most one fetch per cycle, so the
machine reproduces the recorded interleaving exactly, and the
deterministic suites must land byte-for-byte on their goldens
(tests/test_order_replay.py).
"""

from __future__ import annotations

import os
import re
from typing import List, Sequence, Tuple

import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.types import Op

# assignment.c:650-651 printf template
_LINE = re.compile(
    r"Processor (\d+): instr type=([RW]), address=0x([0-9A-Fa-f]+), "
    r"value=(\d+)")


def parse_order_log(lines: Sequence[str]) -> List[Tuple[int, int, int, int]]:
    """[(node, op, addr, value), ...] in recorded global order."""
    out = []
    for ln in lines:
        ln = ln.strip()
        if not ln:
            continue
        m = _LINE.match(ln)
        if not m:
            raise ValueError(f"unparseable instruction_order line: {ln!r}")
        n, t, a, v = m.groups()
        out.append((int(n), int(Op.WRITE if t == "W" else Op.READ),
                    int(a, 16), int(v)))
    return out


def order_rank_from_log(cfg: SystemConfig, lines: Sequence[str],
                        traces) -> np.ndarray:
    """Build the [N, T] ``order_rank`` array for ``state.init_state``.

    Validates the log against the traces: per-node instruction counts
    must match, and each recorded (op, addr) must equal the trace's
    (the reference logs the in-flight instruction verbatim)."""
    N, T = cfg.num_nodes, cfg.max_instrs
    recs = parse_order_log(lines)
    rank = np.full((N, T), np.iinfo(np.int32).max, np.int32)
    seen = [0] * N
    for g, (n, op, addr, _val) in enumerate(recs):
        if n >= N:
            raise ValueError(f"log names node {n}, config has {N}")
        i = seen[n]
        if i >= len(traces[n]):
            raise ValueError(
                f"log has more instructions for node {n} than its trace "
                f"({len(traces[n])})")
        t_op, t_addr, _ = traces[n][i]
        if (int(t_op), int(t_addr)) != (op, addr):
            raise ValueError(
                f"log line {g} (node {n} instr {i}): "
                f"({op}, {addr:#x}) != trace ({int(t_op)}, "
                f"{int(t_addr):#x})")
        rank[n, i] = g
        seen[n] = i + 1
    for n, tr in enumerate(traces):
        if seen[n] != len(tr):
            raise ValueError(
                f"node {n}: log records {seen[n]} instructions, trace "
                f"has {len(tr)}")
    return rank


def load_order_rank(cfg: SystemConfig, suite_dir: str,
                    traces) -> np.ndarray:
    """Read ``<suite_dir>/instruction_order.txt`` into an order_rank
    array (raises FileNotFoundError / ValueError on absent or empty
    logs — test_3/test_4 fixtures ship empty order logs)."""
    path = os.path.join(suite_dir, "instruction_order.txt")
    with open(path) as f:
        lines = f.readlines()
    if not any(ln.strip() for ln in lines):
        raise ValueError(f"{path} is empty (racy suites record no order)")
    return order_rank_from_log(cfg, lines, traces)
