"""Command-line entry point.

Compat surface: ``cache-sim <test_directory>`` mirrors the reference's
``./cache_simulator <test_directory>`` (``assignment.c:126-131``,
``README.md:108-110``): loads ``tests/<dir>/core_<n>.txt`` relative to
--tests-root, runs to quiescence, writes ``core_<n>_output.txt`` golden
dumps into the CWD (or --out-dir).

Beyond the reference: runtime dimensions (--nodes/--cache/--mem/...),
synthetic workloads (--workload), schedule knobs for interleaving search
(--delays/--periods/--seed), and metrics reporting (--metrics).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cache-sim",
        description="TPU-native directory/MESI coherence simulator "
                    "(`cache-sim analyze` runs the static-analysis gate: "
                    "symmetry-reduced protocol model checker, AST + "
                    "jaxpr lint, and the --fuzz differential fuzzer "
                    "with ddmin trace shrinking)")
    p.add_argument("test_dir", nargs="?", default=None,
                   help="test directory name (reference-compat positional)")
    p.add_argument("--tests-root", default="tests",
                   help="prefix for <test_dir> (reference hardcodes 'tests/',"
                        " assignment.c:824)")
    p.add_argument("--out-dir", default=".",
                   help="where to write core_<n>_output.txt dumps")
    p.add_argument("--workload", choices=["uniform", "producer_consumer",
                                          "false_sharing",
                                          "false_sharing_vars",
                                          "false_sharing_vars_padded",
                                          "fft", "radix",
                                          "hotspot", "zipf_hotspot",
                                          "lu"],
                   help="run a synthetic workload instead of trace files "
                        "(fft/radix are SPLASH-2-style reference "
                        "patterns; false_sharing_vars[_padded] is the "
                        "colliding-variables stress and its padding fix; "
                        "zipf_hotspot is the heavy-tailed Zipf address "
                        "mix)")
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--trace-len", type=int, default=32)
    p.add_argument("--queue-capacity", type=int, default=None,
                   help="mailbox slots per node (default 256; shape-"
                        "determining, so it cannot change on --resume)")
    p.add_argument("--seed", type=int, default=0,
                   help="workload PRNG seed")
    p.add_argument("--delays", type=int, nargs="*",
                   help="per-node instruction issue delays (schedule knob)")
    p.add_argument("--periods", type=int, nargs="*",
                   help="per-node instruction issue periods (schedule knob)")
    p.add_argument("--arb-seed", type=int,
                   help="seed for the cross-sender arbitration permutation "
                        "(replaces the reference's OS lock-order "
                        "nondeterminism)")
    p.add_argument("--admission", type=int, default=None,
                   help="max concurrent outstanding requests (backpressure "
                        "window preventing mailbox-overflow livelock; "
                        "default: reference drop semantics)")
    p.add_argument("--max-cycles", type=int, default=100_000)
    p.add_argument("--metrics", action="store_true",
                   help="print step metrics as JSON to stderr")
    p.add_argument("--save-checkpoint", metavar="PATH",
                   help="write a full-state checkpoint after the run "
                        "(resume with --resume; SURVEY §5: the reference "
                        "has no persistence)")
    p.add_argument("--resume", metavar="PATH",
                   help="resume from a checkpoint instead of initializing "
                        "(ignores workload/trace/dimension flags)")
    p.add_argument("--run-cycles", type=int, default=None,
                   help="run exactly this many cycles instead of running "
                        "to quiescence (for checkpoint-then-resume runs)")
    p.add_argument("--dump", action="store_true",
                   help="write golden dumps even without a <test_directory>"
                        " (e.g. after --resume)")
    p.add_argument("--check", action="store_true",
                   help="verify engine invariants after the run and report "
                        "coherence diagnostics (the reference's -DDEBUG "
                        "asserts, whole-machine and vectorized; exit 3 on "
                        "violation)")
    p.add_argument("--check-strict", action="store_true",
                   help="like --check but also fail on coherence-tier "
                        "violations (only meaningful for race-free "
                        "schedules; racy workloads can legally leave "
                        "stale copies — the protocol acks no INVs)")
    p.add_argument("--drop-prob", type=float, default=None,
                   help="fault injection: drop each delivered message "
                        "with this probability (stress for the stall "
                        "watchdog; reference's only fault is the silent "
                        "overflow drop; default 0 = off)")
    p.add_argument("--fault-seed", type=int, default=None,
                   help="PRNG seed for --drop-prob injection "
                        "(default 0; on --resume, re-seeds the "
                        "checkpointed fault PRNG when given)")
    p.add_argument("--stall-threshold", type=int, default=100,
                   help="cycles a node may wait on one request before "
                        "the watchdog reports it stalled")
    p.add_argument("--trace-log", metavar="PATH",
                   help="write an instruction_order.txt-format event log "
                        "of the run (the reference's -DDEBUG_INSTR "
                        "tracing, assignment.c:649-652)")
    p.add_argument("--trace-msgs", action="store_true",
                   help="include message-dequeue events in --trace-log "
                        "(the reference's -DDEBUG_MSG, "
                        "assignment.c:179-182)")
    p.add_argument("--flight-dir", metavar="DIR",
                   help="arm the failure flight recorder (obs/flight): "
                        "on a hang, watchdog trip, or --check "
                        "invariant failure, dump a self-contained "
                        "incident dir here (last --flight-ring cycles "
                        "of telemetry + metrics doc + Perfetto trace "
                        "of the deterministic replay)")
    p.add_argument("--flight-ring", type=int, default=64,
                   help="flight recorder ring depth in cycles "
                        "(default 64)")
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend (default: first device)")
    p.add_argument("--engine", choices=["async", "sync", "native", "omp"],
                   default="async",
                   help="async = message-level JAX engine (reference "
                        "network semantics, schedule knobs, fault "
                        "injection); sync = transactional JAX engine "
                        "(atomic coherence rounds, the throughput path — "
                        "see PERF.md); native = host-side C++ engine with "
                        "async semantics (the differential oracle); "
                        "omp = build and run the reference OpenMP binary "
                        "itself as a backend (BASELINE's "
                        "--backend={omp,jax}; needs --reference-src and "
                        "gcc)")
    p.add_argument("--reference-src",
                   default="/root/reference/assignment.c",
                   help="--engine omp: path to the reference "
                        "assignment.c to build (gcc -fopenmp)")
    p.add_argument("--drain-depth", type=int, default=None,
                   help="sync engine: hit-burst length per round")
    p.add_argument("--txn-width", type=int, default=None,
                   help="sync engine: max coherence transactions "
                        "committed per node per round (multi-"
                        "transaction windows; default 1 = classic "
                        "burst-plus-one-transaction rounds)")
    p.add_argument("--procedural", action="store_true",
                   help="sync engine: compute the uniform workload "
                        "procedurally in-round (O(1) trace memory; "
                        "--trace-len may be arbitrarily long); pairs "
                        "with --seed as the stream seed")
    p.add_argument("--deep-window", action="store_true",
                   help="sync engine: deep-window rounds (dense "
                        "own-entry transaction chains + absorbed remote "
                        "events, ops.deep_engine — the round-3 "
                        "throughput path; --drain-depth sizes the "
                        "window, default 13)")
    p.add_argument("--deep-slots", type=int, default=None,
                   help="deep windows: remote-event slots per window "
                        "(default 8; on --resume an omitted flag keeps "
                        "the checkpoint's value)")
    p.add_argument("--deep-waves", type=int, default=None,
                   help="deep windows: absorption waves — up to this "
                        "many fill requests (mixed read/write) compose "
                        "per directory entry per round; the contended-"
                        "workload lever (max 14; default 1; on "
                        "--resume an omitted flag keeps the "
                        "checkpoint's value)")
    p.add_argument("--sweep-seeds", type=int, metavar="K",
                   help="sync engine: run K arbitration seeds as one "
                        "vmapped ensemble and report which seeds "
                        "reproduce an accepted run_* outcome of the test "
                        "directory — the batched replacement for the "
                        "reference's run-until-match harness "
                        "(test3.sh:6-33); exit 4 if no seed matches")
    return p


def _arb_rank(seed: int, num_nodes: int) -> np.ndarray:
    """--arb-seed → arbitration permutation; the single definition keeps
    the JAX and native engines seed-for-seed comparable."""
    return np.argsort(
        np.random.RandomState(seed).rand(num_nodes)).astype(np.int32)


def _schedule_knobs(args, num_nodes: int) -> dict:
    """--delays/--periods/--arb-seed → state-field overrides (one source
    of truth for fresh runs and --resume)."""
    kw = {}
    if args.delays:
        kw["issue_delay"] = np.asarray(args.delays, np.int32)
    if args.periods:
        kw["issue_period"] = np.asarray(args.periods, np.int32)
    if args.arb_seed is not None:
        kw["arb_rank"] = _arb_rank(args.arb_seed, num_nodes)
    return kw


def _main_sync(args) -> int:
    """--engine sync: the transactional engine's CLI path."""
    from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
    from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
    from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
    from ue22cs343bb1_openmp_assignment_tpu.utils import checkpoint as ckpt
    from ue22cs343bb1_openmp_assignment_tpu.utils.golden import write_dumps

    for flag, why in (("delays", "message-level issue schedules"),
                      ("periods", "message-level issue schedules"),
                      ("drop_prob", "message-drop fault injection"),
                      ("trace_msgs", "message-dequeue event tracing"),
                      ("flight_dir", "the telemetry flight recorder"),
                      ("admission", "mailbox backpressure")):
        if getattr(args, flag):
            print(f"error: --{flag.replace('_', '-')} needs the mailbox "
                  f"network ({why}); use --engine async", file=sys.stderr)
            return 2

    if args.sweep_seeds is not None:
        if args.sweep_seeds < 1:
            print("error: --sweep-seeds must be >= 1", file=sys.stderr)
            return 2
        if not args.test_dir:
            print("error: --sweep-seeds needs a <test_directory> with "
                  "accepted run_* outcomes", file=sys.stderr)
            return 2
        for flag in ("resume", "save_checkpoint", "run_cycles",
                     "trace_log", "check", "check_strict", "metrics",
                     "arb_seed", "dump"):
            if getattr(args, flag) not in (None, False):
                print(f"error: --{flag.replace('_', '-')} cannot combine "
                      "with --sweep-seeds (the sweep reports matches "
                      "only)", file=sys.stderr)
                return 2

    seed = args.arb_seed if args.arb_seed is not None else 0
    if args.resume:
        cfg, st, meta = ckpt.load_checkpoint(args.resume)
        if meta.get("kind") != "sync":
            print("error: checkpoint was written by the async engine; "
                  "resume it without --engine sync", file=sys.stderr)
            return 2
        if (args.drain_depth is not None or args.txn_width is not None
                or args.deep_window or args.deep_slots is not None
                or args.deep_waves is not None):
            # pure compute knobs (window shape; no state shapes depend
            # on them) — overridable on resume like the async path's
            # admission/drop knobs
            import dataclasses as _dc
            over = {}
            if args.drain_depth is not None:
                over["drain_depth"] = args.drain_depth
            if args.txn_width is not None:
                over["txn_width"] = args.txn_width
            if args.deep_window:
                over["deep_window"] = True
            if args.deep_waves is not None:
                over["deep_waves"] = args.deep_waves
            if args.deep_slots is not None:
                # an omitted --deep-slots keeps the checkpoint's slot
                # count: the flag default is indistinguishable from an
                # explicit value, and silently reshaping the round on
                # resume was an advisor finding (round 3)
                over["deep_slots"] = args.deep_slots
            old_cfg = cfg
            cfg = _dc.replace(cfg, **over)
            # changing the lane-key slot-bit width (SB) on resume would
            # leave stale DM_CLAIM keys packed under the old layout in
            # the checkpointed dm — stale keys could then compare below
            # fresh ones, breaking the countdown invariant (advisor,
            # round 4). The layout is (deep_window, slot_bits,
            # deep_read_storm): turning deep windows on adds the ev
            # tag bit, waves add slot bits, read storms add the is_rd
            # bit above the priority field.
            def _layout(c):
                return (c.deep_window, se.slot_bits(c),
                        c.deep_read_storm)
            if _layout(old_cfg) != _layout(cfg) and hasattr(st, "dm"):
                st = st.replace(dm=se.reset_claims(st.dm))
        if args.arb_seed is not None:
            st = st.replace(seed=np.int32(args.arb_seed))
    else:
        dims = dict(num_nodes=args.nodes)
        if args.drain_depth is not None:
            dims["drain_depth"] = args.drain_depth
        if args.txn_width is not None:
            dims["txn_width"] = args.txn_width
        if args.deep_window:
            dims.update(deep_window=True,
                        deep_slots=(8 if args.deep_slots is None
                                    else args.deep_slots),
                        deep_waves=(1 if args.deep_waves is None
                                    else args.deep_waves),
                        txn_width=dims.get("txn_width", 3))
            dims.setdefault("drain_depth", 13)
        if args.procedural:
            cfg = SystemConfig.scale(
                procedural="uniform", max_instrs=1, proc_seed=args.seed,
                queue_capacity=args.queue_capacity or 64, **dims)
            st = se.procedural_state(cfg, args.trace_len, seed=seed)
        elif args.workload:
            cfg = SystemConfig.scale(
                queue_capacity=args.queue_capacity or 64, **dims)
            system = CoherenceSystem.from_workload(
                cfg, args.workload, trace_len=args.trace_len,
                seed=args.seed)
        elif args.test_dir:
            cfg = SystemConfig.reference(**dims)
            path = os.path.join(args.tests_root, args.test_dir)
            try:
                system = CoherenceSystem.from_test_dir(path, cfg)
            except FileNotFoundError as e:
                print(e, file=sys.stderr)
                return 1
            for n in range(cfg.num_nodes):
                print(f"Processor {n} initialized")  # assignment.c:850
        else:
            print("error: provide <test_directory> or --workload",
                  file=sys.stderr)
            return 2
        if not args.procedural:
            st = se.from_sim_state(cfg, system.state, seed=seed)

    if args.sweep_seeds is not None:
        # batched seed sweep over the freshly built machine: one vmapped
        # ensemble dispatch replaces the reference's sleep-kill-diff
        # retry loop (test3.sh:6-33)
        import jax

        from ue22cs343bb1_openmp_assignment_tpu.utils import search
        path = os.path.join(args.tests_root, args.test_dir)
        named = search.load_accepted_named(path, cfg.num_nodes)
        if not named:
            print(f"error: {path} has no run_* accepted-outcome "
                  "directories", file=sys.stderr)
            return 2
        ens = search.sweep_seeds(
            cfg, system.state, range(args.sweep_seeds),
            max_rounds=min(args.max_cycles, se.claim_max_rounds(cfg) - 1))
        quiet = np.asarray(jax.vmap(lambda x: x.quiescent())(ens))
        if not quiet.all():
            print(f"warning: {int((~quiet).sum())} of {args.sweep_seeds} "
                  f"replicas not quiescent after --max-cycles "
                  f"{args.max_cycles} rounds; their dumps cannot match",
                  file=sys.stderr)
        matches = {}
        for r in range(args.sweep_seeds):
            if not quiet[r]:
                continue
            dumps = search.replica_dumps(cfg, ens, r)
            for name, acc in named:
                if dumps == acc:
                    matches[r] = name
                    break
        print(json.dumps({"matches": {str(k): v
                                      for k, v in matches.items()},
                          "seeds_tried": args.sweep_seeds,
                          "accepted_runs": len(named)}))
        return 0 if matches else 4

    if args.trace_log:
        from ue22cs343bb1_openmp_assignment_tpu.utils import eventlog
        chunk = 32
        cap = (args.run_cycles if args.run_cycles is not None
               else args.max_cycles)
        base = int(st.round)
        all_events = []
        done = 0
        while done < cap:
            n = min(chunk, cap - done)
            st, ev = se.run_rounds_traced(cfg, st, n)
            all_events.append({k: np.asarray(v) for k, v in ev.items()})
            done += n
            if args.run_cycles is None and bool(st.quiescent()):
                break
        merged = {k: np.concatenate([e[k] for e in all_events])
                  for k in all_events[0]} if all_events else {}
        if merged:
            eventlog.write_sync_log(args.trace_log, merged, base)
        else:
            open(args.trace_log, "w").close()
    elif args.run_cycles is not None:
        st = se.run_rounds(cfg, st, args.run_cycles)
    else:
        st = se.run_sync_to_quiescence(cfg, st, 16, args.max_cycles)
    if args.save_checkpoint:
        ckpt.save_checkpoint(args.save_checkpoint, cfg, st)
    if args.run_cycles is None and not bool(st.quiescent()):
        print(f"warning: not quiescent after {args.max_cycles} rounds "
              "(conflict retries still pending; raise --max-cycles)",
              file=sys.stderr)
    if args.check or args.check_strict:
        try:
            report = se.check_exact_directory(cfg, st)
        except AssertionError as e:
            print(f"invariant check FAILED: {e}", file=sys.stderr)
            return 3
        print(f"invariant check passed (exact directory); report: "
              f"{json.dumps(report)}", file=sys.stderr)
    if args.test_dir or args.dump:
        write_dumps(cfg, se.to_dump_view(cfg, st), args.out_dir)
    if args.metrics:
        from ue22cs343bb1_openmp_assignment_tpu.obs import schema as obs
        m = {f: int(getattr(st.metrics, f))
             for f in st.metrics.__dataclass_fields__}
        engine = "deep" if cfg.deep_window else "sync"
        print(json.dumps(obs.from_sync(m, engine)), file=sys.stderr)
    return 0


def _main_native(args) -> int:
    """--engine native: the C++ oracle as an execution backend.

    Same observable semantics as the async JAX engine (message-level
    cycles, schedule knobs); host-only, no device."""
    import types as _t

    from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
    from ue22cs343bb1_openmp_assignment_tpu.native.bindings import NativeEngine
    from ue22cs343bb1_openmp_assignment_tpu.utils.golden import write_dumps
    from ue22cs343bb1_openmp_assignment_tpu.utils.trace import load_test_dir

    for flag, why in (("drop_prob", "fault injection"),
                      ("trace_log", "event tracing"),
                      ("flight_dir", "the telemetry flight recorder"),
                      ("admission", "admission gating"),
                      ("save_checkpoint", "checkpointing"),
                      ("resume", "checkpointing"),
                      ("check", "vectorized invariant checking"),
                      ("check_strict", "vectorized invariant checking")):
        if getattr(args, flag):
            print(f"error: --{flag.replace('_', '-')} ({why}) is a JAX-"
                  "engine feature; use --engine async", file=sys.stderr)
            return 2

    if args.workload:
        from ue22cs343bb1_openmp_assignment_tpu.models import workloads
        cfg = SystemConfig.scale(num_nodes=args.nodes,
                                 max_instrs=args.trace_len,
                                 queue_capacity=args.queue_capacity or 256)
        import jax as _jax
        arrs = workloads.GENERATORS[args.workload](
            _jax.random.PRNGKey(args.seed), cfg, args.trace_len)
        eng = NativeEngine(cfg)
        eng.load_instr_arrays(*(np.asarray(a) for a in arrs))
    elif args.test_dir:
        cfg = SystemConfig.reference(num_nodes=args.nodes)
        path = os.path.join(args.tests_root, args.test_dir)
        try:
            traces = load_test_dir(path, cfg.num_nodes, cfg.max_instrs)
        except FileNotFoundError as e:
            print(e, file=sys.stderr)
            return 1
        eng = NativeEngine(cfg)
        eng.load_traces(traces)
        for n in range(cfg.num_nodes):
            print(f"Processor {n} initialized")  # assignment.c:850
    else:
        print("error: provide <test_directory> or --workload",
              file=sys.stderr)
        return 2

    if args.delays or args.periods:
        for knob in ("delays", "periods"):
            vals = getattr(args, knob)
            if vals and len(vals) != cfg.num_nodes:
                print(f"error: --{knob} needs one value per node "
                      f"(got {len(vals)}, --nodes is {cfg.num_nodes})",
                      file=sys.stderr)
                return 2
        eng.set_schedule(args.delays or None, args.periods or None)
    if args.arb_seed is not None:
        eng.set_arbitration(_arb_rank(args.arb_seed, cfg.num_nodes))

    eng.run(args.run_cycles if args.run_cycles is not None
            else args.max_cycles)
    if args.run_cycles is None and not eng.quiescent:
        print(f"warning: not quiescent after {args.max_cycles} cycles",
              file=sys.stderr)
    if args.test_dir or args.dump:
        write_dumps(cfg, _t.SimpleNamespace(**eng.export_state()),
                    args.out_dir)
    if args.metrics:
        from ue22cs343bb1_openmp_assignment_tpu.obs import schema as obs
        print(json.dumps(obs.from_native(eng.metrics())), file=sys.stderr)
    return 0


def _main_omp(args) -> int:
    """--engine omp: the reference OpenMP binary as a backend.

    Closes the last literal gap to BASELINE's "--backend={omp,jax}"
    north-star flag: builds the reference source (``gcc -fopenmp``,
    its documented build line) and runs it on the test directory
    exactly as its harness does (``test3.sh``: background run, wait,
    SIGKILL — the program never exits on its own,
    ``assignment.c:126-135``), leaving core_<n>_output.txt in
    --out-dir. The binary is the reference, so only the reference's
    surface is available: a <test_directory> of 4 cores, no knobs."""
    import shutil
    import signal
    import subprocess
    import tempfile
    import time

    if not args.test_dir:
        print("error: --engine omp runs the reference binary, which "
              "reads a <test_directory>", file=sys.stderr)
        return 2
    for flag in ("workload", "delays", "periods", "arb_seed", "admission",
                 "drop_prob", "fault_seed", "trace_log", "trace_msgs",
                 "save_checkpoint", "resume", "check", "check_strict",
                 "metrics", "dump", "run_cycles", "procedural",
                 "drain_depth", "txn_width", "deep_window", "deep_slots",
                 "deep_waves", "queue_capacity", "sweep_seeds"):
        v = getattr(args, flag)
        # identity checks: 0 and 0.0 compare equal to False but are
        # explicit user values and must be rejected, not dropped
        if v is None or v is False or (isinstance(v, list) and not v):
            continue
        print(f"error: --{flag.replace('_', '-')} is a JAX/native-"
              "engine feature; the omp backend is the reference "
              "binary itself", file=sys.stderr)
        return 2
    if args.nodes != 4:
        print("error: the reference binary is fixed at 4 cores "
              "(assignment.c NUM_CORES)", file=sys.stderr)
        return 2
    if not os.path.isfile(args.reference_src):
        print(f"error: reference source not found at "
              f"{args.reference_src} (set --reference-src)",
              file=sys.stderr)
        return 1
    if shutil.which("gcc") is None:
        print("error: --engine omp needs gcc", file=sys.stderr)
        return 1

    tests_root = os.path.abspath(args.tests_root)
    suite_dir = os.path.join(tests_root, args.test_dir)
    if not os.path.isdir(suite_dir):
        print(f"error: no such test directory: {suite_dir}",
              file=sys.stderr)
        return 1
    with tempfile.TemporaryDirectory(prefix="omp-backend-") as build:
        exe = os.path.join(build, "cache_simulator")
        try:
            subprocess.run(
                ["gcc", "-fopenmp", "-std=c2x", args.reference_src,
                 "-o", exe],
                check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            print(f"error: reference build failed:\n{e.stderr}",
                  file=sys.stderr)
            return 1
        # the loader hardcodes a tests/ prefix relative to CWD
        # (assignment.c:824)
        os.symlink(tests_root, os.path.join(build, "tests"))
        outs = [os.path.join(build, f"core_{n}_output.txt")
                for n in range(4)]
        proc = subprocess.Popen([exe, args.test_dir], cwd=build,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        # run-until-stable-then-kill (the reference never exits): poll
        # for all four dumps holding stable sizes, then SIGKILL
        deadline = max(10.0, args.max_cycles / 10_000)
        time.sleep(1.0)
        t0, last, stable = time.monotonic(), None, 0
        while time.monotonic() - t0 < deadline:
            sizes = [os.path.getsize(o) if os.path.exists(o) else -1
                     for o in outs]
            stable = stable + 1 if (min(sizes) >= 0
                                    and sizes == last) else 0
            if stable >= 3:
                break
            last = sizes
            time.sleep(0.25)
        proc.send_signal(signal.SIGKILL)
        proc.wait()
        missing = [o for o in outs if not os.path.exists(o)]
        if missing:
            print("error: reference binary produced no output within "
                  f"{deadline:.0f}s", file=sys.stderr)
            return 1
        if stable < 3:
            # files exist but their sizes never held stable: the binary
            # was likely killed mid-write; truncated dumps must not be
            # handed out as results
            print("error: reference outputs never stabilized within "
                  f"{deadline:.0f}s (possibly mid-write at kill time); "
                  "rerun on a less loaded host", file=sys.stderr)
            return 1
        os.makedirs(args.out_dir, exist_ok=True)
        for o in outs:
            shutil.copy(o, os.path.join(args.out_dir,
                                        os.path.basename(o)))
    return 0


def main(argv=None) -> int:
    raw = sys.argv[1:] if argv is None else list(argv)
    if raw[:1] == ["analyze"]:
        # the static-analysis gate has its own parser (and no need for
        # the simulator's positional workload argument)
        from ue22cs343bb1_openmp_assignment_tpu.analysis import runner
        return runner.main(raw[1:])
    if raw[:1] == ["stats"]:
        from ue22cs343bb1_openmp_assignment_tpu.obs import cli as obs_cli
        return obs_cli.main_stats(raw[1:])
    if raw[:1] == ["trace"]:
        from ue22cs343bb1_openmp_assignment_tpu.obs import cli as obs_cli
        return obs_cli.main_trace(raw[1:])
    if raw[:1] == ["bench-diff"]:
        from ue22cs343bb1_openmp_assignment_tpu.obs import cli as obs_cli
        return obs_cli.main_bench_diff(raw[1:])
    if raw[:1] == ["txns"]:
        from ue22cs343bb1_openmp_assignment_tpu.obs import cli as obs_cli
        return obs_cli.main_txns(raw[1:])
    if raw[:1] == ["critical-path"]:
        from ue22cs343bb1_openmp_assignment_tpu.obs import cli as obs_cli
        return obs_cli.main_critpath(raw[1:])
    if raw[:1] == ["perf-report"]:
        from ue22cs343bb1_openmp_assignment_tpu.obs import cli as obs_cli
        return obs_cli.main_perfreport(raw[1:])
    if raw[:1] == ["profile"]:
        from ue22cs343bb1_openmp_assignment_tpu.obs import cli as obs_cli
        return obs_cli.main_profile(raw[1:])
    if raw[:1] == ["dashboard"]:
        from ue22cs343bb1_openmp_assignment_tpu.obs import cli as obs_cli
        return obs_cli.main_dashboard(raw[1:])
    if raw[:1] == ["serve"]:
        from ue22cs343bb1_openmp_assignment_tpu import serve as serve_mod
        return serve_mod.main(raw[1:])
    if raw[:1] == ["soak"]:
        from ue22cs343bb1_openmp_assignment_tpu import soak as soak_mod
        return soak_mod.main(raw[1:])
    if raw[:1] == ["daemon"]:
        from ue22cs343bb1_openmp_assignment_tpu.daemon import (
            server as daemon_server)
        return daemon_server.main(raw[1:])
    if raw[:1] == ["submit"]:
        from ue22cs343bb1_openmp_assignment_tpu.daemon import (
            client as daemon_client)
        return daemon_client.main(raw[1:])
    if raw[:1] == ["watch"]:
        from ue22cs343bb1_openmp_assignment_tpu.daemon import (
            client as daemon_client)
        return daemon_client.main_watch(raw[1:])
    if raw[:1] == ["top"]:
        from ue22cs343bb1_openmp_assignment_tpu.obs import (
            fleet as fleet_mod)
        return fleet_mod.main(raw[1:])
    if raw[:1] == ["replay"]:
        from ue22cs343bb1_openmp_assignment_tpu import (
            replay as replay_mod)
        return replay_mod.main(raw[1:])
    args = build_parser().parse_args(raw)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    if args.procedural and args.engine != "sync":
        print("error: --procedural needs --engine sync", file=sys.stderr)
        return 2
    if args.procedural and (args.test_dir or args.workload):
        print("error: --procedural generates its own stream; drop the "
              "<test_directory>/--workload argument", file=sys.stderr)
        return 2
    if args.sweep_seeds and args.engine != "sync":
        print("error: --sweep-seeds is an ensemble sweep on the "
              "transactional engine; add --engine sync", file=sys.stderr)
        return 2
    if args.txn_width is not None and args.engine != "sync":
        print("error: --txn-width sizes the transactional engine's "
              "multi-transaction window; add --engine sync",
              file=sys.stderr)
        return 2
    if args.deep_window and args.engine != "sync":
        print("error: --deep-window is a transactional-engine round "
              "mode; add --engine sync", file=sys.stderr)
        return 2
    if args.engine == "sync":
        return _main_sync(args)
    if args.engine == "native":
        return _main_native(args)
    if args.engine == "omp":
        return _main_omp(args)

    from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
    from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem

    for knob in ("delays", "periods"):
        vals = getattr(args, knob)
        if vals and len(vals) != args.nodes:
            print(f"error: --{knob} needs one value per node "
                  f"(got {len(vals)}, --nodes is {args.nodes})",
                  file=sys.stderr)
            return 2

    if args.resume:
        import dataclasses as _dc
        try:
            system = CoherenceSystem.load(args.resume)
        except ValueError as e:
            if "SyncState" in str(e) or "instr_pack" in str(e):
                print("error: checkpoint was written by the transactional "
                      "engine; resume it with --engine sync",
                      file=sys.stderr)
                return 2
            raise
        cfg = system.cfg
        if args.nodes != cfg.num_nodes and (args.delays or args.periods):
            print("error: --delays/--periods with --resume need --nodes to "
                  f"match the checkpoint ({cfg.num_nodes})", file=sys.stderr)
            return 2
        if (args.queue_capacity is not None
                and args.queue_capacity != cfg.queue_capacity):
            print("error: --queue-capacity is shape-determining and cannot "
                  f"change on --resume (checkpoint has "
                  f"{cfg.queue_capacity})", file=sys.stderr)
            return 2
        # behavior knobs (shape-free) override the checkpointed config —
        # this is the watchdog's recommended recovery path
        cfg_over = {}
        if args.admission is not None:
            cfg_over["admission_window"] = args.admission
        if args.drop_prob is not None:
            cfg_over["drop_prob"] = args.drop_prob
        if cfg_over:
            cfg = _dc.replace(cfg, **cfg_over)
            system = _dc.replace(system, cfg=cfg)
        # schedule knobs override the checkpointed ones when given
        overrides = _schedule_knobs(args, cfg.num_nodes)
        if args.fault_seed is not None:
            from ue22cs343bb1_openmp_assignment_tpu.state import (
                fault_key_from_seed)
            overrides["fault_key"] = fault_key_from_seed(args.fault_seed)
        if overrides:
            system = _dc.replace(
                system, state=system.state.replace(**overrides))
    elif args.workload:
        cfg = SystemConfig.scale(num_nodes=args.nodes,
                                 queue_capacity=args.queue_capacity or 256,
                                 admission_window=args.admission,
                                 drop_prob=args.drop_prob or 0.0)
        init_kw = _schedule_knobs(args, args.nodes)
        init_kw["fault_seed"] = args.fault_seed or 0
        system = CoherenceSystem.from_workload(
            cfg, args.workload, trace_len=args.trace_len, seed=args.seed,
            init_kw=init_kw)
    elif args.test_dir:
        init_kw = _schedule_knobs(args, args.nodes)
        init_kw["fault_seed"] = args.fault_seed or 0
        cfg = SystemConfig.reference(num_nodes=args.nodes,
                                     admission_window=args.admission,
                                     drop_prob=args.drop_prob or 0.0)
        path = os.path.join(args.tests_root, args.test_dir)
        try:
            system = CoherenceSystem.from_test_dir(path, cfg, **init_kw)
        except FileNotFoundError as e:
            print(e, file=sys.stderr)  # clean exit like assignment.c:826-829
            return 1
        for n in range(cfg.num_nodes):
            print(f"Processor {n} initialized")  # assignment.c:850
    else:
        print("error: provide <test_directory> or --workload",
              file=sys.stderr)
        return 2

    # flight recorder: snapshot the pre-run state; on an incident the
    # deterministic engine replays it under telemetry capture, so the
    # normal path pays nothing
    flight0 = system.state if args.flight_dir else None

    def _flight_dump(reason: str, detail: str = "") -> None:
        if flight0 is None:
            return
        from ue22cs343bb1_openmp_assignment_tpu.obs import flight
        fr = flight.FlightRecorder(system.cfg, flight0,
                                   k=args.flight_ring)
        fr.run(max(1, int(system.state.cycle) - int(flight0.cycle)),
               stop_on_quiescence=False)
        out = os.path.join(args.flight_dir,
                           f"incident_{reason.split(':', 1)[0]}")
        fr.dump_incident(out, reason, detail)
        print(f"flight recorder: incident dumped to {out}",
              file=sys.stderr)

    if args.trace_log:
        from ue22cs343bb1_openmp_assignment_tpu.utils import eventlog
        trace_base = int(system.state.cycle)
        if args.run_cycles is not None:
            system, events = system.run_cycles_traced(args.run_cycles)
        else:
            system, events = system.run_traced(args.max_cycles)
        kinds = ("instr", "msg") if args.trace_msgs else ("instr",)
        if events:
            eventlog.write_log(args.trace_log, events, kinds,
                               base_cycle=trace_base)
        else:
            open(args.trace_log, "w").close()
    elif args.run_cycles is not None:
        system = system.run_cycles(args.run_cycles)
    else:
        system = system.run(args.max_cycles)
    if args.save_checkpoint:
        system.save(args.save_checkpoint)
    if args.run_cycles is None and not system.quiescent:
        m = system.metrics
        hint = ""
        if m["msgs_dropped"] > 0:
            hint = (f" ({m['msgs_dropped']} messages dropped on full "
                    "mailboxes — likely livelocked; rerun with --admission "
                    f"{max(1, cfg.queue_capacity // 6)} or a larger "
                    "--queue-capacity)")
        if m["msgs_injected_dropped"] > 0:
            hint += (f" ({m['msgs_injected_dropped']} messages dropped by "
                     f"--drop-prob {cfg.drop_prob} fault injection)")
        print(f"warning: not quiescent after {args.max_cycles} cycles{hint}",
              file=sys.stderr)
        report = system.stall_report(args.stall_threshold)
        if report["count"]:
            print(f"watchdog: {report['count']} node(s) stalled "
                  f">{args.stall_threshold} cycles on one request "
                  f"(first few: {report['nodes'][:4]}); recover by "
                  "resuming a checkpoint with backpressure (--admission) "
                  "or a different schedule", file=sys.stderr)
            _flight_dump("watchdog:stall",
                         f"{report['count']} node(s) stalled "
                         f">{args.stall_threshold} cycles; nodes "
                         f"{report['nodes'][:4]}")
        else:
            _flight_dump("hang:not_quiescent",
                         f"not quiescent after {args.max_cycles} "
                         f"cycles{hint}")

    if args.check or args.check_strict:
        try:
            report = system.check_invariants(
                strict_coherence=args.check_strict)
        except AssertionError as e:
            print(f"invariant check FAILED: {e}", file=sys.stderr)
            _flight_dump("invariant:check", str(e))
            return 3
        if not system.quiescent:
            # the coherence tier is only defined at quiescence
            if args.check_strict:
                print("invariant check FAILED: machine not quiescent — "
                      "coherence tier not checkable", file=sys.stderr)
                _flight_dump("invariant:not_quiescent",
                             "coherence tier not checkable")
                return 3
            print("invariant check passed (engine tier only; not "
                  "quiescent, coherence tier skipped)", file=sys.stderr)
        else:
            print(f"invariant check passed; coherence report: "
                  f"{json.dumps(report)}", file=sys.stderr)
    if args.test_dir or args.dump:  # golden dumps (trace or forced)
        system.write_dumps(args.out_dir)
    if args.metrics:
        from ue22cs343bb1_openmp_assignment_tpu.obs import schema as obs
        print(json.dumps(obs.from_async(system.metrics)), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
