"""Open-loop soak harness: latency under load, measured honestly.

``serve.serve`` is closed-loop — the whole job stream is present at
entry, so it measures *throughput* (jobs/sec) but cannot say anything
about latency under a live arrival process. This module is the
open-loop complement (ROADMAP serving-observability): a deterministic,
seeded Poisson stream of mixed-traffic jobs (:func:`soak_stream`) is
*released* at its scheduled arrival times regardless of how the
machine is doing — arrivals never wait for completions, which is what
makes the measurement free of coordinated omission (PERF.md): a job's
``queue_wait_s`` starts at its **scheduled** arrival, so a stalled
server shows up as queue growth and fat latency tails instead of
silently slowing the load generator down.

The scheduler is a turn loop over the same wave machinery as serve:

1. release every arrival whose scheduled time has passed into the
   admission queue (span ``submit`` stamped at the scheduled time);
2. admit queued jobs into free batch slots (``state.set_state`` — the
   wave jit stays warm, same one-compile contract as serve);
3. sample ``(t, queue_depth, slots_busy)`` — the host-side time series
   behind the backpressure verdict (obs.timeseries.serve_series);
4. run one batched wave to quiescence, stamp spans, extract and free
   every finished slot. When no slot is occupied the clock instead
   jumps/sleeps to the next scheduled arrival.

All timing reads the injected clock (obs.clock): under a
:class:`~ue22cs343bb1_openmp_assignment_tpu.obs.clock.VirtualClock`
every timestamp is a pure function of the schedule, so two soaks with
the same seed emit byte-identical ``cache-sim/serve-trace/v1`` docs —
the determinism gate in tests/test_soak.py.

The summary doc carries the p50/p95/p99 job-latency block
(nearest-rank, obs.timeseries.latency_summary), the queue/occupancy
series, padding-waste and ``mb_dropped`` totals, and a backpressure
verdict (arrival rate vs measured drain rate). ``--slo p95=<ms>``
turns the run into a gate: a breach exits :data:`EXIT_SLO_BREACH` (4,
the obs.regress regression code) after dumping a flight-recorder-style
incident directory (:func:`dump_incident`) with the slowest jobs'
spans, the queue time series, and the Perfetto rendering of the whole
soak.
"""

from __future__ import annotations

import json
import os
import pathlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.obs.clock import (MonotonicClock,
                                                          VirtualClock)
from ue22cs343bb1_openmp_assignment_tpu.serve import (
    DEFAULT_MIX, JobSpec, SpanBook, build_job_arrays, build_job_state,
    job_config, protocol_phase, serve_trace_doc, slot_config,
    weighted_padding_waste, _host_quiescent, _STATE_CACHE)

SCHEMA_ID = "cache-sim/soak/v1"
INCIDENT_SCHEMA_ID = "cache-sim/soak-incident/v1"

#: process exit code on an SLO breach — deliberately the same code
#: obs.regress uses for a bench regression, so CI treats both alike
EXIT_SLO_BREACH = 4

#: latency percentiles an ``--slo`` spec may bound
SLO_METRICS = ("p50", "p95", "p99")

#: slowest jobs carried (with full spans) into an incident doc
INCIDENT_SLOWEST = 5


# lint: host
def soak_stream(arrival_rate: float, duration_s: float, nodes: int = 4,
                trace_len: int = 8, protocol: str = "mesi",
                mix: Tuple[str, ...] = DEFAULT_MIX,
                seed: int = 0) -> List[Tuple[float, JobSpec]]:
    """Deterministic open-loop arrival schedule: a seeded Poisson
    process (exponential inter-arrival gaps at ``arrival_rate`` jobs/s)
    over ``duration_s`` seconds of mixed-traffic jobs — the same
    workload mix and seed convention as serve.mixed_jobs, plus an
    arrival offset per job. Same (rate, duration, seed) → the same
    schedule, byte for byte."""
    if arrival_rate <= 0:
        raise ValueError(f"arrival_rate must be > 0, got {arrival_rate}")
    if duration_s <= 0:
        raise ValueError(f"duration_s must be > 0, got {duration_s}")
    rng = np.random.default_rng(seed)
    arrivals: List[Tuple[float, JobSpec]] = []
    t = 0.0
    i = 0
    while True:
        t += float(rng.exponential(1.0 / arrival_rate))
        if t >= duration_s:
            break
        arrivals.append((t, JobSpec(
            name=f"job{i:03d}", workload=mix[i % len(mix)], nodes=nodes,
            trace_len=trace_len, seed=i, protocol=protocol)))
        i += 1
    return arrivals


# lint: host
def bursty_stream(arrival_rate: float, duration_s: float,
                  nodes: int = 4, trace_len: int = 8,
                  protocol: str = "mesi",
                  mix: Tuple[str, ...] = DEFAULT_MIX, seed: int = 0,
                  on_s: float = 0.25, off_s: float = 0.25,
                  peak_factor: float = 4.0
                  ) -> List[Tuple[float, JobSpec]]:
    """On/off (interrupted) Poisson arrivals: exponentially
    distributed ON windows (mean ``on_s``) emit a Poisson stream at
    ``arrival_rate * peak_factor`` jobs/s, alternating with silent
    OFF windows (mean ``off_s``) — the heavy-tailed burst pattern a
    uniform Poisson stream cannot produce (queues build during
    bursts even when the machine keeps up with the AVERAGE rate).
    Seeded-deterministic like :func:`soak_stream`: same (rate,
    duration, seed, on/off/peak) → the same schedule, byte for byte.
    """
    if arrival_rate <= 0:
        raise ValueError(f"arrival_rate must be > 0, got {arrival_rate}")
    if duration_s <= 0:
        raise ValueError(f"duration_s must be > 0, got {duration_s}")
    if on_s <= 0 or off_s <= 0:
        raise ValueError(f"on_s/off_s must be > 0, got {on_s}/{off_s}")
    if peak_factor <= 0:
        raise ValueError(f"peak_factor must be > 0, got {peak_factor}")
    rng = np.random.default_rng(seed)
    peak = arrival_rate * peak_factor
    arrivals: List[Tuple[float, JobSpec]] = []
    t = 0.0
    i = 0
    on = True
    window_end = float(rng.exponential(on_s))
    while t < duration_s:
        if not on:
            # silent window: jump to its end, open the next burst
            t = window_end
            on = True
            window_end = t + float(rng.exponential(on_s))
            continue
        gap = float(rng.exponential(1.0 / peak))
        if t + gap >= window_end:
            # burst over before the next arrival (memoryless, so the
            # residual gap is simply redrawn in the next ON window)
            t = window_end
            on = False
            window_end = t + float(rng.exponential(off_s))
            continue
        t += gap
        if t >= duration_s:
            break
        arrivals.append((t, JobSpec(
            name=f"job{i:03d}", workload=mix[i % len(mix)], nodes=nodes,
            trace_len=trace_len, seed=i, protocol=protocol)))
        i += 1
    return arrivals


# lint: host
def recorded_stream(source) -> List[Tuple[float, JobSpec, str]]:
    """Schedule-from-recording: a ``cache-sim/recording/v1`` artifact
    (path, directory, or loaded doc) → the open-loop schedule
    ``[(t_s, JobSpec, lane)]`` with the ORIGINAL arrival times and
    lanes preserved — yesterday's live traffic as today's soak
    schedule (replay it with ``cache-sim replay``)."""
    from ue22cs343bb1_openmp_assignment_tpu.obs import recording
    rec = source if isinstance(source, dict) else recording.load(source)
    return recording.arrivals(rec)


# lint: host
def soak(arrivals, slots: int = 4, slot_nodes: Optional[int] = None,
         slot_trace_len: Optional[int] = None, chunk: int = 32,
         max_cycles: int = 100_000, queue_capacity: int = 64,
         arrival_rate: Optional[float] = None, clock=None,
         quiet: bool = True, burn=None) -> dict:
    """Run an open-loop arrival schedule ``[(t_offset_s, JobSpec)]``
    through the batched wave machinery; returns the
    ``cache-sim/soak/v1`` summary doc (latency block, queue/occupancy
    series, backpressure verdict, embedded serve-trace doc).

    One protocol per soak: the wave stepper's message phase is a
    static jit argument, so a mixed-protocol stream would interleave
    two wave sequences and the drain-rate verdict would compare apples
    to oranges.

    ``burn`` (an obs.burnrate.BurnRateMonitor) turns the run into a
    CONTINUOUS SLO check: every extracted job feeds one latency
    sample, and ``doc["burnrate"]`` carries the streaming verdict —
    the --slo end-of-run gate's complement.
    """
    import sys

    import jax

    from ue22cs343bb1_openmp_assignment_tpu import state as st
    from ue22cs343bb1_openmp_assignment_tpu.obs import timeseries
    from ue22cs343bb1_openmp_assignment_tpu.ops import step

    arrivals = sorted(arrivals, key=lambda a: a[0])
    specs = [s for _, s in arrivals]
    if not specs:
        raise ValueError("soak needs at least one arrival")
    protocols = sorted({s.protocol for s in specs})
    if len(protocols) > 1:
        raise ValueError(f"soak streams are single-protocol, "
                         f"got {protocols}")
    protocol = protocols[0]
    phase = protocol_phase(protocol)
    scfg = slot_config(specs, slot_nodes, slot_trace_len,
                       queue_capacity, protocol)
    N, T = scfg.num_nodes, scfg.max_instrs

    clock = clock if clock is not None else MonotonicClock()
    t_start = clock.now()
    book = SpanBook(clock)
    pending = [(t_start + dt, s) for dt, s in arrivals]
    queue: List[JobSpec] = []

    if ("empty", scfg) not in _STATE_CACHE:
        _STATE_CACHE[("empty", scfg)] = st.init_state(scfg)
    empty = _STATE_CACHE[("empty", scfg)]
    occupant: List[Optional[JobSpec]] = [None] * slots
    real_by_slot = [0] * slots
    bstate = st.stack_states([empty] * slots)

    samples: List[Tuple[float, int, int]] = []
    waves: List[dict] = []
    job_docs: Dict[str, dict] = {}
    mb_dropped_total = 0

    while pending or queue or any(o is not None for o in occupant):
        now = clock.now()
        # open-loop release: spans stamp the SCHEDULED arrival time,
        # not the release-check time — queue_wait accrues from the
        # moment the load generator meant the job to exist
        while pending and pending[0][0] <= now:
            t_arr, spec = pending.pop(0)
            book.submit(spec.name, t_arr)
            queue.append(spec)
        for i in range(slots):
            if occupant[i] is None and queue:
                spec = queue.pop(0)
                occupant[i] = spec
                real_by_slot[i] = int(np.sum(build_job_arrays(
                    job_config(spec, queue_capacity), spec)[3]))
                bstate = st.set_state(bstate, i, build_job_state(
                    scfg, job_config(spec, queue_capacity), spec))
                book.admitted(spec.name, wave=len(waves) + 1, slot=i)
        busy = sum(1 for o in occupant if o is not None)
        samples.append((now - t_start, len(queue), busy))
        if busy == 0:
            # idle: nothing to run, jump/sleep to the next arrival
            if pending:
                clock.sleep(pending[0][0] - now)
            continue

        real = sum(real_by_slot)
        t0 = clock.now()
        for o in occupant:
            if o is not None:
                book.running(o.name, t0)
        bstate = step.run_wave_to_quiescence(
            scfg, bstate, chunk, max_cycles, phase)
        host = jax.device_get(bstate)
        quiet_mask = _host_quiescent(host)
        clock.on_wave()
        t_wave_end = clock.now()
        budget = slots * N * T
        occ = np.array([o is not None for o in occupant])
        wave_dropped = int(np.sum(
            np.asarray(host.metrics.msgs_dropped)[occ]))
        waves.append({
            "protocol": protocol,
            "jobs": [o.name for o in occupant if o is not None],
            "wall_s": t_wave_end - t0,
            "slot_instr_budget": budget,
            "real_instrs": real,
            "padding_waste": 1.0 - real / budget,
            "mb_dropped": wave_dropped,
        })
        mb_dropped_total += wave_dropped
        if wave_dropped and not quiet:
            print(f"soak: WARNING wave {len(waves)} dropped "
                  f"{wave_dropped} mailbox messages", file=sys.stderr)

        for i, spec in enumerate(occupant):
            if spec is None:
                continue
            ok = bool(quiet_mask[i])
            book.quiescent(spec.name, ok, t_wave_end)
            job_docs[spec.name] = {
                "quiesced": ok,
                "wave": len(waves),
                "slot": i,
                "cycles": int(np.asarray(st.index_state(host, i).cycle)),
            }
            book.extracted(spec.name)
            if burn is not None:
                # spans() is in extraction order: the one extracted()
                # just closed is last — its e2e is the burn sample
                burn.feed(t_wave_end - t_start,
                          book.spans()[-1]["e2e_s"])
            # the finished (quiescent = fixpoint) state stays in place
            # until the slot is refilled — same contract as serve
            occupant[i] = None
            real_by_slot[i] = 0

    wall = clock.now() - t_start
    spans = book.spans()
    series_summary = timeseries.summarize_serve_series(samples)
    latency = timeseries.latency_summary(
        [s["e2e_s"] for s in spans], arrival_rate=arrival_rate,
        queue_depth_peak=series_summary["queue_depth_peak"])
    # drain rate over BUSY time (waves actually running), not wall:
    # wall includes idle gaps waiting for the next arrival, which
    # would make an under-loaded machine look slow — busy-time drain
    # is the service capacity the arrival rate is compared against
    busy_s = sum(w["wall_s"] for w in waves)
    drain = len(spans) / busy_s if busy_s > 0 else 0.0
    doc = {
        "schema": SCHEMA_ID,
        "slots": slots,
        "arrival_rate": arrival_rate,
        "jobs_total": len(spans),
        "jobs_quiesced": sum(1 for d in job_docs.values()
                             if d["quiesced"]),
        "wave_count": len(waves),
        "wall_s": wall,
        "busy_s": busy_s,
        "drain_rate_jobs_per_s": drain,
        "padding_waste": weighted_padding_waste(waves),
        "mb_dropped": mb_dropped_total,
        "latency": latency,
        "series": timeseries.serve_series(samples),
        "series_summary": series_summary,
        "verdict": backpressure_verdict(arrival_rate, drain,
                                        series_summary),
        "burnrate": None if burn is None else burn.summary(),
        "jobs": job_docs,
        "waves": waves,
        "trace": serve_trace_doc(spans, clock.kind),
    }
    return doc


# lint: host
def soak_daemon(arrivals, addr: str,
                arrival_rate: Optional[float] = None,
                lane_mix: Tuple[str, ...] = ("interactive", "batch"),
                poll_s: float = 0.002, timeout_s: float = 300.0,
                prefix: str = "", quiet: bool = True,
                lanes: Optional[List[str]] = None, burn=None) -> dict:
    """Drive the same open-loop arrival schedule through a RUNNING
    daemon's socket instead of in-process waves.

    The release loop is the client: each job is submitted at its
    SCHEDULED arrival time on the client clock — releases never wait
    for completions, so the stream stays coordinated-omission-free —
    and jobs alternate through ``lane_mix`` (mixed interactive+batch
    tenancy). The headline latency block is CLIENT-OBSERVED: scheduled
    release → result available over the socket, the number a user of
    the service experiences (queueing, scheduling, and transport
    included). The embedded ``trace`` doc is the daemon's own span
    book (server-side time base, exact queue_wait+run+extract == e2e
    decomposition) — the two latency views are reported side by side,
    not mixed, because they live on different clocks.

    Backpressure rejections surface in ``doc["rejected"]`` and the
    verdict; they are never silent and never touch ``mb_dropped``.

    ``prefix`` is prepended to every job name: a daemon rejects
    duplicate names, so successive soaks against the SAME daemon must
    use distinct prefixes (the CLI derives one from ``--seed``).

    ``lanes`` pins each arrival's lane explicitly (aligned with the
    input ``arrivals`` order) — the replay path uses it to preserve a
    recording's ORIGINAL lane per job; by default jobs alternate
    through ``lane_mix``.
    """
    import dataclasses
    import time as _time

    from ue22cs343bb1_openmp_assignment_tpu.daemon.client import (
        DaemonClient)
    from ue22cs343bb1_openmp_assignment_tpu.obs import timeseries

    items = list(arrivals)
    if lanes is None:
        lanes = [lane_mix[i % len(lane_mix)] for i in range(len(items))]
    if len(lanes) != len(items):
        raise ValueError(f"lanes must align with arrivals: "
                         f"{len(lanes)} vs {len(items)}")
    items = sorted(
        ((t, dataclasses.replace(spec, name=prefix + spec.name), lane)
         for (t, spec), lane in zip(items, lanes)),
        key=lambda a: (a[0], a[1].name))
    if not items:
        raise ValueError("soak needs at least one arrival")
    lanes = [lane for _, _, lane in items]

    clock = MonotonicClock()
    with DaemonClient(addr) as client:
        client.wait_up(timeout_s=min(30.0, timeout_s))
        t_start = clock.now()
        deadline = t_start + timeout_s
        pending = [(t_start + dt, spec, lane)
                   for dt, spec, lane in items]
        outstanding: Dict[str, Tuple[float, str]] = {}
        done: Dict[str, dict] = {}
        e2e: Dict[str, Tuple[float, str]] = {}
        rejected: List[dict] = []
        samples: List[Tuple[float, int, int]] = []
        busy_now = 0
        turn = 0
        poll_names: List[str] = []
        while pending or outstanding:
            now = clock.now()
            if now > deadline:
                raise RuntimeError(
                    f"daemon soak timed out after {timeout_s}s with "
                    f"{len(outstanding)} job(s) outstanding")
            while pending and pending[0][0] <= now:
                t_sched, spec, lane = pending.pop(0)
                r = client.submit(spec, lane=lane)
                if r.get("status") == "queued":
                    outstanding[spec.name] = (t_sched, lane)
                else:
                    rejected.append({"job": spec.name, "lane": lane,
                                     "reason": r.get("reason",
                                                     r.get("error"))})
            # poll a bounded rotation of outstanding jobs per turn so
            # release timing stays open-loop even with a deep backlog
            if not poll_names:
                poll_names = sorted(outstanding)
            for name in poll_names[:8]:
                if name not in outstanding:
                    continue
                r = client.result(name)
                if r.get("status") == "done":
                    t_sched, lane = outstanding.pop(name)
                    t_done = clock.now()
                    e2e[name] = (t_done - t_sched, lane)
                    if burn is not None:
                        # client-observed sample on the client clock —
                        # the continuous twin of the headline latency
                        burn.feed(t_done - t_start, t_done - t_sched)
                    done[name] = {
                        "quiesced": bool(r["quiesced"]),
                        "lane": r["lane"], "bucket": r["bucket"],
                        "cycles": int(r["cycles"]),
                    }
            poll_names = poll_names[8:]
            if turn % 20 == 0:
                busy_now = sum(b["busy"]
                               for b in client.stats()["buckets"])
            samples.append((now - t_start, len(outstanding), busy_now))
            turn += 1
            if pending and not outstanding:
                clock.sleep(max(0.0, pending[0][0] - clock.now()))
            elif outstanding:
                _time.sleep(poll_s)
        wall = clock.now() - t_start
        stats = client.stats()
        trace = client.trace()

    series_summary = timeseries.summarize_serve_series(samples)
    lat_s = [v[0] for v in e2e.values()]
    latency = timeseries.latency_summary(
        lat_s, arrival_rate=arrival_rate,
        queue_depth_peak=series_summary["queue_depth_peak"])
    lane_latency = {
        lane: timeseries.latency_summary(
            [s for s, ln in e2e.values() if ln == lane])
        for lane in sorted(set(lanes))}
    drain = stats["drain_rate_jobs_per_s"]
    return {
        "schema": SCHEMA_ID,
        "transport": "daemon",
        "addr": addr,
        "slots": sum(b["slots"] for b in stats["buckets"]),
        "arrival_rate": arrival_rate,
        "jobs_total": len(done) + len(rejected),
        "jobs_quiesced": sum(1 for d in done.values() if d["quiesced"]),
        "rejected": rejected,
        "wave_count": stats["chunks"],
        "wall_s": wall,
        "busy_s": stats["busy_s"],
        "drain_rate_jobs_per_s": drain,
        "padding_waste": stats["padding_waste"] or 0.0,
        "mb_dropped": stats["mb_dropped"],
        "latency": latency,
        "lane_latency": lane_latency,
        "samples_ms": [round(s * 1e3, 6) for s in sorted(lat_s)],
        "series": timeseries.serve_series(samples),
        "series_summary": series_summary,
        "verdict": backpressure_verdict(arrival_rate, drain,
                                        series_summary),
        "burnrate": None if burn is None else burn.summary(),
        "daemon_stats": stats,
        "jobs": done,
        "waves": [],
        "trace": trace,
    }


# lint: host
def backpressure_verdict(arrival_rate: Optional[float], drain: float,
                         series_summary: dict) -> dict:
    """Saturation call: the machine is saturated when jobs arrive
    faster than the measured drain rate — the queue then grows for as
    long as the arrival window lasts (its peak depth is reported
    alongside so the operator sees how far behind it got)."""
    saturated = bool(arrival_rate is not None and drain > 0
                     and arrival_rate > drain)
    return {
        "saturated": saturated,
        "arrival_rate": arrival_rate,
        "drain_rate_jobs_per_s": drain,
        "queue_depth_peak": series_summary["queue_depth_peak"],
    }


# lint: host
def parse_slo(spec: str) -> Dict[str, float]:
    """``"p95=5,p99=20"`` → ``{"p95_ms": 5.0, "p99_ms": 20.0}``;
    bounds are milliseconds on the percentiles in SLO_METRICS."""
    out: Dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad SLO term {part!r} (want p95=<ms>)")
        k, v = part.split("=", 1)
        k = k.strip()
        if k not in SLO_METRICS:
            raise ValueError(f"unknown SLO metric {k!r} "
                             f"(one of {SLO_METRICS})")
        try:
            ms = float(v)
        except ValueError:
            raise ValueError(f"bad SLO bound {v!r} for {k}")
        if ms <= 0:
            raise ValueError(f"SLO bound for {k} must be > 0, got {ms}")
        out[k + "_ms"] = ms
    if not out:
        raise ValueError(f"empty SLO spec {spec!r}")
    return out


# lint: host
def check_slo(latency: Optional[dict],
              slo: Dict[str, float]) -> List[dict]:
    """Breach list (empty = all bounds hold). A soak that completed no
    jobs has no latency block and cannot breach."""
    if latency is None:
        return []
    return [{"metric": k, "limit_ms": limit,
             "observed_ms": latency[k]}
            for k, limit in sorted(slo.items()) if latency[k] > limit]


# lint: host
def dump_incident(out_dir, doc: dict, breaches: List[dict],
                  rec: Optional[dict] = None) -> dict:
    """Write a self-contained SLO-breach incident directory (the
    flight-recorder convention, obs.flight): ``incident.json`` — the
    breaches, the latency block, the backpressure verdict, the
    ``INCIDENT_SLOWEST`` slowest jobs' full spans, and the queue-depth
    time series — plus ``trace.perfetto.json``, the Perfetto rendering
    of every job's lifecycle with flow arrows. When the soak was
    driven from a traffic recording (``rec``, an obs.recording doc),
    the BREACH-WINDOW slice — every job submitted between the first
    submit and last extract of the slowest jobs — is embedded as
    ``recording.jsonl``, making the incident dir itself replayable
    (``cache-sim replay <dir>``). Returns the incident doc."""
    from ue22cs343bb1_openmp_assignment_tpu.obs import (perfetto,
                                                       recording)
    out_dir = str(out_dir)
    os.makedirs(out_dir, exist_ok=True)
    spans = doc["trace"]["spans"]
    trace = perfetto.validate_trace(perfetto.build_serve_trace(spans))
    perfetto.write_trace(
        os.path.join(out_dir, "trace.perfetto.json"), trace)
    slowest = sorted(spans, key=lambda s: (-s["e2e_s"], s["job"]))
    files = ["incident.json", "trace.perfetto.json"]
    if rec is not None:
        window = slowest[:INCIDENT_SLOWEST]
        t_lo = min(s["t_submit"] for s in window)
        t_hi = max(s["t_extracted"] for s in window)
        recording.write(os.path.join(out_dir, recording.FILENAME),
                        recording.slice_window(rec, t_lo, t_hi))
        files.append(recording.FILENAME)
    inc = {
        "schema": INCIDENT_SCHEMA_ID,
        "reason": "slo-breach",
        "breaches": breaches,
        "arrival_rate": doc["arrival_rate"],
        "jobs_total": doc["jobs_total"],
        "latency": doc["latency"],
        "verdict": doc["verdict"],
        "slowest_jobs": slowest[:INCIDENT_SLOWEST],
        "series": doc["series"],
        "series_summary": doc["series_summary"],
        "files": sorted(files),
    }
    with open(os.path.join(out_dir, "incident.json"), "w") as f:
        json.dump(inc, f, indent=1, sort_keys=True)
        f.write("\n")
    return inc


# lint: host
def load_incident(incident_dir) -> dict:
    """Read and schema-check a soak incident doc."""
    path = os.path.join(str(incident_dir), "incident.json")
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != INCIDENT_SCHEMA_ID:
        raise ValueError(f"{path}: schema must be "
                         f"{INCIDENT_SCHEMA_ID!r}, got "
                         f"{doc.get('schema')!r}")
    for k in ("reason", "breaches", "latency", "slowest_jobs",
              "series", "files"):
        if k not in doc:
            raise ValueError(f"{path}: missing key {k!r}")
    return doc


# lint: host
def main(argv=None) -> int:
    """``cache-sim soak`` entry point."""
    import argparse
    ap = argparse.ArgumentParser(
        prog="cache-sim soak",
        description="open-loop soak: release a seeded mixed-traffic "
                    "job stream at a fixed arrival rate and measure "
                    "p50/p95/p99 job latency, queue depth, and "
                    "saturation")
    ap.add_argument("--arrival-rate", type=float, default=20.0,
                    help="jobs per second released (default 20)")
    ap.add_argument("--duration", type=float, default=2.0,
                    help="arrival window in seconds (default 2); the "
                         "run drains fully after the window closes")
    ap.add_argument("--slots", type=int, default=4,
                    help="batch slots per wave (default 4)")
    ap.add_argument("--nodes", type=int, default=4,
                    help="nodes per job (default 4)")
    ap.add_argument("--trace-len", type=int, default=8,
                    help="instructions per node per job (default 8)")
    ap.add_argument("--protocol", default="mesi",
                    help="coherence protocol for the stream "
                         "(default mesi)")
    ap.add_argument("--seed", type=int, default=0,
                    help="arrival-schedule + workload seed (default 0)")
    ap.add_argument("--bursty", action="store_true",
                    help="use the on/off (interrupted) Poisson "
                         "schedule instead of uniform Poisson: "
                         "exponential ON windows at --arrival-rate x "
                         "--burst-peak alternate with silent OFF "
                         "windows — heavy-tailed load that builds "
                         "queues even at a sustainable AVERAGE rate")
    ap.add_argument("--burst-on", type=float, default=0.25,
                    metavar="S",
                    help="mean ON-window length in seconds under "
                         "--bursty (default 0.25)")
    ap.add_argument("--burst-off", type=float, default=0.25,
                    metavar="S",
                    help="mean OFF-window length in seconds under "
                         "--bursty (default 0.25)")
    ap.add_argument("--burst-peak", type=float, default=4.0,
                    metavar="X",
                    help="in-burst rate multiplier over --arrival-rate "
                         "under --bursty (default 4.0)")
    ap.add_argument("--chunk", type=int, default=32)
    ap.add_argument("--max-cycles", type=int, default=100_000)
    ap.add_argument("--queue-capacity", type=int, default=64)
    ap.add_argument("--daemon", default=None, metavar="ADDR",
                    help="drive the stream through a RUNNING "
                         "`cache-sim daemon` at this address (unix "
                         "path or tcp:HOST:PORT) instead of "
                         "in-process waves; latency is then "
                         "client-observed over the socket")
    ap.add_argument("--lane-mix", default="interactive,batch",
                    help="comma list of lanes jobs alternate through "
                         "under --daemon (default interactive,batch)")
    ap.add_argument("--timeout", type=float, default=300.0,
                    help="--daemon run bound in seconds (default 300)")
    ap.add_argument("--virtual-clock", action="store_true",
                    help="run on the deterministic VirtualClock "
                         "(byte-identical trace docs; tests/CI)")
    ap.add_argument("--wave-s", type=float, default=1e-3,
                    help="virtual seconds charged per wave under "
                         "--virtual-clock (default 1e-3)")
    ap.add_argument("--slo", default=None,
                    help='latency SLO, e.g. "p95=5,p99=20" (ms); a '
                         f'breach exits {EXIT_SLO_BREACH} and dumps '
                         'an incident dir')
    ap.add_argument("--burn-slo", default=None, metavar="SPEC",
                    help="CONTINUOUS burn-rate SLO (obs.burnrate), "
                         'e.g. "5ms,objective=0.99,fast=60,slow=300,'
                         'factor=4": every finished job is one '
                         "streaming sample; an alert (both windows "
                         "burning the error budget at factor x) "
                         f"exits {EXIT_SLO_BREACH} — the streaming "
                         "complement of the end-of-run --slo gate")
    ap.add_argument("--incident-dir", default="soak_incident",
                    help="where an SLO breach dumps its incident "
                         "(default ./soak_incident)")
    ap.add_argument("--out", default=None,
                    help="write the full soak doc as JSON here")
    ap.add_argument("--json", action="store_true",
                    help="print the full soak doc as JSON")
    ap.add_argument("--cpu", action="store_true",
                    help="force JAX_PLATFORMS=cpu (set before jax "
                         "import)")
    args = ap.parse_args(argv)
    if args.cpu:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    slo = parse_slo(args.slo) if args.slo else None
    burn = None
    if args.burn_slo:
        from ue22cs343bb1_openmp_assignment_tpu.obs import burnrate
        burn = burnrate.monitor_from_spec(args.burn_slo)
    if args.daemon and args.virtual_clock:
        ap.error("--daemon measures real client-observed latency over "
                 "the socket; it cannot run on --virtual-clock "
                 "(the daemon owns its own clock)")

    if args.bursty:
        arrivals = bursty_stream(
            args.arrival_rate, args.duration, nodes=args.nodes,
            trace_len=args.trace_len, protocol=args.protocol,
            seed=args.seed, on_s=args.burst_on, off_s=args.burst_off,
            peak_factor=args.burst_peak)
    else:
        arrivals = soak_stream(
            args.arrival_rate, args.duration, nodes=args.nodes,
            trace_len=args.trace_len, protocol=args.protocol,
            seed=args.seed)
    if args.daemon:
        lane_mix = tuple(p.strip() for p in args.lane_mix.split(",")
                         if p.strip())
        doc = soak_daemon(arrivals, args.daemon,
                          arrival_rate=args.arrival_rate,
                          lane_mix=lane_mix, timeout_s=args.timeout,
                          prefix=f"s{args.seed}.", quiet=False,
                          burn=burn)
    else:
        clock = (VirtualClock(wave_s=args.wave_s)
                 if args.virtual_clock else MonotonicClock())
        doc = soak(arrivals, slots=args.slots, chunk=args.chunk,
                   max_cycles=args.max_cycles,
                   queue_capacity=args.queue_capacity,
                   arrival_rate=args.arrival_rate, clock=clock,
                   quiet=False, burn=burn)
    if args.out:
        pathlib.Path(args.out).write_text(
            json.dumps(doc, indent=2) + "\n")
    if args.json:
        print(json.dumps(doc, indent=2))
    else:
        lat = doc["latency"]
        v = doc["verdict"]
        lat_str = ("no jobs completed" if lat is None else
                   f"p50={lat['p50_ms']:.2f}ms p95={lat['p95_ms']:.2f}ms "
                   f"p99={lat['p99_ms']:.2f}ms")
        via = (f" via daemon {doc['addr']}"
               if doc.get("transport") == "daemon" else "")
        print(f"soak{via}: {doc['jobs_quiesced']}/{doc['jobs_total']} "
              f"jobs quiesced in {doc['wave_count']} waves, {lat_str}, "
              f"queue_peak={v['queue_depth_peak']}, "
              f"drain={v['drain_rate_jobs_per_s']:.2f} jobs/s, "
              f"{'SATURATED' if v['saturated'] else 'keeping up'}")
        for lane, ls in (doc.get("lane_latency") or {}).items():
            if ls:
                print(f"soak:   lane {lane}: p95={ls['p95_ms']:.2f}ms "
                      f"({ls['jobs']} jobs)")
        if doc.get("rejected"):
            print(f"soak:   {len(doc['rejected'])} job(s) REJECTED "
                  f"by backpressure (explicit, not dropped)")
    if slo:
        breaches = check_slo(doc["latency"], slo)
        if breaches:
            import sys
            dump_incident(args.incident_dir, doc, breaches)
            for b in breaches:
                print(f"soak: SLO BREACH {b['metric']} "
                      f"{b['observed_ms']:.2f}ms > limit "
                      f"{b['limit_ms']:.2f}ms", file=sys.stderr)
            print(f"soak: incident dumped to {args.incident_dir}",
                  file=sys.stderr)
            return EXIT_SLO_BREACH
    if burn is not None and burn.breached():
        import sys
        for a in burn.alerts:
            print(f"soak: BURN-RATE ALERT at t={a['t_s']:.3f}s: "
                  f"fast {a['fast_burn']:.1f}x / slow "
                  f"{a['slow_burn']:.1f}x the {a['objective']:.3%} "
                  f"error budget (> {a['threshold_ms']}ms, factor "
                  f"{a['factor']})", file=sys.stderr)
        dump_incident(args.incident_dir, doc,
                      [{"metric": "burn-rate", **a}
                       for a in burn.alerts])
        print(f"soak: incident dumped to {args.incident_dir}",
              file=sys.stderr)
        return EXIT_SLO_BREACH
    return 0 if doc["jobs_quiesced"] == doc["jobs_total"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
