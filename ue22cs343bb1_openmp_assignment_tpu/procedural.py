"""Procedural instruction streams: hash-computed workloads.

The 'uniform' stream as a pure function of (config, node, index) — the
single source of truth used both by the sync engine inside the round
(cfg.procedural: O(1) trace memory, no window gather) and by
models.workloads.procedural_uniform, which materializes the identical
stream as arrays for the other engines and for bit-exactness tests
(tests/test_procedural.py).
"""

from __future__ import annotations

import jax.numpy as jnp

from ue22cs343bb1_openmp_assignment_tpu import codec
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.types import Op


def _mix(x: jnp.ndarray) -> jnp.ndarray:
    """murmur3-style 32-bit finalizer."""
    x = jnp.asarray(x, jnp.uint32)
    x ^= x >> 16
    x *= jnp.uint32(0x7FEB352D)
    x ^= x >> 15
    x *= jnp.uint32(0x846CA68B)
    x ^= x >> 16
    return x


def procedural_instr(cfg: SystemConfig, node, idx):
    """(op << 28 | addr, value) for instruction `idx` of `node`.

    node/idx: broadcastable i32 arrays. Parameters come from the config
    (proc_seed / proc_local_permille / proc_write_permille)."""
    N, M = cfg.num_nodes, cfg.mem_size
    h = _mix((node.astype(jnp.uint32) * jnp.uint32(0x9E3779B9))
             ^ (idx.astype(jnp.uint32) * jnp.uint32(0x85EBCA77))
             ^ jnp.uint32(cfg.proc_seed * 2654435761 & 0xFFFFFFFF))
    h2 = _mix(h ^ jnp.uint32(0xC2B2AE35))
    is_write = (h % jnp.uint32(1000)).astype(jnp.int32) \
        < cfg.proc_write_permille
    local = ((h >> 10) % jnp.uint32(1000)).astype(jnp.int32) \
        < cfg.proc_local_permille
    remote = ((h2 % jnp.uint32(N))).astype(jnp.int32)
    home = jnp.where(local, node, remote)
    block = ((h2 >> 16) % jnp.uint32(M)).astype(jnp.int32)
    addr = codec.make_address(cfg, home, block)
    op = jnp.where(is_write, int(Op.WRITE), int(Op.READ))
    val = ((h >> 21) & jnp.uint32(0xFF)).astype(jnp.int32)
    return (op << 28) | addr, val
