// Native (C++) coherence engine: deterministic cycle-lockstep oracle.
//
// Role in the framework (SURVEY §2 row C4/C6/C8): the reference's one
// native component is its C/OpenMP simulator; this is the TPU-framework's
// native runtime counterpart — a host-side engine with *identical
// observable semantics to the JAX vectorized engine* (same cycle model,
// same arbitration rules, same quirks), used for:
//   * differential fuzzing of the JAX/Pallas path on random workloads,
//   * fast host-side schedule search for the racy golden suites,
//   * a `--engine native` execution path in the CLI.
//
// Deliberately NOT the reference's architecture: no OpenMP threads, no
// locks, no spinning. One deterministic scheduler steps every node
// through (dequeue-one-message | issue-one-instruction) per cycle;
// deliveries are sorted by (arbitration rank, program order) — the same
// semantics the JAX engine implements with sort+scatter. All dimensions
// are runtime parameters; sharer sets are tiled uint32 words.
//
// Protocol behavior follows the reference's handler contract
// (assignment.c:190-618) including its quirks (latched instruction fill
// values, unconditional unblocks, asymmetric dedup, blind-index writes);
// see ops/handlers.py for the quirk catalogue with line citations.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <deque>
#include <memory>
#include <vector>

namespace {

enum CacheState : int32_t { kModified = 0, kExclusive = 1, kShared = 2,
                            kInvalid = 3 };
enum DirState : int32_t { kEM = 0, kS = 1, kU = 2 };
enum MsgType : int32_t {
  kReadRequest = 0, kWriteRequest = 1, kReplyRd = 2, kReplyWr = 3,
  kReplyId = 4, kInv = 5, kUpgrade = 6, kWritebackInv = 7,
  kWritebackInt = 8, kFlush = 9, kFlushInvack = 10, kEvictShared = 11,
  kEvictModified = 12, kNone = 13,
};
enum OpType : int32_t { kRead = 0, kWrite = 1, kNop = 2 };

using BitVec = std::vector<uint32_t>;

struct Message {
  int32_t type = kNone;
  int32_t sender = 0;
  int32_t addr = 0;
  int32_t value = 0;
  int32_t second = 0;
  int32_t dirstate = 0;
  BitVec bitvec;
};

struct Metrics {
  int64_t cycles = 0, instrs_retired = 0, read_hits = 0, write_hits = 0,
          read_misses = 0, write_misses = 0, upgrades = 0, msgs_dropped = 0,
          invalidations = 0, evictions = 0;
  int64_t msgs_processed[13] = {0};
};

class Engine {
 public:
  Engine(int32_t num_nodes, int32_t cache_size, int32_t mem_size,
         int32_t queue_capacity, int32_t max_instrs)
      : n_(num_nodes), c_(cache_size), m_(mem_size), q_(queue_capacity),
        t_(max_instrs), words_((num_nodes + 31) / 32) {
    block_bits_ = 1;
    while ((1 << block_bits_) < m_) block_bits_++;
    cache_addr_.assign(n_ * c_, invalid_address());
    cache_val_.assign(n_ * c_, 0);
    cache_state_.assign(n_ * c_, kInvalid);
    memory_.resize(n_ * m_);
    for (int32_t node = 0; node < n_; ++node)
      for (int32_t b = 0; b < m_; ++b)
        memory_[node * m_ + b] = (20 * node + b) & 0xFF;
    dir_state_.assign(n_ * m_, kU);
    dir_bitvec_.assign(n_ * m_ * words_, 0);
    instr_op_.assign(n_ * t_, kNop);
    instr_addr_.assign(n_ * t_, 0);
    instr_val_.assign(n_ * t_, 0);
    instr_count_.assign(n_, 0);
    instr_idx_.assign(n_, -1);
    cur_val_.assign(n_, 0);
    waiting_.assign(n_, 0);
    delay_.assign(n_, 0);
    period_.assign(n_, 1);
    arb_rank_.resize(n_);
    for (int32_t i = 0; i < n_; ++i) arb_rank_[i] = i;
    queues_.resize(n_);
  }

  int32_t invalid_address() const {
    // same sentinel rule as config.SystemConfig.invalid_address
    if (n_ <= 8 && c_ == 4 && m_ == 16 && t_ <= 32) return 0xFF;
    int32_t node_bits = 1;
    while ((1 << node_bits) < n_) node_bits++;
    return (1 << (block_bits_ + node_bits + 4)) - 1;
  }

  void load_trace(int32_t node, const int32_t* ops, const int32_t* addrs,
                  const int32_t* vals, int32_t count) {
    instr_count_[node] = count;
    for (int32_t i = 0; i < count && i < t_; ++i) {
      instr_op_[node * t_ + i] = ops[i];
      instr_addr_[node * t_ + i] = addrs[i];
      instr_val_[node * t_ + i] = vals[i] & 0xFF;
    }
  }

  void set_schedule(const int32_t* delays, const int32_t* periods) {
    if (delays) delay_.assign(delays, delays + n_);
    if (periods) period_.assign(periods, periods + n_);
  }

  void set_arbitration(const int32_t* rank) {
    if (rank) arb_rank_.assign(rank, rank + n_);
  }

  void set_admission(int32_t window) { admission_window_ = window; }

  // 0 = mailbox (reference-exact INV messages), 1 = scatter (home-side
  // invalidation, matching ops/handlers.py scatter mode: the home applies
  // the kill set itself at end of cycle; REPLY_ID carries no sharer set).
  void set_inv_mode(int32_t mode) { inv_mode_ = mode; }

  bool quiescent() const {
    for (int32_t i = 0; i < n_; ++i) {
      if (!queues_[i].empty() || waiting_[i]) return false;
      if (instr_idx_[i] < instr_count_[i] - 1) return false;
    }
    return true;
  }

  // Run until quiescent or max_cycles; returns cycles executed.
  int64_t run(int64_t max_cycles) {
    int64_t start = metrics_.cycles;
    while (!quiescent() && metrics_.cycles - start < max_cycles) cycle();
    return metrics_.cycles - start;
  }

  void cycle() {
    // Outgoing sends are buffered per cycle, then delivered in
    // (arb_rank(sender), program order) — identical to ops/mailbox.py.
    pending_.clear();
    pending_inv_.clear();
    // admission snapshot: outstanding requests at cycle start
    inflight_start_ = 0;
    for (uint8_t w : waiting_) inflight_start_ += w;
    admitted_this_cycle_ = 0;
    for (int32_t node = 0; node < n_; ++node) {
      if (!queues_[node].empty()) {
        Message msg = queues_[node].front();
        queues_[node].pop_front();
        metrics_.msgs_processed[msg.type]++;
        handle(node, msg);
      } else if (!waiting_[node]) {
        issue(node);
      }
    }
    deliver();
    apply_pending_inv();
    metrics_.cycles++;
  }

  // ---- state export -----------------------------------------------------
  void export_state(int32_t* cache_addr, int32_t* cache_val,
                    int32_t* cache_state, int32_t* memory,
                    int32_t* dir_state, uint32_t* dir_bitvec) const {
    std::memcpy(cache_addr, cache_addr_.data(),
                cache_addr_.size() * sizeof(int32_t));
    std::memcpy(cache_val, cache_val_.data(),
                cache_val_.size() * sizeof(int32_t));
    std::memcpy(cache_state, cache_state_.data(),
                cache_state_.size() * sizeof(int32_t));
    std::memcpy(memory, memory_.data(), memory_.size() * sizeof(int32_t));
    std::memcpy(dir_state, dir_state_.data(),
                dir_state_.size() * sizeof(int32_t));
    std::memcpy(dir_bitvec, dir_bitvec_.data(),
                dir_bitvec_.size() * sizeof(uint32_t));
  }

  void export_metrics(int64_t* out) const {
    int64_t vals[] = {metrics_.cycles, metrics_.instrs_retired,
                      metrics_.read_hits, metrics_.write_hits,
                      metrics_.read_misses, metrics_.write_misses,
                      metrics_.upgrades, metrics_.msgs_dropped,
                      metrics_.invalidations, metrics_.evictions};
    std::memcpy(out, vals, sizeof(vals));
  }

 private:
  // ---- address codec (codec.py equivalent) ------------------------------
  int32_t home_of(int32_t addr) const { return addr >> block_bits_; }
  int32_t block_of(int32_t addr) const {
    return addr & ((1 << block_bits_) - 1);
  }
  int32_t cline_of(int32_t addr) const { return block_of(addr) % c_; }

  // ---- bitvector helpers ------------------------------------------------
  BitVec bv_get(int32_t node, int32_t block) const {
    const uint32_t* p = &dir_bitvec_[(node * m_ + block) * words_];
    return BitVec(p, p + words_);
  }
  void bv_put(int32_t node, int32_t block, const BitVec& bv) {
    std::memcpy(&dir_bitvec_[(node * m_ + block) * words_], bv.data(),
                words_ * sizeof(uint32_t));
  }
  static bool bv_test(const BitVec& bv, int32_t bit) {
    return (bv[bit / 32] >> (bit % 32)) & 1;
  }
  static void bv_set(BitVec& bv, int32_t bit) {
    bv[bit / 32] |= (1u << (bit % 32));
  }
  static void bv_clear(BitVec& bv, int32_t bit) {
    bv[bit / 32] &= ~(1u << (bit % 32));
  }
  BitVec bv_single(int32_t bit) const {
    BitVec bv(words_, 0);
    bv_set(bv, bit);
    return bv;
  }
  static int32_t bv_popcount(const BitVec& bv) {
    int32_t total = 0;
    for (uint32_t w : bv) total += __builtin_popcount(w);
    return total;
  }
  static int32_t bv_lowest(const BitVec& bv) {
    for (size_t i = 0; i < bv.size(); ++i)
      if (bv[i]) return int32_t(i) * 32 + __builtin_ctz(bv[i]);
    return int32_t(bv.size()) * 32;
  }

  // ---- sends ------------------------------------------------------------
  void send(int32_t receiver, Message msg) {
    pending_.push_back({receiver, std::move(msg)});
  }

  void deliver() {
    // pending_ is already in per-sender program order; a stable sort by
    // arbitration rank of the sender yields the global enqueue order.
    std::vector<size_t> order(pending_.size());
    for (size_t i = 0; i < order.size(); ++i) order[i] = i;
    std::stable_sort(order.begin(), order.end(), [&](size_t a, size_t b) {
      return arb_rank_[pending_[a].second.sender] <
             arb_rank_[pending_[b].second.sender];
    });
    for (size_t i : order) {
      int32_t r = pending_[i].first;
      // Out-of-range receiver (e.g. owner lookup on an empty sharer set
      // returns the bit-width sentinel, state.py:ctz): the JAX engine's
      // delivery scatter drops these uncounted (mode="drop"); match it.
      if (r < 0 || r >= n_) continue;
      if (int32_t(queues_[r].size()) < q_) {
        queues_[r].push_back(std::move(pending_[i].second));
      } else {
        metrics_.msgs_dropped++;  // silent drop, reference overflow rule
      }
    }
  }

  // Scatter-mode invalidations are buffered during the handler loop and
  // applied against end-of-cycle state — the JAX engine computes the kill
  // mask on the post-update cache arrays (ops/step.py), and both engines
  // must see the same tags. The reference tracks no INV-acks
  // (assignment.c:358-361), so no reply traffic is owed.
  void apply_pending_inv() {
    for (const auto& p : pending_inv_) {
      const int32_t addr = p.first;
      const BitVec& bv = p.second;
      const int32_t line = cline_of(addr);
      for (int32_t t = 0; t < n_; ++t) {
        if (!bv_test(bv, t)) continue;
        if (ca(t, line) == addr) {
          if (cs(t, line) != kInvalid) metrics_.invalidations++;
          cs(t, line) = kInvalid;
        }
      }
    }
  }

  // ---- cache helpers ----------------------------------------------------
  int32_t& ca(int32_t node, int32_t line) { return cache_addr_[node*c_+line]; }
  int32_t& cv(int32_t node, int32_t line) { return cache_val_[node*c_+line]; }
  int32_t& cs(int32_t node, int32_t line) { return cache_state_[node*c_+line]; }

  // Eviction notice for a displaced line (handleCacheReplacement
  // contract: E/S -> EVICT_SHARED, M -> EVICT_MODIFIED with value,
  // INVALID -> nothing).
  void evict_notice(int32_t node, int32_t line) {
    int32_t st = cs(node, line);
    if (st == kInvalid) return;
    Message msg;
    msg.sender = node;
    msg.addr = ca(node, line);
    msg.bitvec.assign(words_, 0);
    if (st == kModified) {
      msg.type = kEvictModified;
      msg.value = cv(node, line);
    } else {
      msg.type = kEvictShared;
    }
    metrics_.evictions++;
    send(home_of(msg.addr), msg);
  }

  void fill(int32_t node, int32_t line, int32_t addr, int32_t value,
            int32_t state) {
    ca(node, line) = addr;
    cv(node, line) = value;
    cs(node, line) = state;
  }

  // ---- the 13 handlers --------------------------------------------------
  void handle(int32_t node, const Message& msg) {
    const int32_t home = home_of(msg.addr);
    const int32_t block = block_of(msg.addr);
    const int32_t line = cline_of(msg.addr);
    int32_t& dstate = dir_state_[node * m_ + block];
    int32_t& mem = memory_[node * m_ + block];
    Message out;
    out.bitvec.assign(words_, 0);

    switch (msg.type) {
      case kReadRequest: {  // at home
        BitVec bv = bv_get(node, block);
        if (dstate == kEM) {
          // forward to current owner; directory deferred until FLUSH
          out.type = kWritebackInt;
          out.sender = node;
          out.addr = msg.addr;
          out.second = msg.sender;
          send(bv_lowest(bv), out);
        } else {
          out.type = kReplyRd;
          out.sender = node;
          out.addr = msg.addr;
          out.value = mem;
          out.dirstate = (dstate == kS) ? kS : kEM;
          send(msg.sender, out);
          if (dstate == kS) {
            bv_set(bv, msg.sender);
          } else {
            dstate = kEM;
            bv = bv_single(msg.sender);
          }
          bv_put(node, block, bv);
        }
        break;
      }
      case kReplyRd: {  // at requester
        if (ca(node, line) != msg.addr && cs(node, line) != kInvalid)
          evict_notice(node, line);
        fill(node, line, msg.addr, msg.value,
             msg.dirstate == kS ? kShared : kExclusive);
        waiting_[node] = 0;
        break;
      }
      case kWritebackInt: {  // at old owner: flush to home (+requester)
        out.type = kFlush;
        out.sender = node;
        out.addr = msg.addr;
        out.value = cv(node, line);  // blind by index, like the C
        out.second = msg.second;
        send(home, out);
        if (home != msg.second) send(msg.second, out);  // dedup quirk
        cs(node, line) = kShared;
        break;
      }
      case kFlush: {
        if (node == home) {
          BitVec bv = bv_get(node, block);
          dstate = kS;
          bv_set(bv, msg.second);
          bv_put(node, block, bv);
          mem = msg.value;
        }
        if (node == msg.second) {
          if (ca(node, line) != msg.addr && cs(node, line) != kInvalid)
            evict_notice(node, line);
          fill(node, line, msg.addr, msg.value, kShared);
        }
        waiting_[node] = 0;  // unconditional (quirk 2)
        break;
      }
      case kUpgrade: {  // at home
        BitVec others = bv_get(node, block);
        bv_clear(others, msg.sender);
        out.type = kReplyId;
        out.sender = node;
        out.addr = msg.addr;
        if (inv_mode_ == 1) {
          pending_inv_.emplace_back(msg.addr, others);  // home-side kill
        } else {
          out.bitvec = others;
        }
        send(msg.sender, out);
        dstate = kEM;
        bv_put(node, block, bv_single(msg.sender));
        break;
      }
      case kReplyId: {  // at requester (new owner)
        // mailbox mode only — in scatter mode (1) the home already
        // applied the INVs when it processed the UPGRADE/WRITE_REQUEST
        if (inv_mode_ == 0) {
          for (int32_t i = 0; i < n_; ++i) {
            if (bv_test(msg.bitvec, i)) {
              Message inv;
              inv.type = kInv;
              inv.sender = node;
              inv.addr = msg.addr;
              inv.bitvec.assign(words_, 0);
              send(i, inv);
            }
          }
        }
        if (ca(node, line) != msg.addr && cs(node, line) != kInvalid)
          evict_notice(node, line);
        fill(node, line, msg.addr, cur_val_[node], kModified);  // quirk 1
        waiting_[node] = 0;
        break;
      }
      case kInv: {  // at sharer
        if (ca(node, line) == msg.addr) {
          if (cs(node, line) != kInvalid) metrics_.invalidations++;
          cs(node, line) = kInvalid;
        }
        break;
      }
      case kWriteRequest: {  // at home
        BitVec bv = bv_get(node, block);
        if (dstate == kU) {
          out.type = kReplyWr;
          out.sender = node;
          out.addr = msg.addr;
          send(msg.sender, out);
        } else if (dstate == kS) {
          BitVec others = bv;
          bv_clear(others, msg.sender);
          out.type = kReplyId;
          out.sender = node;
          out.addr = msg.addr;
          if (inv_mode_ == 1) {
            pending_inv_.emplace_back(msg.addr, others);  // home-side kill
          } else {
            out.bitvec = others;
          }
          send(msg.sender, out);
        } else {  // EM: ask old owner to flush+invalidate
          out.type = kWritebackInv;
          out.sender = node;
          out.addr = msg.addr;
          out.value = msg.value;
          out.second = msg.sender;
          send(bv_lowest(bv), out);
        }
        dstate = kEM;  // unconditional immediate update (quirk 4)
        bv_put(node, block, bv_single(msg.sender));
        break;
      }
      case kReplyWr: {  // at requester
        evict_notice(node, line);  // unconditional call, no tag check
        fill(node, line, msg.addr, cur_val_[node], kModified);
        waiting_[node] = 0;
        break;
      }
      case kWritebackInv: {  // at old owner
        out.type = kFlushInvack;
        out.sender = node;
        out.addr = msg.addr;
        out.value = cv(node, line);
        out.second = msg.second;
        send(home, out);
        send(msg.second, out);  // NO dedup (quirk 3)
        cs(node, line) = kInvalid;
        break;
      }
      case kFlushInvack: {
        if (node == home) {
          bv_put(node, block, bv_single(msg.second));
          mem = msg.value;
        }
        if (node == msg.second) {
          if (ca(node, line) != msg.addr && cs(node, line) != kInvalid)
            evict_notice(node, line);
          fill(node, line, msg.addr, cur_val_[node], kModified);
        }
        waiting_[node] = 0;  // unconditional (quirk 2)
        break;
      }
      case kEvictShared: {
        if (node != home) {
          cs(node, line) = kExclusive;  // blind promotion, no tag check
        } else {
          BitVec bv = bv_get(node, block);
          bv_clear(bv, msg.sender);
          bv_put(node, block, bv);
          int32_t sharers = bv_popcount(bv);
          if (sharers == 0) {
            dstate = kU;
          } else if (sharers == 1) {
            dstate = kEM;
            int32_t new_owner = bv_lowest(bv);
            if (new_owner != home) {
              out.type = kEvictShared;
              out.sender = node;
              out.addr = msg.addr;
              out.value = mem;
              send(new_owner, out);
            } else {
              cs(node, line) = kExclusive;  // inline self-promotion
            }
          }
        }
        break;
      }
      case kEvictModified: {  // at home
        mem = msg.value;
        bv_put(node, block, BitVec(words_, 0));
        dstate = kU;
        break;
      }
      default:
        break;
    }
  }

  // ---- instruction frontend --------------------------------------------
  void issue(int32_t node) {
    int64_t cyc = metrics_.cycles;
    if (cyc < delay_[node]) return;
    if ((cyc - delay_[node]) % std::max<int32_t>(period_[node], 1)) return;
    if (instr_idx_[node] >= instr_count_[node] - 1) return;
    int32_t i = instr_idx_[node] + 1;  // peek; commit only past admission
    int32_t op = instr_op_[node * t_ + i];
    int32_t addr = instr_addr_[node * t_ + i];
    int32_t val = instr_val_[node * t_ + i];
    int32_t home = home_of(addr);
    int32_t line = cline_of(addr);
    bool hit = ca(node, line) == addr && cs(node, line) != kInvalid;
    // admission control (backpressure; mirrors ops/frontend.py): an
    // instruction that would create an outstanding request retries next
    // cycle when the window is full.
    bool sends = (op == kRead && !hit) || (op == kWrite && !hit) ||
                 (op == kWrite && hit && cs(node, line) == kShared);
    if (sends && admission_window_ >= 0 &&
        inflight_start_ + admitted_this_cycle_ >= admission_window_) {
      return;
    }
    if (sends) admitted_this_cycle_++;
    instr_idx_[node] = i;
    cur_val_[node] = val;  // latch (quirk 1 source)
    // count at issue, like the JAX frontend's `issued` (every issued
    // instruction eventually completes; counting at unblock instead
    // double-counts under the premature-unblock quirk, SURVEY quirk 2)
    metrics_.instrs_retired++;
    if (op == kNop) {
      return;
    }
    Message msg;
    msg.sender = node;
    msg.addr = addr;
    msg.bitvec.assign(words_, 0);
    if (op == kRead) {
      if (hit) {
        metrics_.read_hits++;
      } else {
        metrics_.read_misses++;
        msg.type = kReadRequest;
        send(home, msg);
        waiting_[node] = 1;
      }
    } else {
      if (hit && (cs(node, line) == kModified ||
                  cs(node, line) == kExclusive)) {
        metrics_.write_hits++;
        cv(node, line) = val;
        cs(node, line) = kModified;
      } else if (hit) {  // SHARED write hit -> upgrade
        metrics_.write_hits++;
        metrics_.upgrades++;
        msg.type = kUpgrade;
        msg.value = val;
        send(home, msg);
        waiting_[node] = 1;
      } else {
        metrics_.write_misses++;
        msg.type = kWriteRequest;
        msg.value = val;
        send(home, msg);
        waiting_[node] = 1;
      }
    }
  }

  const int32_t n_, c_, m_, q_, t_, words_;
  int32_t block_bits_;
  std::vector<int32_t> cache_addr_, cache_val_, cache_state_;
  std::vector<int32_t> memory_, dir_state_;
  std::vector<uint32_t> dir_bitvec_;
  std::vector<int32_t> instr_op_, instr_addr_, instr_val_, instr_count_,
      instr_idx_, cur_val_, delay_, period_, arb_rank_;
  std::vector<uint8_t> waiting_;
  std::vector<std::deque<Message>> queues_;
  std::vector<std::pair<int32_t, Message>> pending_;
  std::vector<std::pair<int32_t, BitVec>> pending_inv_;  // (addr, targets)
  Metrics metrics_;
  int32_t inv_mode_ = 0;           // 0 = mailbox INV, 1 = home-side scatter
  int32_t admission_window_ = -1;  // -1 = no gating (reference semantics)
  int32_t inflight_start_ = 0;
  int32_t admitted_this_cycle_ = 0;
};

}  // namespace

extern "C" {

void* sim_create(int32_t num_nodes, int32_t cache_size, int32_t mem_size,
                 int32_t queue_capacity, int32_t max_instrs) {
  return new Engine(num_nodes, cache_size, mem_size, queue_capacity,
                    max_instrs);
}

void sim_destroy(void* h) { delete static_cast<Engine*>(h); }

void sim_load_trace(void* h, int32_t node, const int32_t* ops,
                    const int32_t* addrs, const int32_t* vals,
                    int32_t count) {
  static_cast<Engine*>(h)->load_trace(node, ops, addrs, vals, count);
}

void sim_set_schedule(void* h, const int32_t* delays,
                      const int32_t* periods) {
  static_cast<Engine*>(h)->set_schedule(delays, periods);
}

void sim_set_arbitration(void* h, const int32_t* rank) {
  static_cast<Engine*>(h)->set_arbitration(rank);
}

void sim_set_admission(void* h, int32_t window) {
  static_cast<Engine*>(h)->set_admission(window);
}

void sim_set_inv_mode(void* h, int32_t mode) {
  static_cast<Engine*>(h)->set_inv_mode(mode);
}

int64_t sim_run(void* h, int64_t max_cycles) {
  return static_cast<Engine*>(h)->run(max_cycles);
}

int32_t sim_quiescent(void* h) {
  return static_cast<Engine*>(h)->quiescent() ? 1 : 0;
}

void sim_export_state(void* h, int32_t* cache_addr, int32_t* cache_val,
                      int32_t* cache_state, int32_t* memory,
                      int32_t* dir_state, uint32_t* dir_bitvec) {
  static_cast<Engine*>(h)->export_state(cache_addr, cache_val, cache_state,
                                        memory, dir_state, dir_bitvec);
}

void sim_export_metrics(void* h, int64_t* out10) {
  static_cast<Engine*>(h)->export_metrics(out10);
}

}  // extern "C"
