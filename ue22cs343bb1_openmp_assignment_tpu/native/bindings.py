"""ctypes bindings for the native C++ engine (builds on first use).

Exposes :class:`NativeEngine`, semantically identical to the JAX engine
(ops/step.cycle): same cycle model, arbitration, schedule knobs, and
protocol quirks — the host-side oracle for differential fuzzing and the
CLI's `--engine native` path.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import shlex
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "engine.cpp")
_CXX = os.environ.get("CXX", "g++")
# CXXFLAGS env overrides, as the Makefile's `CXXFLAGS ?=` did; the flag
# string participates in the cache key, so sanitizer/debug builds get
# their own cached library instead of silently reusing the default one
_CXXFLAGS = shlex.split(os.environ.get(
    "CXXFLAGS", "-O2 -std=c++17 -fPIC -Wall -Wextra"))
# COHERENCE_NATIVE_SANITIZE=1 appends the ASan+UBSan flags (keep in
# sync with the Makefile's SANITIZE=1 block). The host process must
# LD_PRELOAD libasan for the sanitized .so to load — see the Makefile
# note and the slow-marked differential test.
_SANITIZE_FLAGS = ["-fsanitize=address,undefined",
                   "-fno-omit-frame-pointer", "-g"]
if os.environ.get("COHERENCE_NATIVE_SANITIZE") == "1":
    _CXXFLAGS = _CXXFLAGS + _SANITIZE_FLAGS
_lock = threading.Lock()
_lib = None

_METRIC_NAMES = ("cycles", "instrs_retired", "read_hits", "write_hits",
                 "read_misses", "write_misses", "upgrades", "msgs_dropped",
                 "invalidations", "evictions")


def _lib_path() -> str:
    """Build-cache path keyed on the source + compiler command hash.

    No binary is checked in (and mtime comparisons lie after a fresh
    clone, where checkout order decides which file is newer): the
    library is compiled on first use into ``build/`` under a name that
    embeds a content hash, so a source or flag change can never pick up
    a stale binary, and repeat imports reuse the cached build."""
    h = hashlib.sha256()
    with open(_SRC, "rb") as f:
        h.update(f.read())
    h.update(" ".join([_CXX] + _CXXFLAGS).encode())
    return os.path.join(_DIR, "build",
                        f"libcoherence_native-{h.hexdigest()[:16]}.so")


def _build(lib_path: str) -> None:
    os.makedirs(os.path.dirname(lib_path), exist_ok=True)
    tmp = lib_path + f".tmp{os.getpid()}"
    try:
        subprocess.run([_CXX] + _CXXFLAGS + ["-shared", "-o", tmp, _SRC],
                       check=True)
        os.replace(tmp, lib_path)   # atomic: concurrent builders both win
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_library() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        lib_path = _lib_path()
        if not os.path.exists(lib_path):
            _build(lib_path)
        lib = ctypes.CDLL(lib_path)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.sim_create.restype = ctypes.c_void_p
        lib.sim_create.argtypes = [ctypes.c_int32] * 5
        lib.sim_destroy.argtypes = [ctypes.c_void_p]
        lib.sim_load_trace.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                       i32p, i32p, i32p, ctypes.c_int32]
        lib.sim_set_schedule.argtypes = [ctypes.c_void_p, i32p, i32p]
        lib.sim_set_arbitration.argtypes = [ctypes.c_void_p, i32p]
        lib.sim_set_admission.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.sim_set_inv_mode.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.sim_run.restype = ctypes.c_int64
        lib.sim_run.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.sim_quiescent.restype = ctypes.c_int32
        lib.sim_quiescent.argtypes = [ctypes.c_void_p]
        lib.sim_export_state.argtypes = [ctypes.c_void_p, i32p, i32p, i32p,
                                         i32p, i32p, u32p]
        lib.sim_export_metrics.argtypes = [ctypes.c_void_p, i64p]
        _lib = lib
        return lib


def _as_i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


class NativeEngine:
    """Host-side deterministic coherence engine (C++, ctypes-bound)."""

    def __init__(self, cfg):
        self.cfg = cfg
        self._lib = load_library()
        self._h = self._lib.sim_create(cfg.num_nodes, cfg.cache_size,
                                       cfg.mem_size, cfg.queue_capacity,
                                       cfg.max_instrs)
        if cfg.admission_window is not None:
            self._lib.sim_set_admission(self._h, cfg.admission_window)
        self._lib.sim_set_inv_mode(
            self._h, 0 if cfg.inv_mode == "mailbox" else 1)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.sim_destroy(h)
            self._h = None

    def load_traces(self, traces: Sequence[Sequence[tuple]]) -> None:
        """traces: per-node [(op, addr, value), ...] (utils.trace format)."""
        for node, tr in enumerate(traces):
            ops = np.ascontiguousarray([t[0] for t in tr], np.int32)
            addrs = np.ascontiguousarray([t[1] for t in tr], np.int32)
            vals = np.ascontiguousarray([t[2] for t in tr], np.int32)
            self._lib.sim_load_trace(self._h, node, _as_i32p(ops),
                                     _as_i32p(addrs), _as_i32p(vals),
                                     len(tr))

    def load_instr_arrays(self, op, addr, val, count) -> None:
        op, addr, val = (np.asarray(a, np.int32) for a in (op, addr, val))
        count = np.asarray(count, np.int32)
        for node in range(self.cfg.num_nodes):
            n = int(count[node])
            o = np.ascontiguousarray(op[node, :n])
            a = np.ascontiguousarray(addr[node, :n])
            v = np.ascontiguousarray(val[node, :n])
            self._lib.sim_load_trace(self._h, node, _as_i32p(o), _as_i32p(a),
                                     _as_i32p(v), n)

    def set_schedule(self, delays: Optional[Sequence[int]] = None,
                     periods: Optional[Sequence[int]] = None) -> None:
        d = (np.ascontiguousarray(delays, np.int32)
             if delays is not None else None)
        p = (np.ascontiguousarray(periods, np.int32)
             if periods is not None else None)
        self._lib.sim_set_schedule(
            self._h, _as_i32p(d) if d is not None else None,
            _as_i32p(p) if p is not None else None)

    def set_arbitration(self, rank: Sequence[int]) -> None:
        r = np.ascontiguousarray(rank, np.int32)
        self._lib.sim_set_arbitration(self._h, _as_i32p(r))

    def run(self, max_cycles: int = 1_000_000) -> int:
        return int(self._lib.sim_run(self._h, max_cycles))

    @property
    def quiescent(self) -> bool:
        return bool(self._lib.sim_quiescent(self._h))

    def export_state(self) -> dict:
        cfg = self.cfg
        N, C, M, W = (cfg.num_nodes, cfg.cache_size, cfg.mem_size,
                      cfg.bitvec_words)
        ca = np.empty((N, C), np.int32)
        cv = np.empty((N, C), np.int32)
        cs = np.empty((N, C), np.int32)
        mem = np.empty((N, M), np.int32)
        ds = np.empty((N, M), np.int32)
        bv = np.empty((N, M, W), np.uint32)
        self._lib.sim_export_state(
            self._h, _as_i32p(ca), _as_i32p(cv), _as_i32p(cs), _as_i32p(mem),
            _as_i32p(ds), bv.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
        return dict(cache_addr=ca, cache_val=cv, cache_state=cs, memory=mem,
                    dir_state=ds, dir_bitvec=bv)

    def metrics(self) -> dict:
        out = np.empty(10, np.int64)
        self._lib.sim_export_metrics(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        return dict(zip(_METRIC_NAMES, out.tolist()))
