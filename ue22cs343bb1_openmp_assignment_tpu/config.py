"""Runtime system configuration.

The reference freezes all dimensions at compile time
(``assignment.c:6-10``: NUM_PROCS=4, CACHE_SIZE=4, MEM_SIZE=16,
MSG_BUFFER_SIZE=256, MAX_INSTR_NUM=32). Here every dimension is a runtime
parameter so a single TPU chip can step thousands of simulated cores; the
classmethod :meth:`SystemConfig.reference` reproduces the reference's
exact dimensions for byte-parity testing.
"""

from __future__ import annotations

import dataclasses
import math


@dataclasses.dataclass(frozen=True)
class SystemConfig:
    """Static (compile-time, shape-determining) simulation parameters.

    Hashable and frozen so it can be a `static_argnum` to `jax.jit`.
    """

    num_nodes: int = 4          # NUM_PROCS (assignment.c:6); parameterized
    cache_size: int = 4         # direct-mapped lines/node (assignment.c:7)
    mem_size: int = 16          # memory blocks/node (assignment.c:8)
    queue_capacity: int = 256   # mailbox slots/node (assignment.c:9)
    max_instrs: int = 32        # trace length cap/node (assignment.c:10)

    # Message-network semantics -------------------------------------------
    # 'mailbox': INV fan-out travels through mailboxes (exact reference
    #            semantics, needs num_nodes out-slots per node — use for
    #            parity configs, num_nodes <= 64).
    # 'scatter': INV applied as a direct vectorized scatter in the same
    #            cycle (the reference already assumes INV never fails and
    #            tracks no INV-ACKs, assignment.c:358-361; this is the
    #            scale path for thousands of nodes).
    inv_mode: str = "mailbox"

    # Overflow policy: 'drop' matches the reference's silent drop on a full
    # ring (assignment.c:754-762); drops are always counted in metrics.
    overflow_policy: str = "drop"

    # Fault injection: probability that an accepted message is dropped at
    # delivery anyway (seedable via state.fault_key). The reference's only
    # "fault" is the silent overflow drop (assignment.c:754-762); this
    # generalizes it into a testable stress knob for the failure-detection
    # surface (ops.failures): a dropped reply strands its requester, which
    # the stall watchdog then flags. 0.0 = off (default, zero cost).
    drop_prob: float = 0.0

    # Hit-burst depth of the synchronous transactional engine
    # (ops.sync_engine): per round each node retires up to this many
    # consecutive cache hits locally before attempting one coherence
    # transaction. Purely a throughput knob — hits are node-local, so any
    # depth realizes a legal schedule.
    drain_depth: int = 4

    # Transaction-window width of the synchronous transactional engine:
    # per round each node may commit up to this many coherence
    # transactions (read-miss / write-miss / upgrade), provided they
    # touch pairwise-distinct directory entries (fill targets and evicted
    # victims alike); mid-window cache hits retire only on entries the
    # node itself claimed earlier in the window, which keeps every
    # committed round a legal serialization of the reference machine
    # (ops/sync_engine.py `_round_step_multi` docstring). 1 = the
    # classic burst-plus-one-transaction round. Purely a throughput
    # knob: the per-round device-dispatch cost is roughly constant, so
    # wider windows retire more instructions per dispatch.
    txn_width: int = 1

    # Deep-window transactional engine (ops.deep_engine): per round each
    # node composes arbitrarily deep transaction chains on its OWN
    # directory entries (dense, gather-free — the dm table's row index
    # is the address, so a node's own slice aligns with the node axis)
    # and issues at most deep_slots remote events (fill requests /
    # eviction notices), which serialize per-entry through a scatter-min
    # lane and compose after the owning home's chain. Same protocol,
    # far more retired instructions per round on locality-heavy
    # workloads. The window length is drain_depth + txn_width, as for
    # the multi-transaction engine.
    deep_window: bool = False
    # per-node per-round budget of remote events (requests, eviction
    # notices, and remote-hit safety probes share these slots); overflow
    # stops the window for that round
    deep_slots: int = 8
    # per-node per-round budget of own-entry EM-owner value resolutions
    deep_ownerval_slots: int = 4
    # adaptive attempt-horizon slack: next round's attempt cap is
    # last round's retirement + slack (lane politeness: attempts past
    # the cap would claim per-entry lanes they rarely commit, starving
    # other nodes' events). Larger slack = more speculative depth; the
    # steady state solves n ~= c*(n + slack) for commit ratio c, so
    # slack directly scales committed window depth (PERF.md).
    deep_horizon_slack: int = 2
    # absorption waves: per round, up to deep_waves foreign events
    # compose per directory entry (wave 0 = the classic one winner per
    # entry; waves 1+ serialize additional FILL REQUESTS — mixed
    # read/write sequences included — against the previous wave's
    # composed row; per-line outcomes stay exact via the wave-stamp
    # fan-out encoding, ops/deep_engine). 1 = single-winner rounds.
    # Capped at 14 by the 4-bit wave-stamp fields in DM_ACT.
    deep_waves: int = 1
    # read-storm bulk grant (round 5): after the absorption waves, ALL
    # still-losing READ requests on an entry commit together as one
    # final pseudo-wave — reads commute, so k same-round readers
    # compose in one k-aggregated step (S count += k; an EM owner
    # flushes and downgrades via the wave stamps; U rows grant E to a
    # single reader, S to two or more — exactly the reference's
    # read-after-read serialization, assignment.c:211-236). From its
    # first storm slot onward a node's window is in the storm ZONE:
    # further reads (and gated EVICT_SHARED notices) join the same
    # terminal storm point, anything else truncates the window there
    # (program order; ops/deep_engine). The many-readers-one-entry
    # lever (lu's pivot rows, hotspot's read half); costs ~3 [Q, N]
    # index ops per round plus a reads-always-storm lane-key bit, so
    # off by default for low-contention or write-heavy workloads.
    deep_read_storm: bool = False
    # commit-prefix-exact marker/poison flags (round 5): derive the
    # home-side conflict flags from a lane-truncated flag-pass fold
    # instead of the full attempt horizon, eliminating the ghost
    # aborts that pinned committed depth (PERF.md stop-reason
    # anatomy). One extra fold pass + one extra [Q, N] gather per
    # round; False restores the round-4 attempt-based flags (A/B
    # lever, bench --no-exact-flags).
    deep_exact_flags: bool = True

    # Procedural workload (sync engine): when set (e.g. "uniform"),
    # instructions are computed per (node, index) from a counter-based
    # hash inside the round instead of gathered from a stored [N, T]
    # trace — O(1) trace memory for arbitrarily long runs, and one
    # fewer gather per round. Parameters are permille ints so the
    # config stays hashable/static. models.workloads.procedural_uniform
    # materializes the identical trace for cross-checking.
    procedural: str | None = None
    proc_local_permille: int = 800
    proc_write_permille: int = 500
    proc_seed: int = 0

    # Execute the round's node-local phase as fused Pallas TPU kernels
    # instead of XLA fusions: the burst phase of single-transaction
    # rounds (ops.pallas_burst) or the window fold + replay of
    # multi-transaction rounds (ops.pallas_window). Requires a
    # procedural workload (stored-trace windows need a dynamic gather
    # TPU Pallas cannot vectorize). Measured on the attached TPU:
    # +24% on the single path, +19% at txn_width=3 (PERF.md). OFF by
    # default because the CPU fallback is the Pallas interpreter,
    # which is impractically slow at full kernel size — bench.py turns
    # it on automatically when a TPU backend is attached.
    pallas_burst: bool = False

    # Execute the ENTIRE deep-engine round as one fused Pallas kernel
    # (ops.pallas_round): window folds, arbitration, handler effects
    # and fan-out in a single pallas_call with directory/cache/slot
    # state resident in VMEM, index ops routed through exact one-hot
    # MXU matmuls. Bit-identical to the XLA path on supported configs
    # (pallas_round.supported — no read-storm, deep_slots * num_nodes
    # under the scatter-min margin); round_step falls back to the XLA
    # reference path otherwise. OFF by default for the same reason as
    # pallas_burst (CPU fallback is the interpreter); bench.py exposes
    # it as --fused-round.
    fused_round: bool = False

    # Coherence protocol variant. 'mesi' is the reference protocol and
    # the only one the hand-written ops/handlers.py implements; 'moesi'
    # and 'mesif' are expressed as declarative tables
    # (analysis/protocol_table.py) compiled to drop-in message phases.
    # The engine itself is protocol-agnostic — this field's one runtime
    # effect is widening the cache-state range invariant
    # (ops/invariants.py) to admit the variant's extra state (OWNED /
    # FORWARD, types.py), and it keys which table the analysis layer
    # pairs with a scope.
    protocol: str = "mesi"

    # Admission window (backpressure): maximum number of simultaneously
    # outstanding request transactions system-wide. The reference silently
    # drops on overflow (assignment.c:754-762), which at its dimensions is
    # unreachable but at scale livelocks: a dropped reply leaves its
    # requester blocked forever (SURVEY quirk 6). With a window W <= Q/6,
    # no mailbox can overflow (each in-flight transaction enqueues at most
    # ~6 messages against any single queue), so delivery is drop-free.
    # None = reference semantics (no gating).
    admission_window: int | None = None

    # Cross-shard mailbox transport for the sharded engines
    # (parallel/). 'all_to_all' is the shard_map + jax.lax.all_to_all
    # router (parallel/shardmap_comm.py); 'rdma' delivers lanes with a
    # Pallas remote-DMA ring kernel (parallel/rdma_comm.py,
    # pltpu.make_async_remote_copy with send/recv semaphores) that
    # never materializes the full [D*D] exchange tensor. Gated like
    # fused_round: rdma_comm.supported() decides whether the kernel
    # compiles natively (real TPU) or runs under the Pallas
    # interpreter (CPU CI — the correctness contract); unsupported
    # configs fall back to all_to_all. Single-device meshes bypass the
    # transport entirely.
    transport: str = "all_to_all"

    def __post_init__(self):
        if self.num_nodes < 1:
            raise ValueError("num_nodes must be >= 1")
        if self.deep_window and self.mem_size > (1 << 16):
            raise ValueError(
                "deep_window packs block indices in 16 bits; "
                "mem_size must be <= 65536")
        if self.deep_window and self.num_nodes > (1 << 16):
            raise ValueError(
                "deep_window packs requester ids in 16 bits (fan-out "
                "column); num_nodes must be <= 65536")
        if self.txn_width < 1:
            raise ValueError("txn_width must be >= 1")
        if not 1 <= self.deep_waves <= 14:
            raise ValueError(
                "deep_waves must be in [1, 14] (wave stamps pack into "
                "4-bit DM_ACT fields; see ops/deep_engine)")
        if self.deep_read_storm and self.deep_waves > 13:
            raise ValueError(
                "deep_read_storm uses the stamp one past the last "
                "wave, so deep_waves <= 13 when the storm is on "
                "(4-bit DM_ACT stamp fields)")
        if self.deep_read_storm and self.num_nodes > (1 << 15) - 1:
            raise ValueError(
                "deep_read_storm needs num_nodes <= 32767: the "
                "per-entry evictor count packs as ke << 16 in an "
                "int32 scatter-add (ke can reach num_nodes), and "
                "multi-slot storm rows use requester id 0xFFFF as "
                "the matches-nobody sentinel (ops/deep_engine)")
        if self.protocol not in ("mesi", "moesi", "mesif"):
            raise ValueError(f"bad protocol {self.protocol!r}")
        if self.transport not in ("all_to_all", "rdma"):
            raise ValueError(f"bad transport {self.transport!r}")
        if self.inv_mode not in ("mailbox", "scatter"):
            raise ValueError(f"bad inv_mode {self.inv_mode!r}")
        if self.inv_mode == "mailbox" and self.num_nodes > 64:
            raise ValueError(
                "inv_mode='mailbox' materializes num_nodes INV out-slots per "
                "node per cycle; use inv_mode='scatter' above 64 nodes")

    @property
    def allowed_cache_states(self) -> tuple:
        """Legal cache-line state values under cfg.protocol (plain ints,
        so the range invariant can static-unroll over them)."""
        from ue22cs343bb1_openmp_assignment_tpu.types import CacheState
        base = (int(CacheState.MODIFIED), int(CacheState.EXCLUSIVE),
                int(CacheState.SHARED), int(CacheState.INVALID))
        if self.protocol == "moesi":
            return base + (int(CacheState.OWNED),)
        if self.protocol == "mesif":
            return base + (int(CacheState.FORWARD),)
        return base

    # -- address codec geometry -------------------------------------------
    @property
    def block_bits(self) -> int:
        """Bits of the block index inside an address.

        The reference packs (node, block) into one byte as two nibbles
        (``assignment.c:46-49,186-188``); with mem_size=16 the low nibble
        is exactly the block index. Generalized: block field is
        ceil(log2(mem_size)) bits, node id sits above it.
        """
        return max(1, (self.mem_size - 1).bit_length())

    @property
    def addr_bits(self) -> int:
        node_bits = max(1, (self.num_nodes - 1).bit_length())
        return self.block_bits + node_bits

    @property
    def invalid_address(self) -> int:
        """Sentinel for an empty cache line.

        The reference uses 0xFF (``assignment.c:815-817``); generalized to
        an address whose node field is out of range for any valid node.
        With reference dimensions this is exactly 0xFF.
        """
        if self.is_reference_compat:
            return 0xFF
        return (1 << (self.addr_bits + 4)) - 1

    @property
    def bitvec_words(self) -> int:
        """uint32 words per directory sharer-bitvector (tiled for large N).

        The reference uses a single byte (``assignment.c:63``), capping it
        at 8 nodes; we tile ceil(N/32) uint32 words.
        """
        return max(1, math.ceil(self.num_nodes / 32))

    @property
    def msg_bitvec_words(self) -> int:
        """uint32 words of sharer-bitvector payload per mailbox slot.

        Only REPLY_ID carries a sharer set (assignment.c:345,429), and
        only in mailbox INV mode — in scatter mode the home applies the
        invalidations itself when it processes the UPGRADE/WRITE_REQUEST
        (ops/handlers.py), so messages carry no bitvector and the mailbox
        payload shrinks to one dummy word. At 4096 nodes this is the
        difference between a 134 MB and a 1 MB mailbox tensor.
        """
        return self.bitvec_words if self.inv_mode == "mailbox" else 1

    @property
    def is_reference_compat(self) -> bool:
        """True when dimensions match the reference exactly (parity mode)."""
        return (self.num_nodes <= 8 and self.cache_size == 4
                and self.mem_size == 16 and self.max_instrs <= 32)

    # Out-slot layout for candidate messages emitted per node per cycle.
    # Program order within one node's cycle (defines intra-node FIFO order,
    # mirroring the reference's sequential sendMessage calls):
    #   slot 0            : primary send (home reply / flush-to-home /
    #                       frontend request / evict-notify)
    #   slot 1            : secondary send (FLUSH / FLUSH_INVACK to the
    #                       secondReceiver, assignment.c:282,498)
    #   slots 2..2+N-1    : INV fan-out (assignment.c:364-373), mailbox mode
    #   slot last         : eviction notice (sent after INVs in REPLY_ID,
    #                       assignment.c:364-378, and alone in other fills)
    @property
    def inv_slots(self) -> int:
        return self.num_nodes if self.inv_mode == "mailbox" else 0

    @property
    def out_slots(self) -> int:
        return 3 + self.inv_slots

    @classmethod
    def reference(cls, **overrides) -> "SystemConfig":
        """The reference's exact compile-time dimensions (assignment.c:6-10)."""
        base = dict(num_nodes=4, cache_size=4, mem_size=16,
                    queue_capacity=256, max_instrs=32, inv_mode="mailbox")
        base.update(overrides)
        return cls(**base)

    @classmethod
    def scale(cls, num_nodes: int, **overrides) -> "SystemConfig":
        """A large-N benchmark configuration (scatter INV, tiled bitvectors)."""
        base = dict(num_nodes=num_nodes, cache_size=4, mem_size=16,
                    queue_capacity=64, max_instrs=32, inv_mode="scatter")
        base.update(overrides)
        return cls(**base)
