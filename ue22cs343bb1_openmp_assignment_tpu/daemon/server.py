"""The daemon socket layer: transport around DaemonCore.

Threading model (deliberately boring): the thread that calls
:meth:`DaemonServer.run` IS the scheduler — it owns every JAX call
(``pump``). An acceptor thread hands each connection to a handler
thread, and handlers only translate wire lines into core method calls
under the ONE server lock; they never touch device state. A submit
notifies the scheduler's condition variable so an idle daemon wakes
immediately instead of on the poll tick. ``drain`` blocks its handler
on the same condition until the core reports idle; ``shutdown``
responds first, then stops the scheduler after the current chunk and
removes the socket — a clean exit the check.sh smoke verifies leaves
no orphaned process.

The one exception to one-line-in-one-line-out is ``watch``: the
handler thread acks, then PUSHES stats-deltas and live-ops events
(obs.events ring, always attached) on its own connection until the
client's bound hits — snapshots are taken under the server lock, the
poll sleep is not, so a slow watcher falls behind the ring instead of
stalling the scheduler.
"""
# lint: host

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Optional

from ue22cs343bb1_openmp_assignment_tpu.daemon import protocol
from ue22cs343bb1_openmp_assignment_tpu.daemon.core import (
    DaemonCore, attach_emitter, attach_recorder)
from ue22cs343bb1_openmp_assignment_tpu.obs import burnrate, events
from ue22cs343bb1_openmp_assignment_tpu.serve import JobSpec

#: scheduler poll tick when idle (seconds); submits wake it earlier
IDLE_TICK_S = 0.01


class DaemonServer:
    """Serve a DaemonCore over a unix or tcp socket."""

    # lint: host
    def __init__(self, core: DaemonCore, addr: str,
                 quiet: bool = True):
        self.core = core
        self.quiet = quiet
        self.lock = threading.RLock()
        self.wake = threading.Condition(self.lock)
        self._stop = threading.Event()
        self.family, target = protocol.parse_addr(addr)
        self._unix_path: Optional[str] = (
            target if self.family == socket.AF_UNIX else None)
        if self._unix_path and os.path.exists(self._unix_path):
            os.unlink(self._unix_path)      # stale socket from a kill
        self.sock = socket.socket(self.family, socket.SOCK_STREAM)
        if self.family == socket.AF_INET:
            self.sock.setsockopt(socket.SOL_SOCKET,
                                 socket.SO_REUSEADDR, 1)
        self.sock.bind(target)
        self.sock.listen(16)
        self.addr = (self._unix_path if self._unix_path else
                     "tcp:%s:%d" % self.sock.getsockname())

    # lint: host
    def stop(self) -> None:
        self._stop.set()
        with self.wake:
            self.wake.notify_all()

    # lint: host
    def run(self) -> int:
        """The scheduler loop; returns 0 on a clean shutdown."""
        acceptor = threading.Thread(target=self._accept_loop,
                                    daemon=True, name="daemon-accept")
        acceptor.start()
        if not self.quiet:
            print(f"daemon: listening on {self.addr}", flush=True)
        try:
            while not self._stop.is_set():
                with self.wake:
                    ran = self.core.pump() if not self.core.idle() \
                        else False
                    # progress may have flushed a drain or finished a
                    # polled job — let blocked handlers re-check
                    self.wake.notify_all()
                    if not ran and not self._stop.is_set():
                        self.wake.wait(IDLE_TICK_S)
        finally:
            self._close()
        if not self.quiet:
            print("daemon: shut down cleanly", flush=True)
        return 0

    # lint: host
    def _close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
        if self._unix_path and os.path.exists(self._unix_path):
            os.unlink(self._unix_path)

    # lint: host
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return                       # socket closed on stop
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="daemon-conn").start()

    # lint: host
    def _serve_conn(self, conn) -> None:
        f = conn.makefile("rwb")
        try:
            for line in f:
                if not line.strip():
                    continue
                try:
                    req = protocol.decode(line)
                    if req.get("op") == "watch":
                        # the one long-lived op: ack + push rows on
                        # this connection, then fall back into the
                        # plain request/response loop
                        self._watch(req, f)
                        continue
                    resp = self._handle(req)
                except Exception as e:  # noqa: BLE001 — wire boundary
                    resp = protocol.error(None, str(e))
                f.write(protocol.encode(resp))
                f.flush()
                if self._stop.is_set():
                    break
        except (OSError, ValueError):
            pass                             # client went away
        finally:
            try:
                f.close()
                conn.close()
            except OSError:
                pass

    # lint: host
    def _stats_sig(self) -> tuple:
        """A cheap change-signature over the core's lifetime counters
        — computed WITHOUT calling stats(), so idle watch polls never
        bump ``stats_seq`` (a stats row is pushed only when something
        actually moved)."""
        c = self.core
        return (sum(ln.submitted for ln in c.lanes.values()),
                c._rejected_total,
                sum(ln.done for ln in c.lanes.values()),
                sum(len(ln.queue) for ln in c.lanes.values()),
                c.chunks, c.bucket_growths, c.results_evicted,
                c.slo_alerts, c.draining)

    # lint: host
    def _watch(self, req: dict, f) -> None:
        """Stream stats-deltas + live-ops events to one client until
        its ``max_rows``/``max_s`` bound hits or the daemon stops.
        Holds the server lock only to snapshot; the sleep is unlocked,
        so a slow watcher never stalls the scheduler — it just falls
        behind the ring and sees a ``seq`` gap."""
        interval = float(req.get("interval_s",
                                 protocol.DEFAULT_WATCH_INTERVAL_S))
        interval = max(0.01, interval)
        max_rows = req.get("max_rows")
        max_s = req.get("max_s")
        with self.lock:
            if self.core.emitter is None:
                attach_emitter(self.core)    # ring-only, late client
            em = self.core.emitter
            cursor = em.seq                  # new events only
            sig = self._stats_sig()
            stats = self.core.stats()        # the baseline snapshot
        f.write(protocol.encode({"ok": True, "op": "watch",
                                 "streaming": True,
                                 "interval_s": interval,
                                 "cursor": cursor}))
        f.write(protocol.encode({"ok": True, "op": "watch",
                                 "type": "stats", "stats": stats}))
        f.flush()
        rows = 1
        t0 = time.monotonic()
        reason = "stopped"
        while not self._stop.is_set():
            if max_rows is not None and rows >= int(max_rows):
                reason = "max-rows"
                break
            if (max_s is not None
                    and time.monotonic() - t0 >= float(max_s)):
                reason = "max-s"
                break
            self._stop.wait(interval)
            with self.lock:
                evs = em.since(cursor)
                if evs:
                    cursor = evs[-1]["seq"] + 1
                new_sig = self._stats_sig()
                stats = (self.core.stats() if new_sig != sig
                         else None)
                sig = new_sig
            for ev in evs:
                f.write(protocol.encode({"ok": True, "op": "watch",
                                         "type": "event",
                                         "event": ev}))
                rows += 1
            if stats is not None:
                f.write(protocol.encode({"ok": True, "op": "watch",
                                         "type": "stats",
                                         "stats": stats}))
                rows += 1
            if evs or stats is not None:
                f.flush()
        f.write(protocol.encode({"ok": True, "op": "watch",
                                 "type": "end", "reason": reason,
                                 "rows": rows, "cursor": cursor}))
        f.flush()

    # lint: host
    def _handle(self, req: dict) -> dict:
        op = req.get("op")
        if op not in protocol.OPS:
            return protocol.error(op, f"unknown op {op!r} "
                                      f"(one of {protocol.OPS})")
        if op == "ping":
            return {"ok": True, "op": "ping"}
        if op == "submit":
            try:
                spec = JobSpec.from_dict(req.get("spec") or {})
            except (TypeError, ValueError) as e:
                return protocol.error("submit", f"bad job spec: {e}")
            with self.wake:
                resp = self.core.submit(spec,
                                        lane=req.get("lane", "batch"))
                self.wake.notify_all()       # wake an idle scheduler
            return resp
        if op == "status":
            with self.lock:
                return self.core.status(req.get("job", ""))
        if op == "result":
            with self.lock:
                return self.core.result(req.get("job", ""))
        if op == "stats":
            with self.lock:
                return {"ok": True, "op": "stats",
                        "stats": self.core.stats()}
        if op == "trace":
            with self.lock:
                return {"ok": True, "op": "trace",
                        "trace": self.core.trace_doc()}
        if op == "watch":
            # unreachable via _serve_conn (special-cased there); keep
            # a direct _handle("watch") from falling into shutdown
            return protocol.error(
                "watch", "watch is a streaming op, handled on the "
                         "connection")
        if op == "drain":
            with self.wake:
                self.core.drain()
                self.wake.notify_all()
                while not self.core.idle() and not self._stop.is_set():
                    self.wake.wait(IDLE_TICK_S)
                done = sum(ln.done for ln in self.core.lanes.values())
            return {"ok": True, "op": "drain", "drained": True,
                    "jobs_done": done}
        # shutdown: respond, then stop after the current chunk
        self.stop()
        return {"ok": True, "op": "shutdown", "stopping": True}


# lint: host
def parse_lane_weights(spec: str) -> dict:
    """``"interactive=4,batch=1"`` → weight dict (ints >= 1)."""
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad lane weight {part!r} "
                             f"(want lane=N)")
        name, w = part.split("=", 1)
        weight = int(w)
        if weight < 1:
            raise ValueError(f"lane weight must be >= 1, got {w}")
        out[name.strip()] = weight
    if not out:
        raise ValueError(f"empty lane-weight spec {spec!r}")
    return out


# lint: host
def main(argv=None) -> int:
    """``cache-sim daemon`` entry point."""
    import argparse
    ap = argparse.ArgumentParser(
        prog="cache-sim daemon",
        description="persistent serving daemon: accept jobs over a "
                    "unix/tcp socket with continuous admission, "
                    "shape bucketing, and priority lanes")
    ap.add_argument("--addr", required=True,
                    help="listen address: a unix socket path, or "
                         "tcp:HOST:PORT")
    ap.add_argument("--slots", type=int, default=4,
                    help="batch slots per shape bucket (default 4)")
    ap.add_argument("--max-buckets", type=int, default=4,
                    help="slot shape classes per protocol (default 4)")
    ap.add_argument("--chunk", type=int, default=16,
                    help="cycles per admission chunk (default 16) — "
                         "the continuous-admission granularity")
    ap.add_argument("--max-cycles", type=int, default=100_000)
    ap.add_argument("--queue-capacity", type=int, default=64)
    ap.add_argument("--lane-depth", type=int,
                    default=protocol.DEFAULT_LANE_DEPTH,
                    help="per-lane admission queue bound (default "
                         f"{protocol.DEFAULT_LANE_DEPTH}); a full "
                         "lane rejects explicitly")
    ap.add_argument("--lane-weights", default=None,
                    help='admission weights, e.g. '
                         '"interactive=4,batch=1" (the default)')
    ap.add_argument("--retain", type=int,
                    default=protocol.DEFAULT_RETAIN_RESULTS,
                    metavar="N",
                    help="keep only the newest N finished/rejected "
                         "jobs' results, statuses, and spans in "
                         "memory (default "
                         f"{protocol.DEFAULT_RETAIN_RESULTS}) — "
                         "bounds a long-lived daemon; evicted jobs "
                         "answer 'unknown'")
    ap.add_argument("--keep-dumps", action="store_true",
                    help="retain per-node dumps in memory so `result` "
                         "returns them over the socket (off by "
                         "default for a long-lived daemon; --out-dir "
                         "streams dumps to disk either way)")
    ap.add_argument("--out-dir", default=None,
                    help="also stream per-job dumps + metrics here")
    ap.add_argument("--record", default=None, metavar="DIR",
                    help="record mode: stream every ACCEPTED "
                         "submission (full spec, lane, scheduled "
                         "arrival time) and every finished job's "
                         "dump digest into DIR/recording.jsonl "
                         "(cache-sim/recording/v1) — replay the "
                         "captured traffic later with "
                         "`cache-sim replay DIR`")
    ap.add_argument("--events-dir", default=None, metavar="DIR",
                    help="also stream every live-ops event "
                         "(cache-sim/events/v1: submit-accepted, "
                         "admitted, quiesced, lane-reject, "
                         "result-evicted, bucket-growth, slo-alert) "
                         "to DIR/events.jsonl; the in-memory ring "
                         "that feeds `watch` clients is always on")
    ap.add_argument("--events-ring", type=int,
                    default=events.DEFAULT_RING, metavar="N",
                    help="in-memory event ring bound (default "
                         f"{events.DEFAULT_RING} rows); a watch "
                         "client that falls behind sees a seq gap")
    ap.add_argument("--burn-slo", default=None, metavar="SPEC",
                    help="continuous burn-rate SLO, e.g. "
                         '"5ms,objective=0.99,fast=60,slow=300,'
                         'factor=2": every finished job is one '
                         "sample; when BOTH windows burn the error "
                         "budget at factor x, one slo-alert event is "
                         "injected into the stream")
    ap.add_argument("--virtual-clock", action="store_true",
                    help="run the scheduler on the deterministic "
                         "VirtualClock (time advances per wave, not "
                         "by wall time) — recordings and trace docs "
                         "then carry virtual timestamps; tests/CI")
    ap.add_argument("--wave-s", type=float, default=1e-3,
                    help="virtual seconds charged per wave chunk "
                         "under --virtual-clock (default 1e-3)")
    ap.add_argument("--quiet", action="store_true")
    ap.add_argument("--cpu", action="store_true",
                    help="force JAX_PLATFORMS=cpu (set before jax "
                         "import)")
    args = ap.parse_args(argv)
    if args.cpu:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    weights = (parse_lane_weights(args.lane_weights)
               if args.lane_weights else None)
    clock = None
    if args.virtual_clock:
        from ue22cs343bb1_openmp_assignment_tpu.obs.clock import (
            VirtualClock)
        clock = VirtualClock(wave_s=args.wave_s)
    core = DaemonCore(slots=args.slots, max_buckets=args.max_buckets,
                      chunk=args.chunk, max_cycles=args.max_cycles,
                      queue_capacity=args.queue_capacity,
                      lane_depth=args.lane_depth, lane_weights=weights,
                      clock=clock, out_dir=args.out_dir,
                      keep_dumps=args.keep_dumps,
                      retain_results=args.retain)
    if args.record:
        recorder = attach_recorder(core, args.record)
        if not args.quiet:
            print(f"daemon: recording traffic to {recorder.path}",
                  flush=True)
    # the event ring is always on (watch clients need it); --events-dir
    # additionally streams every row to disk
    emitter = attach_emitter(core, args.events_dir,
                             ring=args.events_ring)
    if args.events_dir and not args.quiet:
        print(f"daemon: streaming events to {emitter.path}",
              flush=True)
    if args.burn_slo:
        core.burn = burnrate.monitor_from_spec(args.burn_slo)
    server = DaemonServer(core, args.addr, quiet=args.quiet)
    try:
        return server.run()
    except KeyboardInterrupt:
        server.stop()
        return 0
    finally:
        if core.recorder is not None:
            core.recorder.close()
        if core.emitter is not None:
            core.emitter.close()


if __name__ == "__main__":
    raise SystemExit(main())
