"""The thin daemon client: one socket, newline-delimited JSON.

:class:`DaemonClient` is the programmatic surface (the soak harness's
``--daemon`` transport and the tests use it); :func:`main` is the
``cache-sim submit`` CLI around it. The client is dependency-free on
purpose — socket + json, no jax — so submitting a job never pays the
accelerator-runtime import.
"""
# lint: host

from __future__ import annotations

import json
import socket
import time
from typing import Optional

from ue22cs343bb1_openmp_assignment_tpu.daemon import protocol


class DaemonClient:
    """One persistent connection to a serving daemon."""

    # lint: host
    def __init__(self, addr: str, timeout_s: float = 30.0):
        self.addr = addr
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self._file = None

    # lint: host
    def connect(self) -> "DaemonClient":
        family, target = protocol.parse_addr(self.addr)
        s = socket.socket(family, socket.SOCK_STREAM)
        s.settimeout(self.timeout_s)
        s.connect(target)
        self._sock = s
        self._file = s.makefile("rwb")
        return self

    # lint: host
    def close(self) -> None:
        for h in (self._file, self._sock):
            if h is not None:
                try:
                    h.close()
                except OSError:
                    pass
        self._file = self._sock = None

    # lint: host
    def __enter__(self) -> "DaemonClient":
        # Lazy: request() connects on first use, so wait_up() can own
        # the retry loop during the daemon startup race.
        return self

    # lint: host
    def __exit__(self, *exc) -> None:
        self.close()

    # lint: host
    def request(self, **msg) -> dict:
        """One request line out, one response line back, in order."""
        if self._sock is None:
            self.connect()
        self._file.write(protocol.encode(msg))
        self._file.flush()
        line = self._file.readline()
        if not line:
            raise ConnectionError(
                f"daemon at {self.addr} closed the connection")
        return protocol.decode(line)

    # lint: host
    def wait_up(self, timeout_s: float = 10.0,
                poll_s: float = 0.05) -> dict:
        """Retry connect+ping until the daemon answers (startup
        race); raises ConnectionError after ``timeout_s``."""
        deadline = time.monotonic() + timeout_s
        last = None
        while time.monotonic() < deadline:
            try:
                self.close()
                self.connect()
                return self.ping()
            except (ConnectionError, OSError, ValueError) as e:
                last = e
                time.sleep(poll_s)
        raise ConnectionError(
            f"daemon at {self.addr} not up after {timeout_s}s: {last}")

    # lint: host
    def ping(self) -> dict:
        return self.request(op="ping")

    # lint: host
    def submit(self, spec, lane: str = "batch") -> dict:
        """``spec`` is a JobSpec dataclass or a plain spec dict."""
        if hasattr(spec, "__dataclass_fields__"):
            import dataclasses
            spec = dataclasses.asdict(spec)
        return self.request(op="submit", spec=spec, lane=lane)

    # lint: host
    def status(self, job: str) -> dict:
        return self.request(op="status", job=job)

    # lint: host
    def result(self, job: str) -> dict:
        return self.request(op="result", job=job)

    # lint: host
    def wait(self, job: str, timeout_s: float = 60.0,
             poll_s: float = 0.002) -> dict:
        """Poll ``result`` until the job resolves (done or rejected)."""
        deadline = time.monotonic() + timeout_s
        while True:
            r = self.result(job)
            if r.get("status") in ("done", "rejected", "unknown"):
                return r
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"job {job!r} not done after {timeout_s}s "
                    f"(last status {r.get('status')!r})")
            time.sleep(poll_s)

    # lint: host
    def stats(self) -> dict:
        return self.request(op="stats")["stats"]

    # lint: host
    def watch(self, interval_s: Optional[float] = None,
              max_rows: Optional[int] = None,
              max_s: Optional[float] = None):
        """Generator over the ``watch`` stream: yields the pushed
        rows (``type`` ``"stats"`` / ``"event"``) and returns after
        the terminal ``"end"`` row (also yielded), leaving the
        connection usable for plain requests again."""
        if self._sock is None:
            self.connect()
        req = {"op": "watch"}
        if interval_s is not None:
            req["interval_s"] = float(interval_s)
        if max_rows is not None:
            req["max_rows"] = int(max_rows)
        if max_s is not None:
            req["max_s"] = float(max_s)
        self._file.write(protocol.encode(req))
        self._file.flush()
        ack = protocol.decode(self._file.readline() or b"null")
        if not ack.get("ok") or not ack.get("streaming"):
            raise ConnectionError(f"watch not acked: {ack}")
        while True:
            line = self._file.readline()
            if not line:
                raise ConnectionError(
                    f"daemon at {self.addr} closed the watch stream")
            row = protocol.decode(line)
            yield row
            if row.get("type") == "end":
                return

    # lint: host
    def trace(self) -> dict:
        return self.request(op="trace")["trace"]

    # lint: host
    def drain(self) -> dict:
        return self.request(op="drain")

    # lint: host
    def shutdown(self) -> dict:
        return self.request(op="shutdown")


# lint: host
def main(argv=None) -> int:
    """``cache-sim submit`` entry point: submit jobs to a running
    daemon and optionally wait; also the control surface for ping /
    stats / drain / shutdown."""
    import argparse
    import sys
    ap = argparse.ArgumentParser(
        prog="cache-sim submit",
        description="submit jobs to a running cache-sim daemon over "
                    "its socket (see `cache-sim daemon`)")
    ap.add_argument("--addr", required=True,
                    help="daemon address: unix socket path or "
                         "tcp:HOST:PORT")
    ap.add_argument("--job", action="append", default=[],
                    metavar="JSON",
                    help="one job spec as JSON (repeatable); an "
                         'extra "lane" key overrides --lane per job')
    ap.add_argument("--jobs", default=None,
                    help=".jsonl file or directory of .json specs "
                         "(serve.load_jobs format)")
    ap.add_argument("--lane", default="batch",
                    choices=sorted(protocol.LANES),
                    help="priority lane (default batch)")
    ap.add_argument("--wait", action="store_true",
                    help="poll until every submitted job resolves")
    ap.add_argument("--timeout", type=float, default=60.0,
                    help="--wait bound per job in seconds (default 60)")
    ap.add_argument("--wait-up", type=float, default=None,
                    metavar="S",
                    help="retry-connect for up to S seconds first "
                         "(daemon startup race)")
    ap.add_argument("--ping", action="store_true",
                    help="liveness probe")
    ap.add_argument("--stats", action="store_true",
                    help="print the daemon-stats snapshot as JSON")
    ap.add_argument("--drain", action="store_true",
                    help="stop admission and flush in-flight jobs")
    ap.add_argument("--shutdown", action="store_true",
                    help="stop the daemon (after --drain if both)")
    ap.add_argument("--json", action="store_true",
                    help="print raw response docs as JSON")
    args = ap.parse_args(argv)

    jobs = []
    for j in args.job:
        d = json.loads(j)
        lane = d.pop("lane", args.lane)
        jobs.append((d, lane))
    if args.jobs:
        from ue22cs343bb1_openmp_assignment_tpu.serve import load_jobs
        import dataclasses
        jobs += [(dataclasses.asdict(s), args.lane)
                 for s in load_jobs(args.jobs)]
    if not (jobs or args.ping or args.stats or args.drain
            or args.shutdown):
        ap.error("nothing to do: give --job/--jobs or a control flag")

    rc = 0
    with DaemonClient(args.addr) as client:
        if args.wait_up is not None:
            client.wait_up(args.wait_up)
        if args.ping:
            r = client.ping()
            print(json.dumps(r) if args.json
                  else f"daemon at {args.addr}: "
                       f"{'up' if r.get('ok') else 'DOWN'}")
        submitted = []
        for spec, lane in jobs:
            r = client.submit(spec, lane=lane)
            if args.json:
                print(json.dumps(r))
            else:
                print(f"submit {spec.get('name')!r} [{lane}]: "
                      f"{r.get('status', r.get('error'))}")
            if r.get("status") == "queued":
                submitted.append(spec["name"])
            else:
                rc = 1
        if args.wait:
            for name in submitted:
                r = client.wait(name, timeout_s=args.timeout)
                if args.json:
                    print(json.dumps(r))
                else:
                    print(f"result {name!r}: {r.get('status')} "
                          f"quiesced={r.get('quiesced')} "
                          f"cycles={r.get('cycles')} "
                          f"bucket={r.get('bucket')}")
                if not (r.get("status") == "done"
                        and r.get("quiesced")):
                    rc = 1
        if args.stats:
            print(json.dumps(client.stats(), indent=2))
        if args.drain:
            r = client.drain()
            if not args.json:
                print(f"drained: {r.get('jobs_done')} job(s) done",
                      file=sys.stderr)
        if args.shutdown:
            client.shutdown()
            if not args.json:
                print("daemon stopping", file=sys.stderr)
    return rc


# lint: host
def main_watch(argv=None) -> int:
    """``cache-sim watch`` entry point: follow one daemon's live ops
    stream (stats deltas + structured events) over its socket."""
    import argparse
    ap = argparse.ArgumentParser(
        prog="cache-sim watch",
        description="stream a running daemon's live ops plane: "
                    "stats-deltas plus every scheduler event "
                    "(cache-sim/events/v1) as they happen")
    ap.add_argument("--addr", required=True,
                    help="daemon address: unix socket path or "
                         "tcp:HOST:PORT")
    ap.add_argument("--interval", type=float, default=None,
                    metavar="S",
                    help="server poll cadence in seconds (default "
                         f"{protocol.DEFAULT_WATCH_INTERVAL_S})")
    ap.add_argument("--max-rows", type=int, default=None, metavar="N",
                    help="stop after N pushed rows")
    ap.add_argument("--max-s", type=float, default=None, metavar="S",
                    help="stop after S seconds")
    ap.add_argument("--wait-up", type=float, default=None, metavar="S",
                    help="retry-connect for up to S seconds first")
    ap.add_argument("--json", action="store_true",
                    help="print the raw NDJSON rows instead of the "
                         "human one-liners")
    args = ap.parse_args(argv)

    alerts = 0
    # blocking socket: an idle daemon pushes nothing between deltas,
    # so a read timeout would tear the stream down mid-watch
    with DaemonClient(args.addr, timeout_s=None) as client:
        if args.wait_up is not None:
            client.wait_up(args.wait_up)
        for row in client.watch(interval_s=args.interval,
                                max_rows=args.max_rows,
                                max_s=args.max_s):
            if args.json:
                print(json.dumps(row, sort_keys=True), flush=True)
                continue
            kind = row.get("type")
            if kind == "stats":
                s = row["stats"]
                jobs = s["jobs"]
                print(f"[stats #{s.get('stats_seq', '?')}] "
                      f"up={s['uptime_s']:.3f}s "
                      f"submitted={jobs['submitted']} "
                      f"done={jobs['done']} "
                      f"rejected={jobs['rejected']} "
                      f"chunks={s['chunks']} "
                      f"alerts={s.get('slo_alerts', 0)}", flush=True)
            elif kind == "event":
                ev = dict(row["event"])
                seq = ev.pop("seq")
                t_s = ev.pop("t_s")
                k = ev.pop("kind")
                job = ev.pop("job", None)
                alerts += int(k == "slo-alert")
                extra = " ".join(f"{n}={v}" for n, v
                                 in sorted(ev.items()))
                print(f"[{t_s:9.3f}s #{seq}] {k:<15} "
                      f"{job or '-':<16} {extra}", flush=True)
            else:
                print(f"[end] {row.get('reason')} "
                      f"({row.get('rows')} rows)", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
