"""The daemon wire protocol: newline-delimited JSON over unix/tcp.

One request per line, one response per line, strictly in order on
each connection — the framing a thin client can speak with nothing
but a socket and ``json``. Requests are ``{"op": <op>, ...}``;
responses always carry ``"ok"`` (bool) and echo ``"op"``. Errors are
``{"ok": false, "op": ..., "error": "<message>"}`` and never close
the connection — a client can recover from its own malformed line.

Ops
---
``submit``   ``{"op": "submit", "spec": {<JobSpec dict>},
             "lane": "interactive"|"batch"}`` →
             ``{"ok": true, "status": "queued", "job": <name>,
             "lane": ...}`` or the explicit backpressure response
             ``{"ok": false, "status": "rejected", "reason": ...}``
             (bounded lane queue — never a silent drop).
``status``   ``{"op": "status", "job": <name>}`` → lifecycle state
             (``queued`` / ``running`` / ``done`` / ``rejected`` /
             ``unknown``).
``result``   ``{"op": "result", "job": <name>}`` → the finished job
             doc (metrics, cycles, per-node dumps, lane, bucket) or
             ``{"ok": true, "status": <pending state>}`` to poll.
             Only the newest ``DEFAULT_RETAIN_RESULTS`` terminal jobs
             are retained; older jobs answer ``unknown``.
``stats``    → the validated ``cache-sim/daemon-stats/v1`` snapshot.
             Under ``daemon --record`` its ``recording`` block
             carries the live capture counters (artifact path,
             accepted submissions streamed, result digests written);
             ``recording`` is null when record mode is off.
``trace``    → the ``cache-sim/serve-trace/v1`` doc of completed jobs.
``watch``    ``{"op": "watch", "interval_s": <poll>, "max_s": <stop
             after>, "max_rows": <stop after>}`` — the ONE
             long-lived op: the server acks
             ``{"ok": true, "op": "watch", "streaming": true}`` and
             then pushes NDJSON rows on the same connection —
             ``{"op": "watch", "type": "event", "event": <cache-
             sim/events/v1 row>}`` for every live-ops event and
             ``{"op": "watch", "type": "stats", "stats": <stats
             doc>}`` whenever the counters changed at a poll tick —
             until the bound hits or the daemon stops, then one
             ``{"op": "watch", "type": "end", "reason": ...}`` row,
             after which the connection speaks plain request/response
             again. Rows ride the event ring: a slow client sees a
             ``seq`` gap, never a stalled scheduler.
``drain``    → stop admitting, flush queued + in-flight jobs, respond
             when idle.
``shutdown`` → respond, then stop the scheduler after the current
             chunk and close the socket.
``ping``     → liveness probe.

Addresses
---------
``parse_addr`` accepts ``tcp:HOST:PORT`` for TCP and anything else
(optionally ``unix:PATH``) as a unix-domain socket path — serving
defaults to unix sockets, the same-host fast path.
"""
# lint: host

from __future__ import annotations

import json
import socket
from typing import Tuple

#: every request op the server understands
OPS = ("submit", "status", "result", "stats", "trace", "watch",
       "drain", "shutdown", "ping")

#: default watch-stream poll cadence (seconds): how often the server
#: checks the event ring / stats counters for a watching client
DEFAULT_WATCH_INTERVAL_S = 0.25

#: the priority lanes and their default admission weights: the
#: scheduler picks lanes by smooth weighted round-robin, so at full
#: contention interactive jobs are admitted ~4x as often as batch
LANES = ("interactive", "batch")
DEFAULT_LANE_WEIGHTS = {"interactive": 4, "batch": 1}

#: default bound on each lane's admission queue (backpressure: a
#: submit beyond this is rejected explicitly, never silently dropped)
DEFAULT_LANE_DEPTH = 64

#: default result-retention bound: only the newest N terminal jobs
#: keep their result doc / status entry / closed span in memory, so a
#: long-lived daemon never grows with jobs served (evicted jobs
#: answer ``unknown``; ``--out-dir`` is the durable record)
DEFAULT_RETAIN_RESULTS = 1024


def encode(msg: dict) -> bytes:
    """One protocol message → one wire line (sorted keys, so virtual
    runs are byte-stable end to end)."""
    return (json.dumps(msg, sort_keys=True) + "\n").encode("utf-8")


def decode(line: bytes) -> dict:
    """One wire line → the message dict; raises ValueError on
    anything that is not a JSON object."""
    msg = json.loads(line.decode("utf-8"))
    if not isinstance(msg, dict):
        raise ValueError(f"protocol message must be a JSON object, "
                         f"got {type(msg).__name__}")
    return msg


def error(op, detail: str) -> dict:
    return {"ok": False, "op": op, "error": detail}


def parse_addr(addr: str) -> Tuple[int, object]:
    """``tcp:HOST:PORT`` → (AF_INET, (host, port)); anything else —
    optionally prefixed ``unix:`` — is a unix socket path."""
    if addr.startswith("tcp:"):
        rest = addr[len("tcp:"):]
        if ":" not in rest:
            raise ValueError(f"tcp address must be tcp:HOST:PORT, "
                             f"got {addr!r}")
        host, port = rest.rsplit(":", 1)
        return socket.AF_INET, (host or "127.0.0.1", int(port))
    if addr.startswith("unix:"):
        addr = addr[len("unix:"):]
    if not addr:
        raise ValueError("empty daemon address")
    return socket.AF_UNIX, addr
