"""Persistent serving daemon: the always-on front door (ROADMAP item 2).

``cache-sim serve`` is a batch program — the whole job stream is
present at entry and the process dies with its jit caches. This
package is the production configuration the paper's "millions of
users" framing actually implies:

- :mod:`protocol` — the newline-delimited JSON socket protocol
  (``submit`` / ``status`` / ``result`` / ``stats`` / ``trace`` /
  ``drain`` / ``shutdown``) and unix/tcp address parsing;
- :mod:`bucketing` — slot shape classes chosen from the queue's shape
  histogram (bounds padding_waste, pins compile count);
- :mod:`core` — the deterministic scheduler: continuous admission
  (mid-wave slot swaps via ``ops.step.run_wave_chunk`` +
  ``state.set_state``), priority lanes with weighted admission, and
  bounded queues with explicit ``rejected`` backpressure;
- :mod:`server` — the socket layer around the core (``cache-sim
  daemon``);
- :mod:`client` — the thin ``cache-sim submit`` client.

The core is fully synchronous and clock-injected: under a
VirtualClock two identical submission schedules emit byte-identical
serve-trace docs, so every scheduler behavior is testable without a
socket or wall clock. The server adds ONLY transport: handler threads
enqueue into the core under one lock; the scheduler thread owns every
JAX call.
"""

from ue22cs343bb1_openmp_assignment_tpu.daemon.core import (  # noqa: F401
    DaemonCore, drive)
