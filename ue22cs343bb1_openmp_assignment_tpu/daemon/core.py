"""The daemon scheduler core: continuous admission, lanes, buckets.

This is the whole serving brain, deliberately with NO socket in it:
a synchronous, clock-injected state machine the socket layer
(daemon/server.py) merely transports requests into. Under a
VirtualClock two identical submission schedules produce byte-identical
serve-trace docs and stats — every scheduler behavior (lane priority,
bucket choice, mid-wave swaps, backpressure) is pinned by tests
without a socket or wall clock.

Continuous admission
--------------------
serve.py admits only at wave boundaries: one straggler holds every
finished slot hostage until the whole wave quiesces. The daemon runs
each bucket's wave as a sequence of ``ops.step.run_wave_chunk`` calls
(one jitted chunk of masked cycles, per-slot done mask returned) and
swaps between chunks: a slot whose job is done is extracted and
refilled via ``state.set_state`` while the other slots are still
mid-flight. Correctness rides the PR-9 parity argument unchanged — a
quiescent (or budget-masked) slot is a frozen fixpoint under the
chunk body's done-mask, so neither the extra chunks it sits through
nor the traced-index ``set_state`` swap of a NEIGHBORING slot can
change its bits, and every job's dump stays byte-identical to its
solo run (tests/test_daemon.py).

Shape buckets
-------------
Jobs run in the cheapest slot class covering their (nodes, trace_len),
chosen online from the submitted-shape histogram
(daemon/bucketing.choose_buckets) up to ``max_buckets`` classes per
protocol. Each bucket is one compiled ``run_wave_chunk`` signature;
admission into a full set of buckets never recompiles (the bucketed
recompile-guard prong). When a job fits no bucket and the class
budget is spent, the nearest bucket grows to cover it — only once
idle, counted in ``bucket_growths`` (each growth is one new compile).

Priority lanes + backpressure
-----------------------------
Two lanes (interactive/batch) with bounded FIFO queues. Admission
picks the next lane by smooth weighted round-robin over non-empty
lanes (default 4:1 interactive), so interactive jobs overtake queued
batch work at full contention without starving it. A submit into a
full lane gets an explicit ``rejected`` response — backpressure is
always visible, never a silent drop (and never touches the simulated
machines, so ``mb_dropped`` stays orthogonal).

Result retention
----------------
A long-lived daemon must not grow with jobs served: only the newest
``retain_results`` terminal jobs keep their result doc, status entry,
and closed span (older ones answer ``unknown``; ``--out-dir`` is the
durable record). Lifetime counters (``jobs``, per-lane totals) are
exact forever — only per-job payloads are evicted — and the stats
latency summaries become a sliding window over the retained spans.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.daemon import bucketing, protocol
from ue22cs343bb1_openmp_assignment_tpu.obs import burnrate, events, recording
from ue22cs343bb1_openmp_assignment_tpu.obs.clock import MonotonicClock
from ue22cs343bb1_openmp_assignment_tpu.serve import (
    JobSpec, SpanBook, build_job_arrays, build_job_state, job_config,
    job_dumps, job_metrics_doc, protocol_phase, serve_trace_doc,
    weighted_padding_waste, _STATE_CACHE)

#: bound on the retained queue-depth/occupancy sample trail (each
#: sample is one 3-tuple per scheduler turn; the oldest are dropped)
_MAX_SAMPLES = 65_536


@dataclasses.dataclass
class _Lane:
    """One priority lane: a bounded FIFO plus its admission weight and
    lifetime counters."""

    name: str
    weight: int
    depth: int
    queue: List[Tuple[JobSpec, float]] = dataclasses.field(
        default_factory=list)
    credit: int = 0          # smooth weighted round-robin accumulator
    submitted: int = 0
    admitted: int = 0
    rejected: int = 0
    done: int = 0


class _Bucket:
    """One slot shape class: a compiled chunk-wave over ``slots``
    batch positions at one (nodes, trace_len, protocol) signature."""

    # lint: host
    def __init__(self, shape: bucketing.Shape, proto: str, slots: int,
                 queue_capacity: int):
        from ue22cs343bb1_openmp_assignment_tpu import state as st
        from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
        self.shape = shape
        self.protocol = proto
        self.label = f"{proto}:{shape[0]}x{shape[1]}"
        self.slots = slots
        self.scfg = SystemConfig.scale(
            num_nodes=shape[0], max_instrs=shape[1],
            queue_capacity=queue_capacity, protocol=proto)
        self.phase = protocol_phase(proto)
        if ("empty", self.scfg) not in _STATE_CACHE:
            _STATE_CACHE[("empty", self.scfg)] = st.init_state(self.scfg)
        self.bstate = st.stack_states(
            [_STATE_CACHE[("empty", self.scfg)]] * slots)
        self.occupant: List[Optional[JobSpec]] = [None] * slots
        self.lane_of: List[Optional[str]] = [None] * slots
        self.real_by_slot = [0] * slots
        self.started_chunk = [0] * slots
        self.chunks = 0
        self.admitted = 0

    # lint: host
    def busy(self) -> int:
        return sum(1 for o in self.occupant if o is not None)


class DaemonCore:
    """The deterministic serving scheduler (no transport, no threads).

    The socket layer calls :meth:`submit` / :meth:`status` /
    :meth:`result` / :meth:`stats` / :meth:`trace_doc` /
    :meth:`drain` under its lock and :meth:`pump` from the one
    scheduler thread; tests and :func:`drive` call the same methods
    directly. ``pump`` runs ONE chunk on every occupied bucket —
    admission happens between chunks, which is what makes it
    continuous.
    """

    # lint: host
    def __init__(self, slots: int = 4, max_buckets: int = 4,
                 chunk: int = 16, max_cycles: int = 100_000,
                 queue_capacity: int = 64,
                 lane_depth: int = protocol.DEFAULT_LANE_DEPTH,
                 lane_weights: Optional[Dict[str, int]] = None,
                 clock=None, out_dir=None, keep_dumps: bool = True,
                 retain_results: int = protocol.DEFAULT_RETAIN_RESULTS,
                 recorder: Optional[recording.RecordingWriter] = None,
                 emitter: Optional[events.EventEmitter] = None,
                 burn: Optional[burnrate.BurnRateMonitor] = None):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if max_buckets < 1:
            raise ValueError(f"max_buckets must be >= 1, "
                             f"got {max_buckets}")
        if retain_results < 1:
            raise ValueError(f"retain_results must be >= 1, "
                             f"got {retain_results}")
        weights = dict(protocol.DEFAULT_LANE_WEIGHTS)
        if lane_weights:
            weights.update(lane_weights)
        self.slots = slots
        self.max_buckets = max_buckets
        self.chunk = chunk
        self.max_cycles = max_cycles
        self.queue_capacity = queue_capacity
        self.clock = clock if clock is not None else MonotonicClock()
        self.out_dir = (pathlib.Path(out_dir) if out_dir is not None
                        else None)
        self.keep_dumps = keep_dumps
        self.retain_results = retain_results
        self.recorder = recorder
        self.emitter = emitter
        self.burn = burn
        self.t_start = self.clock.now()
        self.book = SpanBook(self.clock)
        self.lanes: Dict[str, _Lane] = {
            name: _Lane(name=name, weight=int(w), depth=int(lane_depth))
            for name, w in sorted(weights.items())}
        self.buckets: Dict[Tuple[str, int, int], _Bucket] = {}
        self.draining = False
        self.results: Dict[str, dict] = {}
        self._status: Dict[str, str] = {}
        self._hist: Dict[str, Dict[bucketing.Shape, int]] = {}
        self._max_shape: Optional[bucketing.Shape] = None
        self.samples: List[Tuple[float, int, int]] = []
        self.chunks = 0
        self.busy_s = 0.0
        self.mb_dropped = 0
        self.mid_wave_swaps = 0
        self.bucket_growths = 0
        self.queue_depth_peak = 0
        self.results_evicted = 0
        self.stats_seq = 0
        self.slo_alerts = 0
        self._lane_hist: Dict[str, object] = {}
        self._terminal_order: List[str] = []
        self._quiesced_total = 0
        self._real_total = 0
        self._budget_total = 0
        self._rejected_total = 0

    # lint: host
    def _emit(self, kind: str, job: Optional[str] = None,
              **fields) -> None:
        """One live-ops event (obs.events) at the CURRENT clock time,
        as an offset from core start. Always clock.now(), never a
        scheduled submit stamp: event time is when the scheduler acted
        (which also keeps the stream's t_s non-decreasing when an
        open-loop driver back-stamps arrivals)."""
        if self.emitter is not None:
            self.emitter.emit(kind, self.clock.now() - self.t_start,
                              job, **fields)

    # -- admission-side API (called by the socket handlers) ---------------

    # lint: host
    def submit(self, spec: JobSpec, lane: str = "batch",
               t_submit: Optional[float] = None) -> dict:
        """Enqueue one job; returns the protocol response dict.
        ``t_submit`` lets an open-loop driver stamp the SCHEDULED
        arrival time (coordinated-omission-free, the soak convention);
        the socket path stamps receipt time."""
        base = {"ok": True, "op": "submit", "job": spec.name,
                "lane": lane}
        if lane not in self.lanes:
            return protocol.error(
                "submit", f"unknown lane {lane!r} "
                          f"(one of {sorted(self.lanes)})")
        if (spec.name in self._status
                and self._status[spec.name] != "rejected"):
            return protocol.error(
                "submit", f"duplicate job name {spec.name!r}")
        ln = self.lanes[lane]
        if self.draining:
            ln.rejected += 1
            self._rejected_total += 1
            self._status[spec.name] = "rejected"
            self._emit("lane-reject", spec.name, lane=lane,
                       reason="draining")
            self._retire(spec.name)
            return {**base, "ok": False, "status": "rejected",
                    "reason": "draining"}
        if len(ln.queue) >= ln.depth:
            # bounded queue: explicit, attributable backpressure — the
            # client hears "rejected", the simulated machines never see
            # the job (mb_dropped stays zero)
            ln.rejected += 1
            self._rejected_total += 1
            self._status[spec.name] = "rejected"
            self._emit("lane-reject", spec.name, lane=lane,
                       reason="queue-full", depth=ln.depth)
            self._retire(spec.name)
            return {**base, "ok": False, "status": "rejected",
                    "reason": f"lane {lane!r} queue full "
                              f"(depth {ln.depth})"}
        t = self.book._t(t_submit)
        self.book.submit(spec.name, t)
        self.book.annotate(spec.name, lane=lane)
        ln.queue.append((spec, t))
        ln.submitted += 1
        self._status[spec.name] = "queued"
        self._hist.setdefault(spec.protocol, {})
        shape = (spec.nodes, spec.trace_len)
        h = self._hist[spec.protocol]
        h[shape] = h.get(shape, 0) + 1
        self._max_shape = (shape if self._max_shape is None
                           else bucketing.cover(self._max_shape, shape))
        self._sample()
        if self.recorder is not None:
            # every ACCEPTED submission is one recording row: the
            # full spec, the lane, the SCHEDULED arrival time on the
            # injected clock, and the queue depth at accept
            self.recorder.submit(
                spec, lane, t - self.t_start,
                sum(len(x.queue) for x in self.lanes.values()))
        self._emit("submit-accepted", spec.name, lane=lane,
                   depth=len(ln.queue))
        return {**base, "status": "queued"}

    # lint: host
    def status(self, job: str) -> dict:
        return {"ok": True, "op": "status", "job": job,
                "status": self._status.get(job, "unknown")}

    # lint: host
    def result(self, job: str) -> dict:
        st = self._status.get(job, "unknown")
        if st != "done":
            return {"ok": st not in ("unknown", "rejected"),
                    "op": "result", "job": job, "status": st}
        return {"ok": True, "op": "result", "job": job,
                "status": "done", **self.results[job]}

    # lint: host
    def drain(self) -> dict:
        """Stop admitting new jobs; the pump flushes what is queued
        and in flight. The socket layer responds once :meth:`idle`."""
        self.draining = True
        return {"ok": True, "op": "drain", "draining": True}

    # lint: host
    def idle(self) -> bool:
        return (not any(ln.queue for ln in self.lanes.values())
                and not any(b.busy() for b in self.buckets.values()))

    # lint: host
    def _retire(self, name: str) -> None:
        """Record a terminal (done/rejected) job and evict the oldest
        terminal jobs beyond ``retain_results`` — the result doc, the
        status entry, and the closed span all go, so a long-lived
        daemon's memory is bounded no matter how many jobs it serves.
        Evicted jobs answer ``unknown``; ``out_dir`` is the durable
        record. Lifetime counters are never evicted."""
        self._terminal_order.append(name)
        while len(self._terminal_order) > self.retain_results:
            old = self._terminal_order.pop(0)
            # a rejected name may have been resubmitted and be live
            # again (queued/running) — only terminal state is evictable
            if self._status.get(old) in ("done", "rejected"):
                del self._status[old]
                self.results.pop(old, None)
                self.results_evicted += 1
                self._emit("result-evicted", old,
                           retain=self.retain_results)
        self.book.prune(self.retain_results)

    # -- scheduler side ----------------------------------------------------

    # lint: host
    def _sample(self) -> None:
        queued = sum(len(ln.queue) for ln in self.lanes.values())
        busy = sum(b.busy() for b in self.buckets.values())
        self.queue_depth_peak = max(self.queue_depth_peak, queued)
        self.samples.append(
            (self.clock.now() - self.t_start, queued, busy))
        if len(self.samples) > _MAX_SAMPLES:
            del self.samples[:len(self.samples) - _MAX_SAMPLES]

    # lint: host
    def _next_lane(self, skip) -> Optional[Tuple[_Lane, int]]:
        """Smooth weighted round-robin over lanes with queued work:
        each eligible lane gains its weight in credit and the richest
        lane is picked; returns (lane, payback). The payback — the
        round's total credit issue — is debited by :meth:`_admit`
        only once the lane's head job actually lands in a slot, so a
        head-of-line-blocked lane is never charged for admissions
        that did not happen (it keeps its credit and catches up once
        unblocked, holding the configured share ratio)."""
        elig = [ln for ln in self.lanes.values()
                if ln.queue and ln.name not in skip]
        if not elig:
            return None
        for ln in elig:
            ln.credit += ln.weight
        best = max(elig, key=lambda ln: (ln.credit, ln.weight, ln.name))
        return best, sum(ln.weight for ln in elig)

    # lint: host
    def _bucket_count(self, proto: str) -> int:
        return sum(1 for (p, _, _) in self.buckets if p == proto)

    # lint: host
    def _bucket_for_job(self, spec: JobSpec) -> Optional[_Bucket]:
        """The bucket this job runs in, creating or growing one under
        the ≤ max_buckets-per-protocol class budget. None = no class
        can take it right now (the nearest bucket must drain before
        it can grow) — the job stays queued and admission retries."""
        shape = (spec.nodes, spec.trace_len)
        mine = {k: b for k, b in self.buckets.items()
                if k[0] == spec.protocol}
        fit = bucketing.bucket_for(shape,
                                   [b.shape for b in mine.values()])
        if fit is not None:
            return self.buckets[(spec.protocol, fit[0], fit[1])]
        if self._bucket_count(spec.protocol) < self.max_buckets:
            # choose the new class from the full shape histogram seen
            # so far, not the single job: with a bimodal mix the DP
            # proposes the small AND the large class up front, so the
            # classes stabilize after the first few admissions
            chosen = bucketing.choose_buckets(
                self._hist[spec.protocol], self.max_buckets)
            cls = bucketing.bucket_for(shape, chosen)
            b = _Bucket(cls, spec.protocol, self.slots,
                        self.queue_capacity)
            self.buckets[(spec.protocol, cls[0], cls[1])] = b
            return b
        # class budget spent and nothing covers: grow the bucket whose
        # cover costs least — but only once it is idle (growing means a
        # new slot config = a fresh wave compile; swapping it under
        # in-flight jobs would also break their bit parity)
        key = min(mine, key=lambda k: (
            bucketing.cover(mine[k].shape, shape)[0]
            * bucketing.cover(mine[k].shape, shape)[1], k))
        victim = mine[key]
        if victim.busy():
            return None
        grown = bucketing.cover(victim.shape, shape)
        del self.buckets[key]
        b = _Bucket(grown, spec.protocol, self.slots,
                    self.queue_capacity)
        # the grown class REPLACES the victim: carry its lifetime
        # counters so stats() keeps the retired bucket's history
        b.chunks = victim.chunks
        b.admitted = victim.admitted
        self.buckets[(spec.protocol, grown[0], grown[1])] = b
        self.bucket_growths += 1
        self._emit("bucket-growth", spec.name, bucket=b.label,
                   grown_from=victim.label)
        return b

    # lint: host
    def _admit(self) -> None:
        """Fill free slots from the lanes, weighted; stops when no
        eligible lane's head job can be placed."""
        from ue22cs343bb1_openmp_assignment_tpu import state as st
        skip = set()
        while True:
            picked = self._next_lane(skip)
            if picked is None:
                return
            ln, payback = picked
            spec, _ = ln.queue[0]
            b = self._bucket_for_job(spec)
            slot = (None if b is None else
                    next((i for i, o in enumerate(b.occupant)
                          if o is None), None))
            if b is None or slot is None:
                # head-of-line blocked (bucket full / must drain to
                # grow): keep the lane FIFO, let other lanes admit —
                # and do NOT debit the payback (no admission happened)
                skip.add(ln.name)
                continue
            ln.queue.pop(0)
            ln.credit -= payback
            ln.admitted += 1
            b.occupant[slot] = spec
            b.lane_of[slot] = ln.name
            b.started_chunk[slot] = b.chunks
            b.admitted += 1
            jcfg = job_config(spec, self.queue_capacity)
            b.real_by_slot[slot] = int(np.sum(
                build_job_arrays(jcfg, spec)[3]))
            b.bstate = st.set_state(
                b.bstate, slot, build_job_state(b.scfg, jcfg, spec))
            if any(o is not None and b.started_chunk[j] < b.chunks
                   for j, o in enumerate(b.occupant) if j != slot):
                # the defining continuous-admission event: this slot
                # joins a wave other slots are already mid-flight in
                self.mid_wave_swaps += 1
            t = self.clock.now()
            self.book.admitted(spec.name, wave=b.chunks, slot=slot, t=t)
            self.book.running(spec.name, t)
            self.book.annotate(spec.name, bucket=b.label)
            self._status[spec.name] = "running"
            self._emit("admitted", spec.name, lane=ln.name,
                       bucket=b.label, wave=b.chunks, slot=slot)

    # lint: host
    def pump(self) -> bool:
        """One scheduler turn: admit, then run ONE chunk on every
        occupied bucket and extract/refill slots that resolved.
        Returns whether any chunk ran (False = fully idle)."""
        import jax
        self._sample()
        self._admit()
        ran = False
        for key in sorted(self.buckets):
            # the mid-loop _admit below can DELETE a snapshot key: a
            # freed slot admits a head-of-line-blocked job and the job
            # behind it grows an idle bucket (del + re-key in
            # _bucket_for_job) — the grown bucket runs next pump
            b = self.buckets.get(key)
            if b is None or not b.busy():
                continue
            from ue22cs343bb1_openmp_assignment_tpu.ops import step
            t0 = self.clock.now()
            b.bstate, quiet_d, done_d = step.run_wave_chunk(
                b.scfg, b.bstate, self.chunk, self.max_cycles, b.phase)
            quiet, done = jax.device_get((quiet_d, done_d))
            self.clock.on_wave()
            t1 = self.clock.now()
            b.chunks += 1
            self.chunks += 1
            self.busy_s += t1 - t0
            ran = True
            for i, spec in enumerate(b.occupant):
                if spec is not None and bool(done[i]):
                    self._extract(b, i, bool(quiet[i]), t1)
            # continuous admission: refill the freed slots NOW, so the
            # next chunk (this pump or the next) runs them alongside
            # the still-unfinished occupants
            self._admit()
        return ran

    # lint: host
    def _extract(self, b: _Bucket, i: int, ok: bool,
                 t_end: float) -> None:
        import jax
        from ue22cs343bb1_openmp_assignment_tpu import state as st
        spec = b.occupant[i]
        lane = self.lanes[b.lane_of[i]]
        jstate = st.index_state(b.bstate, i)
        jcfg = job_config(spec, self.queue_capacity)
        self.book.quiescent(spec.name, ok, t_end)
        metrics = job_metrics_doc(jstate)
        dropped = int(metrics["mb_dropped"] or 0)
        self.mb_dropped += dropped
        doc = {
            "spec": dataclasses.asdict(spec),
            "lane": lane.name,
            "bucket": b.label,
            "quiesced": ok,
            "cycles": int(np.asarray(jax.device_get(jstate.cycle))),
            "metrics": metrics,
        }
        dumps = job_dumps(b.scfg, jcfg, jstate)
        # the digest is computed HERE, from the dumps, before the
        # _retire below may evict this very doc: a recording's digest
        # column stays complete even for jobs a bounded daemon no
        # longer retains (lifetime counters were already exact; this
        # makes the byte-parity fingerprint exact too)
        doc["digest"] = recording.digest(dumps)
        if self.keep_dumps:
            doc["dumps"] = dumps
        if self.out_dir is not None:
            jdir = self.out_dir / spec.name
            jdir.mkdir(parents=True, exist_ok=True)
            for n, text in enumerate(dumps):
                (jdir / f"node{n}_dump.txt").write_text(text)
            (jdir / "metrics.json").write_text(
                json.dumps({k: v for k, v in doc.items()
                            if k != "dumps"}, indent=2) + "\n")
        self.book.extracted(spec.name)
        # spans() is in extraction order, so the span just closed by
        # extracted() is the last one — its e2e feeds the per-lane
        # mergeable histogram and the burn-rate monitor
        span = self.book._done[-1]
        e2e_s = float(span["e2e_s"])
        if lane.name not in self._lane_hist:
            from ue22cs343bb1_openmp_assignment_tpu.obs import timeseries
            self._lane_hist[lane.name] = timeseries.LogHistogram()
        self._lane_hist[lane.name].observe(e2e_s * 1e3)
        if self.recorder is not None:
            self.recorder.result(spec.name, t_end - self.t_start, ok,
                                 doc["digest"], doc["cycles"], b.label)
        self._emit("quiesced", spec.name, lane=lane.name,
                   bucket=b.label, ok=ok, cycles=doc["cycles"],
                   e2e_ms=e2e_s * 1e3)
        if self.burn is not None:
            alert = self.burn.feed(t_end - self.t_start, e2e_s)
            if alert is not None:
                self.slo_alerts += 1
                self._emit("slo-alert", spec.name,
                           **{k: v for k, v in alert.items()
                              if k != "t_s"})
        self.results[spec.name] = doc
        self._status[spec.name] = "done"
        self._quiesced_total += int(ok)
        self._retire(spec.name)
        lane.done += 1
        self._real_total += b.real_by_slot[i]
        self._budget_total += b.shape[0] * b.shape[1]
        # the finished (quiescent = fixpoint) or budget-dead (masked)
        # state stays in the slot until set_state refills it — same
        # contract as serve.py
        b.occupant[i] = None
        b.lane_of[i] = None
        b.real_by_slot[i] = 0

    # -- reporting ---------------------------------------------------------

    # lint: host
    def record_config(self) -> dict:
        """The scheduler knobs a recording's header carries — enough
        for ``cache-sim replay`` to rebuild an equivalent core, so an
        in-proc replay of a VirtualClock session is bit-faithful by
        default."""
        return {
            "slots": self.slots, "max_buckets": self.max_buckets,
            "chunk": self.chunk, "max_cycles": self.max_cycles,
            "queue_capacity": self.queue_capacity,
            "lane_depth": max(ln.depth for ln in self.lanes.values()),
            "lane_weights": {name: ln.weight for name, ln
                             in sorted(self.lanes.items())},
        }

    # lint: host
    def stats(self) -> dict:
        """The validated ``cache-sim/daemon-stats/v1`` snapshot."""
        from ue22cs343bb1_openmp_assignment_tpu.obs import (
            schema, timeseries)
        done = sum(ln.done for ln in self.lanes.values())
        lane_lat = timeseries.lane_latency_summaries(self.book.spans())
        lanes = {}
        for name, ln in sorted(self.lanes.items()):
            hist = self._lane_hist.get(name)
            lanes[name] = {
                "weight": ln.weight, "depth": ln.depth,
                "queued": len(ln.queue), "submitted": ln.submitted,
                "admitted": ln.admitted, "rejected": ln.rejected,
                "done": ln.done, "latency": lane_lat.get(name),
                # unlike "latency" (a sliding window over RETAINED
                # spans), the histogram is lifetime-exact and
                # fleet-mergeable (fixed edges)
                "hist": None if hist is None else hist.to_doc(),
            }
        buckets = []
        for key in sorted(self.buckets):
            b = self.buckets[key]
            buckets.append({
                "bucket": b.label, "protocol": b.protocol,
                "nodes": b.shape[0], "trace_len": b.shape[1],
                "slots": b.slots, "busy": b.busy(),
                "admitted": b.admitted, "chunks": b.chunks,
            })
        # single-max-shape counterfactual: the budget the SAME done
        # jobs would have burned in one serve.py-style slot class at
        # the max submitted shape — the baseline bucketing must beat
        single = None
        if done and self._max_shape is not None:
            n, t = self._max_shape
            single = 1.0 - self._real_total / (done * n * t)
        # every snapshot gets the next seq — two stats docs from one
        # daemon are totally ordered even when uptime_s ties (virtual
        # clock, no wave between polls)
        self.stats_seq += 1
        doc = {
            "schema": schema.DAEMON_STATS_SCHEMA_ID,
            "clock": self.clock.kind,
            "uptime_s": self.clock.now() - self.t_start,
            "stats_seq": self.stats_seq,
            "draining": self.draining,
            "jobs": {
                "submitted": sum(ln.submitted
                                 for ln in self.lanes.values()),
                "rejected": self._rejected_total,
                "done": done,
                "quiesced": self._quiesced_total,
            },
            "lanes": lanes,
            "buckets": buckets,
            "chunks": self.chunks,
            "busy_s": self.busy_s,
            "drain_rate_jobs_per_s": (done / self.busy_s
                                      if self.busy_s > 0 else 0.0),
            "mb_dropped": self.mb_dropped,
            "mid_wave_swaps": self.mid_wave_swaps,
            "bucket_growths": self.bucket_growths,
            "queue_depth_peak": self.queue_depth_peak,
            "retain_results": self.retain_results,
            "results_evicted": self.results_evicted,
            "recording": (None if self.recorder is None else {
                "path": self.recorder.path,
                "submits": self.recorder.submits,
                "results": self.recorder.results,
            }),
            "events": (None if self.emitter is None else {
                "path": self.emitter.path,
                "ring": self.emitter.ring,
                "seq": self.emitter.seq,
                "dropped": self.emitter.dropped,
            }),
            "slo_alerts": self.slo_alerts,
            "burnrate": (None if self.burn is None
                         else self.burn.summary()),
            "padding_waste": (
                1.0 - self._real_total / self._budget_total
                if self._budget_total else None),
            "single_shape_padding_waste": single,
        }
        return schema.validate_daemon_stats(doc)

    # lint: host
    def trace_doc(self) -> dict:
        """Completed jobs as the validated serve-trace doc (spans
        carry the daemon's lane/bucket annotations)."""
        return serve_trace_doc(self.book.spans(), self.clock.kind)


# lint: host
def attach_recorder(core: DaemonCore,
                    path) -> recording.RecordingWriter:
    """Open a ``cache-sim/recording/v1`` writer on ``path`` (file or
    directory) and attach it to the core; every accepted submission
    and finished job from here on is streamed to it."""
    core.recorder = recording.RecordingWriter(
        path, core.clock.kind, core.record_config())
    return core.recorder


# lint: host
def attach_emitter(core: DaemonCore, path=None,
                   ring: int = events.DEFAULT_RING
                   ) -> events.EventEmitter:
    """Open a ``cache-sim/events/v1`` emitter (ring-only, or also
    streamed to ``path`` — the ``--events-dir`` artifact) and attach
    it to the core; every scheduler decision from here on is one
    structured event the ``watch`` verb can push to clients."""
    core.emitter = events.EventEmitter(
        core.clock.kind, ring=ring, path=path,
        config=core.record_config())
    return core.emitter


# lint: host
def drive(core: DaemonCore, arrivals) -> List[dict]:
    """Run an open-loop schedule ``[(t_offset_s, JobSpec, lane)]``
    directly through a core (no socket): release each job at its
    scheduled offset on the core's clock — submit stamped at the
    SCHEDULED time, coordinated-omission-free — and pump until idle.
    Under a VirtualClock the whole run is deterministic, which is how
    tests soak the daemon for minutes of virtual time in milliseconds
    of real time. Returns the submit responses in release order."""
    clock = core.clock
    t0 = clock.now()
    pending = sorted(
        ((t0 + dt, spec, lane) for dt, spec, lane in arrivals),
        key=lambda a: (a[0], a[1].name))
    responses = []
    while pending or not core.idle():
        now = clock.now()
        while pending and pending[0][0] <= now:
            t_arr, spec, lane = pending.pop(0)
            responses.append(core.submit(spec, lane=lane,
                                         t_submit=t_arr))
        if core.idle():
            if pending:
                clock.sleep(pending[0][0] - now)
            continue
        if not core.pump():
            if not pending:
                raise RuntimeError("daemon core wedged: queued jobs "
                                   "but no admissible bucket")
            clock.sleep(max(0.0, pending[0][0] - clock.now()))
    return responses
