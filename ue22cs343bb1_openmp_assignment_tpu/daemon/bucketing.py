"""Slot shape bucketing: bound padding_waste with a few slot classes.

The single-slot-shape serving layer (serve.py) pads every job to the
max (nodes, trace_len) over the stream — a bimodal mix of tiny and
huge jobs then burns most of its slot instruction budget on padding.
The fix is a SMALL fixed set of slot shape classes ("buckets"): each
job runs in the cheapest bucket that covers its shape, one vmapped
wave per bucket, and the compile count stays pinned at the bucket
count (each bucket is one ``run_wave_chunk`` jit signature — the
bucketed prong of analysis/lint_jaxpr.recompile_guard).

``choose_buckets`` picks ≤ k classes from a shape histogram by exact
dynamic programming over the lexicographically sorted distinct shapes
partitioned into contiguous segments (each segment's class is the
elementwise max over its members, so every member fits). Contiguous-
in-sorted-order is optimal when trace length grows with node count
(the usual fleet shape) and a deterministic, near-optimal heuristic
otherwise — and determinism is load-bearing: the daemon re-chooses
online as the histogram grows, and two identical submission schedules
must build identical buckets (the VirtualClock byte-parity gate).

Costs are in slot-instruction-budget units (``nodes * trace_len`` per
job), the same unit ``serve.weighted_padding_waste`` reports, so "k
buckets strictly beat one max shape" is checkable end to end.
"""
# lint: host

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

Shape = Tuple[int, int]   # (nodes, trace_len)


def shape_histogram(shapes) -> Dict[Shape, int]:
    """Iterable of (nodes, trace_len) → {shape: count}."""
    hist: Dict[Shape, int] = {}
    for s in shapes:
        key = (int(s[0]), int(s[1]))
        hist[key] = hist.get(key, 0) + 1
    return hist


def cover(a: Shape, b: Shape) -> Shape:
    """The smallest shape both fit in (elementwise max)."""
    return (max(a[0], b[0]), max(a[1], b[1]))


def fits(shape: Shape, bucket: Shape) -> bool:
    return shape[0] <= bucket[0] and shape[1] <= bucket[1]


def bucket_for(shape: Shape, buckets) -> Optional[Shape]:
    """The cheapest (min slot budget, then lexicographic) bucket
    covering ``shape``; None when nothing fits."""
    covering = [b for b in buckets if fits(shape, b)]
    if not covering:
        return None
    return min(covering, key=lambda b: (b[0] * b[1], b))


def assignment_cost(hist: Dict[Shape, int], buckets) -> int:
    """Total slot instruction budget when every job in ``hist`` runs
    in its cheapest covering bucket; raises if any shape fits no
    bucket (a chooser bug — chosen buckets always cover by
    construction)."""
    total = 0
    for shape, count in hist.items():
        b = bucket_for(shape, buckets)
        if b is None:
            raise ValueError(f"shape {shape} fits no bucket in "
                             f"{sorted(buckets)}")
        total += count * b[0] * b[1]
    return total


def padding_waste(hist: Dict[Shape, int], buckets) -> float:
    """The weighted padding_waste of running ``hist`` through
    ``buckets`` — 1 - real/budget, the serve summary convention."""
    budget = assignment_cost(hist, buckets)
    real = sum(c * n * t for (n, t), c in hist.items())
    return 1.0 - real / budget if budget else 0.0


def choose_buckets(hist: Dict[Shape, int], k: int) -> List[Shape]:
    """≤ k slot classes for a shape histogram, minimizing total slot
    budget over contiguous segments of the sorted distinct shapes.

    Returns the chosen classes sorted ascending. ``k >= len(hist)``
    degenerates to one exact class per shape (zero shape padding);
    ``k == 1`` degenerates to the single max shape — the baseline the
    bucketing win is measured against.
    """
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    if not hist:
        return []
    shapes = sorted(hist)
    m = len(shapes)
    k = min(k, m)

    # seg_class[i][j] / seg_cost[i][j]: the class covering shapes[i..j]
    # and the budget of running those shapes' jobs in it
    seg_class = [[None] * m for _ in range(m)]
    seg_cost = [[0] * m for _ in range(m)]
    for i in range(m):
        c = shapes[i]
        jobs = 0
        for j in range(i, m):
            c = cover(c, shapes[j])
            jobs += hist[shapes[j]]
            seg_class[i][j] = c
            seg_cost[i][j] = jobs * c[0] * c[1]

    INF = float("inf")
    # best[c][j]: min budget partitioning shapes[0..j] into c+1 segments
    best = [[INF] * m for _ in range(k)]
    cut = [[-1] * m for _ in range(k)]
    for j in range(m):
        best[0][j] = seg_cost[0][j]
    for c in range(1, k):
        for j in range(c, m):
            for i in range(c, j + 1):
                cand = best[c - 1][i - 1] + seg_cost[i][j]
                if cand < best[c][j]:
                    best[c][j] = cand
                    cut[c][j] = i
    segs = min(range(k), key=lambda c: best[c][m - 1])
    bounds = []
    j = m - 1
    for c in range(segs, 0, -1):
        i = cut[c][j]
        bounds.append((i, j))
        j = i - 1
    bounds.append((0, j))
    return sorted(seg_class[i][j] for i, j in bounds)
