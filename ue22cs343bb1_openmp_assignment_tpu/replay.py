"""``cache-sim replay``: the universal front door over every captured
artifact the framework emits.

One command, four artifact kinds, auto-detected (:func:`detect`):

* **recording** — a ``cache-sim/recording/v1`` JSONL (file, or a
  directory holding ``recording.jsonl``): re-driven as an open-loop
  soak schedule with the ORIGINAL arrival times and lanes preserved
  (coordinated-omission-free — releases were scheduled when recorded
  and stay scheduled on replay), through either the in-proc scheduler
  on a VirtualClock (deterministic; the default) or a live daemon
  (``--daemon ADDR``). Per-job dump digests are checked against the
  recorded digest column, and a v1.4 latency block is emitted for BOTH
  sides so ``bench-diff --latency`` adjudicates recorded-vs-replayed.
* **soak incident** — a ``cache-sim/soak-incident/v1`` directory: its
  embedded breach-window ``recording.jsonl`` slice is replayed as
  above (an incident dir IS a replayable artifact).
* **flight incident** — a ``cache-sim/incident/v1`` directory: the
  repro case re-runs through the differential oracle
  (obs.flight.replay_incident).
* **repro fixture** — a ``cache-sim/repro/v1`` dir / ``repro.json``:
  re-run through the full oracle chain (analysis.fixtures.replay),
  exit 0 iff the recorded verdict reproduces.

``--slo`` puts a latency bound on a recording replay: a breach exits
4 (soak.EXIT_SLO_BREACH) and dumps an incident dir that embeds the
breach-window recording slice — and ``--shrink`` then ddmins the JOB
LIST (jobs are the atoms, not instructions) down to a minimal subset
that still breaches, written back as a replayable incident fixture.
"""
# lint: host

from __future__ import annotations

import json
import os
import pathlib
from typing import List, Optional

from ue22cs343bb1_openmp_assignment_tpu import soak as soak_mod
from ue22cs343bb1_openmp_assignment_tpu.obs import recording

#: artifact kinds :func:`detect` can name
KINDS = ("recording", "soak-incident", "flight-incident", "fixture")

#: shared metric string stamped on both sides of a recorded-vs-
#: replayed comparison — bench-diff refuses to compare across metrics
REPLAY_METRIC = "replay_job_latency"


# lint: host
def detect(path) -> str:
    """Classify a captured artifact; returns one of :data:`KINDS` or
    raises ValueError naming everything that was tried."""
    path = str(path)
    tried: List[str] = []
    if os.path.isdir(path):
        inc = os.path.join(path, "incident.json")
        if os.path.exists(inc):
            with open(inc) as f:
                schema = json.load(f).get("schema")
            if schema == soak_mod.INCIDENT_SCHEMA_ID:
                return "soak-incident"
            from ue22cs343bb1_openmp_assignment_tpu.obs import flight
            if schema == flight.SCHEMA_ID:
                return "flight-incident"
            tried.append(f"incident.json with unknown schema "
                         f"{schema!r}")
        if os.path.exists(os.path.join(path, "repro.json")):
            return "fixture"
        if os.path.exists(os.path.join(path, recording.FILENAME)):
            return "recording"
        tried.append("directory without incident.json / repro.json / "
                     + recording.FILENAME)
    elif os.path.exists(path):
        if os.path.basename(path) == "repro.json":
            return "fixture"
        try:
            with open(path) as f:
                first = json.loads(f.readline())
            schema = first.get("schema") if isinstance(first, dict) \
                else None
        except (ValueError, UnicodeDecodeError):
            schema = None
            tried.append("file whose first line is not JSON")
        if schema == recording.SCHEMA_ID:
            return "recording"
        from ue22cs343bb1_openmp_assignment_tpu.analysis import \
            fixtures
        if schema == fixtures.SCHEMA:
            return "fixture"
        if schema is not None:
            tried.append(f"file with unknown schema {schema!r}")
    else:
        tried.append("path does not exist")
    raise ValueError(
        f"{path}: not a replayable artifact ({'; '.join(tried)}) — "
        f"expected a {recording.SCHEMA_ID} JSONL, a soak/flight "
        "incident directory, or a repro fixture")


# lint: host
def replay_recording(rec: dict, daemon: Optional[str] = None,
                     slots: Optional[int] = None,
                     chunk: Optional[int] = None,
                     max_cycles: Optional[int] = None,
                     queue_capacity: Optional[int] = None,
                     wave_s: float = 1e-3, out_dir=None,
                     timeout_s: float = 300.0,
                     quiet: bool = True, burn=None) -> dict:
    """Re-drive a loaded recording; returns a ``cache-sim/soak/v1``-
    shaped doc (``transport: "replay"``) extended with the digest
    audit (``digests_matched`` / ``digest_mismatches``) and the
    RECORDED latency block alongside the replayed one.

    In-proc (default): the core is rebuilt from the recording's config
    fingerprint (CLI overrides win) on a fresh VirtualClock, so a
    virtual-clock capture replays bit-faithfully — identical spans,
    identical dumps, identical latency block. ``--daemon`` instead
    drives a LIVE daemon over its socket via soak.soak_daemon with the
    original per-job lanes pinned; latency is then client-observed.
    """
    rate = recording.derived_arrival_rate(rec)
    sched = recording.arrivals(rec)
    recorded = recording.results_by_job(rec)
    if daemon:
        doc = soak_mod.soak_daemon(
            [(t, spec) for t, spec, _ in sched], daemon,
            arrival_rate=rate, timeout_s=timeout_s, quiet=quiet,
            lanes=[lane for _, _, lane in sched], burn=burn)
        doc["transport"] = "replay-daemon"
        # dumps do not cross the socket; audit what the daemon reports
        doc["digests_matched"] = None
        doc["digest_mismatches"] = []
    else:
        doc = _replay_in_proc(rec, sched, rate, recorded,
                              slots=slots, chunk=chunk,
                              max_cycles=max_cycles,
                              queue_capacity=queue_capacity,
                              wave_s=wave_s, out_dir=out_dir,
                              burn=burn)
    doc["recorded_latency"] = recording.latency_block(
        rec, arrival_rate=rate)
    doc["recorded_jobs"] = len(sched)
    doc["recording_path"] = rec.get("path")
    return doc


# lint: host
def _replay_in_proc(rec: dict, sched, rate: float, recorded: dict,
                    slots=None, chunk=None, max_cycles=None,
                    queue_capacity=None, wave_s: float = 1e-3,
                    out_dir=None, burn=None) -> dict:
    from ue22cs343bb1_openmp_assignment_tpu.daemon.core import (
        DaemonCore, drive)
    from ue22cs343bb1_openmp_assignment_tpu.daemon import protocol
    from ue22cs343bb1_openmp_assignment_tpu.obs import timeseries
    from ue22cs343bb1_openmp_assignment_tpu.obs.clock import (
        VirtualClock)
    cfg = dict(rec.get("config") or {})
    core = DaemonCore(
        slots=int(slots if slots is not None
                  else cfg.get("slots", 4)),
        max_buckets=int(cfg.get("max_buckets", 4)),
        chunk=int(chunk if chunk is not None
                  else cfg.get("chunk", 16)),
        max_cycles=int(max_cycles if max_cycles is not None
                       else cfg.get("max_cycles", 100_000)),
        queue_capacity=int(queue_capacity if queue_capacity is not None
                           else cfg.get("queue_capacity", 64)),
        lane_depth=int(cfg.get("lane_depth",
                               protocol.DEFAULT_LANE_DEPTH)),
        lane_weights=cfg.get("lane_weights"),
        clock=VirtualClock(wave_s=wave_s), burn=burn,
        out_dir=out_dir, keep_dumps=True,
        # replay must never evict: the digest audit and the span-based
        # latency block need every job's result
        retain_results=max(len(sched) + 1,
                           protocol.DEFAULT_RETAIN_RESULTS))
    responses = drive(core, sched)
    rejected = [{"job": r["job"], "lane": r.get("lane"),
                 "reason": r.get("reason", r.get("error"))}
                for r in responses if r.get("status") != "queued"]
    mismatches = []
    matched = 0
    for name, doc in sorted(core.results.items()):
        rrow = recorded.get(name)
        if rrow is None:
            continue
        if doc["digest"] == rrow["digest"]:
            matched += 1
        else:
            mismatches.append({"job": name,
                               "recorded": rrow["digest"],
                               "replayed": doc["digest"]})
    spans = core.book.spans()
    closed = [s for s in spans if s.get("e2e_s") is not None]
    lat_s = [s["e2e_s"] for s in closed]
    series_summary = timeseries.summarize_serve_series(core.samples)
    latency = timeseries.latency_summary(
        lat_s, arrival_rate=rate,
        queue_depth_peak=core.queue_depth_peak)
    if latency is not None:
        latency["samples_ms"] = [round(s * 1e3, 6)
                                 for s in sorted(lat_s)]
    stats = core.stats()
    drain = stats["drain_rate_jobs_per_s"]
    return {
        "schema": soak_mod.SCHEMA_ID,
        "transport": "replay",
        "slots": core.slots,
        "arrival_rate": rate,
        "jobs_total": len(sched),
        "jobs_quiesced": sum(1 for d in core.results.values()
                             if d["quiesced"]),
        "rejected": rejected,
        "wave_count": stats["chunks"],
        "wall_s": stats["uptime_s"],
        "busy_s": stats["busy_s"],
        "drain_rate_jobs_per_s": drain,
        "padding_waste": stats["padding_waste"] or 0.0,
        "mb_dropped": stats["mb_dropped"],
        "latency": latency,
        "lane_latency": timeseries.lane_latency_summaries(spans),
        "samples_ms": [round(s * 1e3, 6) for s in sorted(lat_s)],
        "series": timeseries.serve_series(core.samples),
        "series_summary": series_summary,
        "verdict": soak_mod.backpressure_verdict(rate, drain,
                                                 series_summary),
        "digests_matched": matched,
        "digest_mismatches": mismatches,
        "jobs": {name: {"quiesced": d["quiesced"], "lane": d["lane"],
                        "bucket": d["bucket"], "cycles": d["cycles"],
                        "digest": d["digest"]}
                 for name, d in sorted(core.results.items())},
        "waves": [],
        "trace": core.trace_doc(),
        "burnrate": None if burn is None else burn.summary(),
    }


# lint: host
def latency_entries(rec: dict, doc: dict):
    """The (recorded, replayed) pair of v1.4 bench-history entries the
    latency adjudication runs on. Both sides share the metric string
    and the DERIVED arrival rate (same schedule → same offered load by
    construction), so ``bench-diff --latency`` compares them instead
    of declaring different operating points."""
    from ue22cs343bb1_openmp_assignment_tpu.obs import history
    rate = recording.derived_arrival_rate(rec)
    rec_block = recording.latency_block(rec, arrival_rate=rate)
    rep_block = doc.get("latency")
    if rec_block is None or rep_block is None:
        raise ValueError("latency adjudication needs finished jobs on "
                         "both sides (recording and replay)")
    rep_block = dict(rep_block)
    rep_block["arrival_rate"] = rate
    out = []
    for label, block in (("recorded", rec_block),
                         ("replayed", rep_block)):
        times = [max(ms / 1e3, 1e-9)
                 for ms in block.get("samples_ms") or []]
        out.append(history.entry(
            label=label, source="replay",
            result={"metric": REPLAY_METRIC,
                    "value": float(block["p95_ms"]), "unit": "ms"},
            extra={"engine": "daemon", "rep_times_s": times},
            config=dict(rec.get("config") or {}),
            latency=block))
    return out[0], out[1]


# lint: host
def write_latency_entries(out_dir, rec: dict, doc: dict):
    """Write ``recorded.entry.json`` / ``replayed.entry.json`` (one
    v1.4 entry per file, bench-diff operands) into ``out_dir``;
    returns the two paths."""
    from ue22cs343bb1_openmp_assignment_tpu.obs import history
    os.makedirs(str(out_dir), exist_ok=True)
    a, b = latency_entries(rec, doc)
    paths = []
    for name, entry in (("recorded.entry.json", a),
                        ("replayed.entry.json", b)):
        p = os.path.join(str(out_dir), name)
        if os.path.exists(p):
            os.unlink(p)
        history.append(p, entry)
        paths.append(p)
    return paths[0], paths[1]


# lint: host
def main(argv=None) -> int:
    """``cache-sim replay`` entry point."""
    import argparse
    ap = argparse.ArgumentParser(
        prog="cache-sim replay",
        description="replay any captured artifact: a traffic "
                    "recording (original arrival times preserved), an "
                    "SLO-breach incident dir (its breach-window "
                    "slice), a flight-recorder incident, or a repro "
                    "fixture — the artifact kind is auto-detected")
    ap.add_argument("artifact",
                    help="recording .jsonl / record dir, incident "
                         "dir, fixture dir, or repro.json")
    ap.add_argument("--daemon", default=None, metavar="ADDR",
                    help="replay a recording through a RUNNING "
                         "daemon at this address instead of the "
                         "in-proc scheduler (latency is then "
                         "client-observed over the socket)")
    ap.add_argument("--slots", type=int, default=None,
                    help="override the recorded slots-per-bucket")
    ap.add_argument("--chunk", type=int, default=None,
                    help="override the recorded admission chunk")
    ap.add_argument("--max-cycles", type=int, default=None)
    ap.add_argument("--queue-capacity", type=int, default=None)
    ap.add_argument("--wave-s", type=float, default=1e-3,
                    help="virtual seconds per wave for the in-proc "
                         "replay clock (default 1e-3)")
    ap.add_argument("--timeout", type=float, default=300.0,
                    help="--daemon run bound in seconds (default 300)")
    ap.add_argument("--out", default=None, metavar="DIR",
                    help="write the replay doc plus the recorded/"
                         "replayed v1.4 latency entries here (the "
                         "bench-diff --latency operands)")
    ap.add_argument("--json", action="store_true",
                    help="print the full replay doc as JSON")
    ap.add_argument("--slo", default=None,
                    help='latency SLO on the REPLAYED run, e.g. '
                         '"p95=5" (ms); a breach exits '
                         f"{soak_mod.EXIT_SLO_BREACH} and dumps an "
                         "incident dir embedding the breach-window "
                         "recording slice")
    ap.add_argument("--incident-dir", default="replay_incident",
                    help="where an SLO breach dumps its incident "
                         "(default ./replay_incident)")
    ap.add_argument("--burn-slo", default=None, metavar="SPEC",
                    help="multi-window burn-rate SLO on the replayed "
                         'run, e.g. "5ms,objective=0.99,fast=60,'
                         'slow=300,factor=2" (obs.burnrate); an '
                         f"alert exits {soak_mod.EXIT_SLO_BREACH}")
    ap.add_argument("--shrink", action="store_true",
                    help="on an SLO breach, ddmin the recording's JOB "
                         "LIST to a minimal subset that still "
                         "breaches; writes a replayable incident "
                         "fixture to --shrink-out")
    ap.add_argument("--shrink-out", default="replay_shrunk",
                    metavar="DIR",
                    help="where --shrink writes the minimal "
                         "recording + incident doc (default "
                         "./replay_shrunk)")
    ap.add_argument("--cpu", action="store_true",
                    help="force JAX_PLATFORMS=cpu (set before jax "
                         "import)")
    args = ap.parse_args(argv)
    if args.cpu:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    slo = soak_mod.parse_slo(args.slo) if args.slo else None
    burn = None
    if args.burn_slo:
        from ue22cs343bb1_openmp_assignment_tpu.obs import burnrate
        burn = burnrate.monitor_from_spec(args.burn_slo)
    if args.shrink and not slo:
        ap.error("--shrink needs --slo: the shrink predicate is "
                 "'this subset still breaches the SLO on replay'")

    try:
        kind = detect(args.artifact)
    except ValueError as e:
        print(f"replay: {e}")
        return 2
    print(f"replay: {args.artifact} -> {kind}")

    if kind == "fixture":
        return _replay_fixture(args.artifact, args.json)
    if kind == "flight-incident":
        return _replay_flight(args.artifact, args.json)

    # recording, possibly embedded in a soak-incident dir
    rec = recording.load(args.artifact)
    doc = replay_recording(
        rec, daemon=args.daemon, slots=args.slots, chunk=args.chunk,
        max_cycles=args.max_cycles, queue_capacity=args.queue_capacity,
        wave_s=args.wave_s, timeout_s=args.timeout, burn=burn)
    report = None
    if doc["latency"] is not None \
            and doc["recorded_latency"] is not None:
        from ue22cs343bb1_openmp_assignment_tpu.obs import regress
        a, b = latency_entries(rec, doc)
        report = regress.compare_latency(a, b)
        doc["latency_verdict"] = report
    if args.out:
        out = pathlib.Path(args.out)
        out.mkdir(parents=True, exist_ok=True)
        (out / "replay.json").write_text(
            json.dumps(doc, indent=2, sort_keys=True) + "\n")
        write_latency_entries(out, rec, doc)
        print(f"replay: doc + latency entries written to {out}")
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        _print_summary(doc, report)

    if slo:
        breaches = soak_mod.check_slo(doc["latency"], slo)
        if breaches:
            import sys
            soak_mod.dump_incident(args.incident_dir, doc, breaches,
                                   rec=rec)
            for br in breaches:
                print(f"replay: SLO BREACH {br['metric']} "
                      f"{br['observed_ms']:.2f}ms > limit "
                      f"{br['limit_ms']:.2f}ms", file=sys.stderr)
            print(f"replay: incident (with breach-window recording "
                  f"slice) dumped to {args.incident_dir}",
                  file=sys.stderr)
            if args.shrink:
                _shrink_to_fixture(rec, slo, args, doc)
            return soak_mod.EXIT_SLO_BREACH
        if args.shrink:
            print("replay: --shrink skipped (no SLO breach to "
                  "preserve)")
    if burn is not None and burn.breached():
        import sys
        for a in burn.alerts:
            print(f"replay: BURN-RATE ALERT at t={a['t_s']:.3f}s: "
                  f"fast {a['fast_burn']:.1f}x / slow "
                  f"{a['slow_burn']:.1f}x the {a['objective']:.3%} "
                  f"error budget (> {a['threshold_ms']}ms, factor "
                  f"{a['factor']})", file=sys.stderr)
        soak_mod.dump_incident(
            args.incident_dir, doc,
            [{"metric": "burn-rate", **a} for a in burn.alerts],
            rec=rec)
        print(f"replay: incident dumped to {args.incident_dir}",
              file=sys.stderr)
        return soak_mod.EXIT_SLO_BREACH
    if doc["digest_mismatches"]:
        print(f"replay: {len(doc['digest_mismatches'])} job(s) with "
              "DIVERGENT dumps vs the recording")
        return 1
    return 0 if doc["jobs_quiesced"] == doc["jobs_total"] else 1


# lint: host
def _print_summary(doc: dict, report: Optional[dict]) -> None:
    lat = doc["latency"]
    lat_str = ("no jobs completed" if lat is None else
               f"p50={lat['p50_ms']:.2f}ms p95={lat['p95_ms']:.2f}ms "
               f"p99={lat['p99_ms']:.2f}ms")
    print(f"replay[{doc['transport']}]: {doc['jobs_quiesced']}/"
          f"{doc['jobs_total']} jobs quiesced, {lat_str}")
    if doc.get("digests_matched") is not None:
        print(f"replay: dump digests {doc['digests_matched']}/"
              f"{doc['recorded_jobs']} byte-identical to the "
              f"recording, {len(doc['digest_mismatches'])} "
              "mismatched")
    if report is not None:
        from ue22cs343bb1_openmp_assignment_tpu.obs import regress
        print(regress.format_latency_report(report))


# lint: host
def _replay_fixture(path: str, as_json: bool) -> int:
    from ue22cs343bb1_openmp_assignment_tpu.analysis import fixtures
    res = fixtures.replay(path)
    if as_json:
        safe = {k: v for k, v in res.items()
                if isinstance(v, (str, int, float, bool, list, dict,
                                  type(None)))}
        print(json.dumps(safe, indent=2, sort_keys=True, default=str))
    print(f"replay: fixture verdict {res['verdict']!r} "
          f"(expected {res['expected_verdict']!r}) -> "
          f"{'REPRODUCED' if res['reproduced'] else 'NOT reproduced'}")
    return 0 if res["reproduced"] else 1


# lint: host
def _replay_flight(path: str, as_json: bool) -> int:
    from ue22cs343bb1_openmp_assignment_tpu.obs import flight
    inc = flight.load_incident(path)
    try:
        res = flight.replay_incident(path)
    except FileNotFoundError:
        print(f"replay: incident {path} has no repro.json (reason "
              f"{inc['reason']!r}) — its Perfetto trace is the "
              "artifact; nothing to re-execute")
        return 2
    if as_json:
        safe = {k: v for k, v in res.items()
                if isinstance(v, (str, int, float, bool, list, dict,
                                  type(None)))}
        print(json.dumps(safe, indent=2, sort_keys=True, default=str))
    verdict = res.get("verdict")
    reproduced = verdict != "pass"
    print(f"replay: flight incident (reason {inc['reason']!r}) fresh "
          f"verdict {verdict!r} -> "
          f"{'REPRODUCED' if reproduced else 'NOT reproduced'}")
    return 0 if reproduced else 1


# lint: host
def _shrink_to_fixture(rec: dict, slo, args, full_doc: dict) -> None:
    from ue22cs343bb1_openmp_assignment_tpu.analysis import shrink
    try:
        shrunk, n_tests = shrink.shrink_recording(
            rec, lambda sub: _breaches(sub, slo, args))
    except ValueError as e:
        # possible when the breach was observed through --daemon but
        # the in-proc predicate replay stays under the bound
        print(f"replay: shrink aborted: {e}")
        return
    jobs = sorted({row["job"] for row in shrunk["rows"]
                   if row["event"] == "submit"})
    print(f"replay: shrink converged to {len(jobs)} job(s) in "
          f"{n_tests} replays: {', '.join(jobs)}")
    doc = _replay_for_slo(shrunk, args)
    breaches = soak_mod.check_slo(doc["latency"], slo)
    soak_mod.dump_incident(args.shrink_out, doc, breaches, rec=shrunk)
    print(f"replay: minimal incident fixture written to "
          f"{args.shrink_out} (replay it with `cache-sim replay "
          f"{args.shrink_out}`)")


# lint: host
def _replay_for_slo(sub_rec: dict, args) -> dict:
    return replay_recording(
        sub_rec, slots=args.slots, chunk=args.chunk,
        max_cycles=args.max_cycles,
        queue_capacity=args.queue_capacity, wave_s=args.wave_s)


# lint: host
def _breaches(sub_rec: dict, slo, args) -> bool:
    if not any(row["event"] == "submit" for row in sub_rec["rows"]):
        return False
    doc = _replay_for_slo(sub_rec, args)
    return bool(soak_mod.check_slo(doc["latency"], slo))


if __name__ == "__main__":
    raise SystemExit(main())
