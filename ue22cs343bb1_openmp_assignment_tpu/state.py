"""Simulation state as a pytree of ``[num_nodes, ...]`` device arrays.

The reference keeps one ``processorNode`` struct per OpenMP thread
(``assignment.c:89-95``) plus global locked message rings
(``assignment.c:81-105``). Here the entire machine is one pytree:

* axis 0 of every array is the simulated-node axis — this is the axis
  that is vectorized on one chip and sharded across a device mesh,
* the mailbox is a per-node circular ring exactly like the reference's
  ``messageBuffer`` (head/count, capacity ``cfg.queue_capacity``), but as
  a padded tensor written by a vectorized scatter instead of locks,
* the sharer bitvector is tiled into uint32 words (``cfg.bitvec_words``)
  instead of the reference's single byte (``assignment.c:63``) so the
  directory scales past 8 nodes to tens of thousands.

All fields use int32/uint32: TPU-friendly, and every protocol quantity
(byte values, nibble addresses, states) embeds losslessly.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import struct

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.types import CacheState, DirState, Msg, Op


# Fixed bucket count of the miss-latency histogram (obs layer): bucket b
# counts coherence waits whose issue->retire latency in cycles falls in
# [2^b, 2^(b+1)); the last bucket absorbs everything beyond. Static so
# jit graphs stay shape-static regardless of run length.
LAT_BUCKETS = 16


class Metrics(struct.PyTreeNode):
    """Device-side counters, reduced across nodes (SURVEY §5 observability)."""

    cycles: jnp.ndarray          # [] i32 — cycles executed
    instrs_retired: jnp.ndarray  # [] i32 — instructions completed (hit or fill)
    read_hits: jnp.ndarray       # [] i32
    write_hits: jnp.ndarray      # [] i32
    read_misses: jnp.ndarray     # [] i32
    write_misses: jnp.ndarray    # [] i32
    upgrades: jnp.ndarray        # [] i32 — S write-hits (UPGRADE sent)
    msgs_processed: jnp.ndarray  # [13] i32 — dequeues by transaction type
    msgs_dropped: jnp.ndarray    # [] i32 — ring-overflow drops (quirk 6)
    msgs_injected_dropped: jnp.ndarray  # [] i32 — cfg.drop_prob faults
    invalidations: jnp.ndarray   # [] i32 — INV applications that hit a line
    evictions: jnp.ndarray       # [] i32 — EVICT_* notices sent
    # miss-latency histogram: issue->retire wait lengths in cycles,
    # power-of-two buckets (see LAT_BUCKETS); accumulated on device so
    # the measurement never leaves the jit graph
    lat_hist: jnp.ndarray        # [LAT_BUCKETS] i32
    # mailbox queue-depth high watermark over the whole run (the early
    # overflow-pressure signal behind the silent-drop quirk 6)
    mb_depth_peak: jnp.ndarray   # [] i32

    @classmethod
    def zeros(cls) -> "Metrics":
        z = jnp.zeros((), jnp.int32)
        return cls(cycles=z, instrs_retired=z, read_hits=z, write_hits=z,
                   read_misses=z, write_misses=z, upgrades=z,
                   msgs_processed=jnp.zeros((13,), jnp.int32),
                   msgs_dropped=z, msgs_injected_dropped=z,
                   invalidations=z, evictions=z,
                   lat_hist=jnp.zeros((LAT_BUCKETS,), jnp.int32),
                   mb_depth_peak=z)


# mb_pack column layout
MB_TYPE, MB_SENDER, MB_ADDR, MB_VALUE, MB_SECOND, MB_DIRSTATE, MB_BV0 = (
    0, 1, 2, 3, 4, 5, 6)


class SimState(struct.PyTreeNode):
    """Full machine state. Shapes: N nodes, C cache lines, M memory blocks,
    T max trace length, Q mailbox capacity, W bitvector words."""

    # -- per-node cache (reference cacheLine[], assignment.c:56-60,90) ----
    cache_addr: jnp.ndarray    # [N, C] i32, cfg.invalid_address when empty
    cache_val: jnp.ndarray     # [N, C] i32 (byte-valued)
    cache_state: jnp.ndarray   # [N, C] i32, CacheState

    # -- per-node home memory + directory (assignment.c:62-66,91-92) ------
    memory: jnp.ndarray        # [N, M] i32 (byte-valued)
    dir_state: jnp.ndarray     # [N, M] i32, DirState
    dir_bitvec: jnp.ndarray    # [N, M, W] u32 sharer bits (bit g of word
                               #   g//32 = node g caches this block)

    # -- per-node instruction trace (assignment.c:50-54,93-94) ------------
    instr_op: jnp.ndarray      # [N, T] i32, Op
    instr_addr: jnp.ndarray    # [N, T] i32
    instr_val: jnp.ndarray     # [N, T] i32
    instr_count: jnp.ndarray   # [N] i32
    instr_idx: jnp.ndarray     # [N] i32, last fetched (init -1, assignment.c:160)

    # latched in-flight instruction — the reference's thread-local `instr`
    # (assignment.c:159,647); handlers read it for fill values (quirk 1).
    cur_op: jnp.ndarray        # [N] i32
    cur_addr: jnp.ndarray      # [N] i32
    cur_val: jnp.ndarray       # [N] i32
    waiting: jnp.ndarray       # [N] bool — waitingForReply (assignment.c:162)
    # cycle at which `waiting` was last set (-1 when not waiting) — the
    # stall watchdog's input (ops.failures; reference has no failure
    # detection, SURVEY §5: a node stranded by a dropped reply just
    # spins forever, assignment.c:624-629)
    waiting_since: jnp.ndarray # [N] i32

    # -- mailboxes (reference messageBuffer, assignment.c:81-87) ----------
    # one packed ring tensor: planes MB_TYPE..MB_DIRSTATE then
    # cfg.msg_bitvec_words bitvector words (u32 bitcast to i32) — a
    # message is one [6 + Wm] fiber, so dequeue is ONE gather and
    # delivery ONE scatter regardless of field count. PLANE-MAJOR
    # layout ([P, N, Q], not [N, Q, P]): the minor dims are the large,
    # well-tiling (node, slot) plane, so the per-cycle delivery scatter
    # updates the ring in place instead of forcing a relayout copy of
    # the whole tensor every cycle (PERF.md, async cycle decomposition)
    mb_pack: jnp.ndarray       # [6 + Wm, N, Q] i32
    mb_head: jnp.ndarray       # [N] i32
    mb_count: jnp.ndarray      # [N] i32

    # -- schedule / arbitration knobs (replaces OS nondeterminism) --------
    # A node issues instructions only when cycle >= delay and
    # (cycle - delay) % period == 0. Message processing is never gated.
    # These realize alternative interleavings for the racy suites
    # (test_3/test_4) as a searchable parameter instead of wall-clock
    # retries (SURVEY §4).
    issue_delay: jnp.ndarray   # [N] i32
    issue_period: jnp.ndarray  # [N] i32 (>= 1)
    # Cross-sender arbitration rank: when several nodes' messages hit one
    # receiver in a cycle, lower-rank senders enqueue first — the
    # deterministic, seedable stand-in for the reference's OS
    # lock-acquisition order (quirk source for test_3/test_4).
    arb_rank: jnp.ndarray      # [N] i32 permutation of node ids
    # Interleaving replay (utils.order_replay): global issue rank of each
    # instruction, parsed from a recorded ``instruction_order.txt``
    # (``assignment.c:649-652``). Instruction i of node n may issue only
    # when exactly order_rank[n, i] instructions have issued machine-wide
    # — exactly one fetch per cycle, reproducing the recorded global
    # interleaving. Zero-width ([N, 0]) = replay disabled (the default).
    order_rank: jnp.ndarray    # [N, T] i32 (or [N, 0] when unused)

    # PRNG state for fault injection (cfg.drop_prob); split each cycle
    # inside delivery so drop patterns are reproducible from the seed.
    fault_key: jnp.ndarray     # [2] u32

    cycle: jnp.ndarray         # [] i32
    metrics: Metrics

    # ------------------------------------------------------------------
    @property
    def num_nodes(self) -> int:
        return self.cache_addr.shape[0]

    def quiescent(self) -> jnp.ndarray:
        """True when no message is queued, no node blocked, traces done.

        Replaces the reference's never-terminating spin + external SIGINT
        (``assignment.c:639-645``, ``test3.sh:11``) with a clean fixpoint:
        at quiescence the state equals the reference's final re-armed dump
        (``assignment.c:171-173,635-638``).
        """
        exhausted = self.instr_idx >= self.instr_count - 1
        return (jnp.all(self.mb_count == 0) & jnp.all(~self.waiting)
                & jnp.all(exhausted))


def init_state(cfg: SystemConfig, traces=None, issue_delay=None,
               issue_period=None, instr_arrays=None,
               arb_rank=None, fault_seed: int = 0,
               order_rank=None) -> SimState:
    """Build the initial machine state.

    Mirrors ``initializeProcessor`` (``assignment.c:806-851``): memory
    block *i* of node *t* starts at ``(20*t + i) & 0xFF``, directory
    entries start Unowned with empty bitvectors, cache lines start INVALID
    with the sentinel address.

    ``traces``: optional list (len <= N) of per-node instruction lists
    ``[(op, addr, value), ...]`` (see utils.trace for file loading).
    ``instr_arrays``: optional pre-built device arrays
    ``(op [N,T], addr [N,T], val [N,T], count [N])`` from a workload
    generator (models.workloads) — takes precedence over ``traces``.
    """
    N, C, M = cfg.num_nodes, cfg.cache_size, cfg.mem_size
    T, Q, W = cfg.max_instrs, cfg.queue_capacity, cfg.bitvec_words
    Wm = cfg.msg_bitvec_words

    node_ids = jnp.arange(N, dtype=jnp.int32)
    mem_init = (20 * node_ids[:, None]
                + jnp.arange(M, dtype=jnp.int32)[None, :]) & 0xFF

    instr_op, instr_addr, instr_val, instr_count = build_instr_arrays(
        cfg, traces=traces, instr_arrays=instr_arrays)

    if issue_delay is None:
        issue_delay = jnp.zeros((N,), jnp.int32)
    if issue_period is None:
        issue_period = jnp.ones((N,), jnp.int32)
    if arb_rank is None:
        arb_rank = jnp.arange(N, dtype=jnp.int32)

    return SimState(
        cache_addr=jnp.full((N, C), cfg.invalid_address, jnp.int32),
        cache_val=jnp.zeros((N, C), jnp.int32),
        cache_state=jnp.full((N, C), int(CacheState.INVALID), jnp.int32),
        memory=mem_init,
        dir_state=jnp.full((N, M), int(DirState.U), jnp.int32),
        dir_bitvec=jnp.zeros((N, M, W), jnp.uint32),
        instr_op=instr_op, instr_addr=instr_addr, instr_val=instr_val,
        instr_count=instr_count,
        instr_idx=jnp.full((N,), -1, jnp.int32),
        cur_op=jnp.zeros((N,), jnp.int32),
        cur_addr=jnp.zeros((N,), jnp.int32),
        cur_val=jnp.zeros((N,), jnp.int32),
        waiting=jnp.zeros((N,), bool),
        waiting_since=jnp.full((N,), -1, jnp.int32),
        mb_pack=jnp.zeros((6 + Wm, N, Q), jnp.int32).at[MB_TYPE].set(
            int(Msg.NONE)),
        mb_head=jnp.zeros((N,), jnp.int32),
        mb_count=jnp.zeros((N,), jnp.int32),
        issue_delay=jnp.asarray(issue_delay, jnp.int32),
        issue_period=jnp.asarray(issue_period, jnp.int32),
        arb_rank=jnp.asarray(arb_rank, jnp.int32),
        order_rank=(jnp.zeros((N, 0), jnp.int32) if order_rank is None
                    else jnp.asarray(order_rank, jnp.int32)),
        fault_key=fault_key_from_seed(fault_seed),
        cycle=jnp.zeros((), jnp.int32),
        metrics=Metrics.zeros(),
    )


def build_instr_arrays(cfg: SystemConfig, traces=None, instr_arrays=None):
    """(op, addr, val, count) [N, T] arrays from traces / prebuilt arrays.

    The single trace-to-arrays path shared by init_state and the
    streaming continue_with_traces of both engines."""
    N, T = cfg.num_nodes, cfg.max_instrs
    if instr_arrays is not None:
        instr_op, instr_addr, instr_val, instr_count = (
            jnp.asarray(a, jnp.int32) for a in instr_arrays)
        if instr_op.shape[1] != T:
            raise ValueError(
                f"instr_arrays trace length {instr_op.shape[1]} != "
                f"cfg.max_instrs {T}")
        return instr_op, instr_addr, instr_val, instr_count
    if traces is not None:
        import numpy as np
        op_h = np.full((N, T), int(Op.NOP), np.int32)
        ad_h = np.zeros((N, T), np.int32)
        va_h = np.zeros((N, T), np.int32)
        cnt_h = np.zeros((N,), np.int32)
        for n, tr in enumerate(traces):
            tr = tr[:T]
            cnt_h[n] = len(tr)
            for i, (op, addr, val) in enumerate(tr):
                op_h[n, i] = int(op)
                ad_h[n, i] = int(addr)
                va_h[n, i] = int(val) & 0xFF
        return (jnp.asarray(op_h), jnp.asarray(ad_h), jnp.asarray(va_h),
                jnp.asarray(cnt_h))
    return (jnp.full((N, T), int(Op.NOP), jnp.int32),
            jnp.zeros((N, T), jnp.int32), jnp.zeros((N, T), jnp.int32),
            jnp.zeros((N,), jnp.int32))


def continue_with_traces(cfg: SystemConfig, state: SimState, traces=None,
                         instr_arrays=None) -> SimState:
    """Stream the next trace phase into a quiescent machine.

    The reference caps every run at 32 instructions per node
    (``assignment.c:10``); here arbitrarily long workloads run in
    bounded memory by chaining phases: run to quiescence, swap in the
    next ``max_instrs``-sized chunk, continue. Caches, memories,
    directories and metrics persist; only the instruction stream resets.

    Because the machine is quiescent between phases, every chained
    schedule is a legal schedule of the concatenated trace (all phase-k
    messages drain before any phase-k+1 instruction issues), so on
    schedule-independent workloads the final state is byte-identical to
    one long run (tests/test_streaming.py).

    Raises ValueError if the machine is not quiescent (in-flight
    messages or blocked nodes would interleave with the new phase).
    """
    if not bool(state.quiescent()):
        raise ValueError(
            "continue_with_traces needs a quiescent machine: messages "
            "in flight or nodes blocked (run to quiescence first)")
    op, addr, val, count = build_instr_arrays(
        cfg, traces=traces, instr_arrays=instr_arrays)
    return state.replace(
        instr_op=op, instr_addr=addr, instr_val=val, instr_count=count,
        instr_idx=jnp.full((cfg.num_nodes,), -1, jnp.int32))


def fault_key_from_seed(seed: int) -> jnp.ndarray:
    import jax
    return jax.random.key_data(jax.random.PRNGKey(seed)).astype(jnp.uint32)


# -- batching: a leading job axis over the whole machine -------------------
#
# Every SimState leaf keys its minor axes off the node axis (axis 0), so
# the full machine state — caches, directory, traces, mailboxes, PRNG
# keys, metrics — batches uniformly under ONE extra leading axis: a
# [B, ...] pytree of B independent machines. ops.step vmaps the cycle
# over this axis (the serving layer's wave runner); the helpers below
# are the only sanctioned way in and out of the batch so slot packing
# stays a tree-level concern, invisible to the engine.

_stack_states_jit = None


def stack_states(states) -> SimState:
    """Stack per-job SimStates (identical shapes) into one batched
    pytree with a leading job axis: leaf [..] -> [B, ..].

    Jitted (one program per batch size + shape): a whole-machine state
    is ~39 leaves, and eager per-leaf stacks cost more than the wave
    they feed at small node counts."""
    import jax
    global _stack_states_jit
    if _stack_states_jit is None:
        _stack_states_jit = jax.jit(lambda ss: jax.tree.map(
            lambda *xs: jnp.stack(xs, axis=0), *ss))
    return _stack_states_jit(tuple(states))


def index_state(bstate: SimState, i) -> SimState:
    """Slice job `i` back out of a batched state (inverse of
    stack_states up to device placement)."""
    import jax
    return jax.tree.map(lambda x: x[i], bstate)


_set_state_jit = None


def set_state(bstate: SimState, i, state: SimState) -> SimState:
    """Return the batched state with slot `i` replaced by `state` —
    the between-waves admission primitive of the serving layer.

    Jitted with the slot index traced: one compiled program per batch
    shape covers every slot, and the whole 39-leaf update is a single
    dispatch instead of one eager scatter per leaf (which dominated a
    serve pass before)."""
    import jax
    global _set_state_jit
    if _set_state_jit is None:
        _set_state_jit = jax.jit(
            lambda b, s, j: jax.tree.map(
                lambda bb, ss: bb.at[j].set(ss), b, s))
    return _set_state_jit(bstate, state, jnp.asarray(i, jnp.int32))


def batch_size(bstate: SimState) -> int:
    return bstate.cache_addr.shape[0]


def batch_quiescent(bstate: SimState) -> jnp.ndarray:
    """Per-job quiescence mask [B] of a batched state."""
    import jax
    return jax.vmap(lambda s: s.quiescent())(bstate)


# -- bitvector helpers (tiled uint32 words; reference used one byte) ------

def bit_get(bv: jnp.ndarray, node) -> jnp.ndarray:
    """bv[..., W] -> bool: is `node`'s bit set (vectorized over leading dims)."""
    word = node // 32
    off = node % 32
    w = jnp.take_along_axis(bv, word[..., None].astype(jnp.int32),
                            axis=-1)[..., 0]
    return ((w >> off.astype(jnp.uint32)) & 1).astype(bool)


def bit_set(bv: jnp.ndarray, node, on=True) -> jnp.ndarray:
    """Return bv with `node`'s bit set/cleared."""
    W = bv.shape[-1]
    words = jnp.arange(W, dtype=jnp.int32)
    mask = (words == (node[..., None] // 32)).astype(jnp.uint32)
    bit = mask << jnp.asarray(node[..., None] % 32, jnp.uint32)
    if on:
        return bv | bit
    return bv & ~bit


def bit_single(num_words: int, node) -> jnp.ndarray:
    """A bitvector with exactly `node`'s bit set; node: [...] -> [..., W]."""
    words = jnp.arange(num_words, dtype=jnp.int32)
    mask = (words == (node[..., None] // 32)).astype(jnp.uint32)
    return mask << jnp.asarray(node[..., None] % 32, jnp.uint32)


def popcount(bv: jnp.ndarray) -> jnp.ndarray:
    """Number of set bits; bv [..., W] -> [...] i32 (assignment.c:564)."""
    return jnp.sum(jax_popcount32(bv), axis=-1).astype(jnp.int32)


def jax_popcount32(x: jnp.ndarray) -> jnp.ndarray:
    import jax
    return jax.lax.population_count(x).astype(jnp.int32)


def ctz(bv: jnp.ndarray) -> jnp.ndarray:
    """Index of lowest set bit (assignment.c:209 __builtin_ctz); bv [..., W].

    Returns num_bits if empty (caller must mask)."""
    import jax
    W = bv.shape[-1]
    tz = jax.lax.clz(bv & (~bv + jnp.uint32(1)))  # clz of isolated low bit
    word_ctz = jnp.where(bv == 0, 32, 31 - tz.astype(jnp.int32))
    base = jnp.arange(W, dtype=jnp.int32) * 32
    cand = jnp.where(bv == 0, jnp.int32(32 * W), base + word_ctz)
    return jnp.min(cand, axis=-1)
