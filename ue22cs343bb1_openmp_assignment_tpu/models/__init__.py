"""Model-level APIs: the two high-level machine wrappers.

CoherenceSystem  — message-level engine (byte-parity / research path)
TransactionalSystem — atomic-round engine (throughput / ensemble path)
"""

from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
from ue22cs343bb1_openmp_assignment_tpu.models.transactional import (
    TransactionalSystem)

__all__ = ["CoherenceSystem", "TransactionalSystem"]
