"""CoherenceSystem — the flagship model: a full DASH/MESI directory
machine as one object.

This is the user-facing equivalent of the reference program as a whole
(``./cache_simulator <test_dir>``): load traces, run to quiescence, dump
golden state — plus the capabilities the reference lacks: synthetic
workloads, schedule control, metrics, checkpointing, arbitrary scale.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models import workloads
from ue22cs343bb1_openmp_assignment_tpu.ops.step import (cycle, run_cycles,
                                                         run_to_quiescence)
from ue22cs343bb1_openmp_assignment_tpu.state import SimState, init_state
from ue22cs343bb1_openmp_assignment_tpu.utils import checkpoint, golden, trace


@dataclasses.dataclass
class CoherenceSystem:
    """A configured coherence machine with its current state."""

    cfg: SystemConfig
    state: SimState

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_test_dir(cls, test_dir: str, cfg: Optional[SystemConfig] = None,
                      **init_kw) -> "CoherenceSystem":
        """Load reference-format core_<n>.txt traces (assignment.c:806-851)."""
        cfg = cfg or SystemConfig.reference()
        traces = trace.load_test_dir(test_dir, cfg.num_nodes, cfg.max_instrs)
        return cls(cfg, init_state(cfg, traces, **init_kw))

    @classmethod
    def from_workload(cls, cfg: SystemConfig, name: str = "uniform",
                      trace_len: Optional[int] = None, seed: int = 0,
                      init_kw: Optional[dict] = None,
                      **gen_kw) -> "CoherenceSystem":
        """Build from a synthetic workload generator (models.workloads).

        init_kw: forwarded to state.init_state (schedule knobs:
        issue_delay / issue_period / arb_rank).
        """
        trace_len = trace_len or cfg.max_instrs
        if trace_len != cfg.max_instrs:
            cfg = dataclasses.replace(cfg, max_instrs=trace_len)
        arrays = workloads.GENERATORS[name](
            jax.random.PRNGKey(seed), cfg, trace_len, **gen_kw)
        return cls(cfg, init_state(cfg, instr_arrays=arrays,
                                   **(init_kw or {})))

    @classmethod
    def from_traces(cls, cfg: SystemConfig,
                    traces: Sequence[Sequence[trace.Instr]],
                    **init_kw) -> "CoherenceSystem":
        return cls(cfg, init_state(cfg, list(traces), **init_kw))

    # -- execution ---------------------------------------------------------
    def step(self) -> "CoherenceSystem":
        """Advance one cycle (unjitted; for debugging/inspection)."""
        return dataclasses.replace(self, state=cycle(self.cfg, self.state))

    def run(self, max_cycles: int = 100_000) -> "CoherenceSystem":
        """Run to quiescence — the fixpoint replacing the reference's
        spin-forever + SIGINT termination model."""
        final = run_to_quiescence(self.cfg, self.state, max_cycles)
        return dataclasses.replace(self, state=final)

    def run_cycles(self, n: int) -> "CoherenceSystem":
        return dataclasses.replace(self, state=run_cycles(self.cfg,
                                                          self.state, n))

    def run_cycles_traced(self, n: int):
        """run_cycles + the structured event record; returns
        (system, events) with events a dict of [n, N] host arrays
        (host-side driver: events land in numpy by design)."""
        import numpy as np

        from ue22cs343bb1_openmp_assignment_tpu.ops import step
        state, ev = step.run_cycles_traced(self.cfg, self.state, n)
        return (dataclasses.replace(self, state=state),
                {k: np.asarray(v) for k, v in ev.items()})

    def run_traced(self, max_cycles: int = 100_000, chunk: int = 64):
        """Run to quiescence collecting the structured event log
        (host-side driver: chunked dispatch, events land in numpy).

        Returns (system, events) where events is a dict of
        [cycles, N] host arrays (see ops.step.run_cycles_traced /
        utils.eventlog) — the reference's -DDEBUG_INSTR/-DDEBUG_MSG
        tracing as data instead of interleaved printf. Event rows are
        relative to the starting cycle: pass
        ``base_cycle=int(state.cycle)`` (captured before the run) to
        utils.eventlog for absolute cycle numbers.

        ``max_cycles`` is an absolute cap on ``state.cycle``, matching
        run(); the final chunk is trimmed so the cap is exact. Like
        run_chunked_to_quiescence, the run may overshoot *quiescence*
        by up to chunk-1 cycles — a quiescent state is a fixpoint, so
        only the cycle counters advance and the overshoot cycles
        contribute no events.
        """
        import numpy as np

        from ue22cs343bb1_openmp_assignment_tpu.ops import step
        state = self.state
        chunks = []
        while (not bool(state.quiescent())
               and int(state.cycle) < max_cycles):
            n = min(chunk, max_cycles - int(state.cycle))
            state, ev = step.run_cycles_traced(self.cfg, state, n)
            chunks.append({k: np.asarray(v) for k, v in ev.items()})
        events = ({k: np.concatenate([c[k] for c in chunks])
                   for k in chunks[0]} if chunks else {})
        return dataclasses.replace(self, state=state), events

    # -- observability -----------------------------------------------------
    @property
    def quiescent(self) -> bool:
        return bool(self.state.quiescent())

    @property
    def metrics(self) -> dict:
        m = self.state.metrics
        out = {f.name: jax.device_get(getattr(m, f.name))
               for f in m.__dataclass_fields__.values()}
        return {k: (v.tolist() if hasattr(v, "tolist") else v)
                for k, v in out.items()}

    def dumps(self) -> List[str]:
        """Per-node golden dumps (printProcessorState byte-parity)."""
        return [golden.format_node_dump(d)
                for d in golden.state_to_dumps(self.cfg, self.state)]

    def write_dumps(self, out_dir: str) -> List[str]:
        return golden.write_dumps(self.cfg, self.state, out_dir)

    @property
    def instrs_retired(self) -> int:
        return int(self.state.metrics.instrs_retired)

    # -- failure detection (SURVEY §5: reference has none) ----------------
    def stall_report(self, threshold: int = 100) -> dict:
        """Stall-watchdog report ({"count", "nodes"}): nodes blocked on
        one outstanding request for more than `threshold` cycles (e.g.
        stranded by a dropped reply — injectable via cfg.drop_prob).
        count == 0 means healthy. One device evaluation."""
        from ue22cs343bb1_openmp_assignment_tpu.ops import failures
        return failures.stall_report(self.cfg, self.state, threshold)

    def stalled(self, threshold: int = 100) -> List[dict]:
        """Truncated node list form of :meth:`stall_report`."""
        return self.stall_report(threshold)["nodes"]

    # -- invariant checking (SURVEY §5: the TPU-way -DDEBUG build) --------
    def check_invariants(self, strict_coherence: bool = True) -> dict:
        """Engine-integrity invariants always assert; the cross-node
        coherence tier asserts when ``strict_coherence`` (correct for
        race-free schedules) and is returned as a report otherwise
        (racy workloads can legally leave stale copies — the protocol
        tracks no INV-acks, assignment.c:358-361; see ops.invariants).

        Returns the coherence-tier counts when quiescent, else {}.
        """
        from ue22cs343bb1_openmp_assignment_tpu.ops import invariants
        invariants.assert_invariants(self.cfg, self.state, quiescent=False)
        if not self.quiescent:
            return {}
        report = invariants.coherence_report(self.cfg, self.state)
        if strict_coherence and any(report.values()):
            raise AssertionError(
                f"coherence invariants violated: "
                f"{ {k: v for k, v in report.items() if v} }")
        return report

    def run_checked(self, num_cycles: int) -> "CoherenceSystem":
        """Advance with per-cycle invariant accumulation; raises on any
        violation (one device dispatch for the whole scan)."""
        from ue22cs343bb1_openmp_assignment_tpu.ops import invariants
        state, acc = invariants.run_cycles_checked(self.cfg, self.state,
                                                   num_cycles)
        bad = {k: int(v) for k, v in acc.items() if int(v)}
        if bad:
            raise AssertionError(
                f"protocol invariants violated during run: {bad}")
        return dataclasses.replace(self, state=state)

    # -- persistence (SURVEY §5: reference has none) ----------------------
    def save(self, path: str, meta: Optional[dict] = None) -> None:
        """Checkpoint the whole machine at the current cycle boundary."""
        checkpoint.save_checkpoint(path, self.cfg, self.state, meta)

    @classmethod
    def load(cls, path: str) -> "CoherenceSystem":
        """Resume from a checkpoint; bit-exact continuation."""
        cfg, state, meta = checkpoint.load_checkpoint(path)
        if meta.get("kind", "sim") != "sim":
            raise ValueError(
                f"{path} holds a SyncState (transactional engine) "
                "checkpoint; load it with ops.sync_engine / "
                "--engine sync")
        return cls(cfg, state)
