"""Synthetic workload (memory-reference trace) generators.

The reference ships only hand-written fixture traces up to 68
instructions (``tests/``, SURVEY §6). These generators produce the
benchmark workloads from BASELINE.json's scaling ladder — uniform-random
RD/WR, producer-consumer, and false-sharing stress — directly as
``[num_nodes, trace_len]`` device arrays, on device, from a PRNG key.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ue22cs343bb1_openmp_assignment_tpu import codec
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.types import Op


def uniform_random(key, cfg: SystemConfig, trace_len: int,
                   local_frac: float = 0.8, write_frac: float = 0.5):
    """Uniform-random RD/WR mix; `local_frac` of accesses hit the node's
    own home memory, the rest a uniformly random remote node.

    Returns (instr_op, instr_addr, instr_val, instr_count) arrays.
    """
    N = cfg.num_nodes
    k1, k2, k3, k4, k5 = jax.random.split(key, 5)
    shape = (N, trace_len)
    is_write = jax.random.uniform(k1, shape) < write_frac
    op = jnp.where(is_write, int(Op.WRITE), int(Op.READ)).astype(jnp.int32)

    own = jnp.broadcast_to(jnp.arange(N, dtype=jnp.int32)[:, None], shape)
    remote = jax.random.randint(k2, shape, 0, N, dtype=jnp.int32)
    node = jnp.where(jax.random.uniform(k3, shape) < local_frac, own, remote)
    block = jax.random.randint(k4, shape, 0, cfg.mem_size, dtype=jnp.int32)
    addr = codec.make_address(cfg, node, block)
    val = jax.random.randint(k5, shape, 0, 256, dtype=jnp.int32)
    count = jnp.full((N,), trace_len, jnp.int32)
    return op, addr, val, count


def producer_consumer(key, cfg: SystemConfig, trace_len: int,
                      num_slots: int = 4):
    """Odd nodes write into even neighbors' memory; even nodes read their
    own blocks back — a ping-pong ownership-transfer stress."""
    N = cfg.num_nodes
    k1, k2 = jax.random.split(key)
    shape = (N, trace_len)
    ids = jnp.arange(N, dtype=jnp.int32)[:, None]
    is_producer = (ids % 2) == 1
    partner = jnp.where(is_producer, ids - 1, ids)  # producers target left
    block = jax.random.randint(k1, shape, 0, num_slots, dtype=jnp.int32)
    addr = codec.make_address(cfg, jnp.broadcast_to(partner, shape), block)
    op = jnp.where(jnp.broadcast_to(is_producer, shape),
                   int(Op.WRITE), int(Op.READ)).astype(jnp.int32)
    val = jax.random.randint(k2, shape, 0, 256, dtype=jnp.int32)
    return op, addr, val, jnp.full((N,), trace_len, jnp.int32)


def false_sharing(key, cfg: SystemConfig, trace_len: int,
                  num_hot_blocks: int = 2):
    """Every node hammers the same few blocks of node 0 — maximal
    invalidation / ownership churn (BASELINE.json 65536-core stress)."""
    N = cfg.num_nodes
    k1, k2, k3 = jax.random.split(key, 3)
    shape = (N, trace_len)
    block = jax.random.randint(k1, shape, 0, num_hot_blocks, dtype=jnp.int32)
    addr = codec.make_address(cfg, jnp.zeros(shape, jnp.int32), block)
    is_write = jax.random.uniform(k2, shape) < 0.5
    op = jnp.where(is_write, int(Op.WRITE), int(Op.READ)).astype(jnp.int32)
    val = jax.random.randint(k3, shape, 0, 256, dtype=jnp.int32)
    return op, addr, val, jnp.full((N,), trace_len, jnp.int32)


def false_sharing_vars(key, cfg: SystemConfig, trace_len: int,
                       vars_per_block: int = 4, padded: bool = False,
                       write_frac: float = 0.75):
    """Per-node private variables that collide on a coherence unit.

    The textbook false-sharing shape: node ``n``'s variable belongs to
    group ``n // vars_per_block``, and every node in a group touches the
    *same* block (``group % mem_size`` homed at ``group % N``) — the
    variables are logically disjoint, but the block is the coherence
    unit, so each node's write-mostly stream (``write_frac`` writes)
    invalidates its groupmates anyway. ``padded=True`` is the classic
    cache-line-padding fix: every node's variable moves to its own home
    node's memory, so footprints are provably disjoint across nodes and
    the coherence tier (ops/invariants.py) must be exactly zero — the
    padded/unpadded pair is a before/after benchmark of the same
    logical program.
    """
    N = cfg.num_nodes
    k1, k2 = jax.random.split(key)
    shape = (N, trace_len)
    ids = jnp.arange(N, dtype=jnp.int32)[:, None]
    if padded:
        node = ids                       # own home: disjoint by node
        block = ids % cfg.mem_size
    else:
        group = ids // vars_per_block    # groupmates share one block
        node = group % N
        block = group % cfg.mem_size
    addr = codec.make_address(cfg, jnp.broadcast_to(node, shape),
                              jnp.broadcast_to(block, shape))
    is_write = jax.random.uniform(k1, shape) < write_frac
    op = jnp.where(is_write, int(Op.WRITE), int(Op.READ)).astype(jnp.int32)
    val = jax.random.randint(k2, shape, 0, 256, dtype=jnp.int32)
    return op, addr, val, jnp.full((N,), trace_len, jnp.int32)


def fft_transpose(key, cfg: SystemConfig, trace_len: int):
    """SPLASH-2 FFT-style butterfly/transpose reference pattern
    (BASELINE.json "4096-core tiled directory" config).

    The FFT kernel's communication is staged all-to-all: in stage s,
    thread i exchanges with partner i XOR 2^s — it reads rows homed at
    the partner and writes its own. Emulated per instruction slot t:
    stage = t // 2 (mod log2 N), even t reads a partner block, odd t
    writes a local block — a deterministic, strided cross-node pattern
    with no write races (each node writes only its own home blocks).
    """
    N = cfg.num_nodes
    k1, k2 = jax.random.split(key)
    shape = (N, trace_len)
    stages = max(1, (N - 1).bit_length())
    ids = jnp.arange(N, dtype=jnp.int32)[:, None]
    t = jnp.arange(trace_len, dtype=jnp.int32)[None, :]
    stage = (t // 2) % stages
    partner = (ids ^ (1 << stage)) % N
    is_write = (t % 2) == 1
    node = jnp.where(is_write, ids, partner)
    block = jax.random.randint(k1, shape, 0, cfg.mem_size, dtype=jnp.int32)
    addr = codec.make_address(cfg, jnp.broadcast_to(node, shape), block)
    op = jnp.broadcast_to(
        jnp.where(is_write, int(Op.WRITE), int(Op.READ)),
        shape).astype(jnp.int32)
    val = jax.random.randint(k2, shape, 0, 256, dtype=jnp.int32)
    return op, addr, val, jnp.full((N,), trace_len, jnp.int32)


def radix_sort(key, cfg: SystemConfig, trace_len: int, radix: int = 16):
    """SPLASH-2 radix-sort-style pattern: local histogram reads followed
    by a permutation phase that scatters writes to the node owning each
    key's digit bucket (key-dependent all-to-all with write contention —
    the racy counterpart to fft_transpose).
    """
    N = cfg.num_nodes
    k1, k2, k3 = jax.random.split(key, 3)
    shape = (N, trace_len)
    ids = jnp.arange(N, dtype=jnp.int32)[:, None]
    t = jnp.arange(trace_len, dtype=jnp.int32)[None, :]
    # first half: local histogram builds (reads of own memory)
    hist_phase = t < (trace_len // 2)
    digit = jax.random.randint(k1, shape, 0, radix, dtype=jnp.int32)
    bucket_node = (digit * N // radix) % N      # digit's home bucket
    node = jnp.where(hist_phase, ids, bucket_node)
    block = jax.random.randint(k2, shape, 0, cfg.mem_size, dtype=jnp.int32)
    addr = codec.make_address(cfg, node, block)
    op = jnp.broadcast_to(
        jnp.where(hist_phase, int(Op.READ), int(Op.WRITE)),
        shape).astype(jnp.int32)
    val = jax.random.randint(k3, shape, 0, 256, dtype=jnp.int32)
    return op, addr, val, jnp.full((N,), trace_len, jnp.int32)


def hotspot(key, cfg: SystemConfig, trace_len: int,
            working_set: int = 2, migrate_prob: float = 0.05,
            write_frac: float = 0.5):
    """Temporal-locality workload: each node hammers a small working set
    of blocks (its own plus one shared remote region), occasionally
    migrating to a new set.

    The uniform workload has no temporal locality, so 16 blocks vs 4
    lines per node makes capacity misses dominate; real cache studies
    need hit-dominated phases too. Here consecutive accesses revisit
    `working_set` blocks until a migration draw (`migrate_prob`)
    switches the set — producing long runs that the sync engine's hit
    burst retires in bulk and the async engine serves without traffic.
    """
    N = cfg.num_nodes
    k1, k2, k3, k4, k5 = jax.random.split(key, 5)
    shape = (N, trace_len)
    # segment index = number of migrations so far (prefix sum of draws)
    migrate = jax.random.uniform(k1, shape) < migrate_prob
    seg = jnp.cumsum(migrate.astype(jnp.int32), axis=1)
    # per-(node, segment) private anchor, and per-SEGMENT shared anchor
    # (node-independent so concurrent hot segments really do collide on
    # the same blocks of the hot node — the sharing/invalidation phase)
    seg_key = jnp.arange(N, dtype=jnp.int32)[:, None] * 131071 + seg
    h = (seg_key.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)) >> 8
    h_shared = ((seg.astype(jnp.uint32) + jnp.uint32(0x51ED2705))
                * jnp.uint32(0x85EBCA77)) >> 8
    own = jnp.broadcast_to(jnp.arange(N, dtype=jnp.int32)[:, None], shape)
    hot = jax.random.randint(k2, (), 0, N, dtype=jnp.int32)
    is_hot = (h & 3) == 0
    node = jnp.where(is_hot, hot, own)
    base = jnp.where(is_hot, h_shared.astype(jnp.int32),
                     h.astype(jnp.int32) >> 2) % cfg.mem_size
    off = jax.random.randint(k3, shape, 0, working_set, dtype=jnp.int32)
    block = (base + off) % cfg.mem_size
    addr = codec.make_address(cfg, node, block)
    is_write = jax.random.uniform(k4, shape) < write_frac
    op = jnp.where(is_write, int(Op.WRITE), int(Op.READ)).astype(jnp.int32)
    val = jax.random.randint(k5, shape, 0, 256, dtype=jnp.int32)
    return op, addr, val, jnp.full((N,), trace_len, jnp.int32)


def zipf_hotspot(key, cfg: SystemConfig, trace_len: int,
                 exponent: float = 1.2, hot_ranks: int = 64,
                 write_frac: float = 0.5):
    """Heavy-tailed popularity workload: block popularity follows a
    truncated Zipf law (rank r drawn with probability ∝ r^-exponent
    over the `hot_ranks` most popular blocks), every node sampling
    from the SAME popularity ranking.

    `hotspot` gives temporal locality (each node revisits its own
    small set); this gives POPULARITY skew — a handful of globally hot
    blocks absorb most of the traffic from every node at once, the
    web/KV-cache access law. Rank 1 alone carries ~1/H share, so the
    directory entries of the head blocks see wide sharer sets and
    constant upgrade/invalidate churn while the tail stays cold — the
    worst case for home-node serialization that a uniform stream never
    produces. Inverse-CDF sampling keeps it exact and fully batched.
    """
    N = cfg.num_nodes
    k1, k2, k3 = jax.random.split(key, 3)
    shape = (N, trace_len)
    n_ranks = min(int(hot_ranks), N * cfg.mem_size)
    ranks = jnp.arange(1, n_ranks + 1, dtype=jnp.float32)
    weights = ranks ** jnp.float32(-exponent)
    cdf = jnp.cumsum(weights) / jnp.sum(weights)
    u = jax.random.uniform(k1, shape)
    rank = jnp.searchsorted(cdf, u).astype(jnp.int32)
    # rank → block: hash so consecutive ranks land on unrelated homes
    # (popularity is a property of the block, not of an address range)
    h = (rank.astype(jnp.uint32) + jnp.uint32(1)) \
        * jnp.uint32(0x9E3779B9)
    node = ((h >> 8).astype(jnp.int32) & 0x7FFF) % N
    block = ((h >> 16).astype(jnp.int32) & 0x7FFF) % cfg.mem_size
    addr = codec.make_address(cfg, node, block)
    is_write = jax.random.uniform(k2, shape) < write_frac
    op = jnp.where(is_write, int(Op.WRITE),
                   int(Op.READ)).astype(jnp.int32)
    val = jax.random.randint(k3, shape, 0, 256, dtype=jnp.int32)
    return op, addr, val, jnp.full((N,), trace_len, jnp.int32)


def lu_blocked(key, cfg: SystemConfig, trace_len: int):
    """SPLASH-2 LU-style blocked-factorization reference pattern.

    Phase k of blocked LU: the pivot block (owned by node k mod N) is
    read by every node factoring a block of pivot row/column k; each
    node then updates (reads + writes) its own trailing blocks. Per
    instruction slot t: phase = t // 4; slot 0 reads the phase's pivot
    block (a broadcast hot read — wide sharer sets, the pattern that
    stresses invalidation fan-out when the next phase's owner upgrades
    it), slot 1 reads the node's row-pivot block, slots 2-3
    read-then-write a local trailing block. Deterministic homes, racy
    only on the shared pivot reads.
    """
    N = cfg.num_nodes
    k1, k2 = jax.random.split(key)
    shape = (N, trace_len)
    ids = jnp.arange(N, dtype=jnp.int32)[:, None]
    t = jnp.arange(trace_len, dtype=jnp.int32)[None, :]
    phase = t // 4
    slot = t % 4
    pivot_owner = phase % N
    pivot_block = phase % cfg.mem_size
    row_owner = (phase + ids) % N
    local_block = jax.random.randint(k1, shape, 0, cfg.mem_size,
                                     dtype=jnp.int32)
    node = jnp.where(slot == 0, pivot_owner,
                     jnp.where(slot == 1, row_owner, ids))
    block = jnp.where(slot <= 1, jnp.broadcast_to(pivot_block, shape),
                      local_block)
    addr = codec.make_address(cfg, node, block)
    op = jnp.where(slot == 3, int(Op.WRITE),
                   int(Op.READ)).astype(jnp.int32)
    op = jnp.broadcast_to(op, shape)
    val = jax.random.randint(k2, shape, 0, 256, dtype=jnp.int32)
    return op, addr, val, jnp.full((N,), trace_len, jnp.int32)


def procedural_uniform(key, cfg: SystemConfig, trace_len: int):
    """Materialized twin of the sync engine's procedural 'uniform'
    source (ops.sync_engine.procedural_instr): identical instructions,
    stored as arrays — for parity checks against procedural runs and
    for feeding the other engines. The PRNG `key` is unused; the
    stream is determined by cfg.proc_seed (counter-based)."""
    from ue22cs343bb1_openmp_assignment_tpu.procedural import (
        procedural_instr)
    del key
    N = cfg.num_nodes
    nodes = jnp.arange(N, dtype=jnp.int32)[:, None]
    idxs = jnp.arange(trace_len, dtype=jnp.int32)[None, :]
    oa, val = procedural_instr(cfg, nodes, idxs)
    return (oa >> 28, oa & 0x0FFFFFFF, val,
            jnp.full((N,), trace_len, jnp.int32))


GENERATORS = {
    "uniform": uniform_random,
    "producer_consumer": producer_consumer,
    "false_sharing": false_sharing,
    "false_sharing_vars": false_sharing_vars,
    "false_sharing_vars_padded": functools.partial(false_sharing_vars,
                                                   padded=True),
    "fft": fft_transpose,
    "radix": radix_sort,
    "lu": lu_blocked,
    "hotspot": hotspot,
    "zipf_hotspot": zipf_hotspot,
    "procedural_uniform": procedural_uniform,
}
