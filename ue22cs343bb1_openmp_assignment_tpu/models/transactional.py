"""TransactionalSystem — the sync engine's high-level API.

The throughput twin of models.system.CoherenceSystem: same constructor
surface (fixture tree, synthetic workloads, raw traces), same verbs
(step/run/dumps/save/load/check/metrics), running the transactional
engine (ops.sync_engine) instead of the message-level one. Adds the
capabilities specific to that engine: trace streaming
(`continue_with`), batched seed ensembles (`ensemble`), and the
exact-directory invariant check at any round boundary.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
from ue22cs343bb1_openmp_assignment_tpu.utils import checkpoint, golden


@dataclasses.dataclass
class TransactionalSystem:
    """A configured transactional coherence machine with its state."""

    cfg: SystemConfig
    state: se.SyncState

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_test_dir(cls, test_dir: str,
                      cfg: Optional[SystemConfig] = None,
                      seed: int = 0) -> "TransactionalSystem":
        base = CoherenceSystem.from_test_dir(test_dir, cfg)
        return cls(base.cfg, se.from_sim_state(base.cfg, base.state, seed))

    @classmethod
    def from_workload(cls, cfg: SystemConfig, name: str = "uniform",
                      trace_len: Optional[int] = None,
                      workload_seed: int = 0, seed: int = 0,
                      **gen_kw) -> "TransactionalSystem":
        base = CoherenceSystem.from_workload(
            cfg, name, trace_len=trace_len, seed=workload_seed, **gen_kw)
        return cls(base.cfg, se.from_sim_state(base.cfg, base.state, seed))

    @classmethod
    def from_traces(cls, cfg: SystemConfig, traces: Sequence,
                    seed: int = 0) -> "TransactionalSystem":
        base = CoherenceSystem.from_traces(cfg, traces)
        return cls(cfg, se.from_sim_state(cfg, base.state, seed))

    # -- execution ---------------------------------------------------------
    def step(self) -> "TransactionalSystem":
        """Advance one round (unjitted; for debugging/inspection)."""
        return dataclasses.replace(
            self, state=se.round_step(self.cfg, self.state))

    def run(self, max_rounds: int = 100_000,
            chunk: int = 32) -> "TransactionalSystem":
        """Run until every trace retires (chunked single dispatch)."""
        final = se.run_sync_to_quiescence(self.cfg, self.state, chunk,
                                          max_rounds)
        return dataclasses.replace(self, state=final)

    def run_rounds(self, n: int) -> "TransactionalSystem":
        return dataclasses.replace(
            self, state=se.run_rounds(self.cfg, self.state, n))

    def continue_with(self, traces=None,
                      instr_arrays=None) -> "TransactionalSystem":
        """Stream the next trace phase into the retired machine."""
        return dataclasses.replace(
            self, state=se.continue_with_traces(
                self.cfg, self.state, traces=traces,
                instr_arrays=instr_arrays))

    # -- ensembles ---------------------------------------------------------
    def ensemble(self, seeds: Sequence[int]) -> se.SyncState:
        """[len(seeds), ...] ensemble of this machine under each seed."""
        return se.make_ensemble(
            [self.state.replace(seed=_i32(s)) for s in seeds])

    # -- inspection --------------------------------------------------------
    @property
    def quiescent(self) -> bool:
        return bool(self.state.quiescent())

    @property
    def metrics(self) -> dict:
        import jax
        m = self.state.metrics
        out = {f: jax.device_get(getattr(m, f))
               for f in m.__dataclass_fields__}
        return {k: (v.tolist() if hasattr(v, "tolist") else v)
                for k, v in out.items()}

    @property
    def instrs_retired(self) -> int:
        return int(self.state.metrics.instrs_retired)

    def check_invariants(self) -> dict:
        """Exact-directory invariant (valid at any round boundary)."""
        return se.check_exact_directory(self.cfg, self.state)

    def dumps(self) -> List[str]:
        """printProcessorState-format dumps (byte-parity surface)."""
        view = se.to_dump_view(self.cfg, self.state)
        return [golden.format_node_dump(d)
                for d in golden.state_to_dumps(self.cfg, view)]

    def write_dumps(self, out_dir: str) -> List[str]:
        return golden.write_dumps(
            self.cfg, se.to_dump_view(self.cfg, self.state), out_dir)

    # -- persistence -------------------------------------------------------
    def save(self, path: str, meta: Optional[dict] = None) -> None:
        checkpoint.save_checkpoint(path, self.cfg, self.state, meta)

    @classmethod
    def load(cls, path: str) -> "TransactionalSystem":
        cfg, state, meta = checkpoint.load_checkpoint(path)
        if meta.get("kind") != "sync":
            raise ValueError(
                f"{path} holds an async-engine (SimState) checkpoint; "
                "load it with models.system.CoherenceSystem")
        return cls(cfg, state)


def _i32(x):
    import jax.numpy as jnp
    return jnp.asarray(x, jnp.int32)
