"""Vectorized mailbox: dequeue gather + delivery sort/scatter.

The reference's network is one locked circular ring per node
(``assignment.c:81-105``): producers take ``msgBufferLocks[receiver]``,
append, release (``assignment.c:741-765``); the owner drains its own ring
lock-free (``assignment.c:167-177``). Cross-sender enqueue order is OS
scheduling — the source of the test_3/test_4 nondeterminism.

TPU-native re-design: all N rings live in one padded ``[N, Q]`` tensor
set. Per cycle,

* every non-empty node *gathers* its head message (dequeue),
* every candidate message emitted this cycle carries an explicit
  ``(receiver, priority)``; priority = ``(arb_rank(sender), slot)`` where
  slot index encodes the sender's program order. One lexicographic sort
  over all candidates yields, per receiver, the arrival order — a
  *deterministic, seedable* stand-in for lock-acquisition order. The
  ``arb_rank`` permutation is the seed knob.
* a scatter writes the accepted candidates into the rings; candidates
  beyond free capacity are dropped silently, matching the reference's
  overflow behavior (``assignment.c:754-762``, quirk 6), but counted.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

import jax

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.state import (MB_ADDR, MB_BV0,
                                                      MB_DIRSTATE, MB_SECOND,
                                                      MB_SENDER, MB_TYPE,
                                                      MB_VALUE)
from ue22cs343bb1_openmp_assignment_tpu.types import Msg


class MsgView(NamedTuple):
    """Per-node view of this cycle's dequeued message (masked by has_msg)."""

    has_msg: jnp.ndarray   # [N] bool
    type: jnp.ndarray      # [N] i32 (Msg.NONE where no message)
    sender: jnp.ndarray    # [N] i32
    addr: jnp.ndarray      # [N] i32
    value: jnp.ndarray     # [N] i32
    second: jnp.ndarray    # [N] i32
    dirstate: jnp.ndarray  # [N] i32
    bitvec: jnp.ndarray    # [N, W] u32


class Candidates(NamedTuple):
    """Per-(node, out-slot) candidate messages emitted this cycle.

    Slot order encodes each sender's program order (config.out_slots):
    primary, secondary, INV fan-out, eviction notice.
    """

    type: jnp.ndarray      # [N, S] i32 (Msg.NONE = no message)
    recv: jnp.ndarray      # [N, S] i32
    sender: jnp.ndarray    # [N, S] i32
    addr: jnp.ndarray      # [N, S] i32
    value: jnp.ndarray     # [N, S] i32
    second: jnp.ndarray    # [N, S] i32
    dirstate: jnp.ndarray  # [N, S] i32
    bitvec: jnp.ndarray    # [N, S, W] u32


def empty_candidates(cfg: SystemConfig) -> Candidates:
    N, S, W = cfg.num_nodes, cfg.out_slots, cfg.msg_bitvec_words
    z = jnp.zeros((N, S), jnp.int32)
    return Candidates(type=jnp.full((N, S), int(Msg.NONE), jnp.int32),
                      recv=z, sender=z, addr=z, value=z, second=z,
                      dirstate=z, bitvec=jnp.zeros((N, S, W), jnp.uint32))


def dequeue(cfg: SystemConfig, state) -> tuple:
    """Gather each node's head message; advance head/count where non-empty.

    Returns (MsgView, new_head, new_count). Mirrors the drain step at
    ``assignment.c:174-177`` (one message per node per cycle; the
    drain-all-first priority emerges because instruction fetch is gated on
    an empty queue, see ops.step). One row gather serves every field.
    """
    N = cfg.num_nodes
    rows = jnp.arange(N, dtype=jnp.int32)
    has = state.mb_count > 0
    h = state.mb_head
    safe_h = jnp.where(has, h, 0)
    row = state.mb_pack[:, rows, safe_h]               # [6 + Wm, N]
    view = MsgView(
        has_msg=has,
        type=jnp.where(has, row[MB_TYPE], int(Msg.NONE)),
        sender=row[MB_SENDER],
        addr=row[MB_ADDR],
        value=row[MB_VALUE],
        second=row[MB_SECOND],
        dirstate=row[MB_DIRSTATE],
        bitvec=jax.lax.bitcast_convert_type(row[MB_BV0:].T, jnp.uint32),
    )
    new_head = jnp.where(has, (h + 1) % cfg.queue_capacity, h)
    new_count = state.mb_count - has.astype(jnp.int32)
    return view, new_head, new_count


def candidate_prio(cfg: SystemConfig, arb_rank) -> jnp.ndarray:
    """[N, S] global arbitration priority of each candidate: sender's
    arbitration rank, then program-order slot. THE delivery order — the
    explicit shard_map router (parallel/shardmap_comm.py) ships it
    across shards so routed and global delivery sort identically."""
    S = cfg.out_slots
    return (arb_rank.astype(jnp.int32)[:, None] * S
            + jnp.arange(S, dtype=jnp.int32)[None, :])


def pack_candidates(cand: Candidates) -> jnp.ndarray:
    """[6 + Wm, N, S] i32 payload planes, the exact layout the ring
    scatter writes (shared with the shard_map router)."""
    flat = jnp.stack([cand.type, cand.sender, cand.addr, cand.value,
                      cand.second, cand.dirstate], axis=0)
    bv = jax.lax.bitcast_convert_type(cand.bitvec, jnp.int32)
    return jnp.concatenate(
        [flat, jnp.moveaxis(bv, -1, 0)], axis=0)


def segment_ranks(bucket, valid):
    """(rank, seg_start) of each row within its bucket run.

    `bucket`/`valid` must already be sorted so equal buckets are
    adjacent with invalid rows last; rank counts 0.. within each run
    (the enqueue position / lane slot). Shared by ops.mailbox.deliver
    and parallel/shardmap_comm.make_router."""
    F = bucket.shape[0]
    idx = jnp.arange(F, dtype=jnp.int32)
    is_start = jnp.concatenate(
        [jnp.array([True]), (bucket[1:] != bucket[:-1]) | ~valid[1:]])
    seg_start = jax_cummax(jnp.where(is_start, idx, -1))
    return idx - seg_start, seg_start


def deliver(cfg: SystemConfig, state, cand: Candidates, arb_rank,
            new_head, new_count, *, with_accept: bool = False):
    """Scatter candidates into the rings with deterministic arbitration.

    arb_rank: [N] i32 permutation of node ids — the seedable stand-in for
    the OS lock-acquisition order across concurrent senders. Lower rank
    enqueues first at every receiver this cycle.

    When cfg.drop_prob > 0, each otherwise-accepted message is dropped
    with that probability (fault injection, seeded by state.fault_key) —
    the generalized form of the reference's silent overflow drop
    (``assignment.c:754-762``) as a stress knob for the stall watchdog
    (ops.failures).

    Returns (state updates dict, dropped_count, injected_count). With
    ``with_accept=True`` the updates dict additionally carries
    ``enq_accept``: the final per-candidate accept mask scattered back
    to the original [N, S] slot layout — the message-ledger capture
    (ops.step with_ledger) consumes it; the caller must pop it before
    state.replace. Off by default so the headline path lowers to the
    exact same HLO.
    """
    N, S, Q = cfg.num_nodes, cfg.out_slots, cfg.queue_capacity
    F = N * S

    c_type = cand.type.reshape(F)
    recv = cand.recv.reshape(F)
    # Out-of-range receivers (owner lookup on an empty sharer set yields
    # the ctz sentinel 32*W) are dropped here, uncounted — explicitly, so
    # the capacity gather below never reads a clamped index and the
    # native engine's matching guard (engine.cpp deliver) stays exact.
    valid = (c_type != int(Msg.NONE)) & (recv >= 0) & (recv < N)
    prio = candidate_prio(cfg, arb_rank).reshape(F)

    # group candidates by receiver in arbitration order
    if N * (F + 1) + F < 2**31:
        # single fused sort key fits in int32
        key = jnp.where(valid, recv * (F + 1) + prio,
                        jnp.iinfo(jnp.int32).max)
        order = jnp.argsort(key)
    else:
        # large-N path: two stable sorts (lexicographic by (recv, prio))
        order1 = jnp.argsort(jnp.where(valid, prio, jnp.iinfo(jnp.int32).max),
                             stable=True)
        key2 = jnp.where(valid[order1], recv[order1],
                         jnp.iinfo(jnp.int32).max)
        order = order1[jnp.argsort(key2, stable=True)]
    r_s = recv[order]
    v_s = valid[order]

    # rank within each receiver's run of the sorted array
    rank, seg_start = segment_ranks(r_s, v_s)

    # capacity: free slots after this cycle's dequeue
    safe_r = jnp.where(v_s, r_s, 0)
    free = (Q - new_count)[safe_r]
    accept = v_s & (rank < free)
    dropped_overflow = jnp.sum(v_s & ~accept).astype(jnp.int32)

    # fault injection: drop accepted messages with cfg.drop_prob
    fault_key = state.fault_key
    injected = jnp.zeros((), jnp.int32)
    if cfg.drop_prob > 0.0:
        key = jax.random.wrap_key_data(state.fault_key)
        k_draw, k_next = jax.random.split(key)
        hit = jax.random.bernoulli(k_draw, cfg.drop_prob, accept.shape)
        injected = jnp.sum(accept & hit).astype(jnp.int32)
        accept = accept & ~hit
        # dropped messages would leave holes in the ring; re-rank the
        # survivors within each receiver segment so writes stay dense
        # seg_start >= 0 everywhere (is_start[0] is always True)
        excl = jnp.cumsum(accept.astype(jnp.int32)) - accept.astype(jnp.int32)
        rank = excl - excl[seg_start]
        fault_key = jax.random.key_data(k_next).astype(jnp.uint32)
    pos = (new_head[safe_r] + new_count[safe_r] + rank) % Q

    tgt_r = jnp.where(accept, r_s, N)      # OOB row -> dropped by scatter
    tgt_p = jnp.where(accept, pos, 0)

    # pack the candidate fields into message planes; the whole delivery
    # is then ONE scatter of [6 + Wm, F] fibers into the (node, slot)
    # plane — in place (plane-major ring layout, state.SimState)
    pack = pack_candidates(cand).reshape(-1, F)[:, order]

    updates = dict(
        mb_pack=state.mb_pack.at[:, tgt_r, tgt_p].set(pack, mode="drop"),
        mb_head=new_head,
        mb_count=new_count.at[tgt_r].add(
            accept.astype(jnp.int32), mode="drop"),
        fault_key=fault_key,
    )
    if with_accept:
        # undo the arbitration sort: accept[i] belongs to candidate
        # order[i], so one scatter restores the (node, slot) layout
        acc = jnp.zeros((F,), jnp.bool_).at[order].set(accept)
        updates["enq_accept"] = acc.reshape(N, S)
    return updates, dropped_overflow, injected


def jax_cummax(x: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.associative_scan(jnp.maximum, x)


def push_message(cfg: SystemConfig, state, receiver: int, *, type,
                 sender=0, addr=0, value=0, second=0, dirstate=0,
                 bitvec=0):
    """Host-side single-message enqueue (test/debug injection only).

    The hot path delivers via :func:`deliver`; this mirrors the tail
    append of ``sendMessage`` (``assignment.c:751-764``) one message at a
    time so unit tests can stage arbitrary protocol situations.
    """
    r = receiver
    tail = (int(state.mb_head[r]) + int(state.mb_count[r])) % cfg.queue_capacity
    if int(state.mb_count[r]) >= cfg.queue_capacity:
        return state  # silent drop, like the reference
    W = cfg.msg_bitvec_words
    bv = jnp.zeros((W,), jnp.uint32)
    bv_int = int(bitvec)
    for w in range(W):
        bv = bv.at[w].set((bv_int >> (32 * w)) & 0xFFFFFFFF)
    row = jnp.concatenate(
        [jnp.asarray([int(type), int(sender), int(addr), int(value),
                      int(second), int(dirstate)], jnp.int32),
         jax.lax.bitcast_convert_type(bv, jnp.int32)])
    return state.replace(
        mb_pack=state.mb_pack.at[:, r, tail].set(row),
        mb_count=state.mb_count.at[r].add(1),
    )
