"""Fused Pallas round kernel: one deep-engine round in one kernel.

ROADMAP item 1 names the gap: the deep round is **index-bound** — of
the ~0.82 ms round at deep@4096, ~550 µs sits in 7 separate
gather/scatter XLA fusions (claim scatter-min, side gather, g-slot
gather, per-wave row gather, owner-value gather, commit row scatter,
fan-out gather + promotion scatter), each round-tripping the [E, 7]
directory and [C, N] cache through HBM, plus ~95 µs of copies and
transposes adapting layouts between them. This module executes the
ENTIRE round — window folds, arbitration, composition, fan-out, metric
deltas — as a single ``pl.pallas_call`` instance with all state
resident in VMEM, so per-round state touches HBM exactly twice (one
load, one store).

How it fuses without rewriting the engine
-----------------------------------------
The round middle was already layout-shared with the fold kernels
(ops/pallas_deep); this PR routes its seven index-op families through
an injectable strategy (``deep_engine.XlaIndexOps``) and the fused
kernel substitutes :class:`RoutedIndexOps` while running the IDENTICAL
``deep_engine.deep_round_core`` middle and the identical
``pallas_deep._run_fold`` fold code (ref-style slicing works on plain
arrays) in-kernel. Bit-identity of the fused path therefore reduces to
exactness of the routed ops, which tests/test_pallas_round.py pins
against the XLA reference — interpret mode on CPU, the
tests/test_pallas_deep.py pattern.

Routing index ops through the MXU (Mosaic has no vector gather)
---------------------------------------------------------------
TPU Pallas cannot lower vector gathers/scatters, so every dynamic
access becomes an exact one-hot f32 matmul over entry tiles:

* gather   out[r] = sum_e [idx[r] == e] * v[e]   (row one-hot @ values)
* scatter  out[e] = sum_r [idx[r] == e] * v[r]   (col one-hot @ values)

int32 payloads split into two 16-bit halves per column; each half is a
nonnegative integer < 2**16, exactly representable in f32, and every
output sums at most ONE nonzero product (gathers are functions;
scatter indices are unique per committed wave), so the matmul results
are exact integers under any float precision. One-hot tiles are built
``_TILE`` entries at a time (iota compare — transpose-free for
gathers, one [1, R] -> [R, 1] reshape for scatters) and contracted
with ``precision=HIGHEST``.

The claim/wave scatter-MIN cannot ride a sum, so it uses the chunked
exponent trick: all fresh lane keys this round share the same
countdown high bits (the DM_CLAIM invariant, ops/sync_engine), and the
low ``L = prio_bits + 1 + SB + ST <= 16`` bits are minimised 4 bits at
a time. Contenders route ``2**(A - G*chunk)`` (A=100, G=15) and the
per-entry minimum chunk is recovered as ``#{v : sum < 2**(A - G*v)}``:
with fewer than 2**14 contenders the rounded sum stays strictly inside
``[2**(A-G*m), 2**(A-G*(m-1)))`` for minimum chunk m (sum of positive
powers of two, RN summation error < 0.1%, 16*15 = 240-step exponent
ladder inside f32 normal range), so 16 dense threshold compares read
off the minimum exactly. Contenders then narrow to those matching the
minimum chunk (one routed gather-back) and the next 4 bits repeat —
at most 4 passes. ``supported`` caps ``deep_slots * num_nodes < 2**14``
for the rounding margin (deep@4096 headline: 3 * 4096 = 12288).

VMEM budget at the deep@4096 headline (N=4096, S=16, C=4, Q=3, W=16):
directory [65536, 7] i32 = 1.75 MB, cache 3x[4, 4096] = 192 KB,
window 3x[16, 4096] = 768 KB, fold carry ~250 [1, 4096] vecs ~ 4 MB,
largest routed one-hot tile [12288, 128] f32 = 6 MB transient —
~13 MB peak, inside a 16 MB core. The kernel's HBM contract per round
is its I/O: ~3.8 MB vs the ~3.4 GB/round the unfused path moves
(obs/roofline measures 191377.95 bytes/instr on the XLA path).

Scope: any workload kind (the [W, N] window is built in XLA exactly as
the reference path builds it), any deep_waves, exact flags on or off.
NOT supported (``supported`` returns False, callers fall back to the
XLA path): read-storm configs (duplicate-row storm commits break the
routed scatters' uniqueness contract), with_events/return_stats
callers, and node counts past the scatter-min rounding margin.
Carrying K > 1 rounds per kernel launch (window build in-kernel for
procedural workloads) is the named follow-up in PERF.md.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.procedural import procedural_instr
from ue22cs343bb1_openmp_assignment_tpu.ops import deep_engine
from ue22cs343bb1_openmp_assignment_tpu.ops.deep_engine import (
    state_tiles)
from ue22cs343bb1_openmp_assignment_tpu.ops.pallas_burst import (
    _interpret)
from ue22cs343bb1_openmp_assignment_tpu.ops.pallas_deep import (
    _cat, _run_fold)
from ue22cs343bb1_openmp_assignment_tpu.ops.sync_engine import (
    DM_COLS, DM_COUNT, DM_MEM, DM_OWNER, DM_STATE, SyncState,
    claim_max_rounds, slot_bits)

# chunked scatter-min weight ladder: contenders route 2**(A - G*chunk)
# over _MIN_CHUNK_BITS-wide chunks; G=15 leaves a 2**14
# contender/rounding margin between adjacent chunk thresholds and the
# 16-step ladder spans [2**-125, 2**100], inside f32 normal range.
# These three are THE ladder parameters analysis/kernelcheck audits:
# the derived contender cap, the f32 range lemmas and the supported()
# gate are all functions of (A, G, chunk bits, f32 mantissa width), so
# perturbing any of them (analysis/mutations.KERNEL_MUTATIONS) must
# trip the static verifier.
_MIN_A, _MIN_G = 100, 15
_MIN_CHUNK_BITS = 4

_HI = jax.lax.Precision.HIGHEST


def _tile_of(M: int) -> int:
    """One-hot entry-tile width: 128 lanes when the domain allows."""
    return 128 if M % 128 == 0 else M


def _split16(v):
    """int32 [R, K] -> f32 [R, 2K]: low then high 16-bit halves, each a
    nonnegative integer < 2**16 (exact in f32)."""
    u = v.astype(jnp.uint32)
    return jnp.concatenate([(u & 0xFFFF).astype(jnp.float32),
                            (u >> 16).astype(jnp.float32)], axis=-1)


def _join16(lo, hi):
    """Reassemble int32 from exact-integer f32 halves (wrapping shift
    restores negative values bit-for-bit)."""
    return (hi.astype(jnp.int32) << 16) | lo.astype(jnp.int32)


def _route_gather(mat, idx):
    """Exact one-hot gather: mat [M, K] int32 at idx (any shape) ->
    [*idx.shape, K]. Out-of-range indices yield zero rows (callers
    clip; the scatter-min narrowing relies on the zero)."""
    M, K = mat.shape
    TJ = _tile_of(M)
    V = _split16(mat)                                        # [M, 2K]
    flat = idx.reshape(-1, 1)                                # [R, 1]
    R = flat.shape[0]

    def body(i, acc):
        t0 = i * TJ
        iota = jax.lax.broadcasted_iota(jnp.int32, (1, TJ), 1) + t0
        oh = (flat == iota).astype(jnp.float32)              # [R, TJ]
        vt = jax.lax.dynamic_slice(V, (t0, 0), (TJ, 2 * K))
        return acc + jax.lax.dot(oh, vt, precision=_HI)

    acc = jax.lax.fori_loop(0, M // TJ, body,
                            jnp.zeros((R, 2 * K), jnp.float32))
    return _join16(acc[:, :K], acc[:, K:]).reshape(idx.shape + (K,))


def _route_scatter(mat, idx, rows_):
    """Exact one-hot scatter: rows_ [R, K] into mat [M, K] at idx [R].
    Out-of-range idx (the one-past-the-end drop sentinel) routes
    nowhere; in-range idx are unique (deep_engine.XlaIndexOps
    contract), so each written entry sums exactly one contribution.
    A ones column rides along as the hit count selecting written
    entries from kept ones."""
    M, K = mat.shape
    TJ = _tile_of(M)
    V = jnp.concatenate([_split16(rows_),
                         jnp.ones((rows_.shape[0], 1), jnp.float32)],
                        axis=-1)                             # [R, 2K+1]
    flat = idx.reshape(1, -1)                                # [1, R]

    def body(i, acc):
        t0 = i * TJ
        iota = jax.lax.broadcasted_iota(jnp.int32, (TJ, 1), 0) + t0
        oh = (iota == flat).astype(jnp.float32)              # [TJ, R]
        out_t = jax.lax.dot(oh, V, precision=_HI)            # [TJ, 2K+1]
        return jax.lax.dynamic_update_slice(acc, out_t, (t0, 0))

    acc = jax.lax.fori_loop(0, M // TJ, body,
                            jnp.zeros((M, 2 * K + 1), jnp.float32))
    hit = acc[:, -1:] > 0
    return jnp.where(hit, _join16(acc[:, :K], acc[:, K:2 * K]), mat)


def _route_min(idx, low, in_mask, M, L):
    """Per-entry minimum of contenders' low L-bit values via the
    chunked exponent ladder (module docstring). idx [R] int32 (any
    value outside [0, M) is dropped), low [R] the masked key low bits.
    Returns (has [M] bool, min_low [M] int32)."""
    cb = _MIN_CHUNK_BITS
    nvals = 1 << cb
    nch = max(1, -(-L // cb))
    still = in_mask
    min_low = jnp.zeros((M,), jnp.int32)
    has = None
    TJ = _tile_of(M)
    flat = idx.reshape(1, -1)                                # [1, R]
    for c in range(nch):
        sh = cb * (nch - 1 - c)
        chunk = (low >> sh) & (nvals - 1)                    # [R]
        w = jnp.zeros(idx.shape, jnp.float32)
        for v in range(nvals):
            w = jnp.where(chunk == v,
                          jnp.float32(2.0 ** (_MIN_A - _MIN_G * v)), w)
        w = jnp.where(still, w, 0.0)[:, None]                # [R, 1]

        def body(i, acc):
            t0 = i * TJ
            iota = (jax.lax.broadcasted_iota(jnp.int32, (TJ, 1), 0)
                    + t0)
            oh = (iota == flat).astype(jnp.float32)          # [TJ, R]
            s_t = jax.lax.dot(oh, w, precision=_HI)          # [TJ, 1]
            return jax.lax.dynamic_update_slice(acc, s_t, (t0, 0))

        ssum = jax.lax.fori_loop(0, M // TJ, body,
                                 jnp.zeros((M, 1), jnp.float32))[:, 0]
        if has is None:
            has = ssum > 0.0
        cstar = jnp.zeros((M,), jnp.int32)
        for v in range(nvals):
            cstar = cstar + (
                ssum < jnp.float32(2.0 ** (_MIN_A - _MIN_G * v))
            ).astype(jnp.int32)
        cstar = jnp.minimum(cstar, nvals - 1)      # no-contender: nvals
        min_low = (min_low << cb) | jnp.where(has, cstar, 0)
        if c < nch - 1:
            back = _route_gather(cstar[:, None], idx)[:, 0]
            still = still & (chunk == back)
    return has, min_low


class RoutedIndexOps:
    """deep_engine.XlaIndexOps as exact one-hot f32 matmul routing —
    the Mosaic-lowerable form of the round middle's seven index-op
    families (module docstring). Usable outside the kernel too (plain
    jnp), which is how the fast parity tests pin the routing math
    without paying a Pallas trace."""
    native = False

    def __init__(self, cfg: SystemConfig, round_):
        N = cfg.num_nodes
        prio_bits = max(1, (N - 1).bit_length())
        # low-bit width of the lane key below the shared countdown
        # (deep_engine key layout: prio | [is_rd] | slot | ev)
        self._L = (prio_bits + 1 + slot_bits(cfg)
                   + (1 if cfg.deep_read_storm else 0))
        self._cd = jnp.maximum(
            claim_max_rounds(cfg) - jnp.asarray(round_), 0
        ).astype(jnp.int32)

    def scatter_min(self, dest, idx, vals):
        # contract: vals are this round's lane keys — identical
        # countdown above bit L, so min(dest, countdown<<L | min_low)
        # reproduces the scatter-min exactly (fresh < stale, the
        # DM_CLAIM invariant)
        M = dest.shape[0]
        in_mask = (idx >= 0) & (idx < M)
        low = vals & ((1 << self._L) - 1)
        has, min_low = _route_min(idx, low, in_mask, M, self._L)
        fresh = (self._cd << self._L) | min_low
        return jnp.where(has, jnp.minimum(dest, fresh), dest)

    def gather(self, plane, idx):
        return _route_gather(plane[:, None], idx)[..., 0]

    def gather_rows(self, mat, idx):
        return _route_gather(mat, idx)

    def scatter_rows(self, mat, idx, rows_):
        return _route_scatter(mat, idx, rows_)

    def scatter_col(self, mat, idx, col, vals):
        newc = _route_scatter(mat[:, col:col + 1], idx, vals[:, None])
        return jnp.concatenate([mat[:, :col], newc, mat[:, col + 1:]],
                               axis=1)


def supported(cfg: SystemConfig) -> bool:
    """Can the fused round kernel run this config bit-identically?

    Storm configs are out (duplicate-row commits break the routed
    scatter uniqueness contract — a structural gate, not a margin) and
    the per-entry scatter-min contender count must stay under the
    chunked ladder's derived rounding cap. Both caps are COMPUTED by
    analysis/kernelcheck (the static kernel-contract verifier), not
    hand-derived here: the cap limit 2**(G-1) falls out of (chunk
    bits, weight-exponent gap G, f32 mantissa width), and the
    contender bound is N per entry at deep_waves == 1 (the window's
    dup stop admits one same-entry event per node, ops/deep_fold) vs
    N * deep_slots with absorption waves — which WIDENS the old
    hand-proved `deep_slots * num_nodes < 2**14` gate for single-wave
    configs. Everything else — workload kind, flag mode, protocol
    variant — is in scope."""
    if not cfg.deep_window or cfg.deep_read_storm:
        return False
    from ue22cs343bb1_openmp_assignment_tpu.analysis import kernelcheck
    b = kernelcheck.derived_bounds(cfg)
    return b["max_contenders"] < b["cap_limit"]


def io_contract_bytes(cfg: SystemConfig) -> tuple:
    """(input_bytes, output_bytes) of one fused-round launch — the
    kernel's per-round HBM contract (everything else stays in VMEM).
    Pure shape arithmetic; obs/cli.py turns it into the perf-report's
    ``io-contract`` roofline row (roofline.io_contract_record)."""
    N, C, S = cfg.num_nodes, cfg.cache_size, 1 << cfg.block_bits
    E = N * S
    W = cfg.drain_depth + cfg.txn_width
    elems_in = 2 * N + E * DM_COLS + 3 * C * N + 3 * W * N + N
    elems_out = E * DM_COLS + 3 * C * N + N + 10 * N
    return 4 * elems_in, 4 * elems_out


def _block_shapes(cfg: SystemConfig) -> tuple:
    """((in rows, cols)..., (out rows, cols)...) of the fused-round
    pallas_call blocks, all int32 — the single source of truth shared
    by `_call_round`'s BlockSpecs and analysis/kernelcheck's static
    VMEM-resident accounting (9 inputs, then 4 outputs)."""
    N, C, S = cfg.num_nodes, cfg.cache_size, 1 << cfg.block_bits
    E = N * S
    W = cfg.drain_depth + cfg.txn_width
    ins = ((2, N), (E, DM_COLS), (C, N), (C, N), (C, N), (W, N),
           (W, N), (W, N), (1, N))
    outs = ((E, DM_COLS), (3 * C, N), (1, N), (10, N))
    return ins, outs


def _round_body(cfg: SystemConfig, params, dm0, ca_t, cv_t, cs_t,
                w_oa, w_val, w_live, hor):
    """The whole round on plain arrays: three in-kernel folds
    (pallas_deep._run_fold — ref-style slicing works on plain arrays)
    around the shared deep_round_core middle with routed index ops.
    `_round_kernel` wraps this between one VMEM load and one store;
    analysis/kernelcheck traces THIS function (jax.make_jaxpr) for the
    static VMEM-liveness and Mosaic-lowerability passes, so what the
    analyzer audits is the code object the kernel runs."""
    N, C, S = cfg.num_nodes, cfg.cache_size, 1 << cfg.block_bits
    round_ = params[0, 0]
    seed = params[1, 0]
    dm_own = dm0.reshape(N, S, DM_COLS)
    dm_t4 = tuple(dm_own[:, :, col].T
                  for col in (DM_STATE, DM_COUNT, DM_OWNER, DM_MEM))

    def fold(bad, ocode):
        return _run_fold(cfg, N, ca_t, cv_t, cs_t, dm_t4[0], dm_t4[1],
                         dm_t4[2], dm_t4[3], w_oa, w_val, w_live, hor,
                         bad, ocode, pid=0)

    cb = lambda rows: jnp.concatenate(rows, axis=0)

    def flags_of(fin):
        return dict(mark=cb(fin["mark"]), poison=cb(fin["poison"]))

    fin0 = fold(None, None)
    pre = dict(kind=_cat(fin0["kind"]), ent=_cat(fin0["ent"]),
               sval=_cat(fin0["sval"]), **flags_of(fin0))

    def fold_flags_fn(oc):
        return flags_of(fold(None, oc))

    def fold_replay_fn(bad, oc):
        fin = fold(bad, oc)
        return dict(
            ca=_cat(fin["ca"]), cv=_cat(fin["cv"]), cs=_cat(fin["cs"]),
            cv_src=_cat(fin["cv_src"]), cv_req=_cat(fin["cv_req"]),
            cv_req_src=_cat(fin["cv_req_src"]), lwh=cb(fin["lwh"]),
            dms=_cat(fin["dms"]), dmc=_cat(fin["dmc"]),
            dmo=_cat(fin["dmo"]), dmm=_cat(fin["dmm"]),
            dmm_src=_cat(fin["dmm_src"]), touched=cb(fin["touched"]),
            act_acc=_cat(fin["act_acc"]), comm=cb(fin["comm"]),
            rel=cb(fin["rel"]), relv=_cat(fin["relv"]),
            g_owner=_cat(fin["g_owner"]), g_ci=_cat(fin["g_ci"]),
            n_ret=fin["n_ret"][0], rh=fin["rh"][0], wh=fin["wh"][0],
            cnt=dict(rd_miss=fin["c_rd"][0], wr_miss=fin["c_wr"][0],
                     upg=fin["c_up"][0], ev=fin["c_ev"][0]))

    core = deep_engine.deep_round_core(
        cfg, dm0, round_, seed, pre, fold_flags_fn, fold_replay_fn,
        RoutedIndexOps(cfg, round_))
    cache_out = jnp.concatenate(
        [core["ca_c"], core["cv_c"], core["cs_c"]], axis=0)
    return (core["dm"], cache_out, core["rp"]["n_ret"][None, :],
            core["delta_rows"])


def _round_kernel(cfg: SystemConfig, params_ref, dm_ref, ca_ref,
                  cv_ref, cs_ref, woa_ref, wval_ref, wlive_ref,
                  hor_ref, dm_out_ref, cache_out_ref, nret_ref,
                  delta_ref):
    """One VMEM load, `_round_body`, one VMEM store."""
    dm_out, cache_out, nret, delta = _round_body(
        cfg, params_ref[...], dm_ref[...], ca_ref[...], cv_ref[...],
        cs_ref[...], woa_ref[...], wval_ref[...], wlive_ref[...],
        hor_ref[...])
    dm_out_ref[...] = dm_out
    cache_out_ref[...] = cache_out
    nret_ref[...] = nret
    delta_ref[...] = delta


def _call_round(cfg, params, dm, ca_t, cv_t, cs_t, w_oa, w_val,
                w_live, hor2):
    ins, outs = _block_shapes(cfg)
    blk = lambda s: pl.BlockSpec(s, lambda i: (0, 0))
    return pl.pallas_call(
        functools.partial(_round_kernel, cfg),
        grid=(1,),
        in_specs=[blk(s) for s in ins],
        out_specs=[blk(s) for s in outs],
        out_shape=[jax.ShapeDtypeStruct(s, jnp.int32) for s in outs],
        interpret=_interpret(),
    )(params, dm, ca_t, cv_t, cs_t, w_oa, w_val, w_live, hor2)


def round_step_deep_fused(cfg: SystemConfig, st: SyncState) -> SyncState:
    """One deep round through the fused kernel — bit-identical to
    ``deep_engine.round_step_deep`` on ``supported`` configs
    (tests/test_pallas_round.py). The [W, N] window is built in XLA
    exactly as the reference path builds it (procedural hash or
    stored-trace gather); everything after enters the kernel once."""
    N, C, S = cfg.num_nodes, cfg.cache_size, 1 << cfg.block_bits
    W = cfg.drain_depth + cfg.txn_width
    T = st.instr_pack.shape[1]
    rows = jnp.arange(N, dtype=jnp.int32)
    offs_w = jnp.arange(W, dtype=jnp.int32)[:, None]
    w_idx = st.idx[None, :] + offs_w
    w_live = w_idx < st.instr_count[None, :]
    if cfg.procedural:
        w_oa, w_val = procedural_instr(cfg, rows[None, :], w_idx)
    else:
        w_flat = rows[None, :] * T + jnp.minimum(w_idx, T - 1)
        w = st.instr_pack.reshape(N * T, 2)[w_flat]
        w_oa, w_val = w[..., 0], w[..., 1]
    ca_t, cv_t, cs_t, _ = state_tiles(cfg, st)
    params = jnp.stack([jnp.broadcast_to(st.round, (N,)),
                        jnp.broadcast_to(st.seed, (N,))]
                       ).astype(jnp.int32)
    dm_out, cache_out, nret, delta_rows = _call_round(
        cfg, params, st.dm, ca_t, cv_t, cs_t, w_oa, w_val,
        w_live.astype(jnp.int32), st.horizon[None, :])
    core = dict(ca_c=cache_out[:C], cv_c=cache_out[C:2 * C],
                cs_c=cache_out[2 * C:], dm=dm_out,
                rp=dict(n_ret=nret[0]), delta_rows=delta_rows,
                kind=None)
    return deep_engine._finish_round_deep(cfg, st, core, w_oa, w_val,
                                          False, False)
