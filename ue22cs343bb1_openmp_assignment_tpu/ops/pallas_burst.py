"""Optional Pallas TPU kernel: the burst phase as ONE device kernel.

The transactional round's hit-burst phase (window fetch + hit
classification + burst write effects + stop-slot pick,
``_round_step_single`` phase 1-2) is node-local and gather-free, which
makes it the one hot stage expressible as a single fused Pallas kernel:
each grid step owns a tile of nodes, state rides in VMEM transposed to
``[cache_size, tile]`` so the node axis fills the 128-wide lanes, and
the whole phase is straight VPU arithmetic (the procedural instruction
hash included — `procedural.procedural_instr` and `codec` are reused
verbatim, so the kernel is bit-exact against the XLA path by
construction).

Measured on the attached TPU: +24% over the XLA burst phase at H=16
(PERF.md "Pallas, revised" — the early ~2 ms-per-launch figure came
from eager standalone calls and does not apply to kernels embedded in
a jitted scan body, where this runs like any other fused kernel).
`cfg.pallas_burst` stays OFF by default only because the non-TPU
fallback is the Pallas interpreter, which is impractically slow at
full kernel size; bench.py auto-enables the flag when a TPU backend is
attached. Differential tests pin the two paths bit-identical.

Only the procedural-workload path is covered: a stored-trace window
needs a dynamic row gather, which TPU Pallas has no vector lowering
for — that measured rejection is recorded in PERF.md.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ue22cs343bb1_openmp_assignment_tpu import codec
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.procedural import procedural_instr
from ue22cs343bb1_openmp_assignment_tpu.types import CacheState, Op


def _kernel(cfg: SystemConfig, T: int,
            ca_ref, cv_ref, cs_ref, idx_ref, cnt_ref,
            d_ref, rh_ref, wh_ref, oa_ref, val_ref, lv_ref,
            cvo_ref, cso_ref):
    C, H = cfg.cache_size, cfg.drain_depth
    INV = int(CacheState.INVALID)
    MOD = int(CacheState.MODIFIED)
    EXC = int(CacheState.EXCLUSIVE)
    pid = pl.program_id(0)
    node = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1) + pid * T
    idx = idx_ref[...]                                   # [1, T]
    cnt = cnt_ref[...]
    ca = ca_ref[...]                                     # [C, T]
    cs0 = cs_ref[...]
    cv_rows = [cv_ref[c:c + 1, :] for c in range(C)]     # [1, T] each
    cs_rows = [cs0[c:c + 1, :] for c in range(C)]

    # window slots, classified against the round-start cache (burst hits
    # never change any line's hit/miss class — _round_step_single)
    hits, rds, wrs, oas, vals, lives, cis = [], [], [], [], [], [], []
    for k in range(H + 1):
        w_idx = idx + k
        live = w_idx < cnt
        oa, val = procedural_instr(cfg, node, w_idx)
        op, addr = oa >> 28, oa & 0x0FFFFFFF
        ci = codec.cache_index(cfg, addr)
        l_addr, l_state = ca[0:1, :], cs0[0:1, :]
        for c in range(1, C):
            m = ci == c
            l_addr = jnp.where(m, ca[c:c + 1, :], l_addr)
            l_state = jnp.where(m, cs0[c:c + 1, :], l_state)
        tag_ok = (l_addr == addr) & (l_state != INV)
        rd_hit = live & (op == int(Op.READ)) & tag_ok
        wr_hit = live & (op == int(Op.WRITE)) & tag_ok & (
            (l_state == MOD) | (l_state == EXC))
        nop = live & (op == int(Op.NOP))
        hits.append(rd_hit | wr_hit | nop)
        rds.append(rd_hit)
        wrs.append(wr_hit)
        oas.append(oa)
        vals.append(val)
        lives.append(live)
        cis.append(ci)

    # leading all-hit prefix + burst write effects (last write wins)
    prefix = jnp.ones((1, T), bool)
    zero = jnp.zeros((1, T), jnp.int32)
    d, rh, wh = zero, zero, zero
    for k in range(H):
        prefix = prefix & hits[k]
        d = d + prefix.astype(jnp.int32)
        rh = rh + (rds[k] & prefix).astype(jnp.int32)
        wh = wh + (wrs[k] & prefix).astype(jnp.int32)
        wmask = wrs[k] & prefix
        for c in range(C):
            mc = wmask & (cis[k] == c)
            cv_rows[c] = jnp.where(mc, vals[k], cv_rows[c])
            cs_rows[c] = jnp.where(mc, MOD, cs_rows[c])

    # stop-slot pick (the transaction candidate, slot d)
    oa_s, val_s, lv_s = zero, zero, zero
    for k in range(H + 1):
        selk = d == k
        oa_s = jnp.where(selk, oas[k], oa_s)
        val_s = jnp.where(selk, vals[k], val_s)
        lv_s = jnp.where(selk, lives[k].astype(jnp.int32), lv_s)

    d_ref[...] = d
    rh_ref[...] = rh
    wh_ref[...] = wh
    oa_ref[...] = oa_s
    val_ref[...] = val_s
    lv_ref[...] = lv_s
    cvo_ref[...] = jnp.concatenate(cv_rows, axis=0)
    cso_ref[...] = jnp.concatenate(cs_rows, axis=0)


def _tile(N: int) -> int:
    """Node-axis tile per grid step (shared by ops.pallas_window)."""
    T = N if N <= 1024 else 1024
    if N % T:
        raise ValueError(f"num_nodes {N} not divisible by tile {T}")
    return T


def tileable(N: int) -> bool:
    """True when the node axis fits the kernels' tiling (<=1024 or a
    multiple of 1024). sync_engine.round_step silently keeps the
    bit-identical XLA path for untileable N instead of raising from
    inside the kernel call."""
    return N <= 1024 or N % 1024 == 0


def _interpret() -> bool:
    """Auto-select the Pallas interpreter off-TPU (the CPU test path)."""
    return jax.default_backend() != "tpu"


def burst(cfg: SystemConfig, ca, cv, cs, idx, cnt, interpret=None):
    """Run the burst phase for all nodes; returns
    (d, rh_n, wh_n, oa, val, live, cv', cs') in engine layout.

    interpret=None auto-selects the Pallas interpreter off-TPU (the
    CPU test path); pass False to force compilation.
    """
    N, C = ca.shape
    T = _tile(N)
    if interpret is None:
        interpret = _interpret()
    vec = pl.BlockSpec((1, T), lambda i: (0, i))
    mat = pl.BlockSpec((C, T), lambda i: (0, i))
    v_i32 = jax.ShapeDtypeStruct((1, N), jnp.int32)
    m_i32 = jax.ShapeDtypeStruct((C, N), jnp.int32)
    outs = pl.pallas_call(
        functools.partial(_kernel, cfg, T),
        grid=(N // T,),
        in_specs=[mat, mat, mat, vec, vec],
        out_specs=[vec] * 6 + [mat, mat],
        out_shape=[v_i32] * 6 + [m_i32, m_i32],
        interpret=interpret,
    )(ca.T, cv.T, cs.T, idx[None, :], cnt[None, :])
    d, rh, wh, oa, val, lv, cv_t, cs_t = outs
    return (d[0], rh[0], wh[0], oa[0], val[0], lv[0].astype(bool),
            cv_t.T, cs_t.T)
