"""Protocol-invariant checking — the framework's "race detector".

The reference's only safety net is three asserts compiled in under
``-DDEBUG`` (sole-owner popcount on write-miss-EM ``assignment.c:449``,
SHARED-state on S→E promotion ``assignment.c:556``, sole-owner on
EVICT_MODIFIED ``assignment.c:608-614``); data races themselves are
tolerated by design (SURVEY §5 "race detection: none", quirk 5). The
vectorized engine is deterministic, so race detection becomes *protocol
invariant checking*: whole-machine predicates evaluated on-device every
cycle (cheap reductions) or at quiescence (cross-node coherence).

Two tiers:

* :func:`step_violations` — invariants that hold after **every** cycle,
  even mid-transaction (directory/bitvector consistency, state-range,
  ring-occupancy sanity). Violations here mean the engine itself is
  broken.
* :func:`quiescent_violations` — the full single-writer / coherence
  contract, valid only once traffic has drained (while a transaction is
  in flight the reference deliberately lets cache and directory disagree
  — e.g. the directory moves to EM before the old owner has processed
  WRITEBACK_INV, ``assignment.c:455-457``, quirk 4).

**The coherence tier is a diagnostic, not an engine assert, under racy
workloads.** The reference's protocol deliberately tracks no INV-acks
(``assignment.c:358-361``): an INV that races an in-flight fill can be
processed before the REPLY_RD it should kill arrives (tag mismatch →
no-op, ``assignment.c:389-399``), after which the fill installs a copy
the directory no longer knows about. Both orderings are legal reference
behavior (they are exactly the kind of divergence behind the accepted
``run_*`` variants, SURVEY §4); the scatter-INV scale path
deterministically realizes the INV-first ordering. For race-free
workloads (disjoint footprints like tests/test_1–2, or writers
serialized via issue_delay) the coherence tier must be exactly zero —
that is the engine-correctness claim tests/test_invariants.py pins.

Everything returns a ``{name: violation_count}`` dict of device scalars,
so checks compose with `jit`/`scan` (no host sync until you ask).
"""

from __future__ import annotations

import functools
import operator

import jax.numpy as jnp

from ue22cs343bb1_openmp_assignment_tpu import codec
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.state import (SimState, bit_get,
                                                      popcount)
from ue22cs343bb1_openmp_assignment_tpu.types import CacheState, DirState


def _count(pred) -> jnp.ndarray:
    return jnp.sum(pred).astype(jnp.int32)


def step_predicates(cfg: SystemConfig, state: SimState) -> dict:
    """Engine-tier predicates as violation *masks* (True = violated).

    The single source of the invariant definitions: the dynamic checker
    (:func:`step_violations`) reduces these to counts, the static model
    checker (analysis/model_check.py) evaluates them on every explored
    state and uses the masks to locate offending (node, block) cells.
    Mask shapes vary per predicate ([N, M], [N, C] or [N]); only
    truthiness and position matter.
    """
    pc = popcount(state.dir_bitvec)                       # [N, M]
    is_em = state.dir_state == int(DirState.EM)
    is_s = state.dir_state == int(DirState.S)
    is_u = state.dir_state == int(DirState.U)

    return {
        # directory ⟷ sharer-bitvector consistency
        "em_not_single_owner": is_em & (pc != 1),
        "shared_without_sharers": is_s & (pc < 1),
        "unowned_with_sharers": is_u & (pc != 0),
        # enum ranges (a scatter writing garbage shows up here first)
        "dir_state_out_of_range":
            (state.dir_state < 0) | (state.dir_state > int(DirState.U)),
        # protocol-aware: MESI admits 0..3; the MOESI/MESIF table phases
        # additionally emit OWNED/FORWARD (types.py). Static unroll over
        # the (3-5 element) allowed tuple — cfg is jit-static, so this
        # folds to a constant membership mask.
        "cache_state_out_of_range":
            ~functools.reduce(
                operator.or_,
                [state.cache_state == s
                 for s in cfg.allowed_cache_states]),
        # ring occupancy within capacity, head within ring
        "mailbox_count_oob":
            (state.mb_count < 0) | (state.mb_count > cfg.queue_capacity),
        "mailbox_head_oob":
            (state.mb_head < 0) | (state.mb_head >= cfg.queue_capacity),
        # a node past its trace end must not be mid-request
        "waiting_past_trace_end":
            state.waiting & (state.instr_idx >= state.instr_count),
        # byte-valued payloads stay bytes (values are &0xFF at load,
        # assignment.c:840-845; a handler that forgets the mask drifts)
        "memory_not_byte": (state.memory < 0) | (state.memory > 0xFF),
    }


def step_violations(cfg: SystemConfig, state: SimState) -> dict:
    """Invariants that must hold after every cycle, as counts.

    The directory-side trio mirrors what the reference maintains
    atomically inside each handler (it never leaves a handler with EM
    and ≠1 sharer bits: ``assignment.c:228-231,346-348,455-457,
    570-583,615-616``).
    """
    return {k: _count(v) for k, v in step_predicates(cfg, state).items()}


def quiescent_predicates(cfg: SystemConfig, state: SimState) -> dict:
    """Coherence-tier predicates as violation masks (True = violated).

    Shared definition for the dynamic count reduction
    (:func:`quiescent_violations`) and the static model checker; see
    that function's docstring for the contract.
    """
    N, C, M = cfg.num_nodes, cfg.cache_size, cfg.mem_size
    rows = jnp.arange(N, dtype=jnp.int32)[:, None]        # [N, 1]

    valid = state.cache_state != int(CacheState.INVALID)  # [N, C]
    h = jnp.clip(codec.home_node(cfg, state.cache_addr), 0, N - 1)
    b = jnp.clip(codec.block_index(cfg, state.cache_addr), 0, M - 1)

    dstate = state.dir_state[h, b]                        # [N, C]
    dbv = state.dir_bitvec[h, b]                          # [N, C, W]
    my_bit = bit_get(dbv, jnp.broadcast_to(rows, (N, C)))

    is_m = state.cache_state == int(CacheState.MODIFIED)
    is_e = state.cache_state == int(CacheState.EXCLUSIVE)
    is_s = state.cache_state == int(CacheState.SHARED)

    # owned-copy count per home block: scatter-add of M/E lines
    owners = jnp.zeros((N, M), jnp.int32).at[h, b].add(
        (is_m | is_e).astype(jnp.int32))
    copies = jnp.zeros((N, M), jnp.int32).at[h, b].add(
        valid.astype(jnp.int32))
    mem_val = state.memory[h, b]

    return {
        "valid_line_unknown_to_home": valid & ~my_bit,
        "exclusive_line_dir_not_em":
            (is_m | is_e) & (dstate != int(DirState.EM)),
        "shared_line_dir_unowned": is_s & (dstate == int(DirState.U)),
        "multiple_owners": owners > 1,
        "owner_with_other_copies": (owners == 1) & (copies > 1),
        "clean_line_stale_value":
            (is_e | is_s) & (state.cache_val != mem_val),
        # every directory sharer bit corresponds to a real cached copy:
        # popcount over the directory == scatter-count of valid lines
        # pointing at it (no phantom sharers at quiescence)
        "phantom_sharers": popcount(state.dir_bitvec) != copies,
    }


def quiescent_violations(cfg: SystemConfig, state: SimState) -> dict:
    """The full coherence contract, valid once quiescent(), as counts.

    Cross-checks every cached line against its home directory — the
    single-writer property the whole DASH/MESI protocol exists to
    enforce (``README.md:14-23``):

    * a valid line's bit is set in its home directory entry,
    * MODIFIED/EXCLUSIVE lines coincide with directory EM,
    * a block has at most one M/E copy system-wide, and no other valid
      copies besides it,
    * clean lines (E, S) agree with home memory (S lines were written
      back via FLUSH before demotion, ``assignment.c:301-308``).
    """
    return {k: _count(v)
            for k, v in quiescent_predicates(cfg, state).items()}


def all_violations(cfg: SystemConfig, state: SimState,
                   quiescent: bool = False) -> dict:
    out = step_violations(cfg, state)
    if quiescent:
        out.update(quiescent_violations(cfg, state))
    return out


def assert_invariants(cfg: SystemConfig, state: SimState,
                      quiescent: bool = False) -> None:
    """Host-side check; raises AssertionError naming every violated
    invariant with its count.

    ``quiescent=True`` additionally asserts the coherence tier — only
    meaningful for race-free schedules (see module docstring); use
    :func:`coherence_report` for racy workloads.
    """
    v = {k: int(n) for k, n in all_violations(cfg, state, quiescent).items()}
    bad = {k: n for k, n in v.items() if n}
    if bad:
        raise AssertionError(f"protocol invariants violated: {bad}")


def coherence_report(cfg: SystemConfig, state: SimState) -> dict:
    """Coherence-tier counts as plain ints — the racy-workload
    diagnostic surface (stale copies left by the protocol's unacked-INV
    design show up here, e.g. ``valid_line_unknown_to_home``)."""
    return {k: int(v)
            for k, v in quiescent_violations(cfg, state).items()}


def run_cycles_checked(cfg: SystemConfig, state: SimState,
                       num_cycles: int):
    """Scan `num_cycles` cycles, accumulating per-cycle violation counts.

    Returns (final_state, {name: total_count}) — one device dispatch;
    the per-step tier is cheap reductions, so this is the always-on
    debug runner (the reference's -DDEBUG build, done the TPU way).
    """
    import jax

    from ue22cs343bb1_openmp_assignment_tpu.ops.step import (_ro_outside,
                                                             cycle)

    carry_state0, ro, blanks = _ro_outside(state)

    def body(carry, _):
        s, acc = carry
        s = cycle(cfg, s.replace(**ro))
        v = step_violations(cfg, s)
        acc = {k: acc[k] + v[k] for k in acc}
        return (s.replace(**blanks), acc), None

    zero = {k: jnp.zeros((), jnp.int32)
            for k in step_violations(cfg, state)}
    (final, acc), _ = jax.lax.scan(body, (carry_state0, zero), None,
                                   length=num_cycles)
    return final.replace(**ro), acc
