"""The simulation cycle and its runners.

One :func:`cycle` = the TPU-native equivalent of one trip around the
reference's per-thread event loop (``assignment.c:165-737``), for *all*
nodes at once:

  phase 1  every node with a queued message dequeues exactly one and runs
           its handler (ops.handlers) — masked, branch-free;
  phase 2  every idle, unblocked node fetches one instruction
           (ops.frontend) — a node never does both in one cycle, which
           preserves the reference's drain-before-fetch priority;
  phase 3  all candidate messages are delivered into the rings by one
           arbitration-sorted scatter (ops.mailbox), and (scatter mode)
           INV fan-out is applied as a dense cross-node invalidation.

Termination is a clean fixpoint (state.quiescent()) instead of the
reference's spin-forever + external ``kill -9`` (``assignment.c:639-645``,
``test3.sh:11``): at quiescence the state equals the final re-armed golden
dump.

Everything here is `jit`-compiled with `cfg` static; runners use
`lax.scan` / `lax.while_loop` so arbitrarily long traces never unroll.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ue22cs343bb1_openmp_assignment_tpu import codec
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops import frontend, handlers, mailbox
from ue22cs343bb1_openmp_assignment_tpu.state import LAT_BUCKETS, SimState
from ue22cs343bb1_openmp_assignment_tpu.types import (CacheState, DirState,
                                                      Msg, Op)

#: names of the per-cycle counter-delta vector emitted in telemetry
#: mode (cycle(with_telemetry=True) / run_cycles_telemetry), in order —
#: the same composition the cumulative Metrics update uses
TELEMETRY_COUNTERS = ("instrs_retired", "read_hits", "write_hits",
                      "read_misses", "write_misses", "upgrades",
                      "invalidations", "evictions")


#: field layout of the per-cycle message-ledger sample
#: (cycle(with_ledger=True) / run_cycles_telemetry(..., with_ledger=
#: True)): per-node dequeue record, per-(node, slot) enqueue record
#: with the post-arbitration accept mask, the frontend issue/latch
#: record and the wait-clear mask — everything obs/txntrace.py needs
#: to reconstruct causal transaction spans host-side. With
#: ``with_obs=True`` the sample additionally carries the
#: retire-observation record (obs_retire / obs_val: what value the
#: retiring node's own cache holds for the in-flight address at each
#: retire boundary — the raw input of the axiomatic consistency
#: checker, analysis/axioms.py)
LEDGER_FIELDS = ("deq_has", "deq_sender", "deq_type", "deq_addr",
                 "enq_accept", "enq_type", "enq_recv", "enq_addr",
                 "fetch", "issue", "op", "addr", "value", "unblocked")
LEDGER_OBS_FIELDS = ("obs_retire", "obs_val")

#: miss-taxonomy column order of the profile plane's ``miss_node`` /
#: ``miss_addr`` counters (cycle(with_profile=True) / run_cycles_profile)
#: — Hill & Smith's 3C classes with the conflict/capacity pair collapsed
#: (the sim's direct-mapped cache makes every non-cold tag eviction a
#: conflict) and extended with the two coherence classes a directory
#: protocol adds: a miss whose tag still matches but whose line an INV
#: killed, and a write hit on a SHARED line (upgrade — no data motion,
#: pure permission miss)
PROFILE_MISS_CLASSES = ("cold", "conflict_eviction",
                        "coherence_invalidation", "upgrade")

#: power-of-two buckets of the invalidation fan-out histogram (like
#: state.LAT_BUCKETS): bucket 0 = fan-out exactly 0 is never recorded
#: (a broadcast with no victims emits nothing); bucket b >= 1 = fan-out
#: in [2^(b-1), 2^b), so bucket 1 is single-victim, bucket 2 is 2-3
#: victims, ... — wide enough for a full-broadcast at 2^14 nodes
FANOUT_BUCKETS = 16


def profile_space(cfg: SystemConfig) -> int:
    """Size of the profile plane's address axis: the global address
    space ``N << block_bits`` (codec.make_address packs (node, block)
    into that range). Per-address planes index by raw address."""
    return cfg.num_nodes << cfg.block_bits


def profile_zeros(cfg: SystemConfig):
    """Zero-initialised profile-counter carry for run_cycles_profile.

    All planes accumulate across the scan (unlike the stacked
    per-cycle telemetry samples) so the capture cost is O(planes), not
    O(cycles x planes):

      rd / wr      [N, A]  per-(node, address) read / write accesses,
                           attributed at fetch
      ever         [N, A]  node has ever fetched address (cold-miss
                           classifier input)
      miss_node    [N, 4]  per-node miss counts, PROFILE_MISS_CLASSES
      miss_addr    [A, 4]  per-address miss counts, same columns
      inv_addr     [A]     invalidations attributed to the address
      inv_fanout   [16]    fan-out histogram, FANOUT_BUCKETS buckets
      wb_addr      [A]     dirty writebacks arriving at the home
      last_writer  [A]     last retiring writer node (-1 = none yet)
      mig_addr     [A]     ownership migrations (retired write by a
                           different node than the previous writer)
    """
    N = cfg.num_nodes
    A = profile_space(cfg)
    z = functools.partial(jnp.zeros, dtype=jnp.int32)
    return {
        "rd": z((N, A)), "wr": z((N, A)),
        "ever": jnp.zeros((N, A), bool),
        "miss_node": z((N, 4)), "miss_addr": z((A, 4)),
        "inv_addr": z((A,)), "inv_fanout": z((FANOUT_BUCKETS,)),
        "wb_addr": z((A,)),
        "last_writer": jnp.full((A,), -1, jnp.int32),
        "mig_addr": z((A,)),
    }

#: commit-path seam for the index-pressure auditor's seeded mutation
#: (analysis/mutations.INDEX_MUTATIONS.split_packed_scatter). True =
#: the shipped packed row commit (one scatter per state family, the
#: round-8 consolidation). False = the historical per-plane commit:
#: bit-identical semantics (each split scatter writes its own old value
#: where its column mask is unset, exactly like the packed where-select)
#: but 3x the gather/scatter traffic — invisible to every dynamic
#: oracle, caught only by the static index audit
#: (analysis/indexcheck.py). Production code never flips this.
_PACKED_COMMIT = True


def cycle(cfg: SystemConfig, state: SimState,
          with_events: bool = False, message_phase=None,
          with_telemetry: bool = False, with_ledger: bool = False,
          with_obs: bool = False, deliver_fn=None,
          with_profile: bool = False, prof=None):
    """Advance the whole machine by one cycle.

    Cross-sender arbitration order for this cycle's deliveries comes from
    ``state.arb_rank`` (see ops.mailbox.deliver and state.SimState) — the
    seedable schedule knob; identity by default.

    ``with_events=True`` additionally returns this cycle's event record
    (per-node instruction fetches and message dequeues — the data behind
    the reference's ``DEBUG_INSTR``/``DEBUG_MSG`` printf tracing,
    ``assignment.c:649-652,179-182``) as a dict of [N] arrays; the
    return becomes ``(state, events)``. The default path pays nothing.

    ``message_phase`` overrides the handler-phase function (same
    signature and return contract as ``handlers.message_phase``). The
    static model checker uses this to drive *mutated* handlers through
    the unmodified engine (analysis/mutations.py); production callers
    leave it None.

    ``with_telemetry=True`` additionally returns this cycle's telemetry
    sample (obs layer): the counter-delta vector (TELEMETRY_COUNTERS
    order), per-type message dequeues, mailbox queue-depth watermarks,
    directory-state occupancy and the miss-latency histogram delta —
    all fixed-shape device scalars/vectors, so lax.scan stacks them
    into a time-series without leaving the jit graph. With both event
    and telemetry capture on, the return is ``(state, events, telem)``.

    ``with_ledger=True`` additionally returns this cycle's message
    ledger (LEDGER_FIELDS): the per-node dequeue record, the full
    per-(node, slot) enqueue candidate planes plus their final accept
    mask (mailbox.deliver with_accept), the frontend issue/latch record
    and the wait-clear mask. Fixed-shape like telemetry, so the scan
    stacks it in the same single dispatch; obs/txntrace.py reconstructs
    causal transaction spans from it host-side. Output order with every
    capture on: ``(state, events, telem, ledger)``.

    ``deliver_fn`` overrides phase-3 delivery (same signature and
    return contract as ``mailbox.deliver``, minus ``with_accept``).
    The sharded transports (parallel/rdma_comm.make_routed_deliver)
    use this to route enqueue candidates across shards before a
    shard-local enqueue; single-device callers leave it None.

    ``with_profile=True`` threads the coherence-profiler counter plane
    (``prof``, a profile_zeros dict) through the cycle and appends the
    updated dict LAST in the output tuple: per-(node, address) access
    counts, the PROFILE_MISS_CLASSES miss taxonomy (classified against
    the PRE-commit cache tags plus the cumulative ``ever`` plane),
    invalidation fan-out, home-side dirty writebacks and
    ownership-migration counts. Everything lives in this `if` arm, so
    the default path's trace — and therefore its compiled HLO — is
    bit-identical with the plane off (tests/test_cohprof.py pins
    final-state parity; `bench-diff --bytes` pins the HLO cost
    vector).
    """
    if message_phase is None:
        message_phase = handlers.message_phase
    N = cfg.num_nodes
    rows = jnp.arange(N, dtype=jnp.int32)
    arb_rank = state.arb_rank

    # ---- phase 1: message handlers ---------------------------------------
    mv, new_head, new_count = mailbox.dequeue(cfg, state)
    m_upd, m_cand, inv_scatter, m_stats = message_phase(
        cfg, state, mv)

    # ---- phase 2: instruction frontend (only message-idle, unblocked) ----
    may_issue = ~mv.has_msg & ~state.waiting
    f_upd, f_req, f_stats = frontend.instruction_phase(cfg, state, may_issue)

    # ---- merge write intents (disjoint by node: msg XOR instr) -----------
    # ONE packed scatter per state family instead of five scalar-column
    # scatters (the PERF.md round-5 fragmentation leftover): the three
    # cache columns share (rows, cidx) and the memory/directory columns
    # share the handler's p_block, so each family stacks its planes,
    # gathers the old target row once, where-selects per column (a
    # column's unset mask keeps the old value — identical semantics to
    # the old per-column drop scatters) and commits one row scatter.
    C = cfg.cache_size
    cidx = jnp.where(mv.has_msg, m_upd["cache_idx"], f_upd["cache_idx"])

    def merged(m_int, f_int):
        return (jnp.where(mv.has_msg, m_int[0], f_int[0]),
                jnp.where(mv.has_msg, m_int[1], f_int[1]))

    cmasks, cvals = zip(
        merged(m_upd["cache_state"], f_upd["cache_state"]),
        merged(m_upd["cache_addr"], f_upd["cache_addr"]),
        merged(m_upd["cache_val"], f_upd["cache_val"]))
    any_c = cmasks[0] | cmasks[1] | cmasks[2]

    M = cfg.mem_size
    mm, mi, mval = m_upd["mem"]
    dm, di, dval = m_upd["dir_state"]
    bm, bi, bval = m_upd["dir_bv"]
    # the handlers emit one block index for all three (p_block); the
    # nested where keeps the first set mask's index authoritative
    hidx = jnp.where(mm, mi, jnp.where(dm, di, bi))
    any_h = mm | dm | bm

    if _PACKED_COMMIT:
        cache3 = jnp.stack([state.cache_state, state.cache_addr,
                            state.cache_val], axis=-1)    # [N, C, 3]
        old_c = cache3[rows, jnp.clip(cidx, 0, C - 1)]    # [N, 3]
        row_c = jnp.stack([jnp.where(m, v, old_c[:, k])
                           for k, (m, v) in
                           enumerate(zip(cmasks, cvals))],
                          axis=-1)
        cache3 = cache3.at[rows, jnp.where(any_c, cidx, C)].set(
            row_c, mode="drop")
        cache_state, cache_addr, cache_val = (
            cache3[..., 0], cache3[..., 1], cache3[..., 2])

        bv_i32 = jax.lax.bitcast_convert_type(state.dir_bitvec,
                                              jnp.int32)
        home = jnp.concatenate(
            [state.memory[..., None], state.dir_state[..., None],
             bv_i32],
            axis=-1)                                      # [N, M, 2+Wb]
        old_h = home[rows, jnp.clip(hidx, 0, M - 1)]      # [N, 2+Wb]
        row_h = jnp.concatenate(
            [jnp.where(mm, mval, old_h[:, 0])[:, None],
             jnp.where(dm, dval, old_h[:, 1])[:, None],
             jnp.where(bm[:, None],
                       jax.lax.bitcast_convert_type(bval, jnp.int32),
                       old_h[:, 2:])],
            axis=-1)
        home = home.at[rows, jnp.where(any_h, hidx, M)].set(
            row_h, mode="drop")
        memory, dir_state = home[..., 0], home[..., 1]
        dir_bitvec = jax.lax.bitcast_convert_type(home[..., 2:],
                                                  jnp.uint32)
    else:
        # de-consolidated commit — the _PACKED_COMMIT seam's mutant
        # path (never shipped): one scatter per plane, every split
        # scatter in a family sharing the same literal index vector,
        # each unset column writing back its own gathered old value.
        # Bit-identical to the packed path above; only the static
        # index inventory can tell them apart.
        idx_c = jnp.where(any_c, cidx, C)
        clip_c = jnp.clip(cidx, 0, C - 1)
        idx_h = jnp.where(any_h, hidx, M)
        clip_h = jnp.clip(hidx, 0, M - 1)

        def plane_commit(plane, mask, val, idx, clip):
            old = plane[rows, clip]
            return plane.at[rows, idx].set(
                jnp.where(mask, val, old), mode="drop")

        cache_state = plane_commit(state.cache_state, cmasks[0],
                                   cvals[0], idx_c, clip_c)
        cache_addr = plane_commit(state.cache_addr, cmasks[1],
                                  cvals[1], idx_c, clip_c)
        cache_val = plane_commit(state.cache_val, cmasks[2],
                                 cvals[2], idx_c, clip_c)
        memory = plane_commit(state.memory, mm, mval, idx_h, clip_h)
        dir_state = plane_commit(state.dir_state, dm, dval, idx_h,
                                 clip_h)
        old_bv = state.dir_bitvec[rows, clip_h]
        dir_bitvec = state.dir_bitvec.at[rows, idx_h].set(
            jnp.where(bm[:, None], bval, old_bv), mode="drop")

    waiting = (state.waiting & ~m_upd["wait_clear"]) | f_upd["wait_set"]
    # stall-watchdog input: cycle the current wait began (-1 when idle)
    waiting_since = jnp.where(
        waiting,
        jnp.where(f_upd["wait_set"], state.cycle, state.waiting_since),
        -1)

    fetch, l_op, l_addr, l_val = f_upd["latch"]
    cur_op = jnp.where(fetch, l_op, state.cur_op)
    cur_addr = jnp.where(fetch, l_addr, state.cur_addr)
    cur_val = jnp.where(fetch, l_val, state.cur_val)

    # ---- assemble candidates ---------------------------------------------
    S = cfg.out_slots
    Wm = cfg.msg_bitvec_words
    zero = jnp.zeros((N,), jnp.int32)
    zbv = jnp.zeros((N, Wm), jnp.uint32)
    pt, pr, pa, pv, ps, pd, pb = m_cand["pri"]
    # slot 0 is shared: message-phase primary XOR frontend request
    rt, rr_, ra, rv = f_req
    use_req = ~mv.has_msg
    s0_type = jnp.where(use_req, rt, pt)
    s0_recv = jnp.where(use_req, rr_, pr)
    s0_addr = jnp.where(use_req, ra, pa)
    s0_value = jnp.where(use_req, rv, pv)
    s0_second = jnp.where(use_req, zero, ps)
    s0_dirstate = jnp.where(use_req, zero, pd)
    s0_bitvec = jnp.where(use_req[:, None], zbv, pb)

    st_, sr_, sa_, sv_, ss_ = m_cand["sec"]
    et_, er_, ea_, ev_ = m_cand["ev"]

    def stack(slots):
        return jnp.stack(slots, axis=1)  # [N, S]

    if cfg.inv_mode == "mailbox":
        it_, ir_, ia_ = m_cand["inv"]
        c_type = jnp.concatenate(
            [stack([s0_type, st_]), it_, et_[:, None]], axis=1)
        c_recv = jnp.concatenate(
            [stack([s0_recv, sr_]), ir_, er_[:, None]], axis=1)
        c_addr = jnp.concatenate(
            [stack([s0_addr, sa_]), ia_, ea_[:, None]], axis=1)
        c_value = jnp.concatenate(
            [stack([s0_value, sv_]), jnp.zeros((N, N), jnp.int32),
             ev_[:, None]], axis=1)
        c_second = jnp.concatenate(
            [stack([s0_second, ss_]), jnp.zeros((N, N), jnp.int32),
             zero[:, None]], axis=1)
        c_dirstate = jnp.concatenate(
            [stack([s0_dirstate, zero]), jnp.zeros((N, N), jnp.int32),
             zero[:, None]], axis=1)
        c_bitvec = jnp.concatenate(
            [jnp.stack([s0_bitvec, zbv], axis=1),
             jnp.zeros((N, N, Wm), jnp.uint32), zbv[:, None]], axis=1)
    else:
        c_type = stack([s0_type, st_, et_])
        c_recv = stack([s0_recv, sr_, er_])
        c_addr = stack([s0_addr, sa_, ea_])
        c_value = stack([s0_value, sv_, ev_])
        c_second = stack([s0_second, ss_, zero])
        c_dirstate = stack([s0_dirstate, zero, zero])
        c_bitvec = jnp.stack([s0_bitvec, zbv, zbv], axis=1)

    cand = mailbox.Candidates(
        type=c_type, recv=c_recv,
        sender=jnp.broadcast_to(rows[:, None], c_type.shape),
        addr=c_addr, value=c_value, second=c_second, dirstate=c_dirstate,
        bitvec=c_bitvec)

    # ---- phase 3: delivery -----------------------------------------------
    if deliver_fn is not None:
        mb_upd, dropped, injected = deliver_fn(cfg, state, cand, arb_rank,
                                               new_head, new_count)
    else:
        mb_upd, dropped, injected = mailbox.deliver(cfg, state, cand,
                                                    arb_rank,
                                                    new_head, new_count,
                                                    with_accept=with_ledger)
    enq_accept = mb_upd.pop("enq_accept", None)

    # Vectorized INV application (scale path; reference assumes INV never
    # fails and tracks no acks, assignment.c:358-361). The broadcast for
    # address a can only originate from home(a), and a home processes at
    # most one message per cycle, so each cached line needs exactly one
    # lookup: did my home broadcast my tag this cycle, with my bit set?
    # O(N*C) gathers — no cross-node product.
    inv_applied = jnp.zeros((), jnp.int32)
    kill_live = None                  # [N, C] live lines the INV killed
    if inv_scatter is not None:
        im, ia, ibv = inv_scatter                       # [N], [N], [N, W]
        h = jnp.clip(codec.home_node(cfg, cache_addr), 0, N - 1)  # [N, C]
        active = im[h] & (ia[h] == cache_addr)          # sentinel never matches
        tw = jnp.broadcast_to((rows // 32)[:, None], h.shape)
        tb = (rows % 32).astype(jnp.uint32)[:, None]
        word = ibv[h, tw]                               # [N, C] u32
        kill = active & (((word >> tb) & 1) == 1)
        kill_live = kill & (cache_state != int(CacheState.INVALID))
        inv_applied = jnp.sum(kill_live).astype(jnp.int32)
        cache_state = jnp.where(kill, int(CacheState.INVALID), cache_state)

    # ---- metrics ---------------------------------------------------------
    # ONE stacked reduction for every per-node counter delta, including
    # the per-message-type histogram (a one-hot instead of a scatter-add)
    # and the miss-latency histogram — separate sums/scatters each cost
    # a kernel dispatch (PERF.md)
    mt = state.metrics
    has, t = m_stats["msg_type_onehot"]
    K = mt.msgs_processed.shape[0]                # message-type count
    type_onehot = (jnp.arange(K, dtype=jnp.int32)[:, None] == t[None, :]) \
        & has[None, :]                                          # [K, N]
    # miss-latency histogram input: nodes whose coherence wait cleared
    # this cycle; latency = issue cycle (waiting_since) to retire cycle,
    # bucketed as floor(log2) into LAT_BUCKETS power-of-two bins
    unblocked = m_stats["unblocked"]
    lat = jnp.maximum(state.cycle - state.waiting_since, 1)
    bucket = jnp.clip(31 - jax.lax.clz(lat), 0, LAT_BUCKETS - 1)
    lat_onehot = (jnp.arange(LAT_BUCKETS, dtype=jnp.int32)[:, None]
                  == bucket[None, :]) & unblocked[None, :]      # [B, N]
    counters = jnp.stack([
        f_stats["issued"], f_stats["read_hits"], f_stats["write_hits"],
        f_stats["read_misses"], f_stats["write_misses"],
        f_stats["upgrades"], m_stats["invalidations"],
        m_stats["evictions"],
    ])                                                          # [8, N]
    deltas = jnp.sum(jnp.concatenate(
        [counters, type_onehot, lat_onehot]).astype(jnp.int32),
        axis=1)                                     # [8 + K + B]
    depth_peak = jnp.max(mb_upd["mb_count"])
    metrics = mt.replace(
        cycles=mt.cycles + 1,
        instrs_retired=mt.instrs_retired + deltas[0],
        read_hits=mt.read_hits + deltas[1],
        write_hits=mt.write_hits + deltas[2],
        read_misses=mt.read_misses + deltas[3],
        write_misses=mt.write_misses + deltas[4],
        upgrades=mt.upgrades + deltas[5],
        msgs_processed=mt.msgs_processed + deltas[8:8 + K],
        msgs_dropped=mt.msgs_dropped + dropped,
        msgs_injected_dropped=mt.msgs_injected_dropped + injected,
        invalidations=mt.invalidations + deltas[6] + inv_applied,
        evictions=mt.evictions + deltas[7],
        lat_hist=mt.lat_hist + deltas[8 + K:],
        mb_depth_peak=jnp.maximum(mt.mb_depth_peak, depth_peak),
    )

    # ---- profile plane (coherence profiler, obs/cohprof.py) --------------
    # accumulating counters, not per-cycle samples: every plane below is
    # added into the carried `prof` dict, so the scan output is O(planes)
    new_prof = None
    if with_profile:
        A = profile_space(cfg)
        issued = f_stats["issued"]
        rh, wh = f_stats["read_hits"], f_stats["write_hits"]
        rm, wm = f_stats["read_misses"], f_stats["write_misses"]
        upg = f_stats["upgrades"]
        miss = rm | wm
        addr_f = jnp.clip(l_addr, 0, A - 1)
        # miss taxonomy against the PRE-commit tags: tag matches but the
        # line is INVALID -> a coherence invalidation killed it; no
        # matching tag and this node never fetched the address -> cold;
        # otherwise a conflict eviction displaced it. Upgrades (write
        # hit on SHARED) are the pure permission-miss column.
        ci_f = jnp.clip(codec.cache_index(cfg, l_addr), 0, C - 1)
        tag_f = state.cache_addr[rows, ci_f]
        st_f = state.cache_state[rows, ci_f]
        coh = miss & (tag_f == l_addr) & (st_f == int(CacheState.INVALID))
        seen = prof["ever"][rows, addr_f]
        cold = miss & ~coh & ~seen
        conf = miss & ~coh & seen
        classes = jnp.stack([cold, conf, coh, upg],
                            axis=1).astype(jnp.int32)            # [N, 4]
        any_cls = cold | conf | coh | upg
        rd = prof["rd"].at[rows, jnp.where(rh | rm, addr_f, A)].add(
            1, mode="drop")
        wr = prof["wr"].at[rows, jnp.where(wh | wm, addr_f, A)].add(
            1, mode="drop")
        ever = prof["ever"].at[rows, jnp.where(issued, addr_f, A)].set(
            True, mode="drop")
        miss_node = prof["miss_node"] + classes
        miss_addr = prof["miss_addr"].at[
            jnp.where(any_cls, addr_f, A)].add(classes, mode="drop")

        bins = jnp.arange(FANOUT_BUCKETS, dtype=jnp.int32)

        def fan_hist(fan):
            # power-of-two bucket per broadcasting home; fan == 0 (no
            # victims / no broadcast) records nothing
            fb = jnp.clip(32 - jax.lax.clz(jnp.maximum(fan, 1)),
                          1, FANOUT_BUCKETS - 1)
            oh = (bins[:, None] == fb[None, :]) & (fan > 0)[None, :]
            return jnp.sum(oh.astype(jnp.int32), axis=1)

        inv_addr_p, inv_fan = prof["inv_addr"], prof["inv_fanout"]
        if kill_live is not None:
            # scatter mode: victims and fan-out both come from the dense
            # kill plane of this same cycle, so sum(inv_addr) tracks the
            # inv_applied metric exactly
            tags = jnp.clip(cache_addr, 0, A - 1)
            inv_addr_p = inv_addr_p.at[
                jnp.where(kill_live, tags, A)].add(1, mode="drop")
            fan = jnp.zeros((N,), jnp.int32).at[h].add(
                kill_live.astype(jnp.int32), mode="drop")
            inv_fan = inv_fan + fan_hist(fan)
        else:
            # mailbox mode: victims counted where the INV dequeues and
            # its tag still matches (the same mask the invalidations
            # metric sums); fan-out counted send-side at the home that
            # emitted the broadcast slots this cycle
            ci_m = jnp.clip(codec.cache_index(cfg, mv.addr), 0, C - 1)
            deq_inv = (mv.has_msg & (mv.type == int(Msg.INV))
                       & (state.cache_addr[rows, ci_m] == mv.addr))
            inv_addr_p = inv_addr_p.at[
                jnp.where(deq_inv, jnp.clip(mv.addr, 0, A - 1), A)].add(
                1, mode="drop")
            sent = m_cand["inv"][0] == int(Msg.INV)              # [N, N]
            inv_fan = inv_fan + fan_hist(
                jnp.sum(sent.astype(jnp.int32), axis=1))

        # dirty writebacks, counted once at the home's dequeue (FLUSH /
        # FLUSH_INVACK also reach the requester as the fill reply; the
        # home copy is the memory write)
        wb = (mv.has_msg
              & ((mv.type == int(Msg.FLUSH))
                 | (mv.type == int(Msg.FLUSH_INVACK))
                 | (mv.type == int(Msg.EVICT_MODIFIED)))
              & (codec.home_node(cfg, mv.addr) == rows))
        wb_addr = prof["wb_addr"].at[
            jnp.where(wb, jnp.clip(mv.addr, 0, A - 1), A)].add(
            1, mode="drop")

        # ownership migration: a WRITE retires (immediately on an M/E
        # hit, or at unblock for misses/upgrades — the two are exclusive
        # per node, drain-before-fetch) on an address whose previous
        # retiring writer was a different node
        w_ret = (wh & ~upg) | (unblocked & (state.cur_op == int(Op.WRITE)))
        w_a = jnp.clip(jnp.where(fetch, l_addr, state.cur_addr), 0, A - 1)
        prev = prof["last_writer"][w_a]
        mig = w_ret & (prev >= 0) & (prev != rows)
        mig_addr = prof["mig_addr"].at[
            jnp.where(mig, w_a, A)].add(1, mode="drop")
        # same-address write collisions in one cycle cannot happen (one
        # owner in M/E; one unblock per fill), but keep the update
        # deterministic anyway: lowest node id wins via scatter-min
        sent_max = jnp.iinfo(jnp.int32).max
        cand_w = jnp.full((A,), sent_max, jnp.int32).at[
            jnp.where(w_ret, w_a, A)].min(rows, mode="drop")
        last_writer = jnp.where(cand_w != sent_max, cand_w,
                                prof["last_writer"])

        new_prof = {
            "rd": rd, "wr": wr, "ever": ever,
            "miss_node": miss_node, "miss_addr": miss_addr,
            "inv_addr": inv_addr_p, "inv_fanout": inv_fan,
            "wb_addr": wb_addr,
            "last_writer": last_writer, "mig_addr": mig_addr,
        }

    new_state = state.replace(
        cache_addr=cache_addr, cache_val=cache_val, cache_state=cache_state,
        memory=memory, dir_state=dir_state, dir_bitvec=dir_bitvec,
        instr_idx=f_upd["new_idx"],
        cur_op=cur_op, cur_addr=cur_addr, cur_val=cur_val, waiting=waiting,
        waiting_since=waiting_since,
        cycle=state.cycle + 1, metrics=metrics, **mb_upd)
    if not (with_events or with_telemetry or with_ledger or with_profile):
        return new_state
    out = (new_state,)
    if with_events:
        events = {
            # instruction fetch (assignment.c:649-652)
            "fetch": fetch, "op": l_op, "addr": l_addr, "value": l_val,
            # message dequeue (assignment.c:179-182)
            "msg": mv.has_msg, "msg_sender": mv.sender,
            "msg_type": mv.type, "msg_addr": mv.addr,
        }
        out = out + (events,)
    if with_telemetry:
        # fixed-shape per-cycle sample; stacked by lax.scan into the
        # obs time-series (obs/timeseries.py renders it host-side)
        telem = {
            # counter deltas in TELEMETRY_COUNTERS order (invalidations
            # include the scatter-mode INV fan-out, like the cumulative
            # metric)
            "counters": jnp.stack([
                deltas[0], deltas[1], deltas[2], deltas[3], deltas[4],
                deltas[5], deltas[6] + inv_applied, deltas[7]]),   # [8]
            "msgs_processed": deltas[8:8 + K],                     # [K]
            "msgs_dropped": dropped,
            "msgs_injected_dropped": injected,
            "lat_hist": deltas[8 + K:],                            # [B]
            # mailbox queue-depth watermarks after this cycle's delivery
            "queue_depth_max": depth_peak,
            "queue_depth_total": jnp.sum(mb_upd["mb_count"]),
            # directory-state occupancy over all (home, block) entries
            "dir_occupancy": jnp.stack(
                [jnp.sum(dir_state == int(s)).astype(jnp.int32)
                 for s in (DirState.EM, DirState.S, DirState.U)]), # [3]
            "waiting_nodes": jnp.sum(waiting).astype(jnp.int32),
        }
        out = out + (telem,)
    if with_ledger:
        # one fixed-shape sample per cycle (LEDGER_FIELDS); everything
        # below is a value the cycle already computed — the only extra
        # work is deliver's accept-mask un-permute scatter plus the
        # narrowing casts: the scan stacks T of these samples, so the
        # stacking bytes are the ledger's dominant cost and every
        # field has a small static range (types < 14, node ids < N,
        # addresses <= invalid_address)
        n_dt = (jnp.int8 if cfg.num_nodes <= 127 else
                jnp.int16 if cfg.num_nodes <= 32767 else jnp.int32)
        a_dt = (jnp.int16 if cfg.invalid_address <= 32767
                else jnp.int32)
        ledger = {
            # phase-1 dequeue record (masked by deq_has)
            "deq_has": mv.has_msg,
            "deq_sender": mv.sender.astype(n_dt),
            "deq_type": mv.type.astype(jnp.int8),
            "deq_addr": mv.addr.astype(a_dt),
            # phase-3 enqueue record: candidate planes + final accept
            # mask in (sender, program-order-slot) layout
            "enq_accept": enq_accept,
            "enq_type": c_type.astype(jnp.int8),
            "enq_recv": c_recv.astype(n_dt),
            "enq_addr": c_addr.astype(a_dt),
            # phase-2 frontend record: fetch latch and whether this
            # fetch opened a coherence wait (miss/upgrade = txn issue)
            "fetch": fetch, "issue": f_upd["wait_set"],
            "op": l_op.astype(jnp.int8),
            "addr": l_addr.astype(a_dt), "value": l_val,
            # wait cleared this cycle (span end)
            "unblocked": m_stats["unblocked"],
        }
        if with_obs:
            # retire observation: an instruction retires either at its
            # fetch cycle (hit: fetch without a wait) or at its unblock
            # cycle (miss/upgrade fill) — the two are exclusive per node
            # per cycle (drain-before-fetch). obs_val is what the node's
            # own cache holds for the in-flight address at that boundary
            # (post-update arrays, the value the reference's printf dump
            # would show); -1 = line absent/INVALID at retire. Only the
            # axiomatic consistency checker reads these, so only its
            # captures pay for the extra gathers.
            ledger["obs_retire"] = ((fetch & ~f_upd["wait_set"])
                                    | m_stats["unblocked"])
            ledger["obs_val"] = jnp.where(
                (cache_addr[rows, codec.cache_index(cfg, cur_addr)]
                 == cur_addr)
                & (cache_state[rows, codec.cache_index(cfg, cur_addr)]
                   != int(CacheState.INVALID)),
                cache_val[rows, codec.cache_index(cfg, cur_addr)],
                -1).astype(jnp.int16)
        out = out + (ledger,)
    if with_profile:
        out = out + (new_prof,)
    return out


# -- runners ---------------------------------------------------------------

_RO_FIELDS = ("instr_op", "instr_addr", "instr_val", "issue_delay",
              "issue_period", "arb_rank", "order_rank")


def _ro_outside(state: SimState):
    """(loop-carry state, real-fields dict, placeholders dict): large
    read-only arrays in a scan/while carry get copied every iteration
    when XLA cannot prove aliasing (PERF.md) — the instruction trace and
    schedule knobs never change during a run, so the loops carry
    zero-width placeholders and bodies close over the real arrays
    (restore with .replace(**ro) before cycle, re-blank with
    .replace(**placeholders) after)."""
    ro = {f: getattr(state, f) for f in _RO_FIELDS}
    placeholders = {
        f: jnp.zeros(v.shape[:-1] + (0,), v.dtype) if v.ndim > 1
        else jnp.zeros((0,), v.dtype)
        for f, v in ro.items()}
    return state.replace(**placeholders), ro, placeholders


@functools.partial(jax.jit, static_argnums=(0, 2, 3))
def run_cycles_traced(cfg: SystemConfig, state: SimState,
                      num_cycles: int, message_phase=None):
    """Scan `num_cycles` cycles collecting the per-cycle event record.

    Returns (state, events) with events a dict of [num_cycles, N]
    arrays — the structured replacement for the reference's printf
    tracing (utils.eventlog formats them into the exact
    ``instruction_order.txt`` line format).

    ``message_phase`` is the same static handler-phase override `cycle`
    takes — it lets the differential fuzzer's shrinker (analysis/
    shrink.py) capture an event trace of a *mutated* engine run.
    """

    carry0, ro, blanks = _ro_outside(state)

    def body(s, _):
        out, ev = cycle(cfg, s.replace(**ro), with_events=True,
                        message_phase=message_phase)
        return out.replace(**blanks), ev

    final, events = jax.lax.scan(body, carry0, None, length=num_cycles)
    return final.replace(**ro), events


@functools.partial(jax.jit, static_argnums=(0, 2, 3, 4))
def run_cycles_telemetry(cfg: SystemConfig, state: SimState,
                         num_cycles: int, message_phase=None,
                         with_ledger: bool = False):
    """Scan `num_cycles` cycles collecting the per-cycle telemetry.

    Returns (state, telem) with telem a dict of [num_cycles, ...]
    arrays (see cycle's with_telemetry contract) — the on-device
    time-series behind ``cache-sim stats --timeseries`` and
    obs/timeseries.py. Shape-static: every sample is fixed-size, so
    the jit graph is independent of run length apart from the scan
    trip count.

    ``message_phase`` is the same static handler-phase override `cycle`
    takes — the flight recorder (obs/flight.py) uses it to capture
    telemetry of the fuzzer's *mutated* engine runs.

    ``with_ledger=True`` (static) stacks the per-cycle message ledger
    alongside and returns ``(state, telem, ledger)`` — still ONE
    device dispatch per call; the ledger planes ride the same scan.
    obs/txntrace.py captures this in host-side chunks.
    """
    carry0, ro, blanks = _ro_outside(state)

    if with_ledger:
        def body(s, _):
            out, tel, led = cycle(cfg, s.replace(**ro),
                                  with_telemetry=True, with_ledger=True,
                                  message_phase=message_phase)
            return out.replace(**blanks), (tel, led)

        final, (telem, ledger) = jax.lax.scan(body, carry0, None,
                                              length=num_cycles)
        return final.replace(**ro), telem, ledger

    def body(s, _):
        out, tel = cycle(cfg, s.replace(**ro), with_telemetry=True,
                         message_phase=message_phase)
        return out.replace(**blanks), tel

    final, telem = jax.lax.scan(body, carry0, None, length=num_cycles)
    return final.replace(**ro), telem


@functools.partial(jax.jit, static_argnums=(0, 2, 3, 4))
def run_cycles_ledger(cfg: SystemConfig, state: SimState,
                      num_cycles: int, message_phase=None,
                      with_obs: bool = False):
    """Scan `num_cycles` cycles collecting ONLY the message ledger.

    Same capture as ``run_cycles_telemetry(..., with_ledger=True)``
    minus the telemetry planes (counter deltas, occupancy scans) — the
    ledger samples are bit-identical either way, this path just skips
    work the caller will not read. obs/txntrace.capture runs on this;
    returns ``(state, ledger)``. ``with_obs=True`` (static) adds the
    LEDGER_OBS_FIELDS retire-observation planes for the axiomatic
    consistency checker.
    """
    carry0, ro, blanks = _ro_outside(state)

    def body(s, _):
        out, led = cycle(cfg, s.replace(**ro), with_ledger=True,
                         with_obs=with_obs, message_phase=message_phase)
        return out.replace(**blanks), led

    final, ledger = jax.lax.scan(body, carry0, None, length=num_cycles)
    return final.replace(**ro), ledger


@functools.partial(jax.jit, static_argnums=(0, 2))
def run_cycles(cfg: SystemConfig, state: SimState,
               num_cycles: int) -> SimState:
    """Run a fixed number of cycles under lax.scan (bench path)."""
    carry0, ro, blanks = _ro_outside(state)

    def body(s, _):
        out = cycle(cfg, s.replace(**ro))
        return out.replace(**blanks), None

    final, _ = jax.lax.scan(body, carry0, None, length=num_cycles)
    return final.replace(**ro)


@functools.partial(jax.jit, static_argnums=(0, 2, 3))
def run_cycles_profile(cfg: SystemConfig, state: SimState,
                       num_cycles: int, message_phase=None):
    """Scan `num_cycles` cycles accumulating the coherence-profile plane.

    Returns ``(state, prof)`` with ``prof`` the profile_zeros dict
    after accumulation (see cycle's with_profile contract). Unlike the
    telemetry/ledger runners the capture rides the scan CARRY, not the
    stacked output, so the transfer cost is independent of run length —
    obs/cohprof.py reduces the planes host-side into the
    ``cache-sim/profile/v1`` doc. ``message_phase`` is `cycle`'s static
    handler-phase override (the flight recorder profiles mutant runs
    with it).
    """
    carry0, ro, blanks = _ro_outside(state)
    prof0 = profile_zeros(cfg)

    def body(carry, _):
        s, p = carry
        out, p2 = cycle(cfg, s.replace(**ro), message_phase=message_phase,
                        with_profile=True, prof=p)
        return (out.replace(**blanks), p2), None

    (final, prof), _ = jax.lax.scan(body, (carry0, prof0), None,
                                    length=num_cycles)
    return final.replace(**ro), prof


def _run_quiescence(cfg: SystemConfig, state: SimState, chunk: int,
                    max_cycles: int, message_phase=None,
                    deliver_fn=None) -> SimState:
    """while(not quiescent and cycle < max_cycles): scan `chunk` cycles.

    The termination predicate runs once per chunk, so a run may exceed
    `max_cycles` (or quiescence) by up to chunk-1 cycles; chunk=1 stops
    exactly at the cap. A quiescent state is a fixpoint of `cycle` apart
    from the cycle counters, so quiescence overshoot never changes the
    final state (tests/test_admission.py pins this).
    """

    carry0, ro, blanks = _ro_outside(state)

    def body(s, _):
        out = cycle(cfg, s.replace(**ro), message_phase=message_phase,
                    deliver_fn=deliver_fn)
        return out.replace(**blanks), None

    def cond(s):
        return (~s.quiescent()) & (s.cycle < max_cycles)

    def chunk_body(s):
        s, _ = jax.lax.scan(body, s, None, length=chunk)
        return s

    final = jax.lax.while_loop(cond, chunk_body, carry0)
    return final.replace(**ro)


@functools.partial(jax.jit, static_argnums=(0, 2, 3))
def run_to_quiescence(cfg: SystemConfig, state: SimState,
                      max_cycles: int = 100_000,
                      message_phase=None) -> SimState:
    """Run until no work remains, stopping exactly at max_cycles.

    Replaces the reference's sleep-1s-then-kill harness
    (``test3.sh:9-12``) with an exact fixpoint. ``message_phase`` is
    `cycle`'s static handler-phase override — the differential fuzzer
    (analysis/fuzz.py) uses it to run a seeded-mutant engine to
    quiescence against the clean native oracle.
    """
    return _run_quiescence(cfg, state, 1, max_cycles, message_phase)


@functools.partial(jax.jit, static_argnums=(0, 2, 3, 4, 5))
def run_chunked_to_quiescence(cfg: SystemConfig, state: SimState,
                              chunk: int = 32,
                              max_cycles: int = 100_000,
                              message_phase=None,
                              deliver_fn=None) -> SimState:
    """Quiescence fixpoint with a `chunk`-cycle scan per while iteration.

    One device dispatch for the whole run — essential on high-latency
    device links (the axon tunnel makes each eager op a network round
    trip) — and the quiescence reduction amortizes over the chunk. May
    run up to chunk-1 cycles past quiescence or max_cycles (see
    _run_quiescence). ``message_phase`` is `cycle`'s static
    handler-phase override (protocol-variant solo runs in serve.py);
    ``deliver_fn`` is its static phase-3 delivery override (the
    explicit sharded transports, parallel/rdma_comm) — both hash by
    identity, so callers must build them once per config.
    """
    return _run_quiescence(cfg, state, chunk, max_cycles, message_phase,
                           deliver_fn)


# -- batched wave runner (serving layer) -----------------------------------

def batched_wave(cfg: SystemConfig, bstate: SimState, chunk: int,
                 max_cycles: int, message_phase=None) -> SimState:
    """Run a [B, ...] batch of independent machines to quiescence.

    The serving layer's wave step (serve.py): one vmapped cycle over
    the job axis inside the same chunked-scan-in-while-loop shape as
    _run_quiescence, with per-job early-exit masking — each cycle,
    jobs that are already quiescent (or out of their `max_cycles`
    budget) keep their OLD state instead of the stepped one. Because a
    quiescent state is a fixpoint of `cycle` apart from the cycle
    counters, the mask's only real effect is freezing those counters:
    every job's final state (cycle count and metrics included) is
    bit-identical to running it solo, which is the per-job parity gate
    (tests/test_serve.py). The wave keeps dispatching chunks until
    every job is done.

    Unjitted on purpose — run_wave_to_quiescence is the production
    wrapper (donated batch state, one compile per slot shape); the
    recompile guard (analysis/lint_jaxpr.py) wraps this function in a
    fresh jit to prove heterogeneous same-shape waves share one trace.
    """
    carry0, ro, blanks = _ro_outside(bstate)
    step_all = jax.vmap(lambda s: cycle(cfg, s, message_phase=message_phase))
    done_mask = jax.vmap(lambda s: s.quiescent())

    def body(s, _):
        full = s.replace(**ro)
        done = done_mask(full) | (full.cycle >= max_cycles)
        stepped = step_all(full)

        def freeze(old, new):
            return jnp.where(
                done.reshape(done.shape + (1,) * (new.ndim - 1)), old, new)

        out = jax.tree.map(freeze, full, stepped)
        return out.replace(**blanks), None

    def cond(s):
        full = s.replace(**ro)
        live = (~done_mask(full)) & (full.cycle < max_cycles)
        return jnp.any(live)

    def chunk_body(s):
        s, _ = jax.lax.scan(body, s, None, length=chunk)
        return s

    final = jax.lax.while_loop(cond, chunk_body, carry0)
    return final.replace(**ro)


@functools.partial(jax.jit, static_argnums=(0, 2, 3, 4),
                   donate_argnums=(1,))
def run_wave_to_quiescence(cfg: SystemConfig, bstate: SimState,
                           chunk: int = 32,
                           max_cycles: int = 100_000,
                           message_phase=None) -> SimState:
    """jit-compiled batched_wave with the batch state donated.

    Donation lets XLA reuse the incoming wave's buffers for the
    outgoing ones (the batch tensor dominates serve memory at large
    slot shapes), and the static args pin ONE compile per
    (slot config, chunk, budget, protocol phase) — the serving loop
    swaps jobs in and out of the same compiled wave indefinitely
    (guarded by analysis/lint_jaxpr.recompile_guard). The caller must
    not reuse the donated input batch.
    """
    return batched_wave(cfg, bstate, chunk, max_cycles, message_phase)


def batched_wave_chunk(cfg: SystemConfig, bstate: SimState, chunk: int,
                       max_cycles: int, message_phase=None):
    """Exactly one `chunk`-cycle masked slice of a batched wave.

    Same per-cycle freeze body as batched_wave (done slots keep their
    OLD state each cycle, so a finished job's state and cycle count
    stay bit-identical to its solo run), but WITHOUT the outer
    while-loop: the scheduler owns the loop. That is the continuous-
    admission primitive (daemon/core.py): between chunks the daemon
    swaps finished jobs out and admits queued jobs into the freed
    slots via ``state.set_state`` while the other slots are still
    mid-flight — the wave never stops for stragglers. Returns
    ``(bstate, quiescent, done)``: the stepped batch plus the per-slot
    quiescence mask and the resolved mask (quiescent OR out of cycle
    budget), both [B] bools computed on device so the host fetch is
    two tiny arrays, not the batch.
    """
    carry0, ro, blanks = _ro_outside(bstate)
    step_all = jax.vmap(lambda s: cycle(cfg, s, message_phase=message_phase))
    done_mask = jax.vmap(lambda s: s.quiescent())

    def body(s, _):
        full = s.replace(**ro)
        done = done_mask(full) | (full.cycle >= max_cycles)
        stepped = step_all(full)

        def freeze(old, new):
            return jnp.where(
                done.reshape(done.shape + (1,) * (new.ndim - 1)), old, new)

        out = jax.tree.map(freeze, full, stepped)
        return out.replace(**blanks), None

    s, _ = jax.lax.scan(body, carry0, None, length=chunk)
    full = s.replace(**ro)
    quiet = done_mask(full)
    return full, quiet, quiet | (full.cycle >= max_cycles)


@functools.partial(jax.jit, static_argnums=(0, 2, 3, 4),
                   donate_argnums=(1,))
def run_wave_chunk(cfg: SystemConfig, bstate: SimState, chunk: int = 16,
                   max_cycles: int = 100_000, message_phase=None):
    """jit-compiled batched_wave_chunk with the batch state donated.

    One compile per (slot config, chunk, budget, protocol phase) —
    the daemon keeps one compiled chunk stepper per shape bucket and
    swaps jobs through it indefinitely (the bucketed prong of
    analysis/lint_jaxpr.recompile_guard pins this). The caller must
    not reuse the donated input batch; extraction of finished slots
    reads the RETURNED batch (index_state) before the next chunk call
    donates it back.
    """
    return batched_wave_chunk(cfg, bstate, chunk, max_cycles,
                              message_phase)
