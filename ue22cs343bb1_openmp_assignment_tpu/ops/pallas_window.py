"""Pallas TPU kernels for the multi-transaction window round.

`_round_step_multi`'s window fold — the sequential W-step
classification that sizes each node's transaction window — is
node-local, gather-free under a procedural workload, and the
fusion-fragmented part of the round (~74 XLA fusions plus dozens of
small stacking copies at K=3, PERF.md). Here it runs as TWO fused
kernels around the unavoidable claim scatter / row gather:

* **window kernel** (pre-claim): runs the fold, emits the per-slot
  transaction records ([K, tile] rows), the per-step hit-probe /
  dependent-write records ([W, tile]), and the prefix cache.
* **replay kernel** (post-claim): re-runs the same fold (same helper,
  bit-identical classification) and applies the retired prefix —
  truncation point and resolved fill values now known — producing the
  committed cache and the retirement counters.

Between them the claim scatter-min, the one row gather, win/truncation
resolution, transaction outcomes, and the commit scatter stay in XLA
(they are gathers/scatters either way), computed in the kernels'
transposed [K, N] layout so no per-field transposes appear.

The fold helper mirrors `_round_step_multi`'s fold line for line with
cache state as per-line [1, T] rows (the lane axis is the node tile):
`tests/test_pallas_window.py` pins full rounds bit-identical to the
XLA path. Enabled by `cfg.pallas_burst` (procedural workloads,
`txn_width > 1`, no event tracing).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ue22cs343bb1_openmp_assignment_tpu import codec
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.procedural import procedural_instr
from ue22cs343bb1_openmp_assignment_tpu.types import CacheState, DirState, Op

from ue22cs343bb1_openmp_assignment_tpu.ops.sync_engine import (
    ACT_DOWNGRADE, ACT_KILL, ACT_NONE, ACT_PROMOTE, DM_ACT, DM_CLAIM,
    DM_COLS, DM_COUNT, DM_MEM, DM_OWNER, DM_REQ, DM_STATE, SyncState,
    _round_key, claim_max_rounds)


def _fold(cfg: SystemConfig, T: int, node, idx, cnt, ca, cv, cs):
    """The window fold on [1, T] rows; returns (steps, cv_pre_rows).

    `ca`/`cv`/`cs` are lists of C per-line [1, T] rows. Mirrors the
    fold in `_round_step_multi` exactly (same helpers, same formula
    order) so both paths classify bit-identically.
    """
    C, K = cfg.cache_size, cfg.txn_width
    W = cfg.drain_depth + K
    E = cfg.num_nodes << cfg.block_bits
    INV = int(CacheState.INVALID)
    MOD = int(CacheState.MODIFIED)
    EXC = int(CacheState.EXCLUSIVE)
    SHD = int(CacheState.SHARED)
    one = jnp.ones_like(idx)

    ca_f, cv_f, cs_f = list(ca), list(cv), list(cs)
    fo_f = [one * K for _ in range(C)]
    cv_pre = list(cv_f)
    frozen = jnp.zeros_like(idx, bool)
    stopped = jnp.zeros_like(idx, bool)
    n_txn = jnp.zeros_like(idx)
    fills, victs, steps = [], [], []
    for k in range(W):
        w_idx = idx + k
        live = w_idx < cnt
        oa, val = procedural_instr(cfg, node, w_idx)
        op, addr = oa >> 28, oa & 0x0FFFFFFF
        ci = codec.cache_index(cfg, addr)
        l_addr, l_val, l_state, l_fo = ca_f[0], cv_f[0], cs_f[0], fo_f[0]
        for c in range(1, C):
            m = ci == c
            l_addr = jnp.where(m, ca_f[c], l_addr)
            l_val = jnp.where(m, cv_f[c], l_val)
            l_state = jnp.where(m, cs_f[c], l_state)
            l_fo = jnp.where(m, fo_f[c], l_fo)
        tag_ok = (l_addr == addr) & (l_state != INV)
        is_rd, is_wr = op == int(Op.READ), op == int(Op.WRITE)
        rd_hit = live & is_rd & tag_ok
        wr_hit = live & is_wr & tag_ok & ((l_state == MOD)
                                          | (l_state == EXC))
        wr_dep = live & is_wr & tag_ok & (l_state == SHD) & (l_fo < K)
        hit = rd_hit | wr_hit | wr_dep | (live & (op == int(Op.NOP)))
        upg = live & is_wr & tag_ok & (l_state == SHD) & (l_fo == K)
        rd_miss = live & is_rd & ~tag_ok
        wr_miss = live & is_wr & ~tag_ok
        e1 = jnp.clip(addr, 0, E - 1)
        has_victim = ~tag_ok & (l_state != INV) & (l_addr != addr)
        e2 = jnp.clip(l_addr, 0, E - 1)
        own1 = jnp.zeros_like(idx, bool)
        dup = jnp.zeros_like(idx, bool)
        rel_ord = one * K
        acq_base = one * K
        for te, tv, tord in fills:
            own1 |= tv & (te == e1)
            dup |= tv & (te == e1)
            rel_ord = jnp.where(tv & has_victim & (te == e2), tord,
                                rel_ord)
        for te, tv, tord, telig in victs:
            m = tv & (te == e1)
            dup |= m & ~telig
            acq_base = jnp.where(m & telig, tord, acq_base)
        hc = hit & ~stopped & frozen & ~own1
        hit_ok = (hit & ~stopped & (~frozen | own1)) | hc
        txn = (rd_miss | wr_miss | upg) & ~stopped
        ok = txn & ~dup & (n_txn < K)
        rel_ord = jnp.where(ok, rel_ord, K)
        acq_base = jnp.where(ok, acq_base, K)
        stop_now = ~hit_ok & ~ok & ~stopped
        wlike_f = ok & (wr_miss | upg)
        reacq_rd = ok & rd_miss & (acq_base == K)
        for c in range(C):
            mc = ci == c
            wm = ((wr_hit | wr_dep) & hit_ok) & mc
            cv_f[c] = jnp.where(wm, val, cv_f[c])
            cs_f[c] = jnp.where(wm, MOD, cs_f[c])
            cv_pre[c] = jnp.where(frozen, cv_pre[c], cv_f[c])
        frozen = frozen | ok
        for c in range(C):
            mc = ci == c
            fm = ok & mc
            ca_f[c] = jnp.where(fm, addr, ca_f[c])
            cv_f[c] = jnp.where(wlike_f & mc, val, cv_f[c])
            cs_f[c] = jnp.where(
                fm, jnp.where(wlike_f, MOD,
                              jnp.where(acq_base < K, EXC, SHD)),
                cs_f[c])
            fo_f[c] = jnp.where(fm, jnp.where(reacq_rd, n_txn, K),
                                fo_f[c])
        steps.append(dict(
            hit_ok=hit_ok, rd_hit=rd_hit & hit_ok,
            wr_hit=(wr_hit | wr_dep) & hit_ok,
            dep=jnp.where(wr_dep & hit_ok, l_fo, K),
            ok=ok, ordn=jnp.where(ok, n_txn, K), addr=addr, val=val,
            ci=ci, e1=e1, e2=e2, victim=ok & has_victim,
            rd=ok & rd_miss, wr=ok & wr_miss, up=ok & upg, v_val=l_val,
            v_mod=l_state == MOD, rel_ordn=rel_ord, acq_basen=acq_base,
            hc=hc))
        fills.append((e1, ok, n_txn))
        victs.append((e2, ok & has_victim, n_txn,
                      ((l_state == MOD) | (l_state == EXC))
                      & (rel_ord == K)))
        n_txn = n_txn + ok
        stopped = stopped | stop_now
    return steps, cv_pre


_SLOT_FIELDS = ("ok", "e1", "e2", "val", "v_val", "victim", "rd", "wr",
                "up", "v_mod", "rel_ordn", "acq_basen")
_STEP_FIELDS = ("hc", "dep", "e1")


def _window_kernel(cfg, T, ca_ref, cv_ref, cs_ref, idx_ref, cnt_ref,
                   *out_refs):
    C, K = cfg.cache_size, cfg.txn_width
    W = cfg.drain_depth + K
    pid = pl.program_id(0)
    node = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1) + pid * T
    ca = [ca_ref[c:c + 1, :] for c in range(C)]
    cv = [cv_ref[c:c + 1, :] for c in range(C)]
    cs = [cs_ref[c:c + 1, :] for c in range(C)]
    steps, cv_pre = _fold(cfg, T, node, idx_ref[...], cnt_ref[...],
                          ca, cv, cs)
    # pack by ordinal: slot j's record comes from the step whose
    # transaction ordinal is j
    sel = [[steps[k]["ordn"] == j for k in range(W)] for j in range(K)]

    def slot_rows(name):
        rows = []
        for j in range(K):
            acc = jnp.zeros((1, T), jnp.int32)
            for k in range(W):
                acc = jnp.where(sel[j][k],
                                steps[k][name].astype(jnp.int32), acc)
            rows.append(acc)
        return jnp.concatenate(rows, axis=0)                  # [K, T]

    slot_blocks = [slot_rows(f) for f in _SLOT_FIELDS]
    pos_rows = []
    for j in range(K):
        acc = jnp.zeros((1, T), jnp.int32)
        for k in range(W):
            acc = jnp.where(sel[j][k], k, acc)
        pos_rows.append(acc)
    slot_blocks.append(jnp.concatenate(pos_rows, axis=0))     # pos [K, T]
    step_blocks = [
        jnp.concatenate([steps[k][f].astype(jnp.int32)
                         for k in range(W)], axis=0)
        for f in _STEP_FIELDS]
    # pack into THREE outputs (each pallas output buffer pays a layout
    # copy at the call boundary on this device)
    slot_ref, step_ref, cvp_ref = out_refs
    slot_ref[...] = jnp.concatenate(slot_blocks, axis=0)  # [13K, T]
    step_ref[...] = jnp.concatenate(step_blocks, axis=0)  # [3W, T]
    cvp_ref[...] = jnp.concatenate(cv_pre, axis=0)        # [C, T]


def _replay_kernel(cfg, T, ca_ref, cv_ref, cs_ref, idx_ref, cnt_ref,
                   fl_ref, fs_ref, fv_ref, cache_ref, cnts_ref):
    C, K = cfg.cache_size, cfg.txn_width
    W = cfg.drain_depth + K
    MOD = int(CacheState.MODIFIED)
    pid = pl.program_id(0)
    node = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1) + pid * T
    ca0 = [ca_ref[c:c + 1, :] for c in range(C)]
    cv0 = [cv_ref[c:c + 1, :] for c in range(C)]
    cs0 = [cs_ref[c:c + 1, :] for c in range(C)]
    steps, _ = _fold(cfg, T, node, idx_ref[...], cnt_ref[...],
                     ca0, cv0, cs0)
    first_lose = fl_ref[...]
    ca_c, cv_c, cs_c = list(ca0), list(cv0), list(cs0)
    zero = jnp.zeros((1, T), jnp.int32)
    n_ret, rh, wh = zero, zero, zero
    for k in range(W):
        s = steps[k]
        r = (k < first_lose) & (s["hit_ok"] | s["ok"])
        n_ret = n_ret + r
        rh = rh + (s["rd_hit"] & r)
        wh = wh + (s["wr_hit"] & r)
        fs, fv = zero, zero
        for j in range(K):
            sj = s["ordn"] == j
            fs = jnp.where(sj, fs_ref[j:j + 1, :], fs)
            fv = jnp.where(sj, fv_ref[j:j + 1, :], fv)
        for c in range(C):
            mc = s["ci"] == c
            wm = (s["wr_hit"] & r) & mc
            cv_c[c] = jnp.where(wm, s["val"], cv_c[c])
            cs_c[c] = jnp.where(wm, MOD, cs_c[c])
            fm = (s["ok"] & r) & mc
            ca_c[c] = jnp.where(fm, s["addr"], ca_c[c])
            cv_c[c] = jnp.where(fm, fv, cv_c[c])
            cs_c[c] = jnp.where(fm, fs, cs_c[c])
    cache_ref[...] = jnp.concatenate(ca_c + cv_c + cs_c, axis=0)
    cnts_ref[...] = jnp.concatenate([n_ret, rh, wh], axis=0)


from ue22cs343bb1_openmp_assignment_tpu.ops.pallas_burst import (
    _interpret, _tile)


def _call_window(cfg, ca_t, cv_t, cs_t, idx2, cnt2):
    C, K = cfg.cache_size, cfg.txn_width
    W = cfg.drain_depth + K
    N = cfg.num_nodes
    T = _tile(N)
    vec = pl.BlockSpec((1, T), lambda i: (0, i))
    matC = pl.BlockSpec((C, T), lambda i: (0, i))
    n_slot = len(_SLOT_FIELDS) + 1          # + pos
    n_step = len(_STEP_FIELDS)
    blk = lambda rows: (pl.BlockSpec((rows, T), lambda i: (0, i)),
                        jax.ShapeDtypeStruct((rows, N), jnp.int32))
    slot_spec, slot_shape = blk(n_slot * K)
    step_spec, step_shape = blk(n_step * W)
    cvp_spec, cvp_shape = blk(C)
    return pl.pallas_call(
        functools.partial(_window_kernel, cfg, T),
        grid=(N // T,),
        in_specs=[matC] * 3 + [vec] * 2,
        out_specs=[slot_spec, step_spec, cvp_spec],
        out_shape=[slot_shape, step_shape, cvp_shape],
        interpret=_interpret(),
    )(ca_t, cv_t, cs_t, idx2, cnt2)


def _call_replay(cfg, ca_t, cv_t, cs_t, idx2, cnt2, first_lose,
                 fill_state, fill_val):
    C, K = cfg.cache_size, cfg.txn_width
    N = cfg.num_nodes
    T = _tile(N)
    vec = pl.BlockSpec((1, T), lambda i: (0, i))
    matC = pl.BlockSpec((C, T), lambda i: (0, i))
    matK = pl.BlockSpec((K, T), lambda i: (0, i))
    blk = lambda rows: (pl.BlockSpec((rows, T), lambda i: (0, i)),
                        jax.ShapeDtypeStruct((rows, N), jnp.int32))
    cache_spec, cache_shape = blk(3 * C)
    cnts_spec, cnts_shape = blk(3)
    return pl.pallas_call(
        functools.partial(_replay_kernel, cfg, T),
        grid=(N // T,),
        in_specs=[matC] * 3 + [vec] * 2 + [vec] + [matK] * 2,
        out_specs=[cache_spec, cnts_spec],
        out_shape=[cache_shape, cnts_shape],
        interpret=_interpret(),
    )(ca_t, cv_t, cs_t, idx2, cnt2, first_lose, fill_state, fill_val)


def round_step_multi_pallas(cfg: SystemConfig, st: SyncState) -> SyncState:
    """One multi-transaction round with the fold in Pallas kernels.

    Bit-identical to `_round_step_multi` (tests/test_pallas_window.py);
    requires cfg.procedural and txn_width > 1, no event tracing.
    """
    N, C = cfg.num_nodes, cfg.cache_size
    K = cfg.txn_width
    E = N << cfg.block_bits
    INV = int(CacheState.INVALID)
    MOD = int(CacheState.MODIFIED)
    EXC = int(CacheState.EXCLUSIVE)
    SHD = int(CacheState.SHARED)
    rows0 = jnp.arange(N, dtype=jnp.int32)                   # [N]

    ca_t = st.cache_addr.T
    cv_t = st.cache_val.T
    cs_t = st.cache_state.T
    idx2 = st.idx[None, :]
    cnt2 = st.instr_count[None, :]

    slotmat, stepmat, cv_pre = _call_window(cfg, ca_t, cv_t, cs_t,
                                            idx2, cnt2)
    slot = {f: slotmat[i * K:(i + 1) * K]
            for i, f in enumerate(_SLOT_FIELDS + ("pos",))}
    W = cfg.drain_depth + K
    hc_w, dep_w, he_w = (stepmat[:W], stepmat[W:2 * W],
                         stepmat[2 * W:])                    # [W, N] each

    exists = slot["ok"].astype(bool)                         # [K, N]
    e1_s, e2_s = slot["e1"], slot["e2"]
    val_s, v_val_s = slot["val"], slot["v_val"]
    victim_s = slot["victim"].astype(bool)
    rd_s, wr_s, up_s = (slot["rd"].astype(bool), slot["wr"].astype(bool),
                        slot["up"].astype(bool))
    v_mod_s = slot["v_mod"].astype(bool) & victim_s
    rel_s = jnp.where(exists, slot["rel_ordn"], K)
    acqb_s = jnp.where(exists, slot["acq_basen"], K)
    pos_s = slot["pos"]

    # ---- claim + one row gather (XLA; transposed layout) -----------------
    key = _round_key(cfg, st, rows0)                         # [N]
    c_idx = jnp.concatenate(
        [jnp.where(exists[j], e1_s[j], E) for j in range(K)]
        + [jnp.where(victim_s[j], e2_s[j], E) for j in range(K)])
    # NB: a full-row scatter-min (INT32_MAX identity in non-claim
    # columns) was measured 8% slower than this column scatter despite
    # removing the table's layout-flip copies — the 7x scatter payload
    # costs more than the copies it avoids
    dm_claimed = st.dm.at[c_idx, DM_CLAIM].min(jnp.tile(key, 2 * K),
                                               mode="drop")
    g = dm_claimed[jnp.concatenate(
        [e1_s, e2_s, he_w], axis=0).reshape(-1)].reshape(2 * K + W, N,
                                                         DM_COLS)
    d1, d2, hrow = g[:K], g[K:2 * K], g[2 * K:]
    key1 = key[None, :]
    win = exists & (d1[..., DM_CLAIM] == key1) & (
        ~victim_s | (d2[..., DM_CLAIM] == key1))

    # ---- effective primary rows (reacquire chains) -----------------------
    d1s, d1c, d1o, d1m = (d1[..., DM_STATE], d1[..., DM_COUNT],
                          d1[..., DM_OWNER], d1[..., DM_MEM])
    d2c, d2o, d2m = d2[..., DM_COUNT], d2[..., DM_OWNER], d2[..., DM_MEM]
    pe_m = jnp.where(v_mod_s, v_val_s, d2m)                  # [K, N]
    base_u = jnp.zeros((K, N), bool)
    base_m = jnp.zeros((K, N), jnp.int32)
    for i in range(K):
        m = acqb_s == i
        base_u |= m
        base_m = jnp.where(m, pe_m[i:i + 1], base_m)
    d1s = jnp.where(base_u, int(DirState.U), d1s)
    d1c = jnp.where(base_u, 0, d1c)
    d1m = jnp.where(base_u, base_m, d1m)
    d_u = d1s == int(DirState.U)
    d_em = d1s == int(DirState.EM)

    # ---- truncation: losses + unsafe interior/dependent hits -------------
    prio_bits = max(1, (N - 1).bit_length())
    thresh = (jnp.maximum(claim_max_rounds(cfg) - st.round, 0) + 1) \
        << prio_bits
    hgot = hrow[..., DM_CLAIM]                               # [W, N]
    first_bad_hit = jnp.full((N,), W, jnp.int32)
    for k in range(W):
        dep = dep_w[k]
        dok = jnp.zeros((N,), bool)
        for j in range(K):
            dok |= (dep == j) & d_u[j]
        unsafe = ((hc_w[k].astype(bool)
                   & ~((hgot[k] >= thresh) | (hgot[k] == key)))
                  | ((dep < K) & ~dok))
        first_bad_hit = jnp.minimum(first_bad_hit,
                                    jnp.where(unsafe, k, W))
    eligible = win & (pos_s < first_bad_hit[None, :])
    cum = []
    run = jnp.ones((N,), bool)
    for j in range(K):
        run = run & (eligible[j] | ~exists[j])
        cum.append(run)
    cum = jnp.stack(cum, axis=0)                             # [K, N]
    commit = exists & cum
    first_lose = jnp.minimum(
        jnp.min(jnp.where(exists & ~cum, pos_s, W), axis=0),
        first_bad_hit)                                       # [N]

    # ---- transaction outcomes --------------------------------------------
    rd_w, wr_w, up_w = commit & rd_s, commit & wr_s, commit & up_s
    wlike = wr_w | up_w
    ci_s = codec.cache_index(cfg, e1_s)
    safe_o = jnp.clip(d1o, 0, N - 1)
    # cv_pre is [C, N]: owner o's line ci lives at flat ci * N + o
    val_o = cv_pre.reshape(-1)[ci_s * N + safe_o]            # [K, N]
    n1s = jnp.where(wlike | (rd_w & d_u), int(DirState.EM),
                    int(DirState.S))
    n1c = jnp.where(wlike | (rd_w & d_u), 1,
                    jnp.where(rd_w & d_em, 2, d1c + 1))
    n1o = jnp.where(wlike | (rd_w & d_u), rows0[None, :], d1o)
    n1m = jnp.where((rd_w | wr_w) & d_em, val_o, d1m)
    act1 = jnp.where(wlike, ACT_KILL,
                     jnp.where(rd_w & d_em, ACT_DOWNGRADE, ACT_NONE))
    ev = commit & victim_s
    ev_mod = ev & v_mod_s
    ev_sh = ev & ~ev_mod
    n2c = jnp.where(ev_mod, 0, d2c - 1)
    n2s = jnp.where(n2c == 0, int(DirState.U),
                    jnp.where(n2c == 1, int(DirState.EM), int(DirState.S)))
    n2m = jnp.where(ev_mod, v_val_s, d2m)
    act2 = jnp.where(ev_sh & (n2c == 1), ACT_PROMOTE, ACT_NONE)

    # ---- release / reacquire composition ---------------------------------
    released = jnp.zeros((K, N), bool)
    rel_val = jnp.zeros((K, N), jnp.int32)
    rel_dirty = jnp.zeros((K, N), bool)
    consumed = jnp.zeros((K, N), bool)
    j_iota = jnp.arange(K, dtype=jnp.int32)[:, None]
    for r in range(K):
        m = commit[r:r + 1] & (rel_s[r:r + 1] == j_iota)     # [K, N]
        released |= m
        rel_val = jnp.where(m, v_val_s[r:r + 1], rel_val)
        rel_dirty |= m & v_mod_s[r:r + 1]
        consumed |= commit[r:r + 1] & (acqb_s[r:r + 1] == j_iota)
    rd_rel_s = released & rd_s & ~d_u & ~d_em
    r1s = jnp.where(wlike | (rd_s & d_u), int(DirState.U),
                    jnp.where(rd_s & d_em, int(DirState.EM),
                              jnp.where(d1c == 1, int(DirState.EM),
                                        int(DirState.S))))
    r1c = jnp.where(wlike | (rd_s & d_u), 0,
                    jnp.where(rd_s & d_em, 1, d1c))
    r1m = jnp.where(wlike | rel_dirty, rel_val,
                    jnp.where(rd_s & d_em, val_o, d1m))
    r1a = jnp.where(wlike, ACT_KILL,
                    jnp.where((rd_s & d_em) | (rd_rel_s & (d1c == 1)),
                              ACT_PROMOTE, ACT_NONE))
    n1s = jnp.where(released, r1s, n1s)
    n1c = jnp.where(released, r1c, n1c)
    n1o = jnp.where(released, d1o, n1o)
    n1m = jnp.where(released, r1m, n1m)
    act1 = jnp.where(released, r1a, act1)
    ev_sep = ev & (rel_s == K) & ~consumed

    # ---- commit scatter ---------------------------------------------------
    rtag = st.round << 2
    rowsK = jnp.broadcast_to(rows0[None, :], (K, N))
    keyKb = jnp.broadcast_to(key1, (K, N))
    t_idx = jnp.concatenate([jnp.where(commit, e1_s, E).reshape(-1),
                             jnp.where(ev_sep, e2_s, E).reshape(-1)])
    t_dm = jnp.concatenate([
        jnp.stack([n1s, n1c, n1o, n1m, rtag | act1, rowsK, keyKb],
                  axis=-1).reshape(-1, DM_COLS),
        jnp.stack([n2s, n2c, d2o, n2m, rtag | act2, rowsK, keyKb],
                  axis=-1).reshape(-1, DM_COLS)])
    dm = dm_claimed.at[t_idx].set(t_dm, mode="drop")

    # ---- replay kernel ----------------------------------------------------
    fill_state = jnp.where(rd_s, jnp.where(d_u, EXC, SHD), MOD)
    fill_val = jnp.where(rd_s, jnp.where(d_em, val_o, d1m), val_s)
    cache_mat, cnts = _call_replay(
        cfg, ca_t, cv_t, cs_t, idx2, cnt2, first_lose[None, :],
        fill_state, fill_val)
    ca_c, cv_c, cs_c = (cache_mat[:C], cache_mat[C:2 * C],
                        cache_mat[2 * C:])
    n_retired, rh_n, wh_n = cnts[0], cnts[1], cnts[2]

    # ---- fan-out application (transposed [C, N]) --------------------------
    line_e = jnp.clip(ca_c, 0, E - 1)                        # [C, N]
    line_dm = dm[line_e]                                     # [C, N, 7]
    fresh = (line_dm[..., DM_ACT] >> 2) == st.round
    a_code = jnp.where(fresh, line_dm[..., DM_ACT] & 3, ACT_NONE)
    a_req = line_dm[..., DM_REQ]
    valid = cs_c != INV
    not_self = a_req != rows0[None, :]
    kill = valid & not_self & (a_code == ACT_KILL)
    down = valid & not_self & (a_code == ACT_DOWNGRADE)
    promo = valid & not_self & (a_code == ACT_PROMOTE)
    cs_c = jnp.where(kill, INV,
                     jnp.where(down, SHD, jnp.where(promo, EXC, cs_c)))
    dm = dm.at[jnp.where(promo, line_e, E).reshape(-1), DM_OWNER].set(
        jnp.broadcast_to(rows0[None, :], (C, N)).reshape(-1),
        mode="drop")

    # ---- bookkeeping ------------------------------------------------------
    deltas = jnp.sum(jnp.stack([
        n_retired, rh_n, wh_n,
        jnp.sum(rd_w, axis=0, dtype=jnp.int32),
        jnp.sum(wr_w, axis=0, dtype=jnp.int32),
        jnp.sum(up_w, axis=0, dtype=jnp.int32),
        jnp.sum(exists & ~win, axis=0, dtype=jnp.int32),
        jnp.sum(ev, axis=0, dtype=jnp.int32),
        jnp.sum(kill, axis=0, dtype=jnp.int32),
        jnp.sum(promo, axis=0, dtype=jnp.int32),
    ]), axis=1)                                              # [10]
    mt = st.metrics
    metrics = mt.replace(
        rounds=mt.rounds + 1,
        instrs_retired=mt.instrs_retired + deltas[0],
        read_hits=mt.read_hits + deltas[1],
        write_hits=mt.write_hits + deltas[2],
        read_misses=mt.read_misses + deltas[3],
        write_misses=mt.write_misses + deltas[4],
        upgrades=mt.upgrades + deltas[5],
        conflicts=mt.conflicts + deltas[6],
        evictions=mt.evictions + deltas[7],
        invalidations=mt.invalidations + deltas[8],
        promotions=mt.promotions + deltas[9],
    )
    return st.replace(cache_addr=ca_c.T, cache_val=cv_c.T,
                      cache_state=cs_c.T, dm=dm,
                      idx=st.idx + n_retired, round=st.round + 1,
                      metrics=metrics)
