"""Synchronous transactional engine: whole coherence transactions per round.

The async engine (ops.step) replays the reference's message-level
semantics cycle by cycle: one dequeue + at most one instruction per node
per cycle, multi-hop transactions spread over ~4-6 cycles
(``assignment.c:165-737``). That fidelity is what the parity and race
suites need — but it pays the device's per-dispatch overhead once per
*message hop*.

This engine executes the *same protocol* under a different — equally
legal — schedule: per round, every node first retires a short burst of
cache hits locally, then at most one full coherence transaction
(read-miss / write-miss / upgrade, ``assignment.c:654-735``) commits
**atomically**: all of its hops (request, forward, writeback, fill,
invalidation fan-out, eviction notice — call stacks SURVEY §3.2-3.5)
apply in one step, as if every message of the transaction was delivered
and processed before the next transaction touched the same block. Each
round realizes one serialization of the winning transactions; the
arbitration hash (seedable) picks the winners, replacing OS lock order.

Atomicity buys an invariant the async machine only approaches at
quiescence: **the directory is always exact** — a block's sharer set is
precisely the set of nodes whose cache currently holds its tag valid
(evictions commit inside the displacing transaction, so the directory
never lags a replacement the way in-flight ``EVICT_*`` messages make it
lag in the reference, ``assignment.c:767-804``). Exactness makes the
sharer *bitvector* redundant:

* invalidation fan-out (``assignment.c:364-373``) = "kill every valid
  line holding this tag" — a tag equality test, no sharer set needed;
* the ``EVICT_SHARED`` last-sharer promotion (``assignment.c:584-587``)
  target self-identifies by tag match;
* only the EM owner id (``__builtin_ctz``, ``assignment.c:209``) and
  the sharer count (``__builtin_popcount``, ``assignment.c:564``) need
  storing — two int columns instead of ceil(N/32) words per entry.
  At 4096 nodes this shrinks the directory 32x and removes every
  bitvector gather from the hot path.

Per-round device work (the whole machine, any N): 4 gathers (packed
instruction window; both claimed directory rows; the EM owner's cache
value; the per-line action lookup) + 3 scatters (claim min; packed
entry effects; promotion owner) + fused elementwise, one stacked metric
reduction. No sort, no mailbox tensor. Conflicts (two transactions claiming one
directory entry, or a transaction claiming another's victim entry) are
resolved by a per-round seeded hash priority: losers simply retry next
round — the analogue of losing the lock-acquisition race in the
reference. The hash reshuffles every round, so progress is guaranteed
(the globally minimal claimant always wins both its entries).

Schedules realized here are a strict subset of the reference's (atomic
transactions cannot interleave mid-flight), so racy-suite outcomes are
always *reachable* outcomes of the reference machine; the parity suites
(tests 1/2: node-local, schedule-independent) produce byte-identical
golden dumps (tests/test_sync_engine.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import struct

from ue22cs343bb1_openmp_assignment_tpu import codec
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.procedural import procedural_instr
from ue22cs343bb1_openmp_assignment_tpu.state import (SimState,
                                                      build_instr_arrays)
from ue22cs343bb1_openmp_assignment_tpu.types import CacheState, DirState, Op

# dm column layout: the per-(home, block) directory/memory table, one row
# per entry; entry index == the address itself (addr = home * M + block,
# codec.py / assignment.c:46-49).
DM_STATE, DM_COUNT, DM_OWNER, DM_MEM, DM_ACT, DM_REQ, DM_CLAIM = (
    0, 1, 2, 3, 4, 5, 6)
DM_COLS = 7
# DM_ACT holds (round << 2) | action — the fan-out action table lives in
# the directory row itself; a row whose embedded round differs from the
# current round carries no action, so stale actions self-invalidate and
# the table needs no per-round reset.
#
# DM_CLAIM holds the conflict-resolution scatter-min key. Keys embed
# (max_round - round) in their high bits, so every round's keys compare
# strictly below all stale keys from earlier rounds — the claim column
# never needs resetting either. Consequence: a run is bounded to
# claim_max_rounds(cfg) rounds (2^30 key bits split between the round
# countdown and a node-unique priority); the runners assert the bound.

# per-round action codes scattered at a directory entry, applied by every
# cached line holding that entry's tag (the vectorized stand-in for the
# INV / WRITEBACK_INT / EVICT_SHARED-promotion fan-outs)
ACT_NONE, ACT_KILL, ACT_DOWNGRADE, ACT_PROMOTE = 0, 1, 2, 3


class SyncMetrics(struct.PyTreeNode):
    rounds: jnp.ndarray          # [] i32
    instrs_retired: jnp.ndarray  # [] i32
    read_hits: jnp.ndarray       # [] i32 (burst-retired)
    write_hits: jnp.ndarray      # [] i32 (burst-retired, M/E lines)
    read_misses: jnp.ndarray     # [] i32 (committed RD transactions)
    write_misses: jnp.ndarray    # [] i32 (committed WR transactions)
    upgrades: jnp.ndarray        # [] i32 (committed S-write upgrades)
    conflicts: jnp.ndarray       # [] i32 (transaction attempts that lost)
    evictions: jnp.ndarray       # [] i32 (conflict replacements committed)
    invalidations: jnp.ndarray   # [] i32 (lines killed by fan-out)
    promotions: jnp.ndarray      # [] i32 (S->E last-sharer promotions)

    @classmethod
    def zeros(cls) -> "SyncMetrics":
        z = jnp.zeros((), jnp.int32)
        return cls(rounds=z, instrs_retired=z, read_hits=z, write_hits=z,
                   read_misses=z, write_misses=z, upgrades=z, conflicts=z,
                   evictions=z, invalidations=z, promotions=z)


class SyncState(struct.PyTreeNode):
    """Machine state for the transactional engine (no mailboxes).

    Shapes: N nodes, C cache lines, M blocks/node, T trace length."""

    cache_addr: jnp.ndarray   # [N, C] i32 (cfg.invalid_address = empty)
    cache_val: jnp.ndarray    # [N, C] i32
    cache_state: jnp.ndarray  # [N, C] i32 CacheState

    # directory + memory + per-round fan-out action + claim key, one row
    # per (home, block) entry, flat [N << block_bits, 7] so that row
    # index == the packed address (codec.make_address; rows for
    # block >= mem_size are unused holes when mem_size is not a power of
    # two): DM_STATE DirState, DM_COUNT sharers, DM_OWNER EM owner id,
    # DM_MEM value, DM_ACT round-tagged action, DM_REQ requester/evictor,
    # DM_CLAIM arbitration key (monotone-decreasing per round; preserve
    # across save/restore, reset only at phase boundaries)
    dm: jnp.ndarray           # [N << block_bits, DM_COLS] i32

    instr_pack: jnp.ndarray   # [N, T, 2] i32: [op << 28 | addr, value]
    instr_count: jnp.ndarray  # [N] i32
    idx: jnp.ndarray          # [N] i32: next instruction to execute

    # deep-window attempt horizon (ops.deep_engine): per-node cap on how
    # far the window fold ATTEMPTS each round, adapted AIMD-style
    # (committed + 2, decays on truncation). Bounds the "ghost" events
    # that uncommitted attempts park in lanes/flags, which otherwise
    # inflate contention quadratically. Inert for the other engines.
    horizon: jnp.ndarray      # [N] i32

    seed: jnp.ndarray         # [] i32 arbitration seed (schedule knob)
    round: jnp.ndarray        # [] i32
    metrics: SyncMetrics

    @property
    def num_nodes(self) -> int:
        return self.cache_addr.shape[0]

    def quiescent(self) -> jnp.ndarray:
        return jnp.all(self.idx >= self.instr_count)


def _fresh_dm(cfg: SystemConfig, memory: jnp.ndarray) -> jnp.ndarray:
    """Cold flat directory rows: every entry Unowned with `memory`'s
    image in DM_MEM. Fresh machines start at round 0; pre-stamp DM_ACT
    with an impossible round tag so round 0 sees no stale actions, and
    the claim column above every reachable key."""
    N, M = cfg.num_nodes, cfg.mem_size
    S = 1 << cfg.block_bits          # row stride per home (>= M)
    dm = jnp.zeros((N * S, DM_COLS), jnp.int32)
    dm = dm.at[:, DM_STATE].set(jnp.full((N * S,), int(DirState.U),
                                         jnp.int32))
    dm = dm.at[:, DM_ACT].set(jnp.full((N * S,), -4, jnp.int32))
    dm = dm.at[:, DM_CLAIM].set(
        jnp.full((N * S,), jnp.iinfo(jnp.int32).max, jnp.int32))
    node_rows = jnp.arange(N, dtype=jnp.int32)[:, None] * S
    blocks = jnp.arange(M, dtype=jnp.int32)[None, :]
    return dm.at[(node_rows + blocks).reshape(-1), DM_MEM].set(
        memory.reshape(N * M))


def from_sim_state(cfg: SystemConfig, st: SimState, seed: int = 0) -> SyncState:
    """Adopt a freshly initialized SimState (same loaders/workloads).

    Must be called on a pre-run state (empty mailboxes, cold caches):
    the engines share initial conditions, not mid-flight state.
    """
    N = cfg.num_nodes
    dm = _fresh_dm(cfg, st.memory)
    return SyncState(
        cache_addr=st.cache_addr, cache_val=st.cache_val,
        cache_state=st.cache_state,
        dm=dm,
        instr_pack=jnp.stack(
            [(st.instr_op << 28) | st.instr_addr, st.instr_val], axis=-1),
        instr_count=st.instr_count,
        idx=jnp.zeros((N,), jnp.int32),
        horizon=jnp.full((N,), 1 << 20, jnp.int32),
        seed=jnp.asarray(seed, jnp.int32),
        round=jnp.zeros((), jnp.int32),
        metrics=SyncMetrics.zeros(),
    )


def to_sim_arrays(cfg: SystemConfig, st: SyncState):
    """Reconstruct (memory, dir_state, dir_bitvec) in SimState layout.

    The sharer bitvector (reference ``assignment.c:63``) is derived from
    cache tags — exact, because the transactional engine keeps the
    directory exact (module docstring). Host-side; used by the golden
    dumper and the invariant tests.
    """
    import numpy as np
    N, C, M, W = (cfg.num_nodes, cfg.cache_size, cfg.mem_size,
                  cfg.bitvec_words)
    S = 1 << cfg.block_bits
    dm = np.asarray(st.dm).reshape(N, S, DM_COLS)[:, :M]
    memory = dm[:, :, DM_MEM]
    dir_state = dm[:, :, DM_STATE]
    bv = np.zeros((N, M, W), np.uint32)
    ca = np.asarray(st.cache_addr)
    cs = np.asarray(st.cache_state)
    for n in range(N):
        for c in range(C):
            if cs[n, c] != int(CacheState.INVALID):
                a = int(ca[n, c])
                home = a >> cfg.block_bits
                block = a & ((1 << cfg.block_bits) - 1)
                if 0 <= home < N:
                    bv[home, block, n // 32] |= np.uint32(1 << (n % 32))
    return memory, dir_state, bv


def continue_with_traces(cfg: SystemConfig, st: SyncState, traces=None,
                         instr_arrays=None) -> SyncState:
    """Stream the next trace phase into a retired machine (host-side
    phase boundary — blocks on the quiescence flag by design).

    Transactional-engine twin of state.continue_with_traces: caches,
    the directory table and metrics persist; the instruction stream
    resets. Requires every current trace to be fully retired."""
    if not bool(st.quiescent()):
        raise ValueError(
            "continue_with_traces needs a fully retired machine")
    op, addr, val, count = build_instr_arrays(
        cfg, traces=traces, instr_arrays=instr_arrays)
    # phase boundary: reset the round counter and the round-tagged
    # claim/action columns, so the claim-key budget and action-tag
    # namespace are per phase (metrics stay cumulative).
    dm = reset_claims(st.dm)
    dm = dm.at[:, DM_ACT].set(-4)
    return st.replace(
        dm=dm,
        instr_pack=jnp.stack([(op << 28) | addr, val], axis=-1),
        instr_count=count,
        idx=jnp.zeros((cfg.num_nodes,), jnp.int32),
        horizon=jnp.full((cfg.num_nodes,), 1 << 20, jnp.int32),
        round=jnp.zeros((), jnp.int32))


def to_dump_view(cfg: SystemConfig, st: SyncState):
    """A SimState-shaped view for utils.golden.state_to_dumps."""
    import types as _t
    memory, dir_state, bv = to_sim_arrays(cfg, st)
    return _t.SimpleNamespace(
        memory=memory, dir_state=dir_state, dir_bitvec=bv,
        cache_addr=st.cache_addr, cache_val=st.cache_val,
        cache_state=st.cache_state)


def _assert_round_budget(cfg: SystemConfig, start_round, n: int) -> None:
    """The budget is on the ABSOLUTE round counter (claim keys count
    down from claim_max_rounds): entry round + requested rounds must
    stay inside it. `round` resets at phase boundaries
    (continue_with_traces), not on checkpoint resume. Host-side (reads
    the round scalar), so the public runners call it outside jit."""
    start = int(start_round)
    budget = claim_max_rounds(cfg)
    assert start + n < budget, (
        f"round {start} + {n} rounds exceeds the claim-key budget "
        f"{budget} at {cfg.num_nodes} nodes; chain phases via "
        "continue_with_traces to reset the round counter")


def reset_claims(dm):
    """Clear DM_CLAIM to the idle sentinel (arbitration is transient
    per-round state, never outcome). The ONE place the sentinel lives:
    continue_with_traces resets at phase boundaries, and the CLI resets
    on resume when a flag override changes the lane-key layout.
    asarray: a checkpoint-restored state carries host numpy arrays."""
    return jnp.asarray(dm).at[:, DM_CLAIM].set(jnp.iinfo(jnp.int32).max)


def slot_bits(cfg: SystemConfig) -> int:
    """Lane-key slot-index bit width (SB).

    With absorption waves (deep_waves > 1) a node's same-entry events
    carry their window slot index in the DM_CLAIM lane key so
    re-touches compose across waves; single-wave configs spend no slot
    bits. The ONE definition of the key layout's SB — deep_engine and
    the CLI resume guard both use it, so a layout change cannot
    silently diverge between the engine and the stale-claim reset."""
    return (0 if cfg.deep_waves == 1
            else max(1, (cfg.deep_slots - 1).bit_length()))


def claim_max_rounds(cfg: SystemConfig) -> int:
    """Hard bound on rounds per machine (DM_CLAIM key-packing budget).

    The deep-window engine spends one extra key bit distinguishing
    eviction notices from fill requests in the lane (ops/deep_engine),
    halving the round budget."""
    prio_bits = max(1, (cfg.num_nodes - 1).bit_length())
    if cfg.deep_window:
        # one extra lane key bit (the ev tag) plus, with absorption
        # waves, slot-index bits (slot_bits), plus, with read storms,
        # the is_rd bit above the priority field (ops/deep_engine key
        # layout); the wave-stamp DM_ACT packing (round << 11) further
        # caps the absolute round counter at 2^20
        st_bit = 1 if cfg.deep_read_storm else 0
        return min((1 << (30 - prio_bits - 1 - slot_bits(cfg)
                          - st_bit)) - 1,
                   (1 << 20) - 1)
    return (1 << (30 - prio_bits)) - 1


def check_exact_directory(cfg: SystemConfig, st: SyncState) -> dict:
    """Assert the engine's core invariant; return a summary report.

    The transactional engine must keep the directory *exact* at every
    round boundary (module docstring): an entry's sharer count equals
    the number of valid cache lines holding its tag, EM entries have
    exactly one holder (the recorded owner, in M/E), S entries have only
    SHARED holders, U entries none. This is the engine-tier analogue of
    the reference's -DDEBUG popcount asserts (``assignment.c:449,556,
    608-614``), checkable at any time — not only at quiescence.

    Raises AssertionError on violation. Host-side, vectorized numpy.
    """
    import numpy as np
    N, C, M = cfg.num_nodes, cfg.cache_size, cfg.mem_size
    S = 1 << cfg.block_bits
    E = N * S
    ca = np.asarray(st.cache_addr)
    cs = np.asarray(st.cache_state)
    dm = np.asarray(st.dm)
    valid = cs != int(CacheState.INVALID)
    addrs = ca[valid]
    assert addrs.size == 0 or (addrs.min() >= 0 and addrs.max() < E), (
        "valid cache line holds an out-of-range tag")
    holders = np.bincount(addrs, minlength=E)
    shared_h = np.bincount(ca[valid & (cs == int(CacheState.SHARED))],
                           minlength=E)
    owned_h = holders - shared_h          # M/E holders per entry
    d_state, d_count = dm[:, DM_STATE], dm[:, DM_COUNT]
    is_u = d_state == int(DirState.U)
    is_em = d_state == int(DirState.EM)
    is_s = d_state == int(DirState.S)
    assert np.all(is_u | is_em | is_s), "directory row with corrupt state"
    block_ok = (np.arange(E) & (S - 1)) < M   # real rows (no stride holes)
    assert np.all(is_u[~block_ok] | (holders[~block_ok] == 0)), (
        "stride-hole entry is claimed")
    assert np.all(holders[is_u] == 0), "U entry has holders"
    assert np.all((d_count[is_em] == 1) & (holders[is_em] == 1)
                  & (owned_h[is_em] == 1)), (
        "EM entry without exactly one M/E holder")
    assert np.all((d_count[is_s] == holders[is_s]) & (d_count[is_s] >= 1)
                  & (owned_h[is_s] == 0)), (
        "S entry count/holder-state mismatch")
    # EM owner recorded at the home is the actual holder
    em_rows = np.nonzero(is_em)[0]
    owners = dm[em_rows, DM_OWNER]
    assert owners.size == 0 or (owners.min() >= 0 and owners.max() < N), (
        "EM owner id out of range")
    ci = (em_rows & (S - 1)) % C
    assert np.all((ca[owners, ci] == em_rows)
                  & (cs[owners, ci] != int(CacheState.INVALID))
                  & (cs[owners, ci] != int(CacheState.SHARED))), (
        "EM entry's recorded owner does not hold the line M/E")
    return {
        "entries_u": int(is_u[block_ok].sum()),
        "entries_em": int(is_em.sum()),
        "entries_s": int(is_s.sum()),
        "cached_lines": int(valid.sum()),
    }


def procedural_state(cfg: SystemConfig, length: int,
                     seed: int = 0) -> SyncState:
    """A SyncState whose instructions come from cfg.procedural —
    `length` instructions per node with O(1) trace storage (the
    instr_pack placeholder has one slot; round_step never reads it in
    procedural mode). `length` may far exceed cfg.max_instrs.

    Built directly in the flat dm layout rather than via
    ``from_sim_state(init_state(cfg))``: init_state materializes the
    [N, M, ceil(N/32)] sharer bitvector that the flat layout never
    reads — an O(N^2) *transient* that is 2 TB at the 2^20-node rung.
    Procedural machines stay O(N) end to end."""
    if not cfg.procedural:
        raise ValueError("cfg.procedural must name a generator")
    N, C, M = cfg.num_nodes, cfg.cache_size, cfg.mem_size
    # initializeProcessor's memory image (assignment.c:806-851), the
    # same cold machine state.init_state builds
    memory = (20 * jnp.arange(N, dtype=jnp.int32)[:, None]
              + jnp.arange(M, dtype=jnp.int32)[None, :]) & 0xFF
    return SyncState(
        cache_addr=jnp.full((N, C), cfg.invalid_address, jnp.int32),
        cache_val=jnp.zeros((N, C), jnp.int32),
        cache_state=jnp.full((N, C), int(CacheState.INVALID), jnp.int32),
        dm=_fresh_dm(cfg, memory),
        instr_pack=jnp.zeros((N, 1, 2), jnp.int32),
        instr_count=jnp.full((N,), int(length), jnp.int32),
        idx=jnp.zeros((N,), jnp.int32),
        horizon=jnp.full((N,), 1 << 20, jnp.int32),
        seed=jnp.asarray(seed, jnp.int32),
        round=jnp.zeros((), jnp.int32),
        metrics=SyncMetrics.zeros(),
    )


def _mix(x: jnp.ndarray) -> jnp.ndarray:
    """murmur3-style 32-bit finalizer (deterministic arbitration hash)."""
    x = jnp.asarray(x, jnp.uint32)
    x ^= x >> 16
    x *= jnp.uint32(0x7FEB352D)
    x ^= x >> 15
    x *= jnp.uint32(0x846CA68B)
    x ^= x >> 16
    return x


def _round_key(cfg: SystemConfig, st: SyncState, rows: jnp.ndarray):
    """Per-round claim key: decreasing round countdown in the high bits,
    a reseeded bijective node-priority permutation in the low bits (see
    the DM_CLAIM comment at the top). Keys are unique per node."""
    return _round_key_rs(cfg, st.round, st.seed, rows)


def _round_key_rs(cfg: SystemConfig, round_, seed, rows: jnp.ndarray):
    """`_round_key` on raw (round, seed) scalars instead of a SyncState
    — pure uint32 arithmetic, so the fused Pallas round kernel
    (ops/pallas_round) can recompute keys in-kernel from a two-scalar
    params row rather than streaming a keys array through HBM."""
    N = cfg.num_nodes
    prio_bits = max(1, (N - 1).bit_length())
    mask = jnp.uint32((1 << prio_bits) - 1)
    h = _mix((jnp.asarray(round_).astype(jnp.uint32)
              * jnp.uint32(0x9E3779B9))
             ^ (jnp.asarray(seed).astype(jnp.uint32)
                * jnp.uint32(0x85EBCA77)))
    x = rows.astype(jnp.uint32)
    x = (x * ((h << 1) | jnp.uint32(1)) + (h >> 7)) & mask
    x ^= x >> max(1, prio_bits // 2)
    x = (x * jnp.uint32(0x9E3779B9 | 1)) & mask
    prio = x.astype(jnp.int32)
    # The clamp keeps overrun (round > claim_max_rounds) free of int32
    # wraparound, at two costs beyond the documented stale-claim stalls:
    # every overrun round shares countdown 0, so (a) claims from
    # *earlier* overrun rounds look fresh to the interior-hit safety
    # probe (`thresh` in _round_step_multi), spuriously truncating
    # windows, and (b) the same node always beats the same rivals (the
    # per-round reshuffle is gone), so fairness degrades. Progress is
    # still guaranteed, only slower. The public runners keep this regime
    # unreachable by asserting the budget up front
    # (_assert_round_budget); only direct round_step callers can enter
    # it.
    countdown = jnp.maximum(claim_max_rounds(cfg) - jnp.asarray(round_),
                            0).astype(jnp.int32)
    return (countdown << prio_bits) | prio


def round_step(cfg: SystemConfig, st: SyncState,
               with_events: bool = False):
    """One transactional round; dispatches on cfg.txn_width.

    txn_width == 1: the classic hit-burst plus one atomic transaction
    per node (`_round_step_single`). txn_width > 1: a window of up to
    txn_width transactions per node commits per round
    (`_round_step_multi`) — same protocol, more progress per device
    dispatch. cfg.pallas_burst routes the window fold through fused
    Pallas kernels on procedural workloads (ops.pallas_burst /
    ops.pallas_window), bit-identically."""
    if cfg.deep_window:
        from ue22cs343bb1_openmp_assignment_tpu.ops.deep_engine import (
            round_step_deep)
        if cfg.fused_round and not with_events:
            from ue22cs343bb1_openmp_assignment_tpu.ops import pallas_round
            if pallas_round.supported(cfg):
                # the ENTIRE round as one kernel — folds, arbitration,
                # composition, fan-out — with state resident in VMEM
                # (bit-identical: shared deep_round_core middle, routed
                # index ops); unsupported configs fall through to the
                # reference path below
                return pallas_round.round_step_deep_fused(cfg, st)
        fold_impl = "xla"
        if cfg.pallas_burst and not with_events:
            from ue22cs343bb1_openmp_assignment_tpu.ops import pallas_burst
            if pallas_burst.tileable(cfg.num_nodes):
                # the round middle (arbitration, waves, composition,
                # fan-out) is shared; only the two W-step folds move
                # into the kernels — so every deep feature, including
                # absorption waves, runs under either fold backend
                fold_impl = "pallas"
        return round_step_deep(cfg, st, with_events, fold_impl=fold_impl)
    if cfg.pallas_burst and cfg.procedural and not with_events:
        from ue22cs343bb1_openmp_assignment_tpu.ops import pallas_burst
        use_pallas = pallas_burst.tileable(cfg.num_nodes)
    else:
        use_pallas = False
    if cfg.txn_width == 1:
        return _round_step_single(cfg, st, with_events,
                                  use_pallas=use_pallas)
    if use_pallas:
        from ue22cs343bb1_openmp_assignment_tpu.ops.pallas_window import (
            round_step_multi_pallas)
        return round_step_multi_pallas(cfg, st)
    return _round_step_multi(cfg, st, with_events)


def _round_step_single(cfg: SystemConfig, st: SyncState,
                       with_events: bool = False,
                       use_pallas: bool = False):
    """Advance every node by one burst of hits plus one transaction.

    ``with_events=True`` additionally returns this round's retirement
    record — per-node, per-window-slot (op, addr, value, retired) — the
    transactional engine's answer to the reference's ``-DDEBUG_INSTR``
    tracing (``assignment.c:649-652``); utils.eventlog renders it in the
    exact ``instruction_order.txt`` line format. Default path pays
    nothing."""
    N, C, M = cfg.num_nodes, cfg.cache_size, cfg.mem_size
    T = st.instr_pack.shape[1]
    H = cfg.drain_depth
    E = N << cfg.block_bits          # dm rows; row index == packed address
    rows = jnp.arange(N, dtype=jnp.int32)
    INV = int(CacheState.INVALID)

    ca, cv, cs = st.cache_addr, st.cache_val, st.cache_state
    idx0 = st.idx

    c_iota = jnp.arange(C, dtype=jnp.int32)
    if use_pallas:
        # ---- phases 1-2a as ONE fused Pallas kernel (ops.pallas_burst;
        # flag-gated — see that module's docstring for the economics)
        from ue22cs343bb1_openmp_assignment_tpu.ops import pallas_burst
        d, rh_n, wh_n, oa, val, live, cv, cs = pallas_burst.burst(
            cfg, ca, cv, cs, idx0, st.instr_count)
    else:
        # ---- instruction window: burst of up to H hits + stopped instr —
        # ONE flat gather for the whole window and both fields (idx
        # advances by at most 1 per burst step, so H+1 lookahead always
        # suffices); procedural mode computes the window instead — no
        # trace storage
        offs = jnp.arange(H + 1, dtype=jnp.int32)[None, :]      # [1, H+1]
        w_idx = idx0[:, None] + offs                             # [N, H+1]
        w_live = w_idx < st.instr_count[:, None]
        if cfg.procedural:
            w_oa, w_val = procedural_instr(cfg, rows[:, None], w_idx)
        else:
            w_flat = rows[:, None] * T + jnp.minimum(w_idx, T - 1)
            w = st.instr_pack.reshape(N * T, 2)[w_flat]          # [N,H+1,2]
            w_oa, w_val = w[..., 0], w[..., 1]

        # ---- phase 1: hit burst (node-local, no cross-node effects) ------
        # Vectorized over the whole window at once: within a burst only
        # hits execute, and hits never change any line's tag or hit/miss
        # class (a write hit needs M/E and leaves M — still a write hit;
        # values change, classifications don't). So every window position
        # can be classified against the round-start cache, and the burst
        # length is the length of the leading all-hit prefix.
        w_op, w_addr = w_oa >> 28, w_oa & 0x0FFFFFFF             # [N, H+1]
        w_ci = codec.cache_index(cfg, w_addr)
        w_onehot = w_ci[:, :, None] == c_iota[None, None, :]     # [N,H+1,C]
        pick3 = lambda arr: jnp.sum(
            jnp.where(w_onehot, arr[:, None, :], 0), axis=2)     # [N, H+1]
        wl_addr, wl_state = pick3(ca), pick3(cs)
        w_tagok = (wl_addr == w_addr) & (wl_state != INV)
        w_rdhit = w_live & (w_op == int(Op.READ)) & w_tagok
        w_wrhit = w_live & (w_op == int(Op.WRITE)) & w_tagok & (
            (wl_state == int(CacheState.MODIFIED))
            | (wl_state == int(CacheState.EXCLUSIVE)))
        # in-trace NOPs (malformed trace lines, utils.trace) retire with
        # no effect, like the reference's fall-through on unknown type
        w_nop = w_live & (w_op == int(Op.NOP))
        w_hit = w_rdhit | w_wrhit | w_nop
        # leading all-hit prefix over the first H positions (the H+1-th
        # slot is only ever the transaction candidate)
        prefix = jnp.cumprod(w_hit[:, :H].astype(jnp.int32), axis=1)
        d = jnp.sum(prefix, axis=1)                               # [N] <= H
        in_burst = prefix.astype(bool)                            # [N, H]
        # burst hit counts per node (summed with the other metrics below
        # in one stacked reduction — separate jnp.sum calls each cost a
        # kernel dispatch on the bench device, PERF.md)
        rh_n = jnp.sum(w_rdhit[:, :H] & in_burst, axis=1,
                       dtype=jnp.int32)
        wh_n = jnp.sum(w_wrhit[:, :H] & in_burst, axis=1,
                       dtype=jnp.int32)
        # burst write effects per line: last write in the burst wins; any
        # write leaves the line MODIFIED (static H-step fold, all fused)
        for k in range(H):
            wmask = ((w_wrhit[:, k] & in_burst[:, k])[:, None]
                     & w_onehot[:, k])
            cv = jnp.where(wmask, w_val[:, k][:, None], cv)
            cs = jnp.where(wmask, int(CacheState.MODIFIED), cs)

        # ---- phase 2: classify the stopped instruction ------------------
        d_onehot = offs == d[:, None]                             # [N, H+1]
        pick = lambda arr: jnp.sum(jnp.where(d_onehot, arr, 0), axis=1)
        oa = pick(w_oa)
        val = pick(w_val)
        live = jnp.sum(jnp.where(d_onehot, w_live, False),
                       axis=1).astype(bool)
    op, addr = oa >> 28, oa & 0x0FFFFFFF
    ci = codec.cache_index(cfg, addr)
    onehot_ci = ci[:, None] == c_iota[None, :]                    # [N, C]
    pickc = lambda arr: jnp.sum(jnp.where(onehot_ci, arr, 0), axis=1)
    l_addr, l_val, l_state = pickc(ca), pickc(cv), pickc(cs)
    tag_ok = (l_addr == addr) & (l_state != INV)
    is_rd, is_wr = op == int(Op.READ), op == int(Op.WRITE)
    upg = live & is_wr & tag_ok & (l_state == int(CacheState.SHARED))
    rd_miss = live & is_rd & ~tag_ok
    wr_miss = live & is_wr & ~tag_ok
    txn = rd_miss | wr_miss | upg
    # (a leftover *hit* at the stop position just waits for next round's
    # burst — happens only when the burst budget H was exhausted)

    e1 = jnp.clip(addr, 0, E - 1)                    # entry index == address
    has_victim = txn & ~tag_ok & (l_state != INV) & (l_addr != addr)
    # (upgrade has tag_ok, so no victim; invalid line: no victim — matches
    # handleCacheReplacement's INVALID no-op, assignment.c:771-775)
    e2 = jnp.clip(l_addr, 0, E - 1)

    # ---- conflict resolution: seeded-hash priority, scatter-min ----------
    # per-round priority permutation: an affine-xorshift bijection on
    # prio_bits bits (odd multiplier => bijective mod 2^b; xorshift is
    # invertible), reseeded every round — pairwise-fair arbitration, the
    # stand-in for OS lock order. Keys are unique per node; the round
    # countdown in the high bits is clamped so overrunning the budget
    # degrades to stale-claim stalls, never int32 wraparound.
    key = _round_key(cfg, st, rows)
    c_idx = jnp.concatenate([jnp.where(txn, e1, E),
                             jnp.where(has_victim, e2, E)])
    dm_claimed = st.dm.at[c_idx, DM_CLAIM].min(
        jnp.concatenate([key, key]), mode="drop")

    # ---- gather directory rows + owner value -----------------------------
    dm12 = dm_claimed[jnp.stack([e1, e2], axis=1)]                # [N, 2, 7]
    dm1, dm2 = dm12[:, 0], dm12[:, 1]
    got = dm12[:, :, DM_CLAIM]                                    # [N, 2]
    win = txn & (got[:, 0] == key) & (~has_victim | (got[:, 1] == key))
    d1s, d1c, d1o, d1m = dm1[:, 0], dm1[:, 1], dm1[:, 2], dm1[:, 3]
    d_u = d1s == int(DirState.U)
    d_s = d1s == int(DirState.S)
    d_em = d1s == int(DirState.EM)
    # EM owner's current copy (the value WRITEBACK_INT/INV would flush,
    # assignment.c:268,486) — post-burst, so same-round local writes by
    # the owner are visible, matching hits-before-transactions order
    safe_o = jnp.clip(d1o, 0, N - 1)
    val_o = cv.reshape(-1)[safe_o * C + ci]

    # ---- transaction outcomes (SURVEY §3.2-3.5 collapsed) ----------------
    rd_w, wr_w, up_w = win & rd_miss, win & wr_miss, win & upg
    wlike = wr_w | up_w
    # primary entry update
    n1s = jnp.where(wlike, int(DirState.EM),
                    jnp.where(rd_w & d_u, int(DirState.EM),
                              int(DirState.S)))
    n1c = jnp.where(wlike | (rd_w & d_u), 1,
                    jnp.where(rd_w & d_em, 2, d1c + 1))
    n1o = jnp.where(wlike | (rd_w & d_u), rows, d1o)
    n1m = jnp.where((rd_w | wr_w) & d_em, val_o, d1m)
    act1 = jnp.where(wlike, ACT_KILL,
                     jnp.where(rd_w & d_em, ACT_DOWNGRADE, ACT_NONE))
    # victim entry update (EVICT_SHARED / EVICT_MODIFIED semantics,
    # assignment.c:538-617)
    ev = win & has_victim
    ev_mod = ev & (l_state == int(CacheState.MODIFIED))
    ev_sh = ev & ~ev_mod
    d2c, d2m = dm2[:, 1], dm2[:, 3]
    n2c = jnp.where(ev_mod, 0, d2c - 1)
    n2s = jnp.where(n2c == 0, int(DirState.U),
                    jnp.where(n2c == 1, int(DirState.EM), int(DirState.S)))
    n2m = jnp.where(ev_mod, l_val, d2m)
    n2o = dm2[:, 2]  # updated by the promoted line's own scatter below
    act2 = jnp.where(ev_sh & (n2c == 1), ACT_PROMOTE, ACT_NONE)

    # ---- commit: one packed scatter for both entries ---------------------
    # the round-tagged action columns ride in the same scatter (DM_ACT
    # comment at top): winners stamp their entry with this round's
    # action; untouched rows keep an older round tag = no action
    rtag = st.round << 2
    t_idx = jnp.concatenate([jnp.where(win, e1, E), jnp.where(ev, e2, E)])
    # claim col re-written with the winner's own key — by construction
    # the current minimum, so the full-row set is exact
    t_dm = jnp.concatenate([
        jnp.stack([n1s, n1c, n1o, n1m, rtag | act1, rows, key], axis=1),
        jnp.stack([n2s, n2c, n2o, n2m, rtag | act2, rows, key], axis=1)],
        axis=0)
    dm = dm_claimed.at[t_idx].set(t_dm, mode="drop")

    # ---- per-line fan-out application ------------------------------------
    # every valid line looks up the action at its own tag's entry; the
    # entry index IS the tag, so a hit is automatically tag-matched
    line_e = jnp.clip(ca, 0, E - 1)                               # [N, C]
    line_dm = dm[line_e]                                          # [N, C, 7]
    fresh = (line_dm[..., DM_ACT] >> 2) == st.round
    a_code = jnp.where(fresh, line_dm[..., DM_ACT] & 3, ACT_NONE)
    a_req = line_dm[..., DM_REQ]
    valid = cs != INV
    not_self = a_req != rows[:, None]
    kill = valid & not_self & (a_code == ACT_KILL)
    down = valid & not_self & (a_code == ACT_DOWNGRADE)
    promo = valid & not_self & (a_code == ACT_PROMOTE)
    cs = jnp.where(kill, INV,
                   jnp.where(down, int(CacheState.SHARED),
                             jnp.where(promo, int(CacheState.EXCLUSIVE),
                                       cs)))
    # each promoted line reports itself as its entry's new EM owner
    # (per-line, not per-node: one node can be promoted on several lines
    # in one round when distinct evictions each leave it as last sharer)
    dm = dm.at[jnp.where(promo, line_e, E).reshape(-1), DM_OWNER].set(
        jnp.broadcast_to(rows[:, None], (N, C)).reshape(-1), mode="drop")

    # ---- winner fills its own line ---------------------------------------
    fill_state = jnp.where(
        rd_w, jnp.where(d_u, int(CacheState.EXCLUSIVE),
                        int(CacheState.SHARED)),
        int(CacheState.MODIFIED))
    fill_val = jnp.where(rd_w, jnp.where(d_em, val_o, d1m), val)
    onehot = (jnp.arange(C, dtype=jnp.int32)[None, :] == ci[:, None])
    fmask = onehot & win[:, None]
    ca = jnp.where(fmask, addr[:, None], ca)
    cv = jnp.where(fmask, fill_val[:, None], cv)
    cs = jnp.where(fmask, fill_state[:, None], cs)

    # ---- bookkeeping -----------------------------------------------------
    new_idx = idx0 + d + win.astype(jnp.int32)
    # ONE stacked reduction for every counter delta (each separate
    # jnp.sum is its own kernel dispatch on the bench device)
    deltas = jnp.sum(jnp.stack([
        d + win.astype(jnp.int32),                     # instrs retired
        rh_n, wh_n,
        rd_w.astype(jnp.int32), wr_w.astype(jnp.int32),
        up_w.astype(jnp.int32), (txn & ~win).astype(jnp.int32),
        ev.astype(jnp.int32),
        jnp.sum(kill, axis=1, dtype=jnp.int32),
        jnp.sum(promo, axis=1, dtype=jnp.int32),
    ]), axis=1)                                        # [10]
    mt = st.metrics
    metrics = mt.replace(
        rounds=mt.rounds + 1,
        instrs_retired=mt.instrs_retired + deltas[0],
        read_hits=mt.read_hits + deltas[1],
        write_hits=mt.write_hits + deltas[2],
        read_misses=mt.read_misses + deltas[3],
        write_misses=mt.write_misses + deltas[4],
        upgrades=mt.upgrades + deltas[5],
        conflicts=mt.conflicts + deltas[6],
        evictions=mt.evictions + deltas[7],
        invalidations=mt.invalidations + deltas[8],
        promotions=mt.promotions + deltas[9],
    )
    new_st = st.replace(cache_addr=ca, cache_val=cv, cache_state=cs,
                        dm=dm, idx=new_idx, round=st.round + 1,
                        metrics=metrics)
    if not with_events:
        return new_st
    # retirement record: burst slots below d, plus the transaction slot
    # when it won (slot order == program order within the round)
    slot_retired = (offs < d[:, None]) | ((offs == d[:, None])
                                          & win[:, None])
    events = {"retired": slot_retired, "op": w_op, "addr": w_addr,
              "value": w_val}
    return new_st, events


def _round_step_multi(cfg: SystemConfig, st: SyncState,
                      with_events: bool = False):
    """Advance every node by a window of up to cfg.txn_width transactions.

    Generalizes `_round_step_single` from burst-plus-one-transaction to a
    per-node window of W = drain_depth + txn_width instructions, within
    which up to K = txn_width coherence transactions commit in one round.
    The admission rules keep every committed round a legal serialization
    of the reference machine (same argument shape as the single-txn
    round, SURVEY §3.2-3.5):

    * **Distinct entries.** All directory entries a node's window touches
      — transaction targets and evicted victims alike — must be pairwise
      distinct; a repeat stops the window. Combined with claim
      arbitration (one winner per entry per round), every committed
      transaction reads a directory row no other committed transaction
      touches, so all outcomes may be computed from round-start rows.
      Two relaxations cover the common working-set-cycling patterns of
      small direct-mapped caches; both compose a node's multiple updates
      to one entry into a single scattered row, so each entry still has
      exactly one committed writer:

      - **Release**: a transaction may displace a line the node filled
        earlier in the same window; the entry's final row is the acquire
        outcome followed by the self-eviction (`released` below).
      - **Reacquire**: a transaction may target an entry whose line the
        node itself evicted earlier in the window, provided the
        displaced line was MODIFIED or EXCLUSIVE — then the node was its
        sole holder and the eviction provably left the entry Uncached
        with known memory, so the reacquire proceeds from that composed
        row (`acq_base` below) and the evict's separate victim row is
        suppressed. Evicting a SHARED line may instead leave an
        EM entry whose owner (the promoted last sharer) is unknown at
        composition time, so reacquiring after a SHARED evict stops the
        window, as does any deeper chain on one entry.
    * **Hit admission.** Hits before the node's first transaction (the
      classic burst) retire unconditionally — they serialize before all
      transactions, as in the single-txn round. Mid-window hits after
      the first transaction retire when (a) the node itself claimed the
      entry earlier in the window, or (b) post-claim, the entry carries
      no fresh transaction claim this round (checked against the claim
      column, no extra scatter — hits place no claims). Either way no
      foreign transaction commits on the entry this round, so committed
      windows touch pairwise-disjoint entries and ANY interleaving that
      respects per-node program order — prefix hits first, then whole
      windows node by node — is a legal serialization. An interior hit
      whose entry does carry a foreign claim truncates retirement at
      its window position, exactly like a losing transaction (a foreign
      kill might otherwise have to land between our program-ordered
      reads, which may admit no consistent order).
    * **Truncation.** A transaction that loses claim arbitration
      truncates retirement at its window position: nothing after it
      retires, so the retired stream is always a program-order prefix.
      Progress: the globally minimal-priority node wins every claim it
      makes, so its whole window commits.
    * **Read-fill ambiguity.** A read-miss fill's final state (E vs S)
      depends on the directory row, unknown during the sequential fold;
      the fold records it as SHARED (a reacquire-after-evict fill is
      provably EXCLUSIVE and recorded as such). A later write to an
      ambiguous fill becomes a **dependent hit**: it retires iff the
      fill resolves EXCLUSIVE post-claim (then it is a silent E->M write
      hit, no directory effect); a SHARED resolution would need an
      upgrade transaction, so it truncates retirement at the write's
      window position instead — the ambiguity never reaches a commit.

    Per-round device work matches the single path (one claim
    scatter-min, one row gather, one commit scatter, one fan-out gather,
    one promotion scatter) with K-times larger index vectors — on a
    dispatch-bound device the round cost is nearly flat while retiring
    up to K transactions per node (PERF.md).
    """
    N, C = cfg.num_nodes, cfg.cache_size
    K = cfg.txn_width
    W = cfg.drain_depth + K
    T = st.instr_pack.shape[1]
    E = N << cfg.block_bits
    rows = jnp.arange(N, dtype=jnp.int32)
    INV = int(CacheState.INVALID)
    MOD = int(CacheState.MODIFIED)
    EXC = int(CacheState.EXCLUSIVE)
    SHD = int(CacheState.SHARED)
    idx0 = st.idx

    # ---- instruction window ----------------------------------------------
    offs = jnp.arange(W, dtype=jnp.int32)[None, :]
    w_idx = idx0[:, None] + offs
    w_live = w_idx < st.instr_count[:, None]
    if cfg.procedural:
        w_oa, w_val = procedural_instr(cfg, rows[:, None], w_idx)
    else:
        w_flat = rows[:, None] * T + jnp.minimum(w_idx, T - 1)
        w = st.instr_pack.reshape(N * T, 2)[w_flat]
        w_oa, w_val = w[..., 0], w[..., 1]
    w_op, w_addr = w_oa >> 28, w_oa & 0x0FFFFFFF
    w_ci = codec.cache_index(cfg, w_addr)
    c_iota = jnp.arange(C, dtype=jnp.int32)

    def line_select(ci, *arrs):
        """Read each node's line `ci` from [N, C] arrays via a chain of
        selects — no reduction, so the whole fold stays fusable."""
        outs = [a[:, 0] for a in arrs]
        for c in range(1, C):
            m = ci == c
            outs = [jnp.where(m, a[:, c], o) for a, o in zip(arrs, outs)]
        return outs

    # ---- sequential pre-claim fold (static unroll, all elementwise) ------
    ca_f, cv_f, cs_f = st.cache_addr, st.cache_val, st.cache_state
    cv_pre = cv_f                     # cache values at the first-txn point
    frozen = jnp.zeros((N,), bool)    # node has issued a txn this window
    stopped = jnp.zeros((N,), bool)
    n_txn = jnp.zeros((N,), jnp.int32)
    fills: list = []                  # (entry, valid, ordinal) fill targets
    victs: list = []                  # (entry, valid, ordinal, eligible)
    steps: list = []
    # per-line ordinal of the window read-fill holding it (K = none):
    # writes to such lines are tentative hits, resolved post-claim
    fo_f = jnp.full((N, C), K, jnp.int32)
    for k in range(W):
        addr, op, val = w_addr[:, k], w_op[:, k], w_val[:, k]
        live = w_live[:, k]
        onehot = w_ci[:, k][:, None] == c_iota[None, :]          # [N, C]
        l_addr, l_val, l_state, l_fo = line_select(
            w_ci[:, k], ca_f, cv_f, cs_f, fo_f)
        tag_ok = (l_addr == addr) & (l_state != INV)
        is_rd, is_wr = op == int(Op.READ), op == int(Op.WRITE)
        rd_hit = live & is_rd & tag_ok
        wr_hit = live & is_wr & tag_ok & ((l_state == MOD)
                                          | (l_state == EXC))
        # write on an own window read-fill (tentative SHARED): a
        # tentative hit, resolved post-claim against the fill's d_u
        wr_dep = live & is_wr & tag_ok & (l_state == SHD) & (l_fo < K)
        hit = rd_hit | wr_hit | wr_dep | (live & (op == int(Op.NOP)))
        upg = live & is_wr & tag_ok & (l_state == SHD) & (l_fo == K)
        rd_miss = live & is_rd & ~tag_ok
        wr_miss = live & is_wr & ~tag_ok
        e1 = jnp.clip(addr, 0, E - 1)
        has_victim = ~tag_ok & (l_state != INV) & (l_addr != addr)
        e2 = jnp.clip(l_addr, 0, E - 1)
        own1 = jnp.zeros((N,), bool)  # e1 already claimed by this node
        dup = jnp.zeros((N,), bool)   # e1 re-touches a window entry
        rel_ord = jnp.full((N,), K, jnp.int32)  # own fill being displaced
        acq_base = jnp.full((N,), K, jnp.int32)  # reacquire-after-evict
        for te, tv, tord in fills:
            own1 |= tv & (te == e1)
            dup |= tv & (te == e1)
            # displacing a prior fill is a release (rows compose); prior
            # victims can never be displaced again (their tag left the
            # cache), so only fills need checking against e2
            rel_ord = jnp.where(tv & has_victim & (te == e2), tord,
                                rel_ord)
        for te, tv, tord, telig in victs:
            m = tv & (te == e1)
            dup |= m & ~telig         # reacquire after a SHARED evict
            acq_base = jnp.where(m & telig, tord, acq_base)
        # interior hits on unclaimed entries retire tentatively; their
        # safety (no fresh foreign claim on the entry) resolves
        # post-claim and truncates on failure
        hc = hit & ~stopped & frozen & ~own1
        hit_ok = (hit & ~stopped & (~frozen | own1)) | hc
        txn = (rd_miss | wr_miss | upg) & ~stopped
        ok = txn & ~dup & (n_txn < K)
        rel_ord = jnp.where(ok, rel_ord, K)
        acq_base = jnp.where(ok, acq_base, K)
        stop_now = ~hit_ok & ~ok & ~stopped
        # hit-write effects (last write wins; any write leaves MODIFIED)
        wmask = ((wr_hit | wr_dep) & hit_ok)[:, None] & onehot
        cv_f = jnp.where(wmask, val[:, None], cv_f)
        cs_f = jnp.where(wmask, MOD, cs_f)
        # prefix cache freezes at the node's first issued transaction;
        # it is what foreign transactions observe of this node (the
        # single path's "post-burst" owner-value source)
        cv_pre = jnp.where(frozen[:, None], cv_pre, cv_f)
        frozen = frozen | ok
        # tentative fill: tag always; value only for write-like fills
        # (a read fill's value is resolved post-claim and — by the
        # distinctness rule — never read back inside this window)
        fmask = ok[:, None] & onehot
        ca_f = jnp.where(fmask, addr[:, None], ca_f)
        cv_f = jnp.where((ok & (wr_miss | upg))[:, None] & onehot,
                         val[:, None], cv_f)
        # a reacquire-rd provably fills EXCLUSIVE (the composed entry is
        # Uncached), so record it as such — a later write then hits
        cs_f = jnp.where(fmask,
                         jnp.where((wr_miss | upg)[:, None], MOD,
                                   jnp.where((acq_base < K)[:, None],
                                             EXC, SHD)),
                         cs_f)
        # non-reacquire read fills are E/S-ambiguous: record the line's
        # fill ordinal so later writes to it become dependent hits
        fo_f = jnp.where(fmask,
                         jnp.where((ok & rd_miss & (acq_base == K)),
                                   n_txn, K)[:, None],
                         fo_f)
        steps.append(dict(
            hit_ok=hit_ok, rd_hit=rd_hit & hit_ok,
            wr_hit=(wr_hit | wr_dep) & hit_ok,
            dep=jnp.where(wr_dep & hit_ok, l_fo, K),
            ok=ok, ordn=jnp.where(ok, n_txn, K), addr=addr, val=val,
            e1=e1, e2=e2, victim=ok & has_victim, rd=ok & rd_miss,
            wr=ok & wr_miss, up=ok & upg, v_val=l_val,
            v_mod=l_state == MOD, rel_ordn=rel_ord, acq_basen=acq_base,
            hc=hc, onehot=onehot))
        fills.append((e1, ok, n_txn))
        # a victim is reacquirable when the displaced line was M/E (the
        # node was sole holder -> the evict leaves the entry Uncached)
        # and it was the entry's first touch (not a release)
        victs.append((e2, ok & has_victim,
                      n_txn, ((l_state == MOD) | (l_state == EXC))
                      & (rel_ord == K)))
        n_txn = n_txn + ok
        stopped = stopped | stop_now

    # ---- pack transactions into [N, K] ordinal slots ---------------------
    sel = [[steps[k]["ordn"] == j for k in range(W)] for j in range(K)]

    def pack(name):
        return jnp.stack(
            [sum(jnp.where(sel[j][k], steps[k][name], 0)
                 for k in range(W)) for j in range(K)], axis=1)

    exists = pack("ok").astype(bool)                              # [N, K]
    e1_s, e2_s = pack("e1"), pack("e2")
    val_s, v_val_s = pack("val"), pack("v_val")
    victim_s = pack("victim").astype(bool)
    rd_s, wr_s, up_s = (pack("rd").astype(bool), pack("wr").astype(bool),
                        pack("up").astype(bool))
    v_mod_s = pack("v_mod").astype(bool)
    # releasing slot r displaces the fill of slot rel_s[:, r] (K = none)
    rel_s = jnp.where(exists, pack("rel_ordn"), K)
    pos_s = jnp.stack(
        [sum(jnp.where(sel[j][k], k, 0) for k in range(W))
         for j in range(K)], axis=1)                              # [N, K]

    # ---- claim + win resolution ------------------------------------------
    key = _round_key(cfg, st, rows)
    c_idx = jnp.concatenate(
        [jnp.where(exists[:, j], e1_s[:, j], E) for j in range(K)]
        + [jnp.where(victim_s[:, j], e2_s[:, j], E) for j in range(K)])
    dm_claimed = st.dm.at[c_idx, DM_CLAIM].min(jnp.tile(key, 2 * K),
                                               mode="drop")
    # ONE row gather serves the txn entries, the victim entries, and the
    # interior-hit safety probes
    he = jnp.stack([steps[k]["e1"] for k in range(W)], axis=1)    # [N, W]
    g = dm_claimed[jnp.concatenate([e1_s, e2_s, he], axis=1)]
    d1, d2, hrow = g[:, :K], g[:, K:2 * K], g[:, 2 * K:]
    keyK = key[:, None]
    win = exists & (d1[..., DM_CLAIM] == keyK) & (
        ~victim_s | (d2[..., DM_CLAIM] == keyK))
    # interior-hit safety: the hit's entry carries no fresh foreign
    # transaction claim (fresh keys this round sit strictly below every
    # stale key — the DM_CLAIM countdown invariant)
    prio_bits = max(1, (N - 1).bit_length())
    thresh = (jnp.maximum(claim_max_rounds(cfg) - st.round, 0) + 1) \
        << prio_bits
    hgot = hrow[..., DM_CLAIM]                                    # [N, W]

    # ---- effective primary rows (before commit: truncation needs d_u) ----
    d1s, d1c, d1o, d1m = (d1[..., DM_STATE], d1[..., DM_COUNT],
                          d1[..., DM_OWNER], d1[..., DM_MEM])
    d2c, d2o, d2m = d2[..., DM_COUNT], d2[..., DM_OWNER], d2[..., DM_MEM]
    v_mod_s = v_mod_s & victim_s
    # reacquires chain off the base slot's post-evict row instead of the
    # gathered round-start row: always Uncached (the eligibility rule),
    # memory = the evict's outcome (the flushed value for an M line)
    acqb_s = jnp.where(exists, pack("acq_basen"), K)
    pe_m = jnp.where(v_mod_s, v_val_s, d2m)     # [N, K] post-evict memory
    j_iota = jnp.arange(K, dtype=jnp.int32)[None, :]
    base_u = jnp.zeros((N, K), bool)
    base_m = jnp.zeros((N, K), jnp.int32)
    for i in range(K):
        m = acqb_s == i
        base_u |= m
        base_m = jnp.where(m, pe_m[:, i:i + 1], base_m)
    d1s = jnp.where(base_u, int(DirState.U), d1s)
    d1c = jnp.where(base_u, 0, d1c)
    d1m = jnp.where(base_u, base_m, d1m)
    d_u = d1s == int(DirState.U)
    d_em = d1s == int(DirState.EM)

    # tentative writes on own read fills retire iff the fill resolved
    # EXCLUSIVE (entry Uncached at acquire) — a silent E->M write hit;
    # a SHARED resolution would need an upgrade, so it truncates.
    # Running min over per-step slices — no [N, W] stacks (each stack
    # materializes W buffers = kernels on the bench device)
    first_bad_hit = jnp.full((N,), W, jnp.int32)
    for k in range(W):
        dep = steps[k]["dep"]
        dok = jnp.zeros((N,), bool)
        for j in range(K):
            dok |= (dep == j) & d_u[:, j]
        hg = hgot[:, k]
        unsafe = (steps[k]["hc"] & ~((hg >= thresh) | (hg == key))) \
            | ((dep < K) & ~dok)
        first_bad_hit = jnp.minimum(first_bad_hit,
                                    jnp.where(unsafe, k, W))
    # committed = the leading prefix of transactions that win their
    # claims and sit before any unsafe interior hit; the first loss (or
    # unsafe hit) truncates retirement at its window position
    eligible = win & (pos_s < first_bad_hit[:, None])
    cum = jnp.cumprod((eligible | ~exists).astype(jnp.int32),
                      axis=1).astype(bool)
    commit = exists & cum
    first_lose = jnp.minimum(
        jnp.min(jnp.where(exists & ~cum, pos_s, W), axis=1),
        first_bad_hit)                                            # [N]

    # ---- transaction outcomes (round-start rows; entries disjoint) -------
    rd_w, wr_w, up_w = commit & rd_s, commit & wr_s, commit & up_s
    wlike = wr_w | up_w
    ci_s = codec.cache_index(cfg, e1_s)
    safe_o = jnp.clip(d1o, 0, N - 1)
    val_o = cv_pre.reshape(-1)[safe_o * C + ci_s]                 # [N, K]
    n1s = jnp.where(wlike | (rd_w & d_u), int(DirState.EM),
                    int(DirState.S))
    n1c = jnp.where(wlike | (rd_w & d_u), 1,
                    jnp.where(rd_w & d_em, 2, d1c + 1))
    n1o = jnp.where(wlike | (rd_w & d_u), rows[:, None], d1o)
    n1m = jnp.where((rd_w | wr_w) & d_em, val_o, d1m)
    act1 = jnp.where(wlike, ACT_KILL,
                     jnp.where(rd_w & d_em, ACT_DOWNGRADE, ACT_NONE))
    ev = commit & victim_s
    ev_mod = ev & v_mod_s
    ev_sh = ev & ~ev_mod
    n2c = jnp.where(ev_mod, 0, d2c - 1)
    n2s = jnp.where(n2c == 0, int(DirState.U),
                    jnp.where(n2c == 1, int(DirState.EM), int(DirState.S)))
    n2m = jnp.where(ev_mod, v_val_s, d2m)
    act2 = jnp.where(ev_sh & (n2c == 1), ACT_PROMOTE, ACT_NONE)

    # ---- release composition: fill-then-self-evict as one row ------------
    # A committed txn r whose victim is slot j's own fill (rel_s[:,r]==j)
    # releases slot j: entry e1_j's final row is the acquire outcome
    # followed by the self-eviction, written by slot j's scatter alone.
    released = jnp.zeros((N, K), bool)
    rel_val = jnp.zeros((N, K), jnp.int32)  # line value at displacement
    rel_dirty = jnp.zeros((N, K), bool)     # line MODIFIED at displacement
    consumed = jnp.zeros((N, K), bool)      # victim row superseded by a
    for r in range(K):                      # committed reacquire
        m = commit[:, r:r + 1] & (rel_s[:, r:r + 1] == j_iota)    # [N, K]
        released |= m
        rel_val = jnp.where(m, v_val_s[:, r:r + 1], rel_val)
        rel_dirty |= m & v_mod_s[:, r:r + 1]
        consumed |= commit[:, r:r + 1] & (acqb_s[:, r:r + 1] == j_iota)
    rd_rel_s = released & rd_s & ~d_u & ~d_em                     # rd on S
    r1s = jnp.where(wlike | (rd_s & d_u), int(DirState.U),
                    jnp.where(rd_s & d_em, int(DirState.EM),
                              jnp.where(d1c == 1, int(DirState.EM),
                                        int(DirState.S))))
    r1c = jnp.where(wlike | (rd_s & d_u), 0,
                    jnp.where(rd_s & d_em, 1, d1c))
    # rel_dirty: a read fill written via a dependent hit (E->M) before
    # displacement flushes the written value, like a MODIFIED evict
    r1m = jnp.where(wlike | rel_dirty, rel_val,
                    jnp.where(rd_s & d_em, val_o, d1m))
    r1a = jnp.where(wlike, ACT_KILL,
                    jnp.where((rd_s & d_em) | (rd_rel_s & (d1c == 1)),
                              ACT_PROMOTE, ACT_NONE))
    n1s = jnp.where(released, r1s, n1s)
    n1c = jnp.where(released, r1c, n1c)
    n1o = jnp.where(released, d1o, n1o)
    n1m = jnp.where(released, r1m, n1m)
    act1 = jnp.where(released, r1a, act1)
    # a release's victim row rides in slot j's composed scatter, and a
    # reacquired entry's row is written by the reacquiring slot alone;
    # only unconsumed first-touch victims get their own row
    ev_sep = ev & (rel_s == K) & ~consumed

    # ---- commit: one packed scatter for all entries ----------------------
    rtag = st.round << 2
    rowsK = jnp.broadcast_to(rows[:, None], (N, K))
    keyKb = jnp.broadcast_to(keyK, (N, K))
    t_idx = jnp.concatenate([jnp.where(commit, e1_s, E).reshape(-1),
                             jnp.where(ev_sep, e2_s, E).reshape(-1)])
    t_dm = jnp.concatenate([
        jnp.stack([n1s, n1c, n1o, n1m, rtag | act1, rowsK, keyKb],
                  axis=-1).reshape(-1, DM_COLS),
        jnp.stack([n2s, n2c, d2o, n2m, rtag | act2, rowsK, keyKb],
                  axis=-1).reshape(-1, DM_COLS)])
    dm = dm_claimed.at[t_idx].set(t_dm, mode="drop")

    # ---- replay: apply the retired prefix to the round-start cache -------
    fill_state = jnp.where(rd_s, jnp.where(d_u, EXC, SHD), MOD)   # [N, K]
    fill_val = jnp.where(rd_s, jnp.where(d_em, val_o, d1m), val_s)
    ca_c, cv_c, cs_c = st.cache_addr, st.cache_val, st.cache_state
    # running [N] accumulators fuse into the replay; stacking per-step
    # arrays materialized W extra buffers per counter (copies are
    # kernels on the bench device). The [N, W] record is built only on
    # the events path.
    retired_ks = []
    n_retired = jnp.zeros((N,), jnp.int32)
    rh_n = jnp.zeros((N,), jnp.int32)
    wh_n = jnp.zeros((N,), jnp.int32)
    for k in range(W):
        s = steps[k]
        r = (k < first_lose) & (s["hit_ok"] | s["ok"])
        if with_events:
            retired_ks.append(r)
        n_retired = n_retired + r
        rh_n = rh_n + (s["rd_hit"] & r)
        wh_n = wh_n + (s["wr_hit"] & r)
        wmask = (s["wr_hit"] & r)[:, None] & s["onehot"]
        cv_c = jnp.where(wmask, s["val"][:, None], cv_c)
        cs_c = jnp.where(wmask, MOD, cs_c)
        fs = sum(jnp.where(sel[j][k], fill_state[:, j], 0)
                 for j in range(K))
        fv = sum(jnp.where(sel[j][k], fill_val[:, j], 0)
                 for j in range(K))
        fmask = (s["ok"] & r)[:, None] & s["onehot"]
        ca_c = jnp.where(fmask, s["addr"][:, None], ca_c)
        cv_c = jnp.where(fmask, fv[:, None], cv_c)
        cs_c = jnp.where(fmask, fs[:, None], cs_c)

    # ---- per-line fan-out application (same mechanism as single) ---------
    line_e = jnp.clip(ca_c, 0, E - 1)                             # [N, C]
    line_dm = dm[line_e]                                          # [N, C, 7]
    fresh = (line_dm[..., DM_ACT] >> 2) == st.round
    a_code = jnp.where(fresh, line_dm[..., DM_ACT] & 3, ACT_NONE)
    a_req = line_dm[..., DM_REQ]
    valid = cs_c != INV
    not_self = a_req != rows[:, None]
    kill = valid & not_self & (a_code == ACT_KILL)
    down = valid & not_self & (a_code == ACT_DOWNGRADE)
    promo = valid & not_self & (a_code == ACT_PROMOTE)
    cs_c = jnp.where(kill, INV,
                     jnp.where(down, SHD, jnp.where(promo, EXC, cs_c)))
    dm = dm.at[jnp.where(promo, line_e, E).reshape(-1), DM_OWNER].set(
        jnp.broadcast_to(rows[:, None], (N, C)).reshape(-1), mode="drop")

    # ---- bookkeeping -----------------------------------------------------
    deltas = jnp.sum(jnp.stack([
        n_retired,
        rh_n,
        wh_n,
        jnp.sum(rd_w, axis=1, dtype=jnp.int32),
        jnp.sum(wr_w, axis=1, dtype=jnp.int32),
        jnp.sum(up_w, axis=1, dtype=jnp.int32),
        # conflicts = claim-arbitration losses only (matching the single
        # path's `txn & ~win`), not slots truncated by an earlier loss
        # or a failed dependent/interior hit
        jnp.sum(exists & ~win, axis=1, dtype=jnp.int32),
        jnp.sum(ev, axis=1, dtype=jnp.int32),
        jnp.sum(kill, axis=1, dtype=jnp.int32),
        jnp.sum(promo, axis=1, dtype=jnp.int32),
    ]), axis=1)                                                   # [10]
    mt = st.metrics
    metrics = mt.replace(
        rounds=mt.rounds + 1,
        instrs_retired=mt.instrs_retired + deltas[0],
        read_hits=mt.read_hits + deltas[1],
        write_hits=mt.write_hits + deltas[2],
        read_misses=mt.read_misses + deltas[3],
        write_misses=mt.write_misses + deltas[4],
        upgrades=mt.upgrades + deltas[5],
        conflicts=mt.conflicts + deltas[6],
        evictions=mt.evictions + deltas[7],
        invalidations=mt.invalidations + deltas[8],
        promotions=mt.promotions + deltas[9],
    )
    new_st = st.replace(cache_addr=ca_c, cache_val=cv_c, cache_state=cs_c,
                        dm=dm, idx=idx0 + n_retired, round=st.round + 1,
                        metrics=metrics)
    if not with_events:
        return new_st
    events = {"retired": jnp.stack(retired_ks, axis=1), "op": w_op,
              "addr": w_addr,
              "value": w_val}
    return new_st, events


# -- ensembles -------------------------------------------------------------
#
# The bench device is dispatch-overhead-bound (PERF.md): a kernel over
# R replicas costs nearly the same as over one. An ensemble batches R
# independent machines (different workloads and/or arbitration seeds)
# into one leading axis, vmapping the round — the same mechanism serves
# as the schedule-search harness for the racy parity suites (run many
# arbitration seeds at once, pick the one matching an accepted run).

def make_ensemble(states: list) -> SyncState:
    """Stack per-replica SyncStates into one [R, ...] ensemble state."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *states)


def ensemble_replica(st: SyncState, r: int) -> SyncState:
    """Extract replica r back out of an ensemble state."""
    return jax.tree.map(lambda x: x[r], st)


def run_ensemble_to_quiescence(cfg: SystemConfig, st: SyncState,
                               chunk: int = 32,
                               max_rounds: int = 100_000) -> SyncState:
    """Run an [R, ...] ensemble until every replica's traces retire."""
    _assert_round_budget(cfg, st.round[0], max_rounds)
    return _run_ensemble_jit(cfg, st, chunk, max_rounds)


@functools.partial(jax.jit, static_argnums=(0, 2, 3))
def _run_ensemble_jit(cfg: SystemConfig, st: SyncState, chunk: int,
                      max_rounds: int) -> SyncState:
    carry0, pack = _pack_outside(st)
    vround = jax.vmap(lambda s: round_step(cfg, s))

    def body(s, _):
        out = vround(s.replace(instr_pack=pack))
        return out.replace(instr_pack=carry0.instr_pack), None

    limit = st.round[0] + max_rounds

    def cond(s):
        return jnp.any(~jax.vmap(lambda x: x.quiescent())(s)) & (
            s.round[0] < limit)

    def chunk_body(s):
        s, _ = jax.lax.scan(body, s, None, length=chunk)
        return s

    final = jax.lax.while_loop(cond, chunk_body, carry0)
    return final.replace(instr_pack=pack)


# -- runners ---------------------------------------------------------------

def run_rounds_traced(cfg: SystemConfig, st: SyncState, n: int):
    """Scan n rounds collecting the retirement record: events are
    [n, N, drain_depth+1] arrays (utils.eventlog.sync_to_records)."""
    _assert_round_budget(cfg, st.round, n)
    return _run_rounds_traced_jit(cfg, st, n)


@functools.partial(jax.jit, static_argnums=(0, 2))
def _run_rounds_traced_jit(cfg: SystemConfig, st: SyncState, n: int):
    carry0, pack = _pack_outside(st)

    def body(s, _):
        out, ev = round_step(cfg, s.replace(instr_pack=pack),
                             with_events=True)
        return out.replace(instr_pack=carry0.instr_pack), ev

    final, events = jax.lax.scan(body, carry0, None, length=n)
    return final.replace(instr_pack=pack), events


def run_rounds(cfg: SystemConfig, st: SyncState, n: int) -> SyncState:
    _assert_round_budget(cfg, st.round, n)
    return _run_rounds_jit(cfg, st, n)


@functools.partial(jax.jit, static_argnums=(0, 2))
def _run_rounds_jit(cfg: SystemConfig, st: SyncState, n: int) -> SyncState:
    carry0, pack = _pack_outside(st)

    def body(s, _):
        out = round_step(cfg, s.replace(instr_pack=pack))
        return out.replace(instr_pack=carry0.instr_pack), None

    final, _ = jax.lax.scan(body, carry0, None, length=n)
    return final.replace(instr_pack=pack)


def run_sync_to_quiescence(cfg: SystemConfig, st: SyncState,
                           chunk: int = 32,
                           max_rounds: int = 100_000) -> SyncState:
    """Run until every trace is fully retired (chunked single dispatch)."""
    _assert_round_budget(cfg, st.round, max_rounds)
    return _run_sync_jit(cfg, st, chunk, max_rounds)


def _pack_outside(st: SyncState):
    """(loop-carry state, hoisted trace): the instruction table is
    read-only, and a large array in a scan/while carry gets copied every
    iteration when XLA cannot prove aliasing — at [4096, 1024, 2] that
    copy dominated the round (PERF.md). The loop carries a zero-width
    placeholder instead; bodies close over the real table."""
    placeholder = jnp.zeros(st.instr_pack.shape[:-2] + (0, 2), jnp.int32)
    return st.replace(instr_pack=placeholder), st.instr_pack


@functools.partial(jax.jit, static_argnums=(0, 2, 3))
def _run_sync_jit(cfg: SystemConfig, st: SyncState, chunk: int,
                  max_rounds: int) -> SyncState:
    carry0, pack = _pack_outside(st)

    def body(s, _):
        out = round_step(cfg, s.replace(instr_pack=pack))
        return out.replace(instr_pack=carry0.instr_pack), None

    limit = st.round + max_rounds     # per-call budget (chained phases
                                      # reset `round`, see
                                      # continue_with_traces)

    def cond(s):
        return (~s.quiescent()) & (s.round < limit)

    def chunk_body(s):
        s, _ = jax.lax.scan(body, s, None, length=chunk)
        return s

    final = jax.lax.while_loop(cond, chunk_body, carry0)
    return final.replace(instr_pack=pack)


def run_sync_profile(cfg: SystemConfig, st: SyncState, n: int):
    """Scan n rounds accumulating per-(node, address) retired-access
    planes for the coherence profiler (obs/cohprof.py).

    Returns ``(state, rd, wr)`` with rd/wr [N, N << block_bits] int32:
    retired READ/WRITE accesses folded from the per-round retirement
    record — the sync engine's analogue of the async with_profile
    access planes (miss taxonomy and invalidation attribution are
    async/deep-only; the sharing classifier needs only these). The
    accumulation rides the scan carry, so capture cost is independent
    of n. Works for any round_step dispatch that supports with_events
    (deep rounds use ops.deep_engine.run_deep_profile instead, which
    adds the abort-attribution planes).
    """
    _assert_round_budget(cfg, st.round, n)
    return _run_sync_profile_jit(cfg, st, n)


@functools.partial(jax.jit, static_argnums=(0, 2))
def _run_sync_profile_jit(cfg: SystemConfig, st: SyncState, n: int):
    N = cfg.num_nodes
    A = N << cfg.block_bits
    carry0, pack = _pack_outside(st)
    rows = jnp.arange(N, dtype=jnp.int32)
    z = jnp.zeros((N * A,), jnp.int32)

    def body(carry, _):
        s, rd, wr = carry
        out, ev = round_step(cfg, s.replace(instr_pack=pack),
                             with_events=True)
        ret = ev["retired"]                                   # [N, W]
        addr = jnp.clip(ev["addr"], 0, A - 1)
        flat = rows[:, None] * A + addr                       # [N, W]
        rd = rd.at[jnp.where(ret & (ev["op"] == int(Op.READ)),
                             flat, N * A)].add(1, mode="drop")
        wr = wr.at[jnp.where(ret & (ev["op"] == int(Op.WRITE)),
                             flat, N * A)].add(1, mode="drop")
        return (out.replace(instr_pack=carry0.instr_pack), rd, wr), None

    (final, rd, wr), _ = jax.lax.scan(body, (carry0, z, z), None,
                                      length=n)
    return (final.replace(instr_pack=pack),
            rd.reshape(N, A), wr.reshape(N, A))
